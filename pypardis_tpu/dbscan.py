"""User-facing DBSCAN API.

Mirrors the reference driver (``/root/reference/dbscan/dbscan.py:56-165``):
``DBSCAN(eps, min_samples, metric, max_partitions)`` with ``train`` /
``assignments`` and the same inspectable attribute surface
(``bounding_boxes``, ``expanded_boxes``, ``result``, ``cluster_dict``).
Adds the sklearn-style ``fit`` / ``fit_predict`` conveniences.

Execution strategy replaces Spark end-to-end:

* one device, or small N → pad to a block multiple and run the fused
  single-chip kernel (:mod:`pypardis_tpu.ops`);
* a multi-device mesh → KD-partition on host (tiny metadata), shard
  points over the mesh, halo-exchange boundary slabs, run the kernel per
  shard and merge labels with collectives
  (:mod:`pypardis_tpu.parallel`) — no driver round-trips in the hot
  path, removing the reference's driver-memory merge bottleneck
  (README.md:60, dbscan.py:160-161).
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

import numpy as np

from .aggregator import ClusterAggregator
from .geometry import BoundingBox
from .ops import densify_labels
from .partition import KDPartitioner
from .utils import clamp_block, envreg, round_up, validate_params
from .utils.log import get_logger, log_phase


def jax_backend_name() -> str:
    import jax

    return jax.default_backend()


def _is_device_array(x) -> bool:
    """True for a device-resident jax.Array (not a numpy array)."""
    import jax

    return isinstance(x, jax.Array) and not isinstance(x, np.ndarray)


def _as_keys_points(data):
    """Accept (N,k) arrays, (keys, vectors) pairs, or [(key, vec), ...]
    — the reference's RDD records are (key, vector) pairs (dbscan.py:107).

    A device-resident ``jax.Array`` passes through untouched: it is the
    TPU analogue of the reference's already-distributed RDD, and the
    single-shard driver clusters it without a host round trip.
    """
    if _is_device_array(data) and data.ndim == 2:
        return np.arange(data.shape[0]), data
    if isinstance(data, tuple) and len(data) == 2:
        keys, pts = np.asarray(data[0]), _as_float(data[1])
        if keys.ndim == 1 and pts.ndim == 2 and len(keys) == len(pts):
            return keys, pts
    if (
        isinstance(data, (list, tuple))
        and len(data) > 0
        and isinstance(data[0], tuple)
        and len(data[0]) == 2
        and np.ndim(data[0][1]) >= 1  # (key, vector), not a scalar 2-tuple
    ):
        keys = np.asarray([k for k, _ in data])
        pts = np.asarray([np.asarray(v, dtype=np.float64) for _, v in data])
        return keys, pts
    pts = _as_float(data)
    return np.arange(len(pts)), pts


def _as_float(data) -> np.ndarray:
    """Float view of the input, preserving float32/float64.

    Round 1 forced float64 here, which silently doubled host memory for
    float32 datasets — the common dtype at the target scale.
    """
    # A float np.memmap passes through UNTOUCHED (np.asarray would
    # strip the subclass and break downstream streaming detection; the
    # view's memory would still be file-backed, but the driver could
    # no longer tell).
    if isinstance(data, np.memmap) and data.dtype in (
        np.float32, np.float64
    ):
        return data
    pts = np.asarray(data)
    if pts.dtype not in (np.float32, np.float64):
        pts = pts.astype(np.float64)
    return pts


def _unit_rows(points) -> np.ndarray:
    """Rows scaled to unit L2 norm — the cosine metric's kernel frame.

    On the unit sphere the squared Euclidean distance is ``2 - 2
    cos(theta)``, monotone in angular distance, so after this
    projection the existing L2 kernels serve cosine thresholds
    exactly (``eps_cos -> sqrt(2 * eps_cos)``).  Norms accumulate in
    float64 (the centering-accuracy discipline), chunked so no
    dataset-sized f64 temp exists at any N; float32 inputs stay
    float32.  Zero rows have no direction and reject loudly — the
    sklearn input contract, not a silent all-noise fit.
    """
    pts = _as_float(points)
    out = np.empty(
        pts.shape, np.float64 if pts.dtype == np.float64 else np.float32
    )
    chunk = 1 << 20
    for s in range(0, len(pts), chunk):
        e = min(s + chunk, len(pts))
        sub = np.asarray(pts[s:e], np.float64)
        nrm = np.sqrt(np.einsum("ij,ij->i", sub, sub))
        if not np.isfinite(nrm).all():
            raise ValueError(
                "input contains NaN or infinite coordinates"
            )
        if not nrm.all():
            raise ValueError(
                "metric='cosine' is undefined for zero vectors: row(s) "
                "with zero norm in the input"
            )
        out[s:e] = (sub / nrm[:, None]).astype(out.dtype)
    return out


def _check_finite(points) -> None:
    """Raise ValueError on NaN/inf coordinates.

    A NaN poisons the Morton span (``partition.py`` quantization) into
    an all-identical sort key, which comes back as silently WRONG
    labels rather than an error — the sklearn-style input contract
    (reject, don't corrupt) is worth one streaming O(N*k) pass.  Host
    arrays check in chunks (no dataset-sized temp; memmaps stream from
    disk); device arrays reduce on device and fetch one bool.  Set
    PYPARDIS_SKIP_FINITE_CHECK=1 to skip for trusted pipelines where
    the extra read matters (e.g. repeated fits of a verified memmap).
    """
    if envreg.raw("PYPARDIS_SKIP_FINITE_CHECK") == "1":
        return
    if _is_device_array(points):
        import jax.numpy as jnp

        if not bool(jnp.all(jnp.isfinite(points))):
            raise ValueError(
                "input contains NaN or infinite coordinates"
            )
        return
    points = np.asarray(points)
    if points.dtype.kind not in "fc":
        return  # integral inputs are always finite
    chunk = 1 << 20
    for s in range(0, len(points), chunk):
        if not np.isfinite(points[s:s + chunk]).all():
            raise ValueError(
                "input contains NaN or infinite coordinates"
            )


# One host staging buffer, reused across fits of the same padded shape.
# Re-transferring from the SAME host buffer is ~100x cheaper than from a
# fresh allocation on tunneled deployments (the client pins/registers
# the buffer on first use; verified content-correct under in-place
# mutation) — so repeat fits (eps sweeps, warm benchmarks) skip the
# dominant host->device cost.  Only the most recent shape is kept:
# staging at 10M points is ~640MB of host RSS.
_staging: dict = {}


def _staging_buffer(k: int, cap: int) -> np.ndarray:
    """Borrow the staging buffer (callers return it via
    :func:`_staging_return` after the device transfer).

    The borrow/return protocol keeps concurrent fits correct: a second
    caller while the buffer is checked out simply allocates a fresh
    one (paying the slow-transfer cost, never corrupting the first
    caller's staged data).
    """
    buf = _staging.pop((k, cap), None)
    if buf is None:
        buf = np.empty((k, cap), np.float32)
    return buf


def _staging_return(buf: np.ndarray) -> None:
    _staging.clear()
    _staging[buf.shape] = buf


def _layout_cacheable(cap: int, k: int) -> bool:
    """Whether the single-shard layout cache may retain this fit's
    sorted device arrays between fits.

    The cached ``xs`` can reach ~2x cap rows after segment-break
    padding; retaining multi-GB arrays in HBM between fits would
    crowd out the next fit, so caching is capped (default 512MB of
    coordinates, PYPARDIS_LAYOUT_CACHE_MAX bytes to change) and
    PYPARDIS_LAYOUT_CACHE=0 disables it outright.
    """
    if envreg.raw("PYPARDIS_LAYOUT_CACHE", "1") == "0":
        return False
    max_bytes = int(
        envreg.raw("PYPARDIS_LAYOUT_CACHE_MAX", 1 << 29)
    )
    return 2 * cap * k * 4 <= max_bytes


# Pair-budget hints live in the shared LRU cache (utils.hints); both
# drivers consult and seed it through utils.budget.run_ladders.


def _pad_and_run(
    points, eps, min_samples, metric, block, precision="high", sort=True,
    backend="auto", jobstate=None,
):
    """Center, spatially sort, pad to a block multiple, run the kernel,
    un-sort and slice back.

    Centering (subtracting the dataset mean) is load-bearing: squared
    distances are computed in float32 via the |x|^2+|y|^2-2xy expansion,
    whose absolute error scales with coordinate magnitude — e.g. GPS
    data in projected meters (~1e6) would lose all precision near eps.
    Centering preserves distances and bounds magnitudes.

    Spatial sorting (Morton order) makes contiguous kernel tiles
    spatially tight so tile-level bbox pruning skips most of the N^2
    interaction; labels are root *indices*, so they are mapped back
    through the permutation before returning.
    """
    import jax.numpy as jnp

    from .ops.pipeline import (
        dbscan_device_pipeline,
        device_prep,
        unpack_pipeline_result,
    )
    from .parallel import staging as _dev_staging

    _dev_staging.begin_fit()
    staged = None
    layout_key = None
    if _is_device_array(points):
        n, k = points.shape
        block = clamp_block(block, n)
        cap = round_up(n, block)

        def make_dev():
            return device_prep(points, cap=cap)
    else:
        points = _as_float(points)
        n, k = points.shape
        block = clamp_block(block, n)
        cap = round_up(n, block)
        # The layout products (sorted/segment-broken device arrays)
        # depend only on the data content, geometry, and eps — cache
        # them through the staging economy so a warm repeat fit skips
        # the staging fill, the host->device transfer, AND the device
        # Morton sort (the pipeline's layout stage).  The fingerprint
        # (chunked crc32, ~1GB/s) is orders of magnitude below the
        # transfer it elides on tunneled deployments; gated off for
        # arrays whose retained copy would strain HBM, or via
        # PYPARDIS_LAYOUT_CACHE=0.
        if _layout_cacheable(cap, k):
            layout_key = (
                _dev_staging.points_fingerprint(points), block, cap,
                bool(sort and n > 2 * block), precision, float(eps),
            )

        def make_dev():
            # Host keeps only the float64 mean (float32 accumulation
            # would lose the centering accuracy that protects the
            # |x|^2+|y|^2-2xy expansion at GPS-scale magnitudes) and
            # the zero-pad to cap — so device programs are keyed on
            # the coarse cap, and nearby partition sizes share one
            # compilation.  Everything else — Morton coding, sort, the
            # kernel, un-permutation — runs on device
            # (:mod:`pypardis_tpu.ops.pipeline`), and the result comes
            # back as a single packed transfer: device->host latency
            # is a fixed cost per transfer, not per byte, on tunneled
            # deployments.  Transposed (k, cap) layout: XLA:TPU pads
            # the minor axis of an (N, small-k) buffer to 128 lanes
            # (8x HBM at k=16); point-axis-minor is dense.  Chunked
            # recentring: no full-size float64 temp at any N.  Lazy:
            # a layout-cache hit never fills or ships anything.
            nonlocal staged
            if staged is None:
                center = points.mean(axis=0, dtype=np.float64)
                pts_t = _staging_buffer(k, cap)
                pts_t[:, n:] = 0.0
                chunk = 1 << 20
                for s in range(0, n, chunk):
                    e = min(s + chunk, n)
                    np.subtract(
                        points[s:e].T, center[:, None],
                        out=pts_t[:, s:e], casting="unsafe",
                    )
                staged = pts_t
            # Re-put from the staging buffer: the first transfer is the
            # real cost; repeats from the same pinned buffer are ~8ms.
            # Off-TPU the "transfer" may be a zero-copy view over the
            # numpy memory — which _layout_gather then DONATES, so the
            # next same-shape fit would mutate freed/aliased storage.
            # An explicit copy keeps the reuse correct everywhere; the
            # pin/dedupe win only exists on the tunneled TPU runtime
            # anyway.
            if jax_backend_name() == "tpu":
                return jnp.asarray(staged)
            return jnp.array(staged, copy=True)

    def run(be, pair_budget=None):
        # Transient-fault retries live INSIDE dbscan_device_pipeline
        # (per stage); wrapping again here would multiply the retry
        # count and sleep time on genuine errors.  The pipeline already
        # returns a host array (its bulk fetch is the execution sync).
        # A fresh device copy per attempt: the layout gather DONATES
        # its input (the difference between fitting and OOM at high
        # dimension), so the previous attempt's copy is consumed.
        return np.asarray(
            dbscan_device_pipeline(
                make_dev,
                eps,
                n,
                min_samples=min_samples,
                metric=metric,
                block=block,
                precision=precision,
                backend=be,
                sort=bool(sort and n > 2 * block),
                pair_budget=pair_budget,
                layout_key=layout_key,
                jobstate=jobstate,
            )
        )

    def _restageable(e: BaseException) -> bool:
        # A retry can observe the donated device copy as deleted
        # (re-staging from source recovers), and make_dev() itself can
        # fail UNAVAILABLE while a crashed worker restarts.  Both are
        # worth the backed-off ladder; everything else re-raises.
        ok = "deleted" in str(e) or "UNAVAILABLE" in (
            f"{type(e).__name__}: {e}"
        )
        if ok:
            # Cached layout arrays may be the deleted buffers — the
            # retry must rebuild them, never re-serve dead handles.
            _dev_staging.device_evict("pipeline_layout")
        return ok

    def run_with_restage(be, pair_budget=None):
        # The layout gather donates its input, so each attempt
        # re-stages a fresh device copy; the retry ladder is the shared
        # one from the pipeline (ops/pipeline._transient_retry).
        from .ops.pipeline import _transient_retry

        return _transient_retry(
            "restage", lambda: run(be, pair_budget), retryable=_restageable
        )

    # The shared ladder (utils.budget.run_ladders) consults and seeds
    # the hint cache: data whose density defeats the default budget
    # would otherwise pay the double extract-overflow-rerun (and its
    # recompile) on EVERY fit — observed at 30M x 16-D.  eps/metric
    # are part of the key (the live-pair count depends on them
    # directly); the metric is normalized so callable specs share
    # hints with their string spellings.
    from .ops.distances import _norm_metric
    from .ops.sketch import sketch_dims
    from .utils.budget import run_ladders
    from .utils.hints import dispatch_tag

    # The resolved sketch k is part of the hint key: sketch-space tile
    # boxes prune differently than full-d boxes, so a budget learned
    # with the prefilter on must not seed a sketch-off extraction (and
    # vice versa).
    sketch_k = sketch_dims(k, _norm_metric(metric))
    budget_key = (
        dispatch_tag(cap // block), (k, cap), block, precision,
        float(eps), _norm_metric(metric), sketch_k,
    )

    def ladder(be):
        def run_step(pb, _mr):
            packed = run_with_restage(be, pair_budget=pb)
            # In-band [total, budget] stats ride in the packed row's
            # tail (then the kernel pass count and the two mixed-mode
            # band columns).
            return packed, packed[-5:-3], True

        return run_ladders(run_step, budget_key, None, 1)[0]

    try:
        packed = ladder(backend)
    except Exception as e:  # noqa: BLE001 — rethrown unless a kernel fails
        from .ops.labels import is_kernel_lowering_error

        # 'auto' promises a working default: a Pallas build that cannot
        # lower on this chip degrades to the XLA path with a warning
        # instead of a Mosaic internals dump.  An explicit
        # backend='pallas' stays strict (hardware smoke tests rely on
        # it actually exercising Mosaic).
        if backend != "auto" or not is_kernel_lowering_error(e):
            raise
        get_logger().warning(
            "Pallas kernel failed to lower on %s; falling back to the "
            "XLA kernel path (%s)", jax_backend_name(), e,
        )
        from .utils.retry import note_degraded

        note_degraded("kernel_xla", error=str(e)[:160])
        packed = ladder("xla")
    if staged is not None:
        # The pipeline's host fetch has completed, so the input
        # transfer is long since consumed — safe to recycle the buffer.
        _staging_return(staged)
    roots, core, total, _budget, passes, band_pairs, rescored = (
        unpack_pipeline_result(packed)
    )
    from .obs import current as obs_current
    from .ops.pallas_kernels import _norm_precision_mode, effective_tile

    reused, shipped = _dev_staging.fit_stats()
    eff_block = int(
        effective_tile(block, cap, k, _norm_precision_mode(precision))
        or block
    )
    # The kernel grid's true tile count (the pipeline gauges it — the
    # segment-break layout can pad the kernel capacity past cap, which
    # the packed result doesn't carry): live_pair_fraction's
    # denominator is tiles^2.
    tiles = int(
        obs_current().metrics.gauge("pipeline.kernel_tiles", 0) or 0
    )
    info = {
        "live_pairs": int(total),
        "kernel_passes": int(passes),
        "kernel_tiles": tiles if tiles > 0 else max(1, cap // eff_block),
        "kernel_block": eff_block,
        # Band telemetry (zeros off precision="mixed" and sketch):
        # pairs whose fast-pass / sketch-gate d^2 landed in the rescore
        # band, and tile-pair visits re-run at full precision.  With
        # the sketch prefilter on, the columns are OWNED by the sketch
        # pass (it replaces the mixed fast pass as the classifier).
        "band_pairs": int(band_pairs),
        "rescored_tiles": int(rescored),
        # Resolved random-projection prefilter width (0 = off).
        "sketch_k": int(sketch_k),
        # Layout-cache economy (route "pipeline_layout"): a warm repeat
        # fit reuses the sorted device arrays and ships nothing.
        "staged_bytes_reused": int(reused),
        "staged_bytes": int(shipped),
    }
    return roots[:n], core[:n], info


def _expanded_neighbors(tree, points, eps) -> Dict:
    """{partition label -> point indices in its 2*eps-expanded box} —
    the single constructor of the ``neighbors`` parity surface for BOTH
    sharded routes (host eager, device lazy-on-access)."""
    from .partition import expanded_members

    members = expanded_members(tree, np.asarray(points), 2 * eps)
    return {l: members[l][0] for l in sorted(members)}


def _partition_cluster_dict(parts: np.ndarray, labels: np.ndarray) -> Dict:
    """{"partition:cluster" -> global id} parity codes (reference
    ``cluster_dict``, dbscan.py:99-102): the global dense label doubles
    as the per-partition cluster id after the in-graph merge."""
    sel = labels >= 0
    codes = np.unique(
        parts[sel].astype(np.int64) << 32 | labels[sel].astype(np.int64)
    )
    return {
        f"{c >> 32}:{c & 0xFFFFFFFF}": int(c & 0xFFFFFFFF) for c in codes
    }


def dbscan_partition(iterable, params):
    """API-parity port of the per-partition worker (dbscan.py:12-34).

    Takes ((key, partition), vector) records, runs the TPU kernel on the
    stacked vectors, yields ``(key, "part:cluster[*]")`` with ``'*'``
    marking non-core points — the exact label wire format the reference's
    aggregator consumes.
    """
    data = list(iterable)
    if not data:
        return
    (_, part), _ = data[0]
    x = _as_float(np.stack([np.asarray(v) for (_k, _p), v in data]))
    y = [k for (k, _p), _v in data]
    roots, core, _kinfo = _pad_and_run(
        x,
        params["eps"],
        params["min_samples"],
        params.get("metric", "euclidean"),
        block=256,
        precision=params.get("precision", "high"),
        backend=params.get("backend", "auto"),
    )
    labels = densify_labels(roots)
    for i in range(len(x)):
        flag = "" if core[i] else "*"
        yield (y[i], "%i:%i%s" % (part, labels[i], flag))


def map_cluster_id(x, mapping: Dict[str, int]):
    """Parity port of dbscan.py:37-53 with a plain dict instead of a
    pyspark Broadcast: strip the core marker, look up the global id,
    noise / unmapped → -1."""
    key, cluster_id = x
    cluster_id = next(iter(cluster_id)).strip("*")
    if "-1" not in cluster_id and cluster_id in mapping:
        return key, mapping[cluster_id]
    return key, -1


class SweepResult:
    """Result of an amortized hyperparameter sweep (:meth:`DBSCAN.sweep`).

    ``configs`` is the requested ``(eps, min_samples)`` grid in request
    order; per-config dense labels and core masks are byte-identical to
    an independent ``train()`` at that config on the same mode (the
    sweep's correctness contract, pinned in tests).  ``stats`` is the
    ``report()["sweep"]`` telemetry block; ``per_config`` one dict per
    config (relabel seconds, cluster count, staging reuse).
    """

    def __init__(self, configs, labels, core, per_config, stats):
        self.configs = list(configs)
        self._labels = labels
        self._core = core
        self.per_config = per_config
        self.stats = stats

    def _key(self, eps, min_samples=None):
        if min_samples is None:
            matches = [c for c in self.configs if c[0] == float(eps)]
            if len(matches) != 1:
                raise KeyError(
                    f"eps={eps} matches {len(matches)} configs; pass "
                    f"min_samples too"
                )
            return matches[0]
        key = (float(eps), int(min_samples))
        if key not in self._labels:
            raise KeyError(f"config {key} was not in this sweep")
        return key

    def labels(self, eps, min_samples=None) -> np.ndarray:
        """Dense labels for one config (noise = -1)."""
        return self._labels[self._key(eps, min_samples)]

    def core(self, eps, min_samples=None) -> np.ndarray:
        """Core-sample mask for one config."""
        return self._core[self._key(eps, min_samples)]

    def __len__(self) -> int:
        return len(self.configs)

    def __iter__(self):
        for c in self.configs:
            yield c, self._labels[c]


def sweep_dbscan(points, eps_list, min_samples_list=None, **kw):
    """Functional amortized sweep: ONE distance pass, k clusterings.

    ``kw`` are :class:`DBSCAN` constructor arguments; returns the
    :class:`SweepResult`.  Equivalent to
    ``DBSCAN(**kw).sweep(points, eps_list, min_samples_list)`` — the
    model (with its ``report()`` carrying the ``sweep`` block) is
    reachable as ``result.model``.
    """
    model = DBSCAN(**kw)
    result = model.sweep(points, eps_list, min_samples_list)
    result.model = model
    return result


class DBSCAN:
    """Distributed density-based clustering on a TPU mesh.

    Hyperparameter surface matches the reference exactly
    (dbscan.py:74-102): ``eps``, ``min_samples``, ``metric`` (string or
    scipy callable; Euclidean/cityblock only — box expansion is L-inf),
    ``max_partitions``.
    """

    def __init__(
        self,
        eps: Optional[float] = 0.5,
        min_samples: int = 5,
        metric="euclidean",
        min_cluster_size: Optional[int] = None,
        max_partitions: Optional[int] = None,
        split_method: str = "min_var",
        block: Optional[int] = None,
        mesh=None,
        precision: Optional[str] = None,
        kernel_backend: str = "auto",
        merge: Optional[str] = None,
        profile_dir: Optional[str] = None,
        owner_computes: bool = True,
        overlap: Optional[bool] = None,
        mode: Optional[str] = None,
        flight: Optional[str] = None,
        auto: bool = False,
        tune_corpus: Optional[str] = None,
        sketch=None,
    ):
        # Auto-tuning (pypardis_tpu.tune): knobs the caller passed
        # explicitly are PINNED — the planner never overrides them;
        # ``None`` defaults resolve to the historical values here, so
        # non-auto behavior is unchanged, while ``auto=True`` plans
        # every unpinned knob per fit from a dataset probe + the
        # telemetry corpus.  PYPARDIS_DISPATCH counts as a user pin of
        # the dispatch knob.
        self._tune_pinned: Dict = {}
        if block is not None:
            self._tune_pinned["block"] = int(block)
        else:
            block = 1024
        if precision is not None:
            self._tune_pinned["precision"] = precision
        else:
            precision = "high"
        if merge is not None:
            self._tune_pinned["merge"] = merge
        else:
            merge = "auto"
        if mode is not None:
            self._tune_pinned["mode"] = mode
        else:
            mode = "auto"
        env_dispatch = envreg.raw("PYPARDIS_DISPATCH")
        if env_dispatch and env_dispatch != "auto":
            self._tune_pinned["dispatch"] = env_dispatch
        # Sketch prefilter knob (int k | "auto" | None).  Label-neutral
        # for any k (certified gates + exact rescore), so it rides the
        # PYPARDIS_SKETCH env token for the fit body exactly like the
        # planned dispatch — no signature threading through the
        # drivers.  An explicit value (or a non-"auto" env) pins it
        # against the planner.
        from .ops.sketch import check_sketch_spec

        self.sketch = (
            check_sketch_spec(sketch) if sketch is not None else None
        )
        if self.sketch is not None:
            self._tune_pinned["sketch"] = self.sketch
        else:
            env_sketch = envreg.raw("PYPARDIS_SKETCH")
            if env_sketch is not None and env_sketch != "auto":
                self._tune_pinned["sketch"] = env_sketch
        self.auto = bool(auto)
        # Local corpus override for the auto-fit feedback loop (None
        # defers to PYPARDIS_TUNE_CORPUS / the default archive path).
        self.tune_corpus = tune_corpus
        self._tune_stats: Optional[Dict] = None
        if mode not in ("auto", "kd", "global_morton"):
            raise ValueError(
                f"mode must be auto|kd|global_morton, got {mode!r}"
            )
        # Construction-time validation (the sklearn input contract): a
        # typo'd precision/backend/eps used to surface only when the
        # first fit hit a jit trace or a kernel dispatch, as an opaque
        # deep-stack error.  check_precision also canonicalizes
        # jax.lax.Precision spellings to the mode strings, so report()
        # params and cache keys are stable.
        from .utils.validate import (
            check_kernel_backend, check_metric, check_precision,
        )

        # eps=None opts into the density-hierarchy path (ops.hierarchy):
        # fit() selects eps by HDBSCAN*'s stability rule and exposes it
        # as ``eps_``; a concrete eps still validates loudly.
        validate_params(eps, min_samples, allow_none_eps=True)
        self.eps = None if eps is None else float(eps)
        self.min_samples = int(min_samples)
        # Condensation granularity of the hierarchy path; None defers
        # to max(min_samples, 2) (the HDBSCAN* default coupling).
        if min_cluster_size is not None and int(min_cluster_size) < 2:
            raise ValueError(
                f"min_cluster_size must be >= 2, got {min_cluster_size}"
            )
        self.min_cluster_size = (
            None if min_cluster_size is None else int(min_cluster_size)
        )
        self.metric = metric
        # Canonical metric name ("euclidean"/"cityblock"/"cosine") —
        # cosine is a DRIVER metric (unit-normalize + eps remap onto
        # the L2 kernels, see _kernel_frame); validated here so a bad
        # spec fails at construction, not deep inside a fit.
        self._metric_norm = check_metric(metric, eps)
        self.max_partitions = max_partitions
        self.split_method = split_method
        self.block = int(block)
        self.mesh = mesh
        self.precision = check_precision(precision)
        self.kernel_backend = check_kernel_backend(kernel_backend)
        self.merge = merge
        self.profile_dir = profile_dir
        # Owned-block clustering + edge-table merge on the sharded
        # paths (halo points are adjacency evidence, never re-clustered
        # — see parallel.sharded).  False restores the legacy
        # duplicate-and-recluster step for A/B comparison.
        self.owner_computes = bool(owner_computes)
        # Double-buffered 1-device chained execution (host slab build
        # overlapped with device compute); None defers to the
        # PYPARDIS_CHAINED_OVERLAP env switch (default on).
        self.overlap = overlap
        # Distributed execution mode: "auto"/"kd" run the KD-partition
        # + 2*eps-halo family; "global_morton" shards by contiguous
        # ranges of the global Morton order — zero duplicated rows,
        # boundary TILES ride the exchange ring (parallel.global_morton).
        self.mode = mode
        # Crash-safe flight recorder (pypardis_tpu.obs.flight): a
        # *.jsonl path or a directory for per-fit files; None defers to
        # PYPARDIS_FLIGHT.  A killed run leaves a parseable JSONL
        # post-mortem that obs.replay() turns back into a Chrome trace
        # and a partial report.
        self.flight = flight
        # Reference attribute surface (dbscan.py:93-102).
        self.data = None
        self._result_cache = None
        self.bounding_boxes: Optional[Dict[int, BoundingBox]] = None
        self.expanded_boxes: Optional[Dict[int, BoundingBox]] = None
        self.neighbors = None
        self.cluster_dict = None
        # TPU-native extras.
        self.labels_: Optional[np.ndarray] = None
        self.core_sample_mask_: Optional[np.ndarray] = None
        self.partitioner_: Optional[KDPartitioner] = None
        self.metrics_: Dict[str, float] = {}
        # Telemetry of the most recent fit (pypardis_tpu.obs): the
        # registry/tracer/event-log behind report()/summary()/
        # export_trace().
        self._recorder = None
        self._fit_info: Dict[str, int] = {}
        # Amortized-sweep telemetry of the most recent sweep() — the
        # ``sweep`` block of report().
        self._sweep_stats: Optional[Dict] = None
        # Density-hierarchy state (eps=None fits / sweep("auto")): the
        # ``hierarchy`` block of report(), and the stability-selected
        # eps in the USER frame — the value predict/serving runs at
        # when the model was fitted with eps=None.
        self._hier_stats: Optional[Dict] = None
        self.eps_: Optional[float] = None
        # Serving state (pypardis_tpu.serve): the cached query engine
        # and, for checkpoint-loaded models, the persisted core-point
        # coordinates the index builds from.
        self._serve_engine = None
        self._serve_core_points = None
        # Live-update state (pypardis_tpu.serve.live): the cached
        # LiveModel behind insert()/delete(), its telemetry dict (the
        # report()'s ``live`` block), and the fit generation counter a
        # stale held engine is checked against.
        self._live_model = None
        self._live_stats = None
        self._fit_generation = 0
        # Checkpoint-resumable fit state (utils.jobstate), created per
        # train() when resume=/PYPARDIS_CKPT asks for it.
        self._jobstate = None

    # -- the cosine kernel frame ------------------------------------------

    @property
    def kernel_eps(self) -> float:
        """eps in the KERNEL frame: for ``metric='cosine'`` the L2
        threshold ``sqrt(2 * eps)`` on the unit sphere (``d^2 = 2 - 2
        cos``, monotone in angular distance); for
        ``metric='haversine'`` the CHORD ``2 sin(eps / 2)`` of the
        great-circle angle (monotone on [0, pi]); else eps unchanged.
        The serving index builds against this value
        (:func:`pypardis_tpu.serve.index.build_index`).  An eps=None
        model resolves to the fitted ``eps_`` — the stability-selected
        cut — so predict/serving run at exactly the eps the labels were
        computed at."""
        eps = self._effective_eps()
        if self._metric_norm == "cosine":
            return float(np.sqrt(2.0 * eps))
        if self._metric_norm == "haversine":
            return float(2.0 * np.sin(eps / 2.0))
        return float(eps)

    def _effective_eps(self) -> float:
        """``self.eps``, or the stability-selected ``eps_`` of an
        eps=None model (hierarchy path).  Raises before the first fit —
        there is no eps to serve at until the hierarchy selects one."""
        if self.eps is not None:
            return float(self.eps)
        if self.eps_ is not None:
            return float(self.eps_)
        raise RuntimeError(
            "this model was constructed with eps=None and has not been "
            "fitted yet — fit() selects eps by the stability rule and "
            "exposes it as eps_"
        )

    def _kernel_frame(self):
        """Context manager swapping ``(eps, metric)`` to the kernel
        frame for the duration of a fit/sweep body.

        For the driver metrics (cosine, haversine), every internal
        consumer of ``self.eps`` / ``self.metric`` — halo expansion,
        staging keys, jobstate metadata, the kernels themselves —
        must see the remapped L2 values, and there are a dozen such
        sites; one swap at the boundary keeps them all consistent.
        User-facing values are restored on exit (``report()`` params
        and checkpoints carry the original spec).  A no-op for the
        kernel metrics.
        """
        import contextlib

        if self._metric_norm not in ("cosine", "haversine"):
            return contextlib.nullcontext()

        @contextlib.contextmanager
        def swap():
            saved = (self.eps, self.metric)
            # eps=None hierarchy bodies pick their own kernel-frame
            # ceiling (_hier_ceiling) — only the metric swap matters;
            # every sweep/hierarchy consumer takes eps explicitly.
            eps_k = None if saved[0] is None else self.kernel_eps
            self.eps, self.metric = eps_k, "euclidean"
            try:
                yield
            finally:
                self.eps, self.metric = saved

        return swap()

    # -- training ---------------------------------------------------------

    def train(self, data, resume: Optional[str] = None) -> "DBSCAN":
        """Cluster a (key, vector) dataset (reference dbscan.py:104-126).

        ``metric='cosine'`` fits run in the unit-sphere kernel frame:
        rows are unit-normalized (``model.data`` holds the normalized
        points — the frame every downstream surface, serving included,
        shares) and eps remaps to ``sqrt(2 * eps)`` for the L2 kernels;
        labels are exactly the cosine-threshold clustering.

        ``eps=None`` models take the density-hierarchy path instead:
        one distance pass at a data-derived ceiling, the
        mutual-reachability MST, and HDBSCAN*'s stability rule select
        the flat cut (``eps_``) — see :meth:`_fit_hierarchy`.
        """
        if self.eps is None:
            if resume is not None:
                raise ValueError(
                    "resume/checkpointing is not supported on the "
                    "eps=None hierarchy path"
                )
            return self._fit_hierarchy(data)
        if self._metric_norm in ("cosine", "haversine"):
            keys, points = _as_keys_points(data)
            with self._kernel_frame():
                self._train_impl(
                    (keys, self._driver_frame_rows(points)), resume
                )
            self.eps_ = float(self.eps)
            return self

        self._train_impl(data, resume)
        self.eps_ = float(self.eps)
        return self

    def _driver_frame_rows(self, points) -> np.ndarray:
        """Project raw input rows into the driver metric's kernel
        frame: unit-normalized for cosine, (lat, lon) radians embedded
        onto the 3-D unit sphere for haversine (``model.data`` holds
        the projected rows — the frame every downstream surface,
        serving included, shares)."""
        if self._metric_norm == "cosine":
            return _unit_rows(points)
        from .geometry import latlon_to_unit_sphere

        return latlon_to_unit_sphere(points)

    def _train_impl(self, data, resume: Optional[str] = None) -> "DBSCAN":
        """The metric-agnostic fit body (kernel-frame eps/metric).

        With ``profile_dir`` set, the whole run executes under a
        ``jax.profiler`` trace (TensorBoard/Perfetto-viewable), and
        per-phase wall times always flow through
        :class:`~pypardis_tpu.utils.profiling.PhaseTimer` into
        ``metrics_`` — phases end on materialized outputs, so the
        numbers include async device execution.

        ``resume=path`` makes the fit checkpoint-resumable
        (:mod:`pypardis_tpu.utils.jobstate`): phase-boundary snapshots
        (completed chained partitions, stepped propagation state, the
        global-Morton fixpoint ``lab_map``) stream to ``path`` at the
        ``PYPARDIS_CKPT_EVERY_S`` cadence, and a fit SIGKILLed mid-run
        replays only the unfinished work when retrained with the same
        ``resume`` path — labels byte-identical to an uninterrupted
        fit (the file's fit fingerprint rejects mismatched data or
        params).  ``PYPARDIS_CKPT=<path>`` enables snapshot WRITING for
        fits that never pass ``resume``.
        """
        import contextlib

        from . import obs
        from .utils.profiling import PhaseTimer, trace

        validate_params(self.eps, self.min_samples)
        keys, points = _as_keys_points(data)
        # Auto-tuning happens BEFORE the jobstate opens: the checkpoint
        # fingerprint must describe the PLANNED config (block/mode ride
        # in fit_meta), and planning is deterministic given the same
        # data, env, and corpus — a resumed auto fit re-plans the same
        # config or the fingerprint rejects it loudly.
        dispatch_token = None
        sketch_token = None
        self._tune_stats = None
        if self.auto and len(points):
            dispatch_token, sketch_token = self._plan_auto(points)
        if self.sketch is not None and sketch_token is None:
            # The constructor pin rides the same env token the planner
            # uses — the kernels resolve PYPARDIS_SKETCH wherever a
            # driver doesn't thread the knob explicitly.
            sketch_token = envreg.raw("PYPARDIS_SKETCH", "")
            os.environ["PYPARDIS_SKETCH"] = str(self.sketch)
        ckpt_path = resume or envreg.raw("PYPARDIS_CKPT")
        if ckpt_path:
            from .utils.jobstate import JobState, fit_meta

            self._jobstate = JobState.open(
                ckpt_path,
                fit_meta(
                    points, eps=self.eps, min_samples=self.min_samples,
                    metric=self.metric if isinstance(self.metric, str)
                    else getattr(self.metric, "__name__", "callable"),
                    block=self.block, mode=self.mode,
                ),
                resume=resume is not None,
            )
        else:
            self._jobstate = None
        self._keys = keys
        self.data = points
        t0 = time.perf_counter()
        # Fresh telemetry per fit: recorder (registry + tracer + event
        # log) behind report()/summary()/export_trace(), and a clean
        # metrics_ so refits never carry a previous run's stats.
        rec = obs.RunRecorder()
        self._recorder = rec
        self.metrics_ = {}
        # A refit invalidates the serving surface: the cached engine
        # indexes the PREVIOUS clustering, and checkpoint-carried core
        # points describe a model this fit replaces.  The generation
        # bump is what lets a caller-held stale engine/LiveModel raise
        # a clear error instead of silently serving the old model.
        self._serve_engine = None
        self._serve_core_points = None
        self._live_model = None
        self._live_stats = None
        self._fit_generation += 1
        # A concrete-eps fit supersedes any earlier hierarchy fit: the
        # fitted eps IS the model's eps, and a stale hierarchy block
        # would describe the previous clustering.
        self._hier_stats = None

        if len(points) == 0:
            self.labels_ = np.empty(0, np.int32)
            self.core_sample_mask_ = np.empty(0, bool)
            self.bounding_boxes, self.expanded_boxes = {}, {}
            self.neighbors, self.cluster_dict = {}, {}
            self.result = []
            self.metrics_ = {"total_s": 0.0, "points_per_sec": 0.0}
            self._fit_info = {
                "n_dims": int(points.shape[1]) if points.ndim == 2 else 0,
                "n_devices": 1,
            }
            return self

        timer = PhaseTimer()
        ctx = (
            trace(self.profile_dir)
            if self.profile_dir
            else contextlib.nullcontext()
        )
        n_devices = self._n_devices()
        sharded = n_devices > 1 and len(points) >= 2 * n_devices
        # Crash-safe telemetry: the flight sink (opt-in) streams every
        # span/gauge/event to disk, and the resource sampler thread
        # tracks host-RSS / device-bytes / staging-pool watermarks.
        # Both are torn down in the finally — a fit that raises still
        # joins the sampler and seals the flight file with the error.
        flight = obs.open_flight(self.flight)
        if flight is not None:
            from .parallel import dist as _dist

            rec.attach_flight(flight)
            flight.header(
                params={
                    "eps": self.eps,
                    "min_samples": self.min_samples,
                    "mode": self.mode,
                    "merge": self.merge,
                    "block": self.block,
                },
                n_points=int(len(points)),
                n_dims=int(points.shape[1]),
                n_devices=int(n_devices if sharded else 1),
                n_processes=int(_dist.process_count()),
                process_index=int(_dist.process_index()),
                backend=jax_backend_name(),
            )
        # Live export plane (opt-in via PYPARDIS_METRICS_PORT /
        # PYPARDIS_METRICS_SNAPSHOT): the fit's registry, heartbeats,
        # open spans, and resource watermarks become scrapeable /
        # snapshotted WHILE the fit runs.  Attached after the flight
        # sink so the exporter fanout tees the same record stream.
        exporters = obs.attach_exporters(rec)
        sampler = obs.ResourceSampler(rec).start()
        try:
            with obs.use_recorder(rec), ctx:
                # Inside the recorded region: the finite check is a
                # data-dependent streaming pass (seconds at 100M
                # points), and a rejected input should seal the flight
                # file with the error rather than leave no record.
                _check_finite(points)
                if sharded:
                    self._train_sharded(points, n_devices, timer)
                else:
                    self._train_single(points, timer)
                self.metrics_.update(timer.as_dict())
                self.metrics_["total_s"] = time.perf_counter() - t0
                self.metrics_["points_per_sec"] = len(points) / max(
                    self.metrics_["total_s"], 1e-9
                )
                log_phase(
                    "train",
                    n=len(points),
                    clusters=(
                        int(self.labels_.max()) + 1 if len(points) else 0
                    ),
                    **{k: round(v, 4) for k, v in self.metrics_.items()
                       if isinstance(v, float)},
                )
            self._fit_info = {
                "n_dims": int(points.shape[1]),
                "n_devices": int(n_devices if sharded else 1),
            }
            # Absorb the scalar metrics into the registry so the
            # registry dump alone (counters/gauges/timings) is a
            # complete record.
            for k, v in self.metrics_.items():
                if k.endswith("_s"):
                    continue
                if isinstance(v, (bool, int, float, str, np.integer,
                                  np.floating)):
                    rec.metrics.set(f"run.{k}", v)
        except BaseException as e:
            if flight is not None:
                flight.finish(
                    status="error",
                    error=f"{type(e).__name__}: {str(e)[:300]}",
                )
            raise
        finally:
            sampler.stop()
            if exporters is not None:
                exporters.close()
            if dispatch_token is not None:
                # The planned dispatch rode in PYPARDIS_DISPATCH for
                # the fit body only; restore the ambient value so a
                # later non-auto fit sees the user's environment.
                prev = dispatch_token
                if prev == "":
                    os.environ.pop("PYPARDIS_DISPATCH", None)
                else:
                    os.environ["PYPARDIS_DISPATCH"] = prev
            if sketch_token is not None:
                # Same discipline for the sketch knob's env token.
                if sketch_token == "":
                    os.environ.pop("PYPARDIS_SKETCH", None)
                else:
                    os.environ["PYPARDIS_SKETCH"] = sketch_token
            if self._jobstate is not None:
                # Persist any boundary state the cadence was still
                # holding (a SIGKILL needs no help — every boundary
                # write is atomic; this covers ordinary exceptions).
                try:
                    self._jobstate.flush(force=True)
                except OSError:
                    pass
            if flight is not None:
                flight.finish(status="ok")  # no-op after an error seal
                flight.close()
        # The key-sorted ``result`` list (the reference's final
        # ``sortByKey()``, dbscan.py:164) materializes LAZILY on first
        # access: building N Python tuples costs real wall time at
        # bench scale and gigabytes at the north star, and fit_predict
        # callers never read it.
        self._result_cache = None
        if self.auto and self._tune_stats is not None:
            self._tune_finalize()
        return self

    def fit(self, X) -> "DBSCAN":
        # A device-resident jax.Array flows through without a host
        # round trip (the TPU analogue of an already-distributed RDD);
        # a disk-backed np.memmap stays a memmap so the sharded path
        # can stream it device-by-device.
        if _is_device_array(X) or isinstance(X, np.memmap):
            return self.train(X)
        return self.train(np.asarray(X))

    def fit_predict(self, X) -> np.ndarray:
        return self.fit(X).labels_

    # -- amortized hyperparameter sweeps ----------------------------------

    def sweep(self, data, eps_list, min_samples_list=None) -> SweepResult:
        """Fit every ``(eps, min_samples)`` config with ONE distance pass.

        Hyperparameter search is the workload real users run, and a
        k-config sweep used to pay the full MXU distance pass k times.
        This runs the distance kernels ONCE at ``eps_max =
        max(eps_list)`` and materializes a compacted neighbor-pair
        graph — per live tile pair, the surviving ``(i, j, d2)``
        triples into a budgeted device slab (the OPTICS one-pass/
        many-eps idea, Ankerst et al. SIGMOD 1999, on the Clipper-style
        amortization this repo already serves reads with).  Each config
        then re-thresholds the cached ``d2`` for counts and
        min-propagates labels to a fixpoint over the cached pair list —
        no distance recomputation, no re-staging of owned slabs
        (eps-free staging keys), halo/boundary context built once at
        eps_max so every smaller eps is covered by construction.

        Per-config labels are BYTE-IDENTICAL to an independent
        ``train()`` at that config on the same mode (fused / KD
        owner-computes / global-Morton), pinned in tests.  One known
        caveat, shared with the engine family's own cross-route
        parity: a NON-CORE border point within eps of core points of
        two clusters that stay distinct attaches per the relabel
        engine's canonical-min rule, while each train() route makes
        its own slab-order-dependent choice there (train(kd) vs
        train(fused) already disagree on such points) — the clustering
        partition is identical either way, only that border's cluster
        id differs, and parity geometries without cluster-contact
        borders are exact.  Graph
        overflow past ``PYPARDIS_SWEEP_MAX_PAIRS`` — or any degradable
        build failure — falls back label-safely to per-config refits
        (k distance passes, never wrong labels;
        ``report()["sweep"]["degraded"]`` says so).

        ``min_samples_list=None`` sweeps eps at this model's
        ``min_samples``; otherwise the full eps × min_samples grid
        runs.  Sorted and unsorted ``eps_list`` give identical
        per-config results (the graph depends only on eps_max).  The
        model surface (``labels_`` etc.) is left at the LAST config;
        ``report()["sweep"]`` carries ``distance_passes``,
        ``graph_pairs``, ``graph_bytes``, per-config relabel seconds
        and the amortization estimate.  ``metric='cosine'`` sweeps
        ride the same cached graph (thresholds remap monotonically).
        """
        import time as _time

        from . import obs
        from .utils.profiling import PhaseTimer
        from .utils.validate import check_metric

        # eps_list="auto" extracts the top-stability eps ladder from the
        # density hierarchy instead of requiring a user grid — same ONE
        # distance pass, per-rung labels from dendrogram cuts (no
        # per-config fixpoint at all).
        auto_ladder = isinstance(eps_list, str)
        if auto_ladder and eps_list != "auto":
            raise ValueError(
                f"eps_list must be a sequence of eps values or the "
                f"string 'auto', got {eps_list!r}"
            )
        if auto_ladder:
            eps_vals = None
        else:
            eps_arr = np.atleast_1d(np.asarray(eps_list, np.float64))
            if eps_arr.ndim != 1 or len(eps_arr) == 0:
                raise ValueError(
                    "eps_list must be a non-empty 1-D sequence"
                )
            eps_vals = [float(e) for e in eps_arr]
            for e in eps_vals:
                validate_params(e, 1)
                check_metric(self.metric, e)
        if min_samples_list is None:
            ms_vals = [int(self.min_samples)]
        else:
            ms_arr = np.atleast_1d(np.asarray(min_samples_list))
            if ms_arr.ndim != 1 or len(ms_arr) == 0:
                raise ValueError(
                    "min_samples_list must be None or a non-empty 1-D "
                    "sequence"
                )
            ms_vals = [int(m) for m in ms_arr]
        for m in ms_vals:
            validate_params(1.0, m)
        configs = (
            None if auto_ladder
            else [(e, m) for e in eps_vals for m in ms_vals]
        )

        keys, points = _as_keys_points(data)
        if self._metric_norm in ("cosine", "haversine"):
            points = self._driver_frame_rows(points)
        if len(points) == 0:
            raise ValueError("sweep needs a non-empty dataset")

        t0 = _time.perf_counter()
        rec = obs.RunRecorder()
        self._recorder = rec
        self.metrics_ = {}
        self._serve_engine = None
        self._serve_core_points = None
        self._live_model = None
        self._live_stats = None
        self._fit_generation += 1
        self._keys = keys
        self.data = points
        self.partitioner_ = None
        self.bounding_boxes = self.expanded_boxes = None
        self.neighbors = None
        self.cluster_dict = None
        self._sweep_stats = None
        self._hier_stats = None
        timer = PhaseTimer()
        sampler = obs.ResourceSampler(rec).start()
        try:
            with obs.use_recorder(rec):
                _check_finite(points)
                with self._kernel_frame():
                    if auto_ladder:
                        labels, core, per_cfg, sweep = (
                            self._sweep_auto_run(points, ms_vals, timer)
                        )
                        configs = [tuple(c) for c in sweep["configs"]]
                    else:
                        labels, core, per_cfg, sweep = self._sweep_run(
                            points, configs, timer
                        )
        finally:
            sampler.stop()
        self._result_cache = None
        # Model surface from the LAST config (a sweep leaves a fitted
        # model, like a fit at that config would).
        last = configs[-1]
        self.eps_ = float(last[0])
        self.labels_ = labels[last]
        self.core_sample_mask_ = core[last]
        self.metrics_.update(timer.as_dict())
        self.metrics_["total_s"] = _time.perf_counter() - t0
        self.metrics_["points_per_sec"] = (
            len(configs) * len(points) / max(self.metrics_["total_s"], 1e-9)
        )
        from .parallel import staging as _dev_staging

        reused, shipped = _dev_staging.fit_stats()
        self.metrics_.setdefault("staged_bytes_reused", int(reused))
        self.metrics_.setdefault("staged_bytes", int(shipped))
        self.metrics_.setdefault("live_pairs", int(sweep["graph_pairs"]))
        wall = self.metrics_["total_s"]
        sweep["sweep_wall_s"] = round(wall, 6)
        # Amortization ESTIMATE from the sweep's own walls: a solo fit
        # ~ one distance/graph pass + one propagation.  The probe
        # (scripts/sweep_probe.py) measures the real ratio against
        # actual solo fits and gates on it.
        solo_est = sweep.get("graph_build_s", 0.0) + (
            sweep["relabel_s"][0] if sweep.get("relabel_s") else 0.0
        )
        sweep["sweep_amortization"] = round(
            len(configs) * solo_est / max(wall, 1e-9), 4
        )
        self._sweep_stats = sweep
        self._fit_info = {
            "n_dims": int(points.shape[1]),
            "n_devices": int(sweep.get("n_devices", 1)),
        }
        log_phase(
            "sweep", n=len(points), k=len(configs),
            distance_passes=sweep["distance_passes"],
            graph_pairs=sweep["graph_pairs"],
            seconds=round(wall, 4),
        )
        return SweepResult(configs, labels, core, per_cfg, sweep)

    def _sweep_run(self, points, configs, timer):
        """Routing + graph ladder + per-config relabel (kernel frame).

        Mirrors ``train``'s routing exactly: the sharded gate first
        (``n_devices > 1 and n >= 2 * n_devices``; device-resident
        input always takes the fused path), then ``mode``.
        """
        from .parallel import staging as _staging
        from .parallel.sharded import SweepGraphOverflow
        from .utils.hints import dispatch_tag

        if self._metric_norm == "cosine":
            eps_k = [float(np.sqrt(2.0 * e)) for e, _ in configs]
        elif self._metric_norm == "haversine":
            eps_k = [float(2.0 * np.sin(e / 2.0)) for e, _ in configs]
        else:
            eps_k = [float(e) for e, _ in configs]
        eps_max = max(eps_k)
        n = len(points)
        n_devices = self._n_devices()
        sharded = (
            not _is_device_array(points)
            and n_devices > 1
            and n >= 2 * n_devices
        )
        _staging.begin_fit()
        try:
            if sharded and self.mode == "global_morton":
                run_mode = "global_morton"
                relabel = self._sweep_graph_global(
                    points, eps_max, timer, run_mode, n_devices
                )
            elif sharded:
                run_mode = "kd"
                relabel = self._sweep_graph_kd(
                    points, eps_max, timer, n_devices
                )
            else:
                run_mode = "fused"
                n_devices = 1
                relabel = self._sweep_graph_fused(points, eps_max, timer)
        except Exception as e:  # noqa: BLE001 — rethrown unless degradable
            from .utils.retry import is_degradable_error, note_degraded

            if not (
                isinstance(e, SweepGraphOverflow) or is_degradable_error(e)
            ):
                raise
            note_degraded("sweep_refit", error=str(e)[:160])
            get_logger().warning(
                "sweep graph unavailable (%s); degrading to per-config "
                "refits — labels stay exact, one distance pass per "
                "config", e,
            )
            return self._sweep_refit(points, configs, timer)

        relabel_fn, gstats, _ghandle = relabel
        import time as _time

        labels_out, core_out, per_cfg = {}, {}, []
        relabel_s = []
        passes_total = 0
        reused_before = _staging.fit_stats()[0]
        for i, (cfg, e_k) in enumerate(zip(configs, eps_k)):
            t_c = _time.perf_counter()
            if i:
                # Configs 2..k re-threshold the device-resident graph
                # the first config staged — count the reuse like any
                # warm staging hit.
                _staging.touch_route(_staging.SWEEP_GRAPH_ROUTE)
            with timer.phase("relabel"):
                lab, cor, passes = relabel_fn(e_k, cfg[1])
            reused_now = _staging.fit_stats()[0]
            with timer.phase("densify"):
                dense = densify_labels(lab)
            labels_out[cfg] = dense
            core_out[cfg] = cor
            passes_total += int(passes)
            dt = _time.perf_counter() - t_c
            relabel_s.append(round(dt, 6))
            per_cfg.append(
                {
                    "eps": cfg[0],
                    "min_samples": cfg[1],
                    "relabel_s": round(dt, 6),
                    "n_clusters": int(dense.max()) + 1,
                    "passes": int(passes),
                    "staged_bytes_reused": int(
                        reused_now - reused_before
                    ),
                }
            )
            reused_before = reused_now
        self.metrics_["kernel_passes"] = passes_total + 1
        sweep = {
            "k": len(configs),
            "configs": [[e, m] for e, m in configs],
            "distance_passes": 1,
            "graph_pairs": int(gstats["graph_pairs"]),
            "graph_bytes": int(gstats["graph_bytes"]),
            "graph_build_s": round(float(gstats.get("build_s", 0.0)), 6),
            "relabel_s": relabel_s,
            "mode": run_mode,
            "owner_computes": run_mode != "fused",
            "dispatch": dispatch_tag(
                int(gstats.get("owned_cap", n)) // max(self.block, 1)
            ),
            "degraded": None,
            "n_devices": int(n_devices),
        }
        self.metrics_["n_partitions"] = int(
            gstats.get("n_partitions", 1)
        )
        for k_ in ("boundary_tiles", "boundary_tile_bytes",
                   "halo_factor", "halo_bytes", "partition_sizes"):
            if k_ in gstats:
                self.metrics_[k_] = gstats[k_]
        return labels_out, core_out, per_cfg, sweep

    def _sweep_graph_fused(self, points, eps_max, timer):
        """Fused-route graph: layout once (shared ``pipeline_layout``
        staging route), pair emission in KERNEL-slot space, per-config
        relabel packed through the fused wire format — labels
        byte-identical to ``train()``'s Morton-first numbering."""
        import time as _time

        import jax.numpy as jnp

        from .ops.distances import _norm_metric, sweep_max_edges
        from .ops.pipeline import (
            sweep_config_pack,
            sweep_graph_pipeline,
            unpack_pipeline_result,
        )
        from .parallel import staging as _staging
        from .parallel.sharded import SweepGraphOverflow

        t_b = _time.perf_counter()
        metric_k = self.metric
        n, k = (
            (points.shape[0], points.shape[1])
            if not _is_device_array(points)
            else points.shape
        )
        block = clamp_block(self.block, n)
        cap = round_up(n, block)
        sort = n > 2 * block
        route_key = None
        cached = None
        if not _is_device_array(points) and _layout_cacheable(cap, k):
            fp = _staging.points_fingerprint(points)
            layout_key = (
                fp, block, cap, bool(sort), self.precision,
                float(eps_max),
            )
            route_key = (
                "fused", fp, block, cap, bool(sort), self.precision,
                str(self.metric),
            )
            cached = _staging.device_get_cover(
                _staging.SWEEP_GRAPH_ROUTE, route_key, eps_max
            )
        else:
            layout_key = None

        if cached is not None:
            (gi, gj, dv, mask_k, owner), aux = cached
            stats = np.asarray(aux["stats"])
            cap = int(aux["cap"])
        else:
            with timer.phase("graph"):
                if _is_device_array(points):
                    from .ops.pipeline import device_prep

                    def make_dev():
                        return device_prep(points, cap=cap)
                else:
                    pts_host = _as_float(points)

                    def make_dev():
                        # Fresh staging fill (not the borrowed pool
                        # buffer — the sweep ships once and the graph
                        # outlives it, so pool rotation buys nothing
                        # and returning an aliased buffer would be the
                        # give_back_after_put hazard).
                        center = pts_host.mean(axis=0, dtype=np.float64)
                        buf = np.zeros((k, cap), np.float32)
                        chunk = 1 << 20
                        for s in range(0, n, chunk):
                            e = min(s + chunk, n)
                            np.subtract(
                                pts_host[s:e].T, center[:, None],
                                out=buf[:, s:e], casting="unsafe",
                            )
                        import jax.numpy as _jnp

                        return _jnp.asarray(buf)

                eb = None
                pb = None
                cap_edges = sweep_max_edges()
                for attempt in (0, 1):
                    graph, mask_k, owner, cap, stats = (
                        sweep_graph_pipeline(
                            make_dev, eps_max, n, metric=metric_k,
                            block=block, precision=self.precision,
                            backend=self.kernel_backend, sort=sort,
                            layout_key=layout_key, edge_budget=eb,
                            pair_budget=pb,
                        )
                    )
                    need_e, got_e = int(stats[0]), int(stats[1])
                    need_p, got_p = int(stats[2]), int(stats[3])
                    if need_e > cap_edges:
                        # Checked before the no-overflow break: the
                        # host-compaction route never overflows a
                        # budget (lists grow to the exact total), but
                        # the slab cap still binds.
                        raise SweepGraphOverflow(
                            f"neighbor-pair graph needs {need_e} edges "
                            f"but the sweep cap is {cap_edges} "
                            f"(PYPARDIS_SWEEP_MAX_PAIRS)"
                        )
                    if need_e <= got_e and need_p <= got_p:
                        break
                    if attempt == 1:
                        raise SweepGraphOverflow(
                            f"graph emission overflow persisted after "
                            f"an exact-total retry ({need_e}/{got_e}, "
                            f"{need_p}/{got_p})"
                        )
                    from .obs import event as obs_event

                    obs_event(
                        "pair_overflow", total=need_e, budget=got_e,
                        route="sweep_graph",
                    )
                    eb = round_up(max(need_e, 1), 4096)
                    if need_p > got_p:
                        pb = round_up(max(need_p, 1), 4096)
                gi, gj, dv = graph
            if route_key is not None:
                _staging.device_put_cached(
                    _staging.SWEEP_GRAPH_ROUTE, route_key,
                    (gi, gj, dv, mask_k, owner),
                    aux={
                        "eps_max": float(eps_max), "cap": cap,
                        "stats": np.asarray(stats),
                    },
                )
        build_s = _time.perf_counter() - t_b
        edge_stats = jnp.asarray(stats[:2], jnp.int32)
        metric_norm = _norm_metric(metric_k)

        # Numpy twin of _pipeline_pack's owner unscatter (slot-space
        # roots/core -> global rows) — byte-identical wire semantics,
        # shared by the CPU relabel and the hierarchy path's finalize.
        owner_np = np.asarray(owner)
        mask_np = np.asarray(mask_k)
        capk = len(mask_np)

        def _unscatter(roots_s, core_s):
            valid = roots_s >= 0
            tgt = np.clip(roots_s, 0, capk - 1)
            roots_gl = np.where(valid, owner_np[tgt], -1)
            out = np.full(cap, -1, np.int32)
            core_out = np.zeros(cap, bool)
            sel = owner_np < cap
            out[owner_np[sel]] = roots_gl[sel]
            core_out[owner_np[sel]] = core_s[sel]
            return out[:n], core_out[:n]

        if jax_backend_name() == "cpu":
            # Host relabel in kernel-slot space — segmented reductions
            # instead of XLA scatters.
            from .ops.labels import (
                graph_dbscan_host,
                graph_dbscan_host_prepare,
            )

            state = graph_dbscan_host_prepare(
                np.asarray(gi), np.asarray(gj), np.asarray(dv)
            )

            def relabel(eps_c, ms_c):
                roots_s, core_s, passes = graph_dbscan_host(
                    state, mask_np, eps_c, ms_c, metric=metric_norm
                )
                out, core_out = _unscatter(roots_s, core_s)
                return out, core_out, passes
        else:

            def relabel(eps_c, ms_c):
                packed = np.asarray(
                    sweep_config_pack(
                        gi, gj, dv, mask_k, owner, eps_c, ms_c,
                        edge_stats, cap=cap, metric=metric_norm,
                    )
                )
                roots, core, _t, _b2, passes, _bp, _rs = (
                    unpack_pipeline_result(packed)
                )
                return roots[:n], core[:n], passes

        gstats = {
            "graph_pairs": int(min(int(stats[0]), int(stats[1]))),
            "graph_bytes": int(min(int(stats[0]), int(stats[1]))) * 12,
            "build_s": build_s,
            "n_partitions": 1,
            "owned_cap": cap,
        }
        # Graph handle for the hierarchy path: the slab in THIS route's
        # id space (kernel slots) + the unscatter that maps slot-space
        # labels back to input rows — the fused train()/sweep() wire
        # semantics, so hierarchy cuts land byte-identical.
        ghandle = {
            "gi": gi, "gj": gj, "dv": dv, "mask": mask_np,
            "n_ids": capk, "finalize": _unscatter,
        }
        return relabel, gstats, ghandle

    def _sweep_graph_kd(self, points, eps_max, timer, n_devices):
        """KD-route graph: partition + owner-computes slabs at eps_max
        (staging-cached, owned slabs eps-free) → global-id graph."""
        from .parallel.sharded import sweep_graph_sharded

        with timer.phase("partition"):
            max_parts = (
                n_devices if self.max_partitions is None
                else int(self.max_partitions)
            )
            part = KDPartitioner(
                points,
                max_partitions=max_parts,
                split_method=self.split_method,
            )
            self.partitioner_ = part
            self.metrics_["partition_levels_s"] = [
                round(float(t), 6) for t in part.level_times_s
            ]
            self.metrics_["partition_builder"] = part.builder
            self.bounding_boxes = part.bounding_boxes
            # The graph's halo radius is eps_max (not the model eps —
            # which is None on the hierarchy path): every config below
            # the ceiling is covered by construction.
            self.expanded_boxes = {
                l: b.expand(2 * eps_max)
                for l, b in part.bounding_boxes.items()
            }
        with timer.phase("graph"):
            graph, gstats = sweep_graph_sharded(
                points, part, eps_max, block=self.block, mesh=self.mesh,
                precision=self.precision, backend=self.kernel_backend,
                metric=self.metric,
            )
        return self._global_relabel(graph, len(points), gstats, timer)

    def _sweep_graph_global(self, points, eps_max, timer, run_mode,
                            n_devices):
        """Global-Morton-route graph: morton ranges + boundary tiles at
        eps_max (the ring exchange), zero duplicated rows."""
        from .parallel.global_morton import sweep_graph_global_morton

        if _is_device_array(points):
            raise ValueError(
                "mode='global_morton' needs host-resident input (same "
                "restriction as train)"
            )
        with timer.phase("graph"):
            graph, gstats = sweep_graph_global_morton(
                points, eps_max, block=self.block, mesh=self.mesh,
                precision=self.precision, backend=self.kernel_backend,
                metric=self.metric,
            )
        self.metrics_["partition_builder"] = "morton_range"
        self.metrics_["partition_levels_s"] = []
        return self._global_relabel(graph, len(points), gstats, timer)

    def _global_relabel(self, graph, n, gstats, timer):
        """Per-config relabel closure over a global-id-space graph —
        converges to min-core-gid roots, the sharded routes' canonical
        label convention."""
        import time as _time

        import jax.numpy as jnp

        from .ops.labels import graph_dbscan
        from .parallel import staging as _staging

        t_b = _time.perf_counter()
        gi, gj, dv = graph
        gi_d = jnp.asarray(gi)
        gj_d = jnp.asarray(gj)
        dv_d = jnp.asarray(dv)
        mask = jnp.ones(n, bool)
        route_key = (
            gstats.get("mode", "kd"), n, int(self.block), self.precision,
            str(self.metric),
        )
        _staging.device_put_cached(
            _staging.SWEEP_GRAPH_ROUTE, route_key, (gi_d, gj_d, dv_d),
            aux={"eps_max": 0.0},
        )
        from .ops.distances import _norm_metric

        metric_norm = _norm_metric(self.metric)

        if jax_backend_name() == "cpu":
            # Host relabel fast path: same unique fixpoint, segmented
            # numpy reductions instead of the single-threaded XLA
            # scatters (see ops.labels.graph_dbscan_host).
            from .ops.labels import (
                graph_dbscan_host,
                graph_dbscan_host_prepare,
            )

            state = graph_dbscan_host_prepare(gi, gj, dv)
            mask_np = np.ones(n, bool)

            def relabel(eps_c, ms_c):
                lab, cor, passes = graph_dbscan_host(
                    state, mask_np, eps_c, ms_c, metric=metric_norm
                )
                return lab, cor, passes
        else:

            def relabel(eps_c, ms_c):
                lab, cor, passes = graph_dbscan(
                    gi_d, gj_d, dv_d, mask, eps_c, ms_c,
                    metric=metric_norm,
                )
                return np.asarray(lab), np.asarray(cor), int(passes)

        gstats = dict(gstats, build_s=_time.perf_counter() - t_b
                      + gstats.get("build_s", 0.0))
        # Sharded-route graph handle: already in global-gid space with
        # min-core-gid roots, so finalize is the identity slice.
        ghandle = {
            "gi": gi, "gj": gj, "dv": dv, "mask": np.ones(n, bool),
            "n_ids": n,
            "finalize": lambda lab, cor: (
                np.asarray(lab[:n], np.int32), np.asarray(cor[:n], bool)
            ),
        }
        return relabel, gstats, ghandle

    def _sweep_refit(self, points, configs, timer):
        """Label-safe degradation rung: k independent fits (the
        pre-sweep cost — one distance pass per config, never wrong
        labels).  Runs in the kernel frame on the already-normalized
        points, so cosine configs refit correctly too."""
        import time as _time

        labels_out, core_out, per_cfg = {}, {}, []
        relabel_s = []
        for cfg in configs:
            e_u, ms = cfg
            if self._metric_norm == "cosine":
                e_k = float(np.sqrt(2.0 * e_u))
            elif self._metric_norm == "haversine":
                e_k = float(2.0 * np.sin(e_u / 2.0))
            else:
                e_k = float(e_u)
            t_c = _time.perf_counter()
            m = DBSCAN(
                eps=e_k,
                min_samples=ms,
                metric=self.metric,
                max_partitions=self.max_partitions,
                split_method=self.split_method,
                block=self.block,
                mesh=self.mesh,
                precision=self.precision,
                kernel_backend=self.kernel_backend,
                merge=self.merge,
                owner_computes=self.owner_computes,
                overlap=self.overlap,
                mode=self.mode,
            )
            with timer.phase("refit"):
                m.train(points)
            labels_out[cfg] = np.asarray(m.labels_)
            core_out[cfg] = np.asarray(m.core_sample_mask_)
            dt = _time.perf_counter() - t_c
            relabel_s.append(round(dt, 6))
            per_cfg.append(
                {
                    "eps": e_u,
                    "min_samples": ms,
                    "relabel_s": round(dt, 6),
                    "n_clusters": int(labels_out[cfg].max()) + 1,
                    "passes": 0,
                    "staged_bytes_reused": int(
                        m.metrics_.get("staged_bytes_reused", 0)
                    ),
                }
            )
        from .utils.hints import dispatch_tag

        sweep = {
            "k": len(configs),
            "configs": [[e, m_] for e, m_ in configs],
            "distance_passes": len(configs),
            "graph_pairs": 0,
            "graph_bytes": 0,
            "graph_build_s": 0.0,
            "relabel_s": relabel_s,
            "mode": self.mode,
            "owner_computes": False,
            "dispatch": dispatch_tag(None),
            "degraded": "per_config_refit",
            "n_devices": int(self._n_devices()),
        }
        self.metrics_["n_partitions"] = 1
        return labels_out, core_out, per_cfg, sweep

    # -- density hierarchy (eps-free fits) --------------------------------

    def _user_eps_from_kernel(self, eps_k: float) -> float:
        """Kernel-frame eps -> user frame (inverse of ``kernel_eps``)."""
        if self._metric_norm == "cosine":
            return float(eps_k) ** 2 / 2.0
        if self._metric_norm == "haversine":
            return float(2.0 * np.arcsin(min(float(eps_k) / 2.0, 1.0)))
        return float(eps_k)

    def _hier_ceiling(self, points) -> float:
        """The hierarchy's eps_max (KERNEL frame): the one distance
        pass materializes the pair graph at this ceiling, and the
        cached family is truncated there (root births clamp to it).

        Resolution order: a concrete model eps (``sweep("auto")`` on a
        fitted-eps model — the caller's ceiling by definition; note
        this runs inside ``_kernel_frame``, so ``self.eps`` is already
        remapped), then the ``PYPARDIS_HIER_EPS_MAX`` override (USER
        frame), else a deterministic sample-kNN heuristic: 4x the 98th
        percentile of the ``min_samples``-th-neighbor distance over a
        strided ``PYPARDIS_HIER_SAMPLE``-row sample — an OVERestimate
        of the true core distances (a sample is sparser than the full
        set), so in-cluster MST edges stay below the ceiling.
        """
        if self.eps is not None:
            return float(self.eps)
        env = envreg.raw("PYPARDIS_HIER_EPS_MAX")
        if env:
            e_u = float(env)
            validate_params(e_u, 1)
            if self._metric_norm == "cosine":
                return float(np.sqrt(2.0 * e_u))
            if self._metric_norm == "haversine":
                return float(2.0 * np.sin(e_u / 2.0))
            return e_u
        from .ops.distances import _norm_metric

        km = _norm_metric(self.metric)
        pts = np.asarray(points, np.float32)
        n = len(pts)
        s_max = max(
            2, min(int(envreg.raw("PYPARDIS_HIER_SAMPLE", "2048")), n)
        )
        sample = pts[:: max(1, n // s_max)][:s_max]
        s = len(sample)
        k = min(max(self.min_samples, 2), s - 1)
        if km == "cityblock":
            dk = np.empty(s, np.float32)
            for lo in range(0, s, 256):
                hi = min(lo + 256, s)
                d = np.abs(
                    sample[lo:hi, None, :] - sample[None, :, :]
                ).sum(-1)
                dk[lo:hi] = np.partition(d, k, axis=1)[:, k]
        else:
            sq = (sample * sample).sum(-1)
            d2 = np.maximum(
                sq[:, None] + sq[None, :] - 2.0 * (sample @ sample.T),
                0.0,
            )
            dk = np.sqrt(np.partition(d2, k, axis=1)[:, k])
        ceil = 4.0 * float(np.quantile(dk.astype(np.float64), 0.98))
        if self._metric_norm in ("cosine", "haversine"):
            # Kernel eps is a unit-sphere chord length: past 2 every
            # pair qualifies, which only inflates the pair graph.
            ceil = min(ceil, 1.999)
        return max(ceil, 1e-6)

    def _hier_run(self, points, timer, ms: Optional[int] = None):
        """Routing + graph build + hierarchy construction (kernel
        frame) — the eps-free twin of ``_sweep_run``'s front half.

        Returns a context dict: ``hier`` (the ``min_samples``
        hierarchy), ``build`` (ms -> another Hierarchy over the SAME
        prepared slab — core pass + MST only, no new distance work),
        ``gh``/``gstats``/``run_mode``/``n_devices``/``eps_max_k``.
        """
        from .ops import hierarchy as _hier
        from .ops.distances import _norm_metric
        from .parallel import staging as _staging

        ms = int(self.min_samples if ms is None else ms)
        n = len(points)
        n_devices = self._n_devices()
        sharded = (
            not _is_device_array(points)
            and n_devices > 1
            and n >= 2 * n_devices
        )
        eps_max = self._hier_ceiling(points)
        _staging.begin_fit()
        if sharded and self.mode == "global_morton":
            run_mode = "global_morton"
            _relabel, gstats, gh = self._sweep_graph_global(
                points, eps_max, timer, run_mode, n_devices
            )
        elif sharded:
            run_mode = "kd"
            _relabel, gstats, gh = self._sweep_graph_kd(
                points, eps_max, timer, n_devices
            )
        else:
            run_mode = "fused"
            n_devices = 1
            _relabel, gstats, gh = self._sweep_graph_fused(
                points, eps_max, timer
            )
        km = _norm_metric(self.metric)
        eps_f = np.float32(eps_max)
        thr_max = float(
            eps_f * eps_f if km == "euclidean" else eps_f
        )
        with timer.phase("hierarchy"):
            state = _hier.hierarchy_prepare(
                np.asarray(gh["gi"]), np.asarray(gh["gj"]),
                np.asarray(gh["dv"]),
            )
            cd2 = None
            if jax_backend_name() != "cpu":
                # Accelerator routes run the jitted k-th-smallest twin
                # (bitwise the host values — pinned in tests).
                import jax.numpy as jnp

                cd2 = np.asarray(
                    _hier.core_distances_device(
                        jnp.asarray(gh["gi"]), jnp.asarray(gh["gj"]),
                        jnp.asarray(gh["dv"]), jnp.asarray(gh["mask"]),
                        ms,
                    )
                )

            def build(ms_c: int):
                return _hier.build_hierarchy(
                    state, gh["mask"], gh["n_ids"], int(ms_c),
                    kernel_metric=km,
                    user_frame=self._metric_norm,
                    thr_max=thr_max,
                    min_cluster_size=self.min_cluster_size,
                    cd2=cd2 if int(ms_c) == ms else None,
                )

            hier = build(ms)
        return {
            "hier": hier, "build": build, "gh": gh, "gstats": gstats,
            "run_mode": run_mode, "n_devices": int(n_devices),
            "eps_max_k": float(eps_max),
        }

    def _hier_no_refit(self, e: Exception) -> RuntimeError:
        return RuntimeError(
            f"the density-hierarchy path needs the cached pair graph "
            f"and cannot degrade to per-config refits (there is no eps "
            f"to refit at): {e}.  Raise PYPARDIS_SWEEP_MAX_PAIRS, or "
            f"lower the graph ceiling via PYPARDIS_HIER_EPS_MAX."
        )

    def _fit_hierarchy(self, data) -> "DBSCAN":
        """The eps=None fit: ONE distance pass, stability-selected eps.

        Pair graph at a data-derived ceiling -> per-point core
        distances -> mutual-reachability MST (Borůvka rounds) ->
        dendrogram condensed by ``min_cluster_size`` -> HDBSCAN*'s
        excess-of-mass rule picks the flat cut.  ``labels_`` are
        byte-identical to a solo ``fit(eps_)`` on the same route, and
        every step is deterministic given the data and env —
        byte-reproducible across repeated fits.
        """
        import time as _time

        from . import obs
        from .ops import hierarchy as _hier
        from .parallel.sharded import SweepGraphOverflow
        from .utils.profiling import PhaseTimer
        from .utils.retry import is_degradable_error

        keys, points = _as_keys_points(data)
        if self._metric_norm in ("cosine", "haversine"):
            points = self._driver_frame_rows(points)
        if len(points) == 0:
            raise ValueError("eps=None fits need a non-empty dataset")
        t0 = _time.perf_counter()
        dispatch_token = None
        sketch_token = None
        self._tune_stats = None
        if self.auto:
            dispatch_token, sketch_token = self._plan_auto(points)
        rec = obs.RunRecorder()
        self._recorder = rec
        self.metrics_ = {}
        self._serve_engine = None
        self._serve_core_points = None
        self._live_model = None
        self._live_stats = None
        self._fit_generation += 1
        self._keys = keys
        self.data = points
        self.partitioner_ = None
        self.bounding_boxes = self.expanded_boxes = None
        self.neighbors = None
        self.cluster_dict = None
        self._sweep_stats = None
        self._hier_stats = None
        timer = PhaseTimer()
        sampler = obs.ResourceSampler(rec).start()
        try:
            with obs.use_recorder(rec):
                _check_finite(points)
                with self._kernel_frame():
                    try:
                        ctx = self._hier_run(points, timer)
                    except Exception as e:  # noqa: BLE001
                        if not (
                            isinstance(e, SweepGraphOverflow)
                            or is_degradable_error(e)
                        ):
                            raise
                        raise self._hier_no_refit(e) from e
                    hier = ctx["hier"]
                    _thr_star, eps_u = hier.select_cut()
                    # Label at the ROUND TRIP of eps_ (not the raw cut
                    # weight): labels_ then equal a solo fit(eps_) by
                    # construction, whatever f32 did to the square.
                    thr_rt = float(
                        _hier.thr_from_user_eps(eps_u, self._metric_norm)
                    )
                    with timer.phase("relabel"):
                        lab_s, core_s = hier.labels_at_thr(thr_rt)
                        lab, core = ctx["gh"]["finalize"](lab_s, core_s)
                    with timer.phase("densify"):
                        dense = densify_labels(lab)
        finally:
            sampler.stop()
            if dispatch_token is not None:
                if dispatch_token == "":
                    os.environ.pop("PYPARDIS_DISPATCH", None)
                else:
                    os.environ["PYPARDIS_DISPATCH"] = dispatch_token
            if sketch_token is not None:
                if sketch_token == "":
                    os.environ.pop("PYPARDIS_SKETCH", None)
                else:
                    os.environ["PYPARDIS_SKETCH"] = sketch_token
        self._result_cache = None
        self.labels_ = dense
        self.core_sample_mask_ = np.asarray(core, bool)
        self.eps_ = float(eps_u)
        self.metrics_.update(timer.as_dict())
        self.metrics_["total_s"] = _time.perf_counter() - t0
        self.metrics_["points_per_sec"] = len(points) / max(
            self.metrics_["total_s"], 1e-9
        )
        from .parallel import staging as _dev_staging

        reused, shipped = _dev_staging.fit_stats()
        self.metrics_.setdefault("staged_bytes_reused", int(reused))
        self.metrics_.setdefault("staged_bytes", int(shipped))
        gstats = ctx["gstats"]
        self.metrics_.setdefault("live_pairs", int(gstats["graph_pairs"]))
        self.metrics_["n_partitions"] = int(gstats.get("n_partitions", 1))
        self.metrics_["kernel_passes"] = 2
        self._fit_info = {
            "n_dims": int(points.shape[1]),
            "n_devices": int(ctx["n_devices"]),
        }
        self._hier_stats = self._hier_block(ctx, eps_selected=eps_u)
        log_phase(
            "hierarchy", n=len(points),
            mst_edges=self._hier_stats["mst_edges"],
            boruvka_rounds=self._hier_stats["boruvka_rounds"],
            eps_selected=round(float(eps_u), 6),
            seconds=round(self.metrics_["total_s"], 4),
        )
        if self.auto and self._tune_stats is not None:
            self._tune_finalize()
        return self

    def _hier_block(self, ctx, eps_selected, ladder=None) -> Dict:
        """The ``report()["hierarchy"]`` block (user-frame values)."""
        gstats = ctx["gstats"]
        block = dict(ctx["hier"].telemetry())
        block.update(
            distance_passes=1,
            graph_pairs=int(gstats["graph_pairs"]),
            graph_bytes=int(gstats["graph_bytes"]),
            graph_build_s=round(float(gstats.get("build_s", 0.0)), 6),
            mode=ctx["run_mode"],
            n_devices=int(ctx["n_devices"]),
            eps_max=self._user_eps_from_kernel(ctx["eps_max_k"]),
            eps_selected=float(eps_selected),
            min_samples=int(ctx["hier"].min_samples),
        )
        if ladder is not None:
            block["ladder"] = [float(e) for e in ladder]
        return block

    def _sweep_auto_run(self, points, ms_vals, timer):
        """Ladder extraction + per-rung dendrogram cuts (kernel frame).

        The eps ladder comes from the first ``ms``'s hierarchy (top
        stability cuts); each ``(eps, ms)`` rung labels via a cut of
        that ms's hierarchy — a union-find over ~n MST edges plus one
        border reduceat, skipping the per-config fixpoint entirely —
        and stays byte-identical to a solo fit at that config.
        """
        import time as _time

        from .ops import hierarchy as _hier
        from .parallel.sharded import SweepGraphOverflow
        from .utils.hints import dispatch_tag
        from .utils.retry import is_degradable_error

        try:
            ctx = self._hier_run(points, timer, ms=ms_vals[0])
        except Exception as e:  # noqa: BLE001
            if not (
                isinstance(e, SweepGraphOverflow)
                or is_degradable_error(e)
            ):
                raise
            raise self._hier_no_refit(e) from e
        k = int(envreg.raw("PYPARDIS_HIER_LADDER_K", "8"))
        hier0 = ctx["hier"]
        _thr_star, eps_sel = hier0.select_cut()
        ladder = hier0.eps_ladder(k)
        if not ladder:
            raise RuntimeError(
                "eps_list='auto' found no positive cuts to ladder "
                "(degenerate pair graph — every point isolated at the "
                "ceiling?)"
            )
        hiers = {int(ms_vals[0]): hier0}
        for ms in ms_vals[1:]:
            if int(ms) not in hiers:
                with timer.phase("hierarchy"):
                    hiers[int(ms)] = ctx["build"](ms)
        configs = [
            (float(e), int(m)) for e in ladder for m in ms_vals
        ]
        gh = ctx["gh"]
        labels_out, core_out, per_cfg = {}, {}, []
        relabel_s = []
        for cfg in configs:
            e_u, ms = cfg
            t_c = _time.perf_counter()
            thr = float(_hier.thr_from_user_eps(e_u, self._metric_norm))
            with timer.phase("relabel"):
                lab_s, core_s = hiers[ms].labels_at_thr(thr)
                lab, core = gh["finalize"](lab_s, core_s)
            with timer.phase("densify"):
                dense = densify_labels(lab)
            labels_out[cfg] = dense
            core_out[cfg] = np.asarray(core, bool)
            dt = _time.perf_counter() - t_c
            relabel_s.append(round(dt, 6))
            per_cfg.append(
                {
                    "eps": e_u,
                    "min_samples": ms,
                    "relabel_s": round(dt, 6),
                    "n_clusters": int(dense.max()) + 1,
                    "passes": 1,
                    "staged_bytes_reused": 0,
                }
            )
        self.metrics_["kernel_passes"] = len(configs) + 1
        gstats = ctx["gstats"]
        n = len(points)
        sweep = {
            "k": len(configs),
            "configs": [[e, m] for e, m in configs],
            "distance_passes": 1,
            "graph_pairs": int(gstats["graph_pairs"]),
            "graph_bytes": int(gstats["graph_bytes"]),
            "graph_build_s": round(float(gstats.get("build_s", 0.0)), 6),
            "relabel_s": relabel_s,
            "mode": ctx["run_mode"],
            "owner_computes": ctx["run_mode"] != "fused",
            "dispatch": dispatch_tag(
                int(gstats.get("owned_cap", n)) // max(self.block, 1)
            ),
            "degraded": None,
            "n_devices": int(ctx["n_devices"]),
            "eps_source": "hierarchy_auto",
            "ladder": [float(e) for e in ladder],
        }
        self.metrics_["n_partitions"] = int(
            gstats.get("n_partitions", 1)
        )
        for k_ in ("boundary_tiles", "boundary_tile_bytes",
                   "halo_factor", "halo_bytes", "partition_sizes"):
            if k_ in gstats:
                self.metrics_[k_] = gstats[k_]
        self._hier_stats = self._hier_block(
            ctx, eps_selected=eps_sel, ladder=ladder
        )
        return labels_out, core_out, per_cfg, sweep

    # ``labels_`` / ``core_sample_mask_`` / ``data`` are properties so
    # the live-update path can sync them LAZILY: LiveModel used to copy
    # all three O(N) arrays on EVERY update (the CHANGES PR 8 note) —
    # now an update just marks them dirty, and the copy happens once,
    # here, when something actually reads the model surface.  A
    # sustained write load that never reads labels_ pays zero sync cost.
    @property
    def labels_(self) -> Optional[np.ndarray]:
        lm = self._live_model
        if lm is not None:
            lm._sync_if_dirty()
        return self._labels_v

    @labels_.setter
    def labels_(self, value) -> None:
        self._labels_v = value

    @property
    def core_sample_mask_(self) -> Optional[np.ndarray]:
        lm = self._live_model
        if lm is not None:
            lm._sync_if_dirty()
        return self._core_mask_v

    @core_sample_mask_.setter
    def core_sample_mask_(self, value) -> None:
        self._core_mask_v = value

    @property
    def data(self):
        lm = self._live_model
        if lm is not None:
            lm._sync_if_dirty()
        return self._data_v

    @data.setter
    def data(self, value) -> None:
        self._data_v = value

    @property
    def neighbors(self):
        """{partition label -> indices of the points in its
        2*eps-expanded box} — the reference's per-label neighborhood
        RDDs (dbscan.py:141-151) as index arrays, with ONE meaning on
        every route.  The device-resident sharded route computes it
        lazily on first access (its halos live on device as tight-box
        slabs; the parity surface replays the split tree host-side,
        which requires fetching the coordinates once — an opt-in
        O(N*k) transfer, never paid by fit itself).  Derives from
        ``self.data``/``self.partitioner_`` rather than pinning a
        second reference to the device array: clearing ``model.data``
        releases the HBM and simply disables this surface."""
        if self._neighbors is None and self._neighbors_lazy:
            if self.data is None or self.partitioner_ is None:
                raise RuntimeError(
                    "neighbors needs the training data; model.data was "
                    "cleared after a device-resident fit"
                )
            self._neighbors = _expanded_neighbors(
                self.partitioner_.tree, self.data, self.eps
            )
            self._neighbors_lazy = False
        return self._neighbors

    @neighbors.setter
    def neighbors(self, value):
        self._neighbors = value
        self._neighbors_lazy = False

    @property
    def result(self):
        """Key-sorted [(key, global label)] — the reference's cached
        ``sortByKey()`` product (dbscan.py:162-165), built on first
        access (its lazy-RDD analogue: declared in train, materialized
        by the collecting call)."""
        if self._result_cache is None and self.labels_ is not None:
            order = np.argsort(self._keys, kind="stable")
            self._result_cache = list(
                zip(self._keys[order].tolist(), self.labels_[order].tolist())
            )
        return self._result_cache

    @result.setter
    def result(self, value):
        self._result_cache = value

    def _require_fitted(self) -> None:
        """One not-fitted guard, one message — every result surface
        (``assignments``/``report``/``summary``/``predict``/...) used
        to phrase this differently."""
        if self.labels_ is None:
            raise RuntimeError(
                "this DBSCAN model is not fitted; call fit()/train() first"
            )

    def assignments(self):
        """[(key, global cluster id)] — reference dbscan.py:128-134."""
        self._require_fitted()
        return self.result

    # -- serving ----------------------------------------------------------

    def predict(self, X) -> np.ndarray:
        """Out-of-sample cluster assignment: (N,) int32 labels.

        DBSCAN's own serving rule (Ester et al., KDD 1996): a query
        joins cluster ``c`` iff it lies within ``eps`` of a core point
        of ``c`` — resolved to the NEAREST core point (ties: smallest
        label) — else noise (-1).  Runs through the cached
        :meth:`query_engine`; exact against the brute-force core-point
        oracle on every backend (:mod:`pypardis_tpu.serve`).
        """
        return self.query_engine().predict(X)

    def query_engine(self, **kw):
        """The cached serving engine over this model's core-point index
        (built on first use; kwargs — ``leaves``/``block``/``qblock``/
        ``backend``/``batch_capacity``/... — force a rebuild).  Works on
        checkpoint-loaded models without retraining: ``save_model``
        persists the core points."""
        self._require_fitted()
        if self._serve_engine is None or kw:
            from .serve import QueryEngine

            self._serve_engine = QueryEngine.from_model(self, **kw)
        return self._serve_engine

    # -- live updates -----------------------------------------------------

    def live(self, **kw):
        """The cached :class:`~pypardis_tpu.serve.live.LiveModel` over
        this fitted model — the incremental write surface (built on
        first use; kwargs force a rebuild).  Invalidated by a refit."""
        self._require_fitted()
        if self._metric_norm in ("cosine", "haversine"):
            raise NotImplementedError(
                f"live updates with metric={self._metric_norm!r} are "
                f"not supported yet: the incremental algebra reads "
                f"model.eps in the unit-sphere kernel frame; "
                f"fit/predict/sweep all support it"
            )
        if self._live_model is None or kw:
            from .serve import LiveModel

            self._live_model = LiveModel(self, **kw)
        return self._live_model

    def insert(self, X) -> np.ndarray:
        """Incrementally insert points into the fitted clustering
        (DBSCAN-correct label maintenance, serving index refreshed in
        place); returns the new points' stable ids.  See
        :class:`~pypardis_tpu.serve.live.LiveModel`."""
        return self.live().insert(X)

    def delete(self, ids) -> int:
        """Incrementally delete points by id (as returned by
        :meth:`insert`; the initial fit's points are ``0..n-1``)."""
        return self.live().delete(ids)

    # -- telemetry --------------------------------------------------------

    def report(self) -> Dict:
        """The schema'd telemetry dict of the most recent fit.

        One json-serializable dict (``pypardis_tpu/run_report@1``):
        per-phase wall times, per-device partition sizes, shard-layout
        overheads (``halo_factor``, ``pad_waste``), restage / pair-budget
        / halo-capacity / merge-round ladder event counts, and the full
        metrics-registry dump.  ``bench.py`` embeds the identical
        structure in its JSON line.
        """
        self._require_fitted()
        from .obs import build_run_report

        eng = self._serve_engine
        serving = (
            eng.serving_stats() if eng is not None and eng.queries > 0
            else None
        )
        live = dict(self._live_stats) if self._live_stats else None
        rep = build_run_report(
            self._recorder,
            params={
                "eps": self.eps,
                "min_samples": self.min_samples,
                "metric": self.metric,
                "max_partitions": self.max_partitions,
                "split_method": self.split_method,
                "block": self.block,
                "precision": self.precision,
                "kernel_backend": self.kernel_backend,
                "merge": self.merge,
                "owner_computes": self.owner_computes,
                "overlap": self.overlap,
                "mode": self.mode,
                "flight": self.flight,
                "auto": self.auto,
                "sketch": self.sketch,
            },
            n_points=len(self.labels_),
            n_dims=self._fit_info.get("n_dims", 0),
            n_devices=self._fit_info.get("n_devices", 1),
            backend=jax_backend_name(),
            metrics=self.metrics_,
            serving=serving,
            live=live,
        )
        # Amortized-sweep block (ISSUE 13): present only after sweep();
        # scripts/check_bench_json.py validates it on sweep@1 rows.
        if self._sweep_stats:
            rep["sweep"] = dict(self._sweep_stats)
        # Density-hierarchy block (ISSUE 18): present after an
        # eps=None fit or a sweep(eps_list="auto") — MST / Borůvka /
        # condensed-tree / stability telemetry at ONE distance pass.
        if self._hier_stats:
            rep["hierarchy"] = dict(self._hier_stats)
            rep["params"]["eps_selected"] = self.eps_
        # Auto-tuning block (ISSUE 14): present only on auto=True fits
        # — the plan (with its explain trace), predicted vs measured
        # per-phase seconds, corpus rows consulted, and whether the
        # outcome fed back into the local corpus.
        if self._tune_stats:
            rep["tune"] = dict(self._tune_stats)
        return rep

    def summary(self) -> str:
        """One-screen human rendering of :meth:`report`."""
        from .obs import format_summary

        return format_summary(self.report())

    def export_trace(self, path: str) -> str:
        """Write the fit's driver spans as Chrome-trace JSON (loads in
        chrome://tracing / ui.perfetto.dev).  Complements the
        ``profile_dir`` jax.profiler trace: this one is always recorded
        and costs microseconds.

        Works on a FAILED or partial fit too: whatever spans the
        recorder captured before the exception export fine — unlike
        ``report()``/``summary()``, which need the fitted result.  (A
        SIGKILLed process leaves no recorder at all; that case is the
        flight recorder's: ``obs.replay(path)`` rebuilds the trace from
        the on-disk JSONL.)
        """
        if self._recorder is not None:
            return self._recorder.tracer.export_chrome_trace(path)
        self._require_fitted()  # never fitted: the unified message
        raise RuntimeError(
            "no telemetry recorded for this model (loaded from a "
            "checkpoint?) — export_trace needs an in-process fit"
        )

    # -- auto-tuning ------------------------------------------------------

    def _plan_auto(self, points) -> Optional[str]:
        """Probe the input, harvest the corpus, plan the unpinned
        knobs, and apply the plan to this model's config.

        Returns ``(dispatch_token, sketch_token)`` — the previous
        ``PYPARDIS_DISPATCH`` / ``PYPARDIS_SKETCH`` values (``""`` for
        unset) when the plan took the corresponding knob over — the
        caller restores them after the fit — or ``None`` per knob when
        it was user-pinned or unplanned.  Every planned knob is
        label-safe, so the fit's labels are byte-identical to the same
        explicit config by construction; user-pinned knobs are never
        overridden (:mod:`pypardis_tpu.tune.planner`).
        """
        from .tune import harvest_corpus, plan_fit, probe_dataset
        from .tune.probe import candidate_blocks

        t0 = time.perf_counter()
        pinned = dict(self._tune_pinned)
        if _is_device_array(points):
            pinned["_device_resident"] = True
        try:
            rows = harvest_corpus(local=self.tune_corpus)
        except Exception:  # noqa: BLE001 — harvesting never fails a fit
            rows = []
        cand = set(candidate_blocks(len(points)))
        if "block" in pinned:
            cand.add(int(pinned["block"]))
        hier_ceiling_k = None
        if self.eps is None:
            # eps=None (hierarchy path): probe at the graph ceiling —
            # that IS the radius the one distance pass runs at.
            hier_ceiling_k = self._hier_ceiling(points)
            eps_probe = self._user_eps_from_kernel(hier_ceiling_k)
        else:
            eps_probe = float(self.eps)
        probe = probe_dataset(
            points, eps_probe, blocks=sorted(cand),
            devices=self._n_devices(),
        )
        try:
            from .ops.distances import _norm_metric

            kmetric = _norm_metric(self.metric)
        except ValueError:
            kmetric = "other"
        hier_est = None
        if hier_ceiling_k is not None:
            # Hierarchy cost terms: the core pass scales with stored
            # slab entries (~ per-row neighbors-within-ceiling x n),
            # the MST with rounds x pairs where rounds is logarithmic
            # in the live components (every live point enters Borůvka
            # as its own component).
            pairs_est = max(
                1, int(probe.neighbors_per_point * len(points))
            )
            hier_est = (float(pairs_est), float(len(points)))
        plan = plan_fit(
            probe, pinned, rows, metric=kmetric, hierarchy=hier_est,
        )
        cfg = plan.config
        self.block = int(cfg.get("block", self.block))
        if cfg.get("precision"):
            self.precision = cfg["precision"]
        if cfg.get("merge"):
            self.merge = cfg["merge"]
        if cfg.get("mode"):
            self.mode = cfg["mode"]
        token = None
        if cfg.get("dispatch") and "dispatch" not in self._tune_pinned:
            token = envreg.raw("PYPARDIS_DISPATCH", "")
            os.environ["PYPARDIS_DISPATCH"] = str(cfg["dispatch"])
        sketch_token = None
        if cfg.get("sketch") is not None and (
            "sketch" not in self._tune_pinned
        ):
            sketch_token = envreg.raw("PYPARDIS_SKETCH", "")
            os.environ["PYPARDIS_SKETCH"] = str(cfg["sketch"])
        get_logger().info(
            "auto-tune plan: %s", "; ".join(
                f"{k}={cfg.get(k)}" for k in
                ("mode", "block", "precision", "merge", "dispatch",
                 "sketch")
            ),
        )
        self._tune_stats = {
            "plan": plan.to_dict(),
            "explain": plan.explain(),
            "plan_s": round(time.perf_counter() - t0, 6),
            "probe_s": round(probe.probe_s, 6),
            "corpus_rows": len(rows),
            "predicted_phases": dict(plan.predicted),
        }
        return token, sketch_token

    def _tune_actual_phases(self) -> Dict[str, float]:
        """The fit's measured build/exchange/compute/merge seconds in
        the planner's phase vocabulary (GM reports its own
        decomposition; KD/fused attribute partition->build and
        cluster->compute, matching the model's terms)."""
        m = self.metrics_
        if "gm_build_s" in m or "gm_execute_s" in m:
            return {
                "build_s": float(m.get("gm_build_s", 0.0)),
                "exchange_s": float(m.get("gm_exchange_s", 0.0)),
                "compute_s": float(m.get("gm_execute_s", 0.0)),
                "merge_s": float(m.get("gm_merge_s", 0.0)),
                "total_s": float(m.get("total_s", 0.0)),
            }
        return {
            "build_s": float(m.get("partition_s", 0.0)),
            "exchange_s": 0.0,
            "compute_s": float(m.get("cluster_s", 0.0)),
            "merge_s": 0.0,
            "total_s": float(m.get("total_s", 0.0)),
        }

    def _tune_finalize(self) -> None:
        """Complete the tune telemetry with the measured outcome and
        feed the (features, config, outcome) row back into the local
        corpus — the loop that sharpens the model with use."""
        from .tune import append_local_row, row_from_report

        self._tune_stats["actual_phases"] = self._tune_actual_phases()
        try:
            row = row_from_report(self.report(), source="auto_fit")
        except Exception:  # noqa: BLE001 — feedback never fails a fit
            row = None
        appended = False
        if row is not None:
            appended = append_local_row(
                row, path=self.tune_corpus
                if self.tune_corpus is not None else None,
            )
        self._tune_stats["corpus_appended"] = bool(appended)

    # -- internals --------------------------------------------------------

    def _n_devices(self) -> int:
        if self.mesh is not None:
            return self.mesh.size
        import jax

        return jax.device_count()

    def _train_single(self, points: np.ndarray, timer) -> None:
        # A previous sharded fit's partition tree describes the OLD
        # dataset; clear it so cluster_mapping() can't pair new labels
        # with stale partition assignments.
        self.partitioner_ = None
        with timer.phase("cluster"):
            # _pad_and_run materializes numpy outputs, so the phase
            # bound includes all device execution.
            roots, core, kinfo = _pad_and_run(
                points, self.eps, self.min_samples, self.metric, self.block,
                precision=self.precision, backend=self.kernel_backend,
                jobstate=self._jobstate,
            )
        self.core_sample_mask_ = core
        with timer.phase("densify"):
            self.labels_ = densify_labels(roots)
        self.metrics_["n_partitions"] = 1
        # Kernel telemetry behind the report's achieved-FLOP/s model.
        self.metrics_.update(kinfo)
        if _is_device_array(points):
            # Reduce on device; ONE stacked fetch of the extrema — each
            # device->host transfer has ~0.2s fixed latency on tunneled
            # deployments, so two separate (k,) fetches were costing
            # more than the 200k-point kernel itself.
            import jax.numpy as jnp

            both = np.asarray(
                jnp.stack([jnp.min(points, axis=0), jnp.max(points, axis=0)])
            )
            lo, hi = both[0], both[1]
        else:
            lo, hi = points.min(axis=0), points.max(axis=0)
        box = BoundingBox(lower=lo, upper=hi)
        self.bounding_boxes = {0: box}
        self.expanded_boxes = {0: box.expand(2 * self.eps)}
        self.neighbors = {0: np.arange(len(points))}
        self.cluster_dict = {
            f"0:{l}": int(l) for l in np.unique(self.labels_) if l >= 0
        }

    def _train_sharded(self, points: np.ndarray, n_devices: int,
                       timer) -> None:
        from .parallel.sharded import sharded_dbscan

        if self.mode == "global_morton":
            if _is_device_array(points):
                raise ValueError(
                    "mode='global_morton' needs host-resident input: "
                    "the global Morton keying runs on the host "
                    "(device-resident inputs take the KD ring route)"
                )
            # A disk-backed memmap streams: the global Morton order
            # comes from the external sample-sort
            # (partition.morton_range_split_streaming) and shard slabs
            # assemble one device at a time — host RAM never holds the
            # dataset as anonymous memory (ISSUE 10 tentpole).
            try:
                self._train_sharded_global_morton(points, timer)
                return
            except Exception as e:  # noqa: BLE001 — rethrown below
                from .utils.retry import is_degradable_error, \
                    note_degraded

                if not is_degradable_error(e):
                    raise
                # Terminal mode fallback: the KD owner-computes engine
                # clusters the same data with smaller peak allocations
                # (no global Morton keying copy, host-spillable merge)
                # and is pinned byte-identical across modes — degrade
                # rather than die.
                note_degraded(
                    "kd_owner_computes", mode="global_morton",
                    error=str(e)[:160],
                )
                get_logger().warning(
                    "global-Morton engine failed terminally (%s); "
                    "falling back to the KD owner-computes mode "
                    "(labels are pinned byte-identical)", e,
                )
        if _is_device_array(points):
            # Device-resident input never round-trips the coordinates
            # through the host (the analogue of train(rdd) on
            # already-distributed data, reference dbscan.py:104).
            # merge='host' is honored ON the device route: only the
            # compact occurrence tables come back for the union-find
            # (round-4 review, Next #6 — previously this fetched the
            # whole dataset and bounced to the host path).
            self._train_sharded_device(points, timer)
            return

        with timer.phase("partition"):
            # max_partitions is a user-facing MAX (reference
            # dbscan.py:74-75) — never exceed an explicit value.  Only
            # the default rounds up to a mesh multiple; build_shards
            # pads the partition axis with fully-masked empty slots
            # when the count isn't one.
            if self.max_partitions is None:
                max_parts = n_devices
            else:
                max_parts = int(self.max_partitions)
            part = KDPartitioner(
                points,
                max_partitions=max_parts,
                split_method=self.split_method,
            )
            self.partitioner_ = part
            # Per-level build breakdown (the fast path's depth-scaling
            # contract is observable, not asserted): report() surfaces
            # it as sharding.partition_levels_s.
            self.metrics_["partition_levels_s"] = [
                round(float(t), 6) for t in part.level_times_s
            ]
            self.metrics_["partition_builder"] = part.builder
            self.bounding_boxes = part.bounding_boxes
            self.expanded_boxes = {
                l: b.expand(2 * self.eps)
                for l, b in part.bounding_boxes.items()
            }

        with timer.phase("cluster"):
            # sharded_dbscan returns numpy labels — device work is
            # materialized inside the phase.  A disk-backed memmap
            # takes the ring halo path so the streaming per-device
            # shard build engages (host RAM never holds the dataset as
            # anonymous memory — the reference's larger-than-one-worker
            # premise, README.md:60).
            halo = "ring" if isinstance(points, np.memmap) else "host"
            labels, core, stats = sharded_dbscan(
                points,
                part,
                eps=self.eps,
                min_samples=self.min_samples,
                metric=self.metric,
                block=self.block,
                mesh=self.mesh,
                precision=self.precision,
                backend=self.kernel_backend,
                merge=self.merge,
                halo=halo,
                owner_computes=self.owner_computes,
                overlap=self.overlap,
                jobstate=self._jobstate,
            )
        with timer.phase("densify"):
            self.labels_ = densify_labels(labels)
        self.core_sample_mask_ = core
        self.metrics_.update(stats)
        self.metrics_["n_partitions"] = part.n_partitions
        # Parity surface (reference dbscan.py:93-102).  ``neighbors``:
        # {partition label -> indices of the points in its 2*eps-expanded
        # box} — the reference's per-label neighborhood RDDs, as index
        # arrays (one cheap split-tree replay).  ``cluster_dict``:
        # {"partition:cluster" -> global id}; the sharded path has no
        # partition-local ids after the in-graph merge, so the global
        # dense label doubles as the per-partition cluster id.
        self.neighbors = _expanded_neighbors(part.tree, points, self.eps)
        self.cluster_dict = _partition_cluster_dict(
            part.result, self.labels_
        )

    def _train_sharded_device(self, points, timer) -> None:
        """Sharded fit of a device-resident ``jax.Array``.

        KD boundaries come from a host subsample; routing, layout, ring
        halo exchange, clustering, and merge run on device
        (:func:`pypardis_tpu.parallel.sharded.sharded_dbscan_device`).
        Host traffic: the subsample, (P,) counts, (N,) labels/core, and
        the (N,) int32 partition assignment for the parity surface —
        never the (N, k) coordinates.
        """
        from .parallel.sharded import sharded_dbscan_device

        with timer.phase("cluster"):
            labels, core, stats, part, pid = sharded_dbscan_device(
                points,
                eps=self.eps,
                min_samples=self.min_samples,
                metric=self.metric,
                block=self.block,
                mesh=self.mesh,
                precision=self.precision,
                backend=self.kernel_backend,
                max_partitions=self.max_partitions,
                split_method=self.split_method,
                merge=self.merge,
                owner_computes=self.owner_computes,
            )
        with timer.phase("densify"):
            self.labels_ = densify_labels(labels)
        self.core_sample_mask_ = core
        self.metrics_.update(stats)
        # Promote the subsample-built partitioner to the full-data view:
        # ``result``/``partitions`` come from the device routing (int
        # fetch), so cluster_mapping() and the parity surface reflect
        # the real partition structure.  One stable argsort, not a
        # boolean scan per partition (O(N log N), not O(P*N)).
        from .parallel import dist as _dist

        pid_np = _dist.fetch_np(pid)
        self.metrics_["partition_levels_s"] = [
            round(float(t), 6) for t in part.level_times_s
        ]
        self.metrics_["partition_builder"] = part.builder
        part.result = pid_np
        order = np.argsort(pid_np, kind="stable")
        uniq, starts = np.unique(pid_np[order], return_index=True)
        bounds = np.append(starts, len(order))
        part.partitions = {
            int(l): order[s:e]
            for l, s, e in zip(uniq, bounds[:-1], bounds[1:])
        }
        self.partitioner_ = part
        self.metrics_["n_partitions"] = len(part.partitions)
        # Boxes replay the SPLIT PLANES from an all-space root, so every
        # routed point is inside its partition's box by construction —
        # the subsample-extent boxes would exclude full-data extremes
        # the tree routes by half-space.
        boxes = {0: BoundingBox(k=points.shape[1], all_space=True)}
        for parent, axis, boundary, _left, right in part.tree:
            left_box, right_box = boxes[parent].split(axis, boundary)
            boxes[parent] = left_box
            boxes[right] = right_box
        part.bounding_boxes = boxes
        self.bounding_boxes = boxes
        self.expanded_boxes = {
            l: b.expand(2 * self.eps) for l, b in boxes.items()
        }
        # ``neighbors`` keeps the expanded-membership meaning of every
        # other route (round-4 advisor: the attribute silently changed
        # meaning with input residency) — computed lazily on first
        # access, because it needs the host coordinates the device fit
        # deliberately never fetches.
        self.neighbors = None
        self._neighbors_lazy = True
        self.cluster_dict = _partition_cluster_dict(pid_np, self.labels_)

    def _train_sharded_global_morton(self, points: np.ndarray,
                                     timer) -> None:
        """Zero-duplication global-Morton sharded fit.

        Shards are contiguous ranges of the global Morton order
        (:mod:`pypardis_tpu.parallel.global_morton`) — there is no KD
        partition phase; the Morton keying happens inside the cluster
        phase's build span.  The parity surface maps ranges onto the
        usual attributes: ``partitioner_`` is a
        :class:`~pypardis_tpu.partition.MortonRangePartitioner` (no
        split tree), ``bounding_boxes`` the per-range extents, and
        ``neighbors`` each shard's OWNED rows — zero duplication means
        there is no expanded-membership surface in this mode.
        """
        from .parallel.global_morton import global_morton_dbscan
        from .partition import MortonRangePartitioner

        with timer.phase("cluster"):
            labels, core, stats = global_morton_dbscan(
                points,
                eps=self.eps,
                min_samples=self.min_samples,
                metric=self.metric,
                block=self.block,
                mesh=self.mesh,
                precision=self.precision,
                backend=self.kernel_backend,
                merge=self.merge,
                jobstate=self._jobstate,
            )
        parity = stats.pop("parity", None)
        with timer.phase("densify"):
            self.labels_ = densify_labels(labels)
        self.core_sample_mask_ = core
        self.metrics_.update(stats)
        self.metrics_["partition_builder"] = "morton_range"
        self.metrics_["partition_levels_s"] = []
        if parity is not None and "order" in parity:
            order = np.asarray(parity["order"])
            starts = np.asarray(parity["starts"], dtype=np.int64)
            lo = np.asarray(parity["box_lo"])
            hi = np.asarray(parity["box_hi"])
            boxes = {
                s: BoundingBox(lower=lo[s], upper=hi[s])
                for s in range(len(starts) - 1)
                if starts[s + 1] > starts[s]
            }
            part = MortonRangePartitioner(order, starts, boxes)
            self.partitioner_ = part
            self.metrics_["n_partitions"] = part.n_partitions
            self.bounding_boxes = boxes
            self.expanded_boxes = {
                l: b.expand(2 * self.eps) for l, b in boxes.items()
            }
            self.neighbors = {
                s: part.partitions[s] for s in part.partitions
            }
            self.cluster_dict = _partition_cluster_dict(
                part.result, self.labels_
            )
        elif parity is not None:
            # Streaming/chained build: the O(N) permutation is exactly
            # what the out-of-core route avoids, so the parity surface
            # is ranges + boxes only (partitioner_ stays None — range
            # membership is a property of the on-disk sorted spill, not
            # something worth O(N) host memory to replay).
            starts = np.asarray(parity["starts"], dtype=np.int64)
            lo = np.asarray(parity["box_lo"])
            hi = np.asarray(parity["box_hi"])
            boxes = {
                s: BoundingBox(lower=lo[s], upper=hi[s])
                for s in range(min(len(lo), len(starts) - 1))
                if starts[s + 1] > starts[s]
            }
            self.partitioner_ = None
            self.metrics_["n_partitions"] = len(starts) - 1
            if boxes:
                self.bounding_boxes = boxes
                self.expanded_boxes = {
                    l: b.expand(2 * self.eps) for l, b in boxes.items()
                }
            self.neighbors = None
            self.cluster_dict = {}

    def save(self, path: str) -> None:
        """Checkpoint the trained model (labels, boxes, hyperparams)."""
        from .checkpoint import save_model

        save_model(self, path)

    @classmethod
    def load(cls, path: str) -> "DBSCAN":
        """Restore a checkpointed model; result surface works without
        retraining (the reference had no persistence at all, SURVEY §5)."""
        from .checkpoint import load_model

        return load_model(path)

    @classmethod
    def from_config(cls, config, mesh=None) -> "DBSCAN":
        return config.build(mesh=mesh)

    def cluster_mapping(self) -> ClusterAggregator:
        """Host-side ClusterAggregator over the final labels, for parity
        with the reference's ``cluster_dict`` inspection surface.

        Labels feed in as the REAL ``partition:cluster`` pairs of the
        trained model (the sharded path's KD assignment when present,
        partition 0 otherwise), so the aggregator's ``fwd``/``rev``
        reflect the actual partition structure rather than a fabricated
        single-partition view (round-2 review, Weak #8).

        Vectorized (round-4 review, Weak #7: the per-point ``agg +
        (key, [label])`` loop took minutes after a 10M-point fit):
        every point carries exactly ONE core label here, so the
        aggregator never merges — each distinct "partition:cluster"
        pair simply receives the next fresh global id in first-seen
        point order.  One ``np.unique`` reproduces that state exactly;
        a regression test pins it against the loop.
        """
        agg = ClusterAggregator()
        if self.labels_ is not None:
            parts = (
                np.asarray(self.partitioner_.result)
                if self.partitioner_ is not None
                else np.zeros(len(self.labels_), np.int32)
            )
            labels = np.asarray(self.labels_)
            sel = labels >= 0
            codes = (
                parts[sel].astype(np.int64) << 32
                | labels[sel].astype(np.int64)
            )
            uniq, first = np.unique(codes, return_index=True)
            for gid, c in enumerate(uniq[np.argsort(first, kind="stable")]):
                agg[f"{int(c) >> 32}:{int(c) & 0xFFFFFFFF}"] = gid
            agg.next_global_id = len(uniq)
        self.cluster_dict = dict(agg.fwd)
        return agg
