"""Checkpoint / resume.

The reference has none: ``train`` is monolithic and every intermediate
(bounding boxes, cluster dict, cached RDDs — reference dbscan.py:99-102)
lives only in driver memory (SURVEY §5).  Here the two things worth
persisting are cheap and explicit:

* the **partition tree** — axis/boundary metadata, a few KB — so new
  points can be routed to partitions without re-partitioning;
* the **model result** — labels, core mask, boxes, hyperparameters — so
  ``assignments()`` / ``cluster_mapping()`` work after a restart without
  re-clustering.

Storage is a plain ``.npz`` (numpy) — no orbax dependency needed for
kilobyte-scale metadata plus label vectors.
"""

from __future__ import annotations

import json

import numpy as np

from .geometry import BoundingBox
from .partition import KDPartitioner, route_tree


def _norm_npz(path: str) -> str:
    """np.savez silently appends '.npz' when missing; np.load does not.
    Normalize symmetrically so save('foo') / load('foo') round-trips."""
    return path if str(path).endswith(".npz") else f"{path}.npz"


def save_partitioner(part: KDPartitioner, path: str) -> None:
    """Persist the split tree + boxes (not the points)."""
    labels = sorted(part.bounding_boxes)
    lower = np.stack([part.bounding_boxes[l].lower for l in labels])
    upper = np.stack([part.bounding_boxes[l].upper for l in labels])
    tree = np.asarray(part.tree, dtype=np.float64).reshape(-1, 5)
    np.savez(
        _norm_npz(path),
        kind="kd_partition_tree",
        k=part.k,
        split_method=part.split_method,
        labels=np.asarray(labels),
        lower=lower,
        upper=upper,
        tree=tree,
    )


class PartitionTree:
    """A loaded partition tree: routing + boxes without the data."""

    def __init__(self, k, split_method, labels, lower, upper, tree):
        self.k = int(k)
        self.split_method = str(split_method)
        self.bounding_boxes = {
            int(l): BoundingBox(lower=lo, upper=up)
            for l, lo, up in zip(labels, lower, upper)
        }
        self.tree = [
            (int(p), int(a), float(b), int(lf), int(rt))
            for p, a, b, lf, rt in tree
        ]

    @property
    def n_partitions(self) -> int:
        return len(self.bounding_boxes)

    def route(self, points: np.ndarray) -> np.ndarray:
        """Replay the split tree (shared with KDPartitioner.route);
        validates dimensionality and finiteness against the tree."""
        from .utils.validate import check_query_points

        check_query_points(points, self.k)
        return route_tree(self.tree, points)


def load_partitioner(path: str) -> PartitionTree:
    with np.load(_norm_npz(path), allow_pickle=False) as z:
        if str(z["kind"]) != "kd_partition_tree":
            raise ValueError(f"{path} is not a partition-tree checkpoint")
        return PartitionTree(
            z["k"], z["split_method"], z["labels"], z["lower"], z["upper"],
            z["tree"],
        )


def save_model(model, path: str) -> None:
    """Persist a trained DBSCAN's results + hyperparameters."""
    if model.labels_ is None:
        raise ValueError("model is untrained; nothing to checkpoint")
    boxes = model.bounding_boxes or {}
    labels = sorted(boxes)
    params = {
        "eps": model.eps,
        "min_samples": model.min_samples,
        "metric": model.metric
        if isinstance(model.metric, str)
        else getattr(model.metric, "__name__", "euclidean"),
        "max_partitions": model.max_partitions,
        "split_method": model.split_method,
        "block": model.block,
        "precision": model.precision,
        "kernel_backend": model.kernel_backend,
    }
    keys = np.asarray(model._keys)
    if keys.dtype == object:
        # Object keys would require pickle, which load_model refuses
        # (allow_pickle=False); store their string form instead and say
        # so loudly rather than writing an unreadable checkpoint.
        keys = keys.astype(str)
    # Core-point coordinates (original dtype, cores only — the noise
    # and border rows stay behind): everything a restarted process
    # needs to build the serving index (pypardis_tpu.serve) and answer
    # out-of-sample queries byte-identically without re-clustering.
    cores = getattr(model, "_serve_core_points", None)
    if cores is None and model.data is not None \
            and model.core_sample_mask_ is not None:
        cores = np.asarray(model.data)[
            np.asarray(model.core_sample_mask_, bool)
        ]
    np.savez(
        _norm_npz(path),
        kind="dbscan_model",
        params=json.dumps(params),
        labels_=model.labels_,
        core_sample_mask_=model.core_sample_mask_,
        core_points=(
            cores if cores is not None
            else np.zeros((0, 0), np.float32)
        ),
        keys=keys,
        box_labels=np.asarray(labels, dtype=np.int64),
        box_lower=np.stack([boxes[l].lower for l in labels])
        if labels
        else np.zeros((0, 0)),
        box_upper=np.stack([boxes[l].upper for l in labels])
        if labels
        else np.zeros((0, 0)),
        metrics=json.dumps(model.metrics_),
    )


def load_model(path: str):
    """Rebuild a DBSCAN whose result surface works without retraining."""
    from .dbscan import DBSCAN

    with np.load(_norm_npz(path), allow_pickle=False) as z:
        if str(z["kind"]) != "dbscan_model":
            raise ValueError(f"{path} is not a DBSCAN model checkpoint")
        params = json.loads(str(z["params"]))
        model = DBSCAN(
            eps=params["eps"],
            min_samples=params["min_samples"],
            metric=params["metric"],
            max_partitions=params["max_partitions"],
            split_method=params["split_method"],
            block=params["block"],
            precision=params["precision"],
            kernel_backend=params["kernel_backend"],
        )
        model.labels_ = z["labels_"]
        model.core_sample_mask_ = z["core_sample_mask_"]
        model._keys = z["keys"]
        model.bounding_boxes = {
            int(l): BoundingBox(lower=lo, upper=up)
            for l, lo, up in zip(
                z["box_labels"], z["box_lower"], z["box_upper"]
            )
        }
        model.expanded_boxes = {
            l: b.expand(2 * model.eps)
            for l, b in model.bounding_boxes.items()
        }
        model.metrics_ = json.loads(str(z["metrics"]))
        # Core coordinates (absent in pre-serving checkpoints): the
        # loaded model can build the serving index and predict()
        # without retraining or the original dataset.
        if "core_points" in z.files and z["core_points"].size:
            model._serve_core_points = z["core_points"]
        # ``result`` builds lazily from the restored keys/labels (the
        # property key-sorts; an eager unsorted build here violated the
        # sortByKey contract for non-arange keys).
    return model


def save_index(index, path: str) -> None:
    """Persist a serving index (:class:`pypardis_tpu.serve.
    CorePointIndex`): the padded core slabs, labels, per-block bounds,
    split tree, and geometry — a restarted process loads and serves
    without the model, the dataset, or a rebuild."""
    np.savez(
        _norm_npz(path),
        kind="serve_index",
        params=json.dumps({
            "eps": index.eps,
            "block": index.block,
            "qblock": index.qblock,
            "n_core": index.n_core,
            "leaf_cap": int(index.stats.get("leaf_cap", 0)),
            "n_leaves": int(index.stats.get("n_leaves", 0)),
        }),
        center=index.center,
        tree=np.asarray(index.tree, np.float64).reshape(-1, 5),
        coords=index.coords,
        labels=index.labels,
        blo=index.blo,
        bhi=index.bhi,
    )


def load_index(path: str):
    """Restore a serving index saved by :func:`save_index` (slabs load
    byte-identical, so a restored index serves identical answers)."""
    from .serve import CorePointIndex

    with np.load(_norm_npz(path), allow_pickle=False) as z:
        if str(z["kind"]) != "serve_index":
            raise ValueError(f"{path} is not a serving-index checkpoint")
        params = json.loads(str(z["params"]))
        idx = CorePointIndex(
            eps=params["eps"],
            center=z["center"],
            tree=z["tree"],
            coords=z["coords"],
            labels=z["labels"],
            blo=z["blo"],
            bhi=z["bhi"],
            block=params["block"],
            qblock=params["qblock"],
            n_core=params["n_core"],
            stats={
                "n_core": params["n_core"],
                "n_leaves": params["n_leaves"],
                "leaf_cap": params["leaf_cap"],
                "index_bytes": int(
                    z["coords"].nbytes + z["labels"].nbytes
                    + z["blo"].nbytes + z["bhi"].nbytes
                ),
                "staged_bytes_reused": 0,
                "staged_bytes": 0,
            },
        )
    return idx
