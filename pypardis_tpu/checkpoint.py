"""Checkpoint / resume.

The reference has none: ``train`` is monolithic and every intermediate
(bounding boxes, cluster dict, cached RDDs — reference dbscan.py:99-102)
lives only in driver memory (SURVEY §5).  Here the two things worth
persisting are cheap and explicit:

* the **partition tree** — axis/boundary metadata, a few KB — so new
  points can be routed to partitions without re-partitioning;
* the **model result** — labels, core mask, boxes, hyperparameters — so
  ``assignments()`` / ``cluster_mapping()`` work after a restart without
  re-clustering.

Storage is a plain ``.npz`` (numpy) — no orbax dependency needed for
kilobyte-scale metadata plus label vectors.
"""

from __future__ import annotations

import json

import numpy as np

from .geometry import BoundingBox
from .partition import KDPartitioner, route_tree


def _norm_npz(path: str) -> str:
    """np.savez silently appends '.npz' when missing; np.load does not.
    Normalize symmetrically so save('foo') / load('foo') round-trips."""
    return path if str(path).endswith(".npz") else f"{path}.npz"


def save_partitioner(part: KDPartitioner, path: str) -> None:
    """Persist the split tree + boxes (not the points)."""
    labels = sorted(part.bounding_boxes)
    lower = np.stack([part.bounding_boxes[l].lower for l in labels])
    upper = np.stack([part.bounding_boxes[l].upper for l in labels])
    tree = np.asarray(part.tree, dtype=np.float64).reshape(-1, 5)
    np.savez(
        _norm_npz(path),
        kind="kd_partition_tree",
        k=part.k,
        split_method=part.split_method,
        labels=np.asarray(labels),
        lower=lower,
        upper=upper,
        tree=tree,
    )


class PartitionTree:
    """A loaded partition tree: routing + boxes without the data."""

    def __init__(self, k, split_method, labels, lower, upper, tree):
        self.k = int(k)
        self.split_method = str(split_method)
        self.bounding_boxes = {
            int(l): BoundingBox(lower=lo, upper=up)
            for l, lo, up in zip(labels, lower, upper)
        }
        self.tree = [
            (int(p), int(a), float(b), int(lf), int(rt))
            for p, a, b, lf, rt in tree
        ]

    @property
    def n_partitions(self) -> int:
        return len(self.bounding_boxes)

    def route(self, points: np.ndarray) -> np.ndarray:
        """Replay the split tree (shared with KDPartitioner.route);
        validates dimensionality and finiteness against the tree."""
        from .utils.validate import check_query_points

        check_query_points(points, self.k)
        return route_tree(self.tree, points)


def load_partitioner(path: str) -> PartitionTree:
    with np.load(_norm_npz(path), allow_pickle=False) as z:
        if str(z["kind"]) != "kd_partition_tree":
            raise ValueError(f"{path} is not a partition-tree checkpoint")
        return PartitionTree(
            z["k"], z["split_method"], z["labels"], z["lower"], z["upper"],
            z["tree"],
        )


def save_model(model, path: str, *, live=None, index=None) -> None:
    """Persist a trained DBSCAN's results + hyperparameters.

    ``live``/``index`` (both or neither — the ``LiveModel.save`` path)
    additionally persist the MUTATED live state: the current point set
    with labels/core flags/stable ids, the live routing tree and
    counters, and the in-place-updated serving index slabs byte-exact
    (epoch, leaf->slab map, slot gids included) — so a restarted server
    resumes serving the updated model byte-identically and can keep
    accepting writes.
    """
    if model.labels_ is None:
        raise ValueError("model is untrained; nothing to checkpoint")
    boxes = model.bounding_boxes or {}
    labels = sorted(boxes)
    params = {
        "eps": model.eps,
        "min_samples": model.min_samples,
        "metric": model.metric
        if isinstance(model.metric, str)
        else getattr(model.metric, "__name__", "euclidean"),
        "max_partitions": model.max_partitions,
        "split_method": model.split_method,
        "block": model.block,
        "precision": model.precision,
        "kernel_backend": model.kernel_backend,
    }
    keys = np.asarray(model._keys)
    if keys.dtype == object:
        # Object keys would require pickle, which load_model refuses
        # (allow_pickle=False); store their string form instead and say
        # so loudly rather than writing an unreadable checkpoint.
        keys = keys.astype(str)
    # Core-point coordinates (original dtype, cores only — the noise
    # and border rows stay behind): everything a restarted process
    # needs to build the serving index (pypardis_tpu.serve) and answer
    # out-of-sample queries byte-identically without re-clustering.
    cores = getattr(model, "_serve_core_points", None)
    if cores is None and model.data is not None \
            and model.core_sample_mask_ is not None:
        cores = np.asarray(model.data)[
            np.asarray(model.core_sample_mask_, bool)
        ]
    extra = {}
    # Auto-tuning plan (ISSUE 14): a planned fit's decision record —
    # chosen config, predicted vs measured phases, explain trace —
    # survives the checkpoint, so a loaded model can say why it ran
    # the config it ran (and a re-serving process can reuse it).
    tune = getattr(model, "_tune_stats", None)
    if tune:
        extra["tune"] = json.dumps(tune)
    if live is not None:
        extra.update(
            live_points=np.asarray(live["points"], np.float64),
            live_labels=np.asarray(live["labels"], np.int32),
            live_core=np.asarray(live["core"], bool),
            live_gids=np.asarray(live["gids"], np.int64),
            live_tree=np.asarray(live["tree"], np.float64).reshape(-1, 5),
            live_meta=json.dumps({
                "next_label": int(live["next_label"]),
                "counters": {
                    k: int(v) for k, v in live["counters"].items()
                },
                # A compaction cycle was mid-flight at save time: the
                # saved index is the (complete, consistent) pre-swap
                # generation; the partial one is discarded on load.
                "compact_pending": bool(
                    live.get("compact_pending", False)
                ),
            }),
        )
    if index is not None:
        # Leaf -> slab map flattened to (leaf, slab) pairs (ragged dict
        # otherwise); slot gids ride so deletions keep working after a
        # restore.
        pairs = [
            (int(l), int(s))
            for l, slabs in sorted(index.leaf_slabs.items())
            for s in slabs
        ]
        extra.update(
            index_coords=index.coords,
            index_labels=index.labels,
            index_blo=index.blo,
            index_bhi=index.bhi,
            index_center=index.center,
            index_tree=np.asarray(index.tree, np.float64).reshape(-1, 5),
            index_gids=(
                index.gids if index.gids is not None
                else np.empty(0, np.int64)
            ),
            index_leaf_slabs=np.asarray(pairs, np.int64).reshape(-1, 2),
            index_meta=json.dumps({
                "eps": index.eps,
                "block": index.block,
                "qblock": index.qblock,
                "n_core": index.n_core,
                "leaf_cap": int(index.stats.get("leaf_cap", 0)),
                "n_leaves": int(index.stats.get("n_leaves", 0)),
                "epoch": int(index.epoch),
            }),
        )
    np.savez(
        _norm_npz(path),
        kind="dbscan_model",
        params=json.dumps(params),
        labels_=model.labels_,
        core_sample_mask_=model.core_sample_mask_,
        core_points=(
            cores if cores is not None
            else np.zeros((0, 0), np.float32)
        ),
        keys=keys,
        box_labels=np.asarray(labels, dtype=np.int64),
        box_lower=np.stack([boxes[l].lower for l in labels])
        if labels
        else np.zeros((0, 0)),
        box_upper=np.stack([boxes[l].upper for l in labels])
        if labels
        else np.zeros((0, 0)),
        metrics=json.dumps(model.metrics_),
        **extra,
    )


def load_model(path: str):
    """Rebuild a DBSCAN whose result surface works without retraining."""
    from .dbscan import DBSCAN

    with np.load(_norm_npz(path), allow_pickle=False) as z:
        if str(z["kind"]) != "dbscan_model":
            raise ValueError(f"{path} is not a DBSCAN model checkpoint")
        params = json.loads(str(z["params"]))
        model = DBSCAN(
            eps=params["eps"],
            min_samples=params["min_samples"],
            metric=params["metric"],
            max_partitions=params["max_partitions"],
            split_method=params["split_method"],
            block=params["block"],
            precision=params["precision"],
            kernel_backend=params["kernel_backend"],
        )
        model.labels_ = z["labels_"]
        model.core_sample_mask_ = z["core_sample_mask_"]
        model._keys = z["keys"]
        model.bounding_boxes = {
            int(l): BoundingBox(lower=lo, upper=up)
            for l, lo, up in zip(
                z["box_labels"], z["box_lower"], z["box_upper"]
            )
        }
        model.expanded_boxes = {
            l: b.expand(2 * model.eps)
            for l, b in model.bounding_boxes.items()
        }
        model.metrics_ = json.loads(str(z["metrics"]))
        # Core coordinates (absent in pre-serving checkpoints): the
        # loaded model can build the serving index and predict()
        # without retraining or the original dataset.
        if "core_points" in z.files and z["core_points"].size:
            model._serve_core_points = z["core_points"]
        if "tune" in z.files:
            model._tune_stats = json.loads(str(z["tune"]))
        # Live-update payload (LiveModel.save checkpoints): the mutated
        # point set + byte-exact index slabs, handed to LiveModel.load
        # via _live_ckpt (plain load_model callers never see it).
        if "live_points" in z.files:
            from .serve import CorePointIndex

            imeta = json.loads(str(z["index_meta"]))
            lmeta = json.loads(str(z["live_meta"]))
            leaf_slabs: dict = {}
            for leaf, slab in z["index_leaf_slabs"]:
                leaf_slabs.setdefault(int(leaf), []).append(int(slab))
            idx = CorePointIndex(
                eps=imeta["eps"],
                center=z["index_center"],
                tree=z["index_tree"],
                coords=z["index_coords"],
                labels=z["index_labels"],
                blo=z["index_blo"],
                bhi=z["index_bhi"],
                block=imeta["block"],
                qblock=imeta["qblock"],
                n_core=imeta["n_core"],
                leaf_slabs=leaf_slabs,
                gids=(
                    z["index_gids"] if z["index_gids"].size else None
                ),
                stats={
                    "n_core": imeta["n_core"],
                    "n_leaves": imeta["n_leaves"],
                    "leaf_cap": imeta["leaf_cap"],
                    "index_bytes": int(
                        z["index_coords"].nbytes
                        + z["index_labels"].nbytes
                        + z["index_blo"].nbytes + z["index_bhi"].nbytes
                    ),
                    "staged_bytes_reused": 0,
                    "staged_bytes": 0,
                },
            )
            idx.epoch = int(imeta["epoch"])
            model._live_ckpt = {
                "points": z["live_points"],
                "labels": z["live_labels"],
                "core": z["live_core"],
                "gids": z["live_gids"],
                "tree": z["live_tree"],
                "next_label": lmeta["next_label"],
                "counters": lmeta["counters"],
                "compact_pending": lmeta.get("compact_pending", False),
                "index": idx,
            }
        # ``result`` builds lazily from the restored keys/labels (the
        # property key-sorts; an eager unsorted build here violated the
        # sortByKey contract for non-arange keys).
    return model


def save_index(index, path: str) -> None:
    """Persist a serving index (:class:`pypardis_tpu.serve.
    CorePointIndex`): the padded core slabs, labels, per-block bounds,
    split tree, and geometry — a restarted process loads and serves
    without the model, the dataset, or a rebuild."""
    np.savez(
        _norm_npz(path),
        kind="serve_index",
        params=json.dumps({
            "eps": index.eps,
            "block": index.block,
            "qblock": index.qblock,
            "n_core": index.n_core,
            "leaf_cap": int(index.stats.get("leaf_cap", 0)),
            "n_leaves": int(index.stats.get("n_leaves", 0)),
            # Driver-metric frame: a restored index must keep
            # projecting queries — unit-sphere normalization for
            # cosine (ISSUE 13), (lat, lon) embedding for haversine
            # (ISSUE 14 satellite).
            "unit_norm": bool(getattr(index, "unit_norm", False)),
            "projection": str(getattr(index, "projection", "none")),
        }),
        center=index.center,
        tree=np.asarray(index.tree, np.float64).reshape(-1, 5),
        coords=index.coords,
        labels=index.labels,
        blo=index.blo,
        bhi=index.bhi,
    )


def load_index(path: str, handle=None):
    """Restore a serving index saved by :func:`save_index` (slabs load
    byte-identical, so a restored index serves identical answers).

    ``handle`` names the model the restored index serves in a
    multi-model plane — the index then stages under its own per-handle
    route (the gateway readmission path restores an evicted model this
    way)."""
    from .serve import CorePointIndex

    with np.load(_norm_npz(path), allow_pickle=False) as z:
        if str(z["kind"]) != "serve_index":
            raise ValueError(f"{path} is not a serving-index checkpoint")
        params = json.loads(str(z["params"]))
        idx = CorePointIndex(
            handle=handle,
            eps=params["eps"],
            center=z["center"],
            tree=z["tree"],
            coords=z["coords"],
            labels=z["labels"],
            blo=z["blo"],
            bhi=z["bhi"],
            block=params["block"],
            qblock=params["qblock"],
            n_core=params["n_core"],
            stats={
                "n_core": params["n_core"],
                "n_leaves": params["n_leaves"],
                "leaf_cap": params["leaf_cap"],
                "index_bytes": int(
                    z["coords"].nbytes + z["labels"].nbytes
                    + z["blo"].nbytes + z["bhi"].nbytes
                ),
                "staged_bytes_reused": 0,
                "staged_bytes": 0,
            },
        )
        idx.unit_norm = bool(params.get("unit_norm", False))
        # Pre-haversine checkpoints carry only the bool.
        idx.projection = str(
            params.get("projection", "unit" if idx.unit_norm else "none")
        )
    return idx
