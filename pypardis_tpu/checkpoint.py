"""Checkpoint / resume.

The reference has none: ``train`` is monolithic and every intermediate
(bounding boxes, cluster dict, cached RDDs — reference dbscan.py:99-102)
lives only in driver memory (SURVEY §5).  Here the two things worth
persisting are cheap and explicit:

* the **partition tree** — axis/boundary metadata, a few KB — so new
  points can be routed to partitions without re-partitioning;
* the **model result** — labels, core mask, boxes, hyperparameters — so
  ``assignments()`` / ``cluster_mapping()`` work after a restart without
  re-clustering.

Storage is a plain ``.npz`` (numpy) — no orbax dependency needed for
kilobyte-scale metadata plus label vectors.
"""

from __future__ import annotations

import json

import numpy as np

from .geometry import BoundingBox
from .partition import KDPartitioner, route_tree


def _norm_npz(path: str) -> str:
    """np.savez silently appends '.npz' when missing; np.load does not.
    Normalize symmetrically so save('foo') / load('foo') round-trips."""
    return path if str(path).endswith(".npz") else f"{path}.npz"


def save_partitioner(part: KDPartitioner, path: str) -> None:
    """Persist the split tree + boxes (not the points)."""
    labels = sorted(part.bounding_boxes)
    lower = np.stack([part.bounding_boxes[l].lower for l in labels])
    upper = np.stack([part.bounding_boxes[l].upper for l in labels])
    tree = np.asarray(part.tree, dtype=np.float64).reshape(-1, 5)
    np.savez(
        _norm_npz(path),
        kind="kd_partition_tree",
        k=part.k,
        split_method=part.split_method,
        labels=np.asarray(labels),
        lower=lower,
        upper=upper,
        tree=tree,
    )


class PartitionTree:
    """A loaded partition tree: routing + boxes without the data."""

    def __init__(self, k, split_method, labels, lower, upper, tree):
        self.k = int(k)
        self.split_method = str(split_method)
        self.bounding_boxes = {
            int(l): BoundingBox(lower=lo, upper=up)
            for l, lo, up in zip(labels, lower, upper)
        }
        self.tree = [
            (int(p), int(a), float(b), int(lf), int(rt))
            for p, a, b, lf, rt in tree
        ]

    @property
    def n_partitions(self) -> int:
        return len(self.bounding_boxes)

    def route(self, points: np.ndarray) -> np.ndarray:
        """Replay the split tree (shared with KDPartitioner.route)."""
        return route_tree(self.tree, points)


def load_partitioner(path: str) -> PartitionTree:
    with np.load(_norm_npz(path), allow_pickle=False) as z:
        if str(z["kind"]) != "kd_partition_tree":
            raise ValueError(f"{path} is not a partition-tree checkpoint")
        return PartitionTree(
            z["k"], z["split_method"], z["labels"], z["lower"], z["upper"],
            z["tree"],
        )


def save_model(model, path: str) -> None:
    """Persist a trained DBSCAN's results + hyperparameters."""
    if model.labels_ is None:
        raise ValueError("model is untrained; nothing to checkpoint")
    boxes = model.bounding_boxes or {}
    labels = sorted(boxes)
    params = {
        "eps": model.eps,
        "min_samples": model.min_samples,
        "metric": model.metric
        if isinstance(model.metric, str)
        else getattr(model.metric, "__name__", "euclidean"),
        "max_partitions": model.max_partitions,
        "split_method": model.split_method,
        "block": model.block,
        "precision": model.precision,
        "kernel_backend": model.kernel_backend,
    }
    keys = np.asarray(model._keys)
    if keys.dtype == object:
        # Object keys would require pickle, which load_model refuses
        # (allow_pickle=False); store their string form instead and say
        # so loudly rather than writing an unreadable checkpoint.
        keys = keys.astype(str)
    np.savez(
        _norm_npz(path),
        kind="dbscan_model",
        params=json.dumps(params),
        labels_=model.labels_,
        core_sample_mask_=model.core_sample_mask_,
        keys=keys,
        box_labels=np.asarray(labels, dtype=np.int64),
        box_lower=np.stack([boxes[l].lower for l in labels])
        if labels
        else np.zeros((0, 0)),
        box_upper=np.stack([boxes[l].upper for l in labels])
        if labels
        else np.zeros((0, 0)),
        metrics=json.dumps(model.metrics_),
    )


def load_model(path: str):
    """Rebuild a DBSCAN whose result surface works without retraining."""
    from .dbscan import DBSCAN

    with np.load(_norm_npz(path), allow_pickle=False) as z:
        if str(z["kind"]) != "dbscan_model":
            raise ValueError(f"{path} is not a DBSCAN model checkpoint")
        params = json.loads(str(z["params"]))
        model = DBSCAN(
            eps=params["eps"],
            min_samples=params["min_samples"],
            metric=params["metric"],
            max_partitions=params["max_partitions"],
            split_method=params["split_method"],
            block=params["block"],
            precision=params["precision"],
            kernel_backend=params["kernel_backend"],
        )
        model.labels_ = z["labels_"]
        model.core_sample_mask_ = z["core_sample_mask_"]
        model._keys = z["keys"]
        model.bounding_boxes = {
            int(l): BoundingBox(lower=lo, upper=up)
            for l, lo, up in zip(
                z["box_labels"], z["box_lower"], z["box_upper"]
            )
        }
        model.expanded_boxes = {
            l: b.expand(2 * model.eps)
            for l, b in model.bounding_boxes.items()
        }
        model.metrics_ = json.loads(str(z["metrics"]))
        # ``result`` builds lazily from the restored keys/labels (the
        # property key-sorts; an eager unsorted build here violated the
        # sortByKey contract for non-arange keys).
    return model
