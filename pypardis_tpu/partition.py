"""Spatial KD partitioning.

TPU-native re-design of the reference partition layer
(``/root/reference/dbscan/partition.py:8-183``).  The reference builds its
binary split tree with ~2 cluster-wide Spark ``aggregate`` jobs per split
(partition.py:60,86 — the §3.1 hot spot).  Here the tree is built on the
host in one vectorized pass per split over in-memory (optionally
subsampled) numpy arrays: boundaries come from exact sorts or moment
statistics of the subset, and applying the finished tree to all N points
is a handful of broadcasted comparisons.  The tree itself is tiny metadata
(axis, boundary per node) that later feeds the device-mesh layout.

Split strategies (names and semantics from the reference):

* ``median_search`` — exact median along an axis (partition.py:8-30).
* ``mean_var`` — approximate median: 7 candidate boundaries at
  mean + {-0.9..0.9}·sigma in 0.3·sigma steps, pick argmin |#below-#above|
  (partition.py:33-69).
* ``min_var`` — pick the axis of maximum variance, then ``mean_var``
  boundary on that axis (partition.py:72-95).
* ``rotation`` — axis cycles with tree depth (partition.py:180-183).
"""

from __future__ import annotations

import os
import shutil
import tempfile
import time
from collections import deque
from typing import Dict, Optional

import numpy as np

from .geometry import BoundingBox, BoxStack
from .utils import envreg

_VALID_SPLIT_METHODS = ("min_var", "rotation", "mean_var", "median_search")
_VALID_BUILDERS = ("auto", "level", "legacy")


def _dist():
    # Lazy: partition is imported by parallel.sharded, so a module-top
    # import of parallel.dist would cycle through a half-initialized
    # package.
    from .parallel import dist

    return dist


def median_search_split(values: np.ndarray):
    """Exact-median boundary along one axis.

    ``values``: (M,) coordinates of the subset on the split axis.
    Returns (below_mask, boundary); left = ``< boundary``, right =
    ``>= boundary`` (partition.py:27-30).
    """
    boundary = float(np.median(values))
    below = values < boundary
    return below, boundary


def mean_var_split(values: np.ndarray, mean: float = None, variance: float = None):
    """Approximate-median boundary from moment statistics.

    Evaluates the 7 candidate boundaries ``mean + j*0.3*sigma`` for
    ``j in -3..3`` and keeps the one with the smallest signed balance
    ``|#below - #above|`` (partition.py:58-65).  One pass over the subset,
    no sort.
    """
    if mean is None:
        mean = float(values.mean())
    if variance is None:
        variance = float(values.var())
    std = np.sqrt(variance)
    candidates = mean + np.arange(-0.9, 0.91, 0.3) * std
    # balance[c] = #below(c) - #above(c); reference computes it as a
    # running sum of 2*(v < bound) - 1.
    below_counts = (values[:, None] < candidates[None, :]).sum(axis=0)
    balance = np.abs(2 * below_counts - len(values))
    boundary = float(candidates[int(np.argmin(balance))])
    below = values < boundary
    return below, boundary


def min_var_split(points: np.ndarray):
    """Choose the max-variance axis, then a ``mean_var`` boundary on it.

    ``points``: (M, k) subset.  Returns (axis, below_mask, boundary).
    Matches partition.py:86-94: one moments pass gives per-axis mean and
    variance, the split axis is argmax variance.
    """
    mean = points.mean(axis=0)
    var = points.var(axis=0)
    axis = int(np.argmax(var))
    below, boundary = mean_var_split(
        points[:, axis], mean=float(mean[axis]), variance=float(var[axis])
    )
    return axis, below, boundary


def morton_plan(d: int):
    """(axes_used, bits_per_axis) for a <=128-bit Morton code.

    Round 2 capped codes at one uint64 (6 axes x 10 bits), which left 10
    of 16 dims unsorted on the scale-up config: tiles straddling cluster
    boundaries inherited data-scale bounding boxes in the unsorted dims
    and defeated tile pruning — measured as throughput decaying 320k ->
    127k pts/s from 1M to 10M points.  A 128-bit budget covers every
    axis up to d=32 (top-variance axes beyond that) with >= 4 bits each,
    and fine 16-bit resolution for low-d (GPS-like) data.
    """
    k = min(d, 32)
    if k == 0:  # (N, 0) points: one all-zero word, any order is spatial
        return 0, 0
    bits = max(4, min(16, 128 // k))
    return k, bits


def interleave_bit_words(q_axes, bits: int, word_bits: int, zeros, shift):
    """MSB-first bit interleave of per-axis quantized values into words.

    Shared by the host (uint64/numpy) and device (uint32/jnp) Morton
    implementations — their orderings must stay bit-identical, so the
    packing lives in exactly one place.  ``q_axes``: sequence of k
    unsigned arrays; ``zeros()``: a fresh all-zero word array;
    ``shift(v)``: the int ``v`` as the word dtype (numpy requires typed
    shift amounts).  Code bit e lands in word ``e // word_bits``; the
    leading word is left-padded when ``bits * k % word_bits != 0``
    (harmless for lexicographic comparison).  Returns the word list,
    most significant first — always at least one word.
    """
    k = len(q_axes)
    total = bits * k
    n_words = max(1, -(-total // word_bits))
    words = [zeros() for _ in range(n_words)]
    one = shift(1)
    emitted = n_words * word_bits - total
    for b in range(bits - 1, -1, -1):
        for a in range(k):
            w = emitted // word_bits
            bit = (q_axes[a] >> shift(b)) & one
            words[w] = (words[w] << one) | bit
            emitted += 1
    return words


def _morton_quantize_words(points: np.ndarray, lo, span, bits: int):
    """Quantize an (M, k) chunk against a FIXED (lo, span) frame and
    interleave into uint64 words.

    The elementwise body of :func:`morton_codes`, factored out so the
    streaming external sort (:func:`morton_range_split_streaming`) can
    key memmap chunks one at a time against the globally-computed frame
    and stay byte-identical to the in-RAM keying — quantization and
    interleave are elementwise, so chunking cannot change a single bit.
    """
    k = points.shape[1]
    if k == 0 or bits == 0:
        return [np.zeros(len(points), dtype=np.uint64)]
    q = np.minimum(
        ((points - lo) / span * (1 << bits)).astype(np.uint64), (1 << bits) - 1
    )
    return interleave_bit_words(
        [q[:, a] for a in range(k)],
        bits,
        64,
        lambda: np.zeros(len(points), dtype=np.uint64),
        np.uint64,
    )


def morton_codes(points: np.ndarray):
    """Morton (Z-order) code words for (N, k) points.

    Returns a list of uint64 word arrays, most-significant word first,
    jointly holding the <=128-bit interleaved code (see
    :func:`morton_plan`); quantization is per-axis over the data's range.
    Compare/sort lexicographically — :func:`spatial_order` does.
    """
    points = np.asarray(points)
    if points.dtype not in (np.float32, np.float64):
        points = points.astype(np.float64)
    if points.ndim != 2:
        raise ValueError(f"points must be (N, k), got {points.shape}")
    k, bits = morton_plan(points.shape[1])
    if points.shape[1] > k:
        axes = np.argsort(points.var(axis=0))[::-1][:k]
        points = points[:, np.sort(axes)]
    if k == 0:
        return [np.zeros(len(points), dtype=np.uint64)]
    lo = points.min(axis=0)
    # Floor must not underflow the input dtype (1e-300 is 0 in float32,
    # which made all-equal axes divide by zero).
    span = np.maximum(points.max(axis=0) - lo, np.finfo(points.dtype).tiny)
    return _morton_quantize_words(points, lo, span, bits)


def expanded_members(tree, points: np.ndarray, margin: float):
    """Membership of every point in every margin-expanded partition box,
    by replaying the split tree with widened comparisons.

    This replaces the broadcasted (N, P, k) box query (the round-1 memory
    wall) with an O(N·depth) descent: at each recorded split, a point
    follows the left branch when ``x < boundary + margin`` and the right
    branch when ``x >= boundary - margin`` — both when inside the band.
    Because a leaf's expanded box is exactly the conjunction of its path's
    margin-widened half-space constraints (the root box contains all data
    points by construction), the descent reproduces the reference's
    expanded-box duplication semantics (dbscan.py:141-151, README.md:20-22)
    while the peak extra memory is the duplicated index lists themselves —
    O(N · halo_factor), independent of P and k.

    Returns ``{label: (member_idx, owned_mask)}`` where ``member_idx`` is
    an int array of point indices inside the label's expanded box and
    ``owned_mask`` marks the ones strictly owned by the partition (the
    same ``<`` semantics as :class:`KDPartitioner`), so the halo set is
    ``member_idx[~owned_mask]``.
    """
    points = np.asarray(points)
    n = len(points)
    state = {0: (np.arange(n, dtype=np.int32), np.ones(n, dtype=bool))}
    for parent, axis, boundary, _left, right in tree:
        arr, own = state.pop(int(parent))
        c = points[arr, int(axis)].astype(np.float64, copy=False)
        # Inclusive on the widened upper bound, matching BoxStack
        # membership and the reference's expanded_box.contains (<=).
        lsel = c <= boundary + margin
        rsel = c >= boundary - margin
        state[int(parent)] = (arr[lsel], own[lsel] & (c[lsel] < boundary))
        state[int(right)] = (arr[rsel], own[rsel] & (c[rsel] >= boundary))
    return state


def route_tree(tree, points: np.ndarray) -> np.ndarray:
    """Assign points to partitions by replaying a split tree.

    ``tree``: iterable of (parent_label, axis, boundary, left_label,
    right_label) in construction order — the format produced by
    :class:`KDPartitioner` and round-tripped by
    :mod:`pypardis_tpu.checkpoint`.  Left children keep the parent
    label; points with coordinate >= boundary go right (strict ``<``
    stays left, matching the reference's split semantics,
    partition.py:27-30).

    Inputs are validated against the tree: an array too narrow for the
    recorded split axes, or one carrying NaN/inf coordinates (a NaN
    fails every ``>=`` and silently slides down the left spine), raises
    ValueError instead of routing garbage.
    """
    from .utils.validate import check_query_points

    tree = list(tree)
    points = check_query_points(points).astype(np.float64, copy=False)
    if tree:
        need = max(int(a) for _p, a, _b, _l, _r in tree) + 1
        if points.shape[1] < need:
            raise ValueError(
                f"points have {points.shape[1]} dims but the split tree "
                f"routes on axis {need - 1}"
            )
    labels = np.zeros(len(points), dtype=np.int32)
    for parent, axis, boundary, _left, right in tree:
        mask = labels == int(parent)
        go_right = mask & (points[:, int(axis)] >= boundary)
        labels[go_right] = int(right)
    return labels


def spatial_order(points: np.ndarray) -> np.ndarray:
    """An index permutation grouping spatially nearby points.

    Sorts points along a Morton (Z-order) curve so that contiguous tile
    blocks of the permuted layout have tight bounding boxes — which is
    what makes tile-level pruning in :mod:`pypardis_tpu.ops` effective:
    the O(N^2) pairwise interaction collapses to O(N x local density).
    (Measured against ordering by balanced KD leaves, the direct Morton
    sort is both ~3x cheaper on host and gives faster kernels.)
    """
    points = np.asarray(points)
    if len(points) <= 1:
        return np.arange(len(points))
    words = morton_codes(points)
    if len(words) == 1:
        return np.argsort(words[0], kind="stable")
    return np.lexsort(words[::-1])  # np.lexsort: last key is primary


def _tile_boxes_inram(sub: np.ndarray, order: np.ndarray,
                      block: int):
    """(nt, k) per-tile f32 bounding boxes of the sorted layout."""
    n, k = sub.shape
    nt = -(-n // block)
    lo = np.empty((nt, k), np.float32)
    hi = np.empty((nt, k), np.float32)
    step = max(1, (1 << 22) // max(block, 1))
    for t0 in range(0, nt, step):
        t1 = min(t0 + step, nt)
        rows = sub[order[t0 * block:t1 * block]]
        pad = (t1 - t0) * block - len(rows)
        if pad:
            rows = np.concatenate([rows, np.full((pad, k), rows[-1])])
        tiles = rows.reshape(t1 - t0, block, k)
        lo[t0:t1] = tiles.min(axis=1)
        hi[t0:t1] = tiles.max(axis=1)
    return lo, hi


def _weights_from_boxes(lo: np.ndarray, hi: np.ndarray, eps: float,
                        max_cols: int = 4096) -> np.ndarray:
    """Per-tile live-column counts from (nt, k) tile boxes — the tiled
    kernels' own cost model, shared between the in-RAM and the
    streaming range splits so work-balanced cuts are byte-identical
    whichever builder produced the boxes (f32 tile min/max is exact and
    order-independent, so the boxes themselves already match)."""
    nt, k = lo.shape
    stride = max(1, -(-nt // max_cols))
    clo, chi = lo[::stride], hi[::stride]
    eps2 = np.float32(eps) ** 2
    w = np.zeros(nt)
    # Row-chunk the (chunk, cols, k) gap broadcast to ~8M elements:
    # the old 2^26 budget meant three ~270MB f32 temps live at once at
    # the 10M geometry — the single biggest transient of the whole
    # streaming build.  Chunking is along rows only, so w is
    # byte-identical at any budget.
    chunk = max(1, (1 << 23) // max(len(clo) * k, 1))
    for s in range(0, nt, chunk):
        e = min(s + chunk, nt)
        gap = np.maximum(
            0.0,
            np.maximum(clo[None] - hi[s:e, None],
                       lo[s:e, None] - chi[None]),
        )
        w[s:e] = (np.sum(gap * gap, axis=-1) <= eps2).sum(axis=1)
    return w * stride


def _morton_range_weights(sub: np.ndarray, order: np.ndarray,
                          block: int, eps: float,
                          max_cols: int = 4096) -> np.ndarray:
    """Per-tile work estimate for the balanced range split: the number
    of live (box-gap <= eps) column tiles each row tile of the sorted
    layout sees — exactly the tiled kernels' cost model (work = live
    tile pairs x block^2), computed on (nt, k) host boxes in
    milliseconds.  Past ``max_cols`` tiles the column side is sampled
    on an even stride (Morton-adjacent tiles are spatially redundant,
    so a stride is representative) and the count scaled back up — the
    estimate only has to RANK density, the split quantizes it anyway.
    """
    lo, hi = _tile_boxes_inram(sub, order, block)
    return _weights_from_boxes(lo, hi, eps, max_cols)


_CENTER_CHUNK = 1 << 20


def _chunked_center(points, n: int, k: int,
                    chunk: int = _CENTER_CHUNK) -> np.ndarray:
    """float64 dataset mean by fixed-size chunked accumulation.

    One definition for BOTH the in-RAM and streaming range splits:
    floating-point summation is grouping-sensitive, so the two paths
    must consume identical chunk boundaries (``_CENTER_CHUNK`` rows) to
    produce a byte-identical center — the recentred-f32 frame every
    downstream slab row and sort key lives in.
    """
    acc = np.zeros(k, np.float64)
    for s in range(0, n, chunk):
        acc += np.sum(points[s:min(s + chunk, n)], axis=0,
                      dtype=np.float64)
    return acc / max(n, 1)


def _balanced_starts(w: np.ndarray, n: int, block: int,
                     n_ranges: int, slack: float = 1.5) -> np.ndarray:
    """Range boundaries equalizing cumulative tile WORK, not rows.

    Greedy prefix cuts at the per-tile weight's quantiles, clamped so
    no range exceeds ``slack`` times the equal-rows share of tiles —
    the row cap bounds every shard's slab capacity (the fused program
    pads all shards to the LARGEST range), so a dense region can shed
    work without a sparse shard's padding eating the win.  Cuts land
    on tile boundaries: weights are per-tile, and sub-tile cuts would
    buy nothing the kernels could see.
    """
    nt = len(w)
    cw = np.concatenate([[0.0], np.cumsum(w)])
    max_t = max(1, int(np.ceil(slack * nt / n_ranges)))
    starts_t = np.zeros(n_ranges + 1, dtype=np.int64)
    starts_t[n_ranges] = nt
    prev = 0
    for j in range(1, n_ranges):
        tgt = cw[-1] * j / n_ranges
        t = int(np.searchsorted(cw, tgt))
        if t > 0 and cw[t] - tgt > tgt - cw[t - 1]:
            t -= 1
        t = max(t, prev, nt - (n_ranges - j) * max_t)
        t = min(t, nt, prev + max_t)
        starts_t[j] = prev = t
    return np.minimum(starts_t * block, n)


def morton_range_split(points: np.ndarray, n_ranges: int,
                       chunk: int = 1 << 20, eps: float = None,
                       block: int = None):
    """Global Morton keying + contiguous range splitting.

    The zero-duplication analogue of :class:`KDPartitioner` for the
    ``mode="global_morton"`` distributed engine
    (:mod:`pypardis_tpu.parallel.global_morton`): instead of KD boxes
    whose 2*eps expansions overlap (and duplicate boundary points), the
    WHOLE dataset is keyed by one global Morton order and each shard
    owns a disjoint, contiguous row range of it — every point
    clustered exactly once by construction.

    With ``eps`` and ``block`` given, ranges equalize estimated WORK
    rather than rows: per-tile live-column counts
    (:func:`_morton_range_weights` — the tiled kernels' own cost
    model) are prefix-split at their quantiles, cuts quantized to tile
    boundaries and row counts capped at 1.5x the equal share (the
    fused program pads every shard to the largest range).  Equal-row
    ranges leave dense regions with up to ~1.2x the live pairs of
    sparse ones, and the slowest device binds the whole fused program.
    Without ``eps``/``block`` the split is plain equal rows.  EVERY
    contiguous split yields identical labels — balance is purely a
    performance property — so callers may cache one split across eps
    values.

    The order is computed in the recentred float32 frame (float64 mean
    subtracted, cast to f32 — the exact frame the shard slabs are built
    in, :func:`pypardis_tpu.parallel.sharded._recentre_rows`), so slab
    rows and sort keys can never disagree about borderline ordering.

    This path materializes one f32 copy of the dataset plus the full
    (N,) permutation, so it wants the input comfortably in host RAM.
    Datasets that do not fit take
    :func:`morton_range_split_streaming` — an external sample-sort
    over memmap chunks producing the byte-identical per-range order,
    starts, and center with host memory bounded by O(chunk + sample +
    one spill bucket).  Returns ``(order, starts, center)``: ``order``
    the (N,) int32 global Morton permutation, ``starts`` the
    (n_ranges + 1,) int64 range boundaries (equal ``ceil(N /
    n_ranges)``-row ranges, or work-balanced cuts when ``eps`` and
    ``block`` are given), ``center`` the float64 dataset mean.
    """
    points = np.asarray(points)
    n, k = points.shape
    n_ranges = max(1, int(n_ranges))
    # Chunked f64 accumulation (not np.mean): the ONE center definition
    # shared with the streaming split, so the two paths' recentred-f32
    # frames are byte-identical (see _chunked_center).
    center = _chunked_center(points, n, k)
    sub = np.empty((n, k), np.float32)
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        np.subtract(points[s:e], center, out=sub[s:e], casting="unsafe")
    order = np.asarray(spatial_order(sub), dtype=np.int32)
    if eps is not None and block is not None and n_ranges > 1 and n:
        w = _morton_range_weights(sub, order, int(block), float(eps))
        starts = _balanced_starts(w, n, int(block), n_ranges)
    else:
        per = -(-n // n_ranges)
        starts = np.minimum(
            np.arange(n_ranges + 1, dtype=np.int64) * per, n
        )
    del sub
    return order, starts, center


# ---------------------------------------------------------------------------
# Streaming external sample-sort over memmap chunks (ISSUE 10 tentpole)
# ---------------------------------------------------------------------------


def _lex_searchsorted(cols, spl_cols) -> np.ndarray:
    """Vectorized lexicographic bucket assignment.

    ``cols``: per-row key columns (most-significant first; the last is
    a unique tiebreak, e.g. the row id); ``spl_cols``: the splitters'
    matching columns, lexicographically ascending.  Returns, for each
    row, the count of splitters <= the row's key — i.e. its bucket
    index in ``[0, len(splitters)]``.  Because the composite key is
    UNIQUE (the id column), all-duplicate coordinate geometries still
    spread evenly across buckets instead of collapsing into one.
    """
    n = len(cols[0])
    b1 = len(spl_cols[0])
    lo = np.zeros(n, np.int64)
    if b1 == 0:
        return lo
    hi = np.full(n, b1, np.int64)
    while True:
        active = lo < hi
        if not active.any():
            return lo
        mid = (lo + hi) >> 1
        midc = np.minimum(mid, b1 - 1)
        # le[r] = splitter[mid[r]] <= row r, by column cascade.
        le = np.zeros(n, bool)
        decided = np.zeros(n, bool)
        for c, sc in zip(cols, spl_cols):
            sv = sc[midc]
            le |= ~decided & (sv < c)
            decided |= sv != c
        le |= ~decided  # fully equal -> <=
        lo = np.where(active & le, mid + 1, lo)
        hi = np.where(active & ~le, mid, hi)


def _accum_tile_boxes(tlo, thi, rows, gpos: int, block: int) -> None:
    """Fold sorted rows at global positions [gpos, gpos+len) into the
    per-tile min/max boxes — exact whatever chunking delivers them."""
    m, _k = rows.shape
    if m == 0:
        return
    pos = 0
    head = (-gpos) % block
    if head:
        h = min(head, m)
        t = gpos // block
        np.minimum(tlo[t], rows[:h].min(axis=0), out=tlo[t])
        np.maximum(thi[t], rows[:h].max(axis=0), out=thi[t])
        pos = h
    full = (m - pos) // block
    if full:
        t0 = (gpos + pos) // block
        tiles = rows[pos:pos + full * block].reshape(full, block, -1)
        np.minimum(tlo[t0:t0 + full], tiles.min(axis=1),
                   out=tlo[t0:t0 + full])
        np.maximum(thi[t0:t0 + full], tiles.max(axis=1),
                   out=thi[t0:t0 + full])
        pos += full * block
    if pos < m:
        t = (gpos + pos) // block
        np.minimum(tlo[t], rows[pos:].min(axis=0), out=tlo[t])
        np.maximum(thi[t], rows[pos:].max(axis=0), out=thi[t])


class MortonStreamSplit:
    """The streaming global-Morton split's product handle.

    Produced by :func:`morton_range_split_streaming`.  Holds the range
    boundaries / center / per-tile boxes as tiny metadata plus one
    sorted on-disk spill file; per-range rows are read back on demand
    (:meth:`range_rows` / :meth:`iter_range_rows`) so no caller ever
    needs the full sorted array or the full permutation in host RAM.
    Spill files are tempdir-scoped: :meth:`close` (also via context
    manager and best-effort ``__del__``) removes the directory on both
    success and failure paths.
    """

    def __init__(self, n: int, k: int, starts: np.ndarray,
                 center: np.ndarray, spill_dir: str, sorted_path: str,
                 rec2, tile_lo, tile_hi, stats: Dict, segments=None):
        self.n = int(n)
        self.k = int(k)
        self.starts = np.asarray(starts, dtype=np.int64)
        self.center = np.asarray(center, dtype=np.float64)
        self.tile_lo = tile_lo
        self.tile_hi = tile_hi
        self.stats = dict(stats)
        self._spill_dir = spill_dir
        self._sorted_path = sorted_path
        self._rec2 = rec2
        # Multi-process fleets: the sorted spill is striped over one
        # segment file per process; ``segments`` is the global span
        # table [(gstart, gend, path, file_offset), ...] ascending in
        # gstart.  None = the single sorted.bin file.
        self._segments = segments
        self._closed = False

    @property
    def n_ranges(self) -> int:
        return len(self.starts) - 1

    def _read(self, a: int, b: int) -> np.ndarray:
        if self._closed:
            raise RuntimeError("MortonStreamSplit is closed")
        itemsize = self._rec2.itemsize
        if self._segments is None:
            with open(self._sorted_path, "rb") as f:
                f.seek(a * itemsize)
                buf = f.read((b - a) * itemsize)
            return np.frombuffer(buf, dtype=self._rec2)
        # Striped spill: gather the [a, b) span from every overlapping
        # per-process segment (all on the shared store — any process
        # reads any segment).  Spans partition the global order, so the
        # pieces tile the output exactly.
        out = np.empty(b - a, self._rec2)
        for gs, ge, path, fo in self._segments:
            if ge <= a or gs >= b:
                continue
            s0, s1 = max(a, gs), min(b, ge)
            with open(path, "rb") as f:
                f.seek(fo + (s0 - gs) * itemsize)
                buf = f.read((s1 - s0) * itemsize)
            out[s0 - a:s1 - a] = np.frombuffer(buf, dtype=self._rec2)
        return out

    def range_rows(self, s: int):
        """(ids int32, rows f32 (m, k)) of range ``s`` — the recentred
        f32 rows in global Morton order, exactly what
        ``_recentre_rows(points, order[a:b], center)`` returns on the
        in-RAM path (pinned)."""
        a, b = int(self.starts[s]), int(self.starts[s + 1])
        arr = self._read(a, b)
        return arr["id"].astype(np.int32), arr["x"]

    def iter_range_rows(self, s: int, chunk: int = 1 << 16):
        """Yield ``(offset, ids int32, rows f32)`` pieces of range
        ``s`` so callers can fill slabs without ever materializing a
        whole range (the 100M-run memory contract)."""
        a, b = int(self.starts[s]), int(self.starts[s + 1])
        for c in range(a, b, chunk):
            e = min(c + chunk, b)
            arr = self._read(c, e)
            yield c - a, arr["id"].astype(np.int32), arr["x"]

    def range_ids(self, s: int) -> np.ndarray:
        """The int32 global Morton order restricted to range ``s``."""
        return self.range_rows(s)[0]

    def row_span(self, a: int, b: int):
        """(ids, rows) for an arbitrary global sorted-position span —
        the chained route's tile-granular boundary reads."""
        arr = self._read(int(a), int(b))
        return arr["id"].astype(np.int32), arr["x"]

    def close(self, sync: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        dist = _dist()
        if dist.process_count() > 1:
            # Fleet close: every process reaches here at the same
            # program point; the barrier keeps the coordinator from
            # removing the shared spill while a slower process still
            # reads its last range, then only the coordinator unlinks.
            if sync:
                dist.barrier("stream.close")
            if not dist.is_coordinator():
                return
        shutil.rmtree(self._spill_dir, ignore_errors=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # best-effort: tempdir never outlives the handle
        try:
            # No collective from a destructor — at interpreter teardown
            # a barrier could hang the fleet; an unsynced coordinator
            # rmtree on an abandoned handle has no readers to race.
            self.close(sync=False)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def morton_range_split_streaming(
    points, n_ranges: int, eps: float = None, block: int = None,
    chunk: int = 1 << 17, spill_dir: Optional[str] = None,
    bucket_bytes: Optional[int] = None,
    sample_per_bucket: int = 512, seed: int = 0,
) -> MortonStreamSplit:
    """External sample-sort for the global Morton order.

    The out-of-core twin of :func:`morton_range_split`: ``points`` is
    any row-sliceable (N, k) array — typically a disk-backed
    ``np.memmap`` — and host anonymous memory stays bounded by
    O(chunk + sample + one spill bucket + one range) instead of the
    in-RAM path's full f32 copy + full permutation.  Three passes:

    1. **scan** — chunked f64 center accumulation (the shared
       :func:`_chunked_center` grouping, so the recentred frame is
       byte-identical to the in-RAM split) plus exact per-axis extrema;
       then a uniform row sample is keyed in the recentred-f32 frame
       and ``B - 1`` splitter keys are read off its quantiles.
       Splitters live in the UNIQUE composite key domain
       ``(morton words..., row id)`` — the id tiebreak is exactly what
       a stable sort uses, so a degenerate all-duplicate-rows geometry
       (every Morton key identical) still buckets evenly instead of
       spilling the dataset into one bucket.
    2. **bucket-append** — each chunk is recentred, Morton-keyed
       against the global frame (:func:`_morton_quantize_words` — the
       in-RAM keying, elementwise), and its rows (int64 id + f32
       coords + key words) appended to per-bucket spill files.
    3. **per-bucket sort** — each bucket alone is loaded, stably
       sorted by (words, id), and appended to one sorted spill file;
       per-tile bounding boxes of the global sorted layout accumulate
       on the way through.  Concatenated buckets ARE the stable global
       Morton sort (buckets partition the key domain in order; the id
       column reproduces stability), so every range read is
       byte-identical to ``order[a:b]`` of the in-RAM split — pinned
       by tests/test_global_morton.py.

    ``starts`` then come from the SAME formulas as the in-RAM split:
    equal rows, or work-balanced cuts via :func:`_weights_from_boxes`
    over the streamed tile boxes when ``eps`` and ``block`` are given
    — byte-identical either way.

    Bucket count is sized so one bucket's spill records fit in
    ``bucket_bytes`` (default ``PYPARDIS_STREAM_BUCKET_MB``, 32MB —
    the bucket sort holds ~2.5 bucket-sized temps, and 32MB keeps the
    whole sort under the one-shard term of the memory budget);
    with ``sample_per_bucket`` splitter samples per bucket the max
    bucket stays within ~1.5x the equal share with overwhelming
    probability (NOWSort-style sample-sort bound; the realized max is
    reported in ``stats['stream_max_bucket_rows']``).  Spill lives in
    a fresh tempdir under ``spill_dir`` (default
    ``PYPARDIS_SPILL_DIR`` or the system tempdir) and is removed by
    :meth:`MortonStreamSplit.close` on success and failure alike.

    For d > 32 the axis subset is chosen by chunked-moment variance —
    the same axes as the in-RAM split up to f32-vs-f64 variance
    rounding on near-tied axes; byte parity is pinned for d <= 32
    (every axis keyed).

    **Multi-process fleets** (``parallel.dist``): the build partitions
    across processes — pass 1 runs on the coordinator alone and its
    tiny products (frame constants, splitter keys, spill-dir name)
    broadcast; pass 2 splits by chunk index and pass 3 by bucket index
    (build wall ∝ 1/P), each process appending to its own per-bucket /
    sorted-segment files in ONE shared spill directory every process
    can read (``PYPARDIS_SPILL_DIR`` on a shared store for real
    multi-host fleets; localhost fleets share the system tempdir).
    The unique (words..., id) composite key makes each bucket's sort
    independent of segment arrival order, so range reads stay
    byte-identical to the single-process build — pinned by
    tests/test_multihost.py.
    """
    n, k = points.shape
    n_ranges = max(1, int(n_ranges))
    if n >= np.iinfo(np.int32).max:
        raise ValueError(
            "morton_range_split_streaming: N must fit int32 gids"
        )
    dist = _dist()
    n_procs = dist.process_count()
    my_proc = dist.process_index()

    # -- pass 1: center + exact extrema (+ moments for the d>32 axis
    # subset).  Coordinator-only in a fleet: the products are tiny and
    # broadcasting them keys every process against bit-identical frame
    # constants without P redundant full-data scans.
    if n_procs == 1 or dist.is_coordinator():
        center = _chunked_center(points, n, k)
        lo_raw = np.full(k, np.inf)
        hi_raw = np.full(k, -np.inf)
        sumsq = np.zeros(k, np.float64)
        for s in range(0, n, _CENTER_CHUNK):
            c = np.asarray(points[s:min(s + _CENTER_CHUNK, n)])
            np.minimum(lo_raw, c.min(axis=0), out=lo_raw)
            np.maximum(hi_raw, c.max(axis=0), out=hi_raw)
            if k > 32:
                d = c.astype(np.float64) - center
                sumsq += np.sum(d * d, axis=0)
        frame = (center, lo_raw, hi_raw, sumsq)
    else:
        frame = None
    if n_procs > 1:
        frame = dist.broadcast_arrays(frame)
    center, lo_raw, hi_raw, sumsq = frame
    ka, bits = morton_plan(k)
    axes = np.arange(k)
    if k > ka:
        axes = np.sort(np.argsort(sumsq / max(n, 1))[::-1][:ka])
    # f32(x - center) is monotone in x, so the recentred-f32 extrema
    # are the recentred raw extrema — byte-equal to sub.min()/max() of
    # the in-RAM path's full f32 copy.
    lo32 = np.empty(k, np.float32)
    hi32 = np.empty(k, np.float32)
    np.subtract(lo_raw, center, out=lo32, casting="unsafe")
    np.subtract(hi_raw, center, out=hi32, casting="unsafe")
    lo32, hi32 = lo32[axes], hi32[axes]
    span = np.maximum(hi32 - lo32, np.finfo(np.float32).tiny)
    n_words = max(1, -(-bits * len(axes) // 64)) if len(axes) else 1

    def _keys(sub_chunk):
        return _morton_quantize_words(sub_chunk[:, axes], lo32, span,
                                      bits)

    def _recentred(s, e):
        sub = np.empty((e - s, k), np.float32)
        np.subtract(np.asarray(points[s:e]), center, out=sub,
                    casting="unsafe")
        return sub

    # -- splitters from a uniform sample -------------------------------
    rec_bytes = 8 * n_words + 8 + 4 * k
    if bucket_bytes is None:
        bucket_bytes = int(float(envreg.raw(
            "PYPARDIS_STREAM_BUCKET_MB", 32)) * 1e6)
    n_buckets = int(min(max(1, -(-n * rec_bytes // max(bucket_bytes, 1))),
                        512))
    rng = np.random.default_rng(seed)
    n_sample = int(min(n, max(4096, sample_per_bucket * n_buckets)))
    sampled = 0
    if n_buckets > 1 and n:
        # Coordinator samples and keys; the splitter columns broadcast
        # (the NOWSort move) so every process buckets identically.
        if n_procs == 1 or dist.is_coordinator():
            sample_ids = np.unique(rng.integers(0, n, n_sample))
            sampled = len(sample_ids)
            sw = _keys(_recentred_rows_at(points, sample_ids, center, k))
            s_order = np.lexsort(
                (sample_ids,) + tuple(sw[::-1])
            )
            pos = (np.arange(1, n_buckets)
                   * len(sample_ids)) // n_buckets
            sel = s_order[pos]
            spl_cols = [w[sel] for w in sw] + [sample_ids[sel].astype(
                np.int64)]
        else:
            spl_cols = None
        if n_procs > 1:
            payload = None
            if dist.is_coordinator():
                payload = list(spl_cols) + [np.int64(sampled)]
            out = dist.broadcast_arrays(payload)
            spl_cols, sampled = [np.asarray(a) for a in out[:-1]], int(
                out[-1]
            )
    else:
        n_buckets = 1
        spl_cols = None

    # -- pass 2: bucket-append spill -----------------------------------
    # Fleet: one shared spill dir (coordinator mkdtemp, name
    # broadcast); chunks partition round-robin by chunk index and each
    # process appends to its OWN per-bucket segment files, so pass-2
    # wall drops ∝ 1/P with zero write contention.
    base_dir = spill_dir or envreg.raw("PYPARDIS_SPILL_DIR")
    if n_procs == 1 or dist.is_coordinator():
        sdir = tempfile.mkdtemp(prefix="pypardis_gm_spill_", dir=base_dir)
    else:
        sdir = None
    if n_procs > 1:
        # The broadcast doubles as the "dir exists" rendezvous.
        sdir = dist.broadcast_str(sdir)
    rec = np.dtype([("w", "<u8", (n_words,)), ("id", "<i8"),
                    ("x", "<f4", (k,))])
    rec2 = np.dtype([("id", "<i8"), ("x", "<f4", (k,))])

    def _bucket_path(b: int, p: int) -> str:
        if n_procs == 1:
            return os.path.join(sdir, f"b{b:04d}.bin")
        return os.path.join(sdir, f"b{b:04d}.p{p:02d}.bin")

    try:
        counts = np.zeros(n_buckets, np.int64)
        files = [open(_bucket_path(b, my_proc), "wb")
                 for b in range(n_buckets)]
        try:
            for ci, s in enumerate(range(0, n, chunk)):
                if n_procs > 1 and ci % n_procs != my_proc:
                    continue
                e = min(s + chunk, n)
                sub = _recentred(s, e)
                words = _keys(sub)
                ids = np.arange(s, e, dtype=np.int64)
                arr = np.empty(e - s, rec)
                for j, w in enumerate(words):
                    arr["w"][:, j] = w
                arr["id"] = ids
                arr["x"] = sub
                if n_buckets > 1:
                    bkt = _lex_searchsorted(words + [ids], spl_cols)
                    order = np.argsort(bkt, kind="stable")
                    arr = arr[order]
                    bounds = np.searchsorted(
                        bkt[order], np.arange(n_buckets + 1)
                    )
                else:
                    bounds = np.array([0, e - s])
                for b in range(n_buckets):
                    a0, a1 = int(bounds[b]), int(bounds[b + 1])
                    if a1 > a0:
                        files[b].write(arr[a0:a1].tobytes())
                        counts[b] += a1 - a0
        finally:
            for f in files:
                f.close()
        if n_procs > 1:
            # Nobody sorts a bucket a peer is still appending to; then
            # GLOBAL bucket counts come off the shared store's file
            # sizes (exact — records are fixed-width).
            dist.barrier("stream.pass2")
            counts = np.zeros(n_buckets, np.int64)
            for b in range(n_buckets):
                for p in range(n_procs):
                    try:
                        sz = os.path.getsize(_bucket_path(b, p))
                    except OSError:
                        sz = 0
                    counts[b] += sz // rec.itemsize
            # Second rendezvous: pass 3 unlinks each segment right
            # after reading it, so nobody may start sorting until every
            # peer has finished SIZING — a fast process's unlink would
            # zero a slow peer's counts for the buckets it doesn't own.
            dist.barrier("stream.counts")

        # -- pass 3: sort each bucket alone, stream tile boxes ---------
        # Fleet: buckets partition round-robin; bucket b's records are
        # the concatenation of every process's segment, and the UNIQUE
        # (words..., id) lexsort key makes the sorted bucket
        # independent of segment order — byte-identical to the
        # single-process sort.  Global write positions come from the
        # exclusive bucket-count scan, so tile-box accumulation and
        # range reads see the same global layout.
        nt = -(-n // block) if block else 0
        tlo = np.full((nt, k), np.float32(np.inf)) if nt else None
        thi = np.full((nt, k), np.float32(-np.inf)) if nt else None
        offsets = np.concatenate(([0], np.cumsum(counts)))
        if n_procs == 1:
            sorted_path = os.path.join(sdir, "sorted.bin")
        else:
            sorted_path = os.path.join(
                sdir, f"sorted.p{my_proc:02d}.bin"
            )
        with open(sorted_path, "wb") as out:
            for b in range(n_buckets):
                if n_procs > 1 and b % n_procs != my_proc:
                    continue
                segs = []
                for p in range(n_procs):
                    path = _bucket_path(b, p)
                    if os.path.exists(path):
                        segs.append(np.fromfile(path, dtype=rec))
                        os.unlink(path)
                raw = (
                    segs[0] if len(segs) == 1
                    else np.concatenate(segs) if segs
                    else np.empty(0, rec)
                )
                del segs
                if len(raw) == 0:
                    continue
                perm = np.lexsort(
                    (raw["id"],) + tuple(
                        raw["w"][:, j]
                        for j in range(n_words - 1, -1, -1)
                    )
                )
                srt = raw[perm]
                del raw, perm
                # Piecewise re-pack + write: a whole-bucket rec2 copy
                # plus its tobytes() was two more bucket-sized temps
                # live at the sort's peak for no reason.
                piece = 1 << 17
                for p0 in range(0, len(srt), piece):
                    p1 = min(p0 + piece, len(srt))
                    o2 = np.empty(p1 - p0, rec2)
                    o2["id"] = srt["id"][p0:p1]
                    o2["x"] = srt["x"][p0:p1]
                    out.write(o2.tobytes())
                    del o2
                if nt:
                    _accum_tile_boxes(
                        tlo, thi, srt["x"], int(offsets[b]), block
                    )
                del srt
        segments = None
        if n_procs > 1:
            if nt:
                np.savez(
                    os.path.join(sdir, f"boxes.p{my_proc:02d}.npz"),
                    tlo=tlo, thi=thi,
                )
            dist.barrier("stream.pass3")
            if nt:
                # Elementwise-merge every process's tile boxes: each
                # tile's true box is the min/max over the buckets that
                # touched it, wherever they sorted.
                for p in range(n_procs):
                    if p == my_proc:
                        continue
                    with np.load(os.path.join(
                        sdir, f"boxes.p{p:02d}.npz"
                    )) as z:
                        np.minimum(tlo, z["tlo"], out=tlo)
                        np.maximum(thi, z["thi"], out=thi)
            # Global span table — derivable on every process from the
            # shared counts: bucket b lives in process (b mod P)'s
            # segment file at the running offset of that process's
            # earlier buckets.
            seg_pos = [0] * n_procs
            segments = []
            for b in range(n_buckets):
                c = int(counts[b])
                if not c:
                    continue
                p = b % n_procs
                segments.append((
                    int(offsets[b]), int(offsets[b]) + c,
                    os.path.join(sdir, f"sorted.p{p:02d}.bin"),
                    seg_pos[p] * rec2.itemsize,
                ))
                seg_pos[p] += c

        # -- starts: the in-RAM formulas, verbatim ---------------------
        if eps is not None and block is not None and n_ranges > 1 and n:
            w = _weights_from_boxes(tlo, thi, float(eps))
            starts = _balanced_starts(w, n, int(block), n_ranges)
        else:
            per = -(-n // n_ranges)
            starts = np.minimum(
                np.arange(n_ranges + 1, dtype=np.int64) * per, n
            )
        stats = {
            "stream_buckets": int(n_buckets),
            "stream_max_bucket_rows": int(counts.max()) if n else 0,
            "stream_sample_rows": int(sampled),
            "stream_procs": int(n_procs),
            "spill_bytes": int(n * (rec.itemsize + rec2.itemsize)),
        }
        return MortonStreamSplit(
            n, k, starts, center, sdir, sorted_path, rec2, tlo, thi,
            stats, segments=segments,
        )
    except BaseException:
        # A fleet member failing mid-build is a whole-fleet failure
        # (peers block at the next barrier until the launcher tears
        # them down); only the coordinator owns the shared dir.
        if n_procs == 1 or dist.is_coordinator():
            shutil.rmtree(sdir, ignore_errors=True)
        raise


def _recentred_rows_at(points, ids, center, k):
    """Gather + recentre specific rows (sample keying)."""
    sub = np.empty((len(ids), k), np.float32)
    np.subtract(np.asarray(points[ids]), center, out=sub,
                casting="unsafe")
    return sub


class MortonRangePartitioner:
    """Parity-product shim for the global-Morton distributed mode.

    Presents the :class:`KDPartitioner` product surface (``partitions``
    / ``result`` / ``bounding_boxes`` / ``n_partitions``) over Morton
    ranges, so ``DBSCAN``'s inspection attributes and
    ``cluster_mapping()`` work identically across modes.  There is no
    split tree (``tree == []``) and no ``route()``: Morton ranges are a
    property of the fitted dataset's order, not a spatial predicate new
    points can replay.
    """

    def __init__(self, order: np.ndarray, starts: np.ndarray,
                 bounding_boxes: Dict[int, BoundingBox]):
        order = np.asarray(order, dtype=np.int32)
        starts = np.asarray(starts, dtype=np.int64)
        self.tree: list = []
        self.builder = "morton_range"
        self.level_times_s: list = []
        self.split_method = "morton_range"
        self.bounding_boxes = dict(bounding_boxes)
        self.partitions = {
            s: order[starts[s]:starts[s + 1]].copy()
            for s in range(len(starts) - 1)
        }
        self.result = np.empty(len(order), dtype=np.int32)
        for s, idx in self.partitions.items():
            self.result[idx] = s

    @property
    def n_partitions(self) -> int:
        return len(self.partitions)

    def partition_sizes(self) -> np.ndarray:
        labels = sorted(self.partitions)
        return np.array([len(self.partitions[l]) for l in labels])


# Level-builder buffer pool: the two dataset-sized ping-pong buffers,
# reused across builds of the same geometry (warm refits rebuild the
# partitioner every fit — bench's host reps, eps sweeps).  Reuse also
# sidesteps the first-touch cost: page-faulting fresh pages INSIDE the
# re-bucket gather measured ~8x slower than the gather itself, so fresh
# allocations are pre-faulted with a sequential fill.  Only the most
# recent shape is kept (two buffers ~= one extra dataset pair).
_LEVEL_POOL: Dict = {}


def _borrow_level_buffer(shape, dtype) -> np.ndarray:
    key = (tuple(shape), np.dtype(dtype).str)
    stack = _LEVEL_POOL.get(key)
    if stack:
        return stack.pop()
    buf = np.empty(shape, dtype)
    buf.fill(0)  # pre-fault; see _LEVEL_POOL
    return buf


def _return_level_buffers(bufs) -> None:
    if not bufs:
        return
    key = (bufs[0].shape, bufs[0].dtype.str)
    if set(_LEVEL_POOL) - {key}:
        _LEVEL_POOL.clear()
    stack = _LEVEL_POOL.setdefault(key, [])
    stack.extend(bufs)
    del stack[2:]


def clear_level_pool() -> None:
    """Drop the pooled level-builder buffers (tests, memory pressure)."""
    _LEVEL_POOL.clear()


class KDPartitioner:
    """Binary-tree spatial partitioner over an in-memory point set.

    Constructor surface mirrors the reference
    (``partition.py:98-142``): ``KDPartitioner(data, max_partitions, k,
    split_method)``; unknown split methods silently fall back to
    ``'min_var'`` (partition.py:129-130).  ``data`` is an (N, k) array
    (or anything ``np.asarray`` accepts).

    Products:

    * ``partitions``: {label → int array of point indices}
      (reference: {label → RDD}).
    * ``bounding_boxes``: {label → BoundingBox}.
    * ``result``: (N,) int array, point → partition label
      (reference: union RDD of ((key, label), vector)).
    * ``tree``: list of (parent_label, axis, boundary, left_label,
      right_label) — the whole split tree as metadata, serializable and
      reusable to route new points.

    For very large N pass ``sample_size``: split boundaries are then
    estimated from a uniform subsample (statistically identical for the
    moment-based strategies) and the finished tree is applied to all
    points vectorized.

    ``builder`` selects the tree construction engine.  ``"level"`` (the
    ``"auto"`` default for in-RAM arrays) is the level-synchronous fast
    path: points live in a level-ordered buffer where every tree node
    is a CONTIGUOUS segment, so split statistics read zero-copy views
    instead of an O(node) fancy gather per node, and each level
    re-buckets with one stable in-place permutation — the per-level
    cost is O(N), so the build scales with tree DEPTH instead of node
    count (the legacy builder's per-node gathers made mp=8 -> mp=16
    cost ~5x on 10M points; here it is the extra level, ~1.2x).  The
    products (``tree``, ``result``, ``partitions``, ``bounding_boxes``)
    are byte-identical to ``"legacy"`` under the same seed: segments
    preserve ascending index order and the RNG subsample draws consume
    the identical stream (regression-pinned).  ``"legacy"`` keeps the
    original node-at-a-time builder; ``"auto"`` selects it for
    ``np.memmap`` inputs, where the level buffer's +1x dataset copy
    would defeat the larger-than-RAM streaming premise.  (Memmaps that
    want the zero-duplication engine skip KD partitioning entirely:
    ``mode="global_morton"`` keys them through the external
    sample-sort, :func:`morton_range_split_streaming`.)

    ``level_times_s`` records per-level build seconds for either
    builder — surfaced as ``partition_levels_s`` in
    ``DBSCAN.report()``.
    """

    def __init__(
        self,
        data,
        max_partitions: Optional[int] = None,
        k: Optional[int] = None,
        split_method: str = "min_var",
        sample_size: Optional[int] = 1_000_000,
        seed: int = 0,
        builder: str = "auto",
    ):
        # Keep the caller's dtype: forcing float64 here doubled host
        # memory for float32 datasets (round-1 finding).  Split math
        # runs in float64 on (sub)samples regardless.
        points = np.asarray(data)
        if points.dtype not in (np.float32, np.float64):
            points = points.astype(np.float64)
        if points.ndim != 2:
            raise ValueError(f"data must be (N, k), got shape {points.shape}")
        # C-layout is load-bearing for builder equivalence: fancy row
        # gathers of an F-order array come back F-order, whose
        # contiguous-axis reductions differ in the last ulp from the
        # C-layout views the level builder reads.  (No-op for the
        # common case, including C-order memmaps.)
        if not points.flags.c_contiguous:
            points = np.ascontiguousarray(points)
        self.points = points
        self.k = int(k) if k is not None else points.shape[1]
        self.split_method = (
            split_method if split_method in _VALID_SPLIT_METHODS else "min_var"
        )
        # Reference default is 4**k (partition.py:132-133) — untenable
        # beyond a few dimensions; cap at 256 and at N.
        if max_partitions is None:
            max_partitions = min(4 ** self.k, 256)
        self.max_partitions = max(1, min(int(max_partitions), len(points)))
        self._sample_size = sample_size
        self._rng = np.random.default_rng(seed)
        if builder not in _VALID_BUILDERS:
            raise ValueError(
                f"builder must be one of {_VALID_BUILDERS}, got {builder!r}"
            )
        if builder == "auto":
            # Memmaps keep the O(index)-memory legacy build on the KD
            # route (the level buffer would copy the dataset); the
            # streaming GLOBAL-MORTON route never builds a KD tree at
            # all — morton_range_split_streaming external-sorts the
            # memmap with O(chunk + bucket) host memory instead.
            builder = "legacy" if isinstance(data, np.memmap) else "level"
        self.builder = builder
        self.level_times_s: list = []

        # Global box as a union-reduction of chunk boxes — the same
        # shape as the reference's BoundingBox.union aggregate
        # (partition.py:135-137), just over host chunks instead of RDD
        # partitions; vectorized per chunk, never an (N, P, k) temp.
        chunk = 1 << 20
        global_box = BoundingBox(k=self.k)  # empty: union identity
        for s in range(0, len(points), chunk):
            e = min(s + chunk, len(points))
            global_box = global_box.union(
                BoundingBox(
                    lower=points[s:e].min(axis=0),
                    upper=points[s:e].max(axis=0),
                )
            )
        self.bounding_boxes: Dict[int, BoundingBox] = {}
        self.partitions: Dict[int, np.ndarray] = {}
        self.tree = []
        if self.builder == "level":
            self._create_partitions_level(global_box)
        else:
            self._create_partitions(global_box)

        self.result = np.empty(len(points), dtype=np.int32)
        for label, idx in self.partitions.items():
            self.result[idx] = label

    # -- tree construction -------------------------------------------------

    def _split_subset(self, subset_idx: np.ndarray, depth: int):
        """Pick (axis, boundary) for one node, from a subsample if large."""
        idx = subset_idx
        if self._sample_size is not None and len(idx) > self._sample_size:
            idx = self._rng.choice(idx, size=self._sample_size, replace=False)
        return self._choose_split(self.points[idx], depth)

    def _choose_split(self, pts: np.ndarray, depth: int):
        """(axis, boundary) from an already-gathered (M, k) subset.

        Shared by both builders: the legacy path hands it a fancy-index
        gather, the level path a contiguous view of the level-ordered
        buffer.  Both are (M, k) C-layout arrays holding the same rows
        in the same (ascending-index) order, so every reduction here is
        bit-identical between them.
        """
        if self.split_method == "rotation":
            axis = depth % self.k
            _, boundary = mean_var_split(pts[:, axis])
        elif self.split_method == "mean_var":
            axis = int(np.argmax(pts.var(axis=0)))
            _, boundary = mean_var_split(pts[:, axis])
        elif self.split_method == "median_search":
            axis = int(np.argmax(pts.var(axis=0)))
            _, boundary = median_search_split(pts[:, axis])
        else:  # min_var (reference default)
            axis, _, boundary = min_var_split(pts)
        return axis, boundary

    def _create_partitions(self, root_box: BoundingBox) -> None:
        """Breadth-first split loop (partition.py:152-183).

        Two-queue structure so each tree level completes before the next;
        left child keeps the parent label, right child takes the next
        fresh label (partition.py:173-176).
        """
        # int32 indices: the partition lists total one row per point and
        # ride through the whole shard build — int64 doubled the build's
        # host high-water for nothing below 2^31 points.
        all_idx = np.arange(len(self.points), dtype=np.int32)
        self.partitions = {0: all_idx}
        self.bounding_boxes = {0: root_box}
        next_label = 1
        todo = deque([(0, 0)])  # (label, depth)
        while todo and next_label < self.max_partitions:
            t_level = time.perf_counter()
            level = deque()
            while todo and next_label < self.max_partitions:
                label, depth = todo.popleft()
                idx = self.partitions[label]
                if len(idx) < 2:
                    continue
                axis, boundary = self._split_subset(idx, depth)
                below = self.points[idx, axis] < boundary
                left_idx, right_idx = idx[below], idx[~below]
                if len(left_idx) == 0 or len(right_idx) == 0:
                    # Degenerate boundary (e.g. all-equal coords): fall
                    # back to an exact median split, else give up.
                    _, boundary = median_search_split(self.points[idx, axis])
                    below = self.points[idx, axis] < boundary
                    left_idx, right_idx = idx[below], idx[~below]
                    if len(left_idx) == 0 or len(right_idx) == 0:
                        continue
                box = self.bounding_boxes[label]
                left_box, right_box = box.split(axis, boundary)
                right_label = next_label
                next_label += 1
                self.partitions[label] = left_idx
                self.partitions[right_label] = right_idx
                self.bounding_boxes[label] = left_box
                self.bounding_boxes[right_label] = right_box
                self.tree.append((label, axis, boundary, label, right_label))
                level.append((label, depth + 1))
                level.append((right_label, depth + 1))
            todo.extend(level)
            self.level_times_s.append(time.perf_counter() - t_level)

    def _create_partitions_level(self, root_box: BoundingBox) -> None:
        """Level-synchronous builder: one vectorized pass per tree level.

        Points live in a LEVEL-ORDERED buffer ``pts_lvl`` (one copy of
        the dataset, caller's dtype) alongside the matching index
        permutation ``order``; every tree node is a contiguous segment
        ``[s, e)`` of both.  Per level:

        * split statistics read the segment VIEW (zero-copy — the
          legacy builder fancy-gathers every node's rows, which is the
          O(N)-gathers-per-level term behind the mp=16 build blowup);
          subsampled nodes draw POSITIONS from the same RNG stream the
          legacy builder consumes (``Generator.choice`` draws depend
          only on the population size) and gather within the contiguous
          segment;
        * the split test is one projection of the segment's boundary
          column — a strided view compare, never ``points[idx, axis]``;
        * all of the level's splits then apply as ONE stable
          permutation (``np.take`` through a reused scratch buffer —
          fresh per-node compress temps measured 2-3x slower from page
          faulting alone): left rows compact to the segment head, right
          rows to the tail, so children stay contiguous AND keep
          ascending index order — which is exactly the legacy
          ``idx[below]`` ordering, making every downstream product
          byte-identical.

        Node visit order, label assignment, the budget stop, the
        degenerate-boundary fallback, and the RNG stream all replicate
        the legacy loop exactly (regression-pinned across all four
        split methods).  Peak extra host memory is two dataset-sized
        buffers (the level-ordered points and the permutation scratch)
        — the price of depth-scaling; ``builder="legacy"`` (automatic
        for memmaps) keeps the O(index)-memory node-at-a-time build.
        """
        n = len(self.points)
        self.bounding_boxes = {0: root_box}
        # label -> (start, end) in the level-ordered buffer; finalized
        # into index arrays once the tree is done.
        seg: Dict[int, tuple] = {0: (0, n)}
        identity = np.arange(n, dtype=np.int32)
        order = identity.copy()
        # Level 0 reads self.points directly (segment order == input
        # order); the first re-bucket takes INTO pts_lvl, so the level
        # buffer is only ever allocated written — no up-front copy.
        # C-contiguity is load-bearing for byte-identity: the legacy
        # builder's fancy gathers are always C-layout copies, and
        # numpy's reductions can differ in the last ulp across layouts.
        pts_lvl = self.points
        scratch = None
        borrowed: list = []
        perm = np.empty(n, dtype=np.int32)
        order_scratch = np.empty(n, dtype=np.int32)
        next_label = 1
        todo = deque([(0, 0)])  # (label, depth)
        while todo and next_label < self.max_partitions:
            t_level = time.perf_counter()
            level = deque()
            splits = []  # (label, right_label, s, mid, e, below)
            while todo and next_label < self.max_partitions:
                label, depth = todo.popleft()
                s, e = seg[label]
                if e - s < 2:
                    continue
                view = pts_lvl[s:e]
                if (
                    self._sample_size is not None
                    and e - s > self._sample_size
                ):
                    pos = self._rng.choice(
                        e - s, size=self._sample_size, replace=False
                    )
                    sub = view[pos]
                else:
                    sub = view
                axis, boundary = self._choose_split(sub, depth)
                below = view[:, axis] < boundary
                nb = int(below.sum())
                if nb == 0 or nb == e - s:
                    # Degenerate boundary: exact-median fallback, else
                    # give up on this node (legacy semantics).
                    _, boundary = median_search_split(view[:, axis])
                    below = view[:, axis] < boundary
                    nb = int(below.sum())
                    if nb == 0 or nb == e - s:
                        continue
                box = self.bounding_boxes[label]
                left_box, right_box = box.split(axis, boundary)
                right_label = next_label
                next_label += 1
                self.bounding_boxes[label] = left_box
                self.bounding_boxes[right_label] = right_box
                self.tree.append((label, axis, boundary, label, right_label))
                splits.append((label, right_label, s, s + nb, e, below))
                level.append((label, depth + 1))
                level.append((right_label, depth + 1))
            if splits:
                # The level's single stable re-bucket: unsplit segments
                # ride the identity, split segments compact left-then-
                # right (flatnonzero positions ascend, so both sides
                # keep ascending index order).
                np.copyto(perm, identity)
                for label, right_label, s, mid, e, below in splits:
                    perm[s:mid] = s + np.flatnonzero(below)
                    perm[mid:e] = s + np.flatnonzero(~below)
                    seg[label] = (s, mid)
                    seg[right_label] = (mid, e)
                np.take(order, perm, out=order_scratch)
                order, order_scratch = order_scratch, order
                if level and next_label < self.max_partitions:
                    # The coordinate re-bucket only serves the NEXT
                    # level's stats reads — the final level re-buckets
                    # just the (cheap, int32) order.
                    if scratch is None:
                        scratch = _borrow_level_buffer(
                            self.points.shape, self.points.dtype
                        )
                        borrowed.append(scratch)
                    np.take(pts_lvl, perm, axis=0, out=scratch)
                    if pts_lvl is self.points:  # level 0: read-only input
                        pts_lvl = scratch
                        scratch = None
                    else:
                        pts_lvl, scratch = scratch, pts_lvl
            todo.extend(level)
            self.level_times_s.append(time.perf_counter() - t_level)
        self.partitions = {
            label: order[s:e].copy() for label, (s, e) in seg.items()
        }
        _return_level_buffers(borrowed)

    # -- products ----------------------------------------------------------

    @property
    def n_partitions(self) -> int:
        return len(self.partitions)

    def box_stack(self) -> BoxStack:
        labels = sorted(self.bounding_boxes)
        return BoxStack.from_boxes(self.bounding_boxes[l] for l in labels)

    def partition_sizes(self) -> np.ndarray:
        labels = sorted(self.partitions)
        return np.array([len(self.partitions[l]) for l in labels])

    def route(self, points: np.ndarray) -> np.ndarray:
        """Assign new points to partitions by replaying the split tree.

        Validates dimensionality against the fitted ``k`` and rejects
        non-finite coordinates (see :func:`route_tree`).
        """
        from .utils.validate import check_query_points

        check_query_points(points, self.k)
        return route_tree(self.tree, points)
