"""Spatial KD partitioning.

TPU-native re-design of the reference partition layer
(``/root/reference/dbscan/partition.py:8-183``).  The reference builds its
binary split tree with ~2 cluster-wide Spark ``aggregate`` jobs per split
(partition.py:60,86 — the §3.1 hot spot).  Here the tree is built on the
host in one vectorized pass per split over in-memory (optionally
subsampled) numpy arrays: boundaries come from exact sorts or moment
statistics of the subset, and applying the finished tree to all N points
is a handful of broadcasted comparisons.  The tree itself is tiny metadata
(axis, boundary per node) that later feeds the device-mesh layout.

Split strategies (names and semantics from the reference):

* ``median_search`` — exact median along an axis (partition.py:8-30).
* ``mean_var`` — approximate median: 7 candidate boundaries at
  mean + {-0.9..0.9}·sigma in 0.3·sigma steps, pick argmin |#below-#above|
  (partition.py:33-69).
* ``min_var`` — pick the axis of maximum variance, then ``mean_var``
  boundary on that axis (partition.py:72-95).
* ``rotation`` — axis cycles with tree depth (partition.py:180-183).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional

import numpy as np

from .geometry import BoundingBox, BoxStack

_VALID_SPLIT_METHODS = ("min_var", "rotation", "mean_var", "median_search")
_VALID_BUILDERS = ("auto", "level", "legacy")


def median_search_split(values: np.ndarray):
    """Exact-median boundary along one axis.

    ``values``: (M,) coordinates of the subset on the split axis.
    Returns (below_mask, boundary); left = ``< boundary``, right =
    ``>= boundary`` (partition.py:27-30).
    """
    boundary = float(np.median(values))
    below = values < boundary
    return below, boundary


def mean_var_split(values: np.ndarray, mean: float = None, variance: float = None):
    """Approximate-median boundary from moment statistics.

    Evaluates the 7 candidate boundaries ``mean + j*0.3*sigma`` for
    ``j in -3..3`` and keeps the one with the smallest signed balance
    ``|#below - #above|`` (partition.py:58-65).  One pass over the subset,
    no sort.
    """
    if mean is None:
        mean = float(values.mean())
    if variance is None:
        variance = float(values.var())
    std = np.sqrt(variance)
    candidates = mean + np.arange(-0.9, 0.91, 0.3) * std
    # balance[c] = #below(c) - #above(c); reference computes it as a
    # running sum of 2*(v < bound) - 1.
    below_counts = (values[:, None] < candidates[None, :]).sum(axis=0)
    balance = np.abs(2 * below_counts - len(values))
    boundary = float(candidates[int(np.argmin(balance))])
    below = values < boundary
    return below, boundary


def min_var_split(points: np.ndarray):
    """Choose the max-variance axis, then a ``mean_var`` boundary on it.

    ``points``: (M, k) subset.  Returns (axis, below_mask, boundary).
    Matches partition.py:86-94: one moments pass gives per-axis mean and
    variance, the split axis is argmax variance.
    """
    mean = points.mean(axis=0)
    var = points.var(axis=0)
    axis = int(np.argmax(var))
    below, boundary = mean_var_split(
        points[:, axis], mean=float(mean[axis]), variance=float(var[axis])
    )
    return axis, below, boundary


def morton_plan(d: int):
    """(axes_used, bits_per_axis) for a <=128-bit Morton code.

    Round 2 capped codes at one uint64 (6 axes x 10 bits), which left 10
    of 16 dims unsorted on the scale-up config: tiles straddling cluster
    boundaries inherited data-scale bounding boxes in the unsorted dims
    and defeated tile pruning — measured as throughput decaying 320k ->
    127k pts/s from 1M to 10M points.  A 128-bit budget covers every
    axis up to d=32 (top-variance axes beyond that) with >= 4 bits each,
    and fine 16-bit resolution for low-d (GPS-like) data.
    """
    k = min(d, 32)
    if k == 0:  # (N, 0) points: one all-zero word, any order is spatial
        return 0, 0
    bits = max(4, min(16, 128 // k))
    return k, bits


def interleave_bit_words(q_axes, bits: int, word_bits: int, zeros, shift):
    """MSB-first bit interleave of per-axis quantized values into words.

    Shared by the host (uint64/numpy) and device (uint32/jnp) Morton
    implementations — their orderings must stay bit-identical, so the
    packing lives in exactly one place.  ``q_axes``: sequence of k
    unsigned arrays; ``zeros()``: a fresh all-zero word array;
    ``shift(v)``: the int ``v`` as the word dtype (numpy requires typed
    shift amounts).  Code bit e lands in word ``e // word_bits``; the
    leading word is left-padded when ``bits * k % word_bits != 0``
    (harmless for lexicographic comparison).  Returns the word list,
    most significant first — always at least one word.
    """
    k = len(q_axes)
    total = bits * k
    n_words = max(1, -(-total // word_bits))
    words = [zeros() for _ in range(n_words)]
    one = shift(1)
    emitted = n_words * word_bits - total
    for b in range(bits - 1, -1, -1):
        for a in range(k):
            w = emitted // word_bits
            bit = (q_axes[a] >> shift(b)) & one
            words[w] = (words[w] << one) | bit
            emitted += 1
    return words


def morton_codes(points: np.ndarray):
    """Morton (Z-order) code words for (N, k) points.

    Returns a list of uint64 word arrays, most-significant word first,
    jointly holding the <=128-bit interleaved code (see
    :func:`morton_plan`); quantization is per-axis over the data's range.
    Compare/sort lexicographically — :func:`spatial_order` does.
    """
    points = np.asarray(points)
    if points.dtype not in (np.float32, np.float64):
        points = points.astype(np.float64)
    if points.ndim != 2:
        raise ValueError(f"points must be (N, k), got {points.shape}")
    k, bits = morton_plan(points.shape[1])
    if points.shape[1] > k:
        axes = np.argsort(points.var(axis=0))[::-1][:k]
        points = points[:, np.sort(axes)]
    if k == 0:
        return [np.zeros(len(points), dtype=np.uint64)]
    lo = points.min(axis=0)
    # Floor must not underflow the input dtype (1e-300 is 0 in float32,
    # which made all-equal axes divide by zero).
    span = np.maximum(points.max(axis=0) - lo, np.finfo(points.dtype).tiny)
    q = np.minimum(
        ((points - lo) / span * (1 << bits)).astype(np.uint64), (1 << bits) - 1
    )
    return interleave_bit_words(
        [q[:, a] for a in range(k)],
        bits,
        64,
        lambda: np.zeros(len(points), dtype=np.uint64),
        np.uint64,
    )


def expanded_members(tree, points: np.ndarray, margin: float):
    """Membership of every point in every margin-expanded partition box,
    by replaying the split tree with widened comparisons.

    This replaces the broadcasted (N, P, k) box query (the round-1 memory
    wall) with an O(N·depth) descent: at each recorded split, a point
    follows the left branch when ``x < boundary + margin`` and the right
    branch when ``x >= boundary - margin`` — both when inside the band.
    Because a leaf's expanded box is exactly the conjunction of its path's
    margin-widened half-space constraints (the root box contains all data
    points by construction), the descent reproduces the reference's
    expanded-box duplication semantics (dbscan.py:141-151, README.md:20-22)
    while the peak extra memory is the duplicated index lists themselves —
    O(N · halo_factor), independent of P and k.

    Returns ``{label: (member_idx, owned_mask)}`` where ``member_idx`` is
    an int array of point indices inside the label's expanded box and
    ``owned_mask`` marks the ones strictly owned by the partition (the
    same ``<`` semantics as :class:`KDPartitioner`), so the halo set is
    ``member_idx[~owned_mask]``.
    """
    points = np.asarray(points)
    n = len(points)
    state = {0: (np.arange(n, dtype=np.int32), np.ones(n, dtype=bool))}
    for parent, axis, boundary, _left, right in tree:
        arr, own = state.pop(int(parent))
        c = points[arr, int(axis)].astype(np.float64, copy=False)
        # Inclusive on the widened upper bound, matching BoxStack
        # membership and the reference's expanded_box.contains (<=).
        lsel = c <= boundary + margin
        rsel = c >= boundary - margin
        state[int(parent)] = (arr[lsel], own[lsel] & (c[lsel] < boundary))
        state[int(right)] = (arr[rsel], own[rsel] & (c[rsel] >= boundary))
    return state


def route_tree(tree, points: np.ndarray) -> np.ndarray:
    """Assign points to partitions by replaying a split tree.

    ``tree``: iterable of (parent_label, axis, boundary, left_label,
    right_label) in construction order — the format produced by
    :class:`KDPartitioner` and round-tripped by
    :mod:`pypardis_tpu.checkpoint`.  Left children keep the parent
    label; points with coordinate >= boundary go right (strict ``<``
    stays left, matching the reference's split semantics,
    partition.py:27-30).

    Inputs are validated against the tree: an array too narrow for the
    recorded split axes, or one carrying NaN/inf coordinates (a NaN
    fails every ``>=`` and silently slides down the left spine), raises
    ValueError instead of routing garbage.
    """
    from .utils.validate import check_query_points

    tree = list(tree)
    points = check_query_points(points).astype(np.float64, copy=False)
    if tree:
        need = max(int(a) for _p, a, _b, _l, _r in tree) + 1
        if points.shape[1] < need:
            raise ValueError(
                f"points have {points.shape[1]} dims but the split tree "
                f"routes on axis {need - 1}"
            )
    labels = np.zeros(len(points), dtype=np.int32)
    for parent, axis, boundary, _left, right in tree:
        mask = labels == int(parent)
        go_right = mask & (points[:, int(axis)] >= boundary)
        labels[go_right] = int(right)
    return labels


def spatial_order(points: np.ndarray) -> np.ndarray:
    """An index permutation grouping spatially nearby points.

    Sorts points along a Morton (Z-order) curve so that contiguous tile
    blocks of the permuted layout have tight bounding boxes — which is
    what makes tile-level pruning in :mod:`pypardis_tpu.ops` effective:
    the O(N^2) pairwise interaction collapses to O(N x local density).
    (Measured against ordering by balanced KD leaves, the direct Morton
    sort is both ~3x cheaper on host and gives faster kernels.)
    """
    points = np.asarray(points)
    if len(points) <= 1:
        return np.arange(len(points))
    words = morton_codes(points)
    if len(words) == 1:
        return np.argsort(words[0], kind="stable")
    return np.lexsort(words[::-1])  # np.lexsort: last key is primary


def _morton_range_weights(sub: np.ndarray, order: np.ndarray,
                          block: int, eps: float,
                          max_cols: int = 4096) -> np.ndarray:
    """Per-tile work estimate for the balanced range split: the number
    of live (box-gap <= eps) column tiles each row tile of the sorted
    layout sees — exactly the tiled kernels' cost model (work = live
    tile pairs x block^2), computed on (nt, k) host boxes in
    milliseconds.  Past ``max_cols`` tiles the column side is sampled
    on an even stride (Morton-adjacent tiles are spatially redundant,
    so a stride is representative) and the count scaled back up — the
    estimate only has to RANK density, the split quantizes it anyway.
    """
    n, k = sub.shape
    nt = -(-n // block)
    lo = np.empty((nt, k), np.float32)
    hi = np.empty((nt, k), np.float32)
    step = max(1, (1 << 22) // max(block, 1))
    for t0 in range(0, nt, step):
        t1 = min(t0 + step, nt)
        rows = sub[order[t0 * block:t1 * block]]
        pad = (t1 - t0) * block - len(rows)
        if pad:
            rows = np.concatenate([rows, np.full((pad, k), rows[-1])])
        tiles = rows.reshape(t1 - t0, block, k)
        lo[t0:t1] = tiles.min(axis=1)
        hi[t0:t1] = tiles.max(axis=1)
    stride = max(1, -(-nt // max_cols))
    clo, chi = lo[::stride], hi[::stride]
    eps2 = np.float32(eps) ** 2
    w = np.zeros(nt)
    chunk = max(1, (1 << 26) // max(len(clo) * k, 1))
    for s in range(0, nt, chunk):
        e = min(s + chunk, nt)
        gap = np.maximum(
            0.0,
            np.maximum(clo[None] - hi[s:e, None],
                       lo[s:e, None] - chi[None]),
        )
        w[s:e] = (np.sum(gap * gap, axis=-1) <= eps2).sum(axis=1)
    return w * stride


def _balanced_starts(w: np.ndarray, n: int, block: int,
                     n_ranges: int, slack: float = 1.5) -> np.ndarray:
    """Range boundaries equalizing cumulative tile WORK, not rows.

    Greedy prefix cuts at the per-tile weight's quantiles, clamped so
    no range exceeds ``slack`` times the equal-rows share of tiles —
    the row cap bounds every shard's slab capacity (the fused program
    pads all shards to the LARGEST range), so a dense region can shed
    work without a sparse shard's padding eating the win.  Cuts land
    on tile boundaries: weights are per-tile, and sub-tile cuts would
    buy nothing the kernels could see.
    """
    nt = len(w)
    cw = np.concatenate([[0.0], np.cumsum(w)])
    max_t = max(1, int(np.ceil(slack * nt / n_ranges)))
    starts_t = np.zeros(n_ranges + 1, dtype=np.int64)
    starts_t[n_ranges] = nt
    prev = 0
    for j in range(1, n_ranges):
        tgt = cw[-1] * j / n_ranges
        t = int(np.searchsorted(cw, tgt))
        if t > 0 and cw[t] - tgt > tgt - cw[t - 1]:
            t -= 1
        t = max(t, prev, nt - (n_ranges - j) * max_t)
        t = min(t, nt, prev + max_t)
        starts_t[j] = prev = t
    return np.minimum(starts_t * block, n)


def morton_range_split(points: np.ndarray, n_ranges: int,
                       chunk: int = 1 << 20, eps: float = None,
                       block: int = None):
    """Global Morton keying + contiguous range splitting.

    The zero-duplication analogue of :class:`KDPartitioner` for the
    ``mode="global_morton"`` distributed engine
    (:mod:`pypardis_tpu.parallel.global_morton`): instead of KD boxes
    whose 2*eps expansions overlap (and duplicate boundary points), the
    WHOLE dataset is keyed by one global Morton order and each shard
    owns a disjoint, contiguous row range of it — every point
    clustered exactly once by construction.

    With ``eps`` and ``block`` given, ranges equalize estimated WORK
    rather than rows: per-tile live-column counts
    (:func:`_morton_range_weights` — the tiled kernels' own cost
    model) are prefix-split at their quantiles, cuts quantized to tile
    boundaries and row counts capped at 1.5x the equal share (the
    fused program pads every shard to the largest range).  Equal-row
    ranges leave dense regions with up to ~1.2x the live pairs of
    sparse ones, and the slowest device binds the whole fused program.
    Without ``eps``/``block`` the split is plain equal rows.  EVERY
    contiguous split yields identical labels — balance is purely a
    performance property — so callers may cache one split across eps
    values.

    The order is computed in the recentred float32 frame (float64 mean
    subtracted, cast to f32 — the exact frame the shard slabs are built
    in, :func:`pypardis_tpu.parallel.sharded._recentre_rows`), so slab
    rows and sort keys can never disagree about borderline ordering.

    Requires an in-RAM row-indexable array: the keying materializes one
    f32 copy of the dataset (the KD ring/streaming path remains the
    memmap route).  Returns ``(order, starts, center)``: ``order`` the
    (N,) int32 global Morton permutation, ``starts`` the
    (n_ranges + 1,) int64 range boundaries (equal ``ceil(N /
    n_ranges)``-row ranges, or work-balanced cuts when ``eps`` and
    ``block`` are given), ``center`` the float64 dataset mean.
    """
    points = np.asarray(points)
    n, k = points.shape
    n_ranges = max(1, int(n_ranges))
    center = points.mean(axis=0, dtype=np.float64)
    sub = np.empty((n, k), np.float32)
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        np.subtract(points[s:e], center, out=sub[s:e], casting="unsafe")
    order = np.asarray(spatial_order(sub), dtype=np.int32)
    if eps is not None and block is not None and n_ranges > 1 and n:
        w = _morton_range_weights(sub, order, int(block), float(eps))
        starts = _balanced_starts(w, n, int(block), n_ranges)
    else:
        per = -(-n // n_ranges)
        starts = np.minimum(
            np.arange(n_ranges + 1, dtype=np.int64) * per, n
        )
    del sub
    return order, starts, center


class MortonRangePartitioner:
    """Parity-product shim for the global-Morton distributed mode.

    Presents the :class:`KDPartitioner` product surface (``partitions``
    / ``result`` / ``bounding_boxes`` / ``n_partitions``) over Morton
    ranges, so ``DBSCAN``'s inspection attributes and
    ``cluster_mapping()`` work identically across modes.  There is no
    split tree (``tree == []``) and no ``route()``: Morton ranges are a
    property of the fitted dataset's order, not a spatial predicate new
    points can replay.
    """

    def __init__(self, order: np.ndarray, starts: np.ndarray,
                 bounding_boxes: Dict[int, BoundingBox]):
        order = np.asarray(order, dtype=np.int32)
        starts = np.asarray(starts, dtype=np.int64)
        self.tree: list = []
        self.builder = "morton_range"
        self.level_times_s: list = []
        self.split_method = "morton_range"
        self.bounding_boxes = dict(bounding_boxes)
        self.partitions = {
            s: order[starts[s]:starts[s + 1]].copy()
            for s in range(len(starts) - 1)
        }
        self.result = np.empty(len(order), dtype=np.int32)
        for s, idx in self.partitions.items():
            self.result[idx] = s

    @property
    def n_partitions(self) -> int:
        return len(self.partitions)

    def partition_sizes(self) -> np.ndarray:
        labels = sorted(self.partitions)
        return np.array([len(self.partitions[l]) for l in labels])


# Level-builder buffer pool: the two dataset-sized ping-pong buffers,
# reused across builds of the same geometry (warm refits rebuild the
# partitioner every fit — bench's host reps, eps sweeps).  Reuse also
# sidesteps the first-touch cost: page-faulting fresh pages INSIDE the
# re-bucket gather measured ~8x slower than the gather itself, so fresh
# allocations are pre-faulted with a sequential fill.  Only the most
# recent shape is kept (two buffers ~= one extra dataset pair).
_LEVEL_POOL: Dict = {}


def _borrow_level_buffer(shape, dtype) -> np.ndarray:
    key = (tuple(shape), np.dtype(dtype).str)
    stack = _LEVEL_POOL.get(key)
    if stack:
        return stack.pop()
    buf = np.empty(shape, dtype)
    buf.fill(0)  # pre-fault; see _LEVEL_POOL
    return buf


def _return_level_buffers(bufs) -> None:
    if not bufs:
        return
    key = (bufs[0].shape, bufs[0].dtype.str)
    if set(_LEVEL_POOL) - {key}:
        _LEVEL_POOL.clear()
    stack = _LEVEL_POOL.setdefault(key, [])
    stack.extend(bufs)
    del stack[2:]


def clear_level_pool() -> None:
    """Drop the pooled level-builder buffers (tests, memory pressure)."""
    _LEVEL_POOL.clear()


class KDPartitioner:
    """Binary-tree spatial partitioner over an in-memory point set.

    Constructor surface mirrors the reference
    (``partition.py:98-142``): ``KDPartitioner(data, max_partitions, k,
    split_method)``; unknown split methods silently fall back to
    ``'min_var'`` (partition.py:129-130).  ``data`` is an (N, k) array
    (or anything ``np.asarray`` accepts).

    Products:

    * ``partitions``: {label → int array of point indices}
      (reference: {label → RDD}).
    * ``bounding_boxes``: {label → BoundingBox}.
    * ``result``: (N,) int array, point → partition label
      (reference: union RDD of ((key, label), vector)).
    * ``tree``: list of (parent_label, axis, boundary, left_label,
      right_label) — the whole split tree as metadata, serializable and
      reusable to route new points.

    For very large N pass ``sample_size``: split boundaries are then
    estimated from a uniform subsample (statistically identical for the
    moment-based strategies) and the finished tree is applied to all
    points vectorized.

    ``builder`` selects the tree construction engine.  ``"level"`` (the
    ``"auto"`` default for in-RAM arrays) is the level-synchronous fast
    path: points live in a level-ordered buffer where every tree node
    is a CONTIGUOUS segment, so split statistics read zero-copy views
    instead of an O(node) fancy gather per node, and each level
    re-buckets with one stable in-place permutation — the per-level
    cost is O(N), so the build scales with tree DEPTH instead of node
    count (the legacy builder's per-node gathers made mp=8 -> mp=16
    cost ~5x on 10M points; here it is the extra level, ~1.2x).  The
    products (``tree``, ``result``, ``partitions``, ``bounding_boxes``)
    are byte-identical to ``"legacy"`` under the same seed: segments
    preserve ascending index order and the RNG subsample draws consume
    the identical stream (regression-pinned).  ``"legacy"`` keeps the
    original node-at-a-time builder; ``"auto"`` selects it for
    ``np.memmap`` inputs, where the level buffer's +1x dataset copy
    would defeat the larger-than-RAM streaming premise.

    ``level_times_s`` records per-level build seconds for either
    builder — surfaced as ``partition_levels_s`` in
    ``DBSCAN.report()``.
    """

    def __init__(
        self,
        data,
        max_partitions: Optional[int] = None,
        k: Optional[int] = None,
        split_method: str = "min_var",
        sample_size: Optional[int] = 1_000_000,
        seed: int = 0,
        builder: str = "auto",
    ):
        # Keep the caller's dtype: forcing float64 here doubled host
        # memory for float32 datasets (round-1 finding).  Split math
        # runs in float64 on (sub)samples regardless.
        points = np.asarray(data)
        if points.dtype not in (np.float32, np.float64):
            points = points.astype(np.float64)
        if points.ndim != 2:
            raise ValueError(f"data must be (N, k), got shape {points.shape}")
        # C-layout is load-bearing for builder equivalence: fancy row
        # gathers of an F-order array come back F-order, whose
        # contiguous-axis reductions differ in the last ulp from the
        # C-layout views the level builder reads.  (No-op for the
        # common case, including C-order memmaps.)
        if not points.flags.c_contiguous:
            points = np.ascontiguousarray(points)
        self.points = points
        self.k = int(k) if k is not None else points.shape[1]
        self.split_method = (
            split_method if split_method in _VALID_SPLIT_METHODS else "min_var"
        )
        # Reference default is 4**k (partition.py:132-133) — untenable
        # beyond a few dimensions; cap at 256 and at N.
        if max_partitions is None:
            max_partitions = min(4 ** self.k, 256)
        self.max_partitions = max(1, min(int(max_partitions), len(points)))
        self._sample_size = sample_size
        self._rng = np.random.default_rng(seed)
        if builder not in _VALID_BUILDERS:
            raise ValueError(
                f"builder must be one of {_VALID_BUILDERS}, got {builder!r}"
            )
        if builder == "auto":
            builder = "legacy" if isinstance(data, np.memmap) else "level"
        self.builder = builder
        self.level_times_s: list = []

        # Global box as a union-reduction of chunk boxes — the same
        # shape as the reference's BoundingBox.union aggregate
        # (partition.py:135-137), just over host chunks instead of RDD
        # partitions; vectorized per chunk, never an (N, P, k) temp.
        chunk = 1 << 20
        global_box = BoundingBox(k=self.k)  # empty: union identity
        for s in range(0, len(points), chunk):
            e = min(s + chunk, len(points))
            global_box = global_box.union(
                BoundingBox(
                    lower=points[s:e].min(axis=0),
                    upper=points[s:e].max(axis=0),
                )
            )
        self.bounding_boxes: Dict[int, BoundingBox] = {}
        self.partitions: Dict[int, np.ndarray] = {}
        self.tree = []
        if self.builder == "level":
            self._create_partitions_level(global_box)
        else:
            self._create_partitions(global_box)

        self.result = np.empty(len(points), dtype=np.int32)
        for label, idx in self.partitions.items():
            self.result[idx] = label

    # -- tree construction -------------------------------------------------

    def _split_subset(self, subset_idx: np.ndarray, depth: int):
        """Pick (axis, boundary) for one node, from a subsample if large."""
        idx = subset_idx
        if self._sample_size is not None and len(idx) > self._sample_size:
            idx = self._rng.choice(idx, size=self._sample_size, replace=False)
        return self._choose_split(self.points[idx], depth)

    def _choose_split(self, pts: np.ndarray, depth: int):
        """(axis, boundary) from an already-gathered (M, k) subset.

        Shared by both builders: the legacy path hands it a fancy-index
        gather, the level path a contiguous view of the level-ordered
        buffer.  Both are (M, k) C-layout arrays holding the same rows
        in the same (ascending-index) order, so every reduction here is
        bit-identical between them.
        """
        if self.split_method == "rotation":
            axis = depth % self.k
            _, boundary = mean_var_split(pts[:, axis])
        elif self.split_method == "mean_var":
            axis = int(np.argmax(pts.var(axis=0)))
            _, boundary = mean_var_split(pts[:, axis])
        elif self.split_method == "median_search":
            axis = int(np.argmax(pts.var(axis=0)))
            _, boundary = median_search_split(pts[:, axis])
        else:  # min_var (reference default)
            axis, _, boundary = min_var_split(pts)
        return axis, boundary

    def _create_partitions(self, root_box: BoundingBox) -> None:
        """Breadth-first split loop (partition.py:152-183).

        Two-queue structure so each tree level completes before the next;
        left child keeps the parent label, right child takes the next
        fresh label (partition.py:173-176).
        """
        # int32 indices: the partition lists total one row per point and
        # ride through the whole shard build — int64 doubled the build's
        # host high-water for nothing below 2^31 points.
        all_idx = np.arange(len(self.points), dtype=np.int32)
        self.partitions = {0: all_idx}
        self.bounding_boxes = {0: root_box}
        next_label = 1
        todo = deque([(0, 0)])  # (label, depth)
        while todo and next_label < self.max_partitions:
            t_level = time.perf_counter()
            level = deque()
            while todo and next_label < self.max_partitions:
                label, depth = todo.popleft()
                idx = self.partitions[label]
                if len(idx) < 2:
                    continue
                axis, boundary = self._split_subset(idx, depth)
                below = self.points[idx, axis] < boundary
                left_idx, right_idx = idx[below], idx[~below]
                if len(left_idx) == 0 or len(right_idx) == 0:
                    # Degenerate boundary (e.g. all-equal coords): fall
                    # back to an exact median split, else give up.
                    _, boundary = median_search_split(self.points[idx, axis])
                    below = self.points[idx, axis] < boundary
                    left_idx, right_idx = idx[below], idx[~below]
                    if len(left_idx) == 0 or len(right_idx) == 0:
                        continue
                box = self.bounding_boxes[label]
                left_box, right_box = box.split(axis, boundary)
                right_label = next_label
                next_label += 1
                self.partitions[label] = left_idx
                self.partitions[right_label] = right_idx
                self.bounding_boxes[label] = left_box
                self.bounding_boxes[right_label] = right_box
                self.tree.append((label, axis, boundary, label, right_label))
                level.append((label, depth + 1))
                level.append((right_label, depth + 1))
            todo.extend(level)
            self.level_times_s.append(time.perf_counter() - t_level)

    def _create_partitions_level(self, root_box: BoundingBox) -> None:
        """Level-synchronous builder: one vectorized pass per tree level.

        Points live in a LEVEL-ORDERED buffer ``pts_lvl`` (one copy of
        the dataset, caller's dtype) alongside the matching index
        permutation ``order``; every tree node is a contiguous segment
        ``[s, e)`` of both.  Per level:

        * split statistics read the segment VIEW (zero-copy — the
          legacy builder fancy-gathers every node's rows, which is the
          O(N)-gathers-per-level term behind the mp=16 build blowup);
          subsampled nodes draw POSITIONS from the same RNG stream the
          legacy builder consumes (``Generator.choice`` draws depend
          only on the population size) and gather within the contiguous
          segment;
        * the split test is one projection of the segment's boundary
          column — a strided view compare, never ``points[idx, axis]``;
        * all of the level's splits then apply as ONE stable
          permutation (``np.take`` through a reused scratch buffer —
          fresh per-node compress temps measured 2-3x slower from page
          faulting alone): left rows compact to the segment head, right
          rows to the tail, so children stay contiguous AND keep
          ascending index order — which is exactly the legacy
          ``idx[below]`` ordering, making every downstream product
          byte-identical.

        Node visit order, label assignment, the budget stop, the
        degenerate-boundary fallback, and the RNG stream all replicate
        the legacy loop exactly (regression-pinned across all four
        split methods).  Peak extra host memory is two dataset-sized
        buffers (the level-ordered points and the permutation scratch)
        — the price of depth-scaling; ``builder="legacy"`` (automatic
        for memmaps) keeps the O(index)-memory node-at-a-time build.
        """
        n = len(self.points)
        self.bounding_boxes = {0: root_box}
        # label -> (start, end) in the level-ordered buffer; finalized
        # into index arrays once the tree is done.
        seg: Dict[int, tuple] = {0: (0, n)}
        identity = np.arange(n, dtype=np.int32)
        order = identity.copy()
        # Level 0 reads self.points directly (segment order == input
        # order); the first re-bucket takes INTO pts_lvl, so the level
        # buffer is only ever allocated written — no up-front copy.
        # C-contiguity is load-bearing for byte-identity: the legacy
        # builder's fancy gathers are always C-layout copies, and
        # numpy's reductions can differ in the last ulp across layouts.
        pts_lvl = self.points
        scratch = None
        borrowed: list = []
        perm = np.empty(n, dtype=np.int32)
        order_scratch = np.empty(n, dtype=np.int32)
        next_label = 1
        todo = deque([(0, 0)])  # (label, depth)
        while todo and next_label < self.max_partitions:
            t_level = time.perf_counter()
            level = deque()
            splits = []  # (label, right_label, s, mid, e, below)
            while todo and next_label < self.max_partitions:
                label, depth = todo.popleft()
                s, e = seg[label]
                if e - s < 2:
                    continue
                view = pts_lvl[s:e]
                if (
                    self._sample_size is not None
                    and e - s > self._sample_size
                ):
                    pos = self._rng.choice(
                        e - s, size=self._sample_size, replace=False
                    )
                    sub = view[pos]
                else:
                    sub = view
                axis, boundary = self._choose_split(sub, depth)
                below = view[:, axis] < boundary
                nb = int(below.sum())
                if nb == 0 or nb == e - s:
                    # Degenerate boundary: exact-median fallback, else
                    # give up on this node (legacy semantics).
                    _, boundary = median_search_split(view[:, axis])
                    below = view[:, axis] < boundary
                    nb = int(below.sum())
                    if nb == 0 or nb == e - s:
                        continue
                box = self.bounding_boxes[label]
                left_box, right_box = box.split(axis, boundary)
                right_label = next_label
                next_label += 1
                self.bounding_boxes[label] = left_box
                self.bounding_boxes[right_label] = right_box
                self.tree.append((label, axis, boundary, label, right_label))
                splits.append((label, right_label, s, s + nb, e, below))
                level.append((label, depth + 1))
                level.append((right_label, depth + 1))
            if splits:
                # The level's single stable re-bucket: unsplit segments
                # ride the identity, split segments compact left-then-
                # right (flatnonzero positions ascend, so both sides
                # keep ascending index order).
                np.copyto(perm, identity)
                for label, right_label, s, mid, e, below in splits:
                    perm[s:mid] = s + np.flatnonzero(below)
                    perm[mid:e] = s + np.flatnonzero(~below)
                    seg[label] = (s, mid)
                    seg[right_label] = (mid, e)
                np.take(order, perm, out=order_scratch)
                order, order_scratch = order_scratch, order
                if level and next_label < self.max_partitions:
                    # The coordinate re-bucket only serves the NEXT
                    # level's stats reads — the final level re-buckets
                    # just the (cheap, int32) order.
                    if scratch is None:
                        scratch = _borrow_level_buffer(
                            self.points.shape, self.points.dtype
                        )
                        borrowed.append(scratch)
                    np.take(pts_lvl, perm, axis=0, out=scratch)
                    if pts_lvl is self.points:  # level 0: read-only input
                        pts_lvl = scratch
                        scratch = None
                    else:
                        pts_lvl, scratch = scratch, pts_lvl
            todo.extend(level)
            self.level_times_s.append(time.perf_counter() - t_level)
        self.partitions = {
            label: order[s:e].copy() for label, (s, e) in seg.items()
        }
        _return_level_buffers(borrowed)

    # -- products ----------------------------------------------------------

    @property
    def n_partitions(self) -> int:
        return len(self.partitions)

    def box_stack(self) -> BoxStack:
        labels = sorted(self.bounding_boxes)
        return BoxStack.from_boxes(self.bounding_boxes[l] for l in labels)

    def partition_sizes(self) -> np.ndarray:
        labels = sorted(self.partitions)
        return np.array([len(self.partitions[l]) for l in labels])

    def route(self, points: np.ndarray) -> np.ndarray:
        """Assign new points to partitions by replaying the split tree.

        Validates dimensionality against the fitted ``k`` and rejects
        non-finite coordinates (see :func:`route_tree`).
        """
        from .utils.validate import check_query_points

        check_query_points(points, self.k)
        return route_tree(self.tree, points)
