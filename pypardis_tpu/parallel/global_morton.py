"""Zero-duplication global-Morton distributed mode.

The KD family (:mod:`pypardis_tpu.parallel.sharded`) inherits the
reference's distribution strategy: expand every partition box by 2*eps
and duplicate boundary points into overlapping neighborhoods (PAPER.md
design steps 2-4).  Even owner-computes only softens that tax — the
halo slabs still ship, and KD imbalance keeps
``duplicated_work_factor`` well above 1 (the r5 measurement: 1.54x
clustered volume, ``halo_factor`` 2.158 at 16-D/eps=2.4 — more than
half of every shipped slab is replicated halo rows).  The fused
single-device engine proves duplication is not fundamental: global
Morton tiling clusters the same data with zero replicated rows.  This
module is that program, distributed:

* **Shards are contiguous ranges of the GLOBAL Morton order**
  (:func:`pypardis_tpu.partition.morton_range_split`): each device owns
  a disjoint row range — zero duplicated rows BY CONSTRUCTION
  (``duplicated_work_factor == 1.0``).  Cuts equalize estimated WORK
  (per-tile live-column counts, the kernels' own cost model) rather
  than rows — equal-row ranges leave the densest shard ~1.2x the live
  pairs of the mean and the slowest device binds the fused program —
  and each shard's slab gets the fused engine's segment-break padding
  (:func:`global_morton._gm_segment_layout`) so tiles never straddle
  Z-order jumps.

* **Only boundary TILES ride the ring** (:func:`halo
  .boundary_send_select` / :func:`halo.ring_tile_round`): per-tile
  bounding boxes are all-gathered (metadata, never coordinates), each
  device compacts the tiles whose box lies within eps of some OTHER
  shard's tiles into a small send buffer, and those buffers — not
  whole halo slabs — circulate the ``ppermute`` ring.  A receiving
  device accepts a passing tile iff its box reaches one of its own
  tiles; the box-gap bound makes this exact (any cross-shard eps-pair
  lives in a tile pair whose boxes are within eps, so each side's tile
  is accepted by the other's shard).

* **Counting is owner-computes, clustering local, merging a
  cross-device pmin fixpoint**: owned rows neighbor-count against
  owned + boundary columns (exact — the accepted tiles cover every
  candidate column), boundary slots take their OWNER's core verdict
  via one pmax, relay-only propagation (:func:`ops.labels
  .oc_propagate`) emits the same compact ``(owned_root, gid)``
  occurrence tables the KD merge consumes, and the cross-device
  ``pmin`` label rounds (:func:`sharded._merge_round`) run
  HOST-STEPPED to a fixpoint — one program per round, a per-round
  convergence probe, and a trace span per round
  (``gm.fixpoint_round``), replacing the per-partition label +
  ClusterAggregator merge two-step.  ``merge='host'`` keeps the
  collective-free union-find spill (:func:`sharded._oc_host_tables` +
  :func:`sharded._host_merge_finish`) for point counts where
  replicated (N+1,) arrays stop fitting.

Labels are byte-identical to the fused engine and the KD modes (after
the shared root canonicalization) — every core eps-edge is an
owned-owned or owned-boundary edge on at least one device, boundary
core flags are the owners' exact verdicts, and the merge consumes the
exact wire format the KD occurrence tables use.

Disk-backed ``np.memmap`` inputs STREAM (ISSUE 10): the global Morton
order comes from an external sample-sort over memmap chunks
(:func:`pypardis_tpu.partition.morton_range_split_streaming`,
byte-identical order/starts/center to the in-RAM keying) and shard
slabs assemble on their devices one at a time
(:func:`build_morton_shards_streaming`) — peak host anonymous memory
is O(chunk + sample + one spill bucket), never the in-RAM path's f32
copy + full permutation.  A 1-device mesh can additionally CHAIN the
stream (``chain=R`` / ``PYPARDIS_GM_CHAIN``): R Morton ranges visit
the single chip in turn with exact tile-granular boundary context
(:func:`_gm_chained_dbscan`) — the 100M-on-one-chip route.  Remaining
caveat: per-round fixpoint/ring syncs trade ~one scalar fetch per
round for the convergence probe and the trace separation of exchange
vs compute time (cheap on CPU meshes; hardware sessions should
re-measure).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs import (
    current as obs_current,
    event as obs_event,
    heartbeat as obs_heartbeat,
    span as obs_span,
)
from ..ops.labels import (
    gm_backend,
    oc_counts_banded,
    oc_counts_delta,
    oc_extract,
    oc_propagate_banded,
    oc_raw_counts,
    pair_dispatch,
    resolve_backend,
)
from ..ops.precision import PAIR_STATS_WIDTH
from ..partition import morton_range_split
from ..utils import clamp_block, envreg, faults, round_up, validate_params
from ..utils.budget import run_ladders
from ..utils.retry import (
    Retrier,
    is_degradable_error,
    note_degraded,
    note_giveup,
    note_retry,
)
from . import dist, staging
from .halo import boundary_send_select, ring_tile_round
from .mesh import shard_map
from .sharded import (
    MERGE_HOST_AUTO,
    _canonicalize_roots,
    _exec_stats,
    _host_merge_finish,
    _merge_round,
    _note_first_compile,
    _oc_host_tables,
    _recentre_rows,
    _replicated_core,
    _staged_alloc,
    _with_kernel_fallback,
)

_INT32_MAX = np.iinfo(np.int32).max


def _gm_cache_key(points, n_shards, block, sharding):
    """Content key for the staged global-Morton slabs: keyed by the
    data, the mesh, and the block — NOT by eps, so an eps sweep reuses
    the owned slabs entirely (the boundary tiles are the only
    eps-dependent product, cached separately).  The LAYOUT inside the
    slabs (work-balanced range cuts, segment-break padding) is tuned
    with the first fit's eps; any contiguous split and any break
    placement yield identical labels — eps only steers how well tiles
    prune — so later eps values reuse the first layout rather than
    re-staging the dataset."""
    return (
        "gm",
        staging.points_fingerprint(points),
        int(n_shards),
        int(block),
        tuple(int(d.id) for d in sharding.mesh.devices.flat),
    )


def _gm_segment_layout(rows, block, eps):
    """Host analogue of the fused engine's segment-break layout
    (:func:`pypardis_tpu.ops.pipeline._segment_break_layout`).

    A contiguous Morton range still has Z-order leaks: the tile
    straddling two far-apart cluster runs inherits a bounding box
    covering both, and one loose box defeats the gap test against many
    tiles — measured here as MORE live tile pairs than the KD-halo
    mode despite zero duplicated rows.  Where consecutive sorted rows
    jump farther than 4*eps, start a fresh block-aligned segment
    (budget one break per tile, largest jumps win, so capacity at most
    doubles).  Breaks never affect correctness — only box tightness —
    so the layout may be computed at one eps and reused at another.

    Returns ``(target, padded_len)``: the slab slot of each row and
    the block-multiple capacity this shard needs.  Small or very
    high-D shards (same gates as the fused engine) keep the identity
    layout.
    """
    m, k = rows.shape
    if _segbreak_skip(m, k, block, eps):
        return np.arange(m, dtype=np.int64), round_up(m, block)
    d2 = np.sum((rows[1:] - rows[:-1]) ** 2, axis=1)
    return _segment_layout_from_d2(d2, m, block, eps)


def _segbreak_skip(m, k, block, eps) -> bool:
    """Gates under which a shard keeps the identity layout (same as
    the fused engine's)."""
    return bool(
        m == 0 or eps is None or m < 4 * block or k > 64
        or envreg.raw("PYPARDIS_GM_SEGBREAK", "1") == "0"
    )


def _segment_plan_from_d2(d2, m, block, eps):
    """Break plan from precomputed consecutive-row jump distances —
    split from :func:`_gm_segment_layout` so the streaming build can
    accumulate ``d2`` chunkwise (elementwise, so byte-identical)
    without ever holding a whole range's (m, k) diff temp in host RAM.
    Returns ``(brk_pos, tgt0, src0, plen)``: tiny metadata from which
    any row span's slab targets rebuild (:func:`_plan_targets`)."""
    thr = np.float32(16.0) * np.float32(eps) ** 2
    bt = max(1, m // block)
    brk = d2 > thr
    if int(brk.sum()) > bt:
        kth = np.partition(d2, -bt)[-bt]
        brk = d2 > max(thr, kth)
    seg = np.concatenate([[0], np.cumsum(brk)]).astype(np.int64)
    seg_len = np.bincount(seg)
    padded = -(-seg_len // block) * block
    tgt0 = np.cumsum(padded) - padded
    src0 = np.cumsum(seg_len) - seg_len
    return np.flatnonzero(brk), tgt0, src0, int(padded.sum())


def _plan_targets(plan, off, ln):
    """Slab slot targets for rows [off, off+ln) of a range, from its
    compressed break plan (identity when ``plan`` is None).  Breaks at
    d2 position p separate rows p and p+1, so a row's segment id is
    the count of break positions < its index — ``searchsorted`` on the
    sorted break list, exactly ``cumsum(brk)`` restricted to the
    span."""
    idx = np.arange(off, off + ln, dtype=np.int64)
    if plan is None:
        return idx
    brk_pos, tgt0, src0 = plan
    seg = np.searchsorted(brk_pos, idx, side="left")
    return tgt0[seg] + idx - src0[seg]


def _segment_layout_from_d2(d2, m, block, eps):
    """(target, padded_len) from precomputed jump distances."""
    if m == 0:
        return np.empty(0, np.int64), 0
    brk_pos, tgt0, src0, plen = _segment_plan_from_d2(d2, m, block, eps)
    return _plan_targets((brk_pos, tgt0, src0), 0, m), plen


def build_morton_shards(points, n_shards, block, sharding, eps=None):
    """(owned, mask, gid) device slabs over global Morton ranges.

    Rows within each shard keep the global Morton order (contiguous
    slices of one global sort) with the fused engine's segment-break
    padding applied per shard (:func:`_gm_segment_layout`), so kernel
    tiles are spatially tight — the two properties the fused engine's
    device sort + break layout buy.  Ranges are work-balanced when
    ``eps`` is given (:func:`pypardis_tpu.partition
    .morton_range_split`).  Staged through the staging economy (route
    ``gm_owned``, eps-free key — see :func:`_gm_cache_key` for the
    first-eps layout contract); returns ``(arrays, stats, host_bufs,
    base_key)`` with ``stats`` carrying the ``parity`` extras
    (order/starts/per-shard boxes) the ``DBSCAN`` surface consumes.
    """
    points = np.asarray(points)
    n, k = points.shape
    base = _gm_cache_key(points, n_shards, block, sharding)
    cached = staging.device_get("gm_owned", base)
    if cached is not None:
        arrays, aux = cached
        return arrays, aux, [], base
    order, starts, center = morton_range_split(
        points, n_shards, eps=eps, block=block
    )
    shard_rows = []
    for s in range(n_shards):
        a, b = int(starts[s]), int(starts[s + 1])
        idx = order[a:b]
        rows = _recentre_rows(points, idx, center)
        target, plen = _gm_segment_layout(rows, block, eps)
        shard_rows.append((idx, rows, target, plen))
    cap = round_up(max([p for *_, p in shard_rows] + [1]), block)
    bufs: list = []
    alloc = _staged_alloc(bufs)
    owned = alloc((n_shards, cap, k), np.float32, 0)
    msk = alloc((n_shards, cap), bool, False)
    gid = alloc((n_shards, cap), np.int32, n)
    lo = np.full((n_shards, k), np.inf)
    hi = np.full((n_shards, k), -np.inf)
    sizes = []
    for s, (idx, rows, target, _plen) in enumerate(shard_rows):
        sizes.append(int(len(idx)))
        if len(idx):
            owned[s, target] = rows
            msk[s, target] = True
            gid[s, target] = idx
            lo[s] = rows.min(axis=0) + center
            hi[s] = rows.max(axis=0) + center
    aux = {
        "owned_cap": cap,
        "n_shard_partitions": n_shards,
        "pad_waste": float(n_shards * cap) / max(n, 1) - 1.0,
        "partition_sizes": sizes,
        "parity": {
            "order": order,
            "starts": [int(s) for s in starts],
            "box_lo": lo.tolist(),
            "box_hi": hi.tolist(),
        },
    }
    arrays = staging.transfer(lambda: tuple(
        jax.device_put(a, sharding) for a in (owned, msk, gid)
    ))
    staging.device_put_cached("gm_owned", base, arrays, aux=aux)
    return arrays, aux, bufs, base


def _stream_range_plan(split, s, block, eps):
    """One range's segment-break plan + extent box, streamed.

    Walks the range in pieces (:meth:`MortonStreamSplit
    .iter_range_rows`), accumulating the consecutive-row jump
    distances ``d2`` (elementwise — byte-identical to the in-RAM
    diff) and the range extrema, then derives the break plan from
    :func:`_segment_layout_from_d2`'s body.  Returns ``(plan, plen,
    lo, hi)`` where ``plan`` is None for the identity layout or
    ``(brk_pos, tgt0, src0)`` — tiny metadata from which any piece's
    slab targets rebuild (:func:`_plan_targets`), so the full (m,)
    target array never has to persist across ranges.
    """
    a, b = int(split.starts[s]), int(split.starts[s + 1])
    m, k = b - a, split.k
    lo = np.full(k, np.float32(np.inf), np.float32)
    hi = np.full(k, np.float32(-np.inf), np.float32)
    skip = _segbreak_skip(m, k, block, eps)
    if m == 0:
        return None, 0, lo, hi
    d2 = None if skip else np.empty(max(m - 1, 0), np.float32)
    prev = None
    for off, _ids, rows in split.iter_range_rows(s):
        np.minimum(lo, rows.min(axis=0), out=lo)
        np.maximum(hi, rows.max(axis=0), out=hi)
        if d2 is not None:
            if prev is not None and off > 0:
                d2[off - 1] = np.sum((rows[0] - prev) ** 2)
            if len(rows) > 1:
                diff = rows[1:] - rows[:-1]
                d2[off:off + len(rows) - 1] = np.sum(diff * diff,
                                                     axis=1)
            prev = rows[-1].copy()
    if skip:
        return None, round_up(m, block), lo, hi
    brk_pos, tgt0, src0, plen = _segment_plan_from_d2(d2, m, block, eps)
    return (brk_pos, tgt0, src0), plen, lo, hi


def build_morton_shards_streaming(points, n_shards, block, sharding,
                                  eps=None):
    """Out-of-core twin of :func:`build_morton_shards`.

    ``points`` is any row-sliceable array — typically a disk-backed
    ``np.memmap``.  The global Morton order comes from the external
    sample-sort (:func:`pypardis_tpu.partition
    .morton_range_split_streaming`, byte-identical per-range order /
    starts / center), and each shard's slab is assembled ALONE from
    spill-range pieces and shipped to its device before the next
    begins — peak host anonymous memory is O(stream chunk + sample +
    one spill bucket + one shard slab), never the full f32 copy + full
    permutation + all-shard slab of the in-RAM build.  Slab layout
    (segment breaks, capacity, gid placement) is byte-identical to the
    in-RAM build, so labels ride identical through the whole engine.

    Returns the :func:`build_morton_shards` contract ``(arrays, aux,
    host_bufs, base)`` with ``arrays`` already device-resident and
    ``aux["parity"]`` carrying starts/boxes but NO full order array
    (the O(N) permutation is exactly what this path exists to avoid).
    """
    from ..partition import morton_range_split_streaming

    n, k = points.shape
    base = _gm_cache_key(points, n_shards, block, sharding)
    cached = staging.device_get("gm_owned", base)
    if cached is not None:
        arrays, aux = cached
        return arrays, aux, [], base
    mesh = sharding.mesh
    devices = mesh.devices.reshape(-1)
    split = morton_range_split_streaming(
        points, n_shards, eps=eps, block=block
    )
    try:
        plans, plens, sizes = [], [], []
        lo = np.full((n_shards, k), np.inf)
        hi = np.full((n_shards, k), -np.inf)
        for s in range(n_shards):
            plan, plen, rlo, rhi = _stream_range_plan(
                split, s, block, eps
            )
            plans.append(plan)
            plens.append(plen)
            m = int(split.starts[s + 1] - split.starts[s])
            sizes.append(m)
            if m:
                lo[s] = rlo + split.center
                hi[s] = rhi + split.center
        cap = round_up(max(plens + [1]), block)
        parts = ([], [], [])
        my_proc = dist.process_index()
        for s in range(n_shards):
            # Multi-process fleet: each controller assembles ONLY the
            # shards living on its own devices (device_put to a
            # non-addressable device is illegal, and reading remote
            # shards' spill ranges would be wasted IO anyway —
            # make_array_from_single_device_arrays wants exactly the
            # addressable shards).
            if int(devices[s].process_index) != my_proc:
                continue
            # Device-side slab assembly: the host never allocates a
            # cap-sized buffer — spill pieces ship as they are read
            # and scatter into the device-resident slab, so peak host
            # anon stays O(piece) and "one shard" lives in HBM where
            # it belongs (on the CPU mesh device buffers are host
            # anon — the streammem probe's documented caveat).  The
            # mask derives from the gid slab in-place, saving a third
            # of the transfers.
            dev = devices[s]
            # device_put COMMITS the slab to its device (an
            # uncommitted default_device array migrates back to
            # device 0 and breaks the single-device assembly);
            # committed operands then pin every .at[].set there.
            # graftlint: disable=device-put-aliasing -- commits fresh
            # jnp allocations to the device; no host buffer exists
            ow = jax.device_put(jnp.zeros((cap, k), jnp.float32), dev)
            # graftlint: disable=device-put-aliasing -- same as ow
            gd = jax.device_put(jnp.full((cap,), n, jnp.int32), dev)
            for off, ids, rows in split.iter_range_rows(
                s, chunk=1 << 19
            ):
                tgt = _plan_targets(plans[s], off, len(ids))
                ow, gd = staging.transfer(
                    lambda ow=ow, gd=gd, tgt=tgt, rows=rows,
                    ids=ids: (
                        ow.at[tgt].set(rows),
                        gd.at[tgt].set(ids),
                    )
                )
            ms = gd != jnp.int32(n)
            parts[0].append(ow[None])
            parts[1].append(ms[None])
            parts[2].append(gd[None])
            del ow, ms, gd
        owned = jax.make_array_from_single_device_arrays(
            (n_shards, cap, k), sharding, parts[0]
        )
        msk = jax.make_array_from_single_device_arrays(
            (n_shards, cap), sharding, parts[1]
        )
        gid = jax.make_array_from_single_device_arrays(
            (n_shards, cap), sharding, parts[2]
        )
        aux = {
            "owned_cap": cap,
            "n_shard_partitions": n_shards,
            "pad_waste": float(n_shards * cap) / max(n, 1) - 1.0,
            "partition_sizes": sizes,
            "input": "stream",
            **split.stats,
            "parity": {
                "starts": [int(x) for x in split.starts],
                "box_lo": lo.tolist(),
                "box_hi": hi.tolist(),
            },
        }
    finally:
        split.close()
    arrays = (owned, msk, gid)
    staging.device_put_cached("gm_owned", base, arrays, aux=aux)
    return arrays, aux, [], base


# ---------------------------------------------------------------------------
# boundary-tile exchange programs
# ---------------------------------------------------------------------------

_BOX_BIG = np.float32(3e38)


@functools.partial(
    jax.jit, static_argnames=("gtile", "mesh", "axis")
)
def _gm_plan_step(owned, mask, eps, *, gtile, mesh, axis):
    """Metadata-only exchange capacity plan.

    Per device, the EXACT count of boundary tiles it must SEND (its
    tiles within eps of some remote shard's tiles) and RECEIVE (remote
    tiles within eps of its own) — pure box arithmetic over the
    all-gathered per-tile bounding boxes; no coordinate ever moves.
    Sizing the send/recv buffers from this plan makes the btcap/bcap
    doubling ladder a backstop instead of the common path: the first
    measured north-star run (5M x 16-D) paid TWO full exchange reruns
    (select + P-1 ring rounds + flatten + recompiles, ~2/3 of its
    236.6s exchange wall) climbing the ladder that this one tiny
    program replaces.
    """

    def per_device(o, m):
        cap, k = o.shape[1], o.shape[2]
        nt = cap // gtile
        tiles = o[0].reshape(nt, gtile, k)
        tmsk = m[0].reshape(nt, gtile)
        from ..ops.distances import cross_tile_live, tile_bounds

        lo, hi = tile_bounds(tiles.transpose(0, 2, 1), tmsk)
        n_dev = (
            jax.lax.axis_size(axis)
            if hasattr(jax.lax, "axis_size")
            else jax.lax.psum(1, axis)
        )
        all_lo = jax.lax.all_gather(lo, axis)
        all_hi = jax.lax.all_gather(hi, axis)
        me = jax.lax.axis_index(axis)
        mine = (jnp.arange(n_dev) == me)[:, None, None]
        rem_lo = jnp.where(mine, _BOX_BIG, all_lo).reshape(n_dev * nt, k)
        rem_hi = jnp.where(mine, -_BOX_BIG, all_hi).reshape(n_dev * nt, k)
        send = cross_tile_live(lo, hi, rem_lo, rem_hi, eps)
        recv = cross_tile_live(rem_lo, rem_hi, lo, hi, eps)
        return (
            jnp.sum(send.astype(jnp.int32))[None],
            jnp.sum(recv.astype(jnp.int32))[None],
        )

    sp3 = P("p", None, None)
    sp2 = P("p", None)
    sp1 = P("p")
    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(sp3, sp2),
        out_specs=(sp1, sp1),
        check_vma=False,
    )(owned, mask)


@functools.partial(
    jax.jit,
    static_argnames=(
        "eps", "metric", "block", "mesh", "axis", "precision", "backend",
        "pair_budget",
    ),
)
def _gm_owned_counts_step(
    owned, omsk, *, eps, metric, block, mesh, axis, precision, backend,
    pair_budget,
):
    """Owned-slab raw counts (owned rows x owned columns) as its own
    collective-free program — dispatched BEFORE the boundary exchange
    so the P-1 host-stepped ring rounds hide behind it.  The boundary
    columns' contribution lands afterwards as
    :func:`_gm_counts_delta_step`, and ``owned + delta`` equals the
    fused counts pass bitwise (integer adds over disjoint column sets
    commute), so labels cannot depend on the overlap.  Returns
    ``(counts (P, cap), stats (P, 4) [total, budget, band_pairs,
    rescored_tiles])``."""

    def per_device(o, om):
        cap = o.shape[1]
        kind, pairs, st = oc_extract(
            o[0], eps, om[0], owned=cap, metric=metric, block=block,
            precision=precision, backend=backend, pair_budget=pair_budget,
        )
        counts, band = oc_raw_counts(
            o[0], eps, om[0], owned=cap, metric=metric, block=block,
            precision=precision, kind=kind, pairs=pairs,
        )
        return counts[None], jnp.concatenate([st, band])[None]

    sp3 = P("p", None, None)
    sp2 = P("p", None)
    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(sp3, sp2),
        out_specs=(sp2, sp2),
        check_vma=False,
    )(owned, omsk)


@functools.partial(
    jax.jit,
    static_argnames=(
        "eps", "metric", "block", "mesh", "axis", "precision", "backend",
        "pair_budget",
    ),
)
def _gm_counts_delta_step(
    owned, omsk, bnd, bmsk, *, eps, metric, block, mesh, axis, precision,
    backend, pair_budget,
):
    """Owned rows x boundary columns counts — the exchange-fed half of
    the overlapped counts pass (:func:`_gm_owned_counts_step`).  The
    (owned row, boundary col) restriction is a pair-list filter, so
    this requires the compacted dispatch (Pallas, or XLA pair mode —
    the driver gates the overlap off otherwise).  Returns ``(delta
    (P, cap), stats (P, 4))``."""

    def per_device(o, om, bp, bm):
        cap = o.shape[1]
        pts = jnp.concatenate([o[0], bp[0]], axis=0)
        msk = jnp.concatenate([om[0], bm[0]])
        kind, pairs, st = oc_extract(
            pts, eps, msk, owned=cap, metric=metric, block=block,
            precision=precision, backend=backend, pair_budget=pair_budget,
        )
        delta, band = oc_counts_delta(
            pts, eps, msk, owned=cap, metric=metric, block=block,
            precision=precision, kind=kind, pairs=pairs,
        )
        return delta[None], jnp.concatenate([st, band])[None]

    sp3 = P("p", None, None)
    sp2 = P("p", None)
    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(sp3, sp2, sp3, sp2),
        out_specs=(sp2, sp2),
        check_vma=False,
    )(owned, omsk, bnd, bmsk)


@functools.partial(
    jax.jit, static_argnames=("gtile", "btcap", "bcap", "mesh", "axis",
                              "sketch")
)
def _gm_select_step(owned, mask, gid, eps, *, gtile, btcap, bcap, mesh,
                    axis, sketch=0):
    """Send-side boundary-tile selection + zeroed receive buffers.

    ``sketch`` (resolved k, static): tightens the send set with the
    sketch-space box test (:func:`..parallel.halo.boundary_send_select`)
    — the extra ``n_send_box`` output is the full-d-only count the
    telemetry ratio reports against."""

    def per_device(o, m, g):
        out = boundary_send_select(
            o[0], m[0], g[0], eps, gtile=gtile, btcap=btcap, axis=axis,
            sketch=sketch,
        )
        (s_pts, s_msk, s_gid, s_lo, s_hi, n_send, ovf, my_lo, my_hi,
         n_send_box) = out
        k = o.shape[2]
        r_pts = jnp.zeros((1, bcap, gtile, k), o.dtype)
        r_msk = jnp.zeros((1, bcap, gtile), bool)
        r_gid = jnp.full((1, bcap, gtile), jnp.int32(_INT32_MAX))
        r_val = jnp.zeros((1, bcap), bool)
        r_ovf = jnp.zeros((1,), jnp.int32)
        return (
            s_pts[None], s_msk[None], s_gid[None], s_lo[None], s_hi[None],
            n_send[None], ovf[None], my_lo[None], my_hi[None],
            n_send_box[None],
            r_pts, r_msk, r_gid, r_val, r_ovf,
        )

    sp4 = P("p", None, None, None)
    sp3 = P("p", None, None)
    sp2 = P("p", None)
    sp1 = P("p")
    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(sp3, sp2, sp2),
        out_specs=(
            sp4, sp3, sp3, sp3, sp3, sp1, sp1, sp3, sp3, sp1,
            sp4, sp3, sp3, sp2, sp1,
        ),
        check_vma=False,
    )(owned, mask, gid)


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def _gm_ring_step(
    buf_pts, buf_msk, buf_gid, buf_lo, buf_hi,
    recv_pts, recv_msk, recv_gid, recv_val, recv_ovf,
    my_lo, my_hi, eps, *, mesh, axis,
):
    """One boundary-tile ring round as its own program (host-stepped so
    every round is a trace span and the overflow probe is per-round)."""

    def per_device(bp, bm, bg, bl, bh, rp, rm, rg, rv, ov, ml, mh):
        out = ring_tile_round(
            bp[0], bm[0], bg[0], bl[0], bh[0],
            rp[0], rm[0], rg[0], rv[0], ov[0],
            ml[0], mh[0], eps, axis,
        )
        return tuple(o[None] for o in out)

    sp4 = P("p", None, None, None)
    sp3 = P("p", None, None)
    sp2 = P("p", None)
    sp1 = P("p")
    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(sp4, sp3, sp3, sp3, sp3, sp4, sp3, sp3, sp2, sp1,
                  sp3, sp3),
        out_specs=(sp4, sp3, sp3, sp3, sp3, sp4, sp3, sp3, sp2, sp1),
        check_vma=False,
    )(buf_pts, buf_msk, buf_gid, buf_lo, buf_hi,
      recv_pts, recv_msk, recv_gid, recv_val, recv_ovf, my_lo, my_hi)


@functools.partial(jax.jit, static_argnames=("mesh",))
def _gm_flatten_step(recv_pts, recv_msk, recv_gid, recv_val, my_lo,
                     my_hi, eps, *, mesh):
    """Row-granular retention of the tile-granular transport.

    The ring ships whole exchange tiles (a tile is accepted when its
    box reaches ANY of my tiles), but a kept tile still carries rows
    this shard can never touch — the quantization that would make
    coarse exchanges as heavy as 2*eps halos.  This step MASKS them: a
    row stays valid iff its own distance to SOME of my tile boxes is
    <= eps (exact — an eps-neighbor of my point x lies within eps of
    x's tile box; the Euclidean box gap also lower-bounds the
    cityblock distance, so the filter is safe for both metrics).  Rows
    are NOT re-packed across tiles: each exchange tile keeps its
    sender-contiguous run, so the kernel's per-tile bounding boxes
    (computed over the surviving mask) stay subsets of the sender's
    tight Morton-run boxes — re-packing survivors densely was measured
    to DOUBLE live tile pairs, because globally-Morton-adjacent
    survivor rows can sit across Z-order jumps and their union boxes
    defeat the gap test (the same leak the fused engine's
    segment-break layout exists for).  Fully-filtered tiles become
    all-masked (inverted boxes) and every tiled pass prunes them free.

    Returns the flattened (P, brows, ...) boundary slab plus per-device
    accepted-tile / surviving-row counts for telemetry.
    """

    def per_device(p, m, g, v, ml, mh, e):
        _, bcap, blk, k = p.shape
        rows = bcap * blk
        pts = p[0].reshape(rows, k)
        msk = (m[0] & v[0][:, None]).reshape(rows)

        def gap_step(acc, lohi):
            lo_t, hi_t = lohi
            gap = jnp.maximum(
                0.0,
                jnp.maximum(lo_t[None, :] - pts, pts - hi_t[None, :]),
            )
            return jnp.minimum(acc, jnp.sum(gap * gap, axis=1)), None

        d2, _ = jax.lax.scan(
            gap_step,
            jnp.full((rows,), jnp.float32(3e38)),
            (ml[0], mh[0]),
        )
        keep = (msk & (d2 <= jnp.float32(e) ** 2)).reshape(bcap, blk)
        gidq = jnp.where(keep, g[0], jnp.int32(_INT32_MAX))
        # Order tiles by global Morton position (first surviving gid);
        # empty tiles carry INT32_MAX keys and sink to the tail — which
        # makes the slab COMPACT: the driver slices it down to the mesh
        # max of kept_tiles, so receive-capacity headroom never becomes
        # kernel column tiles.
        tile_key = jnp.min(gidq, axis=1)
        order = jnp.argsort(tile_key, stable=True)
        tiles = jnp.sum(v[0].astype(jnp.int32))
        kept = jnp.sum(keep.astype(jnp.int32))
        kept_tiles = jnp.sum((tile_key < _INT32_MAX).astype(jnp.int32))
        return (
            p[0][order].reshape(1, rows, k),
            keep[order].reshape(1, rows),
            gidq[order].reshape(1, rows),
            tiles[None],
            kept[None],
            kept_tiles[None],
        )

    sp3 = P("p", None, None)
    sp2 = P("p", None)
    sp1 = P("p")
    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P("p", None, None, None), sp3, sp3, sp2, sp3, sp3,
                  P()),
        out_specs=(sp3, sp2, sp2, sp1, sp1, sp1),
        check_vma=False,
    )(recv_pts, recv_msk, recv_gid, recv_val, my_lo, my_hi, eps)


def _gm_exchange(arrays, eps, *, mesh, axis, gtile, bt, bc,
                 round_hook=None, sketch=0):
    """Run the boundary-tile exchange: select, P-1 spanned ring rounds,
    flatten.  Returns ``((bnd, bmsk, bgid), xstats, send_need,
    recv_overflow)`` — ``send_need`` is the exact per-device max of
    boundary tiles (so a send overflow retries with the exact
    capacity), ``recv_overflow`` the max tiles dropped for ``bc``.

    ``round_hook``, when given, is invoked (no args) after every ring
    round completes — the overlap driver uses it to timestamp when the
    concurrently dispatched counts pass went ready, at round
    granularity.  ``xstats`` carries ``ring_wall_s``, the wall seconds
    of the host-stepped ring loop alone (the overlap-efficiency
    denominator).
    """
    import time as _time

    owned, omsk, ogid = arrays
    n_dev = mesh.devices.size
    k = owned.shape[2]
    with obs_span("gm.exchange", ring_rounds=max(n_dev - 1, 0),
                  btcap=bt, bcap=bc) as sp:
        out = _gm_select_step(
            owned, omsk, ogid, np.float32(eps),
            gtile=gtile, btcap=bt, bcap=bc, mesh=mesh, axis=axis,
            sketch=sketch,
        )
        (s_pts, s_msk, s_gid, s_lo, s_hi, n_send, s_ovf, my_lo, my_hi,
         n_send_box, r_pts, r_msk, r_gid, r_val, r_ovf) = out
        state = (s_pts, s_msk, s_gid, s_lo, s_hi,
                 r_pts, r_msk, r_gid, r_val, r_ovf)
        t_ring = _time.perf_counter()
        for r in range(n_dev - 1):
            with obs_span("gm.ring_round", round=r) as rs:

                def one_round(state=state):
                    # Injection site + unified retry scope: the ring
                    # step is pure in its inputs (the Python-held state
                    # tuple is rebound only on success), so a
                    # re-dispatch after a transient fault recomputes
                    # the identical round.  The overflow probe inside
                    # the scope is the sync that surfaces execution
                    # faults here rather than rounds later.
                    faults.maybe_fail("gm.ring_round")
                    out = _gm_ring_step(
                        *state, my_lo, my_hi, np.float32(eps),
                        mesh=mesh, axis=axis,
                    )
                    dist.fetch_np(out[-1])
                    return out

                state = Retrier("gm.ring_round").run(one_round)
                # The per-round overflow probe doubles as the span sync
                # — a scalar fetch, so the span measures the round's
                # execution, not its dispatch.
                rs.sync_on(state[-1])
            if round_hook is not None:
                round_hook()
            obs_heartbeat("gm.ring", r + 1, n_dev - 1, t_ring)
        ring_wall = _time.perf_counter() - t_ring
        bnd, bmsk, bgid, tiles, rows, kept_tiles = _gm_flatten_step(
            state[5], state[6], state[7], state[8], my_lo, my_hi,
            np.float32(eps), mesh=mesh,
        )
        n_send_np = dist.fetch_np(n_send)
        recv_ovf_np = dist.fetch_np(state[-1])
        tiles_np = dist.fetch_np(tiles)
        rows_np = dist.fetch_np(rows)
        # Compact the boundary slab to the mesh max of SURVIVING tiles
        # (the flatten sinks empty tiles to the tail): the receive
        # ladder's capacity headroom would otherwise ride into the
        # cluster step as permanently-masked column tiles — box-pruned,
        # but still per-tile scan iterations in every kernel pass.
        mt = max(1, int(dist.fetch_np(kept_tiles).max()))
        gtile_rows = mt * gtile
        if gtile_rows < bnd.shape[1]:
            bnd = bnd[:, :gtile_rows]
            bmsk = bmsk[:, :gtile_rows]
            bgid = bgid[:, :gtile_rows]
        sent_tiles = int(np.minimum(n_send_np, bt).sum())
        sent_tiles_box = int(
            np.minimum(dist.fetch_np(n_send_box), bt).sum()
        )
        xstats = {
            "boundary_tiles": int(tiles_np.sum()),
            "boundary_rows": int(rows_np.sum()),
            "sent_tiles": sent_tiles,
            # Actual coordinate bytes the ring carries per circulation:
            # the occupancy analogue of the KD host route's halo_bytes
            # (duplicated rows shipped), at tile granularity.
            "boundary_tile_bytes": sent_tiles * gtile * k * 4,
            # Full-d-box-only twins: what the ring WOULD carry without
            # the sketch tightening (== the actual counters when
            # sketch=0).  sent_tiles <= sent_tiles_box always — the
            # sketch test only ANDs into the live mask.
            "sent_tiles_box": sent_tiles_box,
            "boundary_bytes_box": sent_tiles_box * gtile * k * 4,
            "boundary_tile_caps": [int(bt), int(bc)],
            "exchange_tile": int(gtile),
            "ring_wall_s": round(ring_wall, 6),
        }
        sp.set(boundary_tiles=xstats["boundary_tiles"],
               sent_tiles=sent_tiles)
        # Ring-traffic counters (surfaced in summary(); previously
        # only the trace spans existed, so ring traffic was invisible
        # without exporting a trace).  Counters accumulate across
        # capacity-ladder retries — the TRUE bytes every ppermute
        # circulation carried, not just the final attempt's.
        m = obs_current().metrics
        m.inc(
            "gm.ring_bytes_sent",
            xstats["boundary_tile_bytes"] * max(n_dev - 1, 0),
        )
        m.inc("gm.ring_tiles_kept", xstats["boundary_tiles"])
    send_need = int(n_send_np.max()) if n_send_np.size else 0
    return (bnd, bmsk, bgid), xstats, send_need, int(
        recv_ovf_np.max() if recv_ovf_np.size else 0
    )


def _gm_boundary_tiles(arrays, eps, *, mesh, axis, block, btcap, base,
                       round_hook=None, sketch=0):
    """The boundary exchange behind its capacity ladder and the staging
    cache (route ``gm_boundary``, keyed base + eps): warm refits of the
    same data/eps skip the select + ring entirely.

    With ``btcap=None`` (the default) the send/recv capacities come
    from the metadata-only :func:`_gm_plan_step` — exact, so the
    doubling ladder below is a backstop, not two extra full exchange
    passes per cold fit."""
    faults.maybe_fail("gm.exchange")
    # sketch is in the key: the tightened send set changes which tiles
    # the cached boundary slab holds (a superset/subset per setting).
    bkey = base + ("boundary", float(eps), int(sketch))
    cached = staging.device_get("gm_boundary", bkey)
    if cached is not None:
        (bnd, bmsk, bgid), baux = cached
        return (bnd, bmsk, bgid), baux
    n_dev = mesh.devices.size
    cap = arrays[0].shape[1]
    if btcap is None:
        # The exhaustion messages below have always named
        # PYPARDIS_GM_BTCAP as the remedy; until graftlint's env
        # registry audit (R4) nothing actually read it.  An env-set
        # cap is a user contract exactly like an explicit argument.
        env_btcap = envreg.raw("PYPARDIS_GM_BTCAP")
        if env_btcap:
            btcap = int(env_btcap)
    # Exchange granularity == the kernel block: finer exchange tiles
    # were measured to INCREASE live tile pairs (each kernel tile then
    # unions several senders' boxes), and the row-exact retention mask
    # in _gm_flatten_step recovers the volume a coarse tile over-ships.
    gtile = block
    bstep = block // gtile
    nt = cap // gtile
    explicit = btcap is not None
    bc_hard = round_up(max(n_dev - 1, 1) * nt, bstep)
    if explicit:
        bt = min(max(1, int(btcap)), nt)
        bc = min(round_up(max(1, 2 * bt), bstep), bc_hard)
    else:
        # Exact plan: per-device send/recv tile needs from box
        # metadata alone.  The receive need counts every remote tile
        # within eps of mine — exactly the tiles the ring rounds will
        # accept into the recv buffer.
        n_send_pd, n_recv_pd = _gm_plan_step(
            arrays[0], arrays[1], np.float32(eps),
            gtile=gtile, mesh=mesh, axis=axis,
        )
        bt = min(max(1, int(dist.fetch_np(n_send_pd).max())), nt)
        bc = min(
            round_up(max(1, int(dist.fetch_np(n_recv_pd).max())), bstep),
            bc_hard,
        )
    attempts = 6
    while True:
        (bnd, bmsk, bgid), xstats, send_need, recv_ovf = _gm_exchange(
            arrays, eps, mesh=mesh, axis=axis, gtile=gtile, bt=bt, bc=bc,
            round_hook=round_hook, sketch=sketch,
        )
        send_ovf = max(0, send_need - bt)
        if send_ovf == 0 and recv_ovf == 0:
            break
        obs_event(
            "halo_overflow", mode="global_morton", send=send_ovf,
            recv=recv_ovf, btcap=bt, bcap=bc,
        )
        if send_ovf and explicit:
            # An explicit send cap is a user contract: dropped boundary
            # tiles would mean silently wrong labels, so fail loudly —
            # and actionably: the message names the exact need and
            # every knob that raises the cap.
            err = RuntimeError(
                f"global-Morton boundary-tile send buffer overflow: "
                f"btcap={bt} but this mesh/eps needs {send_need} tiles "
                f"per device; pass btcap>={send_need} "
                f"(global_morton_dbscan(btcap=...)) or set "
                f"PYPARDIS_GM_BTCAP={send_need}, or leave btcap unset "
                f"for the auto-doubling ladder"
            )
            note_giveup("gm.btcap", err)
            raise err
        attempts -= 1
        if attempts <= 0:
            err = RuntimeError(
                f"global-Morton boundary-tile buffer overflow persisted "
                f"through {6} capacity retries (btcap={bt}, bcap={bc}); "
                f"pass a larger btcap (global_morton_dbscan(btcap=...) "
                f"or PYPARDIS_GM_BTCAP)"
            )
            note_giveup("gm.btcap", err)
            raise err
        note_retry(
            "gm.btcap", 0.0,
            RuntimeError(
                f"boundary-tile overflow (send={send_ovf}, "
                f"recv={recv_ovf}) at btcap={bt}, bcap={bc}"
            ),
        )
        if send_ovf:
            # n_send is exact, so one retry covers the send side.
            bt = min(nt, max(send_need, 2 * bt))
        if recv_ovf:
            bc = min(
                bc_hard, round_up(max(bc + recv_ovf, 2 * bc), bstep)
            )
    staging.device_put_cached(
        "gm_boundary", bkey, (bnd, bmsk, bgid), aux=xstats
    )
    return (bnd, bmsk, bgid), xstats


# ---------------------------------------------------------------------------
# cluster + fixpoint programs
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=(
        "eps", "min_samples", "metric", "block", "mesh", "axis",
        "n_points", "precision", "backend", "pair_budget",
    ),
)
def _gm_cluster_step(
    owned, omsk, ogid, bnd, bmsk, bgid, own_core=None,
    *, eps, min_samples, metric, block, mesh, axis, n_points,
    precision, backend, pair_budget,
):
    """Owner-computes clustering over the owned + boundary-tile slab.

    Per device: pair extraction + owned-row counts (boundary columns
    are evidence), ONE pmax replicates the owners' core verdicts into
    boundary-slot flags, relay-only propagation emits the occurrence
    tables, and the replicated home-label table is built in-graph.
    Returns ``(home_label (N+1,) replicated, core_g (N+1,) replicated,
    b_glab (P, brows) sharded, pair_stats (P, 5))`` — everything the
    host-stepped fixpoint consumes.

    ``own_core`` (optional, (P, cap) bool sharded): precomputed owned
    core flags from the overlapped counts route (owned-slab pass +
    boundary delta, summed and thresholded host-side) — the in-graph
    counts pass is then skipped and its band columns are zero (the
    driver folds the overlapped passes' bands host-side).
    """
    n1 = n_points + 1
    pre_core = own_core is not None

    def per_device(o, om, og, bp, bm, bg, *pre):
        cap = o.shape[1]
        pts = jnp.concatenate([o[0], bp[0]], axis=0)
        msk = jnp.concatenate([om[0], bm[0]])
        gid = jnp.concatenate([og[0], bg[0]])
        kind, pairs, st = oc_extract(
            pts, eps, msk, owned=cap, metric=metric, block=block,
            precision=precision, backend=backend, pair_budget=pair_budget,
        )
        if pre_core:
            own_core_l = pre[0][0]
            counts_band = jnp.zeros(2, jnp.int32)
        else:
            own_core_l, counts_band = oc_counts_banded(
                pts, eps, min_samples, msk, owned=cap, metric=metric,
                block=block, precision=precision, kind=kind, pairs=pairs,
            )
        core_g = _replicated_core(own_core_l[None], og, axis, n1)
        b_core = (
            core_g[jnp.clip(bg[0], 0, n_points)]
            & (bg[0] < n_points) & bm[0]
        )
        labels, passes, prop_band = oc_propagate_banded(
            pts, eps, msk, jnp.concatenate([own_core_l, b_core]),
            owned=cap, metric=metric, block=block, precision=precision,
            kind=kind, pairs=pairs,
        )
        glabel = jnp.where(
            labels >= 0, jnp.take(gid, jnp.clip(labels, 0, None)), -1
        ).astype(jnp.int32)
        own_glab, b_glab = glabel[:cap], glabel[cap:]
        home_label = (
            jnp.full((n1,), -1, jnp.int32)
            .at[og.reshape(-1)]
            .max(own_glab)
        )
        home_label = jax.lax.pmax(home_label, axis).at[n1 - 1].set(-1)
        pair_stats = jnp.concatenate(
            [st, (1 + passes)[None], counts_band + prop_band]
        )
        return home_label, core_g, b_glab[None], pair_stats[None]

    sp3 = P("p", None, None)
    sp2 = P("p", None)
    extra = (sp2,) if pre_core else ()
    args = (owned, omsk, ogid, bnd, bmsk, bgid)
    if pre_core:
        args = args + (own_core,)
    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(sp3, sp2, sp2, sp3, sp2, sp2) + extra,
        out_specs=(P(), P(), sp2, sp2),
        check_vma=False,
    )(*args)


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "n_points"))
def _gm_fixpoint_step(lab_map, home_label, core_g, bgid, b_glab,
                      *, mesh, axis, n_points):
    """One cross-device pmin label round (:func:`sharded._merge_round`)
    as its own program — the host-stepped fixpoint's unit of work."""

    def per_device(lm, hl, cg, g, l):
        h_gid = g.reshape(-1)
        h_lab = l.reshape(-1)
        h_core = cg[jnp.clip(h_gid, 0, n_points)] & (h_gid < n_points)
        return _merge_round(lm, hl, cg, h_gid, h_lab, h_core, axis)

    sp2 = P("p", None)
    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), P(), P(), sp2, sp2),
        out_specs=(P(), P()),
        check_vma=False,
    )(lab_map, home_label, core_g, bgid, b_glab)


def _gm_fixpoint(home_label, core_g, bgid, b_glab, *, mesh, axis,
                 n_points, merge_rounds, jobstate=None, budget_tag=0):
    """Host-stepped cross-device pmin fixpoint.

    Each round is its own program with a per-round convergence probe
    (one replicated scalar fetch) and a ``gm.fixpoint_round`` trace
    span, so ``export_trace()`` separates merge rounds from cluster
    compute.  Semantics match :func:`sharded._merge_loop` exactly (the
    shared :func:`sharded._merge_round` body); ``converged`` False at
    ``merge_rounds`` means possibly under-merged — the caller's ladder
    retries at 4x, never returns it silently.

    Rounds run under the unified retry layer (site
    ``gm.fixpoint_round``): a transient fault re-dispatches the round
    from the same Python-held ``lab_map`` — pure, so byte-identical.
    With a ``jobstate``, each round's (N+1,) ``lab_map`` snapshots at
    the checkpoint cadence; a SIGKILLed fit resumes mid-fixpoint and
    converges to the identical labels (pmin propagation is monotone
    toward its unique fixpoint from any intermediate state of the same
    tables — which is why snapshots are keyed by the pair budget that
    produced those tables).
    """
    import time as _time

    rep = NamedSharding(mesh, P())
    # graftlint: disable=device-put-aliasing -- fresh np.arange
    lab_map = jax.device_put(np.arange(n_points + 1, dtype=np.int32), rep)
    rounds = 0
    if jobstate is not None:
        saved = jobstate.gm_restore(int(budget_tag), n_points + 1)
        if saved is not None:
            # graftlint: disable=device-put-aliasing -- fresh array
            # deserialized from the checkpoint npz
            lab_map = jax.device_put(saved[0], rep)
            rounds = min(int(saved[1]), max(merge_rounds - 1, 0))
            obs_event("jobstate_restore", route="gm_fixpoint",
                      round=rounds)
    converged = False
    t0 = _time.perf_counter()
    while rounds < merge_rounds:
        # Pod fault drill site: whole-WORKER faults (a process dying
        # or stalling mid-fixpoint).  Outside the per-round Retrier on
        # purpose — in-process retry cannot recover a dead controller;
        # the recovery path is the launcher tearing the fleet down and
        # relaunching with train(resume=) against the coordinator's
        # jobstate snapshot (monotone pmin resumes byte-identically).
        faults.maybe_fail("dist.worker")
        with obs_span("gm.fixpoint_round", round=rounds):

            def one_round(lab_map=lab_map):
                faults.maybe_fail("gm.fixpoint_round")
                new_map, changed = _gm_fixpoint_step(
                    lab_map, home_label, core_g, bgid, b_glab,
                    mesh=mesh, axis=axis, n_points=n_points,
                )
                return new_map, bool(np.asarray(changed))

            lab_map, ch = Retrier("gm.fixpoint_round").run(one_round)
        rounds += 1
        obs_heartbeat("gm.fixpoint", rounds, merge_rounds, t0)
        if jobstate is not None and jobstate.due():
            jobstate.gm_note(
                np.asarray(lab_map), rounds, int(budget_tag)
            )
        if not ch:
            converged = True
            break
    return lab_map, rounds, converged


# ---------------------------------------------------------------------------
# chained 1-device route (streaming ranges through one chip)
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=(
        "owned", "eps", "min_samples", "metric", "block", "precision",
        "backend", "pair_budget",
    ),
)
def _gm_chain_counts(pts, msk, *, owned, eps, min_samples, metric,
                     block, precision, backend, pair_budget):
    """One range's owner-computes COUNTS pass on a single device: the
    per-device half of :func:`_gm_cluster_step` minus every collective
    — owned rows count against owned + boundary columns, nothing else
    runs.  Returns ``(own_core (owned,), pair_stats (5,))``."""
    kind, pairs, st = oc_extract(
        pts, eps, msk, owned=owned, metric=metric, block=block,
        precision=precision, backend=backend, pair_budget=pair_budget,
    )
    core, band = oc_counts_banded(
        pts, eps, min_samples, msk, owned=owned, metric=metric,
        block=block, precision=precision, kind=kind, pairs=pairs,
    )
    return core, jnp.concatenate(
        [st, jnp.ones(1, jnp.int32), band]
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "owned", "eps", "metric", "block", "precision", "backend",
        "pair_budget",
    ),
)
def _gm_chain_propagate(pts, msk, core_all, gid, *, owned, eps, metric,
                        block, precision, backend, pair_budget):
    """One range's relay PROPAGATION pass with host-supplied core
    flags (the chained analogue of :func:`sharded._oc_cluster_step`'s
    per-device body).  Returns ``(glabel (rows,), pair_stats (5,))``
    — global root-gid labels over owned + boundary slots, the exact
    occurrence-table wire format the host union-find consumes."""
    kind, pairs, st = oc_extract(
        pts, eps, msk, owned=owned, metric=metric, block=block,
        precision=precision, backend=backend, pair_budget=pair_budget,
    )
    labels, passes, band = oc_propagate_banded(
        pts, eps, msk, core_all, owned=owned, metric=metric,
        block=block, precision=precision, kind=kind, pairs=pairs,
    )
    glabel = jnp.where(
        labels >= 0, jnp.take(gid, jnp.clip(labels, 0, None)), -1
    ).astype(jnp.int32)
    return glabel, jnp.concatenate([st, (1 + passes)[None], band])


def _chain_boundary_tiles(split, starts, block, eps, n, n_ranges):
    """Tile-granular boundary cover per range, from the streamed
    global tile boxes.

    A tile t is boundary context for range s iff its box lies within
    eps of SOME tile box of s — the same box-gap bound the ring's
    :func:`halo.boundary_send_select` uses, so the cover is exact
    (every cross-range eps-pair lives in a tile pair whose boxes are
    within eps).  A union-box prefilter cuts the exact pass to the
    candidate frontier.  Returns ``(tile_sel, boundary_rows)``.
    """
    tlo, thi = split.tile_lo, split.tile_hi
    nt, k = tlo.shape
    eps2 = np.float32(eps) ** 2
    tile_sel, brows = [], []
    for s in range(n_ranges):
        a, b = int(starts[s]), int(starts[s + 1])
        if b <= a:
            tile_sel.append(np.empty(0, np.int64))
            brows.append(0)
            continue
        ts, te = a // block, -(-b // block)
        ulo = tlo[ts:te].min(axis=0)
        uhi = thi[ts:te].max(axis=0)
        gap_u = np.maximum(
            0.0, np.maximum(ulo[None] - thi, tlo - uhi[None])
        )
        cand = np.flatnonzero(np.sum(gap_u * gap_u, axis=1) <= eps2)
        cand = cand[(cand < ts) | (cand >= te)]
        if len(cand):
            keep = np.zeros(len(cand), bool)
            rlo, rhi = tlo[ts:te], thi[ts:te]
            # Same bounded-transient budget as _weights_from_boxes.
            step = max(1, (1 << 23) // max((te - ts) * k, 1))
            for c0 in range(0, len(cand), step):
                c1 = min(c0 + step, len(cand))
                g = np.maximum(
                    0.0,
                    np.maximum(rlo[None] - thi[cand[c0:c1], None],
                               tlo[cand[c0:c1], None] - rhi[None]),
                )
                keep[c0:c1] = (
                    np.sum(g * g, axis=-1) <= eps2
                ).any(axis=1)
            cand = cand[keep]
        tile_sel.append(cand)
        brows.append(int(sum(
            min((int(t) + 1) * block, n) - int(t) * block
            for t in cand
        )))
    return tile_sel, brows


def _chain_fill_boundary(split, tiles, bcap, block, n, k):
    """(bcap, k) boundary slab for one range: each selected tile keeps
    its own block-aligned slot (sender-tight boxes — the same
    quantization the ring's transport preserves), contiguous tile runs
    coalesced into single spill reads."""
    bp = np.zeros((bcap, k), np.float32)
    bm = np.zeros(bcap, bool)
    bg = np.full(bcap, n, np.int32)
    if len(tiles) == 0:
        return bp, bm, bg
    run_starts = np.flatnonzero(
        np.concatenate([[True], np.diff(tiles) > 1])
    )
    run_ends = np.append(run_starts[1:], len(tiles))
    slot = 0
    for r0, r1 in zip(run_starts, run_ends):
        t0, t1 = int(tiles[r0]), int(tiles[r1 - 1]) + 1
        a, b = t0 * block, min(t1 * block, n)
        ids_r, rows_r = split.row_span(a, b)
        for j in range(t1 - t0):
            p0 = j * block
            p1 = min(p0 + block, len(ids_r))
            dst = slot * block
            bp[dst:dst + (p1 - p0)] = rows_r[p0:p1]
            bm[dst:dst + (p1 - p0)] = True
            bg[dst:dst + (p1 - p0)] = ids_r[p0:p1]
            slot += 1
    return bp, bm, bg


def _gm_chained_dbscan(
    points, eps, min_samples, *, metric, block, precision, backend,
    pair_budget, merge_rounds, n_ranges, mesh, jobstate=None,
):
    """Chained single-device global-Morton clustering of a streamed
    dataset: contiguous Morton ranges visit ONE device one at a time.

    The composition the 100M single-chip north star runs: the external
    sample-sort supplies per-range rows + global tile boxes; each
    range's slab is the fused layout (segment breaks) plus its exact
    tile-granular boundary cover; two chained passes — owner-computes
    counts (exact core verdicts, host-relayed like
    :func:`sharded._oc_counts_step`), then relay propagation — emit
    the standard occurrence tables, and the collective-free host
    union-find merges them (:func:`sharded._host_merge_finish`'s
    machinery on pre-accumulated (N,) tables).  Labels are
    byte-identical to the mesh global-Morton engine and the fused
    single-device engine (pinned).

    Peak device memory is one range's owned + boundary slab; peak host
    anonymous memory is O(stream chunk + one spill bucket + one range
    slab + (N,) label/core tables).  ``duplicated_work_factor`` is 1.0
    — owned rows cluster exactly once; boundary tiles are columns, not
    clustered rows.

    With a ``jobstate``, each range's propagation tables snapshot at
    the checkpoint cadence (the chained payload); a SIGKILLed fit
    resumes past completed ranges byte-identically.
    """
    import time as _time

    from ..partition import morton_range_split_streaming
    from .merge import merge_occurrences

    n, k = points.shape
    n1 = n + 1
    t_wall = _time.perf_counter()
    split = morton_range_split_streaming(
        points, n_ranges, eps=eps, block=block
    )
    try:
        with obs_span("gm.build", chained=True, ranges=n_ranges):
            plans, plens, sizes = [], [], []
            for s in range(n_ranges):
                plan, plen, _lo, _hi = _stream_range_plan(
                    split, s, block, eps
                )
                plans.append(plan)
                plens.append(plen)
                sizes.append(
                    int(split.starts[s + 1] - split.starts[s])
                )
            cap = round_up(max(plens + [1]), block)
        t_build = _time.perf_counter() - t_wall

        t0 = _time.perf_counter()
        with obs_span("gm.exchange", chained=True):
            tile_sel, brows = _chain_boundary_tiles(
                split, split.starts, block, eps, n, n_ranges
            )
            btiles = max((len(c) for c in tile_sel), default=0)
            bcap = round_up(max(btiles, 1) * block, block)
        t_exchange = _time.perf_counter() - t0

        starts = split.starts
        be = gm_backend(
            backend, metric, cap + bcap, cap, block, k, precision
        )
        from ..utils.hints import dispatch_tag

        hint_key = (
            "gm_chain", dispatch_tag((cap + bcap) // block),
            (n_ranges, cap, k), bcap, block, precision, float(eps),
            metric,
        )
        _note_first_compile(
            "global_morton_chained",
            ((n_ranges, cap, k), bcap, block, precision, be),
        )
        t_exec_cell = [0.0]

        def _range_slab(s):
            ow = np.zeros((cap, k), np.float32)
            om = np.zeros(cap, bool)
            og = np.full(cap, n, np.int32)
            for off, ids, rows in split.iter_range_rows(s):
                tgt = _plan_targets(plans[s], off, len(ids))
                ow[tgt] = rows
                om[tgt] = True
                og[tgt] = ids
            bp, bm, bg = _chain_fill_boundary(
                split, tile_sel[s], bcap, block, n, k
            )
            pts = np.concatenate([ow, bp], axis=0)
            msk = np.concatenate([om, bm])
            return pts, msk, og, bg

        def run_step(pb, _mr, be=be):
            t_exec = _time.perf_counter()
            faults.maybe_fail("gm.execute")
            # Snapshots key by the EFFECTIVE pair budget (the ladder's
            # pb, not the caller's arg): tables computed under a
            # budget that later overflowed must never be replayed.
            budget_tag = int(pb or 0)
            restored = (
                jobstate.chained_restore(budget_tag)
                if jobstate is not None else {}
            )
            if restored:
                obs_event("jobstate_restore", route="gm_chained",
                          partitions=len(restored))
            core_full = np.zeros(n1, bool)
            pstats_rows = []
            t_loop = _time.perf_counter()
            with obs_span("gm.execute", merge="host", chained=True):
                # Pass A: exact owner core verdicts, range by range.
                # Slabs are NOT cached between passes — pass B rebuilds
                # each from spill, keeping peak host memory at ONE
                # range's slab (the whole point of the chained route).
                for s in range(n_ranges):
                    if s in restored:
                        _glab_r, core_r, _ps_r = restored[s]
                        og = _restored_gids(split, plans, s, cap, n)
                        sel = og < n
                        core_full[og[sel]] = core_r[:cap][sel]
                        continue
                    pts, msk, og, bg = _range_slab(s)

                    def one_counts(pts=pts, msk=msk):
                        faults.maybe_fail("gm.chained_range")
                        core, ps = _with_kernel_fallback(
                            lambda b2: _gm_chain_counts(
                                pts, msk, owned=cap, eps=float(eps),
                                min_samples=int(min_samples),
                                metric=metric, block=block,
                                precision=precision, backend=b2,
                                pair_budget=pb,
                            ),
                            be,
                        )
                        return np.asarray(core), np.asarray(ps)

                    core_np, ps = Retrier("gm.chained_range").run(
                        one_counts
                    )
                    pstats_rows.append(ps)
                    sel = og < n
                    core_full[og[sel]] = core_np[sel]
                    del pts, msk, og, bg
                    obs_heartbeat(
                        "gm.chained_counts", s + 1, n_ranges, t_loop
                    )
                # Pass B: relay propagation with global core flags.
                home_label = np.full(n, -1, np.int32)
                halo_gids, halo_labs = [], []
                t_loop2 = _time.perf_counter()
                for s in range(n_ranges):
                    if s in restored:
                        glab_r, _core_r, ps_r = restored[s]
                        og = _restored_gids(split, plans, s, cap, n)
                        bg = _restored_bgids(
                            split, tile_sel[s], bcap, block, n
                        )
                        pstats_rows.append(np.asarray(ps_r))
                    else:
                        pts, msk, og, bg = _range_slab(s)
                        core_all = np.concatenate([
                            core_full[np.clip(og, 0, n)] & (og < n),
                            core_full[np.clip(bg, 0, n)] & (bg < n),
                        ])
                        gid_full = np.concatenate([og, bg])

                        def one_prop(pts=pts, msk=msk,
                                     core_all=core_all,
                                     gid_full=gid_full):
                            faults.maybe_fail("gm.chained_range")
                            glab, ps = _with_kernel_fallback(
                                lambda b2: _gm_chain_propagate(
                                    pts, msk, core_all, gid_full,
                                    owned=cap, eps=float(eps),
                                    metric=metric, block=block,
                                    precision=precision, backend=b2,
                                    pair_budget=pb,
                                ),
                                be,
                            )
                            return np.asarray(glab), np.asarray(ps)

                        glab_r, ps = Retrier("gm.chained_range").run(
                            one_prop
                        )
                        pstats_rows.append(ps)
                        if jobstate is not None and jobstate.due():
                            jobstate.chained_note(
                                s, glab_r,
                                core_full[np.clip(og, 0, n)]
                                & (og < n),
                                ps, budget_tag,
                            )
                    sel = og < n
                    home_label[og[sel]] = glab_r[:cap][sel]
                    hsel = bg < n
                    halo_gids.append(bg[hsel])
                    halo_labs.append(glab_r[cap:][hsel])
                    obs_heartbeat(
                        "gm.chained_propagate", s + 1, n_ranges,
                        t_loop2,
                    )
            t_exec_cell[0] = _time.perf_counter() - t_exec
            pstats = np.stack(pstats_rows) if pstats_rows else (
                np.zeros((1, PAIR_STATS_WIDTH), np.int32)
            )
            out = (home_label, core_full[:n],
                   np.concatenate(halo_gids) if halo_gids
                   else np.empty(0, np.int32),
                   np.concatenate(halo_labs) if halo_labs
                   else np.empty(0, np.int32))
            return out, pstats, True

        (home_label, core, halo_gid, halo_lab), pstats = run_ladders(
            run_step, hint_key, pair_budget, merge_rounds
        )
        t0 = _time.perf_counter()
        with obs_span("gm.merge_host", chained=True):
            labels, _mapping = merge_occurrences(
                home_label, core, halo_gid, halo_lab
            )
        t_merge = _time.perf_counter() - t0

        boundary_rows = int(sum(brows))
        boundary_tiles = int(sum(len(c) for c in tile_sel))
        stats = {
            "owned_cap": cap,
            "n_shard_partitions": n_ranges,
            "pad_waste": float(n_ranges * cap) / max(n, 1) - 1.0,
            "partition_sizes": sizes,
            "input": "stream",
            **split.stats,
            "mode": "global_morton",
            "halo_exchange": "chained_tiles",
            "chained": True,
            "ring_rounds": 0,
            "fixpoint_rounds": 0,
            "merge": "host",
            "boundary_tiles": boundary_tiles,
            "boundary_rows": boundary_rows,
            "sent_tiles": boundary_tiles,
            "boundary_tile_bytes": boundary_tiles * block * k * 4,
            # Host-side tile selection is already box-exact; no ring,
            # so the box twins equal the actuals on this route.
            "sent_tiles_box": boundary_tiles,
            "boundary_bytes_box": boundary_tiles * block * k * 4,
            "boundary_tile_caps": [int(btiles), int(btiles)],
            "exchange_tile": int(block),
            "halo_factor": float(boundary_rows) / max(n, 1),
            "halo_bytes": boundary_tiles * block * k * 4,
            "halo_cap": int(bcap),
            "parity": {
                "starts": [int(x) for x in starts],
                "box_lo": [], "box_hi": [],
            },
            "gm_build_s": round(t_build, 6),
            "gm_exchange_s": round(t_exchange, 6),
            "gm_execute_s": round(t_exec_cell[0], 6),
            "gm_merge_s": round(t_merge, 6),
            # The chained route's "exchange" is host-side tile
            # selection — nothing rides a ring, nothing to hide.
            "exchange_overlap_efficiency": 0.0,
        }
        _exec_stats(stats, oc_on=True, pstats=pstats, block=block,
                    k=k, precision=precision, n=n, metric=metric)
        stats["duplicated_work_factor"] = 1.0
        stats["owner_computes"] = True
        return _canonicalize_roots(labels, core), core, stats
    finally:
        split.close()


def _restored_gids(split, plans, s, cap, n):
    """Replay a restored range's deterministic owned-gid table (the
    spill order is deterministic, so this matches the killed run's)."""
    og = np.full(cap, n, np.int32)
    for off, ids, _rows in split.iter_range_rows(s):
        tgt = _plan_targets(plans[s], off, len(ids))
        og[tgt] = ids
    return og


def _restored_bgids(split, tiles, bcap, block, n):
    """Replay a restored range's boundary-gid table."""
    _bp, _bm, bg = _chain_fill_boundary(
        split, tiles, bcap, block, n, split.k
    )
    return bg


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def global_morton_dbscan(
    points,
    eps: float,
    min_samples: int,
    metric="euclidean",
    block: int = 1024,
    mesh: Optional[Mesh] = None,
    precision: str = "high",
    backend: str = "auto",
    merge: str = "auto",
    pair_budget: Optional[int] = None,
    merge_rounds: int = 32,
    btcap: Optional[int] = None,
    stream: Optional[bool] = None,
    chain: Optional[int] = None,
    jobstate=None,
):
    """Cluster ``points`` over the mesh with zero row duplication.

    Returns ``(labels, core, stats)`` — the same contract as
    :func:`sharded.sharded_dbscan`, with ``stats`` additionally
    carrying ``mode="global_morton"``, ``halo_exchange="morton_ring"``,
    the boundary-tile telemetry (``boundary_tiles`` / ``boundary_rows``
    / ``boundary_tile_bytes`` — the ring's actual duplicated-coordinate
    traffic, the KD route's ``halo_bytes`` analogue), the fixpoint
    round count, and ``duplicated_work_factor == 1.0`` (no point is
    ever counted or clustered on more than one shard; padding is
    ``pad_waste``).  ``stats["parity"]`` holds the shard-assignment
    extras the ``DBSCAN`` surface consumes.

    ``btcap`` caps the per-device boundary-tile SEND buffer (tiles of
    ``block`` rows); None starts at a quarter of the shard's tiles and
    retries on overflow with the exact need (each retry recompiles the
    exchange).  ``merge`` as in :func:`sharded.sharded_dbscan`; the
    device route's fixpoint is host-stepped (spans + convergence
    probe), the host route is the collective-free union-find spill.

    ``stream`` routes the shard build through the external sample-sort
    (:func:`build_morton_shards_streaming`): host RAM stays bounded by
    O(chunk + sample + one shard) instead of one f32 copy + one
    permutation + all slabs.  ``None`` auto-enables it for
    ``np.memmap`` inputs — the memmap dispatch the KD ring route has
    always had, now on the fastest engine.  ``chain`` (or
    ``PYPARDIS_GM_CHAIN``) on a 1-device mesh splits the stream into
    that many Morton ranges chained through the single device
    (:func:`_gm_chained_dbscan`) — the 100M single-chip route; labels
    stay byte-identical to the mesh engine.
    """
    from ..ops.distances import _norm_metric

    metric = _norm_metric(metric)
    validate_params(eps, min_samples)
    if merge not in ("auto", "device", "host"):
        raise ValueError(f"merge must be auto|device|host, got {merge!r}")
    if mesh is None:
        from .mesh import default_mesh

        mesh = default_mesh()
    n_shards = mesh.devices.size
    axis = mesh.axis_names[0]
    # np.asarray would strip the memmap subclass and defeat the
    # streaming auto-dispatch (same guard as DBSCAN._as_array).
    if not isinstance(points, np.memmap):
        points = np.asarray(points)
    n, k = points.shape
    if stream is None:
        stream = isinstance(points, np.memmap)
    if chain is None:
        chain = int(envreg.raw("PYPARDIS_GM_CHAIN", "0") or 0)
    if n_shards == 1 and int(chain) > 1:
        import time as _time

        t0 = _time.perf_counter()
        staging.begin_fit()
        block_c = clamp_block(block, -(-n // int(chain)))
        labels, core, stats = _gm_chained_dbscan(
            points, eps, min_samples, metric=metric, block=block_c,
            precision=precision, backend=backend,
            pair_budget=pair_budget, merge_rounds=merge_rounds,
            n_ranges=int(chain), mesh=mesh, jobstate=jobstate,
        )
        stats["gm_total_s"] = round(_time.perf_counter() - t0, 6)
        return labels, core, stats
    if merge == "auto":
        # Host-RSS pressure (PYPARDIS_RSS_SOFT_LIMIT crossed) takes the
        # host-spill merge preemptively — same rung the degradation
        # ladder would reach after a device-merge OOM, chosen before
        # the replicated (N+1,) arrays are ever allocated.
        from ..obs.resources import memory_pressure

        merge = (
            "host" if n >= MERGE_HOST_AUTO or memory_pressure()
            else "device"
        )
    import time as _time

    block = clamp_block(block, -(-n // max(n_shards, 1)))
    sharding = NamedSharding(mesh, P(axis))
    staging.begin_fit()

    t0 = _time.perf_counter()
    with obs_span("gm.build", stream=bool(stream)):
        builder = (
            build_morton_shards_streaming if stream
            else build_morton_shards
        )
        arrays, bstats, host_bufs, base = builder(
            points, n_shards, block, sharding, eps=eps
        )
    t_build = _time.perf_counter() - t0
    owned, omsk, ogid = arrays
    cap = int(bstats["owned_cap"])

    # ---- exchange/compute overlap (ISSUE 11 tentpole prong 2) ----
    # Boundary tiles are consumed by the propagation/pmin-fixpoint
    # stage; the counts pass needs them only ADDITIVELY (owned rows x
    # boundary columns).  So the owned x owned bulk of the counts pass
    # — the dominant compute — dispatches BEFORE the exchange, the P-1
    # host-stepped ring rounds hide behind it, and the small boundary
    # delta (_gm_counts_delta_step) lands after the exchange; the two
    # sums equal the fused counts bitwise (integer adds commute).  The
    # per-round retry/jobstate machinery is untouched: rounds still
    # run one program at a time with their own probe + Retrier scope.
    from ..utils.budget import pair_overflow as _pair_overflow
    from ..utils.hints import PAIR_BUDGET_HINTS, dispatch_tag
    from ..ops.sketch import sketch_dims

    # Same trace-time env resolution the cluster-step kernels use
    # (metric-gated; 0 below min-d or for non-euclidean): the boundary
    # ring's send-side tightening rides the SAME sketch the kernels
    # run, so the telemetry ratio describes one configuration.
    sk_gm = int(sketch_dims(k, metric))
    owned_kind = resolve_backend(backend, metric, cap, block, k, precision)
    # Overlap needs pair lists for the delta pass: gate on the OWNED
    # slab's dispatch decision (the combined slab is never smaller, so
    # its oc_extract resolves the compacted path whenever this does).
    overlap = (
        envreg.raw("PYPARDIS_GM_OVERLAP", "1") != "0"
        and n_shards > 1
        and (owned_kind == "pallas"
             or pair_dispatch(metric, cap // block))
    )
    counts_np = ostats_np = None
    counts_dev = cstats_dev = None
    counts_ready = [None]
    probe_ok = [True]
    counts_backend = [backend]
    pb_owned = None
    t_counts0 = 0.0
    if overlap:
        okey = (
            "gm_owned", dispatch_tag(cap // block), (n_shards, cap, k),
            block, precision, float(eps), metric,
        )
        pb_env = envreg.raw("PYPARDIS_PAIR_BUDGET")
        pb_owned = (
            int(pb_env) if pb_env
            else (pair_budget if pair_budget is not None
                  else PAIR_BUDGET_HINTS.get(okey))
        )

        def _dispatch_counts(pb, b=None):
            def go(b2):
                counts_backend[0] = b2
                return _gm_owned_counts_step(
                    owned, omsk, eps=float(eps), metric=metric,
                    block=block, mesh=mesh, axis=axis,
                    precision=precision, backend=b2, pair_budget=pb,
                )

            if b is not None:
                return go(b)
            return _with_kernel_fallback(go, backend)

        t_counts0 = _time.perf_counter()
        counts_dev, cstats_dev = _dispatch_counts(pb_owned)

        def _counts_hook():
            # Round-granular completion probe for the overlapped
            # counts: is_ready() never blocks, so the hook costs the
            # ring loop nothing and the hidden-seconds measurement
            # gets a timestamp instead of a post-hoc guess.
            if probe_ok[0] and counts_ready[0] is None:
                try:
                    if counts_dev.is_ready():
                        counts_ready[0] = _time.perf_counter()
                except Exception:  # pragma: no cover — probe only
                    probe_ok[0] = False
    else:

        def _counts_hook():  # pragma: no cover — trivially nothing
            return None

    t0 = _time.perf_counter()
    (bnd, bmsk, bgid), xstats = _gm_boundary_tiles(
        arrays, eps, mesh=mesh, axis=axis, block=block, btcap=btcap,
        base=base, round_hook=_counts_hook if overlap else None,
        sketch=sk_gm,
    )
    t_exchange_raw = _time.perf_counter() - t0
    ring_wall = float(xstats.get("ring_wall_s", 0.0) or 0.0)
    xstats = {k_: v for k_, v in xstats.items() if k_ != "ring_wall_s"}
    t_hidden = 0.0
    overlap_eff = 0.0
    brows = int(bnd.shape[1])
    be = gm_backend(backend, metric, cap + brows, cap, block, k, precision)
    if overlap:
        # The combined slab may route to the other backend (Pallas
        # tile misalignment) — the overlapped counts would then mix
        # kernel arithmetics with the delta pass, so discard them and
        # take the non-overlapped path (labels must be byte-identical
        # to the unoverlapped run, not merely close).
        owned_kind_eff = resolve_backend(
            counts_backend[0], metric, cap, block, k, precision
        )
        comb_kind = resolve_backend(
            be, metric, cap + brows, block, k, precision
        )
        if comb_kind != owned_kind_eff:
            obs_event(
                "gm_overlap_abort", owned=owned_kind_eff,
                combined=comb_kind,
            )
            overlap = False
            counts_dev = cstats_dev = None
    if overlap:

        def _fetch_counts():
            nonlocal counts_dev, cstats_dev
            if counts_dev is None:
                counts_dev, cstats_dev = _dispatch_counts(pb_owned)
            try:
                return (
                    dist.fetch_np(counts_dev), dist.fetch_np(cstats_dev)
                )
            except Exception:
                # A transient execution fault poisons the in-flight
                # arrays — drop them so the retry redispatches.
                counts_dev = cstats_dev = None
                raise

        counts_np, ostats_np = Retrier("gm.owned_counts").run(
            _fetch_counts
        )
        need = _pair_overflow(ostats_np[:, :2])
        if need:
            # The owned-slab extraction overflowed its budget: one
            # exact-total redispatch (not overlapped — the exchange is
            # already done) and seed the owned-geometry hint.
            pb_owned = int(need)
            counts_dev = cstats_dev = None
            counts_np, ostats_np = Retrier("gm.owned_counts").run(
                _fetch_counts
            )
            if _pair_overflow(ostats_np[:, :2]):
                raise RuntimeError(
                    f"global-Morton owned-counts pair budget overflow "
                    f"persisted after an exact-total retry (budget "
                    f"{pb_owned}); pass pair_budget or "
                    f"PYPARDIS_PAIR_BUDGET"
                )
            PAIR_BUDGET_HINTS.put(okey, pb_owned)
        t_done = (
            counts_ready[0] if counts_ready[0] is not None
            else _time.perf_counter()
        )
        t_hidden = max(
            0.0, min(t_done - t_counts0, ring_wall, t_exchange_raw)
        )
        overlap_eff = t_hidden / ring_wall if ring_wall > 1e-9 else 0.0
    t_exchange = max(t_exchange_raw - t_hidden, 0.0)
    hint_key = (
        "gm", dispatch_tag((cap + brows) // block), (n_shards, cap, k),
        brows, block, precision, float(eps), metric,
    )
    _note_first_compile(
        "global_morton",
        (owned.shape, brows, block, precision, be, merge),
    )

    stats = {
        k_: bstats[k_]
        for k_ in ("owned_cap", "n_shard_partitions", "pad_waste",
                   "partition_sizes", "parity", "input",
                   "stream_buckets", "stream_max_bucket_rows",
                   "stream_sample_rows", "spill_bytes")
        if k_ in bstats
    }
    stats.update(xstats)
    stats.update(
        mode="global_morton",
        halo_exchange="morton_ring",
        ring_rounds=max(n_shards - 1, 0),
        halo_factor=float(xstats["boundary_rows"]) / max(n, 1),
        halo_bytes=int(xstats["boundary_tile_bytes"]),
        halo_cap=brows,
    )

    omsk_np = dist.fetch_np(omsk) if overlap else None

    def _overlap_core(pb, b2):
        """Boundary-column delta + threshold: the second half of the
        overlapped counts pass.  Returns ``(core (P, cap) numpy, delta
        stats (P, 4))``.  If the kernel-fallback rung handed us a
        backend other than the one that produced the overlapped owned
        counts, recompute them synchronously with ``b2`` — summing
        counts from two kernel arithmetics would break byte parity
        with the non-overlapped run."""
        c_np = counts_np
        if b2 != counts_backend[0]:
            cdev, _sdev = _dispatch_counts(pb_owned, b=b2)
            c_np = dist.fetch_np(cdev)
        delta_dev, dstats_dev = _gm_counts_delta_step(
            owned, omsk, bnd, bmsk, eps=float(eps), metric=metric,
            block=block, mesh=mesh, axis=axis, precision=precision,
            backend=b2, pair_budget=pb,
        )
        dstats = dist.fetch_np(dstats_dev)
        total = c_np + dist.fetch_np(delta_dev)
        # Same self-count clamp as the fused counts pass: a valid
        # point is always within eps of itself.
        core_np = (np.maximum(total, 1) >= int(min_samples)) & omsk_np
        return core_np, dstats

    def _fold_overlap_stats(pstats, dstats):
        """Fold the overlapped counts passes into the propagate
        program's (P, 5) rows: band columns add (owned + delta ARE the
        counts pass), one extra kernel pass is accounted, and the
        delta rows ride along so the ladder's overflow check covers
        the combined-slab delta extraction too (same budget family as
        the propagate rows; the owned-slab pass has its own pre-ladder
        exact retry, so its larger/smaller budget never muddies the
        max-total-vs-max-budget check)."""
        pstats = np.array(dist.fetch_np(pstats), dtype=np.int64)
        pstats = pstats.reshape(-1, pstats.shape[-1])
        if dstats is None:
            return pstats
        pstats[:, 3:5] += ostats_np[:, 2:4] + dstats[:, 2:4]
        pstats[:, 2] += 1
        extra = np.zeros((dstats.shape[0], pstats.shape[1]), np.int64)
        extra[:, :2] = dstats[:, :2]
        return np.vstack([pstats, extra])

    if merge == "host":

        def run_step(pb, _mr):
            faults.maybe_fail("gm.execute")

            def go(b2):
                if overlap:
                    core_np, dstats = _overlap_core(pb, b2)
                    out = _oc_host_tables(
                        (owned, omsk, ogid, bnd, bmsk, bgid),
                        eps=eps, min_samples=min_samples, metric=metric,
                        block=block, mesh=mesh, axis=axis, n_points=n,
                        precision=precision, backend=b2, pair_budget=pb,
                        own_core=core_np,
                    )
                    return out, dstats
                out = _oc_host_tables(
                    (owned, omsk, ogid, bnd, bmsk, bgid),
                    eps=eps, min_samples=min_samples, metric=metric,
                    block=block, mesh=mesh, axis=axis, n_points=n,
                    precision=precision, backend=b2, pair_budget=pb,
                )
                return out, None

            out, dstats = _with_kernel_fallback(go, be)
            # The host union-find merge is exact — no rounds ladder.
            return out[:3], _fold_overlap_stats(out[3], dstats), True

        t0 = _time.perf_counter()
        with obs_span("gm.execute", merge="host"):
            (own_glab, own_core, halo_glab), pstats = run_ladders(
                run_step, hint_key, pair_budget, merge_rounds
            )
        t_execute = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        with obs_span("gm.merge_host"):
            labels, core = _host_merge_finish(
                n, ogid, own_glab, own_core, bgid, halo_glab
            )
        t_merge = _time.perf_counter() - t0
        stats.update(merge="host", fixpoint_rounds=0)
    else:
        rounds_cell = [0]
        merge_s_cell = [0.0]

        def run_step(pb, mr):
            faults.maybe_fail("gm.execute")

            def go(b2):
                if overlap:
                    core_np, dstats = _overlap_core(pb, b2)
                    out = _gm_cluster_step(
                        owned, omsk, ogid, bnd, bmsk, bgid,
                        # graftlint: disable=device-put-aliasing -- fresh _overlap_core host array
                        jax.device_put(core_np, sharding),
                        eps=float(eps), min_samples=int(min_samples),
                        metric=metric, block=block, mesh=mesh,
                        axis=axis, n_points=n, precision=precision,
                        backend=b2, pair_budget=pb,
                    )
                    return out, dstats
                out = _gm_cluster_step(
                    owned, omsk, ogid, bnd, bmsk, bgid,
                    eps=float(eps), min_samples=int(min_samples),
                    metric=metric, block=block, mesh=mesh, axis=axis,
                    n_points=n, precision=precision, backend=b2,
                    pair_budget=pb,
                )
                return out, None

            (home_label, core_g, b_glab, pstats), dstats = (
                _with_kernel_fallback(go, be)
            )
            pstats = _fold_overlap_stats(pstats, dstats)
            t_fix = _time.perf_counter()
            with obs_span("gm.fixpoint") as sp:
                lab_map, rounds, converged = _gm_fixpoint(
                    home_label, core_g, bgid, b_glab, mesh=mesh,
                    axis=axis, n_points=n, merge_rounds=mr,
                    jobstate=jobstate, budget_tag=int(pb or 0),
                )
                sp.set(rounds=rounds, converged=converged)
            merge_s_cell[0] = _time.perf_counter() - t_fix
            rounds_cell[0] = rounds
            return (home_label, core_g, lab_map), pstats, converged

        t0 = _time.perf_counter()
        with obs_span("gm.execute", merge="device"):
            try:
                (home_label, core_g, lab_map), pstats = run_ladders(
                    run_step, hint_key, pair_budget, merge_rounds
                )
            except Exception as e:  # noqa: BLE001 — rethrown below
                if not is_degradable_error(e):
                    raise
                # Degradation rung: the device merge's replicated
                # (N+1,) arrays are this mode's hungriest allocation —
                # rerun with the collective-free host union-find spill
                # (pinned byte-identical).
                note_degraded(
                    "merge_host", mode="global_morton",
                    error=str(e)[:160],
                )
                staging.give_back_after_put(host_bufs)
                return global_morton_dbscan(
                    points, eps=eps, min_samples=min_samples,
                    metric=metric, block=block, mesh=mesh,
                    precision=precision, backend=backend, merge="host",
                    pair_budget=pair_budget, merge_rounds=merge_rounds,
                    btcap=btcap, stream=stream, chain=chain,
                    jobstate=jobstate,
                )
        t_merge = merge_s_cell[0]
        t_execute = _time.perf_counter() - t0 - t_merge
        lab_np = np.asarray(lab_map)
        home_np = np.asarray(home_label)
        final = np.where(
            home_np >= 0, lab_np[np.clip(home_np, 0, n)], -1
        )
        labels = np.where(final == _INT32_MAX, -1, final).astype(
            np.int32
        )[:n]
        core = np.asarray(core_g)[:n]
        stats.update(
            merge="device", merge_rounds=int(rounds_cell[0]),
            merge_converged=True, fixpoint_rounds=int(rounds_cell[0]),
        )

    # Build / exchange / compute / merge decomposition (the north-star
    # artifact row's columns; surfaced as report() phases).  Overlap
    # accounting: the ring seconds that ran concurrently with the
    # owned-prefix counts pass (t_hidden) are attributed to COMPUTE —
    # the device was making counts progress through that window — and
    # removed from the exchange phase, so the four phases still sum to
    # ~wall and "exchange hides behind compute" is a measured split,
    # not a narrative.  exchange_overlap_efficiency = hidden ring
    # seconds / total ring seconds (0.0 with overlap off, on warm
    # cached exchanges, and on every non-GM route).
    stats.update(
        gm_build_s=round(t_build, 6),
        gm_exchange_s=round(t_exchange, 6),
        gm_execute_s=round(max(t_execute, 0.0) + t_hidden, 6),
        gm_merge_s=round(t_merge, 6),
        exchange_overlap_efficiency=round(float(overlap_eff), 6),
    )
    _exec_stats(stats, oc_on=True, pstats=pstats, block=block, k=k,
                precision=precision, n=n, metric=metric)
    # Zero duplicated ROWS by construction: every point is neighbor-
    # counted and clustered exactly once, on its owning shard (the KD
    # gauge counts clustered slots, whose cap is the LARGEST partition;
    # here ranges are equal and padding is already pad_waste).
    stats["duplicated_work_factor"] = 1.0
    stats["owner_computes"] = True
    staging.give_back_after_put(host_bufs)
    return _canonicalize_roots(labels, core), core, stats


def sweep_graph_global_morton(
    points,
    eps,
    *,
    block: int = 1024,
    mesh: Optional[Mesh] = None,
    precision: str = "high",
    backend: str = "auto",
    metric: str = "euclidean",
    btcap: Optional[int] = None,
    edge_budget: Optional[int] = None,
    pair_budget: Optional[int] = None,
    cap_edges: Optional[int] = None,
):
    """ONE distance pass at ``eps`` (the sweep's eps_max) over the
    global-Morton shards → the GLOBAL neighbor-pair graph.

    Rides the real GM machinery: the range build reuses the eps-free
    ``gm_owned`` staging route (a sweep after a fit re-stages nothing)
    and the boundary tiles ride the morton ring at eps_max
    (:func:`_gm_boundary_tiles`, route ``gm_boundary``) — selected at
    the sweep ceiling, so every smaller config's reach set is covered
    by construction (a tile within eps_c of a shard's rows is within
    eps_max of them).  Owned rows emit, boundary slots are column
    evidence only: zero duplicated rows, each directed edge emitted
    exactly once by its owner.

    Returns ``((gi, gj, dval) numpy arrays in global-id space,
    stats)`` with the GM telemetry contract fields
    (``halo_exchange="morton_ring"``, boundary-tile gauges,
    ``duplicated_work_factor == 1.0``).
    """
    from ..ops.distances import sweep_max_edges
    from .sharded import _sweep_slab_graph

    points = np.asarray(points)
    n, k = points.shape
    if mesh is None:
        from .mesh import default_mesh

        mesh = default_mesh()
    n_shards = mesh.devices.size
    axis = mesh.axis_names[0]
    sharding = NamedSharding(mesh, P(axis))
    block = clamp_block(block, -(-n // max(n_shards, 1)))
    if cap_edges is None:
        cap_edges = sweep_max_edges()
    with obs_span("sweep.build", mode="global_morton"):
        arrays, bstats, host_bufs, base = build_morton_shards(
            points, n_shards, block, sharding, eps=eps
        )
    owned, omsk, ogid = arrays
    cap = int(bstats["owned_cap"])
    if n_shards > 1:
        with obs_span("sweep.exchange", mode="global_morton"):
            (bnd, bmsk, bgid), xstats = _gm_boundary_tiles(
                arrays, eps, mesh=mesh, axis=axis, block=block,
                btcap=btcap, base=base,
            )
        brows = int(bnd.shape[1])
        if brows % block:
            raise AssertionError(
                f"boundary rows {brows} not a multiple of block {block}"
            )
    else:
        bnd = bmsk = bgid = None
        brows = 0
        xstats = {
            "boundary_tiles": 0, "boundary_rows": 0,
            "boundary_tile_bytes": 0, "ring_rounds": 0,
        }
    out_i, out_j, out_d = [], [], []
    eb, pb = edge_budget, pair_budget
    # One host gather per slab family — per-shard indexing of the
    # mesh-sharded arrays would dispatch a collective program per
    # slice (see sweep_graph_sharded).
    owned_h, omsk_h, ogid_h = (dist.fetch_np(a) for a in arrays)
    if brows:
        bnd_h, bmsk_h, bgid_h = (
            dist.fetch_np(bnd), dist.fetch_np(bmsk), dist.fetch_np(bgid)
        )
    with obs_span("sweep.extract", mode="global_morton",
                  shards=int(n_shards)):
        for s in range(n_shards):
            if brows:
                pts = np.concatenate([owned_h[s], bnd_h[s]], axis=0)
                msk = np.concatenate([omsk_h[s], bmsk_h[s]])
                gids = np.concatenate([ogid_h[s], bgid_h[s]])
            else:
                pts, msk = owned_h[s], omsk_h[s]
                gids = ogid_h[s]
            gi, gj, dv, eb, pb = _sweep_slab_graph(
                pts, msk, gids, eps, owned_rows=cap, metric=metric,
                block=block, precision=precision, edge_budget=eb,
                pair_budget=pb, cap_edges=cap_edges,
            )
            out_i.append(gi)
            out_j.append(gj)
            out_d.append(dv)
    staging.give_back_after_put(host_bufs)
    gi = np.concatenate(out_i) if out_i else np.empty(0, np.int32)
    gj = np.concatenate(out_j) if out_j else np.empty(0, np.int32)
    dv = np.concatenate(out_d) if out_d else np.empty(0, np.float32)
    stats = {
        "mode": "global_morton",
        "halo_exchange": "morton_ring",
        "owner_computes": True,
        "duplicated_work_factor": 1.0,
        "graph_pairs": int(len(gi)),
        "graph_bytes": int(len(gi)) * 12,
        "n_partitions": int(n_shards),
        **{
            k_: bstats[k_]
            for k_ in (
                "owned_cap", "pad_waste", "partition_sizes",
                "n_shard_partitions",
            )
            if k_ in bstats
        },
        **{
            k_: xstats[k_]
            for k_ in (
                "boundary_tiles", "boundary_rows",
                "boundary_tile_bytes", "ring_rounds",
            )
            if k_ in xstats
        },
    }
    return (gi, gj, dv), stats
