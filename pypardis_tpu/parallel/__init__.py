"""Device-mesh distribution: sharded clustering, halo exchange, label merge.

This subpackage is the TPU-native replacement for the reference's entire
Spark layer (``/root/reference/dbscan/dbscan.py:104-165`` +
``partition.py``'s RDD orchestration): points shard over a
``jax.sharding.Mesh`` by KD partition, the 2*eps halo duplication
(dbscan.py:141-151) becomes padded halo slabs fed to each shard, and the
driver-side label aggregation (dbscan.py:158-161 — the reference's
documented scalability bottleneck, README.md:60) becomes an in-graph
scatter-min label propagation combined across the mesh with ``pmin``
collectives.  One jit, no host round-trips.
"""

from .mesh import default_mesh
from .sharded import sharded_dbscan, sharded_dbscan_device


def global_morton_dbscan(*args, **kwargs):
    """Lazy re-export of the zero-duplication global-Morton engine
    (:func:`pypardis_tpu.parallel.global_morton.global_morton_dbscan`)."""
    from .global_morton import global_morton_dbscan as _gm

    return _gm(*args, **kwargs)


__all__ = [
    "default_mesh",
    "global_morton_dbscan",
    "sharded_dbscan",
    "sharded_dbscan_device",
]
