"""Multi-process execution layer (``jax.distributed``).

One fit spanning N controller processes — the pod-scale seam ROADMAP
item 4 names.  Every process runs the identical host-side Python
(multi-controller SPMD): the same partition, the same per-round
host-stepped loops (the GM boundary ring, the pmin merge fixpoint),
the same jitted ``shard_map`` programs — only now over a mesh built
from EVERY process's devices, so ``ppermute`` rounds and the
convergence all-reduce span processes with no new ladder machinery.

The contract that keeps this safe to land from a CPU container: a
P-process fit is **byte-identical** to the single-process fit with the
same total device count.  The only code that may observe the process
boundary is here:

* :func:`init_distributed` — ``jax.distributed.initialize`` driven by
  the registered ``PYPARDIS_DIST_*`` knobs (CI: N localhost processes
  x ``--xla_force_host_platform_device_count`` faked CPU devices each,
  gloo TCP collectives, coordinator on an ephemeral port).
* :func:`fetch_np` — the one sanctioned device→host fetch for driver
  code.  Single-process (and fully-replicated arrays anywhere) it is
  exactly the historical ``np.asarray``; a ``P("p")``-sharded array in
  a multi-process fit is allgathered so every process sees the same
  full value and the host-side control flow cannot diverge.
* :func:`touch` — the tiny-slice dispatch-fence idiom
  (``np.asarray(x[:1])``) generalized: slicing a non-addressable array
  is illegal, so multi-process fences via ``block_until_ready``.
* :func:`broadcast_bytes` / :func:`broadcast_arrays` — process-0
  rendezvous for host-side decisions (the streaming build's splitter
  keys and spill-dir name — the NOWSort broadcast).
* :func:`launch_fleet` — the localhost subprocess launcher the tests
  and ``scripts/multihost_probe.py`` share: ephemeral coordinator
  port with bind-collision retry, whole-fleet teardown when any
  worker dies (surviving workers would otherwise block forever in a
  collective).

Single-process fits never pay for any of this: every helper's first
branch is a ``process_count() == 1`` check against a cached count.
"""

from __future__ import annotations

import io
import os
import socket
import subprocess
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils import envreg

# Resolved once jax.distributed is (maybe) initialized; cached so the
# hot-path helpers don't re-enter jax.process_count() per fetch.
_PROCESS_COUNT: Optional[int] = None
_INITIALIZED = False


def init_distributed(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join a multi-process fleet; returns True when distributed.

    Arguments fall back to the registered env knobs
    (``PYPARDIS_DIST_COORD`` / ``_NPROCS`` / ``_PROC_ID``), so a worker
    launched by :func:`launch_fleet` needs only
    ``init_distributed()`` before its first jax use.  With no
    coordinator configured this is a no-op returning False — the
    single-process path.  Idempotent.
    """
    global _PROCESS_COUNT, _INITIALIZED
    if _INITIALIZED:
        return True
    coord = coordinator or envreg.raw("PYPARDIS_DIST_COORD")
    nprocs = num_processes
    if nprocs is None:
        env = envreg.raw("PYPARDIS_DIST_NPROCS")
        nprocs = int(env) if env else None
    pid = process_id
    if pid is None:
        env = envreg.raw("PYPARDIS_DIST_PROC_ID")
        pid = int(env) if env not in (None, "") else None
    if not coord or not nprocs or nprocs < 2 or pid is None:
        return False
    import jax

    # CPU fleets need a real inter-process transport; gloo-over-TCP is
    # the jaxlib one.  Guarded: the option only exists on jax versions
    # that split it out, and TPU pods use their native interconnect.
    if "jax_cpu_collectives_implementation" in getattr(
        jax.config, "_value_holders", {}
    ):
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(nprocs),
        process_id=int(pid),
    )
    _INITIALIZED = True
    _PROCESS_COUNT = None  # re-resolve below
    return True


def process_count() -> int:
    """Processes in the fleet (1 on the single-process path), cached."""
    global _PROCESS_COUNT
    if _PROCESS_COUNT is None:
        import jax

        _PROCESS_COUNT = int(jax.process_count())
    return _PROCESS_COUNT


def process_index() -> int:
    """This process's rank in [0, process_count())."""
    if process_count() == 1:
        return 0
    import jax

    return int(jax.process_index())


def is_distributed() -> bool:
    return process_count() > 1


def is_coordinator() -> bool:
    """Process 0: the one that writes shared state (jobstate
    snapshots, spill-dir creation) for the whole fleet."""
    return process_index() == 0


def fetch_np(x) -> np.ndarray:
    """Device→host fetch that every process can trust.

    Single-process: exactly ``np.asarray(x)`` (byte-identical to the
    historical fetch — the zero-overhead contract).  Multi-process: a
    fully-replicated array (the ``out_specs=P()`` convergence probes,
    final label maps) is addressable everywhere and fetches directly;
    a ``P("p")``-sharded array is allgathered (tiled) so the host sees
    the same FULL value on every process — per-round capacity plans,
    overflow flags, and pair stats must drive identical host control
    flow fleet-wide or the lockstep trace diverges.
    """
    if process_count() == 1:
        return np.asarray(x)
    import jax

    if not isinstance(x, jax.Array) or x.is_fully_replicated:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def touch(x) -> None:
    """Dispatch fence: make sure ``x``'s computation has been enqueued
    (single-process keeps the historical tiny-slice fetch; slicing a
    non-addressable multi-process array is illegal, so the fleet path
    blocks on readiness instead)."""
    if process_count() == 1:
        np.asarray(x[(slice(0, 1),) * getattr(x, "ndim", 1)])
        return
    x.block_until_ready()


def barrier(tag: str) -> None:
    """Fleet-wide rendezvous (no-op single-process).  The streaming
    build's pass boundaries and spill-dir teardown use it."""
    if process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)


def broadcast_bytes(data: Optional[bytes]) -> bytes:
    """Process 0's byte string, on every process.

    Rides int32 device arrays (``broadcast_one_to_all`` widens narrow
    integer dtypes, and 64-bit dtypes are unsafe without x64), length
    first so shapes agree fleet-wide.  Non-coordinators may pass
    ``None``/``b""``.
    """
    if process_count() == 1:
        return data or b""
    from jax.experimental import multihost_utils

    head = np.zeros((1,), np.int32)
    if is_coordinator():
        head[0] = len(data or b"")
    n = int(np.asarray(multihost_utils.broadcast_one_to_all(head))[0])
    pad = (-n) % 4
    words = max((n + pad) // 4, 1)
    buf = np.zeros((words,), np.int32)
    if is_coordinator() and n:
        buf = np.frombuffer(
            (data or b"") + b"\0" * pad, np.int32
        ).copy()
    out = np.asarray(
        multihost_utils.broadcast_one_to_all(buf), np.int32
    )
    return out.tobytes()[:n]


def broadcast_str(s: Optional[str]) -> str:
    """Process 0's string, everywhere (spill-dir rendezvous)."""
    if process_count() == 1:
        return s or ""
    payload = (s or "").encode("utf-8") if is_coordinator() else None
    return broadcast_bytes(payload).decode("utf-8")


def broadcast_arrays(arrays) -> List[np.ndarray]:
    """Process 0's numpy arrays, everywhere — dtype and shape ride in
    the payload (npz), so uint64 Morton words and float32 centers
    cross intact.  Non-coordinators may pass ``None``.
    """
    if process_count() == 1:
        return [np.asarray(a) for a in arrays]
    payload = None
    if is_coordinator():
        bio = io.BytesIO()
        np.savez(
            bio, **{f"a{i}": np.asarray(a) for i, a in enumerate(arrays)}
        )
        payload = bio.getvalue()
    blob = broadcast_bytes(payload)
    with np.load(io.BytesIO(blob)) as z:
        return [z[f"a{i}"] for i in range(len(z.files))]


# ---------------------------------------------------------------------------
# Localhost fleet launcher (tests + scripts/multihost_probe.py)
# ---------------------------------------------------------------------------


def pick_port() -> int:
    """An ephemeral localhost TCP port (bind-probe; racy by nature,
    which is why :func:`launch_fleet` retries bind collisions)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.bind(("127.0.0.1", 0))
        return int(s.getsockname()[1])
    finally:
        s.close()


_BIND_ERR_MARKERS = (
    "address already in use",
    "Address already in use",
    "Failed to bind",
    "bind failed",
    "UNKNOWN: Could not start",
)

# gloo's TCP transport can abort the whole process (SIGABRT, C++
# uncaught EnforceNotMet) on transient wire trouble — e.g. another
# fleet's lingering sockets during CI churn.  A relaunch on a fresh
# coordinator port rebuilds every pair from scratch.
_TRANSPORT_ERR_MARKERS = (
    "gloo::EnforceNotMet",
    "Connection reset by peer",
    "Connection refused",
)


def _looks_like_bind_collision(text: str) -> bool:
    return any(m in (text or "") for m in _BIND_ERR_MARKERS)


def _looks_like_transport_abort(rcs, tails) -> bool:
    """A worker died on gloo transport trouble (not a Python error, not
    a kill): SIGABRT plus a transport marker in its stderr."""
    return any(
        rc == -6 and any(m in (t or "") for m in _TRANSPORT_ERR_MARKERS)
        for rc, t in zip(rcs, tails)
    )


def fleet_env(
    port: int, num_processes: int, process_id: int,
    devices_per_process: int, base: Optional[dict] = None,
) -> dict:
    """The env one worker needs: coordinator knobs + the faked-device
    CPU platform (mirrors the test harness's conftest idiom)."""
    env = dict(base if base is not None else os.environ)
    env["PYPARDIS_DIST_COORD"] = f"127.0.0.1:{port}"
    env["PYPARDIS_DIST_NPROCS"] = str(num_processes)
    env["PYPARDIS_DIST_PROC_ID"] = str(process_id)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices_per_process}"
    )
    return env


def launch_fleet(
    argv: Sequence[str],
    num_processes: int,
    devices_per_process: int,
    *,
    env: Optional[dict] = None,
    port: Optional[int] = None,
    timeout_s: float = 900.0,
    retries: int = 3,
    stderr_tail: int = 4096,
) -> Tuple[List[int], int, int, List[str]]:
    """Run ``argv`` as ``num_processes`` lockstep workers on localhost.

    Returns ``(returncodes, port, attempts, stderr_tails)``.  Each
    worker gets :func:`fleet_env`; a coordinator-port bind collision
    (another service grabbed the ephemeral port between probe and
    ``jax.distributed.initialize``) tears the fleet down and retries on
    a fresh port — up to ``retries`` times.  Any worker dying for a
    non-bind reason also tears the whole fleet down (survivors block
    forever inside collectives otherwise) and reports its real exit
    codes; timeouts kill and report -9.
    """
    attempts = 0
    while True:
        attempts += 1
        use_port = port if port is not None else pick_port()
        procs = []
        errfiles = []
        import tempfile

        for pid in range(num_processes):
            ef = tempfile.TemporaryFile(mode="w+")
            errfiles.append(ef)
            procs.append(
                subprocess.Popen(
                    list(argv),
                    env=fleet_env(
                        use_port, num_processes, pid,
                        devices_per_process, base=env,
                    ),
                    stderr=ef,
                )
            )
        deadline = time.time() + timeout_s
        rcs: List[Optional[int]] = [None] * num_processes
        while time.time() < deadline:
            for i, p in enumerate(procs):
                if rcs[i] is None:
                    rcs[i] = p.poll()
            if not any(rc is None for rc in rcs):
                break
            if any(rc not in (None, 0) for rc in rcs):
                break  # early failure: tear the survivors down
            time.sleep(0.05)
        for p in procs:  # teardown: timeout or early failure
            if p.poll() is None:
                p.kill()
        for i, p in enumerate(procs):
            p.wait()
            rcs[i] = p.returncode
        tails = []
        for ef in errfiles:
            ef.seek(0, os.SEEK_END)
            size = ef.tell()
            ef.seek(max(0, size - stderr_tail))
            tails.append(ef.read())
            ef.close()
        # Retry on the failure *signature*, not on an early-failure
        # flag: when every rank aborts inside one poll window (gloo
        # tears down both ends of a broken pair at once) the loop
        # exits via the nobody-live branch, which must retry too.
        if port is None and attempts <= retries and any(
            rc != 0 for rc in rcs
        ) and (
            any(
                _looks_like_bind_collision(t)
                for rc, t in zip(rcs, tails)
                if rc not in (0, None)
            )
            or _looks_like_transport_abort(rcs, tails)
        ):
            continue  # fresh ephemeral port next round
        return [int(rc) for rc in rcs], use_port, attempts, tails
