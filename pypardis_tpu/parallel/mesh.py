"""Mesh construction helpers + shard_map version compat."""

from __future__ import annotations

import jax
from jax.sharding import Mesh


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` across jax versions.

    jax >= 0.6 exposes it at the top level with ``check_vma``; 0.4.x
    only has ``jax.experimental.shard_map.shard_map`` with the older
    ``check_rep`` spelling of the same flag.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=bool(check_vma),
    )


def default_mesh(n_devices: int | None = None, axis_name: str = "p") -> Mesh:
    """A 1-D mesh over the first ``n_devices`` visible devices.

    Spatial data parallelism with halo overlap — the reference's one
    distribution strategy (SURVEY §2) — needs a single mesh axis; the
    KD-partition → device mapping rides on it.

    Multi-process fleets (``parallel.dist.init_distributed``) need no
    variant: after ``jax.distributed.initialize``, ``jax.devices()``
    is the GLOBAL device list in a process-count-independent order, so
    the same 1-D mesh spans every process's chips and ``ppermute``
    rings / ``psum`` probes cross the process boundary transparently.
    Host code must then fetch sharded arrays through
    ``dist.fetch_np`` (a local ``np.asarray`` of a non-addressable
    array is illegal and would diverge the lockstep trace).
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(devices, (axis_name,))
