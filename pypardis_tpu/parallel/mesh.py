"""Mesh construction helpers."""

from __future__ import annotations

import jax
from jax.sharding import Mesh


def default_mesh(n_devices: int | None = None, axis_name: str = "p") -> Mesh:
    """A 1-D mesh over the first ``n_devices`` visible devices.

    Spatial data parallelism with halo overlap — the reference's one
    distribution strategy (SURVEY §2) — needs a single mesh axis; the
    KD-partition → device mapping rides on it.
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(devices, (axis_name,))
