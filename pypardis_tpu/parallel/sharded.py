"""Sharded DBSCAN: per-shard clustering + in-graph global label merge.

Replaces the reference pipeline stages 2-5 (SURVEY §3.1; reference
``dbscan/dbscan.py:114-165``):

* neighborhood duplication (dbscan.py:136-151) → fixed-capacity halo
  slabs per KD partition, built host-side from one vectorized box
  membership query;
* ``partitionBy`` shuffle (dbscan.py:116-118) → arrays whose leading
  (partition) axis is sharded over the device mesh;
* per-partition sklearn DBSCAN (dbscan.py:12-34) → the tiled
  min-propagation kernel (:mod:`pypardis_tpu.ops`), vmapped over each
  device's partitions;
* driver-side ``ClusterAggregator`` merge + broadcast (dbscan.py:158-161,
  the README.md:60 driver-memory bottleneck) → scatter-min label
  propagation over a bipartite point<->cluster graph, combined across the
  mesh with ``pmin`` — merge happens on device, inside the same jit.

Merge semantics match the reference's rules: only points that are core
in their *home* partition link clusters (aggregator.py:38-40 — non-core
border points must not cause merges), and merged clusters take the
minimum id (aggregator.py:45 — here, the minimum root point id).

Why the 2*eps halo makes home-run results exact (reference README.md:20):
every point within eps of a partition's box has its full eps-ball inside
the box expanded by 2*eps, so owned points' core status, cluster
connectivity, and border attachment are all decided correctly in the
home run; cross-partition links are recovered from halo duplicates that
are core somewhere.

Owner-computes (default): the halo slabs are EVIDENCE, not work.  The
reference re-clusters every duplicated point inside every foreign
partition; the default step here (``_device_cluster_merge_oc``)
neighbor-counts owned rows only, takes halo core flags from each
point's OWNER, and lets halo slots merely relay labels between the
owned clusters they touch — cutting per-device clustered volume from
``owned * (1 + halo_factor)`` (3.16x at the r5 geometry) to ``owned``
(``stats["duplicated_work_factor"]``), with byte-identical labels.
``owner_computes=False`` keeps the legacy step for A/B comparison; the
1-device chained path always runs legacy.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..geometry import BoxStack
from ..obs import (
    event as obs_event,
    heartbeat as obs_heartbeat,
    span as obs_span,
)
from ..ops.labels import (
    dbscan_fixed_size,
    oc_counts_banded,
    oc_extract,
    oc_propagate_banded,
)
from ..partition import spatial_order
from ..utils import clamp_block, envreg, faults, round_up
from ..utils.budget import run_ladders
from ..utils.retry import Retrier, is_degradable_error, note_degraded
from . import dist, staging
from .halo import ring_halo_exchange_multi
from .mesh import shard_map

_INT_INF = jnp.iinfo(jnp.int32).max


def _expanded_frame_meta(points, partitioner, eps):
    """The recentred float32 frame shared by every halo path — metadata
    only, never a full recentred copy of the dataset.

    Returns (center, exp_lo, exp_hi, labels): the float64 dataset mean
    and each sorted partition's 2*eps-expanded box recentred on it.
    All halo membership decisions — host box query and device-side ring
    filter — must evaluate in exactly these numbers so borderline
    points land identically everywhere.

    Boundary tolerance: membership is evaluated in float32, so a point
    the reference's float64 filter would include could sit one f32 ULP
    outside the expanded box after recentring/rounding.  The expanded
    bounds are therefore widened by 4 ULPs of their own magnitude —
    covering the recentring rounding error while staying ~1e-6-relative,
    far below any meaningful eps.
    """
    points = np.asarray(points)
    center = points.mean(axis=0, dtype=np.float64)
    labels = sorted(partitioner.partitions)
    stack = BoxStack.from_boxes(
        partitioner.bounding_boxes[l] for l in labels
    )
    exp = stack.expand(2 * eps)
    exp_lo = (exp.lower - center).astype(np.float32)
    exp_hi = (exp.upper - center).astype(np.float32)
    ulp_lo = np.spacing(np.abs(exp_lo), dtype=np.float32)
    ulp_hi = np.spacing(np.abs(exp_hi), dtype=np.float32)
    exp_lo = exp_lo - 4 * ulp_lo
    exp_hi = exp_hi + 4 * ulp_hi
    return center, exp_lo, exp_hi, labels


def _recentre_rows(points, idx, center, chunk: int = 1 << 20):
    """(points[idx] - center) as float32, chunked.

    The round-3 layout recentred the WHOLE dataset up front and then
    gathered slabs from the copy — holding input + full f32 copy +
    owned slabs + halo slabs simultaneously (~3x the dataset in host
    RAM at the 100M north star).  Gathering per partition bounds the
    extra footprint at one partition's rows; chunking bounds the f64
    subtraction temp at O(chunk * k) regardless of partition size.
    """
    sub = np.empty((len(idx), points.shape[1]), np.float32)
    for s in range(0, len(idx), chunk):
        e = min(s + chunk, len(idx))
        np.subtract(
            points[idx[s:e]], center, out=sub[s:e], casting="unsafe"
        )
    return sub


def _fill_slab(slab, mask, gid, j, points, idx, center):
    """Morton-sort partition ``idx`` in the recentred f32 frame and
    write it into row ``j`` of the (P, cap, ...) slab arrays.  Returns
    the sorted index array."""
    if len(idx):
        sub = _recentre_rows(points, idx, center)
        order = spatial_order(sub)
        idx = idx[order]
        slab[j, : len(idx)] = sub[order]
    mask[j, : len(idx)] = True
    gid[j, : len(idx)] = idx
    return idx


def _layout_geometry(partitioner, labels, n_shards, block):
    """Shared shard-layout shape math: (p_real, p_total, part_idx, cap).
    One definition keeps the in-RAM and streaming builds byte-identical
    (tests pin it)."""
    p_real = len(labels)
    p_total = round_up(max(p_real, n_shards), n_shards)
    part_idx = [partitioner.partitions[l] for l in labels]
    cap = round_up(max(len(i) for i in part_idx), block)
    return p_real, p_total, part_idx, cap


def _partition_sizes(part_idx, p_total):
    """Per-shard-slot point counts, padding slots as zeros — the
    telemetry behind the report's per-device partition sizes (slot j
    lives on device ``j // (p_total / n_devices)``)."""
    sizes = [int(len(i)) for i in part_idx]
    return sizes + [0] * (p_total - len(sizes))


def _pad_inverted_boxes(exp_lo, exp_hi, p_total):
    """Pad expanded-box stacks to ``p_total`` with inverted (lo > hi)
    boxes: padding partitions' ring filters match nothing."""
    pad = p_total - exp_lo.shape[0]
    if pad > 0:
        k = exp_lo.shape[1]
        exp_lo = np.concatenate(
            [exp_lo, np.full((pad, k), np.float32(3e38))]
        )
        exp_hi = np.concatenate(
            [exp_hi, np.full((pad, k), np.float32(-3e38))]
        )
    return exp_lo, exp_hi


def _alloc_filled(shape, dtype, fill):
    a = np.empty(shape, dtype)
    a.fill(fill)
    return a


def _staged_alloc(bufs: list):
    """An allocator drawing from the staging pool; every handed-out
    buffer lands in ``bufs`` so the caller can ``give_back`` once the
    device transfer is consumed."""

    def alloc(shape, dtype, fill):
        a = staging.borrow(shape, dtype)
        a.fill(fill)
        bufs.append(a)
        return a

    return alloc


def _owned_layout(points, center, partitioner, labels, n_shards, block,
                  alloc=_alloc_filled):
    """(P, cap, ...) owned slabs, Morton-sorted per partition, gathered
    straight from the input (no dataset-sized recentred temp)."""
    n, k = points.shape
    p_real, p_total, part_idx, cap = _layout_geometry(
        partitioner, labels, n_shards, block
    )
    owned = alloc((p_total, cap, k), np.float32, 0)
    owned_mask = alloc((p_total, cap), bool, False)
    owned_gid = alloc((p_total, cap), np.int32, n)
    owned_idx = [
        _fill_slab(owned, owned_mask, owned_gid, j, points, idx, center)
        for j, idx in enumerate(part_idx)
    ]
    return owned_idx, (owned, owned_mask, owned_gid), cap, p_total


def build_owned_shards(points, partitioner, eps, n_shards, block):
    """Ring-mode layout: owned slabs + expanded boxes, NO host halos.

    The halo sets are never materialized on the host — sizing and
    duplication happen device-side (halo.ring_halo_exchange_multi).
    """
    points = np.asarray(points)
    center, exp_lo, exp_hi, labels = _expanded_frame_meta(
        points, partitioner, eps
    )
    owned_idx, arrays, cap, p_total = _owned_layout(
        points, center, partitioner, labels, n_shards, block
    )
    exp_lo, exp_hi = _pad_inverted_boxes(exp_lo, exp_hi, p_total)
    stats = {
        "owned_cap": cap,
        "n_shard_partitions": p_total,
        "pad_waste": float(p_total * cap) / max(len(points), 1) - 1.0,
        "partition_sizes": _partition_sizes(owned_idx, p_total),
    }
    return arrays, exp_lo, exp_hi, labels, stats


def build_owned_shards_streaming(points, partitioner, eps, block, mesh):
    """Per-DEVICE owned-slab assembly for datasets that must not be
    resident in host RAM (round-4 review, Next #8 — the honest
    single-host analogue of the reference's Spark premise,
    /root/reference/README.md:60: data larger than one worker).

    ``points`` is any row-indexable (N, k) array — typically an
    ``np.memmap`` over a disk file.  Instead of materializing all
    (P, cap, k) slabs at once (anonymous host memory ~ the dataset and
    then some), each DEVICE's (L, cap, k) slab is built alone — chunked
    gathers straight from the memmap — shipped to its device, and
    freed before the next begins.  Peak anonymous host memory is one
    device's slabs plus the partition index lists (int32, one entry
    per point): for an 8-device mesh that is ~1/8 of the dataset.
    Pairs with ``halo='ring'`` (halos never exist host-side) and either
    merge mode; the dataset itself is read exactly twice end to end
    (KD column reads + the slab gather).

    Returns the same ``(arrays, exp_lo, exp_hi, labels, stats)`` shape
    as :func:`build_owned_shards`, with ``arrays`` already
    device-resident and sharded over ``mesh``.
    """
    n, k = points.shape
    center, exp_lo, exp_hi, labels = _expanded_frame_meta(
        points, partitioner, eps
    )
    n_shards = mesh.devices.size
    axis = mesh.axis_names[0]
    p_real, p_total, part_idx, cap = _layout_geometry(
        partitioner, labels, n_shards, block
    )
    L = p_total // n_shards
    exp_lo, exp_hi = _pad_inverted_boxes(exp_lo, exp_hi, p_total)

    devices = mesh.devices.reshape(-1)
    sharding = NamedSharding(mesh, P(axis))
    bufs = ([], [], [])
    for d in range(n_shards):
        # ONE PARTITION of host memory at a time (not one device's L
        # partitions — on a 1-device mesh L == p_total and that would
        # be the whole padded dataset as anonymous RAM, defeating the
        # point); per-partition pieces concatenate ON device d.
        pieces = ([], [], [])
        for jl in range(L):
            p = d * L + jl
            ow = np.zeros((1, cap, k), np.float32)
            ms = np.zeros((1, cap), bool)
            gd = np.full((1, cap), n, np.int32)
            if p < p_real:
                _fill_slab(ow, ms, gd, 0, points, part_idx[p], center)
            for piece, host in zip(pieces, (ow, ms, gd)):
                # graftlint: disable=device-put-aliasing -- ow/ms/gd
                # are freshly np.zeros-allocated per partition and
                # del'd right after the put; never pool-borrowed
                piece.append(jax.device_put(host, devices[d]))
            del ow, ms, gd
        for buf, piece in zip(bufs, pieces):
            buf.append(
                piece[0] if L == 1 else jnp.concatenate(piece, axis=0)
            )
        del pieces

    owned = jax.make_array_from_single_device_arrays(
        (p_total, cap, k), sharding, bufs[0]
    )
    mask = jax.make_array_from_single_device_arrays(
        (p_total, cap), sharding, bufs[1]
    )
    gid = jax.make_array_from_single_device_arrays(
        (p_total, cap), sharding, bufs[2]
    )
    stats = {
        "owned_cap": cap,
        "n_shard_partitions": p_total,
        "pad_waste": float(p_total * cap) / max(n, 1) - 1.0,
        "partition_sizes": _partition_sizes(part_idx, p_total),
        "input": "stream",
    }
    return (owned, mask, gid), exp_lo, exp_hi, labels, stats


def build_shards(points, partitioner, eps, n_shards, block):
    """Lay out points as (P, cap, k) owned slabs + (P, hcap, k) halo slabs.

    ``P`` is the partition count rounded up to a multiple of the mesh
    size (empty partitions are fully masked).  The halo of partition p
    is every point inside its box expanded by 2*eps but not owned by p —
    the reference's duplication semantics (dbscan.py:141-151) without a
    shuffle.  Global point ids ride along so labels are meaningful
    across shards; padded slots carry gid == N (a dump row in the
    scatter arrays).
    """
    points = np.asarray(points)
    center, _exp_lo, _exp_hi, labels = _expanded_frame_meta(
        points, partitioner, eps
    )
    owned_idx, arrays_o, cap, p_total = _owned_layout(
        points, center, partitioner, labels, n_shards, block
    )
    arrays_h, h_stats = _halo_slabs(
        points, partitioner, eps, labels, center, p_total, block
    )
    stats = {
        "owned_cap": cap,
        "n_shard_partitions": p_total,
        "pad_waste": float(p_total * cap) / max(len(points), 1) - 1.0,
        "partition_sizes": _partition_sizes(owned_idx, p_total),
        **h_stats,
    }
    return (*arrays_o, *arrays_h), stats


def _halo_slabs(points, partitioner, eps, labels, center, p_total, block,
                alloc=_alloc_filled):
    """(P, hcap, ...) halo slabs + their stats, separated from the owned
    build so the staging cache can reuse eps-independent owned slabs
    across an eps sweep while rebuilding only these."""
    n, k = points.shape
    # Halo sets from an O(N·depth) split-tree replay with 2*eps-widened
    # comparisons — never a broadcasted (N, P, k) membership temp (the
    # round-1 memory wall).  Replay runs on the raw points in float64
    # boundary arithmetic: exact, and over-inclusion relative to the f32
    # ring-filter frame is harmless (extra halo context never changes an
    # owned point's result).
    from ..partition import expanded_members

    members = expanded_members(partitioner.tree, points, 2 * eps)
    halo_idx = [arr[~own] for arr, own in (members[l] for l in labels)]
    del members

    hcap = round_up(max(max((len(h) for h in halo_idx), default=1), 1), block)
    halo = alloc((p_total, hcap, k), np.float32, 0)
    halo_mask = alloc((p_total, hcap), bool, False)
    halo_gid = alloc((p_total, hcap), np.int32, n)
    n_halo = sum(len(h) for h in halo_idx)
    for j, hi in enumerate(halo_idx):
        _fill_slab(halo, halo_mask, halo_gid, j, points, hi, center)

    stats = {
        "halo_factor": float(n_halo) / max(n, 1),
        "halo_cap": hcap,
        # Actual duplicated coordinate bytes (f32) the halo build ships.
        "halo_bytes": int(n_halo) * k * 4,
    }
    return (halo, halo_mask, halo_gid), stats


def _sharding_cache_key(points, partitioner, n_shards, block, sharding):
    """The content key under which staged device slabs may be reused.

    Hashes the full input buffer and the partition tree — identity is
    never trusted, so in-place mutation between fits rebuilds."""
    return (
        staging.points_fingerprint(points),
        staging.partitioner_fingerprint(partitioner),
        int(n_shards),
        int(block),
        tuple(int(d.id) for d in sharding.mesh.devices.flat),
    )


def _host_build_cached(points, partitioner, eps, n_shards, block, sharding):
    """Host-halo route shard build through the staging economy.

    Returns ``(device_arrays, stats, host_bufs)``: the six device-
    resident slab arrays, the layout stats (including
    ``staged_bytes_reused`` accounting via :mod:`.staging`), and the
    borrowed host buffers to ``give_back`` once the fit's results have
    materialized.  Owned slabs cache WITHOUT eps in the key, halo slabs
    WITH it, so a warm eps sweep re-ships only halos.
    """
    points = np.asarray(points)
    base = _sharding_cache_key(points, partitioner, n_shards, block,
                               sharding)
    cached_o = staging.device_get("host_owned", base)
    cached_h = staging.device_get("host_halo", base + (float(eps),))
    bufs: list = []
    if cached_o is None or cached_h is None:
        center, _lo, _hi, labels = _expanded_frame_meta(
            points, partitioner, eps
        )
    if cached_o is None:
        owned_idx, arrays_o, cap, p_total = _owned_layout(
            points, center, partitioner, labels, n_shards, block,
            alloc=_staged_alloc(bufs),
        )
        o_stats = {
            "owned_cap": cap,
            "n_shard_partitions": p_total,
            "pad_waste": float(p_total * cap) / max(len(points), 1) - 1.0,
            "partition_sizes": _partition_sizes(owned_idx, p_total),
        }
        arrays_o = staging.transfer(lambda: tuple(
            jax.device_put(a, sharding) for a in arrays_o
        ))
        staging.device_put_cached("host_owned", base, arrays_o, aux=o_stats)
    else:
        arrays_o, o_stats = cached_o
    if cached_h is None:
        arrays_h, h_stats = _halo_slabs(
            points, partitioner, eps, labels, center,
            int(o_stats["n_shard_partitions"]), block,
            alloc=_staged_alloc(bufs),
        )
        arrays_h = staging.transfer(lambda: tuple(
            jax.device_put(a, sharding) for a in arrays_h
        ))
        staging.device_put_cached(
            "host_halo", base + (float(eps),), arrays_h, aux=h_stats
        )
    else:
        arrays_h, h_stats = cached_h
    return (*arrays_o, *arrays_h), {**o_stats, **h_stats}, bufs


def _ring_build_cached(points, partitioner, eps, n_shards, block, sharding):
    """Ring route owned-slab build through the staging economy (the
    expanded-box stacks are per-eps metadata, rebuilt every fit)."""
    points = np.asarray(points)
    base = _sharding_cache_key(points, partitioner, n_shards, block,
                               sharding)
    center, exp_lo, exp_hi, labels = _expanded_frame_meta(
        points, partitioner, eps
    )
    cached = staging.device_get("ring_owned", base)
    bufs: list = []
    if cached is None:
        owned_idx, arrays_o, cap, p_total = _owned_layout(
            points, center, partitioner, labels, n_shards, block,
            alloc=_staged_alloc(bufs),
        )
        o_stats = {
            "owned_cap": cap,
            "n_shard_partitions": p_total,
            "pad_waste": float(p_total * cap) / max(len(points), 1) - 1.0,
            "partition_sizes": _partition_sizes(owned_idx, p_total),
        }
        arrays_o = staging.transfer(lambda: tuple(
            jax.device_put(a, sharding) for a in arrays_o
        ))
        staging.device_put_cached("ring_owned", base, arrays_o, aux=o_stats)
    else:
        arrays_o, o_stats = cached
    p_total = int(o_stats["n_shard_partitions"])
    exp_lo, exp_hi = _pad_inverted_boxes(exp_lo, exp_hi, p_total)
    args = (
        *arrays_o,
        # graftlint: disable=device-put-aliasing -- fresh padded box
        # metadata from _pad_inverted_boxes, never pool-borrowed
        jax.device_put(exp_lo, sharding),
        # graftlint: disable=device-put-aliasing -- same as exp_lo
        jax.device_put(exp_hi, sharding),
    )
    return args, dict(o_stats), bufs


# ---------------------------------------------------------------------------
# the jitted sharded step
# ---------------------------------------------------------------------------


def _cluster_local_partitions(
    pts, msk, *, eps, min_samples, metric, block, precision, backend,
    pair_budget,
):
    """Run per-partition DBSCAN over a device's (L, cap, k) partitions.

    L == 1 calls the kernel directly.  For L > 1 BOTH backends run a
    static Python loop over partitions (unrolled into the program):
    pallas_call cannot batch under vmap, and vmapping the XLA kernel
    turns its tile-skip ``lax.cond`` into ``select`` — every pruned
    column tile computes anyway, which measured as a 5x
    multi-partition-per-device cliff (500k x 4-D, 16 partitions on the
    8-device mesh: 904s warm vmapped vs ~1.5x expected from padding).
    Returns (labels, core, pair_stats) with the worst-case (max-total)
    pair stats — the static budget is shared, so max(total) is the
    binding constraint.
    """

    def one_part(p, m):
        return dbscan_fixed_size(
            p, eps, min_samples, m, metric=metric, block=block,
            precision=precision, backend=backend, pair_budget=pair_budget,
        )

    if pts.shape[0] == 1:
        l1, c1, pair_stats = one_part(pts[0], msk[0])
        return l1[None], c1[None], pair_stats
    outs = [one_part(pts[i], msk[i]) for i in range(pts.shape[0])]
    labels = jnp.stack([o[0] for o in outs])
    core = jnp.stack([o[1] for o in outs])
    pair_stats = jnp.stack([o[2] for o in outs]).max(axis=0)
    return labels, core, pair_stats


def _merge_round(lab_map, home_label, core_g, h_gid, h_lab, h_core, axis):
    """ONE cross-device pmin label round of the bipartite merge.

    The body of :func:`_merge_loop`, split out so the global-Morton
    mode (:mod:`pypardis_tpu.parallel.global_morton`) can host-step the
    identical round as its own program — per-round convergence probe +
    trace span — while the fused while_loop path keeps byte-identical
    semantics.  Returns ``(new_map, changed)``.
    """
    n1 = lab_map.shape[0]

    def lookup(lm, lab):
        safe = jnp.clip(lab, 0, n1 - 1)
        return jnp.where(lab >= 0, lm[safe], _INT_INF)

    # point_min[g]: min canonical label over g's occurrences (core only)
    pm_home = jnp.where(core_g, lookup(lab_map, home_label), _INT_INF)
    halo_vals = jnp.where(h_core, lookup(lab_map, h_lab), _INT_INF)
    pm_halo = (
        jnp.full((n1,), _INT_INF, jnp.int32).at[h_gid].min(halo_vals)
    )
    pm_halo = jax.lax.pmin(pm_halo, axis)
    pm = jnp.minimum(pm_home, pm_halo)

    # cluster_min[l]: min point_min over member occurrences
    new_map = lab_map
    home_tgt = jnp.where(core_g, home_label, n1 - 1)
    new_map = new_map.at[jnp.clip(home_tgt, 0, n1 - 1)].min(
        jnp.where(core_g & (home_label >= 0), pm, _INT_INF)
    )
    halo_tgt = jnp.where(h_core & (h_lab >= 0), h_lab, n1 - 1)
    local = jnp.full((n1,), _INT_INF, jnp.int32).at[halo_tgt].min(
        jnp.where(h_core & (h_lab >= 0), pm[h_gid], _INT_INF)
    )
    new_map = jnp.minimum(new_map, jax.lax.pmin(local, axis))

    # pointer jump: chase canonical labels to a fixpoint
    def jump_body(st):
        m, _ = st
        nxt = jnp.where(m != _INT_INF, m[jnp.clip(m, 0, n1 - 1)], m)
        return nxt, jnp.any(nxt != m)

    new_map, _ = jax.lax.while_loop(
        lambda st: st[1], jump_body, (new_map, jnp.bool_(True))
    )
    return new_map, jnp.any(new_map != lab_map)


def _merge_loop(lab_map, home_label, core_g, h_gid, h_lab, h_core, axis,
                max_rounds):
    """Min-label propagation over the bipartite point<->cluster graph.

    ``lab_map``: (N+1,) replicated — cluster key (root gid) -> current
    canonical label.  ``home_label``/``core_g``: (N+1,) replicated.
    ``h_gid``/``h_lab``: this device's halo occurrences (flattened).
    Per round: points take the min canonical label over all their
    occurrences (home vectorized + halo scatter-min, pmin across mesh),
    clusters take the min over their member points, then pointer-jump.

    Returns ``(lab_map, rounds, converged)``.  ``converged`` is False
    when the loop exited at ``max_rounds`` with the last round still
    changing labels — the result may be UNDER-MERGED (a cluster chain
    threading more partitions than rounds covered comes back as several
    clusters) and callers must treat it like the other capacity
    overflows: retry bigger or raise, never return silently (round-3
    review, Weak #1).  All quantities here are replicated across the
    mesh (every update flows through pmin), so the flag is identical on
    every device and the while_loop steps in lockstep.
    """
    def body(state):
        lab_map, _, rounds = state
        new_map, changed = _merge_round(
            lab_map, home_label, core_g, h_gid, h_lab, h_core, axis
        )
        return new_map, changed, rounds + 1

    lab_map, changed, rounds = jax.lax.while_loop(
        lambda st: st[1] & (st[2] < max_rounds),
        body,
        (lab_map, jnp.bool_(True), 0),
    )
    return lab_map, rounds, ~changed


def sharded_step(
    owned, owned_mask, owned_gid, halo, halo_mask, halo_gid,
    *, eps, min_samples, metric, block, mesh, axis, n_points,
    precision="high", backend="auto", pair_budget=None, merge_rounds=32,
    owner_computes=False,
):
    """One fully-sharded clustering step: local DBSCAN + global merge.

    All inputs have leading (partition) axis sharded over ``mesh``;
    outputs are replicated (N,) final labels and core flags, a
    per-device (1, 2) ``[live_pairs_total, budget]`` from the pair
    extraction, and the merge loop's replicated ``(rounds, converged)``
    (see :func:`sharded_dbscan` for the retries).  On a multi-device
    mesh this is the whole distributed hot path in one compiled
    program.

    On a SINGLE-device mesh with several partitions the step chains
    per-partition cluster dispatches instead (one compiled program
    reused L times + one merge program, dispatched OUTSIDE any
    enclosing jit): a 1-device execution of all L partitions runs for
    minutes at benchmark sizes — past tunneled deployments' worker
    watchdog — and recompiles for every L, while a real
    L=1-per-device pod executes exactly one partition per device per
    step.  The chained path reproduces that execution granularity (and
    its compile economy) with identical labels.
    """
    if mesh.devices.size == 1 and owned.shape[0] > 1:
        # The chained path keeps the legacy full-slab clustering: its
        # per-partition dispatches cannot share a pmax'd core table
        # without a collective program between them (owner_computes is
        # ignored here; the driver reports it off).
        return _sharded_step_1dev_chained(
            owned, owned_mask, owned_gid, halo, halo_mask, halo_gid,
            eps=eps, min_samples=min_samples, metric=metric, block=block,
            mesh=mesh, axis=axis, n_points=n_points, precision=precision,
            backend=backend, pair_budget=pair_budget,
            merge_rounds=merge_rounds,
        )
    return _sharded_step_fused(
        owned, owned_mask, owned_gid, halo, halo_mask, halo_gid,
        eps=eps, min_samples=min_samples, metric=metric, block=block,
        mesh=mesh, axis=axis, n_points=n_points, precision=precision,
        backend=backend, pair_budget=pair_budget,
        merge_rounds=merge_rounds, owner_computes=owner_computes,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "eps", "min_samples", "metric", "block", "mesh", "axis", "n_points",
        "precision", "backend", "pair_budget", "merge_rounds",
        "owner_computes",
    ),
)
def _sharded_step_fused(
    owned, owned_mask, owned_gid, halo, halo_mask, halo_gid,
    *, eps, min_samples, metric, block, mesh, axis, n_points,
    precision="high", backend="auto", pair_budget=None, merge_rounds=32,
    owner_computes=False,
):
    body = _device_cluster_merge_oc if owner_computes else (
        _device_cluster_merge
    )

    def per_device(o, om, og, h, hm, hg):
        final, core_g, pstats, rounds, converged = body(
            o, om, og, h, hm, hg,
            eps=eps, min_samples=min_samples, metric=metric, block=block,
            precision=precision, backend=backend, axis=axis,
            n_points=n_points, pair_budget=pair_budget,
            merge_rounds=merge_rounds,
        )
        return final, core_g, pstats[None], rounds, converged

    spec = P("p", None, None)
    spec2 = P("p", None)
    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(spec, spec2, spec2, spec, spec2, spec2),
        out_specs=(P(), P(), P("p", None), P(), P()),
        check_vma=False,
    )(owned, owned_mask, owned_gid, halo, halo_mask, halo_gid)


def _sharded_step_1dev_chained(
    owned, owned_mask, owned_gid, halo, halo_mask, halo_gid,
    *, eps, min_samples, metric, block, mesh, axis, n_points,
    precision, backend, pair_budget, merge_rounds,
):
    """Single-device mesh, L partitions: chained per-partition cluster
    dispatches + one merge-only program.  See :func:`sharded_step`.

    Each partition's (cap + hcap) slab runs through the SAME compiled
    :func:`dbscan_fixed_size` executable (identical shapes), so L, 2L,
    4L partitions share one compile; executions stay short (one
    partition's work — what each device of a real pod would run); and
    the dispatches chain asynchronously on device.  The merge program
    is the identical `_merge_from_tables` body the fused step runs.
    """
    own_glab, own_core, halo_glab, pair_stats = (
        _cluster_tables_1dev_chained(
            owned, owned_mask, owned_gid, halo, halo_mask, halo_gid,
            eps=eps, min_samples=min_samples, metric=metric, block=block,
            precision=precision, backend=backend,
            pair_budget=pair_budget,
        )
    )

    def per_device(a, b, c, d, e):
        final, core_g, rounds, converged = _merge_from_tables(
            a, b, c, d, e, axis=axis, n_points=n_points,
            merge_rounds=merge_rounds,
        )
        return final, core_g, rounds, converged

    mkey = ("merge", own_glab.shape, halo_glab.shape, n_points,
            merge_rounds)
    if mkey not in _chained_compiled:
        obs_event("compile", stage="chained_merge")
        # Idle-device barrier before the merge program's first compile
        # (the cluster dispatches above may still be executing).
        np.asarray(own_glab[:1, :1])
    spec2 = P("p", None)
    final, core_g, rounds, converged = shard_map(
        per_device,
        mesh=mesh,
        in_specs=(spec2, spec2, spec2, spec2, spec2),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )(own_glab, own_core, owned_gid, halo_gid, halo_glab)
    _chained_compiled.add(mkey)
    return final, core_g, pair_stats, rounds, converged


# Configurations whose chained per-partition + merge programs have
# compiled in this process — the first call for a config syncs between
# dispatches so no program COMPILES while the device EXECUTES (the
# axon tunnel's worker-poisoning mode, same discipline as
# ops.pipeline._pipeline_layout).
_chained_compiled: set = set()


def _cluster_tables_1dev_chained(
    owned, owned_mask, owned_gid, halo, halo_mask, halo_gid,
    *, eps, min_samples, metric, block, precision, backend, pair_budget,
):
    """Per-partition cluster dispatches on a 1-device mesh, returning
    the compact label tables ``(own_glab, own_core, halo_glab,
    pair_stats)`` both merge modes consume."""
    from ..ops.labels import dbscan_fixed_size

    L, cap = owned.shape[0], owned.shape[1]
    key = (
        "cluster", owned.shape, halo.shape, float(eps), int(min_samples),
        str(metric), block, precision, backend, pair_budget,
    )
    first = key not in _chained_compiled
    if first:
        obs_event("compile", stage="chained_cluster")
        # Idle-device barrier BEFORE the cluster program's first
        # compile/load: the upstream halo-exchange program may still be
        # executing, and on tunneled deployments bringing a new large
        # program up while the device executes poisons the session
        # (round-3/5 finding — holds for compile-cache loads too).
        np.asarray(halo_gid[:1, :1])
    glabs, cores, pstats = [], [], []
    for p in range(L):
        pts = jnp.concatenate([owned[p], halo[p]], axis=0)
        msk = jnp.concatenate([owned_mask[p], halo_mask[p]])
        gid = jnp.concatenate([owned_gid[p], halo_gid[p]])
        lab, cor, ps = dbscan_fixed_size(
            pts, eps, min_samples, msk, metric=metric, block=block,
            precision=precision, backend=backend, pair_budget=pair_budget,
        )
        glabs.append(
            jnp.where(
                lab >= 0,
                jnp.take(gid, jnp.clip(lab, 0, None)),
                -1,
            ).astype(jnp.int32)
        )
        cores.append(cor)
        pstats.append(ps)
        if jax.default_backend() == "tpu":
            # One tiny fetch per partition: tunneled deployments fail
            # queued RE-executions of a large program with
            # INVALID_ARGUMENT (reproduced at 10M x 16-D: partition 0
            # executes, partitions 1+ die even fully compile-cached;
            # the stage-by-stage probe with a sync between dispatches
            # runs the identical sequence cleanly).  ~0.2s per
            # partition against multi-second executions.
            np.asarray(glabs[-1][:1])
    if first:
        np.asarray(glabs[-1][:1])
        _chained_compiled.add(key)
    own_glab = jnp.stack([g[:cap] for g in glabs])
    halo_glab = jnp.stack([g[cap:] for g in glabs])
    own_core = jnp.stack([c[:cap] for c in cores])
    pair_stats = jnp.stack(pstats).max(axis=0)[None]
    return own_glab, own_core, halo_glab, pair_stats


# ---------------------------------------------------------------------------
# overlapped (double-buffered) 1-device chained route
# ---------------------------------------------------------------------------


def _overlap_enabled(overlap) -> bool:
    """Resolve the chained-overlap switch: explicit argument wins, then
    the PYPARDIS_CHAINED_OVERLAP env kill-switch, default on."""
    if overlap is not None:
        return bool(overlap)
    return envreg.raw("PYPARDIS_CHAINED_OVERLAP", "1") != "0"


def _put_slab(a, dev):
    """Device_put one host slab for the overlapped chained loop.

    On TPU the put is the pinned-staging fast path and the source
    buffer is protected by the rotation discipline (reused only after
    the consuming partition's completion probe).  Off-TPU ``device_put``
    may return a ZERO-COPY view over the numpy memory, which the device
    cache then retains across fits while the pool rewrites the buffer —
    an explicit copy keeps cached slabs immutable everywhere else.
    """
    if jax.default_backend() == "tpu":
        return staging.transfer(lambda: jax.device_put(a, dev))
    return staging.transfer(lambda: jax.device_put(np.array(a), dev))


def _chained_tables_overlap(
    points, partitioner, eps, *, center, part_idx, halo_idx,
    cap, hcap, p_total, block, min_samples, metric, precision, backend,
    pair_budget, base_key, mesh, jobstate=None,
):
    """Double-buffered per-partition build + chained execution.

    The legacy 1-device chained flow is strictly serial on the host
    side: build ALL (P, cap, k) slabs, ship them, then chain the
    per-partition cluster dispatches — every second of Morton sorting
    and slab filling happens while the device sits idle.  Here the loop
    pipelines: while the device executes partition ``p``, the host
    builds and ``device_put``s partition ``p+1``'s slabs, and the
    1-element completion probe of ``p`` (the same fetch the chained
    path already needs against queued-re-execution faults on tunneled
    deployments) doubles as the pipeline barrier.  Exactly one
    execution is ever in flight, preserving the chained path's sync
    discipline; only host work overlaps it.

    Mutation safety: the two rotating pooled coordinate buffers mean
    slab ``p+2``'s host build (the earliest reuse of ``p``'s buffer)
    starts only after ``p``'s probe completed — an in-flight transfer
    can never read a buffer being rewritten, on any backend
    (regression-pinned in tests/test_overlap.py).

    Per-partition device slabs are cached through the staging economy
    (``chained_owned`` keyed WITHOUT eps / ``chained_halo`` WITH it, the
    same split as the stacked host route), so warm refits skip the host
    build and the transfer, and an eps sweep re-ships only halos.

    Returns ``(glabs, cores, pstats_list, gid_o_host, gid_h_host,
    dev_gids, overlap_efficiency)`` — per-partition device label/core
    arrays plus the host gid tables both merges consume.
    """
    import time as _time

    n, k = points.shape
    dev = mesh.devices.reshape(-1)[0]
    own_entry = staging.device_get("chained_owned", base_key)
    halo_entry = staging.device_get(
        "chained_halo", base_key + (float(eps),)
    )
    own_slabs = (
        None if own_entry is None
        else [tuple(own_entry[0][3 * p:3 * p + 3]) for p in range(p_total)]
    )
    halo_slabs = (
        None if halo_entry is None
        else [tuple(halo_entry[0][3 * p:3 * p + 3]) for p in range(p_total)]
    )
    # Host gid tables (fresh, not pooled: the host merge reads them
    # after this loop returns, so they must never alias a reusable
    # buffer).  Cold builds fill them as a byproduct of the slab fill;
    # warm hits replay the deterministic Morton order host-side (the
    # sort runs in the same recentred f32 frame as the cached slabs,
    # so the rows match byte-for-byte) rather than fetching (P, cap)
    # ints back over the link.
    gid_o_host = np.full((p_total, cap), n, np.int32)
    gid_h_host = np.full((p_total, hcap), n, np.int32)

    def _replay_gids(idx_all, gid_host):
        for p in range(p_total):
            idx = idx_all[p]
            if len(idx):
                sub = _recentre_rows(points, idx, center)
                gid_host[p, : len(idx)] = idx[spatial_order(sub)]

    if own_slabs is not None:
        _replay_gids(part_idx, gid_o_host)
    if halo_slabs is not None:
        _replay_gids(halo_idx, gid_h_host)

    built_own = [] if own_slabs is None else own_slabs
    built_halo = [] if halo_slabs is None else halo_slabs
    rot_own = [None, None]
    rot_halo = [None, None]
    host_bufs: list = []

    def _rotating(rot, shape, slot):
        buf = rot[slot]
        if buf is None:
            buf = rot[slot] = staging.borrow(shape, np.float32)
            host_bufs.append(buf)
        return buf

    def _build(p, idx_all, capn, built, rot, gid_host):
        buf = _rotating(rot, (capn, k), p % 2)
        idx = idx_all[p]
        buf[len(idx):] = 0.0
        msk_row = np.zeros((1, capn), bool)
        _fill_slab(buf[None], msk_row, gid_host[p:p + 1], 0, points, idx,
                   center)
        built.append(
            (
                _put_slab(buf, dev),
                _put_slab(msk_row[0], dev),
                _put_slab(gid_host[p], dev),
            )
        )

    # Resume (utils.jobstate): partitions whose label tables a previous
    # (killed) run already snapshotted replay from the file instead of
    # re-dispatching — the tables were fetched post-probe, so they are
    # the kernel's exact outputs and the merge consumes byte-identical
    # inputs.  Snapshots are keyed by the effective pair budget: tables
    # computed under a budget that later overflowed are never reused.
    budget_tag = int(pair_budget or 0)
    restored = (
        jobstate.chained_restore(budget_tag) if jobstate is not None
        else {}
    )
    if restored:
        # Restored partitions skip the slab build, but the merge still
        # needs their (deterministic) gid tables — replay them.
        _replay_gids(part_idx, gid_o_host)
        _replay_gids(halo_idx, gid_h_host)
        obs_event("jobstate_restore", route="chained",
                  partitions=len(restored))
        dev = mesh.devices.reshape(-1)[0]

    def ensure(p):
        # while-driven so the built lists stay index-aligned past
        # restored partitions (a None placeholder keeps the slot; the
        # gid column still ships for the merge programs).
        while own_slabs is None and len(built_own) <= p:
            q = len(built_own)
            if q in restored:
                built_own.append(
                    (None, None, _put_slab(gid_o_host[q], dev))
                )
            else:
                _build(q, part_idx, cap, built_own, rot_own, gid_o_host)
        while halo_slabs is None and len(built_halo) <= p:
            q = len(built_halo)
            if q in restored:
                built_halo.append(
                    (None, None, _put_slab(gid_h_host[q], dev))
                )
            else:
                _build(q, halo_idx, hcap, built_halo, rot_halo,
                       gid_h_host)

    key = (
        "cluster", (p_total, cap, k), (p_total, hcap, k), float(eps),
        int(min_samples), str(metric), block, precision, backend,
        pair_budget,
    )
    first = key not in _chained_compiled
    first_live = next(
        (p for p in range(p_total) if p not in restored), None
    )
    if first_live is not None:
        ensure(first_live)
    if first and first_live is not None:
        obs_event("compile", stage="chained_cluster")
        # Idle-device barrier before the cluster program's first
        # compile (same discipline as _cluster_tables_1dev_chained).
        np.asarray(built_own[first_live][2][:1])

    glabs, cores, pstats = [], [], []
    busy = 0.0
    idle_overlaps = 0
    t_loop = _time.perf_counter()
    for p in range(p_total):
        ensure(p)
        if p in restored:
            glab_np, cor_np, ps_np = restored[p]
            glabs.append(jnp.asarray(glab_np))
            cores.append(jnp.asarray(cor_np))
            pstats.append(jnp.asarray(ps_np))
            obs_heartbeat("chained.partitions", p + 1, p_total, t_loop)
            continue
        po, mo, go = built_own[p]
        ph, mh, hg = built_halo[p]
        t_disp = _time.perf_counter()

        def one_partition():
            # Injection site + unified retry: the dispatch consumes
            # nothing (no donation), so a re-dispatch from the same
            # slabs recomputes the identical tables.
            faults.maybe_fail("chained.partition")
            pts = jnp.concatenate([po, ph], axis=0)
            msk = jnp.concatenate([mo, mh])
            gid = jnp.concatenate([go, hg])
            lab, cor, ps = dbscan_fixed_size(
                pts, eps, min_samples, msk, metric=metric, block=block,
                precision=precision, backend=backend,
                pair_budget=pair_budget,
            )
            glab = jnp.where(
                lab >= 0,
                jnp.take(gid, jnp.clip(lab, 0, None)),
                -1,
            ).astype(jnp.int32)
            # THE overlap: partition p+1's host build + transfer runs
            # while the device executes partition p.
            if p + 1 < p_total:
                ensure(p + 1)
            t_built = _time.perf_counter()
            ready_early = bool(
                getattr(glab, "is_ready", lambda: False)()
            )
            # Completion probe: the chained path's anti-queued-
            # re-execution sync, now also the rotation barrier freeing
            # slab p's buffers — and the sync that surfaces execution
            # faults inside this retry scope.
            np.asarray(glab[:1])
            return glab, cor, ps, t_built, ready_early

        glab, cor, ps, t_built, ready_early = Retrier(
            "chained.partition"
        ).run(one_partition)
        glabs.append(glab)
        cores.append(cor)
        pstats.append(ps)
        t_done = _time.perf_counter()
        # Device-busy upper bound: when the device finished inside the
        # host build window the busy interval is clipped to it.
        busy += (t_built if ready_early else t_done) - t_disp
        if ready_early:
            idle_overlaps += 1
        # Per-partition progress + partitions-remaining ETA (flight
        # file always, log lines via PYPARDIS_HEARTBEAT): a chained
        # 100M-point run is hours of this loop — it must not be silent.
        obs_heartbeat("chained.partitions", p + 1, p_total, t_loop)
        if jobstate is not None and jobstate.due():
            # Phase-boundary snapshot: the post-probe tables, fetched
            # once — the cost of checkpointing, cadence-gated.
            jobstate.chained_note(
                p, np.asarray(glab), np.asarray(cor), np.asarray(ps),
                budget_tag,
            )
    wall = _time.perf_counter() - t_loop
    if first:
        _chained_compiled.add(key)
    if own_slabs is None and not restored:
        staging.device_put_cached(
            "chained_owned", base_key,
            tuple(a for triple in built_own for a in triple),
        )
    if halo_slabs is None and not restored:
        staging.device_put_cached(
            "chained_halo", base_key + (float(eps),),
            tuple(a for triple in built_halo for a in triple),
        )
    staging.give_back_after_put(host_bufs)
    overlap_eff = busy / wall if wall > 0 else 0.0
    from ..utils.log import log_phase

    log_phase(
        "chained_overlap", partitions=p_total,
        overlap_efficiency=round(overlap_eff, 4),
        device_idle_overlaps=idle_overlaps,
        warm=bool(own_entry is not None),
    )
    dev_gids = (
        [t[2] for t in built_own], [t[2] for t in built_halo]
    )
    return glabs, cores, pstats, gid_o_host, gid_h_host, dev_gids, (
        overlap_eff
    )


def _sharded_dbscan_1dev_overlap(
    points, partitioner, *, eps, min_samples, metric, block, mesh, axis,
    n_points, precision, backend, merge, pair_budget, merge_rounds,
    n_shards, base_key, jobstate=None,
):
    """Driver for the overlapped 1-device chained route: geometry +
    halo sets on host, then the double-buffered loop, then the same
    merge programs (in-graph or host union-find) the legacy chained
    path runs — labels byte-identical to it.  ``stats`` additionally
    carries ``overlap_efficiency`` (device-busy / wall seconds of the
    chained loop)."""
    from ..partition import expanded_members

    n, k = points.shape
    center, _lo, _hi, labels = _expanded_frame_meta(
        points, partitioner, eps
    )
    p_real, p_total, part_idx, cap = _layout_geometry(
        partitioner, labels, n_shards, block
    )
    members = expanded_members(partitioner.tree, points, 2 * eps)
    halo_idx = [arr[~own] for arr, own in (members[l] for l in labels)]
    del members
    empty = np.empty(0, np.int32)
    part_idx = list(part_idx) + [empty] * (p_total - len(part_idx))
    halo_idx = list(halo_idx) + [empty] * (p_total - len(halo_idx))
    hcap = round_up(max(max((len(h) for h in halo_idx), default=1), 1),
                    block)
    n_halo = sum(len(h) for h in halo_idx)
    stats = {
        "owned_cap": cap,
        "n_shard_partitions": p_total,
        "pad_waste": float(p_total * cap) / max(n, 1) - 1.0,
        "partition_sizes": _partition_sizes(part_idx, p_total),
        "halo_factor": float(n_halo) / max(n, 1),
        "halo_cap": hcap,
        "halo_bytes": int(n_halo) * k * 4,
    }
    hint_key = _sharded_hint_key(
        (p_total, cap, k), hcap, block, precision, eps, metric
    ) + (False,)
    eff_cell = [0.0]

    def run_step(pb, mr):
        glabs, cores, pstats_l, gid_o, gid_h, dev_gids, eff = (
            _with_kernel_fallback(
                lambda be: _chained_tables_overlap(
                    points, partitioner, eps,
                    center=center, part_idx=part_idx, halo_idx=halo_idx,
                    cap=cap, hcap=hcap, p_total=p_total, block=block,
                    min_samples=min_samples, metric=metric,
                    precision=precision, backend=be, pair_budget=pb,
                    base_key=base_key, mesh=mesh, jobstate=jobstate,
                ),
                backend,
            )
        )
        eff_cell[0] = eff
        own_glab = jnp.stack([g[:cap] for g in glabs])
        halo_glab = jnp.stack([g[cap:] for g in glabs])
        own_core = jnp.stack([c[:cap] for c in cores])
        pair_stats = jnp.stack(pstats_l).max(axis=0)[None]
        if merge == "host":
            # The host union-find merge is exact — no rounds ladder.
            return (
                (own_glab, own_core, halo_glab, gid_o, gid_h),
                pair_stats,
                True,
            )
        og_dev = jnp.stack(dev_gids[0])
        hg_dev = jnp.stack(dev_gids[1])

        def per_device(a, b, c, d, e):
            final, core_g, rounds, converged = _merge_from_tables(
                a, b, c, d, e, axis=axis, n_points=n_points,
                merge_rounds=mr,
            )
            return final, core_g, rounds, converged

        mkey = ("merge", own_glab.shape, halo_glab.shape, n_points, mr)
        if mkey not in _chained_compiled:
            obs_event("compile", stage="chained_merge")
            # Idle-device barrier before the merge program's first
            # compile (the stack dispatches above may still run).
            np.asarray(own_glab[:1, :1])
        spec2 = P("p", None)
        final, core_g, rounds, converged = shard_map(
            per_device,
            mesh=mesh,
            in_specs=(spec2, spec2, spec2, spec2, spec2),
            out_specs=(P(), P(), P(), P()),
            check_vma=False,
        )(own_glab, own_core, og_dev, hg_dev, halo_glab)
        _chained_compiled.add(mkey)
        return (final, core_g, rounds), pair_stats, converged

    with obs_span("sharded.execute", halo="host", merge=merge,
                  overlap=True):
        out, pstats = run_ladders(
            run_step, hint_key, pair_budget, merge_rounds
        )
    if merge == "host":
        own_glab, own_core, halo_glab, gid_o, gid_h = out
        with obs_span("sharded.merge_host"):
            final, core = _host_merge_finish(
                n, gid_o, own_glab, own_core, gid_h, halo_glab
            )
        stats = dict(stats, merge="host")
    else:
        final, core, m_rounds = out
        final, core = np.asarray(final), np.asarray(core)
        stats = dict(
            stats, merge="device", merge_rounds=int(m_rounds),
            merge_converged=True,
        )
    stats["overlap_efficiency"] = round(float(eff_cell[0]), 4)
    _exec_stats(stats, oc_on=False, pstats=pstats, block=block, k=k,
                precision=precision, n=n, metric=metric)
    return _canonicalize_roots(final, core), core, stats


def _device_cluster_merge(
    o, om, og, h, hm, hg, *, eps, min_samples, metric, block, precision,
    backend, axis, n_points, pair_budget=None, merge_rounds=32,
):
    """Shared shard_map body: per-partition DBSCAN + in-graph merge.

    ``o``: (L, cap, k) — this device's partitions; halo slabs ``h`` may
    come from the host layout (build_shards) or a device-side ring
    exchange (halo.ring_halo_exchange_multi).  Returns ``(labels, core,
    pair_stats, rounds, converged)`` — the worst-case (max-total) pair
    stats over this device's partitions, plus the merge loop's
    convergence signal (replicated scalars).
    """
    pts = jnp.concatenate([o, h], axis=1)
    msk = jnp.concatenate([om, hm], axis=1)
    gid = jnp.concatenate([og, hg], axis=1)

    labels, core, pair_stats = _cluster_local_partitions(
        pts, msk, eps=eps, min_samples=min_samples, metric=metric,
        block=block, precision=precision, backend=backend,
        pair_budget=pair_budget,
    )
    # local root index -> global cluster key (root point gid)
    glabel = jnp.where(
        labels >= 0,
        jnp.take_along_axis(gid, jnp.clip(labels, 0, None), axis=1),
        -1,
    ).astype(jnp.int32)

    l_cap = o.shape[1]
    own_glab, halo_glab = glabel[:, :l_cap], glabel[:, l_cap:]
    # Only home-run core status feeds the merge (aggregator.py:38-40
    # semantics); halo-run core flags are intentionally unused.
    own_core = core[:, :l_cap]
    final, core_g, rounds, converged = _merge_from_tables(
        own_glab, own_core, og, hg, halo_glab, axis=axis,
        n_points=n_points, merge_rounds=merge_rounds,
    )
    return final, core_g, pair_stats, rounds, converged


def _oc_counts_device(
    pts, msk, *, cap, eps, min_samples, metric, block, precision,
    backend, pair_budget,
):
    """Pass 1 of the owner-computes step, for one device's L
    partitions: pair extraction + owned-row counts.  Returns ``(
    own_core (L, cap), extracted, band)`` — ``extracted`` is the per-
    partition ``(kind, pairs, stats)`` list pass 2 reuses so the
    Pallas extraction never runs twice in one program; ``band`` the
    worst-partition (2,) mixed-precision band stats of the counts
    pass (zeros off ``precision="mixed"``)."""
    cores, extracted, bands = [], [], []
    for i in range(pts.shape[0]):
        kind, pairs, st = oc_extract(
            pts[i], eps, msk[i], owned=cap, metric=metric, block=block,
            precision=precision, backend=backend, pair_budget=pair_budget,
        )
        extracted.append((kind, pairs, st))
        core_i, band_i = oc_counts_banded(
            pts[i], eps, min_samples, msk[i], owned=cap, metric=metric,
            block=block, precision=precision, kind=kind, pairs=pairs,
        )
        cores.append(core_i)
        bands.append(band_i)
    return jnp.stack(cores), extracted, jnp.stack(bands).max(axis=0)


def _oc_tables_device(
    pts, msk, gid, core_all, extracted, *, cap, eps, metric, block,
    precision, backend, pair_budget, counts_band=None,
):
    """Pass 2 of the owner-computes step: relay propagation per
    partition, local roots mapped through gids.

    ``core_all``: (L, cap + hcap) — owned slots' exact core flags
    followed by the halo slots' OWNER-computed flags.  ``extracted``:
    pass 1's per-partition extraction, or None to re-extract (the
    host-merge route, where the two passes are separate programs).
    ``counts_band``: pass 1's (2,) band stats to fold in (None when
    pass 1 runs as a separate program — the host route sums them
    host-side).  Returns ``(glabel, pair_stats)`` with pair_stats (5,)
    ``[live_pairs, budget, passes, band_pairs, rescored_tiles]``
    worst-case over partitions (the static budget is shared, so max
    binds; band columns are the counts band plus the worst
    partition's propagation band)."""
    glabs, stats2, passes, bands = [], [], [], []
    for i in range(pts.shape[0]):
        if extracted is None:
            kind, pairs, st = oc_extract(
                pts[i], eps, msk[i], owned=cap, metric=metric,
                block=block, precision=precision, backend=backend,
                pair_budget=pair_budget,
            )
        else:
            kind, pairs, st = extracted[i]
        labels_i, p_i, band_i = oc_propagate_banded(
            pts[i], eps, msk[i], core_all[i], owned=cap, metric=metric,
            block=block, precision=precision, kind=kind, pairs=pairs,
        )
        glabs.append(
            jnp.where(
                labels_i >= 0,
                jnp.take(gid[i], jnp.clip(labels_i, 0, None)),
                -1,
            ).astype(jnp.int32)
        )
        stats2.append(st)
        passes.append(p_i)
        bands.append(band_i)
    band = jnp.stack(bands).max(axis=0)
    if counts_band is not None:
        band = band + counts_band
    pair_stats = jnp.concatenate(
        [
            jnp.stack(stats2).max(axis=0),
            (1 + jnp.stack(passes).max())[None],
            band,
        ]
    )
    return jnp.stack(glabs), pair_stats


def _device_cluster_merge_oc(
    o, om, og, h, hm, hg, *, eps, min_samples, metric, block, precision,
    backend, axis, n_points, pair_budget=None, merge_rounds=32,
):
    """Owner-computes shard_map body: owned-only clustering + merge.

    The legacy body (:func:`_device_cluster_merge`) re-clusters every
    halo point inside every foreign partition — the 3.16x duplicated-
    work tax at the r5 geometry.  Here the order inverts: owned-row
    counts first, ONE pmax replicates the owners' core verdicts, and
    the propagation then treats halo slots as relay-only adjacency
    evidence (halo-halo tile pairs skipped — each such edge is some
    partition's owned-halo edge and the merge recovers it from there).
    Halo slots' final labels are the compact (owned_root, halo_gid)
    edge tables; the pmin merge loop consumes them through the exact
    wire format the legacy tables used.
    """
    pts = jnp.concatenate([o, h], axis=1)
    msk = jnp.concatenate([om, hm], axis=1)
    gid = jnp.concatenate([og, hg], axis=1)
    cap = o.shape[1]
    n1 = n_points + 1

    own_core, extracted, counts_band = _oc_counts_device(
        pts, msk, cap=cap, eps=eps, min_samples=min_samples,
        metric=metric, block=block, precision=precision, backend=backend,
        pair_budget=pair_budget,
    )
    core_g = _replicated_core(own_core, og, axis, n1)
    halo_core = (
        core_g[jnp.clip(hg, 0, n_points)] & (hg < n_points) & hm
    )
    glabel, pair_stats = _oc_tables_device(
        pts, msk, gid, jnp.concatenate([own_core, halo_core], axis=1),
        extracted, cap=cap, eps=eps, metric=metric, block=block,
        precision=precision, backend=backend, pair_budget=pair_budget,
        counts_band=counts_band,
    )
    own_glab, halo_glab = glabel[:, :cap], glabel[:, cap:]
    final, core_out, rounds, converged = _merge_from_tables(
        own_glab, own_core, og, hg, halo_glab, axis=axis,
        n_points=n_points, merge_rounds=merge_rounds, core_g=core_g,
    )
    return final, core_out, pair_stats, rounds, converged


def _replicated_core(own_core, og, axis, n1):
    """Replicated (N+1,) home-run core flags from the owned tables.

    Each gid is owned by exactly one shard; padded slots hit the dump
    row n1-1, cleared after the pmax.  In the owner-computes step this
    runs BEFORE label propagation — the owner's verdict is the halo
    slots' core evidence everywhere else.
    """
    core_g = (
        jnp.zeros((n1,), jnp.bool_)
        .at[og.reshape(-1)]
        .max(own_core.reshape(-1))
    )
    core_g = jax.lax.pmax(core_g, axis)
    return core_g.at[n1 - 1].set(False)


def _merge_from_tables(own_glab, own_core, og, hg, halo_glab, *, axis,
                       n_points, merge_rounds, core_g=None):
    """The in-graph merge half of the shard_map body: per-slot label
    tables -> replicated final labels.  Split out so the single-device
    chained path can run it as its OWN program after per-partition
    cluster dispatches.  ``core_g`` lets the owner-computes step reuse
    the replicated core flags it already built before propagation."""
    n1 = n_points + 1
    # Replicated (N+1,) per-point facts from owned slots (each gid is
    # owned by exactly one shard; padded slots hit the dump row n1-1).
    og_flat = og.reshape(-1)
    home_label = (
        jnp.full((n1,), -1, jnp.int32)
        .at[og_flat]
        .max(own_glab.reshape(-1))
    )
    home_label = jax.lax.pmax(home_label, axis)
    if core_g is None:
        core_g = _replicated_core(own_core, og, axis, n1)
    home_label = home_label.at[n1 - 1].set(-1)

    # Halo occurrence tables for the merge (this device's shards).
    h_gid = hg.reshape(-1)
    h_lab = halo_glab.reshape(-1)
    h_core = core_g[jnp.clip(h_gid, 0, n1 - 1)] & (h_gid < n_points)

    # lab_map over cluster keys starts as the identity; propagation
    # only ever reads entries at live label values.
    lab_map = jnp.arange(n1, dtype=jnp.int32)

    lab_map, rounds, converged = _merge_loop(
        lab_map, home_label, core_g, h_gid, h_lab, h_core, axis,
        max_rounds=merge_rounds,
    )

    final = jnp.where(
        home_label >= 0,
        lab_map[jnp.clip(home_label, 0, n1 - 1)],
        -1,
    )
    final = jnp.where(final == _INT_INF, -1, final)
    return final[:n_points], core_g[:n_points], rounds, converged


def sharded_step_local(
    owned, owned_mask, owned_gid, halo, halo_mask, halo_gid,
    *, eps, min_samples, metric, block, mesh, axis,
    precision="high", backend="auto", pair_budget=None,
):
    """Per-shard clustering WITHOUT the in-graph merge.

    The companion of :func:`sharded_step` for ``merge='host'``: each
    device clusters its partitions (owned + halo slabs) and ships back
    only compact per-slot labels — owned labels, owned core flags, and
    the labels its HALO duplicates received — all still sharded on the
    partition axis.  No collective and no replicated (N+1,) state runs
    on device; the cross-partition reconciliation happens on the host
    over these occurrence tables (:mod:`pypardis_tpu.parallel.merge`),
    which is the memory-safe path once N-sized replicated arrays stop
    fitting beside the point data (~20 bytes/point/device).

    Single-device meshes with several partitions chain per-partition
    dispatches outside any enclosing jit, for the same
    watchdog/compile-economy reasons as :func:`sharded_step`; the
    multi-device mesh runs the fused shard_map program.
    """
    if mesh.devices.size == 1 and owned.shape[0] > 1:
        own_glab, own_core, halo_glab, pair_stats = (
            _cluster_tables_1dev_chained(
                owned, owned_mask, owned_gid, halo, halo_mask, halo_gid,
                eps=eps, min_samples=min_samples, metric=metric,
                block=block, precision=precision, backend=backend,
                pair_budget=pair_budget,
            )
        )
        return own_glab, own_core, halo_glab, pair_stats
    return _sharded_step_local_fused(
        owned, owned_mask, owned_gid, halo, halo_mask, halo_gid,
        eps=eps, min_samples=min_samples, metric=metric, block=block,
        mesh=mesh, axis=axis, precision=precision, backend=backend,
        pair_budget=pair_budget,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "eps", "min_samples", "metric", "block", "mesh", "axis",
        "precision", "backend", "pair_budget",
    ),
)
def _sharded_step_local_fused(
    owned, owned_mask, owned_gid, halo, halo_mask, halo_gid,
    *, eps, min_samples, metric, block, mesh, axis,
    precision="high", backend="auto", pair_budget=None,
):
    def per_device(o, om, og, h, hm, hg):
        pts = jnp.concatenate([o, h], axis=1)
        msk = jnp.concatenate([om, hm], axis=1)
        gid = jnp.concatenate([og, hg], axis=1)

        labels, core, pair_stats = _cluster_local_partitions(
            pts, msk, eps=eps, min_samples=min_samples, metric=metric,
            block=block, precision=precision, backend=backend,
            pair_budget=pair_budget,
        )
        glabel = jnp.where(
            labels >= 0,
            jnp.take_along_axis(gid, jnp.clip(labels, 0, None), axis=1),
            -1,
        ).astype(jnp.int32)
        l_cap = o.shape[1]
        return (
            glabel[:, :l_cap],
            core[:, :l_cap],
            glabel[:, l_cap:],
            pair_stats[None],
        )

    spec = P("p", None, None)
    spec2 = P("p", None)
    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(spec, spec2, spec2, spec, spec2, spec2),
        out_specs=(spec2, spec2, spec2, P("p", None)),
        check_vma=False,
    )(owned, owned_mask, owned_gid, halo, halo_mask, halo_gid)


@functools.partial(
    jax.jit,
    static_argnames=(
        "eps", "min_samples", "metric", "block", "mesh", "axis",
        "precision", "backend", "pair_budget",
    ),
)
def _oc_counts_step(
    owned, owned_mask, owned_gid, halo, halo_mask, halo_gid,
    *, eps, min_samples, metric, block, mesh, axis,
    precision="high", backend="auto", pair_budget=None,
):
    """Owner-computes pass 1 as its own collective-free program:
    per-partition owned-row core flags, still sharded on the partition
    axis.  The ``merge='host'`` route runs this, lets the HOST scatter
    the owners' verdicts into halo-slot flags (compact bools — no
    replicated (N+1,) device state, no collective, so the path keeps
    its immunity to the virtual-mesh rendezvous watchdog), then runs
    :func:`_oc_cluster_step`.
    """

    def per_device(o, om, h, hm):
        pts = jnp.concatenate([o, h], axis=1)
        msk = jnp.concatenate([om, hm], axis=1)
        own_core, _extracted, band = _oc_counts_device(
            pts, msk, cap=o.shape[1], eps=eps, min_samples=min_samples,
            metric=metric, block=block, precision=precision,
            backend=backend, pair_budget=pair_budget,
        )
        return own_core, band[None]

    spec = P("p", None, None)
    spec2 = P("p", None)
    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(spec, spec2, spec, spec2),
        out_specs=(spec2, P("p", None)),
        check_vma=False,
    )(owned, owned_mask, halo, halo_mask)


@functools.partial(
    jax.jit,
    static_argnames=(
        "eps", "metric", "block", "mesh", "axis", "precision", "backend",
        "pair_budget",
    ),
)
def _oc_cluster_step(
    owned, owned_mask, owned_gid, halo, halo_mask, halo_gid,
    own_core, halo_core,
    *, eps, metric, block, mesh, axis,
    precision="high", backend="auto", pair_budget=None,
):
    """Owner-computes pass 2 as its own program: relay propagation with
    the host-supplied core flags, emitting the compact label tables the
    host union-find merge consumes (sharded — no replicated state)."""

    def per_device(o, om, og, h, hm, hg, oc, hc):
        pts = jnp.concatenate([o, h], axis=1)
        msk = jnp.concatenate([om, hm], axis=1)
        gid = jnp.concatenate([og, hg], axis=1)
        cap = o.shape[1]
        glabel, pair_stats = _oc_tables_device(
            pts, msk, gid, jnp.concatenate([oc, hc], axis=1), None,
            cap=cap, eps=eps, metric=metric, block=block,
            precision=precision, backend=backend, pair_budget=pair_budget,
        )
        return glabel[:, :cap], glabel[:, cap:], pair_stats[None]

    spec = P("p", None, None)
    spec2 = P("p", None)
    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(spec, spec2, spec2, spec, spec2, spec2, spec2, spec2),
        out_specs=(spec2, spec2, P("p", None)),
        check_vma=False,
    )(owned, owned_mask, owned_gid, halo, halo_mask, halo_gid,
      own_core, halo_core)


def _oc_host_tables(
    arrays, *, eps, min_samples, metric, block, mesh, axis, n_points,
    precision, backend, pair_budget, overflow=None, own_core=None,
):
    """The owner-computes ``merge='host'`` cluster step: two device
    programs with the host relaying the owners' core verdicts between
    them.

    The host round trip ships only compact per-slot bools/ints (the
    same economy as the host merge itself), and the counts fetch
    doubles as the sync point where a ring-exchange ``overflow`` is
    checked before the propagation program runs.  Returns ``(own_glab,
    own_core, halo_glab, pair_stats)`` — the same tables the legacy
    :func:`sharded_step_local` produced, plus 5-wide pair stats (the
    counts program's mixed-precision band columns fold in host-side,
    since the two owner-computes passes are separate programs here).

    ``own_core`` (optional, (P, cap) bool numpy): precomputed owned
    core flags — the global-Morton overlapped-counts route computes
    them from an owned-slab pass plus a boundary delta; the counts
    program here is then skipped (its band columns arrive pre-folded
    from the caller, so they are zeros in the returned rows).
    """
    owned, owned_mask, owned_gid, halo, halo_mask, halo_gid = arrays
    if own_core is None:
        own_core_dev, counts_band = _oc_counts_step(
            *arrays, eps=float(eps), min_samples=int(min_samples),
            metric=metric, block=block, mesh=mesh, axis=axis,
            precision=precision, backend=backend, pair_budget=pair_budget,
        )
        own_core = dist.fetch_np(own_core_dev)
        counts_band_np = dist.fetch_np(counts_band).reshape(-1, 2)
    else:
        own_core = np.asarray(own_core)
        # graftlint: disable=device-put-aliasing -- own_core is a
        # fresh np.asarray copy made one line up, never pool-borrowed
        own_core_dev = jax.device_put(
            own_core, NamedSharding(mesh, P(axis))
        )
        counts_band_np = np.zeros((own_core.shape[0], 2), np.int64)
    if overflow is not None and int(dist.fetch_np(overflow).sum()) != 0:
        raise _HaloOverflow()
    og_np = dist.fetch_np(owned_gid)
    hg_np = dist.fetch_np(halo_gid)
    n = int(n_points)
    core_full = np.zeros(n + 1, bool)
    og_flat = og_np.reshape(-1)
    sel = og_flat < n
    core_full[og_flat[sel]] = own_core.reshape(-1)[sel]
    halo_core = core_full[np.clip(hg_np, 0, n)] & (hg_np < n)
    sharding = NamedSharding(mesh, P(axis))
    own_glab, halo_glab, pstats = _oc_cluster_step(
        # graftlint: disable=device-put-aliasing -- halo_core is a
        # fresh fancy-indexing product of this function
        *arrays, own_core_dev, jax.device_put(halo_core, sharding),
        eps=float(eps), metric=metric, block=block, mesh=mesh, axis=axis,
        precision=precision, backend=backend, pair_budget=pair_budget,
    )
    # Fold the counts program's band columns into the per-device rows
    # (host-side: the two passes are separate programs on this route).
    cb = counts_band_np
    pstats_np = np.array(dist.fetch_np(pstats)).reshape(cb.shape[0], -1)
    pstats_np[:, 3:5] += cb
    return own_glab, own_core_dev, halo_glab, pstats_np


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis", "hcap")
)
def ring_exchange_step(
    owned, owned_mask, owned_gid, exp_lo, exp_hi, *, mesh, axis, hcap
):
    """The device-resident ring halo exchange as its OWN program.

    Separate from the cluster+merge program on purpose: the axon TPU
    compiler's fusion pass CHECK-fails outright (scatter_emitter.cc,
    ``operand_indices.size() == 1``) when the exchange and the merge
    share one module — each compiles and runs fine alone — and the
    split also lets the ring path chain into the very same compiled
    :func:`sharded_step` the host-halo path uses.  The two programs
    chain asynchronously on device, so the split costs dispatch
    latency only.

    NOTE: the halo import lives at module top, not in this traced
    body — an import executed mid-trace runs halo.py's module body
    under the trace, and any module-level jax constant it created
    leaked as a tracer (order-dependent UnexpectedTracerError
    depending on which fit imported what first; halo.py's constants
    are now numpy scalars as a second line of defense).
    """

    def per_device(o, om, og, lo, hi):
        return ring_halo_exchange_multi(o, om, og, lo, hi, hcap, axis)

    spec = P("p", None, None)
    spec2 = P("p", None)
    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(spec, spec2, spec2, spec2, spec2),
        out_specs=(spec, spec2, spec2, P("p")),
        check_vma=False,
    )(owned, owned_mask, owned_gid, exp_lo, exp_hi)


def sharded_step_ring(
    owned, owned_mask, owned_gid, exp_lo, exp_hi,
    *, eps, min_samples, metric, block, mesh, axis, n_points,
    precision="high", backend="auto", hcap, pair_budget=None,
    merge_rounds=32, owner_computes=False,
):
    """Sharded clustering with a device-resident ring halo exchange.

    Like :func:`sharded_step`, but halos never touch the host: each
    device's owned slab circulates the ring (``ppermute`` over ICI) and
    every device keeps the points inside its partitions' 2*eps-expanded
    boxes (:mod:`pypardis_tpu.parallel.halo` — any number of partitions
    per device; the round-2 design required exactly one).  Two chained
    device programs (see :func:`ring_exchange_step` for why).  Returns
    ``(labels, core, overflow, pair_stats, rounds, converged)`` —
    ``overflow`` is the per-partition count of in-box points dropped
    for capacity; nonzero means rerun with a larger ``hcap``.
    """
    halo, halo_mask, halo_gid, overflow = ring_exchange_step(
        owned, owned_mask, owned_gid, exp_lo, exp_hi,
        mesh=mesh, axis=axis, hcap=hcap,
    )
    labels, core, pstats, rounds, converged = sharded_step(
        owned, owned_mask, owned_gid, halo, halo_mask, halo_gid,
        eps=eps, min_samples=min_samples, metric=metric, block=block,
        mesh=mesh, axis=axis, n_points=n_points, precision=precision,
        backend=backend, pair_budget=pair_budget,
        merge_rounds=merge_rounds, owner_computes=owner_computes,
    )
    return labels, core, overflow, pstats, rounds, converged


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def _with_kernel_fallback(fn, backend):
    """Run ``fn(backend)``; if 'auto' selected a Pallas kernel that fails
    to lower on this chip, degrade to the XLA path with a warning (see
    ops.labels.is_kernel_lowering_error).  Explicit 'pallas' stays
    strict."""
    try:
        return fn(backend)
    except Exception as e:  # noqa: BLE001 — rethrown unless a kernel fails
        from ..ops.labels import is_kernel_lowering_error
        from ..utils.log import get_logger

        if backend != "auto" or not is_kernel_lowering_error(e):
            raise
        get_logger().warning(
            "Pallas kernel failed to lower on %s; falling back to the "
            "XLA kernel path (%s)", jax.default_backend(), e,
        )
        # The Pallas→XLA fallback is the first graceful-degradation
        # rung (label-safe: the XLA kernels are pinned byte-identical).
        note_degraded("kernel_xla", error=str(e)[:160])
        return fn("xla")


# Shard-layout/config keys whose fused step program has already been
# traced in this process — telemetry only (events.compile separates
# cold fits from warm ones in DBSCAN.report(); the chained 1-device
# paths have their own _chained_compiled bookkeeping).
_fused_compiled: set = set()


def _note_first_compile(stage: str, key) -> None:
    if key not in _fused_compiled:
        _fused_compiled.add(key)
        obs_event("compile", stage=stage)


# Above this point count, merge='auto' reconciles labels on the host:
# the in-graph merge replicates five (N+1,)-sized int32/bool arrays per
# device (~20 bytes/point/device, ~2GB at 100M) which eventually stops
# fitting beside the point data; the host merge ships only compact
# per-slot label tables.
MERGE_HOST_AUTO = 32_000_000


def _sharded_hint_key(owned_shape, halo_cap, block, precision, eps, metric):
    """Pair-budget hint key for the sharded path (utils.hints cache).

    The binding extraction runs per partition over (cap + hcap) points,
    so both capacities key the entry; eps/metric shape the live-pair
    count directly; the dispatch-mode tag keeps dense-grid budgets from
    over-reserving the compacted kernels (and vice versa).  The
    resolved sketch k keys too: sketch-space tile boxes prune to a
    different live-pair count than full-d boxes, so budgets learned
    under one prefilter setting must not seed the other.
    """
    from ..ops.distances import _norm_metric
    from ..ops.sketch import sketch_dims
    from ..utils.hints import dispatch_tag

    nt = (int(owned_shape[-2]) + int(halo_cap)) // max(int(block), 1)
    sk = sketch_dims(int(owned_shape[-1]), _norm_metric(metric))
    return (
        "sharded", dispatch_tag(nt), tuple(owned_shape), int(halo_cap),
        block, precision, float(eps), str(metric), sk,
    )


class _HaloOverflow(Exception):
    """Ring halo buffer dropped in-box points; the hcap ladder retries."""


def _ring_halo_bytes(stats, hcap, k):
    """Ring-path halo traffic telemetry: the f32 halo-buffer capacity
    bytes each fit ships over the interconnect (the ring exchange fills
    fixed-size buffers, so capacity — not occupancy — is what moves)."""
    return int(stats["n_shard_partitions"]) * int(hcap) * int(k) * 4


def _oc_applies(owner_computes, mesh, p_total) -> bool:
    """Whether the owner-computes step runs: everywhere except the
    1-device chained path (see :func:`sharded_step` — its per-partition
    dispatches cannot share a replicated core table)."""
    return bool(owner_computes) and not (
        mesh.devices.size == 1 and int(p_total) > 1
    )


def _exec_stats(stats, *, oc_on, pstats, block, k, precision, n,
                metric="euclidean"):
    """Fold the execution telemetry every sharded route shares into
    ``stats``: the owner-computes mode, the clustered-volume
    ``duplicated_work_factor`` (slots whose core status is computed
    locally, over dataset points — owner-computes counts only owned
    slots, the legacy step counts owned + every halo duplicate), the
    staging-reuse byte counters, and the live-pair / kernel-pass /
    effective-tile numbers behind ``obs.report``'s FLOP model."""
    p_total = int(stats["n_shard_partitions"])
    cap = int(stats["owned_cap"])
    hcap = int(stats.get("halo_cap", 0))
    clustered = p_total * (cap if oc_on else cap + hcap)
    stats["owner_computes"] = bool(oc_on)
    stats["duplicated_work_factor"] = float(clustered) / max(n, 1)
    reused, shipped = staging.fit_stats()
    stats["staged_bytes_reused"] = int(reused)
    stats["staged_bytes"] = int(shipped)
    if pstats is not None:
        ps = dist.fetch_np(pstats)
        ps = ps.reshape(-1, ps.shape[-1])
        stats["live_pairs"] = int(ps[:, 0].max())
        if ps.shape[1] > 2:
            stats["kernel_passes"] = int(ps[:, 2].max())
        if ps.shape[1] > 4:
            # Mixed-precision band telemetry: worst-case device (the
            # same convention as live_pairs — the binding serial path).
            stats["band_pairs"] = int(ps[:, 3].max())
            stats["rescored_tiles"] = int(ps[:, 4].max())
        from ..ops.pallas_kernels import (
            _norm_precision_mode, effective_tile,
        )

        stats["kernel_block"] = int(
            effective_tile(
                block, max(cap + hcap, 1), int(k),
                _norm_precision_mode(precision),
            ) or block
        )
        # Per-partition slab tiles: live_pair_fraction's denominator is
        # tiles^2 (live_pairs is the worst-case partition's total over
        # the same slab grid, so the fraction is bounded by 1).
        stats["kernel_tiles"] = int(
            -(-max(cap + hcap, 1) // stats["kernel_block"])
        )
    # Resolved random-projection prefilter width (0 = off).  Resolved
    # here at REPORT time from the same env the kernels read at trace
    # time; a mid-session env flip without jax.clear_caches() can make
    # this stale relative to an already-compiled program — telemetry
    # only, labels are sketch-neutral for any k.
    from ..ops.distances import _norm_metric
    from ..ops.sketch import sketch_dims

    stats["sketch_k"] = int(sketch_dims(int(k), _norm_metric(metric)))
    return stats


def _host_merge_finish(n, og, own_glab, own_core, halo_gid, halo_glab):
    """Host-side finish shared by both halo paths under ``merge='host'``:
    rebuild (N,) home labels/core from the owned tables, then union the
    halo occurrence tables (:func:`merge.merge_occurrences`)."""
    from .merge import merge_occurrences

    own_glab = dist.fetch_np(own_glab).reshape(-1)
    own_core = dist.fetch_np(own_core).reshape(-1)
    og_flat = dist.fetch_np(og).reshape(-1)
    sel = og_flat < n
    home_label = np.full(n, -1, np.int32)
    home_label[og_flat[sel]] = own_glab[sel]
    core = np.zeros(n, bool)
    core[og_flat[sel]] = own_core[sel]
    labels, _mapping = merge_occurrences(
        home_label, core, dist.fetch_np(halo_gid),
        dist.fetch_np(halo_glab)
    )
    return labels, core


def sharded_dbscan(
    points,
    partitioner,
    eps: float,
    min_samples: int,
    metric="euclidean",
    block: int = 1024,
    mesh: Optional[Mesh] = None,
    precision: str = "high",
    backend: str = "auto",
    halo: str = "host",
    hcap: Optional[int] = None,
    merge: str = "auto",
    pair_budget: Optional[int] = None,
    merge_rounds: int = 32,
    stream: Optional[bool] = None,
    owner_computes: bool = True,
    overlap: Optional[bool] = None,
    mode: str = "kd",
    jobstate=None,
):
    """Cluster ``points`` over the device mesh.

    Returns ``(labels, core, stats)`` where labels are global root-gid
    labels (-1 noise) for the original point order.

    ``mode``: ``"kd"`` (default) is the KD-partition + 2*eps-halo
    family this function has always run, selected further by ``halo``/
    ``merge``/``owner_computes``.  ``"global_morton"`` dispatches to
    the zero-duplication global-Morton engine
    (:func:`pypardis_tpu.parallel.global_morton.global_morton_dbscan`):
    shards are contiguous ranges of the global Morton order — no
    partitioner, no halo slabs, ``duplicated_work_factor == 1.0`` by
    construction — and only boundary TILES ride the exchange ring.
    Under that mode ``partitioner`` may be None and the KD-specific
    knobs (``halo``/``hcap``/``owner_computes``/``overlap``) are
    ignored; ``stream`` threads through (``None`` auto-streams memmap
    inputs via the external sample-sort build, so the fastest engine
    is no longer the only one that cannot run out-of-core).

    ``owner_computes`` (default True) clusters each device's OWNED
    slots only: halo slots contribute neighbor counts and relay
    adjacency but are never re-clustered, cutting the per-device
    clustered volume from ``owned * (1 + halo_factor)`` back to
    ``owned`` (``stats["duplicated_work_factor"]``).  ``False`` runs
    the legacy full-slab step (the reference's duplicate-and-recluster
    semantics); labels are identical either way.  The 1-device chained
    path always runs legacy (reported via ``stats["owner_computes"]``).

    ``halo``: ``"host"`` materializes halo slabs on the host from one
    vectorized box query (build_shards); ``"ring"`` ships only owned
    slabs and exchanges halos device-side via ``ppermute`` over the
    mesh interconnect (any ``max_partitions``; the host never computes
    halo sets).  ``hcap`` caps the ring halo buffer per partition
    (rounded up to a block multiple) and overflow raises; ``None``
    starts at half the owned capacity and doubles on overflow (each
    retry recompiles).

    ``merge``: ``"device"`` reconciles cross-partition labels in-graph
    (pmin collectives over replicated (N+1,) arrays — the lowest
    latency path); ``"host"`` pulls compact per-slot label tables and
    merges on the host (:mod:`pypardis_tpu.parallel.merge` — the
    memory-safe path when N-sized replicated arrays stop fitting,
    ~20 bytes/point/device); ``"auto"`` switches to host past
    ``MERGE_HOST_AUTO`` points on EITHER halo path.  Under
    ``halo="ring"`` the host merge still exchanges halos device-side;
    only the compact occurrence tables (gid + label per halo slot,
    ~8 bytes/occurrence) come to the host — never coordinates.

    ``pair_budget``: static live tile-pair capacity for the kernels'
    pair extraction; ``None`` consults the shared hint cache
    (utils.hints) and otherwise lets the kernel default apply —
    overflow is detected from the in-band stats and retried once with
    the exact total (a persisting overflow raises).  ``merge_rounds``
    caps the in-graph merge loop; non-convergence retries once at 4x
    and then raises (never returns under-merged labels silently).

    ``stream``: build and ship shard slabs one DEVICE at a time
    (:func:`build_owned_shards_streaming`) so a disk-backed
    ``np.memmap`` larger than host RAM clusters from disk — requires
    ``halo='ring'``.  ``None`` auto-enables it for memmap inputs on
    the ring path.

    ``overlap``: double-buffer the 1-device chained route — build +
    ship partition ``i+1``'s slabs while the device executes partition
    ``i`` (:func:`_chained_tables_overlap`; labels byte-identical to
    the serial build).  ``None`` reads the PYPARDIS_CHAINED_OVERLAP
    env kill-switch and defaults on; a warm stacked-array cache from a
    previous non-overlapped fit still wins (nothing left to overlap).
    Multi-device meshes and the ring path are unaffected.
    """
    from ..ops.distances import _norm_metric
    from .mesh import default_mesh

    if mode == "global_morton":
        from .global_morton import global_morton_dbscan

        # ``stream`` threads through (None auto-enables the external
        # sample-sort build for memmap inputs — the same dispatch the
        # KD ring route has below); the KD-only knobs stay ignored.
        return global_morton_dbscan(
            points, eps=eps, min_samples=min_samples, metric=metric,
            block=block, mesh=mesh, precision=precision, backend=backend,
            merge=merge, pair_budget=pair_budget,
            merge_rounds=merge_rounds, stream=stream,
            jobstate=jobstate,
        )
    if mode != "kd":
        raise ValueError(
            f"mode must be 'kd' or 'global_morton', got {mode!r}"
        )
    metric = _norm_metric(metric)
    if merge not in ("auto", "device", "host"):
        raise ValueError(f"merge must be auto|device|host, got {merge!r}")
    if merge == "auto":
        # Both halo paths can spill the merge to the host (round-4
        # review, Next #6: the ring route used to pin merge='device',
        # so a 100M device-resident fit would replicate ~5 (N+1)-arrays
        # per device in-graph).  Under host-RSS pressure
        # (PYPARDIS_RSS_SOFT_LIMIT crossed — obs.resources) the
        # host-spill rung is taken PREEMPTIVELY: the in-graph merge's
        # replicated (N+1,) arrays are exactly the allocation a
        # watermarked host should not gamble on.
        from ..obs.resources import memory_pressure

        merge = (
            "host"
            if len(points) >= MERGE_HOST_AUTO or memory_pressure()
            else "device"
        )
    if mesh is None:
        mesh = default_mesh()
    n_shards = mesh.devices.size
    axis = mesh.axis_names[0]

    # Size tile blocks to the data: tiny problems shouldn't pay for
    # 1024-wide padding, big ones keep the MXU-friendly width.
    approx = max(len(p) for p in partitioner.partitions.values())
    block = clamp_block(block, approx)

    if stream is None:
        stream = halo == "ring" and isinstance(points, np.memmap)
    if stream and halo != "ring":
        raise ValueError(
            "stream=True requires halo='ring': the streaming build "
            "never materializes host halo slabs"
        )

    def _spill_to_host_merge(e: BaseException):
        # Graceful-degradation rung: a terminal OOM-class failure under
        # merge='device' (its replicated (N+1,) arrays are the hungriest
        # allocation of the fit) reruns with the compact host union-find
        # spill.  Label-safe: both merges are pinned byte-identical.
        note_degraded(
            "merge_host", mode="kd", error=str(e)[:160]
        )
        return sharded_dbscan(
            points, partitioner, eps, min_samples, metric=metric,
            block=block, mesh=mesh, precision=precision, backend=backend,
            halo=halo, hcap=hcap, merge="host", pair_budget=pair_budget,
            merge_rounds=merge_rounds, stream=stream,
            owner_computes=owner_computes, overlap=overlap,
            jobstate=jobstate,
        )

    sharding = NamedSharding(mesh, P(axis))
    staging.begin_fit()
    n, k = points.shape
    host_bufs: list = []
    if halo == "ring":
        with obs_span("sharded.build_shards", halo="ring",
                      stream=bool(stream)):
            if stream:
                arrays, exp_lo, exp_hi, _labels_sorted, stats = (
                    build_owned_shards_streaming(
                        points, partitioner, eps, block, mesh
                    )
                )
                args = (
                    *arrays,
                    jax.device_put(exp_lo, sharding),
                    jax.device_put(exp_hi, sharding),
                )
            else:
                args, stats, host_bufs = _ring_build_cached(
                    points, partitioner, eps, n_shards, block, sharding
                )
        oc_on = _oc_applies(
            owner_computes, mesh, stats["n_shard_partitions"]
        )
        _note_first_compile(
            "sharded_ring",
            (args[0].shape, block, precision, backend, merge, hcap,
             oc_on),
        )
        with obs_span("sharded.execute", halo="ring", merge=merge):
            try:
                out, pstats = _ring_ladder(
                    args, eps=eps, min_samples=min_samples, metric=metric,
                    block=block, mesh=mesh, axis=axis, n_points=n,
                    precision=precision, backend=backend, hcap=hcap,
                    pair_budget=pair_budget, merge_rounds=merge_rounds,
                    cap=int(stats["owned_cap"]), merge=merge,
                    owner_computes=oc_on,
                )
            except Exception as e:  # noqa: BLE001 — rethrown below
                if merge != "device" or not is_degradable_error(e):
                    raise
                staging.give_back_after_put(host_bufs)
                return _spill_to_host_merge(e)
        if merge == "host":
            tables, _zero, used_hcap = out
            own_glab, own_core, halo_glab, halo_gid = tables
            labels, core = _host_merge_finish(
                n, args[2], own_glab, own_core, halo_gid, halo_glab,
            )
            stats = dict(
                stats, halo_exchange="ring", halo_cap=used_hcap,
                merge="host",
                halo_bytes=_ring_halo_bytes(stats, used_hcap, k),
            )
            _exec_stats(stats, oc_on=oc_on, pstats=pstats, block=block,
                        k=k, precision=precision, n=n, metric=metric)
            staging.give_back_after_put(host_bufs)
            return _canonicalize_roots(labels, core), core, stats
        labels, core, m_rounds, used_hcap = out
        stats = dict(
            stats, halo_exchange="ring", halo_cap=used_hcap,
            merge_rounds=int(m_rounds), merge_converged=True,
            halo_bytes=_ring_halo_bytes(stats, used_hcap, k),
        )
        labels, core = dist.fetch_np(labels), dist.fetch_np(core)
        _exec_stats(stats, oc_on=oc_on, pstats=pstats, block=block,
                    k=k, precision=precision, n=n, metric=metric)
        staging.give_back_after_put(host_bufs)
        return _canonicalize_roots(labels, core), core, stats
    if (
        mesh.devices.size == 1
        and len(partitioner.partitions) > 1
        and _overlap_enabled(overlap)
    ):
        base_key = _sharding_cache_key(
            points, partitioner, n_shards, block, sharding
        )
        if not staging.device_peek("host_owned", base_key):
            # The double-buffered chained route: per-partition host
            # build + transfer overlapped with device execution.  A
            # live stacked-array cache (a previous non-overlapped fit)
            # falls through instead — its warm path has no host work
            # left to hide.
            try:
                return _sharded_dbscan_1dev_overlap(
                    points, partitioner, eps=eps, min_samples=min_samples,
                    metric=metric, block=block, mesh=mesh, axis=axis,
                    n_points=n, precision=precision, backend=backend,
                    merge=merge, pair_budget=pair_budget,
                    merge_rounds=merge_rounds, n_shards=n_shards,
                    base_key=base_key, jobstate=jobstate,
                )
            except Exception as e:  # noqa: BLE001 — rethrown below
                if merge != "device" or not is_degradable_error(e):
                    raise
                return _spill_to_host_merge(e)
    with obs_span("sharded.build_shards", halo="host"):
        arrays, stats, host_bufs = _host_build_cached(
            points, partitioner, eps, n_shards, block, sharding
        )
    oc_on = _oc_applies(owner_computes, mesh, stats["n_shard_partitions"])
    hint_key = _sharded_hint_key(
        arrays[0].shape, arrays[3].shape[1], block, precision, eps, metric
    ) + (oc_on,)
    _note_first_compile(
        "sharded_step",
        (arrays[0].shape, arrays[3].shape, block, precision, backend,
         merge, oc_on),
    )

    if merge == "host":

        def run_step(pb, _mr):
            if oc_on:
                out = _with_kernel_fallback(
                    lambda be: _oc_host_tables(
                        arrays,
                        eps=eps,
                        min_samples=min_samples,
                        metric=metric,
                        block=block,
                        mesh=mesh,
                        axis=axis,
                        n_points=n,
                        precision=precision,
                        backend=be,
                        pair_budget=pb,
                    ),
                    backend,
                )
                return out[:3], out[3], True
            out = _with_kernel_fallback(
                lambda be: sharded_step_local(
                    *arrays,
                    eps=float(eps),
                    min_samples=int(min_samples),
                    metric=metric,
                    block=block,
                    mesh=mesh,
                    axis=axis,
                    precision=precision,
                    backend=be,
                    pair_budget=pb,
                ),
                backend,
            )
            # The host union-find merge is exact — no rounds ladder.
            return out[:3], out[3], True

        with obs_span("sharded.execute", halo="host", merge="host"):
            (own_glab, own_core, halo_glab), pstats = run_ladders(
                run_step, hint_key, pair_budget, merge_rounds
            )
        with obs_span("sharded.merge_host"):
            # arrays[2]: (P, cap) owned gids; arrays[5]: halo gids
            labels, core = _host_merge_finish(
                n, arrays[2], own_glab, own_core, arrays[5], halo_glab,
            )
        stats = dict(stats, merge="host")
        _exec_stats(stats, oc_on=oc_on, pstats=pstats, block=block,
                    k=k, precision=precision, n=n, metric=metric)
        staging.give_back_after_put(host_bufs)
        return _canonicalize_roots(labels, core), core, stats

    def run_step(pb, mr):
        # Injection site for the degradation-rung tests: an injected
        # OOM here escapes run_ladders (which only handles capacity
        # overflows) and lands in the merge-spill handler below.
        faults.maybe_fail("sharded.execute")
        labels, core, pstats, m_rounds, converged = _with_kernel_fallback(
            lambda be: sharded_step(
                *arrays,
                eps=float(eps),
                min_samples=int(min_samples),
                metric=metric,
                block=block,
                mesh=mesh,
                axis=axis,
                n_points=n,
                precision=precision,
                backend=be,
                pair_budget=pb,
                merge_rounds=mr,
                owner_computes=oc_on,
            ),
            backend,
        )
        return (labels, core, m_rounds), pstats, converged

    with obs_span("sharded.execute", halo="host", merge="device"):
        try:
            (labels, core, m_rounds), pstats = run_ladders(
                run_step, hint_key, pair_budget, merge_rounds
            )
        except Exception as e:  # noqa: BLE001 — rethrown below
            if not is_degradable_error(e):
                raise
            staging.give_back_after_put(host_bufs)
            return _spill_to_host_merge(e)
    stats = dict(
        stats, merge="device", merge_rounds=int(m_rounds),
        merge_converged=True,
    )
    labels, core = dist.fetch_np(labels), dist.fetch_np(core)
    _exec_stats(stats, oc_on=oc_on, pstats=pstats, block=block,
                k=k, precision=precision, n=n, metric=metric)
    staging.give_back_after_put(host_bufs)
    return _canonicalize_roots(labels, core), core, stats


def _ring_ladder(
    args, *, eps, min_samples, metric, block, mesh, axis, n_points,
    precision, backend, hcap, pair_budget, merge_rounds, cap,
    merge="device", owner_computes=False,
):
    """hcap doubling around the shared pair/rounds ladder for ring-halo
    execution.  ``args``: (owned, mask, gid, exp_lo, exp_hi), already
    placed with the partition-axis sharding.

    ``merge="device"`` runs the fused ring+cluster+in-graph-merge
    program and returns ``(labels, core, merge_rounds_used, hcap)``.
    ``merge="host"`` SPILLS to the host merge (round-4 review, Next #6:
    past ~32M points the in-graph merge replicates five (N+1)-arrays
    per device): the ring exchange still runs device-side, the cluster
    step is :func:`sharded_step_local` (legacy) or the two-program
    owner-computes flow (:func:`_oc_host_tables`), and the return is
    the compact occurrence tables ``((own_glab, own_core, halo_glab,
    halo_gid), 0, hcap)`` for
    :func:`pypardis_tpu.parallel.merge.merge_occurrences`.

    Returns ``(out_with_hcap, pstats)`` — the ladder outputs with the
    final hcap appended, plus the pair stats for driver telemetry.
    """
    explicit = hcap is not None
    this_hcap = (
        round_up(int(hcap), block) if explicit
        else round_up(max(block, cap // 2), block)
    )
    hcap_attempts = 1 if explicit else 4
    while True:
        # hcap changes the tile count, so it keys the hint too.
        hint_key = _sharded_hint_key(
            args[0].shape, this_hcap, block, precision, eps, metric
        ) + (bool(owner_computes),)

        def run_step(pb, mr, hc=this_hcap):
            if merge == "host":
                halo, halo_mask, halo_gid, overflow = ring_exchange_step(
                    *args, mesh=mesh, axis=axis, hcap=hc
                )
                if owner_computes:
                    # The owner-computes flow syncs mid-way anyway (the
                    # counts fetch), so the overflow check rides that
                    # sync — still before the propagation program.
                    own_glab, own_core, halo_glab, pstats = (
                        _with_kernel_fallback(
                            lambda be: _oc_host_tables(
                                (args[0], args[1], args[2],
                                 halo, halo_mask, halo_gid),
                                eps=eps,
                                min_samples=min_samples,
                                metric=metric,
                                block=block,
                                mesh=mesh,
                                axis=axis,
                                n_points=n_points,
                                precision=precision,
                                backend=be,
                                pair_budget=pb,
                                overflow=overflow,
                            ),
                            backend,
                        )
                    )
                    return (
                        (own_glab, own_core, halo_glab, halo_gid), 0
                    ), pstats, True
                # The cluster program dispatches WITHOUT waiting on the
                # overflow fetch — the two device programs chain
                # asynchronously (the point of the ring split), and a
                # host sync here would cost ~0.2s of tunnel latency on
                # every fit.  On the rare overflow the clustered result
                # is discarded and the hcap ladder retries.
                own_glab, own_core, halo_glab, pstats = (
                    _with_kernel_fallback(
                        lambda be: sharded_step_local(
                            args[0], args[1], args[2],
                            halo, halo_mask, halo_gid,
                            eps=float(eps),
                            min_samples=int(min_samples),
                            metric=metric,
                            block=block,
                            mesh=mesh,
                            axis=axis,
                            precision=precision,
                            backend=be,
                            pair_budget=pb,
                        ),
                        backend,
                    )
                )
                if int(dist.fetch_np(overflow).sum()) != 0:
                    raise _HaloOverflow()
                # The host union-find merge is exact — no rounds ladder.
                return (
                    (own_glab, own_core, halo_glab, halo_gid), 0
                ), pstats, True
            labels, core, overflow, pstats, m_rounds, converged = (
                _with_kernel_fallback(
                    lambda be: sharded_step_ring(
                        *args,
                        eps=float(eps),
                        min_samples=int(min_samples),
                        metric=metric,
                        block=block,
                        mesh=mesh,
                        axis=axis,
                        n_points=n_points,
                        precision=precision,
                        backend=be,
                        hcap=hc,
                        pair_budget=pb,
                        merge_rounds=mr,
                        owner_computes=owner_computes,
                    ),
                    backend,
                )
            )
            # Halo capacity is checked FIRST: with dropped in-box
            # points the pair stats and merge result are moot.
            if int(dist.fetch_np(overflow).sum()) != 0:
                raise _HaloOverflow()
            return (labels, core, m_rounds), pstats, converged

        try:
            out, pstats = run_ladders(
                run_step, hint_key, pair_budget, merge_rounds
            )
        except _HaloOverflow:
            obs_event(
                "halo_overflow", hcap=this_hcap,
                retry=hcap_attempts > 1,
            )
            hcap_attempts -= 1
            if hcap_attempts <= 0:
                from ..utils.retry import note_giveup

                err = RuntimeError(
                    f"ring halo buffer overflow at hcap={this_hcap}; "
                    f"pass a larger hcap"
                    if explicit
                    else f"ring halo buffer overflow persisted up to "
                    f"hcap={this_hcap}"
                )
                note_giveup("ring.hcap", err)
                raise err from None
            from ..utils.retry import note_retry

            note_retry(
                "ring.hcap", 0.0,
                RuntimeError(f"halo overflow at hcap={this_hcap}"),
            )
            this_hcap *= 2
            continue
        return (*out, this_hcap), pstats


def sharded_dbscan_device(
    points,
    eps: float,
    min_samples: int,
    metric="euclidean",
    block: int = 1024,
    mesh: Optional[Mesh] = None,
    precision: str = "high",
    backend: str = "auto",
    hcap: Optional[int] = None,
    pair_budget: Optional[int] = None,
    merge_rounds: int = 32,
    max_partitions: Optional[int] = None,
    split_method: str = "min_var",
    sample_size: int = 262_144,
    seed: int = 0,
    merge: str = "auto",
    owner_computes: bool = True,
):
    """Cluster a DEVICE-RESIDENT ``jax.Array`` over the mesh without a
    host round trip of the dataset.

    ``owner_computes``: as in :func:`sharded_dbscan` — owned-only
    clustering with halo slots as adjacency evidence (default True).

    ``merge``: as in :func:`sharded_dbscan` — ``"auto"`` spills the
    label merge to the host past ``MERGE_HOST_AUTO`` points (the
    in-graph merge replicates ~5 (N+1)-arrays per device); the spill
    fetches only the compact occurrence tables (per-slot gid + label
    ints), never the coordinates, so the no-dataset-fetch contract of
    this route holds at every N.

    The TPU analogue of the reference's ``train(rdd)`` on
    already-distributed data (``/root/reference/dbscan/dbscan.py:104``):
    KD split boundaries come from a small host subsample; routing, the
    Morton slab layout, per-partition boxes, the ring halo exchange,
    clustering, and the in-graph merge all run on device
    (:mod:`pypardis_tpu.parallel.device_input`).  Host traffic is the
    subsample, the (P,) partition counts, and the (N,) label/core
    results — never the (N, k) coordinates.

    Returns ``(labels, core, stats, partitioner, pid)`` — ``pid`` is the
    device (N,) partition assignment (fetch it for the parity ``result``
    surface; it is ints, not the dataset), ``partitioner`` the
    subsample-built KDPartitioner whose tree routed the points.
    """
    from ..ops.distances import _norm_metric
    from ..partition import KDPartitioner
    from .device_input import (
        device_owned_layout,
        device_partition_counts,
        device_route,
        tree_arrays,
    )
    from .mesh import default_mesh

    metric = _norm_metric(metric)
    if mesh is None:
        mesh = default_mesh()
    n_shards = mesh.devices.size
    axis = mesh.axis_names[0]
    n, k = points.shape

    # KD boundaries from a host subsample — the statistically identical
    # move KDPartitioner's own sample_size makes host-side.
    rng = np.random.default_rng(seed)
    if n > sample_size:
        sel = np.sort(rng.choice(n, size=sample_size, replace=False))
        sample = np.asarray(points[jnp.asarray(sel)])
    else:
        sample = np.asarray(points)
    part = KDPartitioner(
        sample,
        max_partitions=(n_shards if max_partitions is None
                        else int(max_partitions)),
        split_method=split_method,
        sample_size=None,
    )
    p_total = round_up(max(part.n_partitions, n_shards), n_shards)

    pid = device_route(points, *map(jnp.asarray, tree_arrays(part.tree)))
    counts_dev = device_partition_counts(pid, p_total=p_total)
    max_count = int(dist.fetch_np(counts_dev).max())
    block = clamp_block(block, max_count)
    cap = round_up(max(max_count, 1), block)

    owned, msk, gid, lo, hi = device_owned_layout(
        points, pid, counts_dev, p_total=p_total, cap=cap
    )
    two_eps = jnp.float32(2 * eps)
    # 4-ULP widening matches the host path's _expanded_frame_meta
    # boundary-tolerance discipline: a plain f32 `lo - 2*eps` can round
    # the expanded boundary INWARD by 1 ULP, dropping a halo point
    # sitting exactly on the 2*eps shell that the host route keeps
    # (borderline core-status divergence between the two routes).
    exp_lo = lo - two_eps
    exp_hi = hi + two_eps
    exp_lo = exp_lo - 4 * (
        jnp.nextafter(jnp.abs(exp_lo), jnp.float32(jnp.inf)) - jnp.abs(exp_lo)
    )
    exp_hi = exp_hi + 4 * (
        jnp.nextafter(jnp.abs(exp_hi), jnp.float32(jnp.inf)) - jnp.abs(exp_hi)
    )
    sharding = NamedSharding(mesh, P(axis))
    args = tuple(
        # graftlint: disable=device-put-aliasing -- re-shards the
        # caller's device-resident jnp arrays; no host pool buffer
        jax.device_put(a, sharding)
        for a in (owned, msk, gid, exp_lo, exp_hi)
    )
    if merge not in ("auto", "device", "host"):
        raise ValueError(f"merge must be auto|device|host, got {merge!r}")
    if merge == "auto":
        merge = "host" if n >= MERGE_HOST_AUTO else "device"
    staging.begin_fit()
    oc_on = _oc_applies(owner_computes, mesh, p_total)
    _note_first_compile(
        "sharded_ring",
        (args[0].shape, block, precision, backend, merge, hcap, oc_on),
    )
    with obs_span("sharded.execute", halo="ring", merge=merge,
                  input="device"):
        out, pstats = _ring_ladder(
            args, eps=eps, min_samples=min_samples, metric=metric,
            block=block, mesh=mesh, axis=axis, n_points=n,
            precision=precision, backend=backend, hcap=hcap,
            pair_budget=pair_budget, merge_rounds=merge_rounds, cap=cap,
            merge=merge, owner_computes=oc_on,
        )
    stats = {
        "owned_cap": cap,
        "n_shard_partitions": p_total,
        "pad_waste": float(p_total * cap) / max(n, 1) - 1.0,
        "partition_sizes": [int(c) for c in dist.fetch_np(counts_dev)],
        "input": "device",
        "halo_exchange": "ring",
    }
    if merge == "host":
        tables, _zero, used_hcap = out
        own_glab, own_core, halo_glab, halo_gid = tables
        labels, core = _host_merge_finish(
            n, args[2], own_glab, own_core, halo_gid, halo_glab
        )
        stats.update(
            halo_cap=used_hcap, merge="host",
            halo_bytes=_ring_halo_bytes(stats, used_hcap, k),
        )
        _exec_stats(stats, oc_on=oc_on, pstats=pstats, block=block,
                    k=k, precision=precision, n=n, metric=metric)
        return _canonicalize_roots(labels, core), core, stats, part, pid
    labels, core, m_rounds, used_hcap = out
    stats.update(
        halo_cap=used_hcap, merge_rounds=int(m_rounds),
        merge_converged=True,
        halo_bytes=_ring_halo_bytes(stats, used_hcap, k),
    )
    labels, core = dist.fetch_np(labels), dist.fetch_np(core)
    _exec_stats(stats, oc_on=oc_on, pstats=pstats, block=block,
                k=k, precision=precision, n=n, metric=metric)
    return _canonicalize_roots(labels, core), core, stats, part, pid


class SweepGraphOverflow(RuntimeError):
    """The neighbor-pair graph cannot fit the sweep's edge cap.

    A partial graph would silently miss cross-shard edges, so the
    drivers never relabel from one — ``DBSCAN.sweep`` catches this and
    degrades label-safely to per-config refits (k distance passes, the
    pre-sweep cost, never wrong labels)."""


def _sweep_slab_graph(
    pts, msk, gids, eps, *, owned_rows, metric, block, precision,
    edge_budget, pair_budget, cap_edges,
):
    """One shard slab's directed edges at ``eps``, in global-id space.

    ``pts``/``msk``: the (rows, k) slab (owned prefix + halo/boundary
    context); ``gids``: slab slot -> global point id (pad slots carry
    an arbitrary id — their entries are masked out of the emission).
    Runs the exact-total budget ladder (the PYPARDIS_PAIR_BUDGET
    conventions: overflow is signalled exactly, one retry suffices)
    and raises :class:`SweepGraphOverflow` past ``cap_edges``.
    Returns ``(gi, gj, dval, edge_budget, pair_budget)`` with the
    grown budgets so later shards start where this one ended.
    """
    from ..ops.distances import (
        default_edge_budget,
        neighbor_pair_graph,
        neighbor_pair_graph_host,
        sweep_emission_route,
    )

    rt = owned_rows // block
    if sweep_emission_route() == "host":
        # Host-compaction route (auto on CPU; PYPARDIS_SWEEP_EMISSION
        # forces either): the XLA scatter behind the device emission
        # runs single-threaded on CPU (measured 65x a counts pass);
        # numpy compaction of the same device-computed tiles is
        # memory-speed and budget-free.
        gi, gj, dv, st = neighbor_pair_graph_host(
            pts, msk, eps, metric=metric, block=block,
            precision=precision, layout="nd", row_tiles=rt,
            pair_budget=pair_budget,
        )
        if len(gi) > cap_edges:
            raise SweepGraphOverflow(
                f"neighbor-pair graph needs {len(gi)} edges on one "
                f"shard but the sweep cap is {cap_edges} "
                f"(PYPARDIS_SWEEP_MAX_PAIRS); the sweep degrades to "
                f"per-config refits"
            )
        gids = np.asarray(gids)
        return gids[gi], gids[gj], dv, edge_budget, int(st[3])
    eb = int(edge_budget or default_edge_budget(owned_rows))
    pb = pair_budget
    for attempt in (0, 1):
        gi, gj, dv, st = neighbor_pair_graph(
            pts, msk, eps, metric=metric, block=block,
            precision=precision, layout="nd", row_tiles=rt,
            budget=eb, pair_budget=pb,
        )
        st = np.asarray(st)
        need_e, got_e = int(st[0]), int(st[1])
        need_p, got_p = int(st[2]), int(st[3])
        # Cap check BEFORE the no-overflow break (the fused loop's
        # order): a graph that fits a generous budget must still
        # respect the slab cap — the device-route ladder used to test
        # the cap only after an overflow, a gap the forced-device CI
        # coverage (PYPARDIS_SWEEP_EMISSION) exposed.
        if need_e > cap_edges:
            raise SweepGraphOverflow(
                f"neighbor-pair graph needs {need_e} edges on one shard "
                f"but the sweep cap is {cap_edges} "
                f"(PYPARDIS_SWEEP_MAX_PAIRS); the sweep degrades to "
                f"per-config refits"
            )
        if need_e <= got_e and need_p <= got_p:
            break
        if attempt == 1:
            raise SweepGraphOverflow(
                f"graph emission overflow persisted after an exact-"
                f"total retry (edges {need_e}/{got_e}, tile pairs "
                f"{need_p}/{got_p})"
            )
        obs_event(
            "pair_overflow", total=need_e, budget=got_e,
            route="sweep_graph",
        )
        eb = round_up(max(need_e, 1), 4096)
        if need_p > got_p:
            pb = round_up(max(need_p, 1), 4096)
    dv_np = np.asarray(dv)
    sel = np.isfinite(dv_np)
    gids = np.asarray(gids)
    return (
        gids[np.asarray(gi)[sel]],
        gids[np.asarray(gj)[sel]],
        dv_np[sel],
        eb,
        pb,
    )


def sweep_graph_sharded(
    points,
    partitioner,
    eps,
    *,
    block: int = 1024,
    mesh=None,
    precision: str = "high",
    backend: str = "auto",
    metric: str = "euclidean",
    edge_budget: Optional[int] = None,
    pair_budget: Optional[int] = None,
    cap_edges: Optional[int] = None,
):
    """ONE distance pass at ``eps`` (the sweep's eps_max) over the KD
    owner-computes slabs → the GLOBAL neighbor-pair graph.

    The slab build rides the staging economy exactly like a fit
    (:func:`_host_build_cached`: owned slabs keyed WITHOUT eps, so a
    sweep after a fit — or a second sweep — re-ships only halos), and
    the 2*eps_max halo guarantees every true edge of every config
    ``eps_c <= eps_max`` is present: a neighbor within eps_c of an
    owned point sits inside the eps_max expansion by containment.
    Each directed edge is emitted exactly once, by its row's owner
    (owner-computes: halo slots are column evidence, never rows), so
    per-config counts over the graph are byte-identical to the
    owner-computes counts pass.

    Returns ``((gi, gj, dval) numpy arrays in global-id space,
    stats)``; the per-config relabel over this graph converges to the
    min-core-gid roots — the same canonical labels
    (:func:`_canonicalize_roots`) every sharded train() route emits.
    """
    from ..ops.distances import sweep_max_edges

    points = np.asarray(points)
    n, k = points.shape
    if mesh is None:
        from .mesh import default_mesh

        mesh = default_mesh()
    n_shards = mesh.devices.size
    axis = mesh.axis_names[0]
    sharding = NamedSharding(mesh, P(axis))
    if cap_edges is None:
        cap_edges = sweep_max_edges()
    with obs_span("sweep.build", mode="kd"):
        arrays, bstats, bufs = _host_build_cached(
            points, partitioner, eps, n_shards, block, sharding
        )
    owned, omsk, ogid, halo, hmsk, hgid = arrays
    p_total, cap, _k = owned.shape
    # ONE host gather of the slabs: per-shard indexing of the
    # mesh-sharded arrays dispatches cross-device collective programs
    # per slice (measured seconds each on the faked CPU mesh); the
    # emission pass runs per shard on the default device anyway, so
    # feeding it host slices keeps the loop collective-free.
    slabs = [dist.fetch_np(a) for a in arrays]
    owned_h, omsk_h, ogid_h, halo_h, hmsk_h, hgid_h = slabs
    out_i, out_j, out_d = [], [], []
    eb, pb = edge_budget, pair_budget
    with obs_span("sweep.extract", mode="kd", shards=int(p_total)):
        for p in range(p_total):
            pts = np.concatenate([owned_h[p], halo_h[p]], axis=0)
            msk = np.concatenate([omsk_h[p], hmsk_h[p]])
            gids = np.concatenate([ogid_h[p], hgid_h[p]])
            gi, gj, dv, eb, pb = _sweep_slab_graph(
                pts, msk, gids, eps, owned_rows=cap, metric=metric,
                block=min(block, cap), precision=precision,
                edge_budget=eb, pair_budget=pb, cap_edges=cap_edges,
            )
            out_i.append(gi)
            out_j.append(gj)
            out_d.append(dv)
    staging.give_back_after_put(bufs)
    gi = np.concatenate(out_i) if out_i else np.empty(0, np.int32)
    gj = np.concatenate(out_j) if out_j else np.empty(0, np.int32)
    dv = np.concatenate(out_d) if out_d else np.empty(0, np.float32)
    stats = {
        "mode": "kd",
        "owner_computes": True,
        "graph_pairs": int(len(gi)),
        "graph_bytes": int(len(gi)) * 12,
        "n_partitions": int(p_total),
        **{
            k_: bstats[k_]
            for k_ in (
                "owned_cap", "halo_cap", "halo_factor", "halo_bytes",
                "pad_waste", "partition_sizes", "n_shard_partitions",
            )
            if k_ in bstats
        },
    }
    return (gi, gj, dv), stats


def _canonicalize_roots(labels: np.ndarray, core: np.ndarray) -> np.ndarray:
    """Relabel each cluster to its minimum core-member gid.

    Per-partition roots are minimum *local indices* mapped through gids,
    so the merged cluster key depends on slab ordering (host Morton
    layout vs ring arrival order).  Canonicalizing to the min core gid
    makes sharded labels deterministic across halo paths and identical
    to the single-device kernel's root convention (min core index of
    the component).
    """
    n = len(labels)
    valid = (labels >= 0) & core
    mins = np.full(n + 1, np.iinfo(np.int64).max, np.int64)
    np.minimum.at(mins, labels[valid], np.arange(n)[valid])
    out = labels.copy()
    sel = labels >= 0
    out[sel] = mins[labels[sel]].astype(labels.dtype)
    return out
