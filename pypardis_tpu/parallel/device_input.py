"""Device-resident sharded input: route and lay out shards on device.

The reference's ``train(rdd)`` consumes *already-distributed* data
(``/root/reference/dbscan/dbscan.py:104``) — the driver never holds the
dataset.  The TPU analogue is a device-resident ``jax.Array``: the
round-3 sharded path bounced it through ``np.asarray`` and re-built the
whole layout host-side, paying a full device->host->device round trip
of the dataset.  This module removes the bounce:

* KD split boundaries come from a small host SUBSAMPLE (statistically
  identical for the moment-based strategies — partition.py's
  ``sample_size`` argument does the same thing host-side);
* everything that touches all N points — tree routing, Morton
  ordering, the (P, cap, k) slab gather, per-partition bounding
  boxes — runs on device in a handful of jitted programs;
* halos are exchanged device-side by the ring path
  (:mod:`pypardis_tpu.parallel.halo`), which never needed host halo
  tables in the first place.

Partition boxes here are the TIGHT boxes of each partition's routed
members (scatter-min/max in the recentred f32 frame), not the KD split
boxes: every owned point lies inside its tight box by construction, so
the 2*eps expansion argument (README.md:20 — an owned point's full
eps-ball is inside the expanded box) holds unchanged, and tighter boxes
only shrink the halo.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_BIG = np.float32(3e38)  # numpy scalar: trace-inert at import time


def tree_arrays(tree):
    """Split-tree records as flat arrays for the device router.

    ``tree``: [(parent_label, axis, boundary, left_label, right_label)]
    in construction order (KDPartitioner.tree).  Returns (parent, axis,
    boundary, right) — left children keep the parent label, so only the
    right label is needed.
    """
    if not tree:
        return (
            np.zeros(0, np.int32), np.zeros(0, np.int32),
            np.zeros(0, np.float32), np.zeros(0, np.int32),
        )
    parent = np.array([t[0] for t in tree], np.int32)
    axis = np.array([t[1] for t in tree], np.int32)
    boundary = np.array([t[2] for t in tree], np.float32)
    right = np.array([t[4] for t in tree], np.int32)
    return parent, axis, boundary, right


@jax.jit
def device_route(points, parent, axis, boundary, right):
    """Replay the split tree on device: (N,) partition label per point.

    Split semantics match :func:`pypardis_tpu.partition.route_tree`
    (strict ``<`` stays left, ``>=`` goes right) — a ``lax.scan`` over
    the tiny tree, each step one masked column compare over all points.
    Comparisons evaluate in float32 (JAX's default device precision;
    boundaries are f32-rounded in :func:`tree_arrays`), so a point
    within one f32 ULP of a split plane can route differently than the
    host's float64 replay.  That is immaterial for clustering:
    ownership stays a partition of unity either way, and the device
    path's boxes/halos derive from the ROUTED members, so every
    membership decision downstream is self-consistent.
    """
    n = points.shape[0]
    labels = jnp.zeros(n, jnp.int32)
    if parent.shape[0] == 0:
        return labels

    def body(lab, rec):
        p, a, b, r = rec
        c = jnp.take(points, a, axis=1).astype(jnp.float32)
        go_right = (lab == p) & (c >= b)
        return jnp.where(go_right, r, lab), None

    labels, _ = jax.lax.scan(body, labels, (parent, axis, boundary, right))
    return labels


@functools.partial(jax.jit, static_argnames=("p_total",))
def device_partition_counts(pid, *, p_total):
    return jnp.zeros(p_total, jnp.int32).at[pid].add(1)


@functools.partial(jax.jit, static_argnames=("p_total", "cap"))
def device_owned_layout(points, pid, counts, *, p_total, cap):
    """Gather routed points into Morton-sorted (P, cap, k) owned slabs.

    One global ``lexsort`` keyed (partition, morton-words) produces the
    partition-grouped, spatially-ordered permutation — the device
    analogue of the host layout's per-partition ``spatial_order`` pass.
    ``counts``: the (P,) per-partition counts the caller already built
    with :func:`device_partition_counts` (to size ``cap`` host-side) —
    passed in rather than recomputed.  Returns ``(owned, mask, gid,
    lo, hi)`` where the boxes are the TIGHT per-partition bounds in
    the recentred f32 frame (callers expand by 2*eps); empty/padding
    partitions carry inverted (+BIG, -BIG) boxes that match nothing.
    """
    from ..ops.pipeline import _device_morton_words

    n, k = points.shape
    # Centering by the (input-dtype) mean preserves distances exactly
    # and keeps f32 coordinates small for the matmul expansion — the
    # same contract as ops.pipeline.device_prep.
    center = jnp.mean(points, axis=0)
    xc = (points - center).astype(jnp.float32)
    words = _device_morton_words(xc.T, jnp.ones(n, bool))
    # jnp.lexsort: the LAST key is primary -> partition id first, then
    # morton words most-significant first within each partition.
    perm = jnp.lexsort(tuple(words[::-1]) + (pid,)).astype(jnp.int32)
    pid_s = pid[perm]
    start = jnp.cumsum(counts) - counts
    within = jnp.arange(n, dtype=jnp.int32) - start[pid_s]
    target = pid_s * cap + within
    # Rows are PLACED BY GATHER, never by a 2-D scatter: a 1-D int
    # scatter builds slot -> sorted-source (target is a bijection on
    # valid slots, so no collisions), and the row move is a gather
    # through it.  The axon XLA backend's scatter emitter CHECK-fails
    # outright on scatters with multi-dim operands
    # (scatter_emitter.cc: operand_indices.size() == 1), so row
    # scatters must not appear anywhere in this program.
    src = (
        jnp.full(p_total * cap, n, jnp.int32)
        .at[target]
        .set(jnp.arange(n, dtype=jnp.int32))
    )
    mask = src < n
    safe = jnp.clip(src, 0, n - 1)
    owned = jnp.where(mask[:, None], xc[perm[safe]], 0.0)
    gid = jnp.where(mask, perm[safe], n)
    owned = owned.reshape(p_total, cap, k)
    mask = mask.reshape(p_total, cap)
    # Tight per-partition boxes reduce straight off the slabs (empty
    # and padding partitions come out inverted: +BIG/-BIG).
    valid3 = mask[:, :, None]
    lo = jnp.min(jnp.where(valid3, owned, _BIG), axis=1)
    hi = jnp.max(jnp.where(valid3, owned, -_BIG), axis=1)
    return owned, mask, gid.reshape(p_total, cap), lo, hi
