"""Device-resident halo exchange over the mesh interconnect.

``build_shards`` (sharded.py) materializes each partition's 2*eps halo on
the **host** with a vectorized box query — fine when points start on the
host anyway.  This module is the device-resident alternative for data
that already lives sharded on the mesh: each device's owned slab rides a
**ring** of ``ppermute`` steps (ICI neighbor exchanges, the same pattern
ring attention uses for KV blocks), and every device filters the passing
slabs against its own 2*eps-expanded bounding box, compacting matches
into a fixed-capacity halo buffer.

This replaces the reference's neighborhood duplication
(``/root/reference/dbscan/dbscan.py:136-151`` — a Spark filter+union per
partition over the whole dataset) with P-1 neighbor exchanges and no
host round-trip.  Capacity is static (XLA shapes): callers size ``hcap``
and the returned ``overflow`` count says whether any in-box point had to
be dropped — the driver treats overflow as an error and re-runs with a
bigger capacity.

The exchanged slabs are *transport*, not a mandate to re-cluster: under
the owner-computes step (``sharded._device_cluster_merge_oc``) the
received halo rows serve only as neighbor-count evidence and relay
nodes, so the exchange's byte volume is the whole duplication cost the
ring path pays.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _compact_merge(halo, hmask, hgid, pts, valid, gid):
    """Merge flagged candidates into the fixed-size halo buffer.

    Stable sort by validity (valid rows first) over the concatenation,
    then keep the first hcap rows.  Stability keeps earlier halo entries
    in place, so repeated merges never reorder accepted points.
    """
    hcap = halo.shape[0]
    cat_pts = jnp.concatenate([halo, pts], axis=0)
    cat_msk = jnp.concatenate([hmask, valid], axis=0)
    cat_gid = jnp.concatenate([hgid, gid], axis=0)
    order = jnp.argsort(~cat_msk, stable=True)
    return (
        cat_pts[order[:hcap]],
        cat_msk[order[:hcap]],
        cat_gid[order[:hcap]],
        jnp.sum(cat_msk.astype(jnp.int32)) - jnp.sum(
            cat_msk[order[:hcap]].astype(jnp.int32)
        ),
    )


def ring_halo_exchange_multi(
    owned: jnp.ndarray,
    mask: jnp.ndarray,
    gid: jnp.ndarray,
    boxes_lo: jnp.ndarray,
    boxes_hi: jnp.ndarray,
    hcap: int,
    axis: str,
):
    """Collect each local partition's halo from the whole mesh.

    Must run inside ``shard_map``.  ``owned``: (L, cap, k) this
    device's partitions; ``mask``: (L, cap) validity; ``gid``: (L, cap)
    global point ids.  ``boxes_lo``/``boxes_hi``: (L, k) each
    partition's bounding box already expanded by 2*eps (the reference's
    duplication rule, README.md:20).  Returns ``(halo, halo_mask,
    halo_gid, overflow)`` with shapes (L, hcap, ...) / (L,).

    Round 0 filters the device's OWN slab (cross-partition halos within
    a device, excluding each partition's own points); rounds 1..n_dev-1
    circulate the full (L, cap) slab over the ring and filter remote
    points — so any ``L = n_partitions / n_devices`` works, not just
    one partition per device (round-2 restriction).
    """
    # jax.lax.axis_size only exists on newer jax; psum(1) over the axis
    # is the portable spelling of the same quantity.
    n_dev = (
        jax.lax.axis_size(axis)
        if hasattr(jax.lax, "axis_size")
        else jax.lax.psum(1, axis)
    )
    L, cap, k = owned.shape
    halo = jnp.zeros((L, hcap, k), owned.dtype)
    hmask = jnp.zeros((L, hcap), bool)
    hgid = jnp.full((L, hcap), jnp.int32(2**31 - 1))
    overflow = jnp.zeros((L,), jnp.int32)

    flat_pts = owned.reshape(L * cap, k)
    flat_msk = mask.reshape(L * cap)
    flat_gid = gid.reshape(L * cap)
    # Which local partition each flat slot belongs to (for the local
    # round's own-partition exclusion).
    part_of = jnp.repeat(jnp.arange(L, dtype=jnp.int32), cap)

    def filter_into(halo, hmask, hgid, overflow, pts, msk, gids, excl):
        def one(l, h, hm, hgd):
            inbox = (
                msk
                & jnp.all(pts >= boxes_lo[l][None, :], axis=1)
                & jnp.all(pts <= boxes_hi[l][None, :], axis=1)
            )
            if excl:
                inbox &= part_of != l
            return _compact_merge(h, hm, hgd, pts, inbox, gids)

        out = [one(l, halo[l], hmask[l], hgid[l]) for l in range(L)]
        return (
            jnp.stack([o[0] for o in out]),
            jnp.stack([o[1] for o in out]),
            jnp.stack([o[2] for o in out]),
            overflow + jnp.stack([o[3] for o in out]),
        )

    # Local round: other partitions on this device.  At L == 1 the
    # own-partition exclusion empties it — skip the wasted filter pass.
    if L > 1:
        halo, hmask, hgid, overflow = filter_into(
            halo, hmask, hgid, overflow, flat_pts, flat_msk, flat_gid, True
        )

    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def step(_i, state):
        buf_pts, buf_msk, buf_gid, halo, hmask, hgid, overflow = state
        buf_pts = jax.lax.ppermute(buf_pts, axis, perm)
        buf_msk = jax.lax.ppermute(buf_msk, axis, perm)
        buf_gid = jax.lax.ppermute(buf_gid, axis, perm)
        halo, hmask, hgid, overflow = filter_into(
            halo, hmask, hgid, overflow, buf_pts, buf_msk, buf_gid, False
        )
        return buf_pts, buf_msk, buf_gid, halo, hmask, hgid, overflow

    # fori_loop (not a Python unroll): the traced program stays O(1) in
    # mesh size — 255-device rings compile the same graph as 8-device.
    state = (flat_pts, flat_msk, flat_gid, halo, hmask, hgid, overflow)
    state = jax.lax.fori_loop(0, n_dev - 1, step, state)
    _, _, _, halo, hmask, hgid, overflow = state
    return halo, hmask, hgid, overflow
