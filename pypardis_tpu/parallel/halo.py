"""Device-resident halo exchange over the mesh interconnect.

``build_shards`` (sharded.py) materializes each partition's 2*eps halo on
the **host** with a vectorized box query — fine when points start on the
host anyway.  This module is the device-resident alternative for data
that already lives sharded on the mesh: each device's owned slab rides a
**ring** of ``ppermute`` steps (ICI neighbor exchanges, the same pattern
ring attention uses for KV blocks), and every device filters the passing
slabs against its own 2*eps-expanded bounding box, compacting matches
into a fixed-capacity halo buffer.

This replaces the reference's neighborhood duplication
(``/root/reference/dbscan/dbscan.py:136-151`` — a Spark filter+union per
partition over the whole dataset) with P-1 neighbor exchanges and no
host round-trip.  Capacity is static (XLA shapes): callers size ``hcap``
and the returned ``overflow`` count says whether any in-box point had to
be dropped — the driver treats overflow as an error and re-runs with a
bigger capacity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _compact_merge(halo, hmask, hgid, pts, valid, gid):
    """Merge flagged candidates into the fixed-size halo buffer.

    Stable sort by validity (valid rows first) over the concatenation,
    then keep the first hcap rows.  Stability keeps earlier halo entries
    in place, so repeated merges never reorder accepted points.
    """
    hcap = halo.shape[0]
    cat_pts = jnp.concatenate([halo, pts], axis=0)
    cat_msk = jnp.concatenate([hmask, valid], axis=0)
    cat_gid = jnp.concatenate([hgid, gid], axis=0)
    order = jnp.argsort(~cat_msk, stable=True)
    return (
        cat_pts[order[:hcap]],
        cat_msk[order[:hcap]],
        cat_gid[order[:hcap]],
        jnp.sum(cat_msk.astype(jnp.int32)) - jnp.sum(
            cat_msk[order[:hcap]].astype(jnp.int32)
        ),
    )


def ring_halo_exchange(
    owned: jnp.ndarray,
    mask: jnp.ndarray,
    gid: jnp.ndarray,
    box_lo: jnp.ndarray,
    box_hi: jnp.ndarray,
    hcap: int,
    axis: str,
):
    """Collect every remote point inside this device's expanded box.

    Must run inside ``shard_map``.  ``owned``: (cap, k) this device's
    points; ``mask``: (cap,) validity; ``gid``: (cap,) global point ids.
    ``box_lo``/``box_hi``: (k,) this device's bounding box already
    expanded by 2*eps (the reference's duplication rule, README.md:20).
    Returns ``(halo, halo_mask, halo_gid, overflow)`` with leading
    dimension ``hcap``; ``overflow`` counts in-box points dropped because
    the buffer filled — callers must treat nonzero as an error.
    """
    n_dev = jax.lax.axis_size(axis)
    cap, k = owned.shape
    halo = jnp.zeros((hcap, k), owned.dtype)
    hmask = jnp.zeros((hcap,), bool)
    hgid = jnp.full((hcap,), jnp.int32(2**31 - 1))

    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def step(_i, state):
        buf_pts, buf_msk, buf_gid, halo, hmask, hgid, overflow = state
        buf_pts = jax.lax.ppermute(buf_pts, axis, perm)
        buf_msk = jax.lax.ppermute(buf_msk, axis, perm)
        buf_gid = jax.lax.ppermute(buf_gid, axis, perm)
        inbox = (
            buf_msk
            & jnp.all(buf_pts >= box_lo[None, :], axis=1)
            & jnp.all(buf_pts <= box_hi[None, :], axis=1)
        )
        halo, hmask, hgid, dropped = _compact_merge(
            halo, hmask, hgid, buf_pts, inbox, buf_gid
        )
        return (
            buf_pts, buf_msk, buf_gid, halo, hmask, hgid,
            overflow + dropped,
        )

    # fori_loop (not a Python unroll): the traced program stays O(1) in
    # mesh size — 255-device rings compile the same graph as 8-device.
    state = (owned, mask, gid, halo, hmask, hgid, jnp.int32(0))
    state = jax.lax.fori_loop(0, n_dev - 1, step, state)
    _, _, _, halo, hmask, hgid, overflow = state
    return halo, hmask, hgid, overflow
