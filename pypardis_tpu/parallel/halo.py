"""Device-resident halo exchange over the mesh interconnect.

``build_shards`` (sharded.py) materializes each partition's 2*eps halo on
the **host** with a vectorized box query — fine when points start on the
host anyway.  This module is the device-resident alternative for data
that already lives sharded on the mesh: each device's owned slab rides a
**ring** of ``ppermute`` steps (ICI neighbor exchanges, the same pattern
ring attention uses for KV blocks), and every device filters the passing
slabs against its own 2*eps-expanded bounding box, compacting matches
into a fixed-capacity halo buffer.

This replaces the reference's neighborhood duplication
(``/root/reference/dbscan/dbscan.py:136-151`` — a Spark filter+union per
partition over the whole dataset) with P-1 neighbor exchanges and no
host round-trip.  Capacity is static (XLA shapes): callers size ``hcap``
and the returned ``overflow`` count says whether any in-box point had to
be dropped — the driver treats overflow as an error and re-runs with a
bigger capacity.

The exchanged slabs are *transport*, not a mandate to re-cluster: under
the owner-computes step (``sharded._device_cluster_merge_oc``) the
received halo rows serve only as neighbor-count evidence and relay
nodes, so the exchange's byte volume is the whole duplication cost the
ring path pays.

On a multi-process mesh (``parallel.dist``) nothing here changes: the
ring is a ``ppermute`` over the global 1-D axis, so hops whose
neighbor lives in another process become inter-host sends (gloo TCP on
CPU fleets, ICI/DCN on pods) compiled into the same program — the
fixed-capacity contract and the overflow ladder are process-agnostic.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _compact_merge(halo, hmask, hgid, pts, valid, gid):
    """Merge flagged candidates into the fixed-size halo buffer.

    Stable sort by validity (valid rows first) over the concatenation,
    then keep the first hcap rows.  Stability keeps earlier halo entries
    in place, so repeated merges never reorder accepted points.
    """
    hcap = halo.shape[0]
    cat_pts = jnp.concatenate([halo, pts], axis=0)
    cat_msk = jnp.concatenate([hmask, valid], axis=0)
    cat_gid = jnp.concatenate([hgid, gid], axis=0)
    order = jnp.argsort(~cat_msk, stable=True)
    return (
        cat_pts[order[:hcap]],
        cat_msk[order[:hcap]],
        cat_gid[order[:hcap]],
        jnp.sum(cat_msk.astype(jnp.int32)) - jnp.sum(
            cat_msk[order[:hcap]].astype(jnp.int32)
        ),
    )


def ring_halo_exchange_multi(
    owned: jnp.ndarray,
    mask: jnp.ndarray,
    gid: jnp.ndarray,
    boxes_lo: jnp.ndarray,
    boxes_hi: jnp.ndarray,
    hcap: int,
    axis: str,
):
    """Collect each local partition's halo from the whole mesh.

    Must run inside ``shard_map``.  ``owned``: (L, cap, k) this
    device's partitions; ``mask``: (L, cap) validity; ``gid``: (L, cap)
    global point ids.  ``boxes_lo``/``boxes_hi``: (L, k) each
    partition's bounding box already expanded by 2*eps (the reference's
    duplication rule, README.md:20).  Returns ``(halo, halo_mask,
    halo_gid, overflow)`` with shapes (L, hcap, ...) / (L,).

    Round 0 filters the device's OWN slab (cross-partition halos within
    a device, excluding each partition's own points); rounds 1..n_dev-1
    circulate the full (L, cap) slab over the ring and filter remote
    points — so any ``L = n_partitions / n_devices`` works, not just
    one partition per device (round-2 restriction).
    """
    # jax.lax.axis_size only exists on newer jax; psum(1) over the axis
    # is the portable spelling of the same quantity.
    n_dev = (
        jax.lax.axis_size(axis)
        if hasattr(jax.lax, "axis_size")
        else jax.lax.psum(1, axis)
    )
    L, cap, k = owned.shape
    halo = jnp.zeros((L, hcap, k), owned.dtype)
    hmask = jnp.zeros((L, hcap), bool)
    hgid = jnp.full((L, hcap), jnp.int32(2**31 - 1))
    overflow = jnp.zeros((L,), jnp.int32)

    flat_pts = owned.reshape(L * cap, k)
    flat_msk = mask.reshape(L * cap)
    flat_gid = gid.reshape(L * cap)
    # Which local partition each flat slot belongs to (for the local
    # round's own-partition exclusion).
    part_of = jnp.repeat(jnp.arange(L, dtype=jnp.int32), cap)

    def filter_into(halo, hmask, hgid, overflow, pts, msk, gids, excl):
        def one(l, h, hm, hgd):
            inbox = (
                msk
                & jnp.all(pts >= boxes_lo[l][None, :], axis=1)
                & jnp.all(pts <= boxes_hi[l][None, :], axis=1)
            )
            if excl:
                inbox &= part_of != l
            return _compact_merge(h, hm, hgd, pts, inbox, gids)

        out = [one(l, halo[l], hmask[l], hgid[l]) for l in range(L)]
        return (
            jnp.stack([o[0] for o in out]),
            jnp.stack([o[1] for o in out]),
            jnp.stack([o[2] for o in out]),
            overflow + jnp.stack([o[3] for o in out]),
        )

    # Local round: other partitions on this device.  At L == 1 the
    # own-partition exclusion empties it — skip the wasted filter pass.
    if L > 1:
        halo, hmask, hgid, overflow = filter_into(
            halo, hmask, hgid, overflow, flat_pts, flat_msk, flat_gid, True
        )

    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def step(_i, state):
        buf_pts, buf_msk, buf_gid, halo, hmask, hgid, overflow = state
        buf_pts = jax.lax.ppermute(buf_pts, axis, perm)
        buf_msk = jax.lax.ppermute(buf_msk, axis, perm)
        buf_gid = jax.lax.ppermute(buf_gid, axis, perm)
        halo, hmask, hgid, overflow = filter_into(
            halo, hmask, hgid, overflow, buf_pts, buf_msk, buf_gid, False
        )
        return buf_pts, buf_msk, buf_gid, halo, hmask, hgid, overflow

    # fori_loop (not a Python unroll): the traced program stays O(1) in
    # mesh size — 255-device rings compile the same graph as 8-device.
    state = (flat_pts, flat_msk, flat_gid, halo, hmask, hgid, overflow)
    state = jax.lax.fori_loop(0, n_dev - 1, step, state)
    _, _, _, halo, hmask, hgid, overflow = state
    return halo, hmask, hgid, overflow


# ---------------------------------------------------------------------------
# Tile-granular boundary exchange (global-Morton mode).
#
# The point-granular ring above circulates each device's WHOLE owned
# slab and filters points against 2*eps-expanded KD boxes — correct, but
# the interconnect carries every coordinate P-1 times.  The global-
# Morton mode needs far less: shards are contiguous ranges of one global
# Morton order, so only the kernel TILES whose bounding box lies within
# eps of some other shard's tiles are ever needed elsewhere.  These
# primitives ship exactly those tiles: a send-side selection against
# all-gathered tile boxes (boxes are (nt, d) metadata — tiny), then a
# ring of ppermute steps over the compacted boundary-tile buffers only.
# ---------------------------------------------------------------------------

# NUMPY scalars, not jnp: this module's first import can happen inside
# an active jit trace (sharded.ring_exchange_step used to import it
# lazily from its traced body), and a module-level jnp constant created
# under a trace is a DynamicJaxprTracer that outlives it — every later
# use then dies with UnexpectedTracerError, depending purely on which
# test/fit imported what first.  np scalars are trace-inert and behave
# identically inside the kernels.
import numpy as _np

_INT32_MAX = _np.int32(2**31 - 1)
_BOX_BIG = _np.float32(3e38)


def _keep_tiles(cat_val, cap_tiles):
    """Stable tile compaction order: valid tiles first, keep the first
    ``cap_tiles``.  Returns ``(order, kept_valid, dropped)``."""
    order = jnp.argsort(~cat_val, stable=True)[:cap_tiles]
    kept = cat_val[order]
    dropped = jnp.sum(cat_val.astype(jnp.int32)) - jnp.sum(
        kept.astype(jnp.int32)
    )
    return order, kept, dropped


def boundary_send_select(owned, mask, gid, eps, *, gtile, btcap, axis,
                         sketch=0):
    """Per-device body: select and compact MY boundary tiles.

    Must run inside ``shard_map``.  ``owned``: (cap, k) this shard's
    Morton-range rows; ``mask``/``gid``: (cap,) validity / global ids.
    Computes per-tile bounding boxes (tiles of ``gtile`` rows — the
    EXCHANGE granularity, typically a quarter of the kernel block:
    accepting a tile for one reachable row pulls all its rows, so
    finer exchange tiles cut the shipped boundary volume several-fold
    while the kernel keeps its own MXU-sized tiling over the packed
    slab), all-gathers the boxes across the mesh (metadata only, never
    coordinates), and keeps the tiles whose box lies within eps of ANY
    other device's tile box — the only tiles any other shard can need,
    by the box-gap bound.

    Returns ``(send_pts (btcap, gtile, k), send_msk, send_gid, send_lo,
    send_hi, n_send, overflow, my_lo, my_hi)``.  Invalid send slots
    carry inverted boxes (never accepted downstream), masked rows, and
    INT32_MAX gids.  ``overflow`` counts boundary tiles dropped for
    ``btcap`` — the driver's doubling ladder
    (:func:`pypardis_tpu.parallel.global_morton._gm_boundary_tiles`)
    treats nonzero as a retry, reports each rung through the unified
    retry counters (``retry.gm.btcap.*``), and an EXPLICIT too-small
    cap raises an actionable error naming the exact need and the knobs
    (``btcap=`` / ``PYPARDIS_GM_BTCAP``) — dropped boundary tiles would
    mean silently wrong labels, so exhaustion is always loud.

    ``sketch`` (a RESOLVED projection width k, 0 = off — the caller
    resolves against the metric outside the trace): ALSO require each
    tile's (k+1)-dim sketch-space box to lie within ``sqrt(eps^2 +
    band)`` of some remote tile's sketch box, and send only tiles
    passing BOTH tests.  Each test alone is a sound must-send superset
    (a cross-shard pair within eps keeps its tile live under either
    geometry — the slab distance lower-bounds d^2 up to the certified
    band), so their intersection still contains every needed tile,
    and at high d the sketch boxes prune the ring far harder than the
    full-d boxes whose per-axis gaps wash out.  ``n_send_box`` (the
    full-d-only count) returns alongside for the telemetry ratio; the
    downstream ring/flatten row filters stay full-d and exact.
    """
    from ..ops.distances import (
        _sketch_slab_t, cross_tile_live, tile_bounds,
    )
    from ..ops.sketch import sketch_gate_band, sketch_matrix

    cap, k = owned.shape
    nt = cap // gtile
    tiles = owned.reshape(nt, gtile, k)
    tmsk = mask.reshape(nt, gtile)
    tgid = gid.reshape(nt, gtile)
    tiles_t = tiles.transpose(0, 2, 1)
    lo, hi = tile_bounds(tiles_t, tmsk)  # (nt, k)

    n_dev = (
        jax.lax.axis_size(axis)
        if hasattr(jax.lax, "axis_size")
        else jax.lax.psum(1, axis)
    )
    all_lo = jax.lax.all_gather(lo, axis)  # (P, nt, k)
    all_hi = jax.lax.all_gather(hi, axis)
    me = jax.lax.axis_index(axis)
    mine = (jnp.arange(n_dev) == me)[:, None, None]
    # My own rows inverted: a tile is a BOUNDARY tile only if a REMOTE
    # shard's box reaches it.
    rem_lo = jnp.where(mine, _BOX_BIG, all_lo).reshape(n_dev * nt, k)
    rem_hi = jnp.where(mine, -_BOX_BIG, all_hi).reshape(n_dev * nt, k)
    live = cross_tile_live(lo, hi, rem_lo, rem_hi, eps)
    n_send_box = jnp.sum(live.astype(jnp.int32))
    if sketch:
        q, eta = sketch_matrix(k, sketch)
        slab = _sketch_slab_t(tiles_t, jnp.asarray(q))
        slo, shi = tile_bounds(slab, tmsk)  # (nt, sketch+1)
        # One mesh-wide norm bound: the band must cover the float error
        # at the HIGHEST-norm point on ANY shard, not just mine.
        nmax = jax.lax.pmax(
            jnp.sqrt(jnp.max(jnp.where(
                tmsk, jnp.sum(tiles_t * tiles_t, axis=1), 0.0
            ))),
            axis,
        )
        band = sketch_gate_band(nmax, k, sketch, eta)
        eps_gate = jnp.sqrt(jnp.float32(eps) ** 2 + band)
        all_slo = jax.lax.all_gather(slo, axis)
        all_shi = jax.lax.all_gather(shi, axis)
        sdim = slo.shape[1]
        srem_lo = jnp.where(mine, _BOX_BIG, all_slo).reshape(
            n_dev * nt, sdim
        )
        srem_hi = jnp.where(mine, -_BOX_BIG, all_shi).reshape(
            n_dev * nt, sdim
        )
        live = live & cross_tile_live(
            slo, shi, srem_lo, srem_hi, eps_gate
        )

    order, valid, overflow = _keep_tiles(live, btcap)
    send_pts = jnp.where(valid[:, None, None], tiles[order], 0.0)
    send_msk = tmsk[order] & valid[:, None]
    send_gid = jnp.where(valid[:, None], tgid[order], _INT32_MAX)
    send_lo = jnp.where(valid[:, None], lo[order], _BOX_BIG)
    send_hi = jnp.where(valid[:, None], hi[order], -_BOX_BIG)
    n_send = jnp.sum(live.astype(jnp.int32))
    return (
        send_pts, send_msk, send_gid, send_lo, send_hi, n_send, overflow,
        lo, hi, n_send_box,
    )


def ring_tile_round(
    buf_pts, buf_msk, buf_gid, buf_lo, buf_hi,
    recv_pts, recv_msk, recv_gid, recv_val, overflow,
    my_lo, my_hi, eps, axis,
):
    """One ppermute step of the boundary-tile ring + tile-level accept.

    Must run inside ``shard_map``.  The passing buffer (some sender's
    compacted boundary tiles) moves one hop; each device then accepts
    the tiles whose box lies within eps of any of ITS tile boxes and
    merges them — stably, at tile granularity — into the fixed
    ``recv``-capacity buffer.  Unaccepted tiles keep circulating.
    Invalid/padding tiles carry inverted boxes and are never live.
    ``overflow`` accumulates accepted tiles dropped for capacity.
    """
    from ..ops.distances import cross_tile_live

    n_dev = (
        jax.lax.axis_size(axis)
        if hasattr(jax.lax, "axis_size")
        else jax.lax.psum(1, axis)
    )
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
    buf_pts = jax.lax.ppermute(buf_pts, axis, perm)
    buf_msk = jax.lax.ppermute(buf_msk, axis, perm)
    buf_gid = jax.lax.ppermute(buf_gid, axis, perm)
    buf_lo = jax.lax.ppermute(buf_lo, axis, perm)
    buf_hi = jax.lax.ppermute(buf_hi, axis, perm)

    acc = cross_tile_live(buf_lo, buf_hi, my_lo, my_hi, eps)
    bcap = recv_val.shape[0]
    cat_pts = jnp.concatenate([recv_pts, buf_pts])
    cat_msk = jnp.concatenate([recv_msk, buf_msk & acc[:, None]])
    cat_gid = jnp.concatenate(
        [recv_gid, jnp.where(acc[:, None], buf_gid, _INT32_MAX)]
    )
    cat_val = jnp.concatenate([recv_val, acc])
    order, kept, dropped = _keep_tiles(cat_val, bcap)
    return (
        buf_pts, buf_msk, buf_gid, buf_lo, buf_hi,
        cat_pts[order], cat_msk[order], cat_gid[order], kept,
        overflow + dropped,
    )
