"""Host-side label-merge utilities.

The primary merge is in-graph (``sharded.sharded_step``): scatter-min
propagation + ``pmin`` collectives, replicated over the mesh.  That path
carries O(N) int32 arrays per device; for point counts where N-sized
replicated arrays stop fitting alongside the data, the merge can instead
run on host over *compact occurrence tables* — this module is that path,
and the pure-Python reference implementation the native (C++) resolver
is tested against.

Semantics are identical to the reference's ``ClusterAggregator``
(aggregator.py:38-63): only points that are core in their home partition
link clusters; merged clusters take the minimum id.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from .._native import uf_resolve_dense


def resolve_label_edges(edges: np.ndarray, ids: np.ndarray) -> Dict[int, int]:
    """Union a (E, 2) table of label-equivalence edges.

    ``ids``: the universe of label ids in play (1-D).  Returns
    {label id -> canonical (minimum) label id of its component}.

    Ids are mapped to dense indices with a vectorized sorted-search and
    the union loop runs in the native (C++) resolver when available —
    the Python fallback has identical min-id semantics.  Because
    ``np.unique``-style sorted ids preserve order, the dense min-root
    maps back to the minimum original id of the component.
    """
    ids_sorted = np.unique(np.asarray(ids))
    edges = np.asarray(edges).reshape(-1, 2)
    dense = np.searchsorted(ids_sorted, edges)
    if len(edges):
        # searchsorted returns insertion points for missing ids — make
        # that loud (the dict-based predecessor raised KeyError).
        if len(ids_sorted) == 0:
            raise KeyError(
                f"edge references id(s) not in the empty id universe: "
                f"{edges[0]}"
            )
        clipped = np.clip(dense, 0, len(ids_sorted) - 1)
        if not np.array_equal(ids_sorted[clipped], edges):
            missing = edges[(ids_sorted[clipped] != edges).any(axis=1)][0]
            raise KeyError(
                f"edge references id(s) not in the id universe: {missing}"
            )
    roots = uf_resolve_dense(dense, len(ids_sorted))
    return {
        int(v): int(ids_sorted[roots[i]]) for i, v in enumerate(ids_sorted)
    }


def merge_occurrences(
    home_label: np.ndarray,
    core: np.ndarray,
    occ_gid: np.ndarray,
    occ_label: np.ndarray,
) -> Tuple[np.ndarray, Dict[int, int]]:
    """Merge per-partition labels from halo-duplicate occurrence tables.

    ``home_label``: (N,) each point's label from its home partition
    (root gid, -1 noise).  ``core``: (N,) home-run core flags.
    ``occ_gid``/``occ_label``: flattened halo occurrences — point gid
    and the label that point received in a *foreign* partition.  Both
    sharded cluster steps emit this same wire format: the legacy step's
    occurrences are full re-clustering labels, the owner-computes
    step's are compact (owned_root, halo_gid) edge-table entries (the
    halo point's relay label against the foreign partition's OWNED
    clusters) — the union-find below is indifferent.

    Implements the reference merge rule (aggregator.py:38-40): an
    occurrence links its label to the point's home label only if the
    point is core at home and labeled non-noise in the foreign run.
    Returns (final_labels, mapping).
    """
    home_label = np.asarray(home_label)
    core = np.asarray(core, dtype=bool)
    occ_gid = np.asarray(occ_gid).reshape(-1)
    occ_label = np.asarray(occ_label).reshape(-1)

    link = (
        (occ_gid >= 0)
        & (occ_gid < len(home_label))
        & (occ_label >= 0)
    )
    link &= core[np.clip(occ_gid, 0, len(home_label) - 1)]
    a = home_label[occ_gid[link]]
    b = occ_label[link]
    keep = a >= 0
    edges = np.stack([a[keep], b[keep]], axis=1)

    ids = np.unique(
        np.concatenate([home_label[home_label >= 0], edges.reshape(-1)])
    )
    mapping = resolve_label_edges(edges, ids)
    lut = np.full(int(ids.max()) + 2 if len(ids) else 1, -1, np.int32)
    for k, v in mapping.items():
        lut[k] = v
    from .._native import relabel_i32

    return relabel_i32(home_label, lut, fill=-1), mapping
