"""Sharded-build staging: pinned host slab reuse + device slab cache.

The single-shard driver has had a borrow/return staging protocol since
round 4 (``dbscan._staging_buffer``): re-transferring from the SAME
host allocation is ~100x cheaper on tunneled deployments because the
client pins/registers the buffer on first use.  The sharded build had
neither half of that economy — every fit allocated fresh (P, cap, k)
owned and (P, hcap, k) halo slabs AND re-shipped them (~3.7GB per warm
10M x 16-D fit, ``MESHSCALE_r05.json`` mode=device: warm 694s > cold
410s — the warm fit measured the link, not the program).  This module
supplies both tiers:

* **host pool** (:func:`borrow` / :func:`give_back`): slab-shaped numpy
  buffers keyed by (shape, dtype), reused across fits.  Content is
  always rewritten by the build, so reuse is unconditionally correct;
  the win is the allocation (and, on tunneled TPU runtimes, the pin).
  The borrow/return protocol keeps concurrent fits safe: a second
  caller while a buffer is out simply allocates fresh.

* **device cache** (:func:`device_get` / :func:`device_put_cached`):
  the previous fit's device-resident slab arrays, keyed by a CONTENT
  fingerprint of the inputs that determine them.  A warm refit whose
  points / partition tree / geometry are verifiably unchanged skips
  the host build and the transfer entirely — ``staged_bytes_reused``
  in ``DBSCAN.report()`` is these bytes.  Owned slabs key WITHOUT eps
  (the owned layout is eps-independent), so an eps sweep re-ships only
  the halo slabs.  One entry per route; a key miss evicts before the
  new build so peak HBM never holds two generations.

Fingerprints hash the full points buffer (chunked crc32 — ~1GB/s,
versus single-digit MB/s for re-shipping over a degraded tunnel) plus
the partition tree, so in-place mutation of the input between fits is
detected and the cache misses instead of serving stale slabs.
"""

from __future__ import annotations

import zlib
from typing import Optional, Tuple

import numpy as np

from ..obs import flight_note

_CRC_CHUNK = 1 << 24

# (shape, dtype-str) -> free numpy buffer.  Bounded: give_back keeps
# only the most recent generation of buffers (one fit's worth).
_host_pool: dict = {}

# route -> (key, tuple_of_device_arrays, nbytes).  One entry per route.
_device_cache: dict = {}

# route -> cumulative bytes shipped as IN-PLACE deltas (device_replace):
# the live-update economy's gauge — a pad-slot insert or one-leaf
# rebuild ships kilobytes against a megabyte-scale resident.
_route_delta: dict = {}

# Telemetry for the current fit, reset by begin_fit().
_fit_stats = {"reused": 0, "staged": 0}


def begin_fit() -> None:
    """Reset the per-fit staging counters (one call per sharded fit)."""
    _fit_stats["reused"] = 0
    _fit_stats["staged"] = 0


def fit_stats() -> Tuple[int, int]:
    """(staged_bytes_reused, staged_bytes_shipped) for the current fit."""
    return _fit_stats["reused"], _fit_stats["staged"]


def clear() -> None:
    """Drop every pooled host buffer and cached device array (tests,
    and callers that need the HBM back between fits)."""
    _host_pool.clear()
    _device_cache.clear()
    _route_delta.clear()


def pool_nbytes() -> int:
    """Total bytes the staging economy currently holds — pooled host
    buffers plus cached device slabs (the resource sampler's
    ``resources.staging_pool_bytes`` watermark)."""
    host = sum(int(b.nbytes) for b in _host_pool.values())
    dev = sum(int(e[3]) for e in _device_cache.values())
    return host + dev


def points_fingerprint(points) -> Tuple:
    """Content fingerprint of the input array (chunked crc32).

    Covers shape, dtype and every byte, so a mutated-in-place input can
    never match a cached device slab.  Cost is host-memory-bandwidth
    bound — orders of magnitude below the transfer it can save.
    """
    points = np.asarray(points)
    flat = points.reshape(-1)
    crc = 0
    step = max(1, _CRC_CHUNK // max(points.itemsize, 1))
    for s in range(0, flat.shape[0], step):
        crc = zlib.crc32(
            np.ascontiguousarray(flat[s:s + step]).view(np.uint8), crc
        )
    return (points.shape, str(points.dtype), crc)


def partitioner_fingerprint(partitioner) -> Tuple:
    """Content fingerprint of a KDPartitioner's split structure.

    The tree (split planes) plus the partition count determine the slab
    layout for a given dataset; hashing content rather than identity
    lets ``DBSCAN.fit`` — which builds a fresh (deterministic)
    partitioner per call — hit the cache on warm refits.
    """
    tree = tuple(
        (int(p), int(a), float(b), int(l), int(r))
        for p, a, b, l, r in partitioner.tree
    )
    return (partitioner.n_partitions, hash(tree))


def borrow(shape, dtype) -> np.ndarray:
    """A host buffer of (shape, dtype): pooled if available, else fresh.

    Contents are UNSPECIFIED — callers must fully overwrite.
    """
    key = (tuple(shape), np.dtype(dtype).str)
    buf = _host_pool.pop(key, None)
    if buf is None:
        buf = np.empty(shape, dtype)
    return buf


def give_back(bufs) -> None:
    """Return borrowed buffers to the pool (call only after the device
    transfer is known consumed — e.g. once results materialized).

    NOT for buffers whose device arrays ride the cross-fit device
    cache — use :func:`give_back_after_put` for those (see its
    aliasing contract)."""
    for buf in bufs:
        _host_pool[(buf.shape, buf.dtype.str)] = buf


def _put_aliases_host() -> bool:
    """Whether ``jax.device_put`` of an aligned numpy buffer may be a
    ZERO-COPY view on this backend (CPU), rather than a real transfer
    into device memory (TPU/GPU)."""
    import jax

    return jax.default_backend() == "cpu"


def give_back_after_put(bufs) -> None:
    """Return build buffers whose ``device_put`` products are CACHED
    across fits (the owned/halo/boundary slab routes).

    On CPU, XLA zero-copies aligned numpy buffers, so pooling them
    would let a later ``borrow`` of the same (shape, dtype) overwrite
    memory a cached slab still aliases — observed as corrupted owned
    slabs on the second eps of a sweep (the fit(eps1)→fit(eps2)
    staging-reuse path returned wrong labels).  There the buffers are
    simply dropped; the pin/registration economy pooling funds only
    exists on tunneled TPU runtimes, where device_put really copies.
    Per-batch buffers whose device products are consumed before reuse
    (the serving query slabs) keep the plain :func:`give_back`.
    """
    if not _put_aliases_host():
        give_back(bufs)


def device_get(route: str, key) -> Optional[tuple]:
    """``(arrays, aux)`` cached for ``route`` if ``key`` matches, else
    None (a mismatched entry is evicted so HBM frees before rebuild)."""
    entry = _device_cache.get(route)
    if entry is None:
        return None
    ekey, arrays, aux, nbytes = entry
    if ekey != key:
        del _device_cache[route]
        flight_note("staging.evict", route=route, reason="key_miss")
        return None
    _fit_stats["reused"] += nbytes
    flight_note("staging.reuse", route=route, nbytes=int(nbytes))
    return arrays, dict(aux)


def device_peek(route: str, key) -> bool:
    """True when ``route`` holds a live entry for ``key`` — a pure
    lookahead for drivers choosing between a cached-array path and a
    streaming rebuild.  Never touches the reuse accounting and never
    evicts (the committed ``device_get`` still decides both)."""
    entry = _device_cache.get(route)
    return entry is not None and entry[0] == key


def route_nbytes(route: str) -> int:
    """Bytes currently device-resident for ``route`` (0 when empty) —
    telemetry for long-lived residents like the serving index."""
    entry = _device_cache.get(route)
    return 0 if entry is None else int(entry[3])


def device_evict(route: str) -> None:
    """Drop one route's cached entry (restage paths: a transient
    device fault can delete cached buffers out from under the cache —
    the retry must rebuild, not re-serve dead handles)."""
    if _device_cache.pop(route, None) is not None:
        flight_note("staging.evict", route=route, reason="explicit")


def route_delta_nbytes(route: str) -> int:
    """Cumulative bytes shipped through :func:`device_replace` for a
    delta route — telemetry for in-place index refreshes."""
    return int(_route_delta.get(route, 0))


def device_replace(
    route: str, key, arrays: tuple, *, staged_nbytes: int,
    delta_route: Optional[str] = None,
) -> tuple:
    """Swap a route's cached device arrays for an IN-PLACE-updated
    generation: the entry's resident size is the full new arrays (for
    ``route_nbytes`` / pool watermarks) but the staging counters move
    only by ``staged_nbytes`` — the bytes that actually crossed the
    host->device link (the scattered columns, appended slabs, and
    relabel LUT of a live index delta, never the whole resident)."""
    nbytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize for a in arrays)
    _fit_stats["staged"] += int(staged_nbytes)
    _device_cache[route] = (key, arrays, {}, nbytes)
    if delta_route is not None:
        _route_delta[delta_route] = (
            _route_delta.get(delta_route, 0) + int(staged_nbytes)
        )
    flight_note(
        "staging.device_replace", route=route,
        delta_nbytes=int(staged_nbytes), nbytes=int(nbytes),
    )
    return arrays


def device_put_cached(route: str, key, arrays: tuple, aux=None) -> tuple:
    """Record freshly staged device arrays (plus their build stats) for
    reuse by the next fit."""
    nbytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize for a in arrays)
    _fit_stats["staged"] += nbytes
    _device_cache[route] = (key, arrays, dict(aux or {}), nbytes)
    flight_note("staging.device_put", route=route, nbytes=int(nbytes))
    return arrays


# ---------------------------------------------------------------------------
# Sweep-graph route: the cached neighbor-pair slab behind DBSCAN.sweep.
#
# The graph extracted at eps_max serves EVERY config with eps <=
# eps_max (re-thresholding cached dval is exact), so the route's key is
# eps-FREE — data/mode/grid only — and the eps_max the entry was built
# at rides in its aux.  A later sweep whose eps ceiling fits under the
# cached one reuses the slab outright; per-config relabels inside one
# sweep count their reuse through touch_route so configs 2..k report
# ``staged_bytes_reused > 0`` like any warm staging hit.
# ---------------------------------------------------------------------------

SWEEP_GRAPH_ROUTE = "sweep_graph"


def device_get_cover(route: str, key, eps_needed: float):
    """``(arrays, aux)`` when ``route`` holds an entry for the eps-free
    ``key`` whose recorded ``aux["eps_max"]`` covers ``eps_needed``
    (>=, exact f32 compare is fine — equal sweeps re-key identically).
    A key match with an insufficient ceiling evicts (the rebuild at the
    larger eps_max replaces it); a key miss evicts as usual."""
    entry = _device_cache.get(route)
    if entry is None:
        return None
    ekey, arrays, aux, nbytes = entry
    if ekey != key or float(aux.get("eps_max", -1.0)) < float(eps_needed):
        del _device_cache[route]
        flight_note("staging.evict", route=route, reason="key_miss")
        return None
    _fit_stats["reused"] += nbytes
    flight_note("staging.reuse", route=route, nbytes=int(nbytes))
    return arrays, dict(aux)


def touch_route(route: str) -> int:
    """Count one logical reuse of ``route``'s resident entry (bytes
    added to the fit's reused counter) WITHOUT re-fetching it — the
    per-config accounting of a sweep, where configs 2..k re-threshold
    the device-resident graph the first config staged.  Returns the
    bytes credited (0 when the route is empty)."""
    entry = _device_cache.get(route)
    if entry is None:
        return 0
    nbytes = int(entry[3])
    _fit_stats["reused"] += nbytes
    flight_note("staging.reuse", route=route, nbytes=nbytes)
    return nbytes


def _evict_all_device(error) -> None:
    """OOM recovery between transfer attempts: drop every cached device
    slab so the retry has HBM headroom.  Arrays a driver already holds
    stay alive through its own references; only the cache's retention
    (the cross-fit reuse economy) is sacrificed."""
    if _device_cache:
        flight_note(
            "staging.evict", route="*", reason="oom_recovery",
            nbytes=sum(int(e[3]) for e in _device_cache.values()),
        )
        _device_cache.clear()


def transfer(put_fn, *, site: str = "staging.device_put"):
    """Run a host→device transfer under the unified retry layer.

    ``put_fn`` is a zero-arg callable performing the actual
    ``jax.device_put`` (or equivalent).  Transient tunnel faults retry
    through the standard ladder; an OOM-classified failure first evicts
    the device slab cache (:func:`_evict_all_device`) so the retry has
    the HBM the cache was hoarding — the recovery action that makes a
    transfer-time OOM survivable rather than terminal.  The
    ``staging.device_put`` fault-injection site lives here, inside the
    retry scope, so injected faults recover through exactly this
    machinery.
    """
    from ..utils import faults
    from ..utils.retry import Retrier, is_oom_error, is_transient_error

    def attempt():
        faults.maybe_fail(site)
        return put_fn()

    return Retrier(site, waits=(0.0, 10.0)).run(
        attempt,
        retryable=lambda e: is_transient_error(e) or is_oom_error(e),
        on_retry=lambda e: (
            _evict_all_device(e) if is_oom_error(e) else None
        ),
    )
