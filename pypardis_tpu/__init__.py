"""pypardis_tpu — TPU-native distributed density-based clustering.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
mathematiguy/pypardis ("pyParDis DBSCAN"): dimension-agnostic, distributed
DBSCAN over datasets too large for one worker.  Where the reference
(``/root/reference/dbscan``) distributes work with Spark RDDs and delegates
math to sklearn, this package shards points over a ``jax.sharding.Mesh``,
computes eps-neighborhoods with tiled MXU matmul kernels, and merges
cluster labels with XLA collectives — no driver round-trips in the hot
path.

Public surface mirrors the reference package (``dbscan/__init__.py:3-21``):
``DBSCAN``, ``KDPartitioner``, ``BoundingBox``, ``ClusterAggregator``, the
three split strategies, plus the TPU-native extensions under ``ops`` /
``parallel``.
"""

__version__ = (0, 1, 0)
__version_str__ = ".".join(map(str, __version__))


def _enable_compile_cache():
    """Persist XLA compilations across processes.

    The kernel programs compile in 30-300s at benchmark shapes; the
    persistent cache turns every later process's compile into a <1s
    disk read (verified through the tunneled TPU runtime).  Respects a
    user-set ``jax_compilation_cache_dir``; opt out with
    ``PYPARDIS_COMPILE_CACHE=""``; never fails import (multi-host or
    exotic deployments may reject the config)."""
    import os

    from .utils import envreg

    path = envreg.raw(
        "PYPARDIS_COMPILE_CACHE",
        os.path.join(
            os.path.expanduser("~"), ".cache", "pypardis_tpu", "xla"
        ),
    )
    if not path:
        return
    try:
        import jax

        if jax.config.jax_compilation_cache_dir is None:
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass


_enable_compile_cache()

from . import obs
from .geometry import BoundingBox
from .aggregator import ClusterAggregator, default_value
from .partition import (
    KDPartitioner,
    median_search_split,
    mean_var_split,
    min_var_split,
)
from .dbscan import (
    DBSCAN,
    SweepResult,
    dbscan_partition,
    map_cluster_id,
    sweep_dbscan,
)
from .config import DBSCANConfig
from .checkpoint import (
    load_index,
    load_model,
    load_partitioner,
    save_index,
    save_model,
    save_partitioner,
)
from .serve import CorePointIndex, QueryEngine

__all__ = [
    "obs",
    "BoundingBox",
    "ClusterAggregator",
    "default_value",
    "KDPartitioner",
    "median_search_split",
    "mean_var_split",
    "min_var_split",
    "DBSCAN",
    "DBSCANConfig",
    "SweepResult",
    "sweep_dbscan",
    "dbscan_partition",
    "map_cluster_id",
    "save_model",
    "load_model",
    "save_partitioner",
    "load_partitioner",
    "save_index",
    "load_index",
    "CorePointIndex",
    "QueryEngine",
    "__version__",
]
