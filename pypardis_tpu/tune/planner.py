"""The config planner: feasibility rules, then score the lattice.

``plan_fit`` takes a dataset probe, the user's pinned knobs, and the
harvested corpus; applies the HARD feasibility rules first (these are
correctness/survival constraints, not preferences):

* memmap input on a mesh  -> ``mode="global_morton"`` (the streaming
  external-sample-sort build is the only engine that never holds the
  dataset as anonymous host memory);
* one device (or n too small to shard) -> the fused/chained engine
  (``mode="auto"``; there is nothing to exchange or merge across);
* host-RSS pressure (``memory_pressure()`` or a predicted footprint
  past ``PYPARDIS_RSS_SOFT_LIMIT``) -> ``merge="host"`` (the
  collective-free union-find spill — the same preemptive rung the
  retry layer takes mid-fit);

then enumerates the remaining discrete lattice (mode x block x
precision x merge x dispatch, pinned knobs fixed to their user value),
scores every point with the cost model, and returns a
:class:`TunePlan` carrying the chosen config, its predicted per-phase
seconds, the scored alternatives, and a human-readable ``explain()``
trace of why each knob was chosen.

Every PLANNED knob is label-safe: mode (cross-mode byte parity is
pinned by the engine family's tests), block (pruning granularity
only), precision high<->mixed (byte-identical by the PR 7 band
construction), merge route, dispatch (commutative-fold parity,
PR 11), and sketch (byte-identical for any k by the certified-gate
rescore, :mod:`pypardis_tpu.ops.sketch`) — so ``DBSCAN(auto=True)``
labels are byte-identical to the same explicit config by
construction.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .model import model_for
from .probe import DatasetProbe, candidate_blocks

_KNOBS = ("mode", "block", "precision", "merge", "dispatch", "sketch")
# Planner candidates per knob.  Precision plans only among the
# label-identical-to-high ladder rungs (high / mixed); `highest`
# differs from `high` in last-ulp verdicts on natural near-eps pairs
# (PR 7 note) so auto NEVER picks it — a user who wants it pins it.
_PRECISIONS = ("high", "mixed")
_PASSES = 5  # counts + typical propagation rounds on blob geometries


@dataclass
class TunePlan:
    """A planned configuration plus its full decision record."""

    config: Dict = field(default_factory=dict)
    pinned: Dict = field(default_factory=dict)
    predicted: Dict = field(default_factory=dict)
    candidates: List[Tuple[Dict, float]] = field(default_factory=list)
    rules: List[str] = field(default_factory=list)
    knob_reasons: Dict[str, str] = field(default_factory=dict)
    corpus_rows_used: int = 0
    coef_source: str = ""
    fallback_reason: Optional[str] = None
    probe_summary: Dict = field(default_factory=dict)
    schema: str = "pypardis_tpu/tune_plan@1"

    def to_dict(self) -> Dict:
        return {
            "schema": self.schema,
            "config": dict(self.config),
            "pinned": dict(self.pinned),
            "predicted": dict(self.predicted),
            "candidates": [
                [dict(c), float(t)] for c, t in self.candidates
            ],
            "rules": list(self.rules),
            "knob_reasons": dict(self.knob_reasons),
            "corpus_rows_used": int(self.corpus_rows_used),
            "coef_source": self.coef_source,
            "fallback_reason": self.fallback_reason,
            "probe": dict(self.probe_summary),
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "TunePlan":
        p = cls(
            config=dict(d.get("config", {})),
            pinned=dict(d.get("pinned", {})),
            predicted=dict(d.get("predicted", {})),
            candidates=[
                (dict(c), float(t))
                for c, t in d.get("candidates", [])
            ],
            rules=list(d.get("rules", [])),
            knob_reasons=dict(d.get("knob_reasons", {})),
            corpus_rows_used=int(d.get("corpus_rows_used", 0)),
            coef_source=str(d.get("coef_source", "")),
            fallback_reason=d.get("fallback_reason"),
            probe_summary=dict(d.get("probe", {})),
        )
        return p

    def explain(self) -> str:
        """The human-readable decision trace."""
        c = self.config
        lines = [
            "TunePlan: " + " ".join(
                f"{k}={c.get(k)}" for k in _KNOBS if k in c
            )
        ]
        if self.rules:
            lines.append("  rules: " + "; ".join(self.rules))
        if self.pinned:
            lines.append(
                "  pinned by user: " + ", ".join(
                    f"{k}={v}" for k, v in sorted(self.pinned.items())
                )
            )
        for k in _KNOBS:
            if k in self.knob_reasons:
                lines.append(f"  {k}: {self.knob_reasons[k]}")
        if self.predicted:
            terms = ["build_s", "exchange_s", "compute_s", "merge_s"]
            if "hierarchy_s" in self.predicted:
                terms.append("hierarchy_s")
            lines.append(
                "  predicted: " + " + ".join(
                    f"{p[:-2]} {self.predicted.get(p, 0.0):.2f}s"
                    for p in terms
                )
                + f" = {self.predicted.get('total_s', 0.0):.2f}s"
            )
        if "hier_rounds" in self.predicted:
            lines.append(
                "  hierarchy: core pass over the stored pair slab + "
                f"{int(self.predicted['hier_rounds'])} Borůvka "
                "round(s) (log2 of live components, telemetry-pinned)"
            )
        lines.append(f"  model: {self.coef_source}")
        if self.fallback_reason:
            lines.append(f"  fallback: {self.fallback_reason}")
        pr = self.probe_summary
        if pr:
            lines.append(
                f"  probe: {pr.get('sample_rows', 0)} rows sampled in "
                f"{pr.get('probe_s', 0.0):.3f}s, "
                f"~{pr.get('neighbors_per_point', 0.0):.0f} neighbors/"
                f"point at eps"
            )
        return "\n".join(lines)


def _boundary_bytes_est(probe: DatasetProbe, block: int,
                        devices: int, kd: bool) -> float:
    """Exchange-traffic estimate: rows whose tiles are live against
    tiles across a range cut.  Per cut, about (mean live column tiles
    per row tile) x block rows on each side; KD's 2*eps expansion
    roughly doubles the band."""
    st = probe.blocks.get(block)
    if not st or devices <= 1:
        return 0.0
    mean_live_cols = st["live_pair_fraction"] * st["tiles"]
    rows = 2.0 * mean_live_cols * block * max(devices - 1, 1)
    rows = min(rows, float(probe.n))
    return rows * probe.dim * 4.0 * (2.0 if kd else 1.0)


def plan_fit(
    probe: DatasetProbe,
    pinned: Optional[Dict] = None,
    corpus_rows=None,
    *,
    metric: str = "euclidean",
    hierarchy: Optional[Tuple[float, float]] = None,
) -> TunePlan:
    """Plan the unpinned knobs for one fit described by ``probe``.

    ``metric`` is the KERNEL metric string — the sketch knob is a
    euclidean-only discipline, so any other value (or a callable's
    name) plans ``sketch=0``.  The sketch knob is label-safe like
    every other planned knob (byte parity for any k by the certified
    gate construction, :mod:`pypardis_tpu.ops.sketch`).

    ``hierarchy``: ``(pairs_est, components_est)`` when the fit is the
    eps=None density-hierarchy path — adds the learned hierarchy terms
    (core pass ∝ stored pairs, Borůvka MST ∝ rounds x pairs with
    rounds logarithmic in live components) to every candidate's
    predicted seconds.  The terms are config-invariant (the MST runs
    host-side over the same slab whatever the route), so they shift
    totals honestly without perturbing the knob ranking.
    """
    user_pinned = dict(pinned or {})
    user_pinned.pop("_device_resident", None)
    rules: List[str] = []
    n, devices = probe.n, probe.devices
    sharded = devices > 1 and n >= 2 * devices and not (
        pinned or {}
    ).get("_device_resident", False)
    # ``fixed`` = user pins + feasibility-forced values; only the user
    # pins are reported as pinned (forced knobs show their rule).
    fixed = dict(user_pinned)

    model, coef_tag = model_for(corpus_rows, probe.backend, devices)
    fallback = None
    if coef_tag.startswith("heuristic"):
        fallback = coef_tag

    # -- hard feasibility rules (applied before any scoring) ----------
    forced: Dict[str, object] = {}
    if not sharded:
        forced["mode"] = "auto"
        forced["merge"] = "auto"
        rules.append(
            f"{devices} device(s) / n={n}: fused-or-chained engine "
            f"(nothing to shard)"
        )
    elif probe.is_memmap:
        forced["mode"] = "global_morton"
        rules.append(
            "memmap input -> streaming global-Morton build (host RAM "
            "never holds the dataset)"
        )
    over_limit = (
        probe.rss_soft_limit > 0
        and probe.est_fit_rss_bytes > probe.rss_soft_limit
    )
    if probe.memory_pressure or over_limit:
        forced["merge"] = "host"
        rules.append(
            "host-RSS pressure (soft limit "
            f"{probe.rss_soft_limit}B) -> merge=host (collective-free "
            "union-find spill)"
        )
    for k, v in forced.items():
        if k in user_pinned and user_pinned[k] != v:
            # The user's explicit choice wins — record the conflict,
            # never override a pinned knob.
            rules.append(
                f"NOTE: feasibility rule wanted {k}={v} but the user "
                f"pinned {k}={user_pinned[k]}; keeping the pin"
            )
        else:
            fixed.setdefault(k, v)

    # -- the lattice --------------------------------------------------
    modes = [fixed["mode"]] if "mode" in fixed else (
        ["kd", "global_morton"] if sharded else ["auto"]
    )
    if "block" in fixed:
        blocks = [int(fixed["block"])]
    else:
        cand = candidate_blocks(n, base=tuple(probe.blocks) or (256,))
        blocks = [b for b in cand if b in probe.blocks] \
            or sorted(probe.blocks)
    precisions = [fixed["precision"]] if "precision" in fixed else \
        list(_PRECISIONS)
    merges = [fixed["merge"]] if "merge" in fixed else (
        ["device", "host"] if sharded else ["auto"]
    )
    # -- sketch candidates: off, plus the auto width when the metric
    # and dimensionality admit one.  A user pin restricts the FINAL
    # choice to its resolved width but the alternative still gets
    # scored, so a pin the model disagrees with is conflict-recorded.
    from ..ops.sketch import check_sketch_spec, resolve_sketch

    auto_sk = probe.sketch_k_auto if str(metric) == "euclidean" else 0
    pin_sk = None
    if "sketch" in fixed:
        try:
            pin_sk = resolve_sketch(
                check_sketch_spec(fixed["sketch"]), probe.dim, metric
            )
        except ValueError:
            pin_sk = 0
        sketches = sorted({pin_sk, 0} | ({auto_sk} if auto_sk else set()))
    else:
        sketches = [0, auto_sk] if auto_sk > 0 else [0]

    def _dispatch_for(tiles: float) -> str:
        # Unpinned dispatch follows the engine's own measured
        # crossover (PAIR_DISPATCH_MIN_TILES): below it the pair-list
        # extraction graph's compile tax dominates CI-sized programs —
        # a cliff the steady-state cost model cannot see, so the
        # planner defers to the measured threshold rather than
        # re-deriving it badly.
        if "dispatch" in fixed:
            return str(fixed["dispatch"])
        from ..ops.distances import pair_dispatch_enabled

        return "pair" if pair_dispatch_enabled(int(tiles)) else "dense"

    def _block_stats(block: int) -> Dict[str, float]:
        st = probe.blocks.get(block)
        if st is not None:
            return st
        # A pinned block the probe didn't sample: transfer the nearest
        # sampled block's live-pair FRACTION onto this block's grid —
        # the fraction varies slowly with pruning granularity, and a
        # pinned knob is never scored against alternatives anyway.
        near = min(probe.blocks, key=lambda b: abs(b - block))
        ref = probe.blocks[near]
        tiles = max(1, -(-n // block))
        return {
            "tiles": float(tiles),
            "live_pairs": ref["live_pair_fraction"] * tiles * tiles,
            "live_pair_fraction": ref["live_pair_fraction"],
            "band_fraction": ref["band_fraction"],
            "sketch_band_fraction": ref.get(
                "sketch_band_fraction", 1.0
            ),
        }

    scored: List[Tuple[Dict, Dict]] = []
    for mode, block, prec, merge, sk in itertools.product(
        modes, blocks, precisions, merges, sketches
    ):
        st = _block_stats(block)
        disp = _dispatch_for(st["tiles"])
        phases = model.predict_phases(
            n=n,
            dim=probe.dim,
            devices=devices,
            mode=mode,
            block=block,
            precision=prec,
            merge=merge,
            dispatch=disp,
            live_pairs=st["live_pairs"],
            tiles=st["tiles"],
            band_fraction=st["band_fraction"],
            boundary_bytes=_boundary_bytes_est(
                probe, block, devices, kd=(mode == "kd")
            ),
            is_stream=probe.is_memmap,
            passes=_PASSES,
            sketch=int(sk),
            sketch_band_fraction=st.get("sketch_band_fraction", 1.0),
        )
        if hierarchy is not None:
            hp = model.predict_hierarchy(*hierarchy)
            phases.update(hp)
            phases["total_s"] += hp["hierarchy_s"]
        cfg = {
            "mode": mode, "block": block, "precision": prec,
            "merge": merge, "dispatch": disp, "sketch": int(sk),
        }
        scored.append((cfg, phases))
    if not scored:
        raise ValueError(
            "planner scored zero configs — empty block lattice?"
        )
    # Deterministic choice: total seconds, then the stable knob tuple.
    scored.sort(
        key=lambda it: (
            it[1]["total_s"],
            it[0]["block"], it[0]["mode"], it[0]["precision"],
            it[0]["merge"], it[0]["dispatch"], it[0]["sketch"],
        )
    )
    if pin_sk is not None:
        best_any = scored[0]
        pinned_scored = [
            it for it in scored if it[0]["sketch"] == pin_sk
        ]
        scored = pinned_scored or scored
        if best_any[0]["sketch"] != pin_sk:
            rules.append(
                f"NOTE: cost model preferred sketch="
                f"{best_any[0]['sketch']} "
                f"({best_any[1]['total_s']:.3f}s predicted) but the "
                f"user pinned sketch={user_pinned.get('sketch')} "
                f"(resolves to {pin_sk}); keeping the pin"
            )
    best_cfg, best_phases = scored[0]

    # -- per-knob reasons: chosen value vs the best alternative -------
    reasons: Dict[str, str] = {}
    for knob in _KNOBS:
        if knob in user_pinned:
            reasons[knob] = f"pinned by user ({user_pinned[knob]})"
            continue
        if knob in fixed:
            reasons[knob] = (
                f"forced to {fixed[knob]} by a feasibility rule"
            )
            continue
        alts: Dict[object, float] = {}
        for cfg, ph in scored:
            v = cfg[knob]
            alts[v] = min(alts.get(v, float("inf")), ph["total_s"])
        if knob == "dispatch" and len(alts) < 2:
            reasons[knob] = (
                f"{best_cfg[knob]} — the engine's measured "
                f"pair-dispatch crossover at this tile count"
            )
            continue
        if knob == "sketch" and len(alts) < 2:
            reasons[knob] = (
                "0 — dimensionality below the sketch gate or a "
                "non-euclidean kernel metric (prefilter off)"
            )
            continue
        if len(alts) < 2:
            reasons[knob] = "single candidate"
            continue
        chosen = best_cfg[knob]
        others = {v: t for v, t in alts.items() if v != chosen}
        alt_v, alt_t = min(others.items(), key=lambda it: it[1])
        reasons[knob] = (
            f"{chosen} predicted {alts[chosen]:.3f}s vs best "
            f"alternative {alt_v} at {alt_t:.3f}s"
        )

    return TunePlan(
        config=best_cfg,
        pinned=user_pinned,
        predicted=best_phases,
        candidates=[
            (cfg, ph["total_s"]) for cfg, ph in scored[:8]
        ],
        rules=rules,
        knob_reasons=reasons,
        corpus_rows_used=len(corpus_rows or []),
        coef_source=coef_tag,
        fallback_reason=fallback,
        probe_summary={
            "n": probe.n,
            "dim": probe.dim,
            "devices": probe.devices,
            "backend": probe.backend,
            "is_memmap": probe.is_memmap,
            "sample_rows": probe.sample_rows,
            "probe_s": probe.probe_s,
            "neighbors_per_point": probe.neighbors_per_point,
            "memory_pressure": probe.memory_pressure,
        },
    )
