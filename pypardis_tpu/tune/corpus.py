"""The tuning corpus: every observed run, one schema'd feature table.

Rows come from three places, normalized into the same
``pypardis_tpu/tuning_corpus@1`` shape:

* the committed benchmark archives (``BENCH_*.json`` /
  ``MESHSCALE_*.json`` / ``NORTHSTAR_*.json`` / ``*_probe`` rows) —
  anything carrying a ``run_report@1`` telemetry block yields a FULL
  row; partial archives (old BENCH tails, MESHSCALE mesh_rows) yield
  partial rows with the unknown config fields null;
* any JSON file/line the caller points :func:`harvest_corpus` at
  (flight/report archives replayed to reports work too);
* the local auto-fit archive (:func:`local_corpus_path`), one JSONL
  row per ``DBSCAN(auto=True)`` fit — the feedback loop that sharpens
  the model with use.

A row is dataset stats x config x outcome:

``features``: n, dim, devices, backend, input (ram/stream/device)
``config``:   mode, block, precision, merge, dispatch, owner_computes
``outcome``:  wall_s, per-phase build/exchange/compute/merge seconds,
              samples_per_sec, live_pairs, live_pair_fraction,
              kernel_passes, band_fraction, duplicated_work_factor,
              halo_bytes (boundary bytes on GM), peak_host_rss_bytes

Unknown fields are ``None`` — the model fitter only consumes rows
that carry what its term needs, but every observed run is kept (the
corpus is an archive, not a training set).
"""

from __future__ import annotations

import glob
import json
import os
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional
from ..utils import envreg

CORPUS_SCHEMA = "pypardis_tpu/tuning_corpus@1"

# Committed-archive filename patterns the harvester scans for.
_ARCHIVE_GLOBS = (
    "BENCH_*.json",
    "BENCH_SCALE_*.json",
    "MESHSCALE_*.json",
    "MULTICHIP_*.json",
    "NORTHSTAR_*.json",
    "STREAMMEM_*.json",
)


@dataclass
class CorpusRow:
    """One observed run (schema ``tuning_corpus@1``)."""

    # -- features (dataset stats) --
    n: Optional[int] = None
    dim: Optional[int] = None
    devices: Optional[int] = None
    backend: Optional[str] = None
    input: Optional[str] = None  # ram | stream | device
    # -- config --
    mode: Optional[str] = None  # fused | kd | global_morton | chained
    block: Optional[int] = None
    precision: Optional[str] = None
    merge: Optional[str] = None
    dispatch: Optional[str] = None  # pair | dense
    owner_computes: Optional[bool] = None
    # -- outcome --
    wall_s: Optional[float] = None
    build_s: Optional[float] = None
    exchange_s: Optional[float] = None
    compute_s: Optional[float] = None
    merge_s: Optional[float] = None
    samples_per_sec: Optional[float] = None
    live_pairs: Optional[int] = None
    live_pair_fraction: Optional[float] = None
    kernel_tiles: Optional[int] = None
    kernel_passes: Optional[int] = None
    band_fraction: Optional[float] = None
    # Resolved sketch-prefilter width of the run's kernel passes (0 =
    # off, None = the archive predates the knob).  When > 0 the run's
    # band_fraction IS the sketch rescore fraction (the stats columns
    # are shared — see ops.sketch), which is how the compute-term
    # fitter prices sketch rows.
    sketch_k: Optional[int] = None
    duplicated_work_factor: Optional[float] = None
    halo_bytes: Optional[int] = None
    peak_host_rss_bytes: Optional[int] = None
    # -- hierarchy outcome (eps=None fits; None = no hierarchy ran) --
    hier_pairs: Optional[int] = None       # stored pairs the core pass reduced
    hier_components: Optional[int] = None  # live components entering Borůvka
    hier_core_s: Optional[float] = None    # core-distance pass seconds
    hier_mst_s: Optional[float] = None     # MST (all Borůvka rounds) seconds
    # -- provenance --
    source: str = ""
    schema: str = field(default=CORPUS_SCHEMA)

    def to_dict(self) -> Dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "CorpusRow":
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in d.items() if k in known})

    def complete_for_compute(self) -> bool:
        """Whether the compute-term fitter can consume this row."""
        return None not in (
            self.compute_s, self.live_pairs, self.block, self.dim,
            self.kernel_passes,
        ) and self.compute_s > 0


def _num(v):
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return v if v == v and abs(v) != float("inf") else None


def row_from_report(report: Dict, *, wall_s=None,
                    source: str = "") -> Optional[CorpusRow]:
    """A corpus row from one ``run_report@1`` telemetry dict.

    Phase mapping: the global-Morton engine reports its own
    ``gm_build/gm_exchange/gm_execute/gm_merge`` decomposition; the KD
    and fused routes attribute the partition phase to build and the
    cluster phase to compute (their exchange rides inside the cluster
    span — the model treats it as part of the compute term for those
    modes, which is exactly how their wall behaves).
    """
    if not isinstance(report, dict) or "run" not in report:
        return None
    run = report.get("run", {})
    sh = report.get("sharding", {})
    comp = report.get("compute", {})
    phases = report.get("phases", {})
    params = report.get("params", {})
    res = report.get("resources", {})

    devices = int(run.get("n_devices", 1) or 1)
    if sh.get("mode") == "global_morton":
        mode = "global_morton"
        build = _num(phases.get("gm_build"))
        exchange = _num(phases.get("gm_exchange"))
        compute = _num(phases.get("gm_execute"))
        merge_s = _num(phases.get("gm_merge"))
        halo = _num(sh.get("boundary_tile_bytes"))
    else:
        mode = ("chained" if sh.get("chained") else
                "kd" if devices > 1 else "fused")
        build = _num(phases.get("partition"))
        exchange = None
        compute = _num(phases.get("cluster"))
        merge_s = None
        halo = _num(sh.get("halo_bytes"))

    tiles = _num(comp.get("kernel_tiles"))
    pairs = _num(comp.get("live_pairs"))
    dispatch = None
    if tiles and pairs is not None:
        # The report doesn't carry the dispatch tag directly; recover
        # it the way the kernels decided it (trace-time auto policy).
        try:
            from ..ops.distances import pair_dispatch_enabled

            dispatch = "pair" if pair_dispatch_enabled(int(tiles)) \
                else "dense"
        except Exception:  # noqa: BLE001 — provenance only
            dispatch = None

    total = _num(run.get("total_s"))
    pps = _num(run.get("points_per_sec"))
    hier = report.get("hierarchy", {})
    hier = hier if isinstance(hier, dict) else {}
    _hp = _num(hier.get("graph_pairs"))
    _hc = _num(hier.get("n_live"))  # initial Borůvka components
    return CorpusRow(
        n=int(run.get("n_points", 0) or 0) or None,
        dim=int(run.get("n_dims", 0) or 0) or None,
        devices=devices,
        backend=str(run.get("backend")) if run.get("backend") else None,
        input=str(sh.get("input", "ram")),
        mode=mode,
        block=int(comp.get("kernel_block") or params.get("block") or 0)
        or None,
        precision=comp.get("precision_mode") or params.get("precision"),
        merge=sh.get("merge"),
        dispatch=dispatch,
        owner_computes=sh.get("owner_computes"),
        wall_s=_num(wall_s) if wall_s is not None else total,
        build_s=build,
        exchange_s=exchange,
        compute_s=compute,
        merge_s=merge_s,
        samples_per_sec=pps,
        live_pairs=int(pairs) if pairs is not None else None,
        live_pair_fraction=_num(comp.get("live_pair_fraction")),
        kernel_tiles=int(tiles) if tiles is not None else None,
        kernel_passes=int(comp.get("kernel_passes") or 0) or None,
        band_fraction=_num(comp.get("band_fraction")),
        sketch_k=(
            int(comp["sketch_k"]) if _num(comp.get("sketch_k"))
            is not None else None
        ),
        duplicated_work_factor=_num(sh.get("duplicated_work_factor")),
        halo_bytes=int(halo) if halo is not None else None,
        peak_host_rss_bytes=int(
            _num(res.get("peak_host_rss_bytes")) or 0
        ) or None,
        hier_pairs=int(_hp) if _hp is not None else None,
        hier_components=int(_hc) if _hc is not None else None,
        hier_core_s=_num(hier.get("core_pass_s")),
        hier_mst_s=_num(hier.get("mst_s")),
        source=source,
    )


def _rows_from_obj(obj, source: str) -> List[CorpusRow]:
    """Corpus rows from one parsed JSON object of any archive shape."""
    rows: List[CorpusRow] = []
    if not isinstance(obj, dict):
        return rows
    if obj.get("schema") == CORPUS_SCHEMA:
        rows.append(CorpusRow.from_dict(obj))
        return rows
    # run_report@1 embedded as `telemetry` (bench/probe/northstar rows)
    # or the object IS a report.
    tel = obj.get("telemetry") if isinstance(
        obj.get("telemetry"), dict
    ) else (obj if obj.get("schema", "").endswith("run_report@1")
            else None)
    if tel is not None:
        # Prefer the row's own best-of-N samples over total_s: archived
        # `samples_s` are the timed-region walls the metric was cut
        # from; the report total includes generation/oracle overheads.
        wall = None
        samples = obj.get("samples_s")
        if isinstance(samples, list) and samples:
            finite = [s for s in samples if _num(s) is not None]
            if finite:
                wall = min(finite)
        r = row_from_report(tel, wall_s=wall, source=source)
        if r is not None:
            rows.append(r)
        return rows
    # BENCH_r0*.json archive shape: {"n","cmd","rc","tail","parsed"} —
    # the tail holds the emitted JSON line(s), possibly telemetry-free
    # on old rounds.
    if "tail" in obj and isinstance(obj["tail"], str):
        for ln in obj["tail"].splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                inner = json.loads(ln)
            except json.JSONDecodeError:
                continue
            rows.extend(_rows_from_obj(inner, source))
        if not rows and isinstance(obj.get("parsed"), dict):
            p = obj["parsed"]
            if _num(p.get("value")) is not None:
                rows.append(CorpusRow(
                    samples_per_sec=float(p["value"]),
                    source=source,
                ))
        return rows
    # MESHSCALE archive: partial mesh_rows (no telemetry block, but
    # real measured walls on real device counts).
    if isinstance(obj.get("mesh_rows"), list):
        for r in obj["mesh_rows"]:
            if not isinstance(r, dict):
                continue
            wall = _num(r.get("warm_fit_s")) or _num(r.get("cold_fit_s"))
            rows.append(CorpusRow(
                n=int(r.get("n", 0) or 0) or None,
                dim=int(r.get("dim", 0) or 0) or None,
                devices=int(r.get("mesh_devices", 0) or 0) or None,
                backend=r.get("platform"),
                mode=r.get("mode"),
                merge=r.get("merge"),
                wall_s=wall,
                build_s=_num(r.get("partition_s")),
                samples_per_sec=_num(r.get("warm_pts_per_sec_total")),
                source=source,
            ))
        return rows
    return rows


def local_corpus_path() -> Optional[str]:
    """The local auto-fit archive path (``PYPARDIS_TUNE_CORPUS``).

    Default: ``~/.cache/pypardis_tpu/tuning_corpus.jsonl``.  Set the
    env var to a path to relocate it, or to ``0``/empty to disable the
    feedback loop entirely (auto fits then plan from the committed
    archives and heuristics alone).
    """
    env = envreg.raw("PYPARDIS_TUNE_CORPUS")
    if env is not None:
        if env in ("", "0"):
            return None
        return env
    return os.path.join(
        os.path.expanduser("~"), ".cache", "pypardis_tpu",
        "tuning_corpus.jsonl",
    )


def append_local_row(row: CorpusRow, path: Optional[str] = None) -> bool:
    """Append one auto-fit row to the local archive (atomic enough:
    one ``write`` of one line in append mode).  Returns False when the
    archive is disabled or unwritable — the feedback loop is an
    optimization, never a fit failure."""
    if path is None:
        path = local_corpus_path()
    if not path:
        return False
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(row.to_dict()) + "\n")
        return True
    except OSError:
        return False


# Parsed-file cache keyed by (path, mtime, size): an auto fit
# harvests on EVERY fit (the feedback loop), but the committed
# archives change only on commit — re-parsing them per fit was a
# measurable slice of the <=5% probe-overhead budget.
_FILE_CACHE: Dict = {}


def _rows_from_file(path: str) -> List[CorpusRow]:
    try:
        st = os.stat(path)
        key = (path, st.st_mtime_ns, st.st_size)
    except OSError:
        return []
    hit = _FILE_CACHE.get(path)
    if hit is not None and hit[0] == key:
        return hit[1]
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return []
    objs = []
    try:
        objs = [json.loads(text)]
    except json.JSONDecodeError:
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue
            try:
                objs.append(json.loads(ln))
            except json.JSONDecodeError:
                continue
    rows: List[CorpusRow] = []
    for obj in objs:
        rows.extend(_rows_from_obj(obj, os.path.basename(path)))
    _FILE_CACHE[path] = (key, rows)
    return rows


def harvest_corpus(
    roots=None, *, local: Optional[str] = None, extra_files=None,
) -> List[CorpusRow]:
    """Harvest every reachable observed run into corpus rows.

    ``roots``: directories to scan for the committed archive globs
    (default: the current working directory — where a repo checkout
    keeps its ``BENCH_*.json`` family — plus ``PYPARDIS_TUNE_ROOT``
    when set).  ``local``: the auto-fit JSONL archive (default
    :func:`local_corpus_path`).  ``extra_files``: any further JSON /
    JSONL files.  Unreadable or unparseable files are skipped — the
    corpus harvests what exists, it never fails a fit.  Parsed
    archives are cached per (mtime, size), so the per-fit harvest of
    an auto model costs a handful of ``stat`` calls.
    """
    if roots is None:
        roots = [os.getcwd()]
        env_root = envreg.raw("PYPARDIS_TUNE_ROOT")
        if env_root:
            roots.append(env_root)
    files: List[str] = []
    for root in roots:
        for pat in _ARCHIVE_GLOBS:
            files.extend(sorted(glob.glob(os.path.join(root, pat))))
    if extra_files:
        files.extend(extra_files)
    rows: List[CorpusRow] = []
    for path in files:
        rows.extend(_rows_from_file(path))
    lpath = local if local is not None else local_corpus_path()
    if lpath and os.path.exists(lpath):
        try:
            with open(lpath) as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        d = json.loads(ln)
                    except json.JSONDecodeError:
                        continue  # torn final line of a killed writer
                    rows.append(CorpusRow.from_dict(d))
        except OSError:
            pass
    return rows
