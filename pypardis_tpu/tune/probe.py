"""Bounded-cost dataset probe: the features a plan depends on.

One sampling pass over the input — never the full dataset — estimating
what the cost model needs: density around eps, predicted live
tile-pair fraction per candidate block (the tiled kernels' own work
model), the mixed-precision band fraction, and the memory footprint
vs ``PYPARDIS_RSS_SOFT_LIMIT``.  Reuses the partitioner's Morton-tile
arithmetic (:func:`~pypardis_tpu.partition._chunked_center`,
``spatial_order``, tile boxes, box-gap live counts) so the estimates
share the engine's own geometry, and reads memmaps in strided
contiguous chunks so out-of-core fits can be planned without faulting
the whole file.

The tile-geometry trick that makes a SAMPLE predictive: for a full-
data kernel block ``B``, probe the sample of ``S`` rows at block
``b = max(1, B * S / n)`` — the sample then has the same tile COUNT
``T = ceil(n / B)`` as the full run, each sample tile subsamples the
same spatial cell the full tile covers, so its bounding box (and the
box-gap live-pair count) estimates the full tile's directly.  Sampled
live weights transfer as-is: est live pairs = sum(w), est fraction =
sum(w) / T^2.

Cost bound: ``PYPARDIS_TUNE_SAMPLE`` rows (default 32768) for the
tile pass, 1024 rows for the exact pairwise density pass — both
independent of n.
"""

from __future__ import annotations

import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

import numpy as np
from ..utils import envreg

_DENSITY_ROWS = 1024
# Relative half-width of the mixed-precision rescore band around
# eps^2 used for the band-fraction ESTIMATE (the real band is the
# bf16 worst-case bound from ops.precision; ~2% of eps^2 is its
# observed magnitude on recentred data — the estimate only has to
# rank precision modes, the kernels compute the exact band anyway).
_BAND_REL = 0.02


@dataclass
class DatasetProbe:
    """Schema'd probe result (``tune_probe@1``)."""

    n: int
    dim: int
    eps: float
    devices: int
    backend: str
    is_memmap: bool
    dtype_bytes: int
    dataset_bytes: int
    sample_rows: int
    probe_s: float
    # Estimated within-eps neighbors per point (self included — the
    # kernels count self-pairs too).
    neighbors_per_point: float
    # Fraction of ALL point pairs within eps (sampled, exact pass).
    pair_fraction_in_eps: float
    # Fraction of sampled pairs whose d^2 lands in the mixed-precision
    # rescore band around eps^2.
    pair_fraction_in_band: float
    # Sketch-prefilter features: the auto sketch width for this dim
    # (0 below the min-d gate) and the fraction of sampled pairs the
    # certified sketch gate at that width leaves AMBIGUOUS (neither
    # definitely-in nor definitely-out) — the pairs that pay the
    # full-d rescore.  Measured with the REAL projection matrix and
    # gate band, so the estimate shares the kernels' own geometry.
    sketch_k_auto: int = 0
    pair_fraction_in_sketch_band: float = 0.0
    # Per candidate block: estimated tiles, live tile pairs, live
    # tile-pair fraction, and the derived band fraction (band pairs /
    # pairs examined per pass).
    blocks: Dict[int, Dict[str, float]] = field(default_factory=dict)
    rss_soft_limit: int = 0
    memory_pressure: bool = False
    # Predicted peak anonymous footprint of an in-RAM fit (staged f32
    # slabs ~= 3x the f32 dataset: host staging + device copy + layout
    # products), for the feasibility rules.
    est_fit_rss_bytes: int = 0
    schema: str = "pypardis_tpu/tune_probe@1"

    def to_dict(self) -> Dict:
        d = asdict(self)
        d["blocks"] = {str(k): v for k, v in self.blocks.items()}
        return d


def _live_fraction(lo, hi, eps: float, row_cap: int = 512,
                   col_cap: int = 1024) -> float:
    """Live (box-gap <= eps) fraction of the tile-pair grid, from a
    strided subsample of row and column tiles.

    The engine's own ``_weights_from_boxes`` computes exact per-tile
    counts for the work-balanced split; the probe only needs the
    FRACTION, which is invariant under even-stride sampling
    (Morton-adjacent tiles are spatially redundant), so capping both
    sides bounds the pass at ``row_cap * col_cap`` box pairs per
    candidate block regardless of n.
    """
    nt = len(lo)
    rs = max(1, -(-nt // row_cap))
    cs = max(1, -(-nt // col_cap))
    rlo, rhi = lo[::rs], hi[::rs]
    clo, chi = lo[::cs], hi[::cs]
    gap = np.maximum(
        0.0,
        np.maximum(clo[None] - rhi[:, None], rlo[:, None] - chi[None]),
    )
    eps2 = np.float32(eps) ** 2
    return float(
        (np.sum(gap * gap, axis=-1) <= eps2).mean()
    )


def _sample_rows(points, n: int, k: int, target: int) -> np.ndarray:
    """A (<=target, k) float sample in strided contiguous chunks.

    Contiguous chunks keep memmap reads sequential (64 seeks, not
    ``target`` random faults); the even stride keeps the sample
    spatially representative of the global Morton geometry.
    """
    if n <= target:
        return np.asarray(points[:], dtype=np.float64, copy=True) \
            if not isinstance(points, np.ndarray) else \
            np.array(points, dtype=np.float64, copy=True)
    chunks = 64
    per = max(1, target // chunks)
    out = np.empty((per * chunks, k), np.float64)
    stride = n / chunks
    for c in range(chunks):
        s = min(int(c * stride), n - per)
        out[c * per:(c + 1) * per] = points[s:s + per]
    return out


def probe_dataset(
    points,
    eps: float,
    *,
    blocks=(128, 256, 512, 1024),
    devices: Optional[int] = None,
    backend: Optional[str] = None,
    sample_rows: Optional[int] = None,
) -> DatasetProbe:
    """Estimate the plan-relevant features of ``points`` at ``eps``.

    ``eps`` is the KERNEL-frame threshold (the caller remaps cosine/
    haversine before probing, exactly as the fit does).  ``blocks``
    are the candidate kernel blocks the planner will score.
    """
    from ..obs.resources import (
        host_rss_bytes, memory_pressure, rss_soft_limit,
    )
    from ..partition import (
        _chunked_center, _tile_boxes_inram, spatial_order,
    )

    t0 = time.perf_counter()
    n, k = points.shape
    if sample_rows is None:
        env = envreg.raw("PYPARDIS_TUNE_SAMPLE")
        if env:
            sample_rows = int(env)
        else:
            # Adaptive: the probe must stay a small FRACTION of the
            # fit, and fit wall grows with n while the probe's cost
            # tracks the sample — n/16 keeps the ratio bounded at
            # small n, the 32768 cap keeps it bounded at large n.
            sample_rows = min(1 << 15, max(1 << 12, n // 16))
    if devices is None:
        import jax

        devices = jax.device_count()
    if backend is None:
        import jax

        backend = jax.default_backend()
    is_memmap = isinstance(points, np.memmap)
    dtype_bytes = int(np.dtype(points.dtype).itemsize) \
        if np.dtype(points.dtype).kind == "f" else 8

    sample = _sample_rows(points, n, k, max(int(sample_rows), 256))
    s_rows = len(sample)
    # The probe's own center (sample-bounded cost): fine for tile
    # geometry — recentring only needs magnitude control, and the
    # sample mean is within O(sigma/sqrt(S)) of the dataset mean.
    center = _chunked_center(sample, s_rows, k)
    sub = (sample - center).astype(np.float32)
    order = spatial_order(sub)

    # -- exact pairwise density on a small sub-sample -----------------
    dens = sub[
        np.linspace(0, s_rows - 1, min(s_rows, _DENSITY_ROWS)).astype(
            np.int64
        )
    ].astype(np.float64)
    # |x|^2 + |y|^2 - 2xy via one gemm (the kernels' own expansion):
    # the naive (m, m, k) broadcast temp costs seconds at 2048 rows,
    # the gemm milliseconds.
    sq = np.einsum("ij,ij->i", dens, dens)
    d2 = np.maximum(
        sq[:, None] + sq[None, :] - 2.0 * (dens @ dens.T), 0.0
    ).ravel()
    eps2 = float(eps) ** 2
    m = len(dens) * len(dens)
    p_eps = float(np.count_nonzero(d2 <= eps2)) / m
    p_band = float(
        np.count_nonzero(np.abs(d2 - eps2) <= _BAND_REL * eps2)
    ) / m
    neighbors = p_eps * n

    # -- sketch-gate ambiguity on the same sub-sample -----------------
    # Run the REAL certified gate (projection matrix, residual bound,
    # gate band — ops.sketch) over the density pairs at the auto width:
    # the fraction left ambiguous is what the cost model charges the
    # full-d rescore term for.  Host numpy throughout; O(m^2 k).
    from ..ops.sketch import (
        resolve_sketch, sketch_gate_band, sketch_matrix,
    )

    sk_auto = resolve_sketch("auto", k)
    p_sk_band = 0.0
    if sk_auto > 0:
        q, eta = sketch_matrix(k, sk_auto)
        s = dens @ q.astype(np.float64)
        ssq = np.einsum("ij,ij->i", s, s)
        resid = np.sqrt(np.maximum(sq - ssq, 0.0))
        t2 = np.maximum(
            ssq[:, None] + ssq[None, :] - 2.0 * (s @ s.T), 0.0
        ) + (resid[:, None] - resid[None, :]) ** 2
        up = t2 + 4.0 * resid[:, None] * resid[None, :]
        nmax = float(np.sqrt(sq.max())) if len(sq) else 0.0
        band = float(sketch_gate_band(nmax, k, sk_auto, eta))
        ambig = ~((t2.ravel() - band > eps2)
                  | (up.ravel() <= eps2 - band))
        p_sk_band = float(np.count_nonzero(ambig)) / m

    # -- per-block tile geometry --------------------------------------
    block_stats: Dict[int, Dict[str, float]] = {}
    for B in sorted({int(b) for b in blocks}):
        if B <= 0:
            continue
        tiles = max(1, -(-n // B))
        b_s = max(1, int(round(B * s_rows / n)))
        lo, hi = _tile_boxes_inram(sub, order, b_s)
        frac = min(1.0, _live_fraction(lo, hi, float(eps)))
        live_pairs = frac * tiles * tiles
        band_fraction = min(
            1.0, p_band / frac if frac > 0 else 0.0
        )
        # Same pair-mass-to-live-mass transfer band_fraction uses: the
        # share of LIVE pair work the sketch gate leaves ambiguous.
        sketch_band_fraction = min(
            1.0, p_sk_band / frac if frac > 0 else 0.0
        )
        block_stats[B] = {
            "tiles": float(tiles),
            "live_pairs": float(live_pairs),
            "live_pair_fraction": float(frac),
            "band_fraction": float(band_fraction),
            "sketch_band_fraction": float(sketch_band_fraction),
        }

    limit = rss_soft_limit()
    est_rss = int(3 * n * k * 4) + host_rss_bytes()
    return DatasetProbe(
        n=int(n),
        dim=int(k),
        eps=float(eps),
        devices=int(devices),
        backend=str(backend),
        is_memmap=bool(is_memmap),
        dtype_bytes=dtype_bytes,
        dataset_bytes=int(n * k * dtype_bytes),
        sample_rows=int(s_rows),
        probe_s=float(time.perf_counter() - t0),
        neighbors_per_point=float(neighbors),
        pair_fraction_in_eps=p_eps,
        pair_fraction_in_band=p_band,
        sketch_k_auto=int(sk_auto),
        pair_fraction_in_sketch_band=float(p_sk_band),
        blocks=block_stats,
        rss_soft_limit=int(limit),
        memory_pressure=bool(memory_pressure()),
        est_fit_rss_bytes=est_rss,
    )


def candidate_blocks(n: int, base=(128, 256, 512, 1024)) -> List[int]:
    """The block lattice clamped to the dataset (a block above n/2
    degenerates to one tile — keep one such candidate at most)."""
    from ..utils import clamp_block

    out = sorted({int(clamp_block(b, n)) for b in base})
    return out or [128]
