"""Interpretable analytic cost model, coefficients fit from the corpus.

Per-phase terms (the tiled engine's own work model — the same
decomposition ``report()["compute"]`` and the northstar rows carry):

* ``build``    ~ ``build_row_s * n * dim`` (streaming builds pay a
  separate, larger coefficient — the external sample-sort reads the
  file three times);
* ``exchange`` ~ ``exch_byte_s * boundary_bytes`` (global-Morton mesh
  route only; the KD halo cost rides inside compute as duplicated
  work, which is how its wall actually behaves);
* ``compute``  ~ ``pair_flop_s * live_pairs * block^2 * (dim+2) * 2 *
  passes * precision_factor  +  pair_visit_s * live_pairs * passes``
  (+ ``tile_scan_s * tiles^2 * passes`` under dense dispatch — the
  scan iterations the pair compaction removes);
* ``merge``    ~ ``merge_host_row_s * n`` (host union-find spill) or
  ``merge_round_s * devices * rounds`` (in-graph pmin fixpoint).

Coefficients are least-squares fit per ``(backend, devices)`` bucket
from corpus rows that carry the term's operands; a bucket with too few
rows falls back to the same backend at any device count, then to the
documented heuristic defaults below (each traceable to a committed
measurement — see the inline notes).  The fit is per-coefficient, so a
corpus that can only inform the compute term still sharpens it while
exchange/build/merge ride the defaults.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from .corpus import CorpusRow

# Heuristic defaults per backend family.  CPU numbers are derived from
# the committed NORTHSTAR_smoke.json (5M x 16-D, faked 8-dev mesh:
# compute 185.1s over 126072 live pairs at block 256 x 6 passes ->
# ~9.6 GFLOP/s sustained; exchange 178.3s over 156MB of boundary
# tiles; host merge 13.1s over 5M rows) and the PR 11 kernel-probe
# measurements.  TPU numbers assume the bf16_3x f32-synthesis ceiling
# of the chip peak (obs.report's table) — they are placeholders the
# corpus replaces after one real-hardware row.
_DEFAULTS = {
    "cpu": {
        "build_row_s": 1.2e-7,       # in-RAM morton build, s/element
        "build_row_stream_s": 4.0e-7,  # external sample-sort s/element
        "pair_flop_s": 1.0e-10,      # ~10 GFLOP/s sustained
        "pair_visit_s": 2.0e-6,      # per live tile-pair dispatch
        "tile_scan_s": 3.0e-7,       # dense-grid scan iteration
        "exch_byte_s": 1.1e-6,       # host-stepped ring, s/byte
        "merge_host_row_s": 2.6e-6,  # union-find spill, s/row
        "merge_round_s": 0.05,       # pmin fixpoint, s/round/device
        "hier_pair_s": 2.5e-7,       # core-dist pass, s/stored pair
        "hier_round_s": 6.0e-8,      # Borůvka, s/pair/round
    },
    "tpu": {
        "build_row_s": 2.0e-9,
        "build_row_stream_s": 4.0e-7,  # disk-bound either way
        "pair_flop_s": 1.0 / 60e12,  # ~peak/3 at v5e-class silicon
        "pair_visit_s": 2.0e-7,
        "tile_scan_s": 5.0e-8,
        "exch_byte_s": 2.0e-9,       # ICI, not a host-stepped ring
        "merge_host_row_s": 2.6e-6,  # host merge is host-bound anywhere
        # The hierarchy terms are host-bound on any backend: the pair
        # slab lands on host for the MST either way.
        "merge_round_s": 0.002,
        "hier_pair_s": 2.5e-7,
        "hier_round_s": 6.0e-8,
    },
}


def boruvka_rounds_est(components: float) -> int:
    """The Borůvka round budget the engine itself is pinned to:
    components at least halve per round, so ``ceil(log2(C0)) + 1``
    (the +1 is the final no-progress detection round)."""
    import math

    return int(math.ceil(math.log2(max(float(components), 2.0)))) + 1
_FIXPOINT_ROUNDS = 3  # observed 3 on every committed GM row


def precision_factor(backend: str, precision: str,
                     band_fraction: float = 0.0) -> float:
    """Relative per-pair cost vs ``high``.

    On CPU the fast pass IS the exact pass (``_fast_is_exact``), so
    ``mixed`` only adds the classification bookkeeping (~+10%
    measured, PR 7).  On the MXU ``high`` synthesizes f32 from three
    bf16 passes while ``mixed`` runs one bf16 pass plus the
    band-fraction-weighted exact rescore.
    """
    p = str(precision)
    if backend == "cpu":
        return {"default": 1.0, "high": 1.0, "highest": 1.6,
                "mixed": 1.1}.get(p, 1.0)
    return {
        "default": 0.34,
        "high": 1.0,
        "highest": 2.0,
        "mixed": 0.34 + 3.0 * min(max(band_fraction, 0.0), 1.0),
    }.get(p, 1.0)


def _pair_flops(
    live_pairs: float, block: int, dim: int, passes: int, pf: float,
    backend: str, sketch: int = 0, sketch_band_fraction: float = 1.0,
) -> float:
    """Model FLOPs of the distance pass for one config.

    Sketch off: the classic ``pairs * B^2 * (dim+2) * 2 * passes * pf``.
    Sketch on (``sketch`` = resolved k): every pair runs the (k+1)-dim
    slab gate at HIGHEST precision — ``(k+3)`` columns with the same
    augmented-operand accounting — and only the ambiguous fraction
    reruns the full-d exact term, so the two terms are
    ``pairs * B^2 * (k+3)`` + ``band_fraction * pairs * B^2 * (d+2)``.
    One shared ``pair_flop_s`` coefficient prices both (they run on the
    same unit, the MXU/gemm path), which is what lets sketch rows and
    unsketched rows fit the SAME coefficient.
    """
    base = float(live_pairs) * block * block * 2.0 * passes
    if sketch <= 0:
        return base * (dim + 2) * pf
    sbf = min(max(float(sketch_band_fraction), 0.0), 1.0)
    pf_hi = precision_factor(backend, "highest")
    return base * ((sketch + 3) * pf_hi + sbf * (dim + 2) * pf)


def _nonneg_lstsq(X: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Plain least squares with negative coefficients clamped to 0 and
    refit on the surviving columns — enough structure for 1-2 column
    physical models (a full NNLS dependency is not warranted)."""
    cols = list(range(X.shape[1]))
    for _ in range(X.shape[1]):
        beta, *_ = np.linalg.lstsq(X[:, cols], y, rcond=None)
        if (beta >= 0).all():
            out = np.zeros(X.shape[1])
            out[cols] = beta
            return out
        cols = [c for c, b in zip(cols, beta) if b > 0]
        if not cols:
            return np.zeros(X.shape[1])
    out = np.zeros(X.shape[1])
    out[cols] = np.maximum(beta, 0.0)
    return out


@dataclass
class CostModel:
    """Per-phase coefficients for one ``(backend, devices)`` bucket."""

    backend: str = "cpu"
    devices: int = 1
    coef: Dict[str, float] = field(default_factory=dict)
    # Which corpus rows informed which coefficient (counts), and where
    # each coefficient came from ("corpus", "corpus:any-devices",
    # "heuristic") — the explain() provenance.
    rows_used: int = 0
    sources: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def fit_from_corpus(
        cls, rows: List[CorpusRow], backend: str, devices: int,
    ) -> "CostModel":
        """Least squares per coefficient over matching-bucket rows."""
        fam = "cpu" if backend == "cpu" else "tpu"
        coef = dict(_DEFAULTS[fam])
        sources = {k: "heuristic" for k in coef}
        used = 0

        def bucket(strict: bool) -> List[CorpusRow]:
            return [
                r for r in rows
                if r.backend == backend
                and (not strict or r.devices == devices)
            ]

        def accept(key: str, val: float, tag: str) -> bool:
            # Sanity bound: a fitted coefficient more than 100x off
            # the documented default is an artifact of a tiny or
            # degenerate bucket (two colinear rows solve exactly and
            # generalize terribly), not a measurement — keep the
            # heuristic and let the bucket grow.
            lo, hi = _DEFAULTS[fam][key] / 100.0, \
                _DEFAULTS[fam][key] * 100.0
            if not (val > 0 and lo <= val <= hi):
                return False
            coef[key] = float(val)
            sources[key] = tag
            return True

        for strict, tag in ((True, "corpus"),
                            (False, "corpus:any-devices")):
            sel = bucket(strict)
            # -- compute term: [flops, pair visits] -> compute_s ------
            comp = [r for r in sel if r.complete_for_compute()
                    and sources.get("pair_flop_s") == "heuristic"]
            # >= 4 rows for the 2-column fit: two rows solve exactly
            # (zero residual, zero generalization) and a degenerate
            # solve once inverted the planner's whole block ranking.
            if len(comp) >= 4:
                X = np.array([
                    [
                        _pair_flops(
                            r.live_pairs, r.block, r.dim,
                            r.kernel_passes or 1,
                            precision_factor(
                                backend, r.precision or "high",
                                r.band_fraction or 0.0,
                            ),
                            backend,
                            sketch=r.sketch_k or 0,
                            sketch_band_fraction=(
                                r.band_fraction
                                if r.band_fraction is not None else 1.0
                            ),
                        ),
                        float(r.live_pairs * (r.kernel_passes or 1)),
                    ]
                    for r in comp
                ])
                y = np.array([r.compute_s for r in comp])
                beta = _nonneg_lstsq(X, y)
                hit = accept("pair_flop_s", float(beta[0]), tag)
                hit = accept(
                    "pair_visit_s", float(beta[1]), tag
                ) or hit
                if hit:
                    used += len(comp)
            # -- exchange term: boundary bytes -> exchange_s ----------
            exch = [
                r for r in sel
                if r.exchange_s and r.halo_bytes
                and sources.get("exch_byte_s") == "heuristic"
            ]
            if exch:
                num = sum(r.exchange_s for r in exch)
                den = sum(r.halo_bytes for r in exch)
                if den > 0 and accept(
                    "exch_byte_s", float(num / den), tag
                ):
                    used += len(exch)
            # -- build term: n*dim -> build_s (stream rows separate) --
            for key, want_stream in (("build_row_s", False),
                                     ("build_row_stream_s", True)):
                bld = [
                    r for r in sel
                    if r.build_s and r.n and r.dim
                    and (r.input == "stream") == want_stream
                    and sources.get(key) == "heuristic"
                ]
                if bld:
                    num = sum(r.build_s for r in bld)
                    den = sum(float(r.n * r.dim) for r in bld)
                    if den > 0 and accept(key, float(num / den), tag):
                        used += len(bld)
            # -- merge term -------------------------------------------
            mh = [
                r for r in sel
                if r.merge_s and r.n and r.merge == "host"
                and sources.get("merge_host_row_s") == "heuristic"
            ]
            if mh and accept(
                "merge_host_row_s",
                float(sum(r.merge_s for r in mh)
                      / sum(float(r.n) for r in mh)),
                tag,
            ):
                used += len(mh)
            md = [
                r for r in sel
                if r.merge_s and r.devices and r.merge == "device"
                and sources.get("merge_round_s") == "heuristic"
            ]
            if md and accept(
                "merge_round_s",
                float(sum(r.merge_s for r in md)
                      / sum(float(r.devices * _FIXPOINT_ROUNDS)
                            for r in md)),
                tag,
            ):
                used += len(md)
            # -- hierarchy terms: core pass ∝ stored pairs; MST ∝
            # rounds(log of live components) x pairs (Borůvka) --------
            hc = [
                r for r in sel
                if r.hier_core_s and r.hier_pairs
                and sources.get("hier_pair_s") == "heuristic"
            ]
            if hc and accept(
                "hier_pair_s",
                float(sum(r.hier_core_s for r in hc)
                      / sum(float(r.hier_pairs) for r in hc)),
                tag,
            ):
                used += len(hc)
            hm = [
                r for r in sel
                if r.hier_mst_s and r.hier_pairs and r.hier_components
                and sources.get("hier_round_s") == "heuristic"
            ]
            if hm and accept(
                "hier_round_s",
                float(sum(r.hier_mst_s for r in hm)
                      / sum(float(r.hier_pairs)
                            * boruvka_rounds_est(r.hier_components)
                            for r in hm)),
                tag,
            ):
                used += len(hm)
        return cls(
            backend=backend, devices=devices, coef=coef,
            rows_used=used, sources=sources,
        )

    # -- prediction -------------------------------------------------------

    def predict_phases(
        self,
        *,
        n: int,
        dim: int,
        devices: int,
        mode: str,
        block: int,
        precision: str,
        merge: str,
        dispatch: str,
        live_pairs: float,
        tiles: float,
        band_fraction: float = 0.0,
        boundary_bytes: float = 0.0,
        is_stream: bool = False,
        passes: int = 4,
        sketch: int = 0,
        sketch_band_fraction: float = 1.0,
    ) -> Dict[str, float]:
        """Predicted per-phase seconds for one concrete config.

        ``live_pairs``/``tiles``/``band_fraction`` come from the probe
        at this ``block``; ``boundary_bytes`` is the planner's
        exchange-traffic estimate (0 off the GM mesh route).  On a
        mesh, per-device work divides by the device count while the
        host-stepped terms (exchange, host merge) do not — on the
        1-core CI mesh that division is a no-op, which the CPU bucket's
        coefficients already absorb (they were fit on faked meshes).
        """
        c = self.coef
        par = max(1, devices if self.backend != "cpu" else 1)
        pf = precision_factor(self.backend, precision, band_fraction)
        flops = _pair_flops(
            live_pairs, block, dim, passes, pf, self.backend,
            sketch=sketch, sketch_band_fraction=sketch_band_fraction,
        )
        compute = (
            c["pair_flop_s"] * flops
            + c["pair_visit_s"] * float(live_pairs) * passes
        ) / par
        if dispatch == "dense":
            compute += c["tile_scan_s"] * float(tiles) ** 2 * passes \
                / par
        build_key = "build_row_stream_s" if is_stream else "build_row_s"
        build = c[build_key] * float(n) * dim
        exchange = 0.0
        if mode == "global_morton" and devices > 1:
            exchange = c["exch_byte_s"] * float(boundary_bytes)
        if mode == "kd" and devices > 1:
            # KD halo cost is duplicated compute, not a wall phase of
            # its own: the halo slab rows re-enter the kernels.
            dup = 1.0 + min(
                1.0, float(boundary_bytes) / max(n * dim * 4.0, 1.0)
            )
            compute *= dup
        if merge == "host":
            merge_s = c["merge_host_row_s"] * float(n)
        elif devices > 1:
            merge_s = c["merge_round_s"] * devices * _FIXPOINT_ROUNDS
        else:
            merge_s = 0.0
        total = build + exchange + compute + merge_s
        return {
            "build_s": float(build),
            "exchange_s": float(exchange),
            "compute_s": float(compute),
            "merge_s": float(merge_s),
            "total_s": float(total),
        }

    def predict_hierarchy(
        self, pairs: float, components: float,
    ) -> Dict[str, float]:
        """Predicted hierarchy seconds for an eps=None fit.

        ``pairs`` = stored pair-slab entries the one distance pass
        emits at the graph ceiling; ``components`` = live points
        entering Borůvka (each starts as its own component).  The core
        pass is one segment reduction over the slab; each Borůvka
        round is a segment-min + union-find contraction over the same
        slab, and rounds are logarithmic in the components
        (:func:`boruvka_rounds_est`) — both host-bound on any backend.
        """
        c = self.coef
        rounds = boruvka_rounds_est(components)
        core_s = c["hier_pair_s"] * float(pairs)
        mst_s = c["hier_round_s"] * rounds * float(pairs)
        return {
            "hier_core_s": float(core_s),
            "hier_mst_s": float(mst_s),
            "hier_rounds": float(rounds),
            "hierarchy_s": float(core_s + mst_s),
        }


def model_for(
    rows: Optional[List[CorpusRow]], backend: str, devices: int,
) -> Tuple[CostModel, str]:
    """A fitted model plus a one-line provenance tag."""
    model = CostModel.fit_from_corpus(rows or [], backend, devices)
    n_corpus = sum(
        1 for s in model.sources.values() if s.startswith("corpus")
    )
    if n_corpus == 0:
        tag = f"heuristic defaults ({backend}); no corpus bucket matched"
    else:
        tag = (
            f"{n_corpus}/{len(model.sources)} coefficients fit from "
            f"{model.rows_used} corpus row(s), bucket "
            f"({backend}, {devices} devices)"
        )
    return model, tag
