"""Auto-tuning subsystem: telemetry-fed cost model + config planner.

The engine has six distributed modes, two dispatch policies, three
precision ladders, two merge routes, and a block-size knob whose best
value flipped 1024->256 when pair dispatch landed — and until this
package the user picked all of them by hand.  The NoisePage/OtterTune
move (fit a model on your own observed runs, plan from it) applied to
the clustering stack:

* :mod:`~pypardis_tpu.tune.corpus` — harvest every committed
  ``BENCH_*``/``MESHSCALE_*``/``NORTHSTAR_*`` row plus the local
  auto-fit archive into one schema'd feature table
  (``tuning_corpus@1``);
* :mod:`~pypardis_tpu.tune.probe` — a bounded-cost sampling pass over
  the input estimating the features a plan depends on (density at eps,
  live tile-pair fraction per candidate block, mixed-precision band
  fraction, memory footprint) — memmap-safe, so out-of-core fits plan
  too;
* :mod:`~pypardis_tpu.tune.model` — an interpretable analytic
  per-phase cost model whose coefficients fit from the corpus by least
  squares per ``(backend, devices)`` bucket, with documented heuristic
  fallbacks;
* :mod:`~pypardis_tpu.tune.planner` — hard feasibility rules first
  (memmap -> streaming global-Morton, 1 device -> chained, RSS
  pressure -> merge=host), then score the discrete config lattice and
  return a :class:`~pypardis_tpu.tune.planner.TunePlan` with an
  ``explain()`` trace.

Surface: ``DBSCAN(auto=True)`` — user-set knobs are pinned, only
unset ones are planned, and every planned knob is label-safe, so
labels are byte-identical to the same explicit config by
construction.  Each auto fit appends its own (features, config,
outcome) row to the local corpus so the model sharpens with use.
"""

from .corpus import (
    CORPUS_SCHEMA,
    CorpusRow,
    append_local_row,
    harvest_corpus,
    local_corpus_path,
    row_from_report,
)
from .model import CostModel
from .planner import TunePlan, plan_fit
from .probe import DatasetProbe, probe_dataset

__all__ = [
    "CORPUS_SCHEMA",
    "CorpusRow",
    "CostModel",
    "DatasetProbe",
    "TunePlan",
    "append_local_row",
    "harvest_corpus",
    "local_corpus_path",
    "plan_fit",
    "probe_dataset",
    "row_from_report",
]
