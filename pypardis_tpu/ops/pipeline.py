"""Fused on-device single-shard DBSCAN pipeline.

The round-2 driver did spatial sorting (Morton codes + argsort), padding,
and result decoding on the host, then pulled ``roots`` and ``core`` to the
host as two separate transfers.  Profiling on the real chip showed the
kernel itself is a minority of end-to-end time: host Morton coding +
sorting cost ~80ms at 200k points, and every device->host transfer has a
large fixed latency (remote-tunnel deployments measure ~100ms *per
transfer* regardless of size).

This module keeps the whole hot path on the device, where the reference
keeps it on Spark executors (``/root/reference/dbscan/dbscan.py:12-34``):

* quantize + interleave Morton codes on-device (vector shifts, fused by
  XLA into a handful of passes);
* ``lexsort`` the code's uint32 words (1-4 of them, per
  :func:`pypardis_tpu.partition.morton_plan`) on-device (TPU sort HLO)
  — word-sliced so it runs in JAX's default 32-bit mode;
* gather points into sorted order, staying in the ``(d, cap)``
  transposed layout end to end (XLA:TPU pads the minor axis of
  ``(N, small-d)`` buffers 8x in HBM; point-axis-minor stays dense);
* run the fixed-size DBSCAN kernel (:func:`dbscan_fixed_size`);
* map sorted-space root indices back through the permutation and
  scatter labels/core to input order;
* pack ``(roots, core)`` into ONE ``(2, cap)`` int32 array so the
  driver performs exactly one device->host transfer.

Shapes are static in ``cap = round_up(n, block)`` only; the true count
``n`` rides as a traced scalar, so partitions of nearby sizes share one
compiled program.  The only host work left in the driver is the float64
mean (centering accuracy at GPS-scale magnitudes), the zero-pad to
``cap``, and the final label densification.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .labels import dbscan_fixed_size

def _device_morton_words(x, mask):
    """Per-point Morton code as a list of uint32 words (most significant
    first), masked-last.

    ``x``: (d, cap) float32, centered; ``mask``: (cap,) validity.  Invalid
    points get all-ones codes so a stable sort keeps them at the end (the
    ``arange(cap) < n`` mask stays true after permutation).

    The code budget is <=128 bits over up to 32 axes
    (:func:`pypardis_tpu.partition.morton_plan` — the round-2 single-
    uint64 budget left most dims unsorted at d=16 and broke tile pruning
    at scale); words are uint32 because TPU JAX runs in 32-bit mode.
    """
    from ..partition import interleave_bit_words, morton_plan

    d, cap = x.shape
    k, bits = morton_plan(d)
    if k == 0:
        return [jnp.where(mask, jnp.uint32(0), jnp.uint32(0xFFFFFFFF))]
    if d > k:
        # Keep the k highest-variance axes (matches the host
        # morton_codes axis choice); row gather by traced indices.
        xm = jnp.where(mask[None, :], x, 0.0)
        n_valid = jnp.maximum(jnp.sum(mask), 1)
        mean = jnp.sum(xm, axis=1, keepdims=True) / n_valid
        var = jnp.sum(
            jnp.where(mask[None, :], (x - mean) ** 2, 0.0), axis=1
        )
        _, axes = jax.lax.top_k(var, k)
        x = jnp.take(x, jnp.sort(axes), axis=0)
    big = jnp.float32(3.0e38)
    lo = jnp.min(jnp.where(mask[None, :], x, big), axis=1, keepdims=True)
    hi = jnp.max(jnp.where(mask[None, :], x, -big), axis=1, keepdims=True)
    span = jnp.maximum(hi - lo, jnp.finfo(jnp.float32).tiny)
    q = jnp.clip(
        ((x - lo) / span * (1 << bits)).astype(jnp.int32), 0, (1 << bits) - 1
    ).astype(jnp.uint32)
    words = interleave_bit_words(
        [q[a] for a in range(k)],
        bits,
        32,
        lambda: jnp.zeros(cap, jnp.uint32),
        jnp.uint32,
    )
    inval = jnp.uint32(0xFFFFFFFF)
    return [jnp.where(mask, w, inval) for w in words]


def _segment_break_layout(xs, mask, perm, eps, block: int, bt: int):
    """Re-lay sorted points so spatially distant runs never share a tile.

    A Morton sort leaves one leak: the tile straddling two far-apart
    clusters inherits a bounding box covering both, and that one loose
    box can fail the gap test against hundreds of tiles (measured ~30x
    more live tile pairs than the data's density warrants at 10M x 16-D).
    Cure: where consecutive sorted points jump farther than 4*eps, start
    a fresh block-aligned segment, so every tile's box stays cluster-
    tight.  The pad budget is static — ``bt`` breaks — and when the data
    offers more jumps than budget, only the ``bt`` largest win (the rest
    stay merged: correctness never depends on breaks, only pruning
    efficiency does).

    Returns ``(ys, mask2, owner)`` with capacity ``cap2 = cap +
    (bt + 1) * block``: scattered coordinates, validity, and each slot's
    original point id (``cap`` for pad slots — callers scatter results
    through ``owner`` into a (cap+1,)-sized dump-row array).
    """
    d, cap = xs.shape
    cap2 = cap + (bt + 1) * block
    d2 = jnp.sum((xs[:, 1:] - xs[:, :-1]) ** 2, axis=0)
    pair_ok = mask[1:] & mask[:-1]
    jump = jnp.concatenate(
        [jnp.zeros(1, xs.dtype), jnp.where(pair_ok, d2, 0.0)]
    )
    # Break where the jump clears 4*eps AND ranks within budget.
    kth = jax.lax.top_k(jump, bt)[0][-1]
    eps2 = jnp.asarray(eps, xs.dtype) ** 2
    brk = jump > jnp.maximum(16.0 * eps2, kth)
    seg = jnp.cumsum(brk.astype(jnp.int32))
    nseg_max = bt + 1
    seg_len = jnp.zeros(nseg_max, jnp.int32).at[seg].add(1)
    padded = -(-seg_len // block) * block
    seg_tgt0 = jnp.cumsum(padded) - padded  # block-aligned segment starts
    seg_src0 = jnp.cumsum(seg_len) - seg_len
    target = seg_tgt0[seg] + jnp.arange(cap, dtype=jnp.int32) - seg_src0[seg]
    ys = jnp.zeros((d, cap2), xs.dtype).at[:, target].set(xs)
    mask2 = jnp.zeros(cap2, bool).at[target].set(mask)
    owner = jnp.full(cap2, cap, jnp.int32).at[target].set(perm)
    return ys, mask2, owner


@functools.partial(
    jax.jit,
    static_argnames=(
        "min_samples", "metric", "block", "precision", "backend", "sort",
        "pair_budget",
    ),
)
def dbscan_device_pipeline(
    points_t,
    eps,
    n,
    min_samples: int,
    metric: str = "euclidean",
    block: int = 1024,
    precision: str = "high",
    backend: str = "auto",
    sort: bool = True,
    pair_budget: int | None = None,
):
    """points_t: (d, cap) float32, centered, zero-padded past ``n``
    (traced).  Returns (2, cap + 1) int32: row 0 = cluster root index
    per point (input order, -1 noise), row 1 = core flags; the extra
    final column is ``[live_pairs_total, budget]`` from the Pallas
    tile-pair extraction (rides in-band so the driver gets results and
    overflow status in ONE device->host transfer; zeros on XLA)."""
    d, cap = points_t.shape
    mask = jnp.arange(cap) < n
    if sort:
        words = _device_morton_words(points_t, mask)
        # jnp.lexsort: the LAST key is primary -> most significant first.
        perm = jnp.lexsort(tuple(words[::-1])).astype(jnp.int32)
        xs = jnp.take(points_t, perm, axis=1)
        # Segment-break padding (worth its pad waste only once the
        # problem spans enough tiles for box mixing to matter).  Budget
        # one break per tile: pad capacity at most doubles (HBM-cheap)
        # and a tighter budget measurably re-leaks — at 10M x 16-D the
        # data has ~3k genuine cluster transitions in Morton order but
        # cap/block/8 allowed only 610 breaks.
        bt = max(64, cap // block)
        if cap >= 16 * block:
            xs, mask_k, owner = _segment_break_layout(
                xs, mask, perm, eps, block, bt
            )
        else:
            mask_k, owner = mask, perm
    else:
        owner = None
        mask_k = mask
        xs = points_t
    roots_s, core_s, pair_stats = dbscan_fixed_size(
        xs,
        eps,
        min_samples,
        mask_k,
        metric=metric,
        block=block,
        precision=precision,
        backend=backend,
        layout="dn",
        pair_budget=pair_budget,
    )
    if owner is not None:
        # Kernel-space root indices -> original point ids, then scatter
        # rows back to input order.  ``owner`` sends pad slots to the
        # dump row ``cap`` of a (cap+1,)-sized scatter target.
        capk = xs.shape[1]
        valid = roots_s >= 0
        tgt = jnp.clip(roots_s, 0, capk - 1)
        roots_g = jnp.where(valid, owner[tgt], -1)
        safe_owner = jnp.clip(owner, 0, cap)
        roots = (
            jnp.zeros(cap + 1, jnp.int32).at[safe_owner].set(roots_g)[:cap]
        )
        core = (
            jnp.zeros(cap + 1, jnp.int32)
            .at[safe_owner]
            .set(core_s.astype(jnp.int32))[:cap]
        )
    else:
        roots, core = roots_s, core_s.astype(jnp.int32)
    return jnp.concatenate(
        [jnp.stack([roots, core]), pair_stats[:, None]], axis=1
    )
