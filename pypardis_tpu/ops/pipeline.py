"""Fused on-device single-shard DBSCAN pipeline.

The round-2 driver did spatial sorting (Morton codes + argsort), padding,
and result decoding on the host, then pulled ``roots`` and ``core`` to the
host as two separate transfers.  Profiling on the real chip showed the
kernel itself is a minority of end-to-end time: host Morton coding +
sorting cost ~80ms at 200k points, and every device->host transfer has a
large fixed latency (remote-tunnel deployments measure ~100ms *per
transfer* regardless of size).

This module keeps the whole hot path on the device, where the reference
keeps it on Spark executors (``/root/reference/dbscan/dbscan.py:12-34``):

* quantize + interleave Morton codes on-device (vector shifts, fused by
  XLA into a handful of passes);
* ``lexsort`` the code's uint32 words (1-4 of them, per
  :func:`pypardis_tpu.partition.morton_plan`) on-device (TPU sort HLO)
  — word-sliced so it runs in JAX's default 32-bit mode;
* gather points into sorted order, staying in the ``(d, cap)``
  transposed layout end to end (XLA:TPU pads the minor axis of
  ``(N, small-d)`` buffers 8x in HBM; point-axis-minor stays dense);
* run the fixed-size DBSCAN kernel (:func:`dbscan_fixed_size`);
* map sorted-space root indices back through the permutation and
  scatter labels/core to input order;
* pack ``(roots, core)`` into ONE ``(2, cap)`` int32 array so the
  driver performs exactly one device->host transfer.

Shapes are static in ``cap = round_up(n, block)`` only; the true count
``n`` rides as a traced scalar, so partitions of nearby sizes share one
compiled program.  The only host work left in the driver is the float64
mean (centering accuracy at GPS-scale magnitudes), the zero-pad to
``cap``, and the final label densification.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

import numpy as np

from ..utils import envreg
from .labels import dbscan_fixed_size
from .precision import PAIR_STATS_WIDTH

# Shapes/configs whose stage-2 programs have already been compiled —
# see dbscan_device_pipeline for why the first call must sync between
# stages on this deployment.  (The stepped path's equivalent discipline
# lives inside labels.dbscan_prepare_pallas.)
_compiled_pipeline_keys: set = set()

# Point-axis chunk for the Morton word interleave (see
# _device_morton_words): bounds XLA's live temps at big caps.
_MORTON_CHUNK = 1 << 22

def _device_morton_words(x, mask):
    """Per-point Morton code as a list of uint32 words (most significant
    first), masked-last.

    ``x``: (d, cap) float32, centered; ``mask``: (cap,) validity.  Invalid
    points get all-ones codes so a stable sort keeps them at the end (the
    ``arange(cap) < n`` mask stays true after permutation).

    The code budget is <=128 bits over up to 32 axes
    (:func:`pypardis_tpu.partition.morton_plan` — the round-2 single-
    uint64 budget left most dims unsorted at d=16 and broke tile pruning
    at scale); words are uint32 because TPU JAX runs in 32-bit mode.
    """
    from ..partition import interleave_bit_words, morton_plan

    d, cap = x.shape
    k, bits = morton_plan(d)
    if k == 0:
        return [jnp.where(mask, jnp.uint32(0), jnp.uint32(0xFFFFFFFF))]
    if d > k:
        # Keep the k highest-variance axes (matches the host
        # morton_codes axis choice); row gather by traced indices.
        xm = jnp.where(mask[None, :], x, 0.0)
        n_valid = jnp.maximum(jnp.sum(mask), 1)
        mean = jnp.sum(xm, axis=1, keepdims=True) / n_valid
        var = jnp.sum(
            jnp.where(mask[None, :], (x - mean) ** 2, 0.0), axis=1
        )
        _, axes = jax.lax.top_k(var, k)
        x = jnp.take(x, jnp.sort(axes), axis=0)
    big = jnp.float32(3.0e38)
    lo = jnp.min(jnp.where(mask[None, :], x, big), axis=1, keepdims=True)
    hi = jnp.max(jnp.where(mask[None, :], x, -big), axis=1, keepdims=True)
    span = jnp.maximum(hi - lo, jnp.finfo(jnp.float32).tiny)
    inval = jnp.uint32(0xFFFFFFFF)

    def words_for(xc, mc):
        n_c = xc.shape[1]
        q = jnp.clip(
            ((xc - lo) / span * (1 << bits)).astype(jnp.int32),
            0, (1 << bits) - 1,
        ).astype(jnp.uint32)
        ws = interleave_bit_words(
            [q[a] for a in range(xc.shape[0])],
            bits,
            32,
            lambda: jnp.zeros(n_c, jnp.uint32),
            jnp.uint32,
        )
        return [jnp.where(mc, w, inval) for w in ws]

    # The 128 shift/or steps of the interleave leave XLA with dozens of
    # point-length u32 temps live at once — measured 18.25GB of HLO
    # temps at 50M x 16-D, an outright compile-OOM on a 16GB chip.
    # Chunking the point axis under lax.scan bounds the temps at
    # O(chunk); the last chunk overlaps its predecessor (clamped start)
    # and rewrites identical values, so no padding copy is needed.
    chunk = _MORTON_CHUNK
    if cap <= chunk:
        return words_for(x, mask)
    nc = -(-cap // chunk)
    n_words = max(1, -(-bits * x.shape[0] // 32))

    def body(carry, c):
        s = jnp.minimum(c * chunk, cap - chunk)
        xc = jax.lax.dynamic_slice(x, (0, s), (x.shape[0], chunk))
        mc = jax.lax.dynamic_slice(mask, (s,), (chunk,))
        ws = words_for(xc, mc)
        # A packing-formula drift would otherwise be silently truncated
        # by zip — corrupting the sort only at > _MORTON_CHUNK inputs.
        assert len(ws) == n_words, (len(ws), n_words)
        carry = [
            jax.lax.dynamic_update_slice(W, w, (s,))
            for W, w in zip(carry, ws)
        ]
        return carry, None

    init = [jnp.zeros(cap, jnp.uint32) for _ in range(n_words)]
    words, _ = jax.lax.scan(body, init, jnp.arange(nc))
    return words


def _segment_break_layout(xs, mask, perm, eps, block: int, bt: int):
    """Re-lay sorted points so spatially distant runs never share a tile.

    A Morton sort leaves one leak: the tile straddling two far-apart
    clusters inherits a bounding box covering both, and that one loose
    box can fail the gap test against hundreds of tiles (measured ~30x
    more live tile pairs than the data's density warrants at 10M x 16-D).
    Cure: where consecutive sorted points jump farther than 4*eps, start
    a fresh block-aligned segment, so every tile's box stays cluster-
    tight.  The pad budget is static — ``bt`` breaks — and when the data
    offers more jumps than budget, only the ``bt`` largest win (the rest
    stay merged: correctness never depends on breaks, only pruning
    efficiency does).

    Returns ``(ys, mask2, owner)`` with capacity ``cap2 = cap +
    (bt + 1) * block``: scattered coordinates, validity, and each slot's
    original point id (``cap`` for pad slots — callers scatter results
    through ``owner`` into a (cap+1,)-sized dump-row array).
    """
    d, cap = xs.shape
    cap2 = cap + (bt + 1) * block
    d2 = jnp.sum((xs[:, 1:] - xs[:, :-1]) ** 2, axis=0)
    pair_ok = mask[1:] & mask[:-1]
    jump = jnp.concatenate(
        [jnp.zeros(1, xs.dtype), jnp.where(pair_ok, d2, 0.0)]
    )
    # Break where the jump clears 4*eps AND ranks within budget.  The
    # rank threshold usually doesn't bind (clusters in the thousands vs
    # a budget of one break per tile), so the top-k only runs when the
    # 4*eps count actually exceeds the budget — lax.cond executes one
    # branch, and top_k at k=cap/block over tens of millions of jumps
    # measured whole seconds at 25M points.
    eps2 = jnp.asarray(eps, xs.dtype) ** 2
    base = 16.0 * eps2
    n_big = jnp.sum(jump > base)
    kth = jax.lax.cond(
        n_big > bt,
        lambda: jax.lax.top_k(jump, bt)[0][-1],
        lambda: jnp.zeros((), xs.dtype),
    )
    brk = jump > jnp.maximum(base, kth)
    seg = jnp.cumsum(brk.astype(jnp.int32))
    nseg_max = bt + 1
    seg_len = jnp.zeros(nseg_max, jnp.int32).at[seg].add(1)
    padded = -(-seg_len // block) * block
    seg_tgt0 = jnp.cumsum(padded) - padded  # block-aligned segment starts
    seg_src0 = jnp.cumsum(seg_len) - seg_len
    target = seg_tgt0[seg] + jnp.arange(cap, dtype=jnp.int32) - seg_src0[seg]
    ys = jnp.zeros((d, cap2), xs.dtype).at[:, target].set(xs)
    mask2 = jnp.zeros(cap2, bool).at[target].set(mask)
    owner = jnp.full(cap2, cap, jnp.int32).at[target].set(perm)
    return ys, mask2, owner


@functools.partial(jax.jit, static_argnames=("cap",))
def device_prep(points, *, cap):
    """Center / transpose / pad an (n, d) device-resident array to the
    (d, cap) float32 pipeline layout, entirely on device.

    The host path computes the centering mean in float64; here it is
    float32 — harmless, because centering by ANY constant vector
    preserves pairwise distances exactly, and an f32 mean is within
    ~1e-7 relative of the true mean, so the centered coordinates stay
    small (the only property the matmul distance expansion needs).
    Device-resident input is the TPU analogue of the reference's
    already-distributed RDD (``/root/reference/dbscan/dbscan.py:104``):
    data produced by an upstream device pipeline never round-trips
    through the host.
    """
    n, d = points.shape
    # Center in the INPUT dtype, cast after: under enable_x64 a float64
    # device array keeps its precision through the subtraction, so
    # GPS-scale magnitudes (~1e6) don't quantize at f32 before the
    # mean comes off (the same guarantee the host path's f64 mean
    # provides).
    mean = jnp.mean(points, axis=0)
    xt = (points - mean).astype(jnp.float32).T
    return jnp.pad(xt, ((0, 0), (0, cap - n)))


@jax.jit
def _layout_words(points_t, n):
    """Layout program 1: per-point Morton words (masked-last)."""
    mask = jnp.arange(points_t.shape[1]) < n
    return _device_morton_words(points_t, mask), mask


@jax.jit
def _layout_perm(words):
    """Layout program 2: the variadic lexsort alone.

    jnp.lexsort: the LAST key is primary -> most significant first.
    """
    return jnp.lexsort(tuple(words[::-1])).astype(jnp.int32)


@functools.partial(jax.jit, donate_argnums=(0,))
def _layout_gather(points_t, perm, n):
    """Layout program 3: gather points into sorted order.

    Invalid points carry all-ones codes and sort last, so the
    ``arange(cap) < n`` mask is permutation-invariant.  ``points_t`` is
    DONATED: the sorted copy reuses its HBM, which is the difference
    between fitting and OOM at e.g. 1M x 512-D (2GB per full-dataset
    copy).  Callers needing the original after a fault re-stage it
    (dbscan.py's rerun path).
    """
    return jnp.take(points_t, perm, axis=1), jnp.arange(points_t.shape[1]) < n


# No donation here: every output is cap2-sized (> cap), so the input
# can never alias — donating would only delete xs and emit warnings.
_segment_break_jit = jax.jit(
    _segment_break_layout, static_argnames=("block", "bt")
)


def _pipeline_layout(points_t, eps, n, block: int, sort: bool,
                     precision: str = "high"):
    """Stage 1: device Morton sort + segment-break padding.

    Returns (xs, mask_k, owner); ``owner`` is None-encoded as the plain
    permutation when no break layout ran (sort=False returns identity).

    NOT one fused jit: each step (Morton words / lexsort / gather /
    segment-break) dispatches as its own small program.  The axon
    client deterministically corrupts its executable session once a
    second *large* fused program is compiled — after which RE-executing
    any later-compiled large program (the Pallas cluster stage) fails
    INVALID_ARGUMENT and the session is dead until process restart
    (reproduced: merely .lower().compile() of the fused layout, never
    executed, was enough; each sub-program alone is benign).  The steps
    chain asynchronously on device and have no fusion opportunities
    across the sort barrier, so the split costs only dispatch latency.
    """
    d, cap = points_t.shape
    if not sort:
        return (
            points_t,
            jnp.arange(cap) < n,
            jnp.arange(cap, dtype=jnp.int32),
        )
    words, mask = _layout_words(points_t, n)
    perm = _layout_perm(words)
    xs, mask = _layout_gather(points_t, perm, n)
    # Segment-break padding (worth its pad waste only once the
    # problem spans enough tiles for box mixing to matter).  Segments
    # align to whole PAIR_GROUP-of-kernel-tiles so the extraction's
    # group boxes never union across segments (a cross-segment union
    # box in high-D covers unrelated clusters and kills group
    # pruning).  Budget one break per alignment unit: pad capacity at
    # most doubles (HBM-cheap), and a tighter budget measurably
    # re-leaks — at 10M x 16-D the data has ~4k genuine cluster
    # transitions in Morton order.
    from .distances import PAIR_GROUP
    from .pallas_kernels import _norm_precision_mode, _pallas_block

    align = PAIR_GROUP * _pallas_block(
        block, cap, d, _norm_precision_mode(precision)
    )
    bt = max(64, cap // align)
    # High-D gate: past ~64 dims Morton boxes barely prune (the code
    # covers only the top-32-variance axes and box volumes concentrate),
    # so the break layout's up-to-2x capacity pad buys nothing and its
    # extra full-dataset copy OOMs HBM at e.g. 1M x 512-D (2GB input,
    # ~14GB of staged copies measured before the fix).
    if cap >= 16 * block and d <= 64:
        return _segment_break_jit(xs, mask, perm, eps, block=align, bt=bt)
    return xs, mask, perm


@functools.partial(jax.jit, static_argnames=("cap",))
def _pipeline_finish_pack(f, border, core, mask_k, pair_stats, owner, *, cap):
    """Stepped-path tail: finish labels + unscatter + pack in ONE jit
    (eager op-by-op dispatch of the 2x-capacity arrays would both cost
    extra passes and widen the unretryable surface)."""
    from .labels import finish_labels

    labels = finish_labels(f, border, core, mask_k)
    return _pipeline_pack(labels, core, pair_stats, owner, cap=cap)


@functools.partial(jax.jit, static_argnames=("cap",))
def _pipeline_pack(roots_s, core_s, pair_stats, owner, *, cap):
    """Unscatter kernel-space results to input order and pack.

    Kernel-space root indices -> original point ids, then scatter rows
    back to input order.  ``owner`` sends pad slots to the dump row
    ``cap`` of a (cap+1,)-sized scatter target.

    Output is ONE (cap + 5,) int32 row — ``(root + 1) | core << 30``
    per point plus the five pair stats — rather than separate root/core
    rows: the device->host result transfer runs at single-digit MB/s on
    degraded tunnel sessions, so halving its bytes is wall-clock that
    matters.  Roots are < cap <= 2^30 (checked at trace time), so bit
    30 is free.  Decode: ``root = (v & 0x3FFFFFFF) - 1``,
    ``core = v >> 30``.
    """
    if cap >= 1 << 30:
        raise ValueError(f"cap {cap} overflows the packed-label encoding")
    capk = roots_s.shape[0]
    valid = roots_s >= 0
    tgt = jnp.clip(roots_s, 0, capk - 1)
    roots_g = jnp.where(valid, owner[tgt], -1)
    packed = (roots_g + 1) | (core_s.astype(jnp.int32) << 30)
    safe_owner = jnp.clip(owner, 0, cap)
    out = jnp.zeros(cap + 1, jnp.int32).at[safe_owner].set(packed)[:cap]
    return jnp.concatenate([out, pair_stats])


def unpack_pipeline_result(packed):
    """Host-side decode of :func:`_pipeline_pack`'s single int32 row.

    Returns ``(roots, core, total, budget, passes, band_pairs,
    rescored_tiles)`` — roots in input order (-1 noise), core as bool,
    plus the live tile-pair stats, the kernel pass count (the
    FLOP-model ``passes`` term), and the mixed-precision band
    telemetry (zeros on non-mixed fits).
    """
    body = packed[:-PAIR_STATS_WIDTH]
    roots = (body & 0x3FFFFFFF) - 1
    core = (body >> 30) > 0
    stats = tuple(int(v) for v in packed[-PAIR_STATS_WIDTH:])
    return (roots, core) + stats


@functools.partial(
    jax.jit,
    static_argnames=(
        "cap", "min_samples", "metric", "block", "precision", "backend",
        "pair_budget", "sketch",
    ),
)
def _pipeline_cluster(
    xs, mask_k, owner, eps, *, cap, min_samples, metric, block, precision,
    backend, pair_budget, sketch=None,
):
    """Stage 2 (fused): fixed-size DBSCAN + unscatter + pack.

    ``sketch`` arrives RESOLVED (a concrete k or None-for-env) from
    :func:`dbscan_device_pipeline` — resolving outside the jit keeps
    the compiled-program key honest about which prefilter it baked in.
    """
    roots_s, core_s, pair_stats = dbscan_fixed_size(
        xs,
        eps,
        min_samples,
        mask_k,
        metric=metric,
        block=block,
        precision=precision,
        backend=backend,
        layout="dn",
        pair_budget=pair_budget,
        sketch=sketch,
    )
    return _pipeline_pack(roots_s, core_s, pair_stats, owner, cap=cap)


# Kernel capacities past this run the host-stepped propagation loop
# (one device call per round, labels.py's stepped section) instead of
# the fused while_loop.  Stepping exists for deployments whose worker
# watchdog kills any single execution running minutes: a fused 25M
# x 2-D fit (kernel capacity ~50M after break padding) reproducibly
# crashed the tunneled worker mid-execution, while the stepped run —
# each round seconds long — completed at 287k pts/sec/chip.  A fused
# 10M x 16-D fit (capacity ~23M) runs 30s and is fine, so the default
# threshold sits between the two observed points; override via
# PYPARDIS_STEP_THRESHOLD=<points> (stepping trades one fused
# execution for per-round dispatch latency, so small fits stay fused).
STEP_THRESHOLD = int(
    envreg.raw("PYPARDIS_STEP_THRESHOLD", 1 << 25)
)
MAX_ROUNDS = 64
# Propagation rounds fused per stepped device call (see
# _cluster_stepped): divides the per-call sync latency by the batch.
ROUND_BATCH = int(
    envreg.raw("PYPARDIS_ROUND_BATCH", 8)
)


def _default_transient(e: BaseException) -> bool:
    from ..utils.retry import is_transient_error

    return is_transient_error(e)


def _transient_retry(stage, fn, retryable=_default_transient):
    """Retry a device call through transient axon-runtime faults.

    The tunneled single-chip deployment sporadically fails a large
    Pallas program's re-execution with INVALID_ARGUMENT / INTERNAL (the
    identical call succeeds moments later), and a crashed worker
    surfaces as UNAVAILABLE until it restarts.  Pure environment
    nondeterminism — the retried call computes the same pure function.
    ``retryable`` classifies which exceptions are worth the 0/10/75s
    ladder; everything else re-raises immediately.  Since the
    fault-tolerance PR this is a thin veneer over the unified
    :class:`pypardis_tpu.utils.retry.Retrier` (same ladder, plus the
    per-site ``retry.<stage>.attempts/giveups`` counters and the
    shared deadline/jitter machinery).
    """
    from ..utils.retry import DEFAULT_WAITS, Retrier

    return Retrier(stage, waits=DEFAULT_WAITS).run(
        fn, retryable=retryable
    )


def _step_overlap_enabled() -> bool:
    """Whether the stepped loop speculatively dispatches batch ``b+1``
    before reading batch ``b``'s convergence flag, overlapping the
    flag's device->host latency (~0.2-2s per batch on tunneled links)
    with the next batch's execution.

    Default OFF on TPU: speculation queues a second execution of the
    round program, which is exactly the queued-re-execution mode that
    poisons tunneled axon workers (see _cluster_tables_1dev_chained's
    probe discipline).  PYPARDIS_STEP_OVERLAP=1 opts in on deployments
    without that failure mode; =0 forces the serial loop anywhere.
    """
    env = envreg.raw("PYPARDIS_STEP_OVERLAP")
    if env is not None:
        return env == "1"
    import jax as _jax

    return _jax.default_backend() != "tpu"


def _cluster_stepped(
    xs, mask_k, owner, eps, *, cap, min_samples, block, precision,
    pair_budget, jobstate=None,
):
    """Stage 2 (host-stepped, Pallas): one device call per round batch.

    Emits a per-stage breakdown (prepare / rounds / border / pack wall
    seconds, batch count and size, speculation stats) as ``stepped.*``
    gauges on the current telemetry recorder — surfaced as the
    ``stepped`` section of ``DBSCAN.report()``, so "bounded by the
    tunnel, not compute" is a measurement, not an attribution.  Each
    consumed batch also fires :func:`pypardis_tpu.obs.heartbeat`
    (``stepped.rounds``): per-round progress + a rounds-remaining ETA
    in the flight file, and opt-in log lines via PYPARDIS_HEARTBEAT —
    a multi-hour 100M-point stepped run is no longer silent between
    dispatch and convergence.
    """
    from ..obs import current as obs_current, heartbeat as obs_heartbeat
    from .labels import (
        dbscan_border_pallas,
        dbscan_prepare_pallas,
        dbscan_rounds_pallas,
    )

    kw = dict(block=block, precision=precision, layout="dn")
    import time as _time

    t0 = _time.perf_counter()

    def run_prepare():
        # The compile/sync discipline for the two prepare programs AND
        # for the round program's first compile lives inside
        # dbscan_prepare_pallas (it syncs its outputs on the first call
        # for a configuration, so the device is idle when the round
        # program's compile starts here).
        return dbscan_prepare_pallas(
            xs, eps, min_samples, mask_k, pair_budget=pair_budget, **kw
        )

    (rows, cols), pair_stats, core, f, band0 = _transient_retry(
        "prepare", run_prepare
    )
    # Resume: the pair list / core flags recompute deterministically
    # above; only the propagation state f needs restoring.  Min-label
    # propagation is monotone toward a unique fixpoint, so continuing
    # from ANY intermediate state of the same tables reaches labels
    # byte-identical to the uninterrupted run.  Snapshots are keyed by
    # the effective pair budget — state written under a budget that
    # later overflowed is never resumed.
    budget_tag = int(pair_budget or 0)
    resumed_batches = 0
    if jobstate is not None:
        saved = jobstate.stepped_restore(budget_tag, int(f.shape[0]))
        if saved is not None:
            f = jnp.asarray(saved[0])
            resumed_batches = int(saved[1])
    # Mixed-precision band telemetry accumulates host-side across the
    # stepped dispatches (each device call reports its own batch; the
    # convergence-flag fetch is already a sync point, so the extra
    # tiny fetch rides the same round trip).  Zeros on other modes.
    band_acc = np.zeros(2, np.int64)
    band_acc += np.asarray(band0, np.int64)
    prepare_s = _time.perf_counter() - t0
    g = None
    converged = False
    # ROUND_BATCH propagation rounds per device call: the per-call
    # convergence-flag sync costs ~0.2-2s of tunnel latency, which at
    # 50M points dominated the whole fit when paid per round.  Each
    # call still runs only seconds (bounded by the batch), far below
    # the worker watchdog that motivates host stepping.

    # Watchdog ceiling: a single degraded round at ~100M capacity can
    # run the better part of a minute, and a full 8-round batch at that
    # size crashed the worker outright (round-4 measurement) — scale
    # the batch down with capacity so one call stays safely short.
    batch_k = max(1, min(ROUND_BATCH, (1 << 27) // max(xs.shape[1], 1)))
    max_batches = max(-(-MAX_ROUNDS // batch_k) - resumed_batches, 1)
    speculate = _step_overlap_enabled()
    batches = 0  # batches whose results were CONSUMED
    dispatched = 0  # includes the wasted post-fixpoint speculation
    t_rounds = _time.perf_counter()

    def dispatch(fi):
        nonlocal dispatched
        dispatched += 1
        return dbscan_rounds_pallas(
            xs, fi, eps, core, mask_k, rows, cols, k_rounds=batch_k, **kw
        )

    if not speculate:
        for _ in range(max_batches):
            def some_rounds(f=f):
                from ..utils import faults

                faults.maybe_fail("stepped.batch")
                out = dispatch(f)
                return out + (bool(out[2]),)  # sync inside retry scope

            f, g, _, band_b, changed = _transient_retry(
                "round", some_rounds
            )
            band_acc += np.asarray(band_b, np.int64)
            batches += 1
            obs_heartbeat("stepped.rounds", batches, max_batches, t_rounds)
            if jobstate is not None and jobstate.due():
                # The (capk,) fetch is the snapshot's cost — cadence-
                # gated (PYPARDIS_CKPT_EVERY_S), never paid otherwise.
                jobstate.stepped_note(
                    np.asarray(f), resumed_batches + batches, budget_tag
                )
            if not changed:  # the last executed round was a fixpoint
                converged = True
                break
    else:
        # Double-buffered rounds: batch b+1 dispatches from batch b's
        # (still in-flight) state BEFORE b's convergence flag is read,
        # so the flag's host round trip overlaps b+1's execution.  A
        # batch run past the fixpoint recomputes the identical state
        # (min-label propagation is idempotent there), so consuming
        # batch b's outputs keeps results byte-identical to the serial
        # loop; the one speculative batch after convergence is wasted
        # work the overlap already paid for.
        pending = None  # (f_out, g_out, changed_handle), unsynced
        while batches < max_batches and not converged:
            last = batches + 1 >= max_batches

            def one_window():
                nonlocal pending
                try:
                    from ..utils import faults

                    faults.maybe_fail("stepped.batch")
                    cur = pending if pending is not None else dispatch(f)
                    spec = None if last else dispatch(cur[0])
                    changed = bool(np.asarray(cur[2]))
                    return cur, spec, changed
                except Exception:
                    # The in-flight window may be poisoned — drop it so
                    # the retry redispatches from the last synced state.
                    pending = None
                    raise

            cur, pending, changed = _transient_retry("round", one_window)
            batches += 1
            obs_heartbeat("stepped.rounds", batches, max_batches, t_rounds)
            f, g = cur[0], cur[1]
            if jobstate is not None and jobstate.due():
                jobstate.stepped_note(
                    np.asarray(f), resumed_batches + batches, budget_tag
                )
            band_acc += np.asarray(cur[3], np.int64)
            if not changed:
                converged = True
    rounds_s = _time.perf_counter() - t_rounds
    from ..utils.log import log_phase

    log_phase(
        "stepped_rounds", batches=batches, batch_size=batch_k,
        converged=converged, speculate=speculate,
        dispatched=dispatched, seconds=round(rounds_s, 2),
    )
    border_s = 0.0
    if not converged:
        t_b = _time.perf_counter()
        g, band_b = _transient_retry(
            "border",
            lambda: dbscan_border_pallas(
                xs, f, eps, core, mask_k, rows, cols, **kw
            ),
        )
        band_acc += np.asarray(band_b, np.int64)
        border_s = _time.perf_counter() - t_b
    # Kernel passes for the FLOP model: one counts pass, batch_k minlab
    # rounds per DISPATCHED batch (the speculative post-fixpoint batch
    # executed too; the in-batch convergence round is not observable
    # from the host — this is a tight upper bound), plus the explicit
    # border pass on a non-converged exit.
    passes = 1 + dispatched * batch_k + (0 if converged else 1)
    pair_stats = jnp.concatenate(
        [
            pair_stats[:2], jnp.asarray([passes], jnp.int32),
            jnp.asarray(
                np.minimum(band_acc, np.iinfo(np.int32).max), jnp.int32
            ),
        ]
    )
    t_p = _time.perf_counter()
    out = _transient_retry(
        "pack",
        lambda: np.array(_pipeline_finish_pack(
            f, g, core, mask_k, pair_stats, owner, cap=cap
        )),
    )
    m = obs_current().metrics
    m.set("stepped.prepare_s", round(prepare_s, 6))
    m.set("stepped.rounds_s", round(rounds_s, 6))
    m.set("stepped.border_s", round(border_s, 6))
    m.set("stepped.pack_s", round(_time.perf_counter() - t_p, 6))
    m.set("stepped.batches", batches)
    m.set("stepped.batch_size", batch_k)
    m.set("stepped.dispatched_batches", dispatched)
    m.set("stepped.speculate", speculate)
    m.set("stepped.converged", converged)
    return out


def dbscan_device_pipeline(
    points_t,
    eps,
    n,
    min_samples: int,
    metric: str = "euclidean",
    block: int = 1024,
    precision: str = "high",
    backend: str = "auto",
    sort: bool = True,
    pair_budget: int | None = None,
    layout_key=None,
    jobstate=None,
    sketch: int | str | None = None,
):
    """points_t: (d, cap) float32, centered, zero-padded past ``n``
    (traced) — or a ZERO-ARG CALLABLE producing it, evaluated only
    when the layout actually runs (see ``layout_key``).  Returns a
    host (cap + 5,) int32 array: per point the packed ``(root + 1) |
    core << 30`` value (input order; decode via
    :func:`unpack_pipeline_result`), then ``[live_pairs_total,
    budget, passes, band_pairs, rescored_tiles]`` (rides in-band so
    the driver gets results and overflow status in ONE device->host
    transfer; budget zeros on XLA, band columns zero off
    ``precision="mixed"``).  Materialized on host here so the bulk
    transfer doubles as the execution-fault sync inside the retry
    scope.

    ``layout_key``: content key under which the layout products —
    the sorted/segment-broken ``(xs, mask, owner)`` device arrays,
    which depend on the data, block, precision, and eps but NOT on
    min_samples/metric/pair_budget — are cached through the staging
    economy (:mod:`pypardis_tpu.parallel.staging`, route
    ``pipeline_layout``).  A warm repeat fit then skips the host
    staging fill, the host->device transfer, AND the device Morton
    sort; nothing downstream donates these arrays, so reuse is safe.
    None (e.g. device-resident input, or arrays too large to retain —
    the driver gates) disables caching.

    Two separately-jitted stages rather than one fused program: the
    fused compile at ~50M-point capacities crashed the axon compile
    helper outright, and each stage alone compiles in ~20s.  The
    stages chain asynchronously on device, so the split costs no host
    round-trip — except the very first call for a given shape, which
    syncs stage 1 before tracing stage 2: compiling a large program
    while the device is mid-execution also crashed the worker
    (reproduced repeatedly at 25M points; every compile-idle staged
    run succeeded).
    """
    from ..obs import event as obs_event, span as obs_span
    from .labels import resolve_backend

    cached = None
    if layout_key is not None:
        from ..parallel import staging as _staging

        cached = _staging.device_get("pipeline_layout", layout_key)
    if cached is not None:
        (xs, mask_k, owner), aux = cached
        cap = int(aux["cap"])
    else:
        if callable(points_t):
            points_t = points_t()
        cap = points_t.shape[1]
        key = (
            points_t.shape, points_t.dtype, min_samples, metric, block,
            precision, backend, sort, pair_budget,
        )

        def run_layout():
            out = _pipeline_layout(
                points_t, eps, n, block=block, sort=sort,
                precision=precision
            )
            if key not in _compiled_pipeline_keys:
                obs_event("compile", stage="pipeline")
                # First time for this shape: let stage 1 finish on
                # device before stage 2's compile starts
                # (block_until_ready can return early on tunneled
                # deployments; a 1-element transfer is a reliable
                # barrier).
                np.asarray(out[0][:1, :1])
                _compiled_pipeline_keys.add(key)
            return out

        with obs_span("pipeline.layout", sort=bool(sort)):
            xs, mask_k, owner = _transient_retry("layout", run_layout)
        if layout_key is not None:
            _staging.device_put_cached(
                "pipeline_layout", layout_key, (xs, mask_k, owner),
                aux={"cap": cap},
            )
    capk = xs.shape[1]
    # The kernel grid's tile count (post segment-break capacity / the
    # effective tile): the denominator of report()'s live_pair_fraction
    # — the driver cannot see capk (the packed result is cap-sized), so
    # it rides as a gauge on the fit's registry.
    from ..obs import current as obs_current
    from .pallas_kernels import _norm_precision_mode, effective_tile

    _eff = effective_tile(
        block, capk, xs.shape[0], _norm_precision_mode(precision)
    ) or min(block, capk)
    obs_current().metrics.set(
        "pipeline.kernel_tiles", max(1, capk // _eff)
    )
    # Resolve the sketch spec HERE, outside every jit: the knob becomes
    # a static argument of the cluster program, so the compiled-program
    # cache key says exactly which prefilter it carries (the env
    # default resolves once per call, not once per trace).  The
    # host-stepped route below ignores it — it pins sketch=0 (it
    # exists for 10M+-point LOW-d workloads where the prefilter has
    # nothing to amortize; see ops.labels._prepare_counts).
    from .sketch import check_sketch_spec, resolve_sketch, sketch_dims

    if sketch is None:
        sk = sketch_dims(xs.shape[0], metric)
    else:
        sk = resolve_sketch(check_sketch_spec(sketch), xs.shape[0], metric)
    stepped = (
        capk >= STEP_THRESHOLD
        and resolve_backend(
            backend, metric, capk, block, xs.shape[0], precision
        ) == "pallas"
    )
    if stepped:
        with obs_span("pipeline.cluster", mode="stepped") as sp:
            out = _cluster_stepped(
                xs, mask_k, owner, eps,
                cap=cap, min_samples=min_samples, block=block,
                precision=precision, pair_budget=pair_budget,
                jobstate=jobstate,
            )
            sp.set(capacity=int(xs.shape[1]))
            return out

    def run_cluster():
        from ..utils import faults

        faults.maybe_fail("pipeline.cluster")
        out = _pipeline_cluster(
            xs, mask_k, owner, eps,
            cap=cap, min_samples=min_samples, metric=metric, block=block,
            precision=precision, backend=backend, pair_budget=pair_budget,
            sketch=sk,
        )
        # The bulk transfer IS the sync: execution faults surface here,
        # inside the retry scope, and the steady-state fit pays exactly
        # one device->host round trip (a separate 1-element probe fetch
        # costs a full tunnel round trip — ~0.2s at best, seconds under
        # load — per fit).
        return np.array(out)

    with obs_span("pipeline.cluster", mode="fused"):
        return _transient_retry("cluster", run_cluster)


# ---------------------------------------------------------------------------
# Amortized-sweep pipeline: ONE layout + ONE pair-emission pass at
# eps_max, then one packed relabel program per (eps, min_samples)
# config over the cached kernel-space graph.  The graph lives in
# KERNEL-slot space so each config's roots map back through the same
# ``owner`` permutation the fused fit uses (_pipeline_pack) — labels
# byte-identical to an independent dbscan_device_pipeline run at that
# config, Morton-first cluster numbering included.
# ---------------------------------------------------------------------------


def sweep_graph_pipeline(
    points_t,
    eps,
    n,
    metric: str = "euclidean",
    block: int = 1024,
    precision: str = "high",
    backend: str = "auto",
    sort: bool = True,
    layout_key=None,
    edge_budget: int | None = None,
    pair_budget: int | None = None,
):
    """Layout + neighbor-pair graph extraction for a parameter sweep.

    ``points_t``/``n``/``sort``/``layout_key`` as in
    :func:`dbscan_device_pipeline` (the layout products are shared
    through the same ``pipeline_layout`` staging route, so a sweep
    after a fit at the same eps ceiling re-stages nothing); ``eps`` is
    the sweep's eps_max.  Returns ``((gi, gj, dval), mask_k, owner,
    cap, stats)`` with the graph as device-resident kernel-space
    slabs and ``stats`` the host (4,) int32 ``[edge_total,
    edge_budget, tile_total, tile_budget]`` — the caller owns the
    exact-total retry ladder (either overflow invalidates the graph).
    """
    from ..obs import span as obs_span
    from .distances import neighbor_pair_graph
    from .labels import resolve_backend
    from .pallas_kernels import graph_emission_tile

    cached = None
    if layout_key is not None:
        from ..parallel import staging as _staging

        cached = _staging.device_get("pipeline_layout", layout_key)
    if cached is not None:
        (xs, mask_k, owner), aux = cached
        cap = int(aux["cap"])
    else:
        if callable(points_t):
            points_t = points_t()
        cap = points_t.shape[1]

        def run_layout():
            return _pipeline_layout(
                points_t, eps, n, block=block, sort=sort,
                precision=precision,
            )

        with obs_span("sweep.layout", sort=bool(sort)):
            xs, mask_k, owner = _transient_retry("layout", run_layout)
        if layout_key is not None:
            from ..parallel import staging as _staging

            _staging.device_put_cached(
                "pipeline_layout", layout_key, (xs, mask_k, owner),
                aux={"cap": cap},
            )
    capk = xs.shape[1]
    d = xs.shape[0]
    # Emission on the kernels' own grid: the Pallas effective tile on
    # TPU (keeps tile-pair budgets/hints aligned with the Mosaic
    # kernels), the XLA kernels' block elsewhere.  Tile choice never
    # changes which pairs survive — only pruning granularity.
    kind = resolve_backend(backend, metric, capk, block, d, precision)
    tile = (
        graph_emission_tile(block, capk, d, precision)
        if kind == "pallas"
        else min(block, capk)
    )

    def run_extract():
        from .distances import sweep_emission_route

        if sweep_emission_route() == "host":
            # Host-compaction emission (auto on CPU; see distances
            # .neighbor_pair_graph_host): same device arithmetic,
            # numpy stream compaction — the CPU XLA scatter behind the
            # device route is single-threaded and dominated the sweep.
            from .distances import neighbor_pair_graph_host

            gi, gj, dval, st = neighbor_pair_graph_host(
                xs, mask_k, eps, metric=metric, block=tile,
                precision=precision, layout="dn",
                pair_budget=pair_budget,
            )
            return (
                (jnp.asarray(gi), jnp.asarray(gj), jnp.asarray(dval)),
                np.asarray(st),
            )
        gi, gj, dval, st = neighbor_pair_graph(
            xs, mask_k, eps, metric=metric, block=tile,
            precision=precision, layout="dn", budget=edge_budget,
            pair_budget=pair_budget,
        )
        # The tiny stats fetch is the execution sync inside the retry
        # scope; the bulk graph stays device-resident for the configs.
        return (gi, gj, dval), np.asarray(st)

    with obs_span("sweep.extract"):
        graph, stats = _transient_retry("sweep_extract", run_extract)
    return graph, mask_k, owner, cap, stats


@functools.partial(
    jax.jit, static_argnames=("cap", "metric", "max_rounds")
)
def sweep_config_pack(
    gi, gj, dval, mask_k, owner, eps, min_samples, edge_stats, *,
    cap, metric: str = "euclidean", max_rounds: int = 64,
):
    """One sweep config's relabel over the cached kernel-space graph,
    packed in the pipeline's single-transfer wire format (decode via
    :func:`unpack_pipeline_result`).  ``eps``/``min_samples`` are
    traced, so every config of a sweep shares one compiled program."""
    from .labels import graph_dbscan

    labels, core, passes = graph_dbscan(
        gi, gj, dval, mask_k, eps, min_samples, metric=metric,
        max_rounds=max_rounds,
    )
    pair_stats = jnp.concatenate(
        [edge_stats[:2], passes[None], jnp.zeros(2, jnp.int32)]
    )
    return _pipeline_pack(labels, core, pair_stats, owner, cap=cap)
