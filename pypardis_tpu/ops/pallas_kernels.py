"""Pallas TPU kernels for the eps-neighborhood hot loop.

The XLA path in :mod:`pypardis_tpu.ops.distances` expresses the tiled
pairwise interaction as ``lax.map`` over row tiles with a ``lax.scan`` +
``lax.cond`` over column tiles.  These kernels implement the same two
primitives — eps-neighbor counting and min-label-over-neighbors — as
hand-scheduled Mosaic programs:

* one grid program per **output tile**; its points and bounding box
  arrive via grid-sliced BlockSpecs;
* source tiles stay in **HBM** and are DMA'd into VMEM scratch only when
  their bounding box lies within eps of the output tile's — pruned tiles
  cost neither FLOPs nor HBM bandwidth.  Pruning is two-level: one gap
  test per GROUP of tiles against coarse group boxes resident in VMEM,
  then per-tile gap tests against the group's per-tile boxes, which are
  themselves DMA'd from HBM only when the group survives — so VMEM
  holds O(ng) bounds, independent of the point count;
* the distance tile is one MXU contraction of **norm-augmented
  operands** ``[-2(y-c); 1; |y-c|^2]^T [x-c; |x-c|^2; 1] = |x-y|^2``
  consumed immediately by the compare-and-reduce in registers, so the
  N x N interaction never touches HBM.

Layout (the round-1 design stored coordinates ``(N, d)``-major, which
XLA:TPU pads 8x in HBM for small d — the 10M-point memory wall):

* coordinates travel **transposed** as ``(nt, d, block)`` — the big
  point axis is minor, so the HBM image is dense for any d, and no lane
  padding of coordinates is needed at all;
* per-point scalars (labels) and outputs travel as ``(nt, 1, block)``
  rows — dense, and already in the ``(1, block)`` broadcast layout the
  kernel consumes.  Labels ride as int32 (sentinel INT32_MAX), so any
  shard size up to HBM capacity is supported (the round-1 float32
  label encoding capped shards at 2^24 points);
* one masked coordinate array serves as both row and column operand of
  both kernels; the min-label kernel restricts *sources* via the label
  sentinel (a non-source's INT32_MAX label never wins a min), so no
  second N-sized coordinate copy exists.

Numerics:

* every tile pair is computed **recentred on the output tile's box
  center**, so operand magnitudes are tile-local and the classic
  ``|x|^2+|y|^2-2xy`` cancellation does not amplify absolute coordinate
  scale (the dataset-level recentring in the drivers bounds it further);
* ``precision="high"`` (default) runs a manual **3-pass bf16 split
  matmul** (hi/lo decomposition: ``x = hi(x) + lo(x)``, dropping only
  the lo*lo term).  The dropped term is ~2^-18 relative to *operand
  magnitude* — i.e. fp32-class only when tiles are spatially tight
  (the Morton-sorted driver layout); on loose tiles the absolute d2
  error can reach coordinate scale x 2^-18 and flip shell-adjacent
  pairs (bounded in tests/test_tpu_smoke.py; cluster-level output is
  ARI-stable).  Mosaic has no native bf16_3x, which in round 1
  silently upgraded "high" to HIGHEST and cost 2x.
* ``precision="highest"`` uses native HIGHEST; ``"default"`` a single
  bf16 pass (fast, ~2^-8-relative — opt-in only).

Masking convention: invalid points get coordinates ``BIG`` (squared
distance overflows past any eps) before entering the kernel; no boolean
mask ever does.

Only the Euclidean metric goes through Pallas (cityblock has no matmul
decomposition and stays on the XLA path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_INT_INF = jnp.iinfo(jnp.int32).max
# Masked-out points get these coordinates: BIG^2 = 4e38 overflows fp32
# (max ~3.4e38) to inf, so a valid-vs-masked pair has d2 = inf and a
# masked-vs-masked pair d2 = inf - inf = NaN — either way the <= eps^2
# adjacency test is False.
BIG = jnp.float32(2e19)

GROUP = 16  # source tiles covered by one group-level gap test

_PRECISION_MODES = ("default", "high", "highest")


def _norm_precision_mode(precision) -> str:
    """Normalize to one of the kernel's static precision modes."""
    if isinstance(precision, jax.lax.Precision):
        return {
            jax.lax.Precision.DEFAULT: "default",
            jax.lax.Precision.HIGH: "high",
            jax.lax.Precision.HIGHEST: "highest",
        }[precision]
    p = str(precision).lower()
    if p not in _PRECISION_MODES:
        raise ValueError(
            f"precision must be one of {_PRECISION_MODES}, got {precision!r}"
        )
    return p


def _dot_t(a, b, mode):
    """(K, m) x (K, n) → (m, n): contraction over the leading axis.

    ``mode="high"`` is the manual bf16_3x: split each operand into a
    bf16 head plus a bf16-rounded residual and accumulate the three
    significant cross terms with single-pass (DEFAULT) MXU dots.  The
    dropped lo*lo term is O(2^-18) relative — fp32-class accuracy.
    """
    dims = (((0,), (0,)), ((), ()))

    def dot(x, y, prec):
        return jax.lax.dot_general(
            x, y, dims, precision=prec, preferred_element_type=jnp.float32
        )

    if mode == "highest":
        return dot(a, b, jax.lax.Precision.HIGHEST)
    if mode == "default":
        return dot(a, b, jax.lax.Precision.DEFAULT)
    ah = a.astype(jnp.bfloat16).astype(jnp.float32)
    al = a - ah
    bh = b.astype(jnp.bfloat16).astype(jnp.float32)
    bl = b - bh
    d = jax.lax.Precision.DEFAULT
    return dot(ah, bh, d) + (dot(ah, bl, d) + dot(al, bh, d))


def _aug_out(x, c):
    """Output-side augmented operand: [x-c; |x-c|^2; 1] → (d+2, bo)."""
    xc = x - c
    xsq = jnp.sum(xc * xc, axis=0, keepdims=True)
    return jnp.concatenate([xc, xsq, jnp.ones_like(xsq)], axis=0)


def _aug_src(y, c):
    """Source-side augmented operand: [-2(y-c); 1; |y-c|^2] → (d+2, bs)."""
    yc = y - c
    ysq = jnp.sum(yc * yc, axis=0, keepdims=True)
    return jnp.concatenate([-2.0 * yc, jnp.ones_like(ysq), ysq], axis=0)


def _gap2(lo_a, hi_a, lo_b, hi_b):
    """Squared gap between two boxes given as (1, d) bound rows."""
    gap = jnp.maximum(jnp.maximum(lo_b - hi_a, lo_a - hi_b), 0.0)
    return jnp.sum(gap * gap)


def _count_kernel(
    eps2_ref, glo_ref, ghi_ref, rlo_ref, rhi_ref, c_ref, tblo_ref, tbhi_ref,
    x_ref, yhbm_ref, out_ref,
    ybuf, blo, bhi, ysem, lsem, hsem,
    *, mode, group,
):
    eps2 = eps2_ref[0]
    ng = glo_ref.shape[0]
    # Row-tile bounds arrive as a (1, 1, dp) grid-sliced block (the
    # leading singleton keeps the last two block dims equal to the array
    # dims, and dp is the lane-padded d — both Mosaic layout
    # requirements); drop it to the (1, dp) row shape.  Padded lanes are
    # zero in every box, contributing zero gap.
    rlo = rlo_ref[0]
    rhi = rhi_ref[0]
    # Recentre every tile pair on the output tile's box center: operand
    # magnitudes become tile-local, keeping the matmul expansion's
    # cancellation error at eps scale.  Empty tiles carry inverted
    # (+BIG, -BIG) bounds whose midpoint is 0 — recentring is a no-op.
    # The (d, 1) center rides as its own unpadded input: the bounds are
    # lane-padded for DMA tiling, so deriving it in-kernel would need a
    # lane slice.
    c = c_ref[0]
    out_aug = _aug_out(x_ref[0], c)
    out_ref[0] = jnp.zeros_like(out_ref[0])

    def group_body(g, _):
        ggap2 = _gap2(
            glo_ref[pl.ds(g, 1), :], ghi_ref[pl.ds(g, 1), :], rlo, rhi
        )

        @pl.when(ggap2 <= eps2)
        def _():
            # The group survived: fetch its per-tile boxes from HBM.
            ldma = pltpu.make_async_copy(tblo_ref.at[g], blo, lsem)
            hdma = pltpu.make_async_copy(tbhi_ref.at[g], bhi, hsem)
            ldma.start()
            hdma.start()
            ldma.wait()
            hdma.wait()

            def tile_body(jj, _):
                gap2 = _gap2(
                    blo[pl.ds(jj, 1), :], bhi[pl.ds(jj, 1), :], rlo, rhi
                )

                @pl.when(gap2 <= eps2)
                def _():
                    ydma = pltpu.make_async_copy(
                        yhbm_ref.at[g * group + jj], ybuf, ysem
                    )
                    ydma.start()
                    ydma.wait()
                    d2 = _dot_t(_aug_src(ybuf[:], c), out_aug, mode)
                    adj = (d2 <= eps2).astype(jnp.int32)
                    out_ref[0] += jnp.sum(adj, axis=0, keepdims=True)

                return 0

            jax.lax.fori_loop(0, group, tile_body, 0)

        return 0

    jax.lax.fori_loop(0, ng, group_body, 0)


def _minlab_kernel(
    eps2_ref, glo_ref, ghi_ref, rlo_ref, rhi_ref, c_ref, tblo_ref, tbhi_ref,
    x_ref, yhbm_ref, ylab_ref, out_ref,
    ybuf, lbuf, blo, bhi, ysem, labsem, lsem, hsem,
    *, mode, group,
):
    eps2 = eps2_ref[0]
    ng = glo_ref.shape[0]
    rlo = rlo_ref[0]
    rhi = rhi_ref[0]
    c = c_ref[0]
    out_aug = _aug_out(x_ref[0], c)
    out_ref[0] = jnp.full_like(out_ref[0], _INT_INF)

    def group_body(g, _):
        ggap2 = _gap2(
            glo_ref[pl.ds(g, 1), :], ghi_ref[pl.ds(g, 1), :], rlo, rhi
        )

        @pl.when(ggap2 <= eps2)
        def _():
            ldma = pltpu.make_async_copy(tblo_ref.at[g], blo, lsem)
            hdma = pltpu.make_async_copy(tbhi_ref.at[g], bhi, hsem)
            ldma.start()
            hdma.start()
            ldma.wait()
            hdma.wait()

            def tile_body(jj, _):
                gap2 = _gap2(
                    blo[pl.ds(jj, 1), :], bhi[pl.ds(jj, 1), :], rlo, rhi
                )

                @pl.when(gap2 <= eps2)
                def _():
                    j = g * group + jj
                    ydma = pltpu.make_async_copy(
                        yhbm_ref.at[j], ybuf, ysem
                    )
                    labdma = pltpu.make_async_copy(
                        ylab_ref.at[j], lbuf, labsem
                    )
                    ydma.start()
                    labdma.start()
                    ydma.wait()
                    labdma.wait()
                    d2 = _dot_t(_aug_src(ybuf[:], c), out_aug, mode)
                    lab_col = jnp.transpose(lbuf[:], (1, 0))
                    cand = jnp.where(d2 <= eps2, lab_col, _INT_INF)
                    out_ref[0] = jnp.minimum(
                        out_ref[0], jnp.min(cand, axis=0, keepdims=True)
                    )

                return 0

            jax.lax.fori_loop(0, group, tile_body, 0)

        return 0

    jax.lax.fori_loop(0, ng, group_body, 0)


def _tiles_t(points, block, layout):
    """Transposed tiles (nt, d, block) from (N, d) or (d, N) input."""
    if layout == "nd":
        n, d = points.shape
        nt = n // block
        return points.astype(jnp.float32).reshape(nt, block, d).transpose(
            0, 2, 1
        )
    d, n = points.shape
    nt = n // block
    return points.astype(jnp.float32).reshape(d, nt, block).transpose(1, 0, 2)


def _masked_bounds(tiles, mask_t):
    """(nt, d) lower/upper bounds over masked points; empty tiles get
    inverted (+BIG, -BIG) boxes so they always prune."""
    lo = jnp.min(jnp.where(mask_t, tiles, BIG), axis=2)
    hi = jnp.max(jnp.where(mask_t, tiles, -BIG), axis=2)
    return lo, hi


def _lane_pad(a, dp):
    """Zero-pad the last (lane) dim of (nt, d) bounds to dp.

    HBM DMA slices must be 128-aligned on the lane dim (Mosaic memref
    tiling); a zero lower *and* upper bound in the padded lanes makes
    every box-gap contribution there exactly zero, so padding never
    changes a pruning decision.
    """
    nt, d = a.shape
    if dp == d:
        return a
    return jnp.concatenate([a, jnp.zeros((nt, dp - d), a.dtype)], axis=1)


def _grouped_bounds(lo, hi):
    """Pack (nt, dp) per-tile bounds for the two-level pruning scheme.

    Returns (tblo, tbhi, glo, ghi): per-tile boxes regrouped as
    (ng, GROUP, dp) HBM-resident arrays (DMA'd per surviving group) and
    coarse per-group boxes (ng, dp) kept in VMEM.  Padded tiles carry
    inverted boxes and always prune.
    """
    nt, d = lo.shape
    ng = -(-nt // GROUP)
    pad = ng * GROUP - nt
    lo_p = jnp.concatenate([lo, jnp.full((pad, d), BIG)], axis=0)
    hi_p = jnp.concatenate([hi, jnp.full((pad, d), -BIG)], axis=0)
    tblo = lo_p.reshape(ng, GROUP, d)
    tbhi = hi_p.reshape(ng, GROUP, d)
    glo = jnp.min(tblo, axis=1)
    ghi = jnp.max(tbhi, axis=1)
    return tblo, tbhi, glo, ghi


def _pallas_block(block: int, n: int, d: int, mode: str = "high") -> int:
    """Largest tile that keeps the fp32 distance tile plus operand
    blocks comfortably inside VMEM and divides n.

    The default bf16_3x mode materializes more than the plain path: the
    hi/lo operand splits (four extra (d+2, b) blocks) and up to three
    (b, b) dot results before the adds fuse — budget for them so a
    Mosaic VMEM overflow can't appear only on hardware.  The 32MB cap
    (v5e/v4 VMEM is 128MB) admits b=1024 in every mode — measured ~2x
    over b=512 at 5M points: half the per-tile DMA waits and a better
    MXU aspect — while leaving headroom for Mosaic's own double
    buffering of the grid blocks.  b=2048 would put the bf16_3x
    worst case past 80MB; not worth the risk for <10% fewer DMAs.
    """
    b = min(block, n)
    if mode == "high":
        tile_words, opnd_words = 4, 8
    else:
        tile_words, opnd_words = 2, 4
    while b > 128 and (
        tile_words * b * b * 4 + opnd_words * b * (d + 2) * 4
        > 32 * 1024 * 1024
        or n % b != 0
    ):
        b //= 2
    return b


def _shape_nd(points, layout):
    if layout == "nd":
        return points.shape
    d, n = points.shape
    return n, d


@functools.partial(
    jax.jit, static_argnames=("block", "precision", "interpret", "layout")
)
def neighbor_counts_pallas(
    points: jnp.ndarray,
    eps,
    mask: jnp.ndarray,
    block: int = 1024,
    precision: str = "high",
    interpret: bool = False,
    layout: str = "nd",
) -> jnp.ndarray:
    """Pallas analogue of :func:`pypardis_tpu.ops.distances.neighbor_counts`
    (Euclidean only)."""
    n, d = _shape_nd(points, layout)
    mode = _norm_precision_mode(precision)
    block = _pallas_block(block, n, d, mode)
    assert n % block == 0, (n, block)
    nt = n // block
    dp = -(-d // 128) * 128
    tiles = _tiles_t(points, block, layout)
    mask_t = mask.reshape(nt, 1, block)
    ycols = jnp.where(mask_t, tiles, BIG)
    lo, hi = _masked_bounds(tiles, mask_t)
    centers = (0.5 * (lo + hi))[:, :, None]
    lo_p = _lane_pad(lo, dp)
    hi_p = _lane_pad(hi, dp)
    tblo, tbhi, glo, ghi = _grouped_bounds(lo_p, hi_p)
    ng = glo.shape[0]
    eps2 = jnp.asarray(eps, jnp.float32).reshape(1) ** 2

    counts = pl.pallas_call(
        functools.partial(_count_kernel, mode=mode, group=GROUP),
        grid=(nt,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((ng, dp), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((ng, dp), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (1, 1, dp), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, dp), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, d, 1), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pltpu.HBM),
            pl.BlockSpec(memory_space=pltpu.HBM),
            pl.BlockSpec(
                (1, d, block), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pltpu.HBM),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((nt, 1, block), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((d, block), jnp.float32),
            pltpu.VMEM((GROUP, dp), jnp.float32),
            pltpu.VMEM((GROUP, dp), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )(
        eps2, glo, ghi,
        lo_p.reshape(nt, 1, dp), hi_p.reshape(nt, 1, dp),
        centers, tblo, tbhi, ycols, ycols,
    )
    return jnp.where(mask, counts.reshape(-1), 0)


@functools.partial(
    jax.jit, static_argnames=("block", "precision", "interpret", "layout")
)
def min_neighbor_label_pallas(
    points: jnp.ndarray,
    labels: jnp.ndarray,
    eps,
    src_mask: jnp.ndarray,
    block: int = 1024,
    precision: str = "high",
    interpret: bool = False,
    row_mask: jnp.ndarray | None = None,
    layout: str = "nd",
) -> jnp.ndarray:
    """Pallas analogue of
    :func:`pypardis_tpu.ops.distances.min_neighbor_label` (Euclidean).

    Labels travel as int32 with sentinel INT32_MAX.  The coordinate
    operand is masked by ``row_mask`` (validity); source restriction to
    ``src_mask`` rides on the label sentinel — a non-source's INT32_MAX
    never wins a min — so rows and columns share one array.  Rows
    outside ``row_mask`` may return INT32_MAX; callers mask them.  The
    default (``None``) covers ALL rows.
    """
    n, d = _shape_nd(points, layout)
    mode = _norm_precision_mode(precision)
    block = _pallas_block(block, n, d, mode)
    assert n % block == 0, (n, block)
    nt = n // block
    dp = -(-d // 128) * 128
    tiles = _tiles_t(points, block, layout)
    if row_mask is None:
        ycols = tiles
        rlo = jnp.min(tiles, axis=2)
        rhi = jnp.max(tiles, axis=2)
    else:
        # The same array is row and source operand; keep coordinates
        # real wherever EITHER mask holds so a source outside row_mask
        # is never silently lost (its label sentinel alone governs
        # source participation).
        rm = row_mask.reshape(nt, 1, block)
        ycols = jnp.where(rm | src_mask.reshape(nt, 1, block), tiles, BIG)
        rlo, rhi = _masked_bounds(tiles, rm)
    centers = (0.5 * (rlo + rhi))[:, :, None]
    rlo_p = _lane_pad(rlo, dp)
    rhi_p = _lane_pad(rhi, dp)
    # Source-side pruning boxes cover src points only (tighter than the
    # row-validity boxes; correctness only needs them to *cover* srcs).
    slo, shi = _masked_bounds(tiles, src_mask.reshape(nt, 1, block))
    tblo, tbhi, glo, ghi = _grouped_bounds(
        _lane_pad(slo, dp), _lane_pad(shi, dp)
    )
    ng = glo.shape[0]
    labi = jnp.where(src_mask, labels, _INT_INF).reshape(nt, 1, block)
    eps2 = jnp.asarray(eps, jnp.float32).reshape(1) ** 2

    best = pl.pallas_call(
        functools.partial(_minlab_kernel, mode=mode, group=GROUP),
        grid=(nt,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((ng, dp), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((ng, dp), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (1, 1, dp), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, dp), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, d, 1), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pltpu.HBM),
            pl.BlockSpec(memory_space=pltpu.HBM),
            pl.BlockSpec(
                (1, d, block), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pltpu.HBM),
            pl.BlockSpec(memory_space=pltpu.HBM),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((nt, 1, block), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((d, block), jnp.float32),
            pltpu.VMEM((1, block), jnp.int32),
            pltpu.VMEM((GROUP, dp), jnp.float32),
            pltpu.VMEM((GROUP, dp), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )(
        eps2, glo, ghi,
        rlo_p.reshape(nt, 1, dp), rhi_p.reshape(nt, 1, dp),
        centers, tblo, tbhi, ycols, ycols, labi,
    )
    return best.reshape(-1)
