"""Pallas TPU kernels for the eps-neighborhood hot loop.

The XLA path in :mod:`pypardis_tpu.ops.distances` expresses the tiled
pairwise interaction as ``lax.map`` over row tiles with a ``lax.scan`` +
``lax.cond`` over column tiles.  These kernels implement the same two
primitives — eps-neighbor counting and min-label-over-neighbors — as
hand-scheduled Mosaic programs:

* one grid program per **row tile**; the row block and all tile bounding
  boxes live in VMEM;
* column tiles stay in **HBM** and are DMA'd into VMEM scratch buffers
  only when their bounding box lies within eps of the row tile's — the
  pruned tiles cost neither FLOPs nor HBM bandwidth;
* the distance tile ``|x|^2 + |y|^2 - 2 x @ y.T`` is computed on the MXU
  and consumed immediately by the compare-and-reduce in registers, so the
  N x N interaction never touches HBM.

Layout notes (Mosaic DMA slices must be tile-aligned):

* coordinates are zero-padded to a multiple of 128 lanes so a column
  block DMA ``(1, block, d_pad)`` is lane-aligned;
* per-point scalars (squared norms, labels) travel as ``(nt, 1, block)``
  float32 rows — a ``(1, 1, block)`` slice is aligned, and arrives in
  exactly the ``(1, bj)`` broadcast layout the kernel consumes.  Labels
  therefore ride as float32, which is exact for indices < 2^24; the
  no-label sentinel is ``+inf``.

Masking convention: callers pre-mask the *column* operand — invalid /
non-source points get coordinates ``BIG`` (squared distance overflows
past any eps) and labels ``+inf``.  No boolean mask ever enters the
kernel.

Only the Euclidean metric goes through Pallas (cityblock has no matmul
decomposition and stays on the XLA path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_INT_INF = jnp.iinfo(jnp.int32).max
_F_INF = float("inf")  # python float: jnp scalars become captured consts in kernels
# Masked-out column points get these coordinates: BIG^2 overflows fp32 to
# inf, so d2 is inf (or NaN for BIG-vs-BIG pairs) and the <= eps^2
# adjacency test is always False.
BIG = jnp.float32(1e19)
# float32 labels are exact up to 2^24.
MAX_LABEL_POINTS = 1 << 24


def _pallas_precision(precision):
    """Mosaic's dot lowering supports only DEFAULT (single-pass bf16) and
    HIGHEST (fp32) — map the XLA-path's bf16_3x default up to HIGHEST."""
    from .distances import _norm_precision

    p = _norm_precision(precision)
    return (
        jax.lax.Precision.DEFAULT
        if p == jax.lax.Precision.DEFAULT
        else jax.lax.Precision.HIGHEST
    )


def _tile_gap2(lo_ref, hi_ref, i, rlo_ref, rhi_ref, j):
    """Squared box-to-box gap between row tile i and column tile j."""
    lo_i = rlo_ref[pl.ds(i, 1), :]
    hi_i = rhi_ref[pl.ds(i, 1), :]
    lo_j = lo_ref[pl.ds(j, 1), :]
    hi_j = hi_ref[pl.ds(j, 1), :]
    gap = jnp.maximum(jnp.maximum(lo_j - hi_i, lo_i - hi_j), 0.0)
    return jnp.sum(gap * gap)


def _sq_dists(x, xx, ybuf, ysq, precision):
    """(bi, d) rows vs (bj, d) cols -> (bi, bj) squared distances.

    ``xx``: (bi, 1) row squared norms; ``ysq``: (1, bj) column squared
    norms (inf for masked columns).
    """
    t = jax.lax.dot_general(
        x,
        ybuf,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision,
    )
    return xx + ysq - 2.0 * t


def _count_kernel(
    eps2_ref, lo_ref, hi_ref, glo_ref, ghi_ref, x_ref, yhbm_ref, ysq_ref,
    out_ref, ybuf, sbuf, ysem, ssem,
    *, precision, group,
):
    i = pl.program_id(0)
    ng = glo_ref.shape[0]
    eps2 = eps2_ref[0]
    x = x_ref[:]
    xx = jnp.sum(x * x, axis=1, keepdims=True)
    out_ref[0] = jnp.zeros_like(out_ref[0])

    def tile_body(j, _):
        gap2 = _tile_gap2(lo_ref, hi_ref, i, lo_ref, hi_ref, j)

        @pl.when(gap2 <= eps2)
        def _():
            ydma = pltpu.make_async_copy(yhbm_ref.at[j], ybuf, ysem)
            sdma = pltpu.make_async_copy(ysq_ref.at[j], sbuf, ssem)
            ydma.start()
            sdma.start()
            ydma.wait()
            sdma.wait()
            d2 = _sq_dists(x, xx, ybuf[:], sbuf[0], precision)
            adj = (d2 <= eps2).astype(jnp.int32)
            out_ref[0] += jnp.sum(adj, axis=1, keepdims=True)

        return 0

    def group_body(g, _):
        # Group-level skip: one gap test covers `group` column tiles.
        ggap2 = _tile_gap2(glo_ref, ghi_ref, i, lo_ref, hi_ref, g)

        @pl.when(ggap2 <= eps2)
        def _():
            jax.lax.fori_loop(g * group, (g + 1) * group, tile_body, 0)

        return 0

    jax.lax.fori_loop(0, ng, group_body, 0)


def _minlab_kernel(
    eps2_ref, lo_ref, hi_ref, rlo_ref, rhi_ref, glo_ref, ghi_ref, x_ref,
    yhbm_ref, ysq_ref, ylab_ref, out_ref,
    ybuf, sbuf, lbuf, ysem, ssem, lsem,
    *, precision, group,
):
    i = pl.program_id(0)
    ng = glo_ref.shape[0]
    eps2 = eps2_ref[0]
    x = x_ref[:]
    xx = jnp.sum(x * x, axis=1, keepdims=True)
    out_ref[0] = jnp.full_like(out_ref[0], _F_INF)

    def tile_body(j, _):
        gap2 = _tile_gap2(lo_ref, hi_ref, i, rlo_ref, rhi_ref, j)

        @pl.when(gap2 <= eps2)
        def _():
            ydma = pltpu.make_async_copy(yhbm_ref.at[j], ybuf, ysem)
            sdma = pltpu.make_async_copy(ysq_ref.at[j], sbuf, ssem)
            ldma = pltpu.make_async_copy(ylab_ref.at[j], lbuf, lsem)
            ydma.start()
            sdma.start()
            ldma.start()
            ydma.wait()
            sdma.wait()
            ldma.wait()
            d2 = _sq_dists(x, xx, ybuf[:], sbuf[0], precision)
            cand = jnp.where(d2 <= eps2, lbuf[0], _F_INF)
            out_ref[0] = jnp.minimum(
                out_ref[0], jnp.min(cand, axis=1, keepdims=True)
            )

        return 0

    def group_body(g, _):
        ggap2 = _tile_gap2(glo_ref, ghi_ref, i, rlo_ref, rhi_ref, g)

        @pl.when(ggap2 <= eps2)
        def _():
            jax.lax.fori_loop(g * group, (g + 1) * group, tile_body, 0)

        return 0

    jax.lax.fori_loop(0, ng, group_body, 0)


def _pad_lanes(x: jnp.ndarray, d_pad: int) -> jnp.ndarray:
    n, d = x.shape
    if d == d_pad:
        return x
    return jnp.concatenate([x, jnp.zeros((n, d_pad - d), x.dtype)], axis=1)


def _prep(points, mask, block, d_pad):
    """Mask columns to BIG; compute tile bounds, squared norms, padded
    column blocks."""
    n, d = points.shape
    nt = n // block
    pts_m = jnp.where(mask[:, None], points.astype(jnp.float32), BIG)
    tiles = pts_m.reshape(nt, block, d)
    # Bounds over masked coords: invalid points sit at +BIG, which would
    # inflate the upper bound — mask them back out with the inverted-box
    # convention (lo=+BIG, hi=-BIG for empty tiles).
    m = mask.reshape(nt, block)[..., None]
    lo = jnp.min(jnp.where(m, tiles, BIG), axis=1)
    hi = jnp.max(jnp.where(m, tiles, -BIG), axis=1)
    # Squared norms of masked coords overflow to +inf, which keeps masked
    # columns out of every adjacency no matter what the matmul returns.
    ysq = jnp.sum(pts_m * pts_m, axis=1).reshape(nt, 1, block)
    ycols = _pad_lanes(pts_m, d_pad).reshape(nt, block, d_pad)
    return ycols, ysq, lo, hi


GROUP = 16  # column tiles covered by one group-level gap test


def _group_bounds(lo, hi):
    """Coarse bounds over GROUP-sized runs of column tiles, padded with
    inverted boxes so padded tiles always prune."""
    nt, d = lo.shape
    ng = -(-nt // GROUP)
    pad = ng * GROUP - nt
    lo_p = jnp.concatenate([lo, jnp.full((pad, d), BIG)], axis=0)
    hi_p = jnp.concatenate([hi, jnp.full((pad, d), -BIG)], axis=0)
    glo = jnp.min(lo_p.reshape(ng, GROUP, d), axis=1)
    ghi = jnp.max(hi_p.reshape(ng, GROUP, d), axis=1)
    return lo_p, hi_p, glo, ghi


def _pallas_block(block: int, n: int, d_pad: int) -> int:
    """Largest row/column tile that keeps the fp32 distance tile plus
    operand blocks comfortably inside VMEM and divides n."""
    b = min(block, n)
    while b > 128 and (
        2 * b * b * 4 + 3 * b * d_pad * 4 > 10 * 1024 * 1024 or n % b != 0
    ):
        b //= 2
    return b


def _round_up_128(d: int) -> int:
    return -(-d // 128) * 128


@functools.partial(
    jax.jit, static_argnames=("block", "precision", "interpret")
)
def neighbor_counts_pallas(
    points: jnp.ndarray,
    eps,
    mask: jnp.ndarray,
    block: int = 1024,
    precision: str = "high",
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas analogue of :func:`pypardis_tpu.ops.distances.neighbor_counts`
    (Euclidean only)."""
    n, d = points.shape
    d_pad = _round_up_128(d)
    block = _pallas_block(block, n, d_pad)
    assert n % block == 0, (n, block)
    nt = n // block
    ycols, ysq, lo, hi = _prep(points, mask, block, d_pad)
    xrows = ycols.reshape(n, d_pad)
    lo_p, hi_p, glo, ghi = _group_bounds(lo, hi)
    ntp, ng = lo_p.shape[0], glo.shape[0]
    eps2 = jnp.asarray(eps, jnp.float32).reshape(1) ** 2

    counts = pl.pallas_call(
        functools.partial(
            _count_kernel,
            precision=_pallas_precision(precision),
            group=GROUP,
        ),
        grid=(nt,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((ntp, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((ntp, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((ng, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((ng, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (block, d_pad), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pltpu.HBM),
            pl.BlockSpec(memory_space=pltpu.HBM),
        ],
        out_specs=pl.BlockSpec(
            (1, block, 1), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((nt, block, 1), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((block, d_pad), jnp.float32),
            pltpu.VMEM((1, block), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )(eps2, lo_p, hi_p, glo, ghi, xrows, ycols, ysq)
    return jnp.where(mask, counts.reshape(-1), 0)


@functools.partial(
    jax.jit, static_argnames=("block", "precision", "interpret")
)
def min_neighbor_label_pallas(
    points: jnp.ndarray,
    labels: jnp.ndarray,
    eps,
    src_mask: jnp.ndarray,
    block: int = 1024,
    precision: str = "high",
    interpret: bool = False,
    row_mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Pallas analogue of
    :func:`pypardis_tpu.ops.distances.min_neighbor_label` (Euclidean).

    Labels travel as float32 (exact below 2^24); INT32_MAX maps to +inf
    and back.
    """
    n, d = points.shape
    if n >= MAX_LABEL_POINTS:
        raise ValueError(
            f"pallas label kernel supports < 2^24 points per shard, got {n}"
        )
    d_pad = _round_up_128(d)
    block = _pallas_block(block, n, d_pad)
    assert n % block == 0, (n, block)
    nt = n // block
    ycols, ysq, lo, hi = _prep(points, src_mask, block, d_pad)
    if row_mask is None:
        rlo, rhi = lo, hi
    else:
        _, _, rlo, rhi = _prep(points, row_mask, block, d_pad)
    lo_p, hi_p, glo, ghi = _group_bounds(lo, hi)
    ntp, ng = lo_p.shape[0], glo.shape[0]
    # Row operand: raw coordinates — rows outside row_mask still get
    # outputs; callers mask them.
    xrows = _pad_lanes(points.astype(jnp.float32), d_pad)
    labf = jnp.where(
        src_mask & (labels != _INT_INF), labels.astype(jnp.float32), _F_INF
    ).reshape(nt, 1, block)
    eps2 = jnp.asarray(eps, jnp.float32).reshape(1) ** 2

    best = pl.pallas_call(
        functools.partial(
            _minlab_kernel,
            precision=_pallas_precision(precision),
            group=GROUP,
        ),
        grid=(nt,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((ntp, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((ntp, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((nt, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((nt, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((ng, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((ng, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (block, d_pad), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pltpu.HBM),
            pl.BlockSpec(memory_space=pltpu.HBM),
            pl.BlockSpec(memory_space=pltpu.HBM),
        ],
        out_specs=pl.BlockSpec(
            (1, block, 1), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((nt, block, 1), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((block, d_pad), jnp.float32),
            pltpu.VMEM((1, block), jnp.float32),
            pltpu.VMEM((1, block), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        interpret=interpret,
    )(eps2, lo_p, hi_p, rlo, rhi, glo, ghi, xrows, ycols, ysq, labf)
    best = best.reshape(-1)
    return jnp.where(jnp.isfinite(best), best.astype(jnp.int32), _INT_INF)
