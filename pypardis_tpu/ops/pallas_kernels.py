"""Pallas TPU kernels for the eps-neighborhood hot loop.

The XLA path in :mod:`pypardis_tpu.ops.distances` expresses the tiled
pairwise interaction as ``lax.map`` over row tiles with a ``lax.scan`` +
``lax.cond`` over column tiles.  These kernels implement the same two
primitives — eps-neighbor counting and min-label-over-neighbors — as
**pair-list** Mosaic programs:

* tile-level pruning happens OUTSIDE the kernel: one vectorized XLA pass
  over per-tile bounding boxes (:func:`live_tile_pairs` in
  :mod:`pypardis_tpu.ops.distances`) emits the row-major list of (row
  tile, col tile) pairs whose boxes lie within eps.  The round-2/3
  design scanned all nt^2/GROUP group boxes *inside* the kernel, which
  put an O(nt^2) sequential scalar loop on the critical path — measured
  4.2s of pure scan overhead per pass at 10M points with every pair
  pruned;
* the grid is the pair list itself (scalar-prefetched row/col index
  arrays — the Mosaic block-sparse idiom).  Each program loads its two
  coordinate tiles via BlockSpec index maps that read the prefetched
  indices, so Mosaic's own pipeline machinery double-buffers the HBM
  traffic — no hand-written DMA, no semaphores;
* pairs arrive sorted by row tile, so each output block's visits are
  consecutive: the kernel initializes the accumulator on the first
  visit of a row (prefetched-row change) and accumulates in VMEM across
  the run — the standard Pallas reduction pattern;
* the distance tile is one MXU contraction of **norm-augmented
  operands** ``[-2(y-c); 1; |y-c|^2]^T [x-c; |x-c|^2; 1] = |x-y|^2``
  consumed immediately by the compare-and-reduce in registers, so the
  N x N interaction never touches HBM.

Layout: coordinates stay in the drivers' ``(d, N)`` transposed layout —
the big point axis minor, dense in HBM for any d — and kernel BlockSpecs
index (d, block) column blocks out of it DIRECTLY.  No tile-transposed
copy, no masked copy, and no dump-block concat ever materializes
(together those were ~12-18GB of HLO temps at 50M x 16-D — the round-4
single-chip ceiling); padding pairs clamp their index maps to a real
block and skip compute.  Per-point scalars (labels, validity) and
outputs travel as ``(nt, 1, block)`` rows.  Labels ride as int32
(sentinel INT32_MAX), so any shard size up to HBM capacity is
supported.

Numerics:

* every tile pair is computed **recentred on the output tile's box
  center**, so operand magnitudes are tile-local and the classic
  ``|x|^2+|y|^2-2xy`` cancellation does not amplify absolute coordinate
  scale (the dataset-level recentring in the drivers bounds it further);
* ``precision="high"`` (default) runs a manual **3-pass bf16 split
  matmul** (hi/lo decomposition: ``x = hi(x) + lo(x)``, dropping only
  the lo*lo term).  The dropped term is ~2^-18 relative to *operand
  magnitude* — i.e. fp32-class only when tiles are spatially tight
  (the Morton-sorted, segment-broken driver layout); on loose tiles the
  absolute d2 error can reach coordinate scale x 2^-18 and flip
  shell-adjacent pairs (bounded in tests/test_tpu_smoke.py;
  cluster-level output is ARI-stable).  Mosaic has no native bf16_3x,
  which in round 1 silently upgraded "high" to HIGHEST and cost 2x.
* ``precision="highest"`` uses native HIGHEST; ``"default"`` a single
  bf16 pass (fast, ~2^-8-relative — opt-in only).

Masking convention: coordinates enter the kernels UNMASKED.  Column
validity applies inside the count kernel from tiny per-tile int32 mask
blocks; the minlab kernel's source restriction and validity ride
entirely on the label sentinel (a non-source or invalid point's
INT32_MAX never wins a min); invalid ROW outputs are garbage the
callers mask.  Padding entries of the pair list carry row ``nt`` — a
dump output row sliced off by the caller.

Only the Euclidean metric goes through Pallas (cityblock has no matmul
decomposition and stays on the XLA path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_INT_INF = jnp.iinfo(jnp.int32).max
# Sentinel for empty-tile bounding boxes (_bounds_dn): inverted
# (+BIG, -BIG) boxes put their gap to anything astronomically past any
# eps, so empty tiles always prune.
BIG = np.float32(2e19)  # numpy scalar: trace-inert at import time

# One normalizer for BOTH backends (pypardis_tpu.ops.precision) — the
# kernel name is kept for its existing callers.
from .precision import (  # noqa: E402  (import placement is historical)
    band_halfwidth as _band_halfwidth,
    norm_precision_mode as _norm_precision_mode,
)


def _dot_t(a, b, mode):
    """(K, m) x (K, n) → (m, n): contraction over the leading axis.

    ``mode="high"`` is the manual bf16_3x: split each operand into a
    bf16 head plus a bf16-rounded residual and accumulate the three
    significant cross terms with single-pass (DEFAULT) MXU dots.  The
    dropped lo*lo term is O(2^-18) relative — fp32-class accuracy.
    """
    dims = (((0,), (0,)), ((), ()))

    def dot(x, y, prec):
        return jax.lax.dot_general(
            x, y, dims, precision=prec, preferred_element_type=jnp.float32
        )

    if mode == "highest":
        return dot(a, b, jax.lax.Precision.HIGHEST)
    if mode == "default":
        return dot(a, b, jax.lax.Precision.DEFAULT)
    ah = a.astype(jnp.bfloat16).astype(jnp.float32)
    al = a - ah
    bh = b.astype(jnp.bfloat16).astype(jnp.float32)
    bl = b - bh
    d = jax.lax.Precision.DEFAULT
    return dot(ah, bh, d) + (dot(ah, bl, d) + dot(al, bh, d))


def _aug_out(x, c):
    """Output-side augmented operand: [x-c; |x-c|^2; 1] → (d+2, bo)."""
    xc = x - c
    xsq = jnp.sum(xc * xc, axis=0, keepdims=True)
    return jnp.concatenate([xc, xsq, jnp.ones_like(xsq)], axis=0)


def _aug_src(y, c):
    """Source-side augmented operand: [-2(y-c); 1; |y-c|^2] → (d+2, bs)."""
    yc = y - c
    ysq = jnp.sum(yc * yc, axis=0, keepdims=True)
    return jnp.concatenate([-2.0 * yc, jnp.ones_like(ysq), ysq], axis=0)


def _first_visit(rows_ref):
    """True on the first grid step of a run of equal row-tile indices."""
    p = pl.program_id(0)
    prev = rows_ref[jnp.maximum(p, 1) - 1]
    return (p == 0) | (rows_ref[p] != prev)


def _mixed_classify(x, y, c, eps2, src_valid):
    """Banded classification for one Mosaic tile pair.

    One bf16 pass (``"default"`` dot of the augmented recentred
    operands) puts every pair definitely-in, definitely-out, or
    in-band against ``eps2 +- band`` — the band from the shared bf16
    error bound (:func:`pypardis_tpu.ops.precision.band_halfwidth`)
    at the tiles' recentred NORM maxima (the source side masked by
    ``src_valid``, a (block, 1) validity column, so sentinel/pad slots
    cannot blow the bound up to their global-frame magnitude; the
    output side has no in-kernel mask — a pad-bearing row tile's
    looser band only costs extra rescores, never correctness).
    Returns ``(d2f, xa, ya, n_band_pairs, need_rescore)``: a tile
    containing an in-band valid pair must emit verdicts from a
    bf16_3x (``"high"``) recompute of the whole tile — the callers
    guard that dot behind ``pl.when(need)`` so a clean tile really
    does run at the single-pass bf16 peak.  The rescore shares this
    recentred frame (it IS the plain ``"high"`` kernel arithmetic),
    so out-of-band fast verdicts provably match it and the combined
    output is byte-identical to a full ``"high"`` run.
    """
    xa = _aug_out(x, c)
    ya = _aug_src(y, c)
    d2f = _dot_t(ya, xa, "default")
    xc = x - c
    yc = y - c
    # keepdims reductions: Mosaic prefers >=2-D intermediates (the
    # same discipline as _aug_out/_aug_src).
    nx = jnp.sqrt(jnp.max(jnp.sum(xc * xc, axis=0, keepdims=True)))
    ny = jnp.sqrt(jnp.max(jnp.where(
        jnp.transpose(src_valid, (1, 0)),
        jnp.sum(yc * yc, axis=0, keepdims=True),
        0.0,
    )))
    band = _band_halfwidth(nx, ny)
    ambig = (jnp.abs(d2f - eps2) <= band) & src_valid
    n_band = jnp.sum(ambig, dtype=jnp.int32)
    return d2f, xa, ya, n_band, n_band > 0


def _stats_init(stats_ref, block):
    """Zero the per-call band-stats block on the first grid step."""
    @pl.when(pl.program_id(0) == 0)
    def _():
        stats_ref[0] = jnp.zeros_like(stats_ref[0])


def _stats_add(stats_ref, block, n_band, rescored):
    """Accumulate ``[band_pairs, rescored_tiles]`` into slots 0/1 of
    the (1, block) stats block (vector add — Mosaic-friendlier than a
    scalar VMEM store)."""
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, block), 1)
    stats_ref[0] += (
        jnp.where(iota == 0, n_band, 0)
        + jnp.where(iota == 1, rescored, 0)
    )


def _count_pairs_kernel(rows_ref, cols_ref, eps2_ref, c_ref, x_ref, y_ref,
                        m_ref, out_ref, stats_ref=None, *, mode, nt):
    eps2 = eps2_ref[0]
    # Recentre the pair on the output tile's box center: operand
    # magnitudes become tile-local, keeping the matmul expansion's
    # cancellation error at eps scale.
    c = c_ref[0]
    # Scalar reads stay at kernel top level: program_id inside a nested
    # pl.when branch is invisible to the Pallas interpreter's grid env.
    real = rows_ref[pl.program_id(0)] < nt
    first = _first_visit(rows_ref)
    if stats_ref is not None:
        _stats_init(stats_ref, out_ref.shape[-1])

    # First visit of a row within this call: start from the identity.
    # Rows a call never visits keep uninitialized garbage — callers
    # mask with the visited-rows set (see _pair_call).
    @pl.when(real & first)
    def _():
        out_ref[0] = jnp.zeros_like(out_ref[0])

    # Padding pairs carry row == nt: skip their (block x block) matmul
    # entirely (their index maps clamp, but the FLOPs would be real —
    # at small N padding dominates the budget).
    @pl.when(real)
    def _():
        # x/y are (d, block) blocks indexed straight out of the (d, N)
        # operand — no tile-transposed copy exists anywhere.
        # Column validity rides as a tiny int32 block applied HERE, in
        # VMEM, instead of as a full-size masked copy of the
        # coordinates in HBM (the r4 50M compile-OOM).  Invalid ROW
        # points produce garbage counts; callers mask rows anyway.
        valid_col = jnp.transpose(m_ref[0], (1, 0)) > 0

        def emit(d2):
            adj = ((d2 <= eps2) & valid_col).astype(jnp.int32)
            out_ref[0] += jnp.sum(adj, axis=0, keepdims=True)

        if mode == "mixed":
            d2f, xa, ya, n_band, need = _mixed_classify(
                x_ref[...], y_ref[...], c, eps2, valid_col
            )
            _stats_add(
                stats_ref, out_ref.shape[-1], n_band,
                need.astype(jnp.int32),
            )

            # The rescore dot only RUNS for tiles with an in-band pair
            # — a clean tile stays at the single-pass bf16 peak.
            @pl.when(need)
            def _():
                emit(_dot_t(ya, xa, "high"))

            @pl.when(~need)
            def _():
                emit(d2f)
        else:
            emit(_dot_t(
                _aug_src(y_ref[...], c), _aug_out(x_ref[...], c), mode
            ))


def _minlab_pairs_kernel(rows_ref, cols_ref, eps2_ref, c_ref, x_ref, y_ref,
                         lab_ref, out_ref, stats_ref=None, *, mode, nt):
    eps2 = eps2_ref[0]
    c = c_ref[0]
    real = rows_ref[pl.program_id(0)] < nt
    first = _first_visit(rows_ref)
    if stats_ref is not None:
        _stats_init(stats_ref, out_ref.shape[-1])

    @pl.when(real & first)
    def _():
        out_ref[0] = jnp.full_like(out_ref[0], _INT_INF)

    @pl.when(real)
    def _():
        lab_col = jnp.transpose(lab_ref[0], (1, 0))

        def emit(d2):
            cand = jnp.where(d2 <= eps2, lab_col, _INT_INF)
            out_ref[0] = jnp.minimum(
                out_ref[0], jnp.min(cand, axis=0, keepdims=True)
            )

        if mode == "mixed":
            # Source restriction/validity ride on the label sentinel;
            # the same mask keeps sentinel columns out of the rescore
            # decision.  No stats output here: band stats are
            # deterministic per pass, and the counts kernel already
            # measured them — the in-band test below exists only to
            # gate the rescore.
            d2f, xa, ya, _n_band, need = _mixed_classify(
                x_ref[...], y_ref[...], c, eps2, lab_col != _INT_INF,
            )

            @pl.when(need)
            def _():
                emit(_dot_t(ya, xa, "high"))

            @pl.when(~need)
            def _():
                emit(d2f)
        else:
            emit(_dot_t(
                _aug_src(y_ref[...], c), _aug_out(x_ref[...], c), mode
            ))


def _sketch_gates(sx, sy, k, eps2, band, valid):
    """Sketch-space classification for one Mosaic tile pair.

    ``sx``/``sy``: (skp, block) slab blocks — rows 0..k-1 the
    projection, row k the orthogonal-residual norm, rows past k zero
    padding (inert in every sum).  The slab distance ``t2`` (source x
    output orientation, matching the kernels' dot) LOWER-bounds the
    full-d d2 and ``t2 + 4*ri*rj`` UPPER-bounds it; ``band`` absorbs
    every float/orthogonality defect
    (:func:`pypardis_tpu.ops.sketch.sketch_gate_band`).  Returns
    ``(sure_in, n_band, need)`` — ``sure_in`` the certified in-gate
    adjacency for tiles that skip the rescore, ``need`` whether any
    valid pair landed in the band (the whole tile then reruns the
    full-d arithmetic).  HIGHEST-precision dot: k is small, so the
    exact-f32 passes are cheap relative to the (d+2) rescore they
    replace.
    """
    sxx = jnp.sum(sx * sx, axis=0, keepdims=True)  # (1, block)
    syy = jnp.sum(sy * sy, axis=0, keepdims=True)
    t2 = (
        jnp.transpose(syy, (1, 0)) + sxx - 2.0 * _dot_t(sy, sx, "highest")
    )
    up = t2 + 4.0 * sy[k][:, None] * sx[k][None, :]
    sure_in = up <= eps2 - band
    sure_out = t2 - band > eps2
    ambig = (~(sure_in | sure_out)) & valid
    n_band = jnp.sum(ambig, dtype=jnp.int32)
    return sure_in, n_band, n_band > 0


def _count_pairs_sketch_kernel(
    rows_ref, cols_ref, eps2_ref, c_ref, x_ref, y_ref, sx_ref, sy_ref,
    m_ref, out_ref, stats_ref, *, mode, nt, k,
):
    """Sketch-prefiltered twin of :func:`_count_pairs_kernel`: the
    (k+1)-row slab blocks classify every pair against ``eps2 +- band``
    (both prefetched — ``eps2_ref`` is (2,) ``[eps2, band]`` here) and
    only a tile with an in-band valid pair runs the full-d augmented
    dot; certified gate verdicts are byte-identical to that dot's, so
    counts match the unsketched kernel exactly.  ``mode="mixed"``
    rescores at ``"high"`` — bitwise the mixed contract's output.
    Stats slots 0/1 carry [sketch-band pairs, rescored tiles]."""
    eps2 = eps2_ref[0]
    band = eps2_ref[1]
    c = c_ref[0]
    real = rows_ref[pl.program_id(0)] < nt
    first = _first_visit(rows_ref)
    _stats_init(stats_ref, out_ref.shape[-1])
    resc_mode = "high" if mode == "mixed" else mode

    @pl.when(real & first)
    def _():
        out_ref[0] = jnp.zeros_like(out_ref[0])

    @pl.when(real)
    def _():
        valid_col = jnp.transpose(m_ref[0], (1, 0)) > 0
        sure_in, n_band, need = _sketch_gates(
            sx_ref[...], sy_ref[...], k, eps2, band, valid_col
        )
        _stats_add(
            stats_ref, out_ref.shape[-1], n_band, need.astype(jnp.int32)
        )

        def emit(adj):
            out_ref[0] += jnp.sum(
                (adj & valid_col).astype(jnp.int32), axis=0, keepdims=True
            )

        # The full-d dot only RUNS for tiles with an in-band pair — a
        # classified tile costs one k-dim HIGHEST dot, not a (d+2) one.
        @pl.when(need)
        def _():
            emit(_dot_t(
                _aug_src(y_ref[...], c), _aug_out(x_ref[...], c), resc_mode
            ) <= eps2)

        @pl.when(~need)
        def _():
            emit(sure_in)


def _minlab_pairs_sketch_kernel(
    rows_ref, cols_ref, eps2_ref, c_ref, x_ref, y_ref, sx_ref, sy_ref,
    lab_ref, out_ref, *, mode, nt, k,
):
    """Sketch-prefiltered twin of :func:`_minlab_pairs_kernel` (no
    stats output — the propagation discipline: the counts kernel
    already measured them; the gate here only routes the rescore)."""
    eps2 = eps2_ref[0]
    band = eps2_ref[1]
    c = c_ref[0]
    real = rows_ref[pl.program_id(0)] < nt
    first = _first_visit(rows_ref)
    resc_mode = "high" if mode == "mixed" else mode

    @pl.when(real & first)
    def _():
        out_ref[0] = jnp.full_like(out_ref[0], _INT_INF)

    @pl.when(real)
    def _():
        lab_col = jnp.transpose(lab_ref[0], (1, 0))
        sure_in, _n_band, need = _sketch_gates(
            sx_ref[...], sy_ref[...], k, eps2, band, lab_col != _INT_INF
        )

        def emit(adj):
            cand = jnp.where(adj, lab_col, _INT_INF)
            out_ref[0] = jnp.minimum(
                out_ref[0], jnp.min(cand, axis=0, keepdims=True)
            )

        @pl.when(need)
        def _():
            emit(_dot_t(
                _aug_src(y_ref[...], c), _aug_out(x_ref[...], c), resc_mode
            ) <= eps2)

        @pl.when(~need)
        def _():
            emit(sure_in)


def _points_dn(points, layout):
    """The kernels' canonical (d, N) float32 operand layout.

    For ``layout="dn"`` float32 input this is the identity — the
    kernels' BlockSpecs index tile columns of this array DIRECTLY, so
    no (nt, d, block) tile copy ever materializes (that copy was a
    5.96GB HLO temp in every kernel-calling program at 50M x 16-D,
    the round-4 HBM ceiling).  ``layout="nd"`` callers pay one
    transpose — they are the small paths.
    """
    if layout == "nd":
        return points.astype(jnp.float32).T
    return points.astype(jnp.float32)


# Tile-axis chunk for _bounds_dn: keeps the masked reduce's where()
# temps at O(chunk) instead of O(dataset) — at 50M x 16-D (cap2 ~100M
# after segment-break padding) an unchunked masked reduce needed
# 2 x 5.96GB of HLO temps and compile-failed on the 16GB chip.
_BOUNDS_CHUNK_ELEMS = 1 << 26


def _bounds_dn(pts_dn, mask, nt, block):
    """(nt, d) masked per-tile bounds straight off the (d, N) layout.

    Empty tiles get inverted (+BIG, -BIG) boxes so they always prune.
    Chunked over tiles; the last chunk overlaps its predecessor
    (clamped start) and rewrites identical values.
    """
    d, n = pts_dn.shape

    def direct(start_col, width):
        seg = jax.lax.dynamic_slice(
            pts_dn, (0, start_col), (d, width * block)
        ).reshape(d, width, block)
        msk = jax.lax.dynamic_slice(
            mask, (start_col,), (width * block,)
        ).reshape(1, width, block)
        lo = jnp.min(jnp.where(msk, seg, BIG), axis=2).T
        hi = jnp.max(jnp.where(msk, seg, -BIG), axis=2).T
        return lo, hi  # (width, d)

    chunk = max(1, _BOUNDS_CHUNK_ELEMS // max(d * block, 1))
    if nt <= chunk:
        return direct(0, nt)

    nc = -(-nt // chunk)

    def body(carry, c):
        lo_all, hi_all = carry
        s = jnp.minimum(c * chunk, nt - chunk)
        lo, hi = direct(s * block, chunk)
        return (
            jax.lax.dynamic_update_slice(lo_all, lo, (s, 0)),
            jax.lax.dynamic_update_slice(hi_all, hi, (s, 0)),
        ), None

    init = (
        jnp.zeros((nt, d), jnp.float32),
        jnp.zeros((nt, d), jnp.float32),
    )
    (lo, hi), _ = jax.lax.scan(body, init, jnp.arange(nc))
    return lo, hi


def _centers_dn(pts_dn, mask, nt, block):
    """Per-tile recentring points: box centers of valid coords,
    (nt, d, 1).  Empty tiles carry inverted bounds whose midpoint is
    0 — recentring is a no-op there."""
    lo, hi = _bounds_dn(pts_dn, mask, nt, block)
    return (0.5 * (lo + hi))[:, :, None]


def _round8(v: int) -> int:
    """Round up to the Mosaic f32 second-minor multiple (8)."""
    return -(-int(v) // 8) * 8


def _sketch_stage(pts_dn, mask, sk, mode):
    """Stage the random-projection slab for the sketch kernels.

    ``(d, N)`` coordinates → ``((skp, N) slab, band)``: rows 0..sk-1
    the HIGHEST-precision projection ``Q^T x``, row sk the orthogonal
    residual norm, rows past that zero padding up to ``skp =
    round8(sk + 1)`` so the slab blocks satisfy Mosaic's f32
    second-minor constraint (zero rows are inert in every slab sum).
    ``band`` is the certified gate half-width
    (:func:`pypardis_tpu.ops.sketch.sketch_gate_band`) at the masked
    global norm maximum; ``fast_exact=False`` because the Pallas
    ``"default"`` dot is single-pass bf16 on hardware (in interpret
    mode this merely over-widens the band — extra rescores, never a
    wrong verdict).
    """
    from .sketch import sketch_gate_band, sketch_matrix

    d, n = pts_dn.shape
    q, eta = sketch_matrix(d, sk)
    proj = jax.lax.dot_general(
        jnp.asarray(q), pts_dn, (((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    full = jnp.sum(pts_dn * pts_dn, axis=0, keepdims=True)
    res = jnp.sqrt(jnp.maximum(
        full - jnp.sum(proj * proj, axis=0, keepdims=True), 0.0
    ))
    skp = _round8(sk + 1)
    parts = [proj, res]
    if skp > sk + 1:
        parts.append(jnp.zeros((skp - (sk + 1), n), jnp.float32))
    slab = jnp.concatenate(parts, axis=0)
    nmax = jnp.sqrt(jnp.max(jnp.where(mask, full[0], 0.0)))
    band = sketch_gate_band(nmax, d, sk, eta, precision=mode,
                            fast_exact=False)
    return slab, band


def _pallas_block(block: int, n: int, d: int, mode: str = "high") -> int:
    """Largest tile that keeps the fp32 distance tile plus operand
    blocks comfortably inside VMEM and divides n.

    Deliberately sketch-independent: callers size pair lists and
    owner-computes splits from ``(block, n, d, mode)`` alone, so the
    grid must not shift when the sketch prefilter turns on.  The
    sketch temps — two (skp <= 72, b) slab blocks and ~3 extra (b, b)
    gate masks — fit the gap between the 32MB budget and Mosaic's
    128MB VMEM at every admitted b.

    The default bf16_3x mode materializes more than the plain path: the
    hi/lo operand splits (four extra (d+2, b) blocks) and up to three
    (b, b) dot results before the adds fuse — budget for them so a
    Mosaic VMEM overflow can't appear only on hardware.  The 32MB cap
    (v5e/v4 VMEM is 128MB) admits b=1024 in every mode — measured ~2x
    over b=512 at 5M points: half the per-tile DMA waits and a better
    MXU aspect — while leaving headroom for Mosaic's double buffering
    of the grid blocks.  b=2048 would put the bf16_3x worst case past
    80MB; not worth the risk for <10% fewer DMAs.
    """
    b = min(block, n)
    if mode == "high":
        tile_words, opnd_words = 4, 8
    elif mode == "mixed":
        # Worst case is the rescored tile: the bf16_3x budget PLUS the
        # live fast-pass tile and the band/classification temps.
        tile_words, opnd_words = 6, 8
    else:
        tile_words, opnd_words = 2, 4
    while b > 128 and (
        tile_words * b * b * 4 + opnd_words * b * (d + 2) * 4
        > 32 * 1024 * 1024
        or n % b != 0
    ):
        b //= 2
    return b


def _check_mosaic_tile(block: int, n: int, interpret: bool) -> None:
    """Fail a Mosaic-illegal tile with a readable error, up front.

    ``backend='auto'`` never reaches here (``resolve_backend`` consults
    :func:`effective_tile`); an EXPLICIT ``backend='pallas'`` with e.g.
    block=64 would otherwise surface Mosaic lowering internals.
    Interpret mode (CPU tests) has no tiling constraint.
    """
    if n % block != 0:
        raise ValueError(f"pallas tile {block} does not divide n={n}")
    if not interpret and block % 128 != 0:
        raise ValueError(
            f"pallas kernels require a tile that is a multiple of 128 "
            f"(Mosaic constraint on the trailing block dim of the (d, N) "
            f"layout); effective tile {block} from block/n={n}. "
            f"Use backend='auto' or 'xla' for this configuration."
        )


def gm_tile_aligned(block: int, n_total: int, owned: int, d: int,
                    mode: str = "high") -> bool:
    """Whether the Pallas kernels can run a global-Morton owned+boundary
    slab of ``n_total`` rows whose first ``owned`` are the shard's own
    range.

    The owner-computes pair-list filters split the tile-pair list at
    ``owned // tile`` (``ops.labels._oc_sorted_pairs``), so the
    effective tile must divide BOTH the total capacity and the owned
    prefix — a boundary buffer whose offset lands mid-tile would mix
    owned and cross-shard rows inside one Mosaic tile and corrupt the
    row/column split.  Callers route misaligned configs to the XLA
    kernels explicitly (:func:`pypardis_tpu.ops.labels.gm_backend`)
    instead of paying a lowering-failure/fallback cycle on hardware.
    """
    b = effective_tile(block, n_total, d, mode)
    return b is not None and owned % b == 0


def effective_tile(block: int, n: int, d: int, mode: str = "high"):
    """The tile the Pallas kernels would actually run, or ``None`` when
    no Mosaic-legal tile exists for this (block, n).

    The kernels BlockSpec-index ``(d, tile)`` column blocks straight off
    the canonical ``(d, N)`` array, so Mosaic requires the trailing
    block dim to be a multiple of 128 (the first dim is the full array
    dim ``d`` and is unconstrained).  ``_pallas_block`` can return a
    sub-128 or non-dividing tile (user block < 128, or n with no
    128-multiple divisor, e.g. n=4000): those configs must run the XLA
    path — :func:`pypardis_tpu.ops.labels.resolve_backend` consults this
    so ``backend='auto'`` routes them there without a
    lowering-failure/fallback cycle.
    """
    b = _pallas_block(block, n, d, mode)
    if b % 128 == 0 and n % b == 0:
        return b
    return None


def graph_emission_tile(
    block: int, n: int, d: int, precision: str = "high"
) -> int:
    """The tile the sweep's pair-emission pass should run on.

    The emission pass (:func:`pypardis_tpu.ops.distances
    .neighbor_pair_graph`) enumerates the SAME live tile pairs the
    kernels dispatch over; running it on the Pallas kernels' effective
    tile keeps the two grids — and therefore their pair budgets /
    hints — aligned on TPU, exactly the discipline the dense-dispatch
    ``count_live_tile_pairs`` follows.  Tile choice never changes which
    (i, j) pairs survive (the eps threshold is per pair; tiles only set
    pruning granularity), so off-TPU callers may pass any divisor —
    this helper just picks the grid-consistent one when a Mosaic tile
    exists.
    """
    return (
        effective_tile(block, n, d, _norm_precision_mode(precision))
        or min(block, n)
    )


def _shape_nd(points, layout):
    if layout not in ("nd", "dn"):
        raise ValueError(f"layout must be 'nd' or 'dn', got {layout!r}")
    if layout == "nd":
        return points.shape
    d, n = points.shape
    return n, d


# Pairs per pallas_call: the row/col index arrays ride in SMEM (scalar
# prefetch), and SMEM is ~1MB/core — 48k pairs is 384KB of int32 x2,
# comfortable alongside Mosaic's own scalars.  Longer lists run as a
# lax.scan of chunked calls whose partials merge into a carried
# accumulator on the rows each chunk visited.  (An earlier design
# threaded the accumulator through input_output_aliases instead; the
# axon runtime deterministically failed RE-execution of such programs
# with INVALID_ARGUMENT, and the merge's extra traffic is only the
# (nt+1, block) accumulator per chunk — tens of ms per pass.)
CHUNK_PAIRS = 48 * 1024


def _pair_call(kernel, nt, d, block, n_extra_in, interpret, identity,
               combine, band_stats=False, sketch_dim=0):
    """Common pallas_call plumbing for the two pair-list kernels.

    Grid = one program per pair-list entry; the row/col tile index
    arrays and eps^2 ride as scalar prefetch, so BlockSpec index maps
    can address HBM blocks by them.  Padding entries carry row nt — the
    dump row of the (nt+1)-row output, sliced off by callers.

    ``identity``: the neutral value rows start from (0 / INT_INF);
    ``combine``: how per-chunk partials fold into the accumulator (add
    / minimum).  Rows a chunk never visits hold uninitialized memory in
    its partial; the visited-rows mask keeps them out of the merge, and
    rows no chunk visits come back as ``identity``.

    ``sketch_dim`` (the sketch-prefiltered kernels): inserts two
    (sketch_dim, block) slab blocks after the coordinate tiles, indexed
    by the same clamped row/col maps off a (sketch_dim, N) slab array
    the caller appends to ``arrays`` between the coordinates and the
    int32 blocks.

    ``band_stats`` (the ``mode="mixed"`` kernels): adds a second
    (1, 1, block) int32 output whose constant index map keeps the
    block live in VMEM across the whole sequential grid — the standard
    full-reduction idiom — holding ``[band_pairs, rescored_tiles]`` in
    slots 0/1.  Chunked runs sum the per-chunk partials.  The call
    then returns ``(acc, (2,) int32)``.
    """

    def specs(n_pairs):
        # INPUT index maps CLAMP the tile index: padding pairs carry
        # row == nt, and fetching a real (skipped) block beats giving
        # every input a concatenated dump block — at 50M x 16-D the
        # dump-block concat plus the masked coordinate copy were
        # 2 x 5.96GB of HLO temps, an outright compile-OOM.  The
        # kernels' `real` guard skips all compute for padding pairs;
        # only the OUTPUT keeps a dump row (it is (nt+1, 1, block)
        # int32 — small).
        def rclamp(p, r, c, e):
            return (jnp.minimum(r[p], nt - 1), 0, 0)

        def cclamp(p, r, c, e):
            return (jnp.minimum(c[p], nt - 1), 0, 0)

        # Coordinate blocks index the (d, N) operand directly: block
        # (d, block) at column-block min(idx, nt-1).
        def rclamp2(p, r, c, e):
            return (0, jnp.minimum(r[p], nt - 1))

        def cclamp2(p, r, c, e):
            return (0, jnp.minimum(c[p], nt - 1))

        row_keyed_out = pl.BlockSpec(
            (1, 1, block), lambda p, r, c, e: (r[p], 0, 0),
            memory_space=pltpu.VMEM,
        )
        in_specs = [
            # per-row-tile recentring center, (nt, d, 1)
            pl.BlockSpec((1, d, 1), rclamp, memory_space=pltpu.VMEM),
            # output-side coordinate tile (rows), from the (d, N) array
            pl.BlockSpec((d, block), rclamp2, memory_space=pltpu.VMEM),
            # source-side coordinate tile (cols), from the (d, N) array
            pl.BlockSpec((d, block), cclamp2, memory_space=pltpu.VMEM),
        ] + ([
            # sketch slab tiles (rows then cols) from the (skp, N) slab
            # array — same clamped column-block maps as the coordinates
            pl.BlockSpec((sketch_dim, block), rclamp2,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((sketch_dim, block), cclamp2,
                         memory_space=pltpu.VMEM),
        ] if sketch_dim else []) + [
            # per-point int32 rows keyed by the col tile (labels/masks)
            pl.BlockSpec((1, 1, block), cclamp, memory_space=pltpu.VMEM)
        ] * n_extra_in
        out_specs = row_keyed_out
        if band_stats:
            # Constant-index-map stats block: lives in VMEM across the
            # whole sequential grid (the standard full-reduction idiom)
            # so the mixed kernels accumulate [band_pairs,
            # rescored_tiles] without touching HBM per pair.
            out_specs = (
                row_keyed_out,
                pl.BlockSpec(
                    (1, 1, block), lambda p, r, c, e: (0, 0, 0),
                    memory_space=pltpu.VMEM,
                ),
            )
        return pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(n_pairs,),
            in_specs=in_specs,
            out_specs=out_specs,
        )

    def one_call(rows, cols, eps2, arrays):
        out_shape = jax.ShapeDtypeStruct((nt + 1, 1, block), jnp.int32)
        if band_stats:
            out_shape = (
                out_shape,
                jax.ShapeDtypeStruct((1, 1, block), jnp.int32),
            )
        out = pl.pallas_call(
            kernel,
            grid_spec=specs(rows.shape[0]),
            out_shape=out_shape,
            interpret=interpret,
        )(rows, cols, eps2, *arrays)
        if band_stats:
            return out[0], out[1][0, 0, :2]
        return out, jnp.zeros(2, jnp.int32)

    def merge(acc, partial, rows):
        visited = jnp.zeros(nt + 1, bool).at[rows].set(True)
        return jnp.where(
            visited[:, None, None], combine(acc, partial), acc
        )

    def call(rows, cols, eps2, *arrays):
        n_pairs = rows.shape[0]
        acc0 = jnp.full((nt + 1, 1, block), identity, jnp.int32)
        if n_pairs <= CHUNK_PAIRS:
            partial, st = one_call(rows, cols, eps2, arrays)
            out = merge(acc0, partial, rows)
            return (out, st) if band_stats else out
        nch = -(-n_pairs // CHUNK_PAIRS)
        pad = nch * CHUNK_PAIRS - n_pairs
        rows = jnp.concatenate([rows, jnp.full(pad, nt, jnp.int32)])
        cols = jnp.concatenate([cols, jnp.zeros(pad, jnp.int32)])

        def body(carry, rc):
            acc, st_acc = carry
            r, c = rc
            partial, st = one_call(r, c, eps2, arrays)
            return (merge(acc, partial, r), st_acc + st), None

        (acc, st), _ = jax.lax.scan(
            body,
            (acc0, jnp.zeros(2, jnp.int32)),
            (
                rows.reshape(nch, CHUNK_PAIRS),
                cols.reshape(nch, CHUNK_PAIRS),
            ),
        )
        return (acc, st) if band_stats else acc

    return call


def kernel_pair_list(
    points, eps, mask, block: int, precision, layout: str,
    budget: int | None = None, src_mask=None, sketch: int = 0,
):
    """Live tile-pair list sized to the kernels' OWN tile grid.

    The single place that knows how the Pallas kernels tile their input
    (``_pallas_block`` + ``_points_dn`` + ``_bounds_dn``): callers
    running several passes over one point set extract here once and
    hand ``pairs`` to every kernel call, guaranteed consistent with the
    grid the kernels build from the same arguments.  ``src_mask``
    optionally tightens the column boxes (row boxes always cover
    ``mask``).  Returns ``(rows, cols), (2,) int32 [total, budget]``;
    ``total > budget`` means the list was truncated and results built
    from it are invalid (retry with ``budget >= total``).

    ``sketch`` (a RESOLVED k, 0 = off): extract over (k+1)-dim slab
    boxes at the widened gate ``sqrt(eps^2 + band)`` instead of full-d
    boxes.  Sound standalone: ``d2 <= eps^2`` implies the slab distance
    ``t2 <= eps^2 + band`` (projection contracts plus the certified
    float band), so a slab box gap past the gate proves no in-eps pair
    — and k+1 ~ 17..65 gap dims prune far better per byte than d=512
    full-d boxes.  NEVER combine full-d and slab gaps additively
    (each test is only sound alone); the list here uses the slab test
    alone, which already subsumes most full-d pruning at high d.
    """
    from .distances import default_pair_budget, live_tile_pairs

    n, d = _shape_nd(points, layout)
    pb = _pallas_block(block, n, d, _norm_precision_mode(precision))
    nt = n // pb
    pts_dn = _points_dn(points, layout)
    gate = eps
    if sketch:
        band_mask = mask if src_mask is None else (mask | src_mask)
        slab, band = _sketch_stage(
            pts_dn, band_mask, sketch, _norm_precision_mode(precision)
        )
        gate = jnp.sqrt(jnp.asarray(eps, jnp.float32) ** 2 + band)
        box_src = slab
    else:
        box_src = pts_dn
    lo, hi = _bounds_dn(box_src, mask, nt, pb)
    if src_mask is None:
        lo_col, hi_col = None, None
    else:
        lo_col, hi_col = _bounds_dn(box_src, src_mask, nt, pb)
    if budget is None:
        budget = default_pair_budget(nt)
    budget = min(budget, nt * nt)
    rows, cols, total = live_tile_pairs(
        lo, hi, gate, lo_col, hi_col, budget=budget
    )
    return (rows, cols), jnp.stack([total, jnp.int32(budget)])


def _resolve_sketch_k(sketch, d):
    """Resolve a sketch spec to a concrete k for the Pallas kernels
    (Euclidean-only module, so the metric is fixed).  ``None`` defers
    to the ``PYPARDIS_SKETCH`` env default at TRACE time — the
    dispatch-knob precedent: the choice bakes into the compiled
    program, flips need ``jax.clear_caches()``."""
    from .sketch import resolve_sketch, sketch_dims

    if sketch is None:
        return sketch_dims(d, "euclidean")
    return resolve_sketch(sketch, d, "euclidean")


@functools.partial(
    jax.jit,
    static_argnames=("block", "precision", "interpret", "layout",
                     "sketch"),
)
def neighbor_counts_pallas(
    points: jnp.ndarray,
    eps,
    mask: jnp.ndarray,
    block: int = 1024,
    precision: str = "high",
    interpret: bool = False,
    layout: str = "nd",
    pairs=None,
    sketch: int | str | None = None,
) -> jnp.ndarray:
    """Pallas analogue of :func:`pypardis_tpu.ops.distances.neighbor_counts`
    (Euclidean only).

    ``pairs``: optional precomputed ``(rows, cols)`` live tile-pair
    list (row-major sorted; padding rows == nt) from
    :func:`kernel_pair_list` — callers running several passes over one
    point set (:func:`pypardis_tpu.ops.labels.dbscan_fixed_size`) share
    one list across all of them, and own overflow handling.  ``None``
    extracts here; if the default budget truncates the list, every
    count comes back -1 (loudly invalid, never silently low).

    With ``precision="mixed"`` the return widens to ``(counts,
    band_stats)`` — band_stats (2,) int32 ``[band_pairs,
    rescored_tiles]``; counts byte-identical to ``precision="high"``
    (the banded-rescore contract, see
    :mod:`pypardis_tpu.ops.precision`).

    ``sketch`` resolves like the dispatch knob (``None`` → env at
    trace time, see :func:`_resolve_sketch_k`); a resolved ``k > 0``
    also widens the return to ``(counts, band_stats)``, where the
    stats now count SKETCH-band pairs and rescored tiles — counts stay
    byte-identical to the unsketched pass (certified gates, exact
    rescore).
    """
    n, d = _shape_nd(points, layout)
    mode = _norm_precision_mode(precision)
    mixed = mode == "mixed"
    sk = _resolve_sketch_k(sketch, d)
    banded = mixed or sk > 0
    block = _pallas_block(block, n, d, mode)
    _check_mosaic_tile(block, n, interpret)
    nt = n // block
    pts_dn = _points_dn(points, layout)
    mask_t = mask.reshape(nt, 1, block)
    centers = _centers_dn(pts_dn, mask, nt, block)
    poison = None
    if pairs is None:
        pairs, stats = kernel_pair_list(
            points, eps, mask, block, precision, layout, sketch=sk
        )
        poison = stats[0] > stats[1]
    rows, cols = pairs
    eps2 = jnp.asarray(eps, jnp.float32).reshape(1) ** 2
    # Coordinates go in UNMASKED and UNTILED — the kernel blocks index
    # the (d, N) layout directly (column validity applies inside the
    # kernel from the tiny int32 mask blocks; padding pairs fetch
    # clamped real blocks and skip compute).  No dump-block concats,
    # no masked copy, no tile-transposed copy: the kernel program
    # carries NO dataset-sized temps at all.
    if sk:
        slab, sband = _sketch_stage(pts_dn, mask, sk, mode)
        kern = functools.partial(
            _count_pairs_sketch_kernel, mode=mode, nt=nt, k=sk
        )
        out = _pair_call(
            kern, nt, d, block, 1, interpret,
            identity=0, combine=jnp.add, band_stats=True,
            sketch_dim=slab.shape[0],
        )(rows, cols, jnp.stack([eps2[0], sband]), centers,
          pts_dn, pts_dn, slab, slab, mask_t.astype(jnp.int32))
    else:
        out = _pair_call(
            functools.partial(_count_pairs_kernel, mode=mode, nt=nt),
            nt, d, block, 1, interpret,
            identity=0, combine=jnp.add, band_stats=mixed,
        )(rows, cols, eps2, centers, pts_dn, pts_dn,
          mask_t.astype(jnp.int32))
    counts, band = out if banded else (out, None)
    counts = jnp.where(mask, counts[:nt].reshape(-1), 0)
    if poison is not None:
        counts = jnp.where(poison, -1, counts)
    if banded:
        return counts, band
    return counts


@functools.partial(
    jax.jit,
    static_argnames=("block", "precision", "interpret", "layout",
                     "sketch"),
)
def min_neighbor_label_pallas(
    points: jnp.ndarray,
    labels: jnp.ndarray,
    eps,
    src_mask: jnp.ndarray,
    block: int = 1024,
    precision: str = "high",
    interpret: bool = False,
    row_mask: jnp.ndarray | None = None,
    layout: str = "nd",
    pairs=None,
    sketch: int | str | None = None,
) -> jnp.ndarray:
    """Pallas analogue of
    :func:`pypardis_tpu.ops.distances.min_neighbor_label` (Euclidean).

    Labels travel as int32 with sentinel INT32_MAX.  Coordinates enter
    UNMASKED; both validity and source restriction to ``src_mask`` ride
    on the label sentinel (a non-source or invalid point's INT32_MAX
    never wins a min), so rows and columns share one array.  Rows
    outside ``row_mask`` return ARBITRARY values (their leftover
    coordinates may sit within eps of real points) — callers MUST mask
    them out, never test against the sentinel alone.  ``row_mask`` only
    tightens the per-tile pruning boxes; the default (``None``) covers
    ALL rows.  ``pairs`` as in :func:`neighbor_counts_pallas` (a pair
    list covering validity boxes is a superset of any src subset, so
    sharing one list is sound); a truncated self-extracted list poisons
    every row to INT32_MIN.

    With ``precision="mixed"`` the return widens to ``(best,
    band_stats)`` for signature uniformity with
    :func:`neighbor_counts_pallas` — but the stats here are always
    zeros: band telemetry is deterministic per pass and measured once,
    by the counts kernel; this kernel's in-band test only gates its
    own tile rescores.  A resolved ``sketch`` k > 0 widens the return
    the same way (zeros — same discipline).
    """
    n, d = _shape_nd(points, layout)
    mode = _norm_precision_mode(precision)
    mixed = mode == "mixed"
    sk = _resolve_sketch_k(sketch, d)
    banded = mixed or sk > 0
    block = _pallas_block(block, n, d, mode)
    _check_mosaic_tile(block, n, interpret)
    nt = n // block
    pts_dn = _points_dn(points, layout)
    if row_mask is None:
        rm_flat = jnp.ones(n, bool)
    else:
        rm_flat = row_mask
    centers = _centers_dn(pts_dn, rm_flat, nt, block)
    poison = None
    if pairs is None:
        pairs, stats = kernel_pair_list(
            points, eps, rm_flat, block, precision, layout,
            src_mask=src_mask, sketch=sk,
        )
        poison = stats[0] > stats[1]
    rows, cols = pairs
    labi = jnp.where(src_mask, labels, _INT_INF).reshape(nt, 1, block)
    eps2 = jnp.asarray(eps, jnp.float32).reshape(1) ** 2
    # Unmasked coordinates: source restriction and validity both ride
    # on the label sentinel (labi above — a non-source or invalid
    # point's INT32_MAX never wins a min), and rows outside row_mask
    # return garbage callers mask anyway.  No masked coordinate copy,
    # no dump-block concats (clamped index maps) — see
    # neighbor_counts_pallas.
    # No stats output on the propagation kernel: band stats come from
    # the counts pass (they are deterministic per pass); the minlab
    # kernel's in-band test only gates its rescore.
    if sk:
        # Band norm bound over rows AND sources: a tight row_mask must
        # not shrink the certified band below a high-norm src column's
        # float error.
        slab, sband = _sketch_stage(pts_dn, rm_flat | src_mask, sk, mode)
        best = _pair_call(
            functools.partial(
                _minlab_pairs_sketch_kernel, mode=mode, nt=nt, k=sk
            ),
            nt, d, block, 1, interpret,
            identity=_INT_INF, combine=jnp.minimum,
            sketch_dim=slab.shape[0],
        )(rows, cols, jnp.stack([eps2[0], sband]), centers,
          pts_dn, pts_dn, slab, slab, labi)
    else:
        best = _pair_call(
            functools.partial(_minlab_pairs_kernel, mode=mode, nt=nt),
            nt, d, block, 1, interpret,
            identity=_INT_INF, combine=jnp.minimum,
        )(rows, cols, eps2, centers, pts_dn, pts_dn, labi)
    best = best[:nt].reshape(-1)
    if poison is not None:
        best = jnp.where(poison, jnp.iinfo(jnp.int32).min, best)
    if banded:
        return best, jnp.zeros(2, jnp.int32)
    return best


# -- serving: out-of-sample query kernel ---------------------------------


def _query_leaf_kernel(leaf_ref, zero_ref, eps2_ref, q_ref, c_ref, lab_ref,
                       out_lab_ref, out_d2_ref, *, d, mode):
    """Grid (nqt, nb): query tile i folds column block j of its leaf's
    core slab into the running per-row (min d2, min label among ties).

    d^2 accumulates per axis in index order — the same IEEE float32 op
    sequence as :func:`pypardis_tpu.ops.query.axis_sq_dists`, each
    square sealed against FMA contraction with the prefetched runtime
    zero (``ops.query.seal_f32``) — so the result is bit-identical to
    the XLA path and the numpy oracle (the serving exactness contract).
    The MXU decomposition is deliberately not used for the SCORING
    pass: its accumulation order is backend-scheduled.  Pad core slots
    carry PAD_COORD (d^2 overflows to +inf) and INT32_MAX labels, so
    no mask enters the kernel at all.

    ``mode="mixed"`` adds the bf16-peak block pre-filter
    (:func:`pypardis_tpu.ops.query._fast_block_keep`): one DEFAULT MXU
    dot lower-bounds every pair's d^2 against the prefetched eps^2,
    and the expensive sealed VPU pass runs only for blocks that could
    hold a within-eps candidate — the final verdict is bitwise
    unchanged (a pruned block provably cannot contribute one).
    """
    from .query import _fast_block_keep, seal_f32

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_lab_ref[0] = jnp.full_like(out_lab_ref[0], _INT_INF)
        out_d2_ref[0] = jnp.full_like(out_d2_ref[0], jnp.inf)

    z = zero_ref[0]
    q = q_ref[0]  # (d, qb)
    c = c_ref[...]  # (d, block)

    def score():
        diff = q[0][:, None] - c[0][None, :]
        acc = seal_f32(diff * diff, z)
        for a in range(1, d):
            diff = q[a][:, None] - c[a][None, :]
            acc = acc + seal_f32(diff * diff, z)
        lb = lab_ref[0, 0, :]
        m = jnp.min(acc, axis=1)
        cand = jnp.min(
            jnp.where(acc == m[:, None], lb[None, :], _INT_INF), axis=1
        )
        bd2 = out_d2_ref[0, 0, :]
        bl = out_lab_ref[0, 0, :]
        take = (m < bd2) | ((m == bd2) & (cand < bl))
        out_d2_ref[0, 0, :] = jnp.where(take, m, bd2)
        out_lab_ref[0, 0, :] = jnp.where(take, cand, bl)

    if mode == "mixed":
        # Pad-robust block center: PAD_COORD slots (2e19) would poison
        # a plain max, so real slots are selected by magnitude first.
        # An all-pad block yields a NaN center -> NaN fast distances ->
        # keep is False, which is correct (pads can never win a min).
        real = c < jnp.float32(1e18)
        cmax = jnp.max(jnp.where(real, c, -jnp.inf), axis=1)
        cmin = jnp.min(jnp.where(real, c, jnp.inf), axis=1)
        ctr = (0.5 * (cmax + cmin))[:, None]

        @pl.when(_fast_block_keep(q, c, eps2_ref[0], ctr))
        def _():
            score()
    else:
        score()


@functools.partial(
    jax.jit, static_argnames=("block", "nb", "interpret", "precision")
)
def query_min_core_pallas(
    q, tile_leaf, coords, labels, zero_i32, eps2_f, *, block, nb,
    interpret=False, precision="high",
):
    """Pallas twin of :func:`pypardis_tpu.ops.query.query_min_core`.

    Same packed (2, nqt, qb) int32 result contract (labels +
    bitcast d2); the leaf indirection rides as scalar prefetch so each
    tile's BlockSpecs address its leaf's slab blocks directly (the
    block-sparse idiom of the fit kernels).  ``zero_i32``: a (1,) int32
    zero ARRAY from the caller — it must reach the kernel as a traced
    runtime value for the anti-FMA seal (``ops.query.seal_f32``) to
    survive compilation.  ``eps2_f``: a (1,) float32 eps^2 array
    (prefetched; consumed only by ``precision="mixed"``'s block
    pre-filter).  No box pruning inside — every block of the leaf's
    slab is visited in the non-mixed modes, which is semantically
    identical (pruning only skips provably-losing blocks) and keeps
    the kernel a pure reduction; ``"mixed"`` prunes blocks with one
    bf16 dot and rescores survivors through the identical sealed path,
    preserving the bitwise oracle contract.
    """
    mode = _norm_precision_mode(precision)
    nqt, d, qb = q.shape
    lab3 = labels.reshape(-1, 1, block)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(nqt, nb),
        in_specs=[
            pl.BlockSpec(
                (1, d, qb), lambda i, j, leaf, z, e: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (d, block), lambda i, j, leaf, z, e: (0, leaf[i] * nb + j),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block),
                lambda i, j, leaf, z, e: (leaf[i] * nb + j, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=(
            pl.BlockSpec(
                (1, 1, qb), lambda i, j, leaf, z, e: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, qb), lambda i, j, leaf, z, e: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ),
    )
    labs, d2 = pl.pallas_call(
        functools.partial(_query_leaf_kernel, d=d, mode=mode),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((nqt, 1, qb), jnp.int32),
            jax.ShapeDtypeStruct((nqt, 1, qb), jnp.float32),
        ),
        interpret=interpret,
    )(tile_leaf, zero_i32, eps2_f, q, coords, lab3)
    return jnp.stack([
        labs[:, 0, :],
        jax.lax.bitcast_convert_type(d2[:, 0, :], jnp.int32),
    ])
