"""The single source of truth for kernel precision modes.

Both backends map the same user surface — ``precision=`` on
``DBSCAN`` / ``dbscan_fixed_size`` / the serving engine — onto their
kernels, and until this module they normalized it independently
(``ops.distances._norm_precision`` vs
``ops.pallas_kernels._norm_precision_mode``), which is exactly how a
new mode could silently drift between them.  Everything precision-
related that must agree across backends lives here:

* the mode ladder and its normalizer (strings and
  ``jax.lax.Precision`` spellings);
* the bf16 single-pass error bound behind ``precision="mixed"``'s
  band classification — the one constant both the XLA scan kernels,
  the Mosaic pair-list kernels, and the serving query kernels must
  derive their rescore band from, or the "byte-identical to high"
  contract silently breaks on one backend only.

The ladder (fastest → most exact):

``"default"``
    One bf16 MXU pass.  ~2^-8-relative d^2 error — opt-in lossy.
``"mixed"``
    One bf16 MXU pass PLUS an exact rescore of every tile containing a
    pair whose fast d^2 lands within the conservative error band of
    eps^2 (:func:`band_halfwidth`).  Labels are byte-identical to
    ``"high"`` by construction — the band bound guarantees every
    fast-pass verdict outside the band matches the high-precision
    verdict, and in-band tiles recompute at ``"high"`` outright.
``"high"``
    bf16_3x (three bf16 passes synthesizing ~fp32).  The default.
``"highest"``
    Native fp32 — the exact fallback for adversarially scaled data.
"""

from __future__ import annotations

PRECISION_MODES = ("default", "high", "highest", "mixed")

# bf16 has 8 explicit mantissa bits: unit roundoff 2^-9 under
# round-to-nearest; a product of two rounded operands carries
# <= (2*2^-9 + 2^-18) ~ 2^-8 relative error per term.
BF16_EPS = 2.0 ** -8

# Safety margin on the analytic fast-pass bound (band_halfwidth): the
# analytic terms are already worst-case (every rounding conspiring in
# one direction, Cauchy-Schwarz at the per-tile maxima), so 25% slack
# is generous; it also absorbs the bf16_3x rescore's own dropped-term
# error (~2^-18-relative — 500x below the fast band) when the rescore
# runs in the same recentred frame.
_BAND_SAFETY = 1.25

# Width of the in-band pair-stats row every kernel route emits:
# [live_pairs_total, budget, kernel_passes, band_pairs, rescored_tiles].
# The last two are zero on every non-mixed precision mode.
PAIR_STATS_WIDTH = 5


def norm_precision_mode(precision) -> str:
    """Normalize any accepted precision spelling to a canonical mode.

    Accepts the mode strings (any case) and the three
    ``jax.lax.Precision`` enum values (which map onto the non-mixed
    rungs).  Raises ValueError otherwise — this is the error message
    every entry point shows, so the accepted surface cannot drift
    between backends.
    """
    import jax

    if isinstance(precision, jax.lax.Precision):
        return {
            jax.lax.Precision.DEFAULT: "default",
            jax.lax.Precision.HIGH: "high",
            jax.lax.Precision.HIGHEST: "highest",
        }[precision]
    p = str(precision).lower()
    if p not in PRECISION_MODES:
        raise ValueError(
            f"precision must be one of {PRECISION_MODES} (or a "
            f"jax.lax.Precision), got {precision!r}"
        )
    return p


def band_halfwidth(nx, ny):
    """Conservative bound on ``|d2_fast - d2_true|`` for one bf16 pass.

    ``nx``/``ny``: EUCLIDEAN NORM bounds of the two operand point sets
    *in the frame the fast pass computes in* — per-tile maxima of
    ``|x - c|`` after recentring in the fit kernels, per-point norms
    in the serving kernels (pad slots there carry astronomically large
    coordinates, and a per-element band keeps one pad from poisoning a
    whole tile's bound).  Broadcasting follows the operands.

    Derivation.  Both single-pass forms — the plain ``|x|^2 + |y|^2 -
    2 x.y`` (norms in f32, only the dot in bf16) and the Mosaic
    kernels' augmented-operand dot ``[-2(y-c); 1; |y-c|^2]^T [x-c;
    |x-c|^2; 1]`` — lose accuracy to bf16 operand rounding:

    * coordinate products: each operand entry rounds with relative
      error <= 2^-9, so a product term carries <= ~2^-8 |x_a||y_a|;
      summed over axes, Cauchy-Schwarz gives ``sum_a |x_a||y_a| <=
      |x||y| <= nx*ny`` — with the 2x coefficient of the cross term
      that is ``2^-7 * nx * ny`` (NOT d * max-coordinate^2: the norm
      bound is a factor ~d tighter on isotropic data, which is what
      keeps the band a few percent of eps^2 instead of covering it);
    * the augmented form's |.|^2 rows round once each:
      ``<= 2^-9 * (nx^2 + ny^2)`` (the paired "1" entries are exact
      in bf16, so these terms never multiply each other);
    * f32 MXU accumulation adds ~2^-23-relative dust.

    The returned bound covers both forms with _BAND_SAFETY margin::

        band = 1.25 * (2^-7 * nx * ny + 2^-9 * (nx^2 + ny^2))

    Any pair whose fast d^2 lands further than ``band`` (plus
    :func:`exact_slack` when the rescore runs in a different frame)
    from eps^2 provably has the same within-eps verdict as the exact
    pass — that is the entire exactness argument of
    ``precision="mixed"``.
    """
    return _BAND_SAFETY * (
        2.0 * BF16_EPS * nx * ny
        + 0.5 * BF16_EPS * (nx * nx + ny * ny)
    )


def exact_slack(nx, ny):
    """Error bound of the EXACT pass itself, in its own frame.

    Added to :func:`band_halfwidth` when the rescore pass computes in
    a different coordinate frame than the fast pass (the XLA fit
    kernels rescore in the global dataset frame while the fast pass is
    tile-recentred; the serving kernels rescore through the sealed
    axis-ordered f32 sum in the index frame).  Covers both the bf16_3x
    dropped-term error (~2^-17 nx ny) and f32 cancellation in
    ``|x|^2+|y|^2-2xy`` at frame magnitudes (~2^-21 (nx+ny)^2)::

        slack = 2^-16 * (nx + ny)^2
    """
    s = nx + ny
    return (2.0 ** -16) * s * s
