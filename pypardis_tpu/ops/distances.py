"""Tiled eps-neighborhood primitives.

The reference delegates the eps-radius region query to sklearn's ball
tree / brute force inside each Spark partition
(``/root/reference/dbscan/dbscan.py:28-30``).  On TPU the same query is a
streamed block-pairwise computation: squared Euclidean distances decompose
into ``|x|^2 + |y|^2 - 2 x @ y.T`` so the dominant cost is a matmul on the
MXU; the (rows x cols) tile is consumed immediately by a compare-and-reduce
so the N x N interaction never hits HBM.

Layout: XLA:TPU tiles the last two axes of every buffer to (8, 128), so a
``(N, d)`` coordinate array with small d is padded 8x in HBM (d=16 ->
128 lanes) — the round-1 memory wall at 10M+ points.  All internal tile
representations here are therefore **transposed**: ``(nt, d, block)``
with the big point axis minor, which is dense for any d.  Public entry
points accept the conventional ``(N, d)`` (``layout="nd"``) or the
memory-optimal ``(d, N)`` (``layout="dn"``) and normalize immediately.

Everything here is shape-static and jit/shard_map-safe: callers pad point
sets to a fixed capacity and pass a validity mask.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import envreg

_INT_INF = jnp.iinfo(jnp.int32).max
# Finite stand-in for +/-inf in tile bounding boxes: differences of two
# bounds must not produce inf-inf NaNs.
_BIG = np.float32(3e38)  # numpy scalar: trace-inert at import time

_PRECISIONS = {
    "default": jax.lax.Precision.DEFAULT,
    "high": jax.lax.Precision.HIGH,
    "highest": jax.lax.Precision.HIGHEST,
}


def _norm_precision(precision):
    """MXU precision for a SINGLE distance matmul.

    fp32 matmuls on TPU are synthesized from bfloat16 passes: ``high``
    (bf16_3x, ~fp32-accurate, 2x faster than ``highest``) is the default;
    ``highest`` is the exact fp32 fallback for adversarially scaled data.
    Normalization delegates to the shared mode ladder
    (:mod:`pypardis_tpu.ops.precision`) so the accepted surface cannot
    drift between backends; ``"mixed"`` is a TWO-pass discipline and is
    dispatched above this level — a mixed mode reaching a single dot is
    a plumbing bug, reported as such.
    """
    from .precision import norm_precision_mode

    mode = norm_precision_mode(precision)
    if mode == "mixed":
        raise ValueError(
            "precision='mixed' is a banded two-pass mode and cannot "
            "select a single matmul precision; use neighbor_counts / "
            "min_neighbor_label with precision='mixed' (internal "
            "dispatch error if you did)"
        )
    return _PRECISIONS[mode]


def _norm_metric(metric) -> str:
    """Accept reference-style metric spec: string or scipy callable.

    The reference takes a *callable* defaulting to
    ``scipy.spatial.distance.euclidean`` and documents that only
    Euclidean / cityblock are safe because box expansion is L-inf
    (dbscan.py:74-91).  We accept those callables by name plus the usual
    string spellings.
    """
    if callable(metric):
        metric = getattr(metric, "__name__", str(metric))
    metric = str(metric).lower()
    if metric in ("euclidean", "l2"):
        return "euclidean"
    if metric == "sqeuclidean":
        # sqeuclidean thresholds *squared* distance at eps — silently
        # aliasing it to euclidean would change eps semantics.
        raise ValueError(
            "metric 'sqeuclidean' is not supported: its eps thresholds "
            "squared distance; use metric='euclidean' with eps=sqrt(eps)"
        )
    if metric in ("cityblock", "manhattan", "l1"):
        return "cityblock"
    if metric in ("cosine", "angular"):
        # Cosine is a DRIVER-level metric: DBSCAN unit-normalizes the
        # rows and remaps eps onto the L2 kernels (on the unit sphere
        # d^2 = 2 - 2*cos(theta), monotone in angular distance, so the
        # existing kernels serve it exactly).  The kernels themselves
        # are L2/L1-only and must never see it.
        raise ValueError(
            "metric 'cosine' is served at the driver level (unit-"
            "normalization + eps remap — use DBSCAN(metric='cosine')); "
            "the tiled kernels are euclidean/cityblock only (internal "
            "dispatch error if a driver passed it through)"
        )
    raise ValueError(
        f"unsupported metric {metric!r}: TPU path supports euclidean and "
        "cityblock (the reference documents the same restriction, "
        "dbscan.py:88-91)"
    )


def _norm_layout(layout: str) -> str:
    if layout not in ("nd", "dn"):
        raise ValueError(f"layout must be 'nd' or 'dn', got {layout!r}")
    return layout


def pairwise_sq_dists(
    x: jnp.ndarray, y: jnp.ndarray, precision="highest"
) -> jnp.ndarray:
    """(n, d) x (m, d) → (n, m) squared Euclidean distances (one tile)."""
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    xx = jnp.sum(x * x, axis=1, keepdims=True)
    yy = jnp.sum(y * y, axis=1, keepdims=True)
    d2 = xx + yy.T - 2.0 * jax.lax.dot(
        x, y.T, precision=_norm_precision(precision)
    )
    return jnp.maximum(d2, 0.0)


def _tile_d2_t(xi, yj, precision):
    """(d, br) x (d, bc) transposed tiles → (br, bc) f32 squared
    distances via the |x|^2+|y|^2-2xy matmul expansion at the given
    single-dot precision."""
    xx = jnp.sum(xi * xi, axis=0)[:, None]
    yy = jnp.sum(yj * yj, axis=0)[None, :]
    return xx + yy - 2.0 * jax.lax.dot_general(
        xi, yj, (((0,), (0,)), ((), ())),
        precision=_norm_precision(precision),
        preferred_element_type=jnp.float32,
    )


def _tile_adjacency_t(xi, yj, eps, metric, precision):
    """(d, br) x (d, bc) transposed tiles → (br, bc) bool: within eps."""
    if metric == "euclidean":
        return _tile_d2_t(xi, yj, precision) <= eps * eps
    # cityblock: no matmul decomposition; broadcast |xi - yj| sum on VPU.
    d1 = jnp.sum(jnp.abs(xi[:, :, None] - yj[:, None, :]), axis=0)
    return d1 <= eps


def _fast_is_exact() -> bool:
    """Whether Precision.DEFAULT already IS the exact f32 dot on this
    backend — true on CPU, where XLA ignores the precision config and
    every dot runs one f32 pass.  The mixed rescore is then provably a
    bitwise no-op (same dot, same schedule), so the XLA kernels skip
    dispatching it; ``rescored_tiles`` still counts the tiles whose
    pairs REQUIRED exact verdicts (the classification is backend-
    independent, which keeps CI band telemetry predictive of the
    chip).  GPU stays conservative: DEFAULT may run TF32 there."""
    return jax.default_backend() == "cpu"


def _nmax_t(pts, valid):
    """Masked per-tile norm maximum: max Euclidean point norm of the
    (d, block) tile over ``valid`` slots."""
    return jnp.sqrt(jnp.max(jnp.where(
        valid, jnp.sum(pts * pts, axis=0), 0.0
    )))


def _mixed_band_t(xi, yj, c, row_valid, col_valid):
    """The mixed-mode classification band for one tile pair: the
    shared bf16 fast-pass bound at the masked RECENTRED norm maxima
    (padding slots — zeros, which sit at global-frame magnitude after
    recentring — are masked out of the bounds) plus the global-frame
    slack of the uncentred high rescore."""
    from .precision import band_halfwidth, exact_slack

    return band_halfwidth(
        _nmax_t(xi - c, row_valid), _nmax_t(yj - c, col_valid)
    ) + exact_slack(
        _nmax_t(xi, row_valid), _nmax_t(yj, col_valid)
    )


def _tile_adjacency_mixed_t(xi, yj, eps2, c, row_valid, col_valid,
                            collect_stats=True):
    """Banded mixed-precision adjacency for one tile pair.

    On a lossy-DEFAULT backend (TPU): the fast pass recentres both
    tiles on ``c`` (the row tile's box center, (d, 1)) so bf16 operand
    magnitudes are tile-local — the same trick the Mosaic kernels
    apply — and classifies every pair against ``eps2 +- band``
    (:func:`_mixed_band_t`).  Only a tile containing an in-band
    ("ambiguous") valid pair recomputes at ``high`` (bf16_3x, in the
    ORIGINAL frame — bitwise the same arithmetic the plain
    ``precision="high"`` pass runs) and uses those distances for the
    WHOLE tile.  Out-of-band fast verdicts provably match the high
    verdicts (:mod:`pypardis_tpu.ops.precision`), so the returned
    adjacency is byte-identical to ``_tile_adjacency_t(...,
    precision="high")`` on every valid element — the exactness
    contract of ``precision="mixed"``.

    On an exact-DEFAULT backend (CPU — :func:`_fast_is_exact`): the
    single uncentred DEFAULT dot already IS the high pass bitwise, so
    verdicts come straight from it and the band machinery runs only
    when ``collect_stats`` asks for telemetry — classification is
    identical either way, the pair verdicts never depend on it.

    ``collect_stats``: band stats are deterministic per (points, eps,
    layout) — every pass over the same live pairs classifies them
    identically — so the drivers measure them ONCE, on the counts
    pass, and the propagation passes skip the bookkeeping
    (``collect_stats=False``); on lossy backends those passes still
    compute the in-band test, because it gates their rescore.

    Returns ``(adj & col_valid, n_band_pairs, rescored)``; stats and
    the rescore decision are masked to valid rows x valid cols, so
    padding slots can neither inflate the band telemetry nor force a
    rescore.
    """
    stat_mask = row_valid[:, None] & col_valid[None, :]
    if _fast_is_exact():
        d2 = _tile_d2_t(xi, yj, "default")  # == the high pass, bitwise
        n_band = resc = jnp.int32(0)
        if collect_stats:
            band = _mixed_band_t(xi, yj, c, row_valid, col_valid)
            ambig = (jnp.abs(d2 - eps2) <= band) & stat_mask
            n_band = jnp.sum(ambig, dtype=jnp.int32)
            resc = (n_band > 0).astype(jnp.int32)
        return (d2 <= eps2) & col_valid[None, :], n_band, resc

    d2f = _tile_d2_t(xi - c, yj - c, "default")
    band = _mixed_band_t(xi, yj, c, row_valid, col_valid)
    ambig = (jnp.abs(d2f - eps2) <= band) & stat_mask
    if collect_stats:
        n_band = jnp.sum(ambig, dtype=jnp.int32)
        need = n_band > 0
    else:
        n_band = jnp.int32(0)
        need = jnp.any(ambig)
    d2 = jax.lax.cond(
        need, lambda: _tile_d2_t(xi, yj, "high"), lambda: d2f
    )
    resc = need.astype(jnp.int32) if collect_stats else jnp.int32(0)
    return (d2 <= eps2) & col_valid[None, :], n_band, resc


def _sketch_slab_t(pts, q):
    """(nt, d, block) tiles x (d, k) projection → (nt, k+1, block)
    sketch slabs: rows 0..k-1 the HIGHEST-precision projection
    ``Q^T x``, row k the orthogonal-residual norm ``r = sqrt(|x|^2 -
    |Q^T x|^2)`` (clamped at 0).  Each slab column depends only on its
    own point column, so slabs computed by different drivers over
    different stagings of the same points are interchangeable
    classification evidence — the mu=0 frame discipline: no internal
    recentring, the drivers' global centering is what keeps magnitudes
    (and hence :func:`pypardis_tpu.ops.sketch.sketch_gate_band`)
    small, and correctness never depends on it."""
    proj = jax.lax.dot_general(
        q, pts, (((0,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    ).transpose(1, 0, 2)
    full = jnp.sum(pts * pts, axis=1, keepdims=True)
    res = jnp.sqrt(jnp.maximum(
        full - jnp.sum(proj * proj, axis=1, keepdims=True), 0.0
    ))
    return jnp.concatenate([proj, res], axis=1)


def _global_nmax(pts, msk):
    """Masked Euclidean norm maximum over a whole (nt, d, block) slab."""
    n2 = jnp.sum(pts * pts, axis=1)
    return jnp.sqrt(jnp.max(jnp.where(msk, n2, 0.0)))


def _sketch_setup(pts, msk, sk, precision):
    """Shared sketch-pass staging for one kernel invocation: the
    (d, k) projection (a trace-time numpy constant — seeded, cached,
    identical on every host), the (nt, k+1, block) slabs, and the
    certified classification band at the slab's masked norm maximum.
    Returns ``(slab, band)``."""
    from .sketch import sketch_gate_band, sketch_matrix

    d = pts.shape[1]
    q, eta = sketch_matrix(d, sk)
    slab = _sketch_slab_t(pts, jnp.asarray(q))
    band = sketch_gate_band(
        _global_nmax(pts, msk), d, sk, eta,
        precision=precision, fast_exact=_fast_is_exact(),
    )
    return slab, band


def _tile_adjacency_sketch_t(
    xi, yj, si, sj, eps, eps2, band, c, row_valid, col_valid,
    precision, mixed, collect_stats=True,
):
    """Sketch-prefiltered adjacency for one tile pair (euclidean only).

    The (k+1)-dim slab distance ``t2`` LOWER-bounds the full-d ``d2``
    and ``t2 + 4*ri*rj`` UPPER-bounds it (the residual vectors live in
    the orthogonal complement of the sketch subspace, so they meet the
    projected difference at right angles), with every float/
    orthogonality defect absorbed into ``band``
    (:func:`pypardis_tpu.ops.sketch.sketch_gate_band`).  Pairs outside
    ``eps2 +- band`` therefore classify certifiably from the slab
    alone; a tile containing an in-band valid pair rescores the WHOLE
    tile with the unchanged full-d kernel arithmetic (the
    ``precision='mixed'`` machinery when the caller runs mixed —
    itself byte-identical to ``'high'``).  Non-rescored tiles take the
    certified in-gate as adjacency.  Labels are byte-identical to the
    unsketched pass for ANY k — the sketch only decides WHERE the
    exact arithmetic runs, never what it concludes.

    Returns ``(adj & col_valid, n_band_pairs, rescored)`` shaped like
    :func:`_tile_adjacency_mixed_t` — the PAIR_STATS band columns are
    reused wholesale: under sketch they count sketch-band pairs and
    sketch-rescored tiles.
    """
    stat_mask = row_valid[:, None] & col_valid[None, :]
    t2 = _tile_d2_t(si, sj, "highest")
    up = t2 + 4.0 * si[-1][:, None] * sj[-1][None, :]
    sure_in = up <= eps2 - band
    sure_out = t2 - band > eps2
    ambig = ~(sure_in | sure_out) & stat_mask
    if collect_stats:
        n_band = jnp.sum(ambig, dtype=jnp.int32)
        need = n_band > 0
    else:
        n_band = jnp.int32(0)
        need = jnp.any(ambig)

    def rescore():
        if mixed:
            adj, _nb, _rs = _tile_adjacency_mixed_t(
                xi, yj, eps2, c, row_valid, col_valid,
                collect_stats=False,
            )
            return adj
        return (
            _tile_adjacency_t(xi, yj, eps, "euclidean", precision)
            & col_valid[None, :]
        )

    adj = jax.lax.cond(
        need, rescore, lambda: sure_in & col_valid[None, :]
    )
    resc = need.astype(jnp.int32) if collect_stats else jnp.int32(0)
    return adj, n_band, resc


def _tiles_t(points, mask, block, layout):
    """Normalize to transposed tiles: (nt, d, block) + (nt, block) mask."""
    if layout not in ("nd", "dn"):
        raise ValueError(f"layout must be 'nd' or 'dn', got {layout!r}")
    if layout == "nd":
        n, d = points.shape
        assert n % block == 0, (n, block)
        nt = n // block
        pts = points.reshape(nt, block, d).transpose(0, 2, 1)
    else:
        d, n = points.shape
        assert n % block == 0, (n, block)
        nt = n // block
        pts = points.reshape(d, nt, block).transpose(1, 0, 2)
    msk = mask.reshape(nt, block)
    return nt, pts, msk


def tile_bounds(pts: jnp.ndarray, msk: jnp.ndarray):
    """Per-tile bounding boxes: (nt, d, block) transposed tiles + (nt,
    block) mask → (nt, d) lower / upper bounds over valid points.

    Empty tiles get an inverted box (lo=+BIG, hi=-BIG) whose gap to any
    other box is huge, so they are pruned automatically.
    """
    valid = msk[:, None, :]
    lo = jnp.min(jnp.where(valid, pts, _BIG), axis=2)
    hi = jnp.max(jnp.where(valid, pts, -_BIG), axis=2)
    return lo, hi


def tile_skip_mask(lo_i, hi_i, lo, hi, eps, metric):
    """Which column tiles cannot contain an eps-neighbor of row tile i.

    ``lo_i``/``hi_i``: (d,) bounds of the row tile; ``lo``/``hi``:
    (nt, d) bounds of all column tiles.  Returns (nt,) bool skip mask —
    True where the minimum box-to-box distance exceeds eps.  This is the
    tile-level analogue of the reference's expanded-box membership filter
    (dbscan.py:146-147): spatial locality makes the N^2 interaction
    sparse at the tile level.
    """
    gap = jnp.maximum(
        0.0, jnp.maximum(lo - hi_i[None, :], lo_i[None, :] - hi)
    )
    if metric == "euclidean":
        return jnp.sum(gap * gap, axis=1) > eps * eps
    return jnp.sum(gap, axis=1) > eps


def count_live_tile_pairs(
    points: jnp.ndarray,
    mask: jnp.ndarray,
    eps,
    metric: str = "euclidean",
    block: int = 1024,
    layout: str = "nd",
) -> jnp.ndarray:
    """Scalar int32: total (row, col) tile pairs the gap test keeps.

    The XLA-path analogue of the Pallas extraction's true pair total
    (:func:`live_tile_pairs`): exactly the column-tile visits the tiled
    passes will compute.  The XLA kernels never drop pairs, so this is
    purely diagnostic/reporting — it lets the drivers' budget-overflow
    ladder (and its tests) exercise off-TPU, where Mosaic is absent.
    Row tiles are processed in CHUNKS (a scan of ~nt/chunk batched gap
    tests, the live_tile_pairs memory discipline), not one sequential
    dispatch per row — per-row lax.map at nt~10k would re-create the
    serialized-scan overhead the extraction was restructured to avoid.
    """
    metric = _norm_metric(metric)
    layout = _norm_layout(layout)
    nt, pts, msk = _tiles_t(points, mask, block, layout)
    d = pts.shape[1]
    lo, hi = tile_bounds(pts, msk)
    # (chunk, nt, d) gap tensor bounded ~256MB, like live_tile_pairs.
    chunk = max(1, min(nt, -(-(1 << 26) // max(nt * d, 1))))
    nc = -(-nt // chunk)
    # Padding rows carry inverted boxes: their gap to anything is
    # astronomically positive, so they never count as live.
    lo_p, hi_p = _pad_boxes(lo, hi, nc * chunk)

    def body(acc, c):
        s = c * chunk
        rlo = jax.lax.dynamic_slice_in_dim(lo_p, s, chunk)
        rhi = jax.lax.dynamic_slice_in_dim(hi_p, s, chunk)
        gap = jnp.maximum(
            0.0,
            jnp.maximum(lo[None] - rhi[:, None], rlo[:, None] - hi[None]),
        )
        if metric == "euclidean":
            live = jnp.sum(gap * gap, axis=-1) <= jnp.float32(eps) ** 2
        else:
            live = jnp.sum(gap, axis=-1) <= eps
        return acc + jnp.sum(live.astype(jnp.int32)), None

    total, _ = jax.lax.scan(body, jnp.int32(0), jnp.arange(nc))
    return total


def cross_tile_live(
    lo_r: jnp.ndarray,
    hi_r: jnp.ndarray,
    lo_c: jnp.ndarray,
    hi_c: jnp.ndarray,
    eps,
    metric: str = "euclidean",
) -> jnp.ndarray:
    """(nt_r,) bool: row tile i's box lies within eps of ANY column box.

    The boundary-tile selector of the global-Morton distributed mode
    (:mod:`pypardis_tpu.parallel.global_morton`): row boxes are one
    shard's kernel tiles, column boxes another shard's (or every other
    shard's, all-gathered).  A column tile whose box clears eps of every
    row box cannot contain an eps-neighbor of any row point (the same
    box-gap bound :func:`tile_skip_mask` uses), so the row shard never
    needs it — this predicate is what keeps the ring exchange at tile
    granularity instead of whole halo slabs.  Inverted (+BIG, -BIG)
    boxes — empty tiles, padding, the caller's own tiles — are never
    live.  Chunked like :func:`count_live_tile_pairs` so the
    (chunk, nc, d) gap tensor stays ~256MB at any tile count.
    """
    metric = _norm_metric(metric)
    nt, d = lo_r.shape
    nc = lo_c.shape[0]
    chunk = max(1, min(nt, -(-(1 << 26) // max(nc * d, 1))))
    nch = -(-nt // chunk)
    lo_p, hi_p = _pad_boxes(lo_r, hi_r, nch * chunk)

    def body(carry, c):
        s = c * chunk
        rlo = jax.lax.dynamic_slice_in_dim(lo_p, s, chunk)
        rhi = jax.lax.dynamic_slice_in_dim(hi_p, s, chunk)
        gap = jnp.maximum(
            0.0,
            jnp.maximum(lo_c[None] - rhi[:, None], rlo[:, None] - hi_c[None]),
        )
        if metric == "euclidean":
            live = jnp.sum(gap * gap, axis=-1) <= jnp.float32(eps) ** 2
        else:
            live = jnp.sum(gap, axis=-1) <= eps
        return carry, jnp.any(live, axis=1)

    _, liv = jax.lax.scan(body, jnp.int32(0), jnp.arange(nch))
    return liv.reshape(-1)[:nt]


def default_pair_budget(nt: int) -> int:
    """Default live-pair capacity: 48 pairs per row tile.

    Morton-sorted, segment-broken layouts measure ~9-29 live column
    tiles per row (2M x 16-D constant-density probe); 48 gives slack
    without inflating the scatter arrays (budget * 8 bytes).  Callers
    detect overflow via the returned true total and retry with an exact
    budget.
    """
    return max(4096, 48 * nt)


# Tiles per group in the two-level extraction.  Small on purpose: a
# group's box is the union of its tiles' boxes, and a union spanning
# several Morton segments (= unrelated clusters) covers so much space
# that group pruning stops working — measured 37% of all group pairs
# live at 10M x 16-D with 16-tile groups.  4-tile groups combined with
# group-aligned segment padding (pipeline._segment_break_layout) keep
# every group inside one segment.
PAIR_GROUP = 4


def _csr_scan(live_fn, rid_fn, cid_fn, nc, budget, dump_row):
    """Chunked compaction of a virtual boolean matrix into (rows, cols).

    ``live_fn(c)`` -> flat bool chunk c; ``rid_fn``/``cid_fn(c)`` ->
    the int32 ids each flat slot maps to.  Emits the True slots' ids in
    scan order into static-length ``budget`` arrays (padding: row ==
    dump_row, col == 0) plus the TRUE total.  Live entries past the
    budget land on the dump slot — dropped, signalled via total >
    budget.
    """

    def body(carry, c):
        rows_out, cols_out, total = carry
        live = live_fn(c)
        inc = jnp.cumsum(live.astype(jnp.int32))
        pos = total + inc - live  # exclusive running position
        tgt = jnp.where(live, jnp.minimum(pos, budget), budget)
        rows_out = rows_out.at[tgt].set(rid_fn(c))
        cols_out = cols_out.at[tgt].set(cid_fn(c))
        return (rows_out, cols_out, total + inc[-1]), None

    init = (
        jnp.full(budget + 1, dump_row, jnp.int32),
        jnp.zeros(budget + 1, jnp.int32),
        jnp.int32(0),
    )
    (rows_out, cols_out, total), _ = jax.lax.scan(
        body, init, jnp.arange(nc)
    )
    return rows_out[:budget], cols_out[:budget], total


def _pad_boxes(lo, hi, n_to):
    pad = max(0, n_to - lo.shape[0])
    return (
        jnp.concatenate([lo, jnp.full((pad, lo.shape[1]), _BIG)]),
        jnp.concatenate([hi, jnp.full((pad, hi.shape[1]), -_BIG)]),
    )


def live_tile_pairs(
    lo: jnp.ndarray,
    hi: jnp.ndarray,
    eps,
    lo_col: jnp.ndarray | None = None,
    hi_col: jnp.ndarray | None = None,
    budget: int | None = None,
):
    """Row-major list of tile pairs whose bounding boxes lie within eps.

    ``lo``/``hi``: (nt, d) row-tile bounds; ``lo_col``/``hi_col``
    default to the same boxes.  Returns ``(rows, cols, total)`` with
    ``rows``/``cols`` of static length ``budget`` (padding entries:
    row == nt, col == 0 — callers give the kernel an (nt+1)-row dump
    output) and ``total`` the TRUE live-pair count.  When ``total >
    budget`` the excess pairs were dropped — results built from the
    list are invalid and the caller must retry with ``budget >=
    total`` (the count is exact, so one retry always suffices for the
    same inputs).

    This is the tile-pruning stage of the Pallas path, hoisted out of
    the kernel (the round-3 kernels carried the scan as an O(nt^2)
    sequential scalar loop — 4.2s/pass of pure overhead at 10M
    points).  It is itself two-level, because the flat (nt x nt) gap
    matrix is quadratic too (measured 29s at nt=49k): group-of-16
    boxes prune first, and only surviving group pairs expand to the
    16x16 tile-pair test.  Soundness: a tile box is contained in its
    group's box, so box-min-distance(groups) <= box-min-distance
    (tiles) — a live tile pair can never hide behind a pruned group
    pair.  Empty/padding tiles carry inverted (+BIG, -BIG) boxes whose
    gap to anything is astronomically positive.
    """
    nt, d = lo.shape
    if lo_col is None:
        lo_col, hi_col = lo, hi
    if budget is None:
        budget = default_pair_budget(nt)
    # nt^2 is the exhaustive list — a budget past it is pure waste, and
    # clamping makes small-nt extractions overflow-proof by construction.
    budget = min(budget, nt * nt)
    eps2 = jnp.asarray(eps, jnp.float32) ** 2
    G = PAIR_GROUP
    ng = -(-nt // G)
    # Per-tile boxes padded to full groups, plus one inverted dump
    # group at index ng (the group-pair list pads rows there).
    tlo_r, thi_r = _pad_boxes(lo, hi, (ng + 1) * G)
    tlo_c, thi_c = _pad_boxes(lo_col, hi_col, (ng + 1) * G)
    glo_r = tlo_r.reshape(ng + 1, G, d).min(axis=1)
    ghi_r = thi_r.reshape(ng + 1, G, d).max(axis=1)
    glo_c = tlo_c.reshape(ng + 1, G, d).min(axis=1)
    ghi_c = thi_c.reshape(ng + 1, G, d).max(axis=1)

    def box_gap_live(rlo, rhi, clo, chi):
        gap = jnp.maximum(
            0.0, jnp.maximum(clo - rhi[..., None, :], rlo[..., None, :] - chi)
        )
        return jnp.sum(gap * gap, axis=-1) <= eps2

    # Level 1: live group pairs.  Looser group boxes can pair where no
    # tile pair is live, so the group list needs headroom ABOVE the
    # tile budget (at 10M x 16-D: 192k live group pairs vs 120k live
    # tile pairs) — 2x covers the observed ratio with margin.  An
    # earlier budget//2 sizing inverted this: at 30M x 16-D the 1.66M
    # true group pairs overflowed the 1.4M group budget, inflating the
    # returned total to the saturated g_need bound (26.6M vs 1.7M true
    # tile pairs) and sending every fit through a 10x-oversized retry.
    # Memory is two budget_g int32 rows — negligible.  A genuine
    # overflow still folds into the returned total (same caller retry).
    budget_g = min(max(2 * budget, 8192), ng * ng)
    # Chunk so the (chunk, ng, d) gap tensor stays ~256MB — the d
    # factor matters: at 512-D an un-scaled chunk materialized 8.6GB
    # and OOM'd the chip.  (At d=16 this reduces to the old 1<<22/ng.)
    chunk_g = max(1, min(ng, -(-(1 << 26) // max(ng * d, 1))))
    nc_g = -(-ng // chunk_g)
    # Row-side group boxes padded to whole chunks with inverted boxes:
    # dynamic_slice CLAMPS an out-of-range start, which would misalign
    # the last chunk's live mask against its row ids and silently drop
    # real pairs (while underreporting the total).
    glo_rp, ghi_rp = _pad_boxes(glo_r, ghi_r, nc_g * chunk_g)

    def live_g(c):
        s = c * chunk_g
        rlo = jax.lax.dynamic_slice_in_dim(glo_rp, s, chunk_g)
        rhi = jax.lax.dynamic_slice_in_dim(ghi_rp, s, chunk_g)
        return box_gap_live(rlo, rhi, glo_c[None, :ng], ghi_c[None, :ng]
                            ).reshape(-1)

    def rid_g(c):
        return jnp.broadcast_to(
            c * chunk_g + jnp.arange(chunk_g, dtype=jnp.int32)[:, None],
            (chunk_g, ng),
        ).reshape(-1)

    def cid_g(c):
        return jnp.broadcast_to(
            jnp.arange(ng, dtype=jnp.int32)[None], (chunk_g, ng)
        ).reshape(-1)

    rows_g, cols_g, total_g = _csr_scan(
        live_g, rid_g, cid_g, nc_g, budget_g, ng
    )

    # Level 2: expand surviving group pairs to tile pairs.  Padding
    # group pairs point at the inverted dump group — never live.
    tlo_rg = tlo_r.reshape(ng + 1, G, d)
    thi_rg = thi_r.reshape(ng + 1, G, d)
    tlo_cg = tlo_c.reshape(ng + 1, G, d)
    thi_cg = thi_c.reshape(ng + 1, G, d)
    # Clamp to the group-pair budget: the memory bound alone admits a
    # ~500k chunk, and at small grids (the sweep emission runs this at
    # nt in the tens) padding budget_g=8k up to one such chunk made the
    # level-2 expansion compute 64x dead box tests per call — 0.9s of
    # pure padding waste per emission at the probe geometry.
    chunk_p = max(1, min(budget_g, (1 << 26) // (G * G * d)))
    nc_p = -(-budget_g // chunk_p)
    pad_p = nc_p * chunk_p - budget_g
    rows_gp = jnp.concatenate([rows_g, jnp.full(pad_p, ng, jnp.int32)])
    cols_gp = jnp.concatenate([cols_g, jnp.zeros(pad_p, jnp.int32)])
    iota_g = jnp.arange(G, dtype=jnp.int32)

    def slab(c):
        a = jax.lax.dynamic_slice_in_dim(rows_gp, c * chunk_p, chunk_p)
        b = jax.lax.dynamic_slice_in_dim(cols_gp, c * chunk_p, chunk_p)
        return a, b

    def live_t(c):
        a, b = slab(c)
        return box_gap_live(
            tlo_rg[a], thi_rg[a], tlo_cg[b][:, None], thi_cg[b][:, None]
        ).reshape(-1)

    def rid_t(c):
        a, _ = slab(c)
        rid = a[:, None, None] * G + iota_g[None, :, None]
        # Padded tiles inside real groups never go live; the dump
        # group maps to row ids >= nt, clamped onto the dump row nt.
        return jnp.minimum(
            jnp.broadcast_to(rid, (chunk_p, G, G)).reshape(-1), nt
        )

    def cid_t(c):
        _, b = slab(c)
        cid = b[:, None, None] * G + iota_g[None, None, :]
        return jnp.minimum(
            jnp.broadcast_to(cid, (chunk_p, G, G)).reshape(-1), nt - 1
        )

    rows_t, cols_t, total_t = _csr_scan(
        live_t, rid_t, cid_t, nc_p, budget, nt
    )
    # Expansion emits in group-pair order; the kernel needs row-major
    # (each output row's visits consecutive).  Stable argsort on the
    # row id alone — column order within a row is irrelevant.
    order = jnp.argsort(rows_t, stable=True)
    # A group-level overflow also invalidates the list; fold it into
    # the total so the caller's exact-budget retry covers both levels
    # (saturated product: overflow-safe in 32-bit mode; a retry this
    # large only happens when the data defeats tile pruning outright).
    g_need = jnp.minimum(
        total_g.astype(jnp.float32) * (G * G), jnp.float32(1 << 30)
    ).astype(jnp.int32)
    total = jnp.maximum(total_t, jnp.where(total_g > budget_g, g_need, 0))
    return rows_t[order], cols_t[order], total


# Below this tile count the dense grid stays the default: its scan
# overhead is ~nt^2 cheap cond iterations (sub-second below ~2k
# tiles), while the compacted path adds the two-level extraction graph
# to EVERY kernel program — measured as a 10x compile-time tax on the
# CI-sized sharded programs (8 unrolled partitions x extraction each),
# for zero runtime win at small nt (the 200k x 16-D probe measures
# dense == pair at nt<=800).  Past it, the dense scan's quadratic
# iteration count dominates runtime (the 5M north-star's 666.5s
# compute wall) and the one-time compile is noise.
PAIR_DISPATCH_MIN_TILES = int(
    envreg.raw("PYPARDIS_PAIR_DISPATCH_TILES", 2048)
)


def pair_dispatch_enabled(nt: int | None = None) -> bool:
    """Whether the XLA kernels dispatch over the compacted live
    tile-pair list instead of scanning the dense T^2 column grid and
    disproving pruned pairs one ``lax.cond`` at a time.

    ``PYPARDIS_DISPATCH``: ``auto`` (default) compacts once the grid
    reaches :data:`PAIR_DISPATCH_MIN_TILES` tiles (``nt`` — callers
    pass their slab's tile count; None means "unknown", treated as
    small); ``pair`` forces the compacted path everywhere; ``dense``
    restores the dense grid — the parity oracle for the compacted path
    (labels are byte-identical by construction: box-gap pruning is the
    soundness argument either way, and integer count/min accumulation
    commutes).  Read at TRACE time: flipping the env mid-process only
    affects programs compiled afterwards (tests call
    ``jax.clear_caches()`` around a flip).
    """
    env = envreg.raw("PYPARDIS_DISPATCH", "auto")
    if env == "dense":
        return False
    if env == "pair":
        return True
    return nt is not None and nt >= PAIR_DISPATCH_MIN_TILES


def xla_pair_list(
    points, mask, eps, block: int, layout: str, budget: int | None = None,
    sketch: int = 0, precision: str = "high",
):
    """Live tile-pair list sized to the XLA kernels' OWN tile grid
    (``nt = n / block``) — the twin of
    :func:`pypardis_tpu.ops.pallas_kernels.kernel_pair_list` for the
    pure-XLA tiled passes.  Extracted ONCE per fit and shared by the
    counts pass and every propagation pass; the list covers validity
    boxes, a superset of any per-pass source subset (core masks), so
    sharing is sound.  Returns ``((rows, cols), (2,) int32 [total,
    budget])`` with the usual overflow contract: ``total > budget``
    means pairs were dropped and results built from the list are
    INVALID — the drivers' ladder retries with the exact total.

    ``sketch`` (a RESOLVED k — callers resolve the spec once): extract
    over SKETCH-space tile boxes at the widened gate ``sqrt(eps^2 +
    band)`` instead of full-d boxes.  At high d axis-aligned full-d
    boxes go useless (every pair "live"); the (k+1)-dim slab boxes
    stay tight.  Soundness: a pair with kernel ``d2 <= eps^2`` has
    slab distance ``t2 <= eps^2 + band`` (the gate-band certification
    run in reverse), so its boxes lie within the widened gate — a
    pruned pair provably contributes nothing, the same argument the
    full-d extraction rides.  ``precision`` only sizes the band (the
    ``default``-precision kernel needs the wider one).
    """
    layout = _norm_layout(layout)
    nt, pts, msk = _tiles_t(points, mask, block, layout)
    if budget is None:
        budget = default_pair_budget(nt)
    budget = min(budget, nt * nt)
    if sketch:
        slab, sband = _sketch_setup(pts, msk, sketch, precision)
        slo, shi = tile_bounds(slab, msk)
        eps_gate = jnp.sqrt(jnp.float32(eps) ** 2 + sband)
        rows, cols, total = live_tile_pairs(
            slo, shi, eps_gate, budget=budget
        )
    else:
        lo, hi = tile_bounds(pts, msk)
        rows, cols, total = live_tile_pairs(lo, hi, eps, budget=budget)
    return (rows, cols), jnp.stack([total, jnp.int32(budget)])


# Pairs per inner scan of the compacted XLA dispatch: each chunk's
# per-pair (block,) partial rows materialize as one (chunk, block)
# scan output (block=1024 -> 16MB int32) and fold into the (nt+1,
# block) accumulator with ONE unconditional scatter — the accumulator
# never threads through a per-pair lax.cond, whose operand copies were
# measured to dwarf the live compute (a 4MB carry copied per pair at
# north-star tile counts is hundreds of GB of memcpy per pass).
_XLA_PAIR_CHUNK = 4096


def _pair_scan_chunks(pairs, nt, per_pair, fold, identity, block):
    """Shared driver for the compacted XLA dispatch.

    ``per_pair(r, c) -> ((block,) row, (2,) band)`` computes one live
    tile pair (behind a ``lax.cond`` whose carry is only scalars —
    skipped/padding pairs cost an iteration, never a tile of compute
    or an accumulator copy); ``fold(acc, tgt, vals)`` scatters a
    chunk's rows into the (nt+1, block) accumulator (row ``nt`` is the
    dump row padding/skipped pairs target).  Returns ``(acc, band)``.
    """
    rows, cols = pairs
    n_pairs = rows.shape[0]
    chunk = min(_XLA_PAIR_CHUNK, max(n_pairs, 1))
    nch = -(-n_pairs // chunk)
    pad = nch * chunk - n_pairs
    rows = jnp.concatenate([rows, jnp.full(pad, nt, jnp.int32)])
    cols = jnp.concatenate([cols, jnp.zeros(pad, jnp.int32)])
    rows = rows.reshape(nch, chunk)
    cols = cols.reshape(nch, chunk)

    def inner(carry, rc):
        band = carry
        r, c = rc

        def compute(b):
            vals, nb = per_pair(r, c)
            return b + nb, vals

        def skip(b):
            return b, jnp.full((block,), identity, jnp.int32)

        band, vals = jax.lax.cond(r >= nt, skip, compute, band)
        return band, vals

    def outer(carry, rc):
        acc, band = carry
        r, c = rc
        band, vals = jax.lax.scan(inner, band, (r, c))
        # Padding/skipped pairs carry the identity and target the dump
        # row, so one unsorted scatter per chunk folds everything.
        acc = fold(acc, jnp.minimum(r, nt), vals)
        return (acc, band), None

    acc0 = jnp.full((nt + 1, block), identity, jnp.int32)
    (acc, band), _ = jax.lax.scan(
        outer, (acc0, jnp.zeros(2, jnp.int32)), (rows, cols)
    )
    return acc[:nt], band


def _counts_over_pairs(
    pts, msk, lo, hi, pairs, eps, eps2, rt, metric, precision, mixed,
    slab=None, band=None,
):
    """Counts pass driven by a compacted pair list — the XLA analogue
    of the Pallas kernels' pair-list grid.  Padding entries carry row
    ``nt`` and rows past ``rt`` (the owner-computes row restriction)
    skip outright, so the MXU/VPU never visits a pair the boxes
    already ruled out.  Integer adds commute, so counts are
    byte-identical to the dense scan's.  ``slab``/``band``: the sketch
    prefilter's (nt, k+1, block) slabs and certified band — listed
    pairs then classify in sketch space and only in-band tiles run the
    full-d arithmetic (:func:`_tile_adjacency_sketch_t`).  Returns
    ``(counts[:rt*block], (2,) band stats)``."""
    nt, _d, block = pts.shape
    rows, cols = pairs
    centers = 0.5 * (lo + hi)
    # The row restriction folds into the pair ids: restricted rows
    # become dump-row padding before the shared chunked scan.
    rows = jnp.where(rows < rt, rows, nt)

    def per_pair(r, c):
        rr = jnp.minimum(r, nt - 1)
        cc = jnp.minimum(c, nt - 1)
        xi, mi = pts[rr], msk[rr]
        yj, mj = pts[cc], msk[cc]
        if slab is not None:
            adj, n_band, resc = _tile_adjacency_sketch_t(
                xi, yj, slab[rr], slab[cc], eps, eps2, band,
                centers[rr][:, None], mi, mj, precision, mixed,
            )
        elif mixed:
            adj, n_band, resc = _tile_adjacency_mixed_t(
                xi, yj, eps2, centers[rr][:, None], mi, mj,
            )
        else:
            adj = _tile_adjacency_t(xi, yj, eps, metric, precision)
            adj &= mj[None, :]
            n_band = resc = jnp.int32(0)
        cnt = jnp.sum(adj, axis=1, dtype=jnp.int32)
        return cnt, jnp.stack([n_band, resc])

    def fold(acc, tgt, vals):
        return acc.at[tgt].add(vals)

    acc, band = _pair_scan_chunks(
        (rows, cols), nt, per_pair, fold, 0, block
    )
    return acc[:rt].reshape(-1), band


def _minlab_over_pairs(
    pts, smsk, lab, row_lo, row_hi, pairs, eps, eps2, owned_tiles,
    metric, precision, mixed, slab=None, band=None,
):
    """Min-label pass over a compacted pair list (see
    :func:`_counts_over_pairs`; min accumulation commutes too).
    ``owned_tiles`` drops (halo row, halo col) entries exactly like
    the dense kernel's tile-pair skip; the pair list may cover
    validity boxes — the extra pairs a tighter source mask would have
    pruned contribute only INT32_MAX candidates, so the result is
    identical."""
    nt, _d, block = pts.shape
    rows, cols = pairs
    centers = 0.5 * (row_lo + row_hi)
    if owned_tiles is not None:
        halo_halo = (rows >= owned_tiles) & (cols >= owned_tiles)
        rows = jnp.where(halo_halo, nt, rows)

    def per_pair(r, c):
        rr = jnp.minimum(r, nt - 1)
        cc = jnp.minimum(c, nt - 1)
        xi = pts[rr]
        yj, mj, lj = pts[cc], smsk[cc], lab[cc]
        if slab is not None:
            adj, n_band, resc = _tile_adjacency_sketch_t(
                xi, yj, slab[rr], slab[cc], eps, eps2, band,
                centers[rr][:, None], jnp.ones((block,), bool), mj,
                precision, mixed, collect_stats=False,
            )
        elif mixed:
            adj, n_band, resc = _tile_adjacency_mixed_t(
                xi, yj, eps2, centers[rr][:, None],
                jnp.ones((block,), bool), mj, collect_stats=False,
            )
        else:
            adj = _tile_adjacency_t(xi, yj, eps, metric, precision)
            adj &= mj[None, :]
            n_band = resc = jnp.int32(0)
        cand = jnp.where(adj, lj[None, :], _INT_INF)
        return jnp.min(cand, axis=1), jnp.stack([n_band, resc])

    def fold(acc, tgt, vals):
        return acc.at[tgt].min(vals)

    acc, band = _pair_scan_chunks(
        (rows, cols), nt, per_pair, fold, _INT_INF, block
    )
    return acc.reshape(-1), band


@functools.partial(
    jax.jit,
    static_argnames=(
        "metric", "block", "precision", "layout", "row_tiles", "sketch",
    ),
)
def neighbor_counts(
    points: jnp.ndarray,
    eps: float,
    mask: jnp.ndarray,
    metric: str = "euclidean",
    block: int = 1024,
    precision: str = "high",
    layout: str = "nd",
    row_tiles: int | None = None,
    pairs=None,
    sketch: int | str | None = None,
) -> jnp.ndarray:
    """Per-point count of valid points within eps (self included).

    ``points``: (N, d) (``layout="nd"``) or (d, N) (``layout="dn"``)
    with N a multiple of ``block``; ``mask``: (N,) bool.  Returns (N,)
    int32.  Row tiles map over the grid; column tiles are a ``lax.scan``
    accumulation, so peak memory is O(block^2).  Column tiles whose
    bounding box lies farther than eps from the row tile's are skipped
    (``lax.cond``), so spatially sorted inputs do O(N * local density)
    work instead of O(N^2).

    ``pairs``: optional precomputed ``(rows, cols)`` live tile-pair
    list from :func:`xla_pair_list` (row-major; padding rows == nt).
    When given, the kernel dispatches ONE scan step per listed pair
    instead of walking the dense nt^2 grid — the compacted cell-list
    dispatch; counts are byte-identical (integer adds commute, and a
    box-pruned pair provably contributes zero).  The caller owns the
    overflow contract: a truncated list silently misses pairs, so
    only lists whose extraction reported ``total <= budget`` are
    valid.

    ``row_tiles`` restricts the computed ROWS to the first
    ``row_tiles * block`` points (the output shrinks to match) while
    columns still cover all N — the owner-computes primitive: owned
    slots occupy the slab prefix, and their counts need halo columns
    as evidence without ever counting the halo rows themselves.

    ``sketch``: the random-projection prefilter
    (:mod:`pypardis_tpu.ops.sketch`) — ``None`` resolves
    ``PYPARDIS_SKETCH`` at TRACE time, an int pins k (0 disables).
    When active the return widens to ``(counts, band_stats)`` exactly
    like ``mixed`` (the band columns then count SKETCH-band pairs /
    rescored tiles); counts stay byte-identical to the unsketched
    pass for any k.

    With ``precision="mixed"`` the return widens to ``(counts,
    band_stats)`` — band_stats a (2,) int32 ``[band_pairs,
    rescored_tiles]`` from the banded single-bf16-pass classification
    (:func:`_tile_adjacency_mixed_t`); counts are byte-identical to
    ``precision="high"``.
    """
    from .precision import norm_precision_mode
    from .sketch import resolve_sketch, sketch_dims

    metric = _norm_metric(metric)
    layout = _norm_layout(layout)
    mixed = norm_precision_mode(precision) == "mixed"
    if mixed and metric != "euclidean":
        raise ValueError(
            "precision='mixed' supports only the euclidean metric (the "
            "banded pass is a matmul discipline); use 'high'/'highest'"
        )
    nt, pts, msk = _tiles_t(points, mask, block, layout)
    d = pts.shape[1]
    sk = (
        sketch_dims(d, metric) if sketch is None
        else resolve_sketch(sketch, d, metric)
    )
    lo, hi = tile_bounds(pts, msk)
    rt = nt if row_tiles is None else min(row_tiles, nt)
    eps2 = jnp.float32(eps) ** 2
    banded = mixed or sk > 0
    if sk:
        slab, sband = _sketch_setup(pts, msk, sk, precision)
    else:
        slab = sband = None

    if pairs is not None:
        counts, band = _counts_over_pairs(
            pts, msk, lo, hi, pairs, eps, eps2, rt, metric, precision,
            mixed, slab=slab, band=sband,
        )
        counts = jnp.where(mask[: rt * block], counts, 0)
        if not banded:
            return counts
        return counts, band

    if sk:
        slo, shi = tile_bounds(slab, msk)
        eps_gate = jnp.sqrt(eps2 + sband)

    def row_tile(xi, mi, lo_i, hi_i, si=None, slo_i=None, shi_i=None):
        skip = tile_skip_mask(lo_i, hi_i, lo, hi, eps, metric)
        if sk:
            # Sketch-space boxes prune independently of the full-d
            # boxes (each test is sound alone — a live pair has
            # t2 <= eps2 + band, so its slab boxes lie within the
            # widened gate); the AND is strictly tighter.
            skip = skip | tile_skip_mask(
                slo_i, shi_i, slo, shi, eps_gate, "euclidean"
            )
        ctr = (0.5 * (lo_i + hi_i))[:, None]

        def col_step(carry, jc):
            def compute(c):
                a, bp, rs = c
                yj, mj = pts[jc], msk[jc]
                if sk:
                    adj, n_band, resc = _tile_adjacency_sketch_t(
                        xi, yj, si, slab[jc], eps, eps2, sband, ctr,
                        mi, mj, precision, mixed,
                    )
                elif mixed:
                    adj, n_band, resc = _tile_adjacency_mixed_t(
                        xi, yj, eps2, ctr, mi, mj,
                    )
                else:
                    adj = _tile_adjacency_t(xi, yj, eps, metric, precision)
                    adj &= mj[None, :]
                    n_band = resc = jnp.int32(0)
                return (
                    a + jnp.sum(adj, axis=1, dtype=jnp.int32),
                    bp + n_band, rs + resc,
                )

            return jax.lax.cond(skip[jc], lambda c: c, compute, carry), None

        acc0 = (
            jnp.zeros((block,), jnp.int32), jnp.int32(0), jnp.int32(0)
        )
        (counts, bp, rs), _ = jax.lax.scan(col_step, acc0, jnp.arange(nt))
        return jnp.where(mi, counts, 0), bp, rs

    ops = (pts[:rt], msk[:rt], lo[:rt], hi[:rt])
    if sk:
        ops = ops + (slab[:rt], slo[:rt], shi[:rt])
    counts, bps, rss = jax.lax.map(lambda args: row_tile(*args), ops)
    counts = counts.reshape(-1)
    if not banded:
        return counts
    return counts, jnp.stack([jnp.sum(bps), jnp.sum(rss)])


@functools.partial(
    jax.jit,
    static_argnames=(
        "metric", "block", "precision", "layout", "owned_tiles", "sketch",
    ),
)
def min_neighbor_label(
    points: jnp.ndarray,
    labels: jnp.ndarray,
    eps: float,
    src_mask: jnp.ndarray,
    metric: str = "euclidean",
    block: int = 1024,
    precision: str = "high",
    row_mask: jnp.ndarray | None = None,
    layout: str = "nd",
    owned_tiles: int | None = None,
    pairs=None,
    sketch: int | str | None = None,
) -> jnp.ndarray:
    """Per-point min label over eps-neighbors drawn from ``src_mask``.

    ``labels``: (N,) int32 (INT32_MAX = no label).  Only neighbors with
    ``src_mask[j]`` contribute.  Returns (N,) int32, INT32_MAX where no
    masked neighbor is within eps.  This single primitive powers both the
    core-graph min-propagation step and the border-point assignment pass.
    ``row_mask`` tightens the per-tile bounding boxes used for tile-level
    pruning to the rows the caller will actually read; rows outside it
    may be silently pruned to INT32_MAX.  The default (``None``) covers
    ALL rows, so every row's output is correct — pass a mask only when
    you will mask those rows out anyway.

    ``owned_tiles`` declares the first ``owned_tiles * block`` slots as
    OWNED and the rest as halo: (halo row, halo col) tile pairs are
    skipped outright.  Halo slots then exchange labels with owned slots
    only — the owner-computes adjacency rule, where halo-halo edges are
    each some partition's owned-halo edge and are recovered there.

    ``pairs``: optional compacted live tile-pair list (see
    :func:`neighbor_counts`); the same ``owned_tiles`` skip applies per
    listed entry, so callers share ONE unfiltered list across passes.

    ``sketch``: the random-projection prefilter — same resolution and
    widened-return contract as :func:`neighbor_counts` (propagation
    passes skip the band bookkeeping exactly like ``mixed``; the
    returned stats row is zeros).

    With ``precision="mixed"`` the return widens to ``(best,
    band_stats)`` — see :func:`neighbor_counts`; labels are
    byte-identical to ``precision="high"``.
    """
    from .precision import norm_precision_mode
    from .sketch import resolve_sketch, sketch_dims

    metric = _norm_metric(metric)
    layout = _norm_layout(layout)
    mixed = norm_precision_mode(precision) == "mixed"
    if mixed and metric != "euclidean":
        raise ValueError(
            "precision='mixed' supports only the euclidean metric (the "
            "banded pass is a matmul discipline); use 'high'/'highest'"
        )
    nt, pts, smsk = _tiles_t(points, src_mask, block, layout)
    d = pts.shape[1]
    sk = (
        sketch_dims(d, metric) if sketch is None
        else resolve_sketch(sketch, d, metric)
    )
    lab = labels.reshape(nt, block)
    lo, hi = tile_bounds(pts, smsk)
    if row_mask is None:
        # Full coverage: row bounds over every row (padding included —
        # only a pruning-tightness cost, never a correctness one).
        rmsk = jnp.ones_like(smsk)
    else:
        rmsk = row_mask.reshape(nt, block)
    row_lo, row_hi = tile_bounds(pts, rmsk)
    col_ids = jnp.arange(nt, dtype=jnp.int32)
    eps2 = jnp.float32(eps) ** 2
    banded = mixed or sk > 0
    if sk:
        # Band norm bound over rows AND sources: a tight row/source
        # mask must not shrink the certified band below the float
        # error of the other side's highest-norm point.
        slab, sband = _sketch_setup(pts, smsk | rmsk, sk, precision)
    else:
        slab = sband = None

    if pairs is not None:
        best, band = _minlab_over_pairs(
            pts, smsk, lab, row_lo, row_hi, pairs, eps, eps2,
            owned_tiles, metric, precision, mixed,
            slab=slab, band=sband,
        )
        if not banded:
            return best
        return best, band

    if sk:
        slo, shi = tile_bounds(slab, smsk)
        srow_lo, srow_hi = tile_bounds(slab, rmsk)
        eps_gate = jnp.sqrt(eps2 + sband)

    def row_tile(ri, xi, mi, lo_i, hi_i, si=None, slo_i=None, shi_i=None):
        skip = tile_skip_mask(lo_i, hi_i, lo, hi, eps, metric)
        if sk:
            skip = skip | tile_skip_mask(
                slo_i, shi_i, slo, shi, eps_gate, "euclidean"
            )
        if owned_tiles is not None:
            skip = skip | ((ri >= owned_tiles) & (col_ids >= owned_tiles))
        ctr = (0.5 * (lo_i + hi_i))[:, None]

        def col_step(carry, jc):
            def compute(c):
                a, bp, rs = c
                yj, mj, lj = pts[jc], smsk[jc], lab[jc]
                if sk:
                    adj, n_band, resc = _tile_adjacency_sketch_t(
                        xi, yj, si, slab[jc], eps, eps2, sband, ctr,
                        mi, mj, precision, mixed, collect_stats=False,
                    )
                elif mixed:
                    # Propagation passes skip the band bookkeeping —
                    # stats are deterministic per pass and the counts
                    # pass already measured them (on lossy backends
                    # the in-band test still runs: it gates the
                    # rescore).
                    adj, n_band, resc = _tile_adjacency_mixed_t(
                        xi, yj, eps2, ctr, mi, mj, collect_stats=False,
                    )
                else:
                    adj = _tile_adjacency_t(xi, yj, eps, metric, precision)
                    adj &= mj[None, :]
                    n_band = resc = jnp.int32(0)
                cand = jnp.where(adj, lj[None, :], _INT_INF)
                return (
                    jnp.minimum(a, jnp.min(cand, axis=1)),
                    bp + n_band, rs + resc,
                )

            return jax.lax.cond(skip[jc], lambda c: c, compute, carry), None

        acc0 = (
            jnp.full((block,), _INT_INF, jnp.int32),
            jnp.int32(0), jnp.int32(0),
        )
        (best, bp, rs), _ = jax.lax.scan(col_step, acc0, jnp.arange(nt))
        return best, bp, rs

    ops = (jnp.arange(nt, dtype=jnp.int32), pts, rmsk, row_lo, row_hi)
    if sk:
        ops = ops + (slab, srow_lo, srow_hi)
    best, bps, rss = jax.lax.map(lambda args: row_tile(*args), ops)
    best = best.reshape(-1)
    if not banded:
        return best
    return best, jnp.stack([jnp.sum(bps), jnp.sum(rss)])


# ---------------------------------------------------------------------------
# Neighbor-pair graph emission — the amortized-sweep distance pass.
#
# A hyperparameter sweep re-runs the SAME distance arithmetic k times
# with only the threshold changing.  One emission pass at eps_max
# materializes every surviving (i, j, dval) triple into a budgeted
# CSR-style slab; each (eps <= eps_max, min_samples) config then
# re-thresholds the cached dval and label-propagates over the cached
# pair list — no distance recomputation (ops.labels.graph_dbscan).
# dval is computed by exactly the arithmetic the tiled kernels run
# (the |x|^2+|y|^2-2xy expansion at the same dot precision), so a
# per-config re-threshold reproduces the kernels' adjacency BITWISE —
# the sweep's byte-parity contract.
# ---------------------------------------------------------------------------

_F32_INF = np.float32(np.inf)


def sweep_max_edges() -> int:
    """Hard cap on the sweep's neighbor-pair graph slab, in edges
    (``PYPARDIS_SWEEP_MAX_PAIRS``; default 2^26 ~ 768MB at 12
    bytes/edge).  Past it the sweep degrades label-safely to
    per-config refits instead of allocating an unbounded slab — the
    graph is an amortization, never a correctness requirement."""
    return int(envreg.raw("PYPARDIS_SWEEP_MAX_PAIRS", str(1 << 26)))


def sweep_emission_route() -> str:
    """Which pair-emission path the sweep graph build takes
    (``host`` or ``device``).

    ``PYPARDIS_SWEEP_EMISSION`` forces it; ``auto`` (default) routes
    to host compaction on CPU — the XLA scatter behind the device
    emission is single-threaded there (measured 65x a counts pass,
    PR 13) — and to the device emission everywhere else.  The forced
    ``device`` spelling is what lets CPU CI exercise the device
    route's exact-total edge-budget ladder (the PR 13 NOTE debt).
    """
    env = envreg.raw("PYPARDIS_SWEEP_EMISSION", "auto")
    if env in ("host", "device"):
        return env
    return "host" if jax.default_backend() == "cpu" else "device"


def default_edge_budget(n: int) -> int:
    """Default neighbor-pair graph capacity: 96 directed edges per row
    (``PYPARDIS_SWEEP_EDGE_BUDGET`` overrides the per-row default —
    the deterministic way to drive the exact-total retry ladder in
    tests and to pre-size known-dense sweeps).

    Self-pairs ride in the graph (the kernels count them too), and the
    blob/manifold probe geometries measure ~20-60 within-eps neighbors
    per point at mid-gap eps; 96 gives slack without inflating the
    slab (budget * 12 bytes).  Overflow is signalled exactly (the
    returned total is the true count), so one retry always suffices.
    """
    env = envreg.raw("PYPARDIS_SWEEP_EDGE_BUDGET")
    if env:
        return max(1, int(env))
    return max(1 << 16, 96 * n)


@functools.partial(
    jax.jit,
    static_argnames=(
        "metric", "block", "precision", "layout", "row_tiles", "budget",
        "pair_budget",
    ),
)
def neighbor_pair_graph(
    points: jnp.ndarray,
    mask: jnp.ndarray,
    eps,
    metric: str = "euclidean",
    block: int = 1024,
    precision: str = "high",
    layout: str = "nd",
    row_tiles: int | None = None,
    budget: int | None = None,
    pair_budget: int | None = None,
):
    """Emit every surviving ``(i, j, dval)`` neighbor triple at ``eps``.

    ``dval`` is the kernels' threshold quantity — squared Euclidean
    distance (``metric="euclidean"``) or the L1 distance
    (``"cityblock"``) — computed with the SAME tile arithmetic the
    counts/minlab kernels use, so ``dval <= eps_c^2`` (resp. ``<=
    eps_c``) at any config ``eps_c <= eps`` reproduces that config's
    kernel adjacency bitwise.  Driven over the compacted live
    tile-pair list (:func:`live_tile_pairs` — the PR 11 machinery), so
    the MXU never visits a pair the boxes already ruled out.

    ``row_tiles`` restricts EMITTING rows to the first ``row_tiles *
    block`` slots (the owner-computes discipline: owned rows emit, halo
    /boundary slots serve as column evidence only — each directed edge
    is emitted exactly once, by its row's owner).  Self-pairs are
    included when they pass the threshold, exactly as the kernels'
    adjacency does.

    Returns ``(gi, gj, dval, stats)``: budget-sized int32/int32/f32
    slabs (inert padding: ``dval == +inf``, never live at any config)
    and a (4,) int32 ``[edge_total, edge_budget, tile_pair_total,
    tile_pair_budget]``.  Either ``total > budget`` means entries were
    dropped — the graph is INVALID and the caller must retry with the
    exact totals (both are exact counts, one retry suffices).

    ``precision="mixed"`` runs the rescore arithmetic (bitwise the
    ``high`` pass — the mode's exactness contract) for every emitted
    pair: the cached dval must be exact at EVERY config threshold, not
    just inside the band around ``eps`` that the one-pass banded
    verdicts certify.
    """
    from .precision import norm_precision_mode

    metric = _norm_metric(metric)
    layout = _norm_layout(layout)
    prec = norm_precision_mode(precision)
    if prec == "mixed":
        prec = "high"
    nt, pts, msk = _tiles_t(points, mask, block, layout)
    lo, hi = tile_bounds(pts, msk)
    rt = nt if row_tiles is None else min(int(row_tiles), nt)
    if pair_budget is None:
        pair_budget = default_pair_budget(nt)
    pair_budget = min(int(pair_budget), nt * nt)
    rows, cols, tile_total = live_tile_pairs(
        lo, hi, eps, budget=pair_budget
    )
    if budget is None:
        budget = default_edge_budget(rt * block)
    budget = int(budget)
    # The owner-computes row restriction folds into the pair ids:
    # restricted rows become dump-row padding before the scan.
    rows = jnp.where(rows < rt, rows, nt)
    eps_f = jnp.asarray(eps, jnp.float32)
    iota = jnp.arange(block, dtype=jnp.int32)
    # Pairs per scan step: one step per pair made the emission
    # dispatch-bound (measured ~10ms of loop overhead per step on CPU
    # — 10x the counts pass over the same pairs); batching C pairs
    # turns the distance work into ONE batched matmul and the
    # compaction into one cumsum + one scatter per step.  The (C,
    # block, block) temp is capped ~16MB.
    chunk = max(1, min(int(rows.shape[0]), (1 << 22) // (block * block)))
    n_pairs = int(rows.shape[0])
    nch = -(-n_pairs // chunk)
    pad = nch * chunk - n_pairs
    rows = jnp.concatenate([rows, jnp.full(pad, nt, jnp.int32)])
    cols = jnp.concatenate([cols, jnp.zeros(pad, jnp.int32)])
    rows = rows.reshape(nch, chunk)
    cols = cols.reshape(nch, chunk)

    def body(carry, rc):
        gi_o, gj_o, dv_o, total = carry
        r, c = rc
        rr = jnp.minimum(r, nt - 1)
        cc = jnp.minimum(c, nt - 1)
        xi, mi = pts[rr], msk[rr]  # (C, d, b), (C, b)
        yj, mj = pts[cc], msk[cc]
        if metric == "euclidean":
            xx = jnp.sum(xi * xi, axis=1)
            yy = jnp.sum(yj * yj, axis=1)
            dval = xx[:, :, None] + yy[:, None, :] - 2.0 * (
                jax.lax.dot_general(
                    xi, yj, (((1,), (1,)), ((0,), (0,))),
                    precision=_norm_precision(prec),
                    preferred_element_type=jnp.float32,
                )
            )
            live = dval <= eps_f * eps_f
        else:
            dval = jnp.sum(
                jnp.abs(xi[:, :, :, None] - yj[:, :, None, :]), axis=1
            )
            live = dval <= eps_f
        # Padding/row-restricted pairs (r == nt) are masked out rather
        # than branched around — at chunk granularity a cond would
        # compute everything anyway.
        live = (
            live
            & mi[:, :, None]
            & mj[:, None, :]
            & (r < nt)[:, None, None]
        )
        ii = (rr * block)[:, None, None] + iota[None, :, None]
        jj = (cc * block)[:, None, None] + iota[None, None, :]
        livef = live.reshape(-1)
        inc = jnp.cumsum(livef.astype(jnp.int32))
        pos = total + inc - livef
        # Live entries take fresh slots in scan order; everything else
        # (non-live, and live entries past the budget) lands on the
        # dump slot ``budget`` — dropped, signalled via total > budget.
        tgt = jnp.where(livef, jnp.minimum(pos, budget), budget)
        gi_o = gi_o.at[tgt].set(
            jnp.broadcast_to(ii, live.shape).reshape(-1)
        )
        gj_o = gj_o.at[tgt].set(
            jnp.broadcast_to(jj, live.shape).reshape(-1)
        )
        dv_o = dv_o.at[tgt].set(
            jnp.where(livef, dval.reshape(-1), _F32_INF)
        )
        return (gi_o, gj_o, dv_o, total + inc[-1]), None

    init = (
        jnp.zeros(budget + 1, jnp.int32),
        jnp.zeros(budget + 1, jnp.int32),
        jnp.full(budget + 1, _F32_INF, jnp.float32),
        jnp.int32(0),
    )
    (gi_o, gj_o, dv_o, total), _ = jax.lax.scan(body, init, (rows, cols))
    stats = jnp.stack(
        [
            total,
            jnp.int32(budget),
            tile_total,
            jnp.int32(pair_budget),
        ]
    )
    return gi_o[:budget], gj_o[:budget], dv_o[:budget], stats


@functools.partial(
    jax.jit,
    static_argnames=("block", "layout", "row_tiles", "pair_budget"),
)
def _graph_live_pairs(
    points, mask, eps, *, block, layout, row_tiles, pair_budget,
):
    """Shared pair-list half of the emission: the live tile pairs with
    the owner-computes row restriction folded in (restricted/padding
    rows == nt)."""
    nt, pts, msk = _tiles_t(points, mask, block, layout)
    lo, hi = tile_bounds(pts, msk)
    rt = nt if row_tiles is None else min(int(row_tiles), nt)
    pb = (
        default_pair_budget(nt) if pair_budget is None
        else int(pair_budget)
    )
    pb = min(pb, nt * nt)
    rows, cols, total = live_tile_pairs(lo, hi, eps, budget=pb)
    return jnp.where(rows < rt, rows, nt), cols, total, jnp.int32(pb)


@functools.partial(
    jax.jit,
    static_argnames=("metric", "block", "precision", "layout"),
)
def _graph_chunk(
    points, mask, eps, rows_c, cols_c, *, metric, block, precision,
    layout,
):
    """One chunk of pairs' ``(live, dval)`` tiles — the compute half of
    the emission, shared by the device-scatter and host-compaction
    routes (same batched arithmetic, so the stored d2 is identical)."""
    nt, pts, msk = _tiles_t(points, mask, block, layout)
    eps_f = jnp.asarray(eps, jnp.float32)
    rr = jnp.minimum(rows_c, nt - 1)
    cc = jnp.minimum(cols_c, nt - 1)
    xi, mi = pts[rr], msk[rr]
    yj, mj = pts[cc], msk[cc]
    if metric == "euclidean":
        xx = jnp.sum(xi * xi, axis=1)
        yy = jnp.sum(yj * yj, axis=1)
        dval = xx[:, :, None] + yy[:, None, :] - 2.0 * (
            jax.lax.dot_general(
                xi, yj, (((1,), (1,)), ((0,), (0,))),
                precision=_norm_precision(precision),
                preferred_element_type=jnp.float32,
            )
        )
        live = dval <= eps_f * eps_f
    else:
        dval = jnp.sum(
            jnp.abs(xi[:, :, :, None] - yj[:, :, None, :]), axis=1
        )
        live = dval <= eps_f
    live = (
        live
        & mi[:, :, None]
        & mj[:, None, :]
        & (rows_c < nt)[:, None, None]
    )
    return live, dval


def neighbor_pair_graph_host(
    points,
    mask,
    eps,
    metric: str = "euclidean",
    block: int = 1024,
    precision: str = "high",
    layout: str = "nd",
    row_tiles: int | None = None,
    pair_budget: int | None = None,
):
    """Host-compaction twin of :func:`neighbor_pair_graph`.

    Same tile pruning, same batched distance arithmetic (the stored
    dval is bitwise the device route's), but the stream compaction
    runs in numpy: each chunk's ``(live, dval)`` tiles come back to
    the host and ``np.flatnonzero`` extracts the survivors.  On CPU
    the XLA scatter behind the device route runs single-threaded at
    ~10x the matmul cost (measured 65x a fit's counts pass at the
    probe geometry); here the fetch is a zero-copy view and the
    compaction runs at memory speed.  No edge budget exists — host
    lists grow to the exact total — so the only overflow contract left
    is the tile-pair one.  Returns numpy ``(gi, gj, dval, stats)``
    with the stats row shaped like the device route's (edge budget ==
    total: never overflows).
    """
    from .precision import norm_precision_mode

    metric = _norm_metric(metric)
    layout = _norm_layout(layout)
    prec = norm_precision_mode(precision)
    if prec == "mixed":
        prec = "high"
    n = points.shape[0] if layout == "nd" else points.shape[1]
    nt = n // block
    rows, cols, tile_total, pb = _graph_live_pairs(
        points, mask, eps, block=block, layout=layout,
        row_tiles=row_tiles, pair_budget=pair_budget,
    )
    tile_total = int(tile_total)
    pb = int(pb)
    if tile_total > pb:
        # Same exact-retry contract as the device route, handled here
        # (the caller's ladder never sees a truncated host graph).
        rows, cols, tile_total2, pb = _graph_live_pairs(
            points, mask, eps, block=block, layout=layout,
            row_tiles=row_tiles,
            pair_budget=int(-(-tile_total // 4096)) * 4096,
        )
        tile_total, pb = int(tile_total2), int(pb)
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    keep = rows < nt  # drop padding/row-restricted pairs host-side
    rows, cols = rows[keep], cols[keep]
    chunk = max(1, min(max(len(rows), 1), (1 << 22) // (block * block)))
    out_i, out_j, out_d = [], [], []
    for s in range(0, len(rows), chunk):
        rc = rows[s:s + chunk]
        cc = cols[s:s + chunk]
        if len(rc) < chunk:  # pad to the compiled chunk shape
            pad = chunk - len(rc)
            rc = np.concatenate([rc, np.full(pad, nt, np.int32)])
            cc = np.concatenate([cc, np.zeros(pad, np.int32)])
        live, dval = _graph_chunk(
            points, mask, eps, jnp.asarray(rc), jnp.asarray(cc),
            metric=metric, block=block, precision=prec, layout=layout,
        )
        live = np.asarray(live)
        dval = np.asarray(dval)
        p, i, j = np.nonzero(live)
        out_i.append((rc[p] * block + i).astype(np.int32))
        out_j.append((cc[p] * block + j).astype(np.int32))
        out_d.append(dval[p, i, j])
    gi = (
        np.concatenate(out_i) if out_i else np.empty(0, np.int32)
    )
    gj = (
        np.concatenate(out_j) if out_j else np.empty(0, np.int32)
    )
    dv = (
        np.concatenate(out_d) if out_d else np.empty(0, np.float32)
    )
    stats = np.array([len(gi), len(gi), tile_total, pb], np.int32)
    return gi, gj, dv, stats
