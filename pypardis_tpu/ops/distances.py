"""Tiled eps-neighborhood primitives.

The reference delegates the eps-radius region query to sklearn's ball
tree / brute force inside each Spark partition
(``/root/reference/dbscan/dbscan.py:28-30``).  On TPU the same query is a
streamed block-pairwise computation: squared Euclidean distances decompose
into ``|x|^2 + |y|^2 - 2 x @ y.T`` so the dominant cost is a matmul on the
MXU; the (rows x cols) tile is consumed immediately by a compare-and-reduce
so the N x N interaction never hits HBM.

Everything here is shape-static and jit/shard_map-safe: callers pad point
sets to a fixed capacity and pass a validity mask.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_INT_INF = jnp.iinfo(jnp.int32).max


def _norm_metric(metric) -> str:
    """Accept reference-style metric spec: string or scipy callable.

    The reference takes a *callable* defaulting to
    ``scipy.spatial.distance.euclidean`` and documents that only
    Euclidean / cityblock are safe because box expansion is L-inf
    (dbscan.py:74-91).  We accept those callables by name plus the usual
    string spellings.
    """
    if callable(metric):
        metric = getattr(metric, "__name__", str(metric))
    metric = str(metric).lower()
    if metric in ("euclidean", "l2"):
        return "euclidean"
    if metric == "sqeuclidean":
        # sqeuclidean thresholds *squared* distance at eps — silently
        # aliasing it to euclidean would change eps semantics.
        raise ValueError(
            "metric 'sqeuclidean' is not supported: its eps thresholds "
            "squared distance; use metric='euclidean' with eps=sqrt(eps)"
        )
    if metric in ("cityblock", "manhattan", "l1"):
        return "cityblock"
    raise ValueError(
        f"unsupported metric {metric!r}: TPU path supports euclidean and "
        "cityblock (the reference documents the same restriction, "
        "dbscan.py:88-91)"
    )


def pairwise_sq_dists(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """(n, d) x (m, d) → (n, m) squared Euclidean distances (one tile)."""
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    xx = jnp.sum(x * x, axis=1, keepdims=True)
    yy = jnp.sum(y * y, axis=1, keepdims=True)
    d2 = xx + yy.T - 2.0 * jax.lax.dot(
        x, y.T, precision=jax.lax.Precision.HIGHEST
    )
    return jnp.maximum(d2, 0.0)


def _tile_adjacency(xi, yj, eps, metric):
    """(br, d) x (bc, d) → (br, bc) bool: within eps under ``metric``."""
    if metric == "euclidean":
        return pairwise_sq_dists(xi, yj) <= eps * eps
    # cityblock: no matmul decomposition; broadcast |xi - yj| sum on VPU.
    d1 = jnp.sum(jnp.abs(xi[:, None, :] - yj[None, :, :]), axis=-1)
    return d1 <= eps


def _tiles(points, mask, block):
    n = points.shape[0]
    assert n % block == 0, (n, block)
    nt = n // block
    pts = points.reshape(nt, block, points.shape[1])
    msk = mask.reshape(nt, block)
    return nt, pts, msk


@functools.partial(
    jax.jit, static_argnames=("metric", "block")
)
def neighbor_counts(
    points: jnp.ndarray,
    eps: float,
    mask: jnp.ndarray,
    metric: str = "euclidean",
    block: int = 1024,
) -> jnp.ndarray:
    """Per-point count of valid points within eps (self included).

    ``points``: (N, d) with N a multiple of ``block``; ``mask``: (N,) bool.
    Returns (N,) int32.  Row tiles map over the grid; column tiles are a
    ``lax.scan`` accumulation, so peak memory is O(block^2).
    """
    metric = _norm_metric(metric)
    nt, pts, msk = _tiles(points, mask, block)

    def row_tile(xi, mi):
        def col_step(acc, jc):
            yj, mj = pts[jc], msk[jc]
            adj = _tile_adjacency(xi, yj, eps, metric) & mj[None, :]
            return acc + jnp.sum(adj, axis=1, dtype=jnp.int32), None

        acc0 = jnp.zeros((block,), jnp.int32)
        counts, _ = jax.lax.scan(col_step, acc0, jnp.arange(nt))
        return jnp.where(mi, counts, 0)

    counts = jax.lax.map(lambda args: row_tile(*args), (pts, msk))
    return counts.reshape(-1)


@functools.partial(
    jax.jit, static_argnames=("metric", "block")
)
def min_neighbor_label(
    points: jnp.ndarray,
    labels: jnp.ndarray,
    eps: float,
    src_mask: jnp.ndarray,
    metric: str = "euclidean",
    block: int = 1024,
) -> jnp.ndarray:
    """Per-point min label over eps-neighbors drawn from ``src_mask``.

    ``labels``: (N,) int32 (INT32_MAX = no label).  Only neighbors with
    ``src_mask[j]`` contribute.  Returns (N,) int32, INT32_MAX where no
    masked neighbor is within eps.  This single primitive powers both the
    core-graph min-propagation step and the border-point assignment pass.
    """
    metric = _norm_metric(metric)
    nt, pts, _ = _tiles(points, src_mask, block)
    n = points.shape[0]
    lab = labels.reshape(nt, block)
    smsk = src_mask.reshape(nt, block)

    def row_tile(xi):
        def col_step(acc, jc):
            yj, mj, lj = pts[jc], smsk[jc], lab[jc]
            adj = _tile_adjacency(xi, yj, eps, metric) & mj[None, :]
            cand = jnp.where(adj, lj[None, :], _INT_INF)
            return jnp.minimum(acc, jnp.min(cand, axis=1)), None

        acc0 = jnp.full((block,), _INT_INF, jnp.int32)
        best, _ = jax.lax.scan(col_step, acc0, jnp.arange(nt))
        return best

    best = jax.lax.map(row_tile, pts)
    return best.reshape(-1)
