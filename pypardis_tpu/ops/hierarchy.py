"""Density hierarchy over one cached neighbor-pair graph.

PR 13's sweep amortizes k eps-configs into ONE distance pass by caching
the ``(i, j, d2)`` triples at ``eps_max``.  That graph subsumes the
*entire continuous clustering family* below the ceiling (the OPTICS
observation, Ankerst et al. SIGMOD 1999), and HDBSCAN\\* (Campello et
al., PAKDD 2013) shows the family collapses to a minimum spanning tree
over MUTUAL-REACHABILITY distances plus a stability rule:

  ``mreach(i, j) = max(core_k(i), core_k(j), d(i, j))``

where ``core_k(p)`` is the distance to p's ``min_samples``-th neighbor.
Single-linkage over mreach IS the DBSCAN* hierarchy — cutting the MST
at any threshold reproduces the core-core components of a DBSCAN fit at
that eps — so every cut, the condensed dendrogram, and the
excess-of-mass flat selection all come out of the one cached graph with
no further distance work.

Everything here operates in ONE id space (kernel slots for the fused
route, global gids for the sharded routes) on the host-compacted slab;
the caller owns the mapping back to input rows.  Thresholds live in the
KERNEL d2 domain (squared L2, or L1 for cityblock) and compare in
float32 exactly as :func:`pypardis_tpu.ops.labels.graph_dbscan_host`
does, which is what makes :meth:`Hierarchy.labels_at_thr` byte-identical
to the relabel engine at the same threshold — the correctness backbone
pinned in ``tests/test_hierarchy.py``:

* ``cd2(p) <= thr``  ⟺  p has >= min_samples row entries within thr
  (same row, same f32 values — the k-th smallest of the row), which is
  exactly the relabel engine's ``max(counts, 1) >= min_samples`` core
  rule for ``min_samples >= 2`` (``min_samples <= 1`` pins cd2 = 0, the
  self-count clamp);
* a candidate edge has ``mreach2 <= thr``  ⟺  the pair is adjacent at
  thr AND both endpoints are core at thr, so the mreach graph's
  thr-prefix components equal the core-core subgraph components; and
* any MST of that graph preserves per-threshold connectivity (the
  Kruskal prefix property), so a union-find over the MST edges with
  ``w <= thr`` — ~n edges instead of the full pair list — yields the
  same min-core-id roots.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .._native import uf_resolve_dense
from .labels import _INT_INF

_I64_INF = np.int64(np.iinfo(np.int64).max)


# ---------------------------------------------------------------------------
# threshold <-> user-eps frame maps
#
# The slab's d2 values are in the KERNEL frame; user-facing eps is in
# the driver frame for cosine/haversine.  The forward map replicates the
# engines' round trip EXACTLY (f64 driver remap, then the f32 square of
# graph_dbscan_host) so a ladder eps chosen here re-thresholds to the
# intended prefix when a solo fit or a sweep config runs it.
# ---------------------------------------------------------------------------


def thr_from_user_eps(eps_u: float, frame: str) -> np.float32:
    """User-frame eps -> internal f32 threshold (the engine round trip)."""
    if frame == "cityblock":
        return np.float32(eps_u)
    if frame == "cosine":
        e = np.float32(np.sqrt(2.0 * eps_u))
    elif frame == "haversine":
        e = np.float32(2.0 * np.sin(eps_u / 2.0))
    else:
        e = np.float32(eps_u)
    return e * e


def user_eps_from_thr(thr: float, frame: str) -> float:
    """Internal threshold -> user-frame eps (f64 inverse of the remap)."""
    t = float(thr)
    if frame == "cityblock":
        return t
    if frame == "cosine":
        return t / 2.0
    if frame == "haversine":
        return float(2.0 * np.arcsin(min(np.sqrt(t) / 2.0, 1.0)))
    return float(np.sqrt(t))


# ---------------------------------------------------------------------------
# prepare + core distances
# ---------------------------------------------------------------------------


def hierarchy_prepare(gi, gj, dval):
    """Sort-once slab state for the hierarchy AND the host relabel.

    Like :func:`~pypardis_tpu.ops.labels.graph_dbscan_host_prepare` but
    rows are additionally sorted by ascending dval WITHIN each row
    (``np.lexsort`` with gi primary), so the ``min_samples``-th smallest
    of a row is a direct index — the k-th-smallest segment reduction.
    ``graph_dbscan_host`` only needs row contiguity for its reduceat
    calls, so this state is a drop-in for it too: one sort serves both
    the per-config relabel and every hierarchy pass.
    """
    gi = np.asarray(gi, np.int64)
    gj = np.asarray(gj, np.int64)
    dv = np.asarray(dval, np.float32)
    order = np.lexsort((dv, gi))
    gi_s = gi[order]
    gj_s = gj[order]
    dv_s = dv[order]
    if len(gi_s):
        starts = np.concatenate(
            [[0], np.flatnonzero(np.diff(gi_s)) + 1]
        ).astype(np.int64)
        uniq = gi_s[starts]
    else:
        starts = np.empty(0, np.int64)
        uniq = np.empty(0, np.int64)
    return gi_s, gj_s, dv_s, starts, uniq


def core_distances(state, mask, min_samples: int) -> np.ndarray:
    """Per-point squared core distance from the prepared slab.

    ``cd2[p]`` = the ``min_samples``-th smallest dval of p's row (+inf
    when the row is shorter — never core below the ceiling), except
    ``min_samples <= 1`` pins valid points to 0: the engines' self-count
    clamp (``max(counts, 1)``) makes every valid point core at any eps,
    and a zero core distance reproduces that.  Device-slab +inf padding
    sorts to the tail of row 0 and can only ever select +inf — inert.
    """
    gi_s, gj_s, dv_s, starts, uniq = state
    mask = np.asarray(mask, bool)
    n = len(mask)
    ms = int(min_samples)
    cd2 = np.full(n, np.inf, np.float32)
    if ms <= 1:
        cd2[mask] = np.float32(0.0)
        return cd2
    if len(starts):
        counts = np.diff(np.append(starts, len(gi_s)))
        has = counts >= ms
        cd2[uniq[has]] = dv_s[starts[has] + (ms - 1)]
    cd2[~mask] = np.inf
    return cd2


@jax.jit
def core_distances_device(gi, gj, dval, mask, min_samples):
    """Jitted device twin of :func:`core_distances` (same f32 values).

    One lexsort + a first-occurrence rank turns the k-th-smallest
    segment reduction into a single masked scatter-min — no host round
    trip for the accelerator routes.  ``min_samples`` is traced, so one
    compiled program serves every config.
    """
    n = mask.shape[0]
    order = jnp.lexsort((dval, gi))
    gi_s = gi[order].astype(jnp.int32)
    dv_s = dval[order]
    first = jnp.searchsorted(gi_s, gi_s, side="left")
    rank = jnp.arange(gi_s.shape[0], dtype=jnp.int32) - first.astype(
        jnp.int32
    )
    ms = jnp.asarray(min_samples, jnp.int32)
    hit = rank == (ms - 1)
    # Dump slot n for the non-hits; clip keeps the scatter in range.
    tgt = jnp.where(hit, jnp.clip(gi_s, 0, n), n)
    cd2 = jnp.full(n + 1, jnp.inf, jnp.float32).at[tgt].min(
        jnp.where(hit, dv_s, jnp.inf)
    )[:n]
    cd2 = jnp.where(mask, cd2, jnp.inf)
    return jnp.where(
        ms <= 1, jnp.where(mask, jnp.float32(0.0), jnp.inf), cd2
    )


# ---------------------------------------------------------------------------
# mutual-reachability MST — Borůvka rounds over the compacted pair list
# ---------------------------------------------------------------------------


def mutual_reachability_mst(state, cd2, n: int):
    """Borůvka MST over the mutual-reachability graph.

    Candidate edges are the canonical (i < j) half of the slab with
    ``w = max(cd2[i], cd2[j], dval)`` finite; +inf padding and edges
    touching never-core points drop out here.  Edges get a unique rank
    by ``lexsort((j, i, w))`` — a total order, so each component's
    minimum incident edge is deterministic and the chosen set is
    cycle-free without any tie-handling.  Each round is a segment-min
    (``np.minimum.at`` over component labels) + a union-find
    contraction — the pmin-fixpoint shape of
    ``parallel/merge.resolve_label_edges``, which also supplies the
    min-id root convention.

    Returns ``(mi, mj, mw, info)`` with mw ascending-rank-ordered and
    ``info`` carrying ``boruvka_rounds`` / ``n_live`` /
    ``n_components`` / ``round_cap`` (the ``ceil(log2(C0)) + 1``
    convergence bound the probe pins).
    """
    gi_s, gj_s, dv_s, starts, uniq = state
    w = np.maximum(dv_s, np.maximum(cd2[gi_s], cd2[gj_s]))
    sel = (gi_s < gj_s) & np.isfinite(w)
    mi = gi_s[sel]
    mj = gj_s[sel]
    mw = w[sel].astype(np.float32)
    order = np.lexsort((mj, mi, mw))
    mi, mj, mw = mi[order], mj[order], mw[order]
    m = len(mi)
    live_ids = np.unique(np.concatenate([mi, mj])) if m else mi
    n_live = int(len(live_ids))
    chosen = np.zeros(m, bool)
    lab = np.arange(n, dtype=np.int64)
    ranks = np.arange(m, dtype=np.int64)
    rounds = 0
    c0 = 0
    while m:
        a = lab[mi]
        b = lab[mj]
        live = a != b
        if not live.any():
            break
        if rounds == 0:
            c0 = int(len(np.unique(np.concatenate([a[live], b[live]]))))
        rounds += 1
        best = np.full(n, _I64_INF)
        np.minimum.at(best, a[live], ranks[live])
        np.minimum.at(best, b[live], ranks[live])
        chosen[best[best < _I64_INF]] = True
        lab = uf_resolve_dense(
            np.stack([mi[chosen], mj[chosen]], axis=1), n
        )
    idx = np.flatnonzero(chosen)
    n_components = (
        int(len(np.unique(lab[live_ids]))) if n_live else 0
    )
    info = {
        "mst_edges": int(len(idx)),
        "boruvka_rounds": int(rounds),
        "round_cap": int(np.ceil(np.log2(max(c0, 2)))) + 1,
        "n_live": n_live,
        "n_components": n_components,
        "candidate_edges": int(m),
    }
    return mi[idx], mj[idx], mw[idx], info


# ---------------------------------------------------------------------------
# dendrogram: Kruskal merge forest -> condensed tree -> stability
# ---------------------------------------------------------------------------


def _merge_forest(mi, mj, mw, n: int):
    """Kruskal merge sequence over the MST edges (ascending (w, i, j)).

    Returns ``(left, right, weight, size, roots)`` — internal node t
    has id ``n + t``; ``roots`` are the final tree-node ids of the
    forest (one per connected component of the mreach graph).
    """
    order = np.lexsort((mj, mi, mw))
    ei, ej, ew = mi[order], mj[order], mw[order]
    m = len(ei)
    parent = np.arange(n, dtype=np.int64)

    def find(x: int) -> int:
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    node = np.arange(n, dtype=np.int64)
    size = np.ones(n, np.int64)
    left = np.empty(m, np.int64)
    right = np.empty(m, np.int64)
    weight = np.empty(m, np.float64)
    msize = np.empty(m, np.int64)
    for t in range(m):
        ra, rb = find(int(ei[t])), find(int(ej[t]))
        left[t], right[t] = node[ra], node[rb]
        weight[t] = ew[t]
        msize[t] = size[ra] + size[rb]
        parent[rb] = ra
        size[ra] += size[rb]
        node[ra] = n + t
    seen = set()
    roots = []
    for p in np.unique(np.concatenate([ei, ej])) if m else []:
        r = find(int(p))
        if r not in seen:
            seen.add(r)
            roots.append(int(node[r]))
    return left, right, weight, msize, sorted(roots)


class _Cluster:
    """One condensed cluster: alive for ``thr in [end_w, birth_w)``."""

    __slots__ = (
        "cid", "parent", "birth_w", "end_w", "size", "exits",
        "children", "stability",
    )

    def __init__(self, cid, parent, birth_w, size):
        self.cid = cid
        self.parent = parent
        self.birth_w = birth_w
        self.end_w = 0.0
        self.size = size
        self.exits: List[Tuple[float, int]] = []
        self.children: List[int] = []
        self.stability = 0.0


class Hierarchy:
    """Condensed density hierarchy + flat-cut machinery over one slab.

    Built by :func:`build_hierarchy`; ``labels_at_thr`` is the cheap
    per-cut path (union-find over ~n MST edges + one reduceat border
    attach — no per-config fixpoint), byte-identical to
    ``graph_dbscan_host`` at the same threshold and min_samples.
    """

    def __init__(self, state, mask, n, min_samples, kernel_metric,
                 user_frame, thr_max, cd2, mst, info):
        self.state = state
        self.mask = np.asarray(mask, bool)
        self.n = int(n)
        self.min_samples = int(min_samples)
        self.kernel_metric = kernel_metric
        self.user_frame = user_frame
        self.thr_max = float(thr_max)
        self.cd2 = cd2
        self.mi, self.mj, self.mw = mst
        self.info = dict(info)
        self.clusters: List[_Cluster] = []
        self.selected: List[int] = []
        self._lambda_floor = 1e-12

    # -- flat labels -----------------------------------------------------

    def labels_at_thr(self, thr):
        """Flat labels at an internal f32 threshold (slab id space).

        Same fixpoint as the relabel engine at THIS hierarchy's
        min_samples (the MST's weights bake in these core distances —
        a different min_samples needs its own :func:`build_hierarchy`
        over the shared prepared state): core by core-distance (== the
        counts rule), components by union-find over the MST's
        thr-prefix (== core-core components, see module docstring),
        min-core-id roots, borders to the min adjacent core root.
        Returns ``(labels, core)``; the caller densifies / unscatters.
        """
        gi_s, gj_s, dv_s, starts, uniq = self.state
        thr_f = np.float32(thr)
        if self.min_samples <= 1:
            core = self.mask.copy()
        else:
            core = (self.cd2 <= thr_f) & self.mask
        sel = self.mw <= thr_f
        roots = uf_resolve_dense(
            np.stack([self.mi[sel], self.mj[sel]], axis=1), self.n
        )
        f = np.where(core, roots, np.int64(_INT_INF))
        adj = dv_s <= thr_f
        border = np.full(self.n, np.int64(_INT_INF))
        if len(starts):
            cand = np.where(
                adj & core[gj_s], f[gj_s], np.int64(_INT_INF)
            )
            border[uniq] = np.minimum.reduceat(cand, starts)
        labels = np.where(
            core, f,
            np.where(self.mask & (border != _INT_INF), border, -1),
        ).astype(np.int32)
        return labels, core

    # -- condensation ----------------------------------------------------

    def _lam(self, w: float, birth: bool = False) -> float:
        """HDBSCAN*'s lambda = 1 / distance, in the USER frame.

        Duplicate points give zero-distance merges; the floor (half the
        smallest positive distance in the tree, data-deterministic)
        keeps lambda finite without reordering any comparisons.  Birth
        weights clamp at the graph ceiling: the cached family is
        truncated at eps_max, so a root component's stability honestly
        starts there instead of pretending the cluster was born at
        infinite distance.
        """
        if birth:
            w = min(w, self.thr_max)
        d = user_eps_from_thr(w, self.user_frame)
        return 1.0 / max(d, self._lambda_floor)

    def condense(self, min_cluster_size: int) -> None:
        """Condense the merge forest by ``min_cluster_size`` and score
        every condensed cluster with the excess-of-mass stability
        ``sum_p (lambda_exit(p) - lambda_birth)``, then run the EOM
        bottom-up selection (a cluster beats its subtree iff its own
        stability >= the sum of the children's winning subtrees)."""
        mcs = int(min_cluster_size)
        left, right, weight, msize, roots = _merge_forest(
            self.mi, self.mj, self.mw, self.n
        )
        pos_d = [
            user_eps_from_thr(w, self.user_frame)
            for w in np.unique(weight) if w > 0
        ]
        self._lambda_floor = (
            0.5 * min(pos_d) if pos_d else 1e-12
        )
        self.clusters = []
        n = self.n

        def nsize(node: int) -> int:
            return 1 if node < n else int(msize[node - n])

        stack: List[Tuple[int, int]] = []  # (tree node, cluster idx)
        for r in roots:
            if nsize(r) < mcs:
                continue
            c = _Cluster(len(self.clusters), None, np.inf, nsize(r))
            self.clusters.append(c)
            stack.append((r, c.cid))
        while stack:
            node, cid = stack.pop()
            c = self.clusters[cid]
            while True:
                if node < n:
                    # mcs >= 2, so a bare leaf only arises for a
                    # degenerate 1-point component — closed above.
                    c.end_w = 0.0
                    break
                t = node - n
                a, b = int(left[t]), int(right[t])
                sa, sb = nsize(a), nsize(b)
                w = float(weight[t])
                if sa >= mcs and sb >= mcs:
                    c.end_w = w
                    for child in (a, b):
                        cc = _Cluster(
                            len(self.clusters), cid, w, nsize(child)
                        )
                        c.children.append(cc.cid)
                        self.clusters.append(cc)
                        stack.append((child, cc.cid))
                    break
                if sa < mcs and sb < mcs:
                    c.exits.append((w, sa + sb))
                    c.end_w = w
                    break
                keep, drop = (a, b) if sa >= mcs else (b, a)
                c.exits.append((w, nsize(drop)))
                node = keep
        for c in self.clusters:
            lb = self._lam(c.birth_w, birth=True)
            c.stability = sum(
                (self._lam(w) - lb) * cnt for w, cnt in c.exits
            )
            for ch in c.children:
                c.stability += (
                    self._lam(self.clusters[ch].birth_w) - lb
                ) * self.clusters[ch].size
        # EOM bottom-up: children were appended after their parent, so
        # reverse construction order IS leaves-first.
        subtree = [0.0] * len(self.clusters)
        wins = [False] * len(self.clusters)
        for c in reversed(self.clusters):
            kids = sum(subtree[ch] for ch in c.children)
            if not c.children or c.stability >= kids:
                wins[c.cid] = True
                subtree[c.cid] = c.stability
            else:
                subtree[c.cid] = kids
        self.selected = []
        blocked = [False] * len(self.clusters)
        for c in self.clusters:  # top-down: parents precede children
            if blocked[c.cid] or not wins[c.cid]:
                continue
            self.selected.append(c.cid)
            desc = list(c.children)
            while desc:
                d = desc.pop()
                blocked[d] = True
                desc.extend(self.clusters[d].children)
        self.info["condensed_clusters"] = len(self.clusters)
        self.info["selected_clusters"] = len(self.selected)
        self.info["stability_total"] = round(
            float(sum(self.clusters[c].stability for c in self.selected)),
            6,
        )

    # -- flat-cut selection ---------------------------------------------

    def _cut_candidates(self) -> np.ndarray:
        ws = np.unique(self.mw.astype(np.float64))
        ws = ws[np.isfinite(ws)]
        return np.append(ws, self.thr_max) if len(ws) else np.asarray(
            [self.thr_max]
        )

    def cut_scores(self) -> List[Tuple[float, float]]:
        """``(thr, score)`` per candidate cut — score is the summed
        stability of EOM-selected clusters alive at thr (alive:
        ``end_w <= thr < birth_w``; labels are constant between
        consecutive distinct MST weights, so these are ALL the distinct
        cuts the family has).  Sweep-line over birth/death events: one
        cumsum instead of a cuts x clusters scan."""
        cands = self._cut_candidates()
        add = np.zeros(len(cands), np.float64)
        if self.selected:
            ends = np.asarray(
                [self.clusters[c].end_w for c in self.selected]
            )
            births = np.asarray(
                [self.clusters[c].birth_w for c in self.selected]
            )
            stabs = np.asarray(
                [self.clusters[c].stability for c in self.selected]
            )
            on = np.searchsorted(cands, ends, side="left")
            off = np.searchsorted(cands, births, side="left")
            np.add.at(add, on[on < len(cands)], stabs[on < len(cands)])
            np.subtract.at(
                add, off[off < len(cands)], stabs[off < len(cands)]
            )
        scores = np.cumsum(add)
        return [(float(t), float(s)) for t, s in zip(cands, scores)]

    def select_cut(self) -> Tuple[float, float]:
        """The stability-selected flat cut: ``(thr_star, eps_user)``.

        Argmax of :meth:`cut_scores`; ties break toward the LARGER
        threshold (fewer noise points for equal stability mass).  The
        returned eps is the f64 midpoint of ``[thr_star, next distinct
        weight)`` mapped to the user frame, round-trip-checked so a solo
        ``fit(eps)`` re-thresholds inside the same interval — with the
        exact boundary as the deterministic fallback when the interval
        is too narrow (< 4 ulps) to hold a midpoint.
        """
        cands = self._cut_candidates()
        scores = self.cut_scores()
        best_thr, best_s = scores[0]
        for thr, s in scores[1:]:
            if s > best_s or (s == best_s and thr > best_thr):
                best_thr, best_s = thr, s
        self.info["cut_thr"] = float(best_thr)
        self.info["cut_score"] = round(float(best_s), 6)
        nxt = cands[cands > best_thr]
        hi = float(nxt[0]) if len(nxt) else float(
            np.nextafter(np.float32(best_thr), np.float32(np.inf))
        )
        return float(best_thr), self._interval_eps(best_thr, hi)

    def _interval_eps(self, lo: float, hi: float) -> float:
        """A user-frame eps whose engine round trip lands in [lo, hi).

        Labels are constant on the interval, so ANY such eps names the
        same clustering; the midpoint maximizes slack against the f32
        re-square.  Falls back to the exact lower boundary (always
        representable: slab weights ARE f32 values) if the round trip
        escapes — e.g. a sub-4-ulp interval.
        """
        wide = (hi - lo) >= 4 * float(
            np.spacing(np.float32(max(lo, 1e-30)))
        )
        if wide:
            mid = 0.5 * (lo + hi)
            eps_u = user_eps_from_thr(mid, self.user_frame)
            rt = float(thr_from_user_eps(eps_u, self.user_frame))
            if lo <= rt < hi:
                return eps_u
        return user_eps_from_thr(lo, self.user_frame)

    def eps_ladder(self, k: int) -> List[float]:
        """Top-``k``-stability eps ladder for ``sweep(eps_list="auto")``.

        Candidate cuts ranked by :meth:`cut_scores` (ties toward larger
        thr), each mapped to a round-trip-safe user eps; deduplicated,
        returned ASCENDING so the sweep's eps_max is the last rung.
        Fewer than k distinct cuts return what exists.
        """
        cands = self._cut_candidates()
        ranked = sorted(
            self.cut_scores(), key=lambda ts: (-ts[1], -ts[0])
        )
        out: List[float] = []
        for thr, _s in ranked:
            if len(out) >= int(k):
                break
            nxt = cands[cands > thr]
            hi = float(nxt[0]) if len(nxt) else float(
                np.nextafter(np.float32(thr), np.float32(np.inf))
            )
            eps_u = self._interval_eps(thr, hi)
            if eps_u > 0 and eps_u not in out:
                out.append(eps_u)
        return sorted(out)

    def telemetry(self) -> Dict:
        """The ``report()["hierarchy"]`` block body (caller adds the
        route/timing fields it owns)."""
        return dict(self.info)


def build_hierarchy(
    state,
    mask,
    n: int,
    min_samples: int,
    *,
    kernel_metric: str = "euclidean",
    user_frame: str = "euclidean",
    thr_max: float,
    min_cluster_size: Optional[int] = None,
    cd2: Optional[np.ndarray] = None,
) -> Hierarchy:
    """Core distances + Borůvka MST + condensed tree in one call.

    ``state`` comes from :func:`hierarchy_prepare` (dv-sorted rows);
    ``thr_max`` is the graph ceiling in the internal d2/d1 domain;
    ``cd2`` may be passed in when the jitted device twin already
    computed it (must equal the host values bitwise — pinned in tests).
    ``min_cluster_size`` defaults to ``max(min_samples, 2)``.
    """
    mcs = (
        max(int(min_samples), 2) if min_cluster_size is None
        else int(min_cluster_size)
    )
    if mcs < 2:
        raise ValueError(
            f"min_cluster_size must be >= 2, got {min_cluster_size}"
        )
    t0 = time.perf_counter()
    if cd2 is None:
        cd2 = core_distances(state, mask, min_samples)
    t1 = time.perf_counter()
    mi, mj, mw, info = mutual_reachability_mst(state, cd2, n)
    t2 = time.perf_counter()
    h = Hierarchy(
        state, mask, n, min_samples, kernel_metric, user_frame,
        thr_max, cd2, (mi, mj, mw), info,
    )
    h.condense(mcs)
    t3 = time.perf_counter()
    h.info["min_cluster_size"] = mcs
    h.info["core_pass_s"] = round(t1 - t0, 6)
    h.info["mst_s"] = round(t2 - t1, 6)
    h.info["condense_s"] = round(t3 - t2, 6)
    return h
