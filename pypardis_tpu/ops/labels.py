"""Parallel DBSCAN labeling as core-graph connected components.

Textbook DBSCAN (and sklearn's implementation, which the reference calls at
``/root/reference/dbscan/dbscan.py:28-30``) expands clusters sequentially
by region queries — unusable under XLA's static-trace model.  The parallel
formulation: a point is *core* iff >= min_samples valid points lie within
eps; clusters are the connected components of the graph on core points with
edges at distance <= eps; border points attach to any adjacent core point;
everything else is noise.

Components are found by min-label propagation with pointer-jumping
shortcuts (the FastSV/Shiloach-Vishkin family): each core point starts
labeled with its own index, repeatedly takes the min label among its core
eps-neighbors (one tiled N^2 pass on the MXU), then chases labels
transitively (cheap gathers) until a fixpoint.  Everything is
fixed-shape: `lax.while_loop` over a bounded iteration count.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .distances import min_neighbor_label, neighbor_counts

_INT_INF = jnp.iinfo(jnp.int32).max


def _is_mixed(precision) -> bool:
    from .precision import norm_precision_mode

    return norm_precision_mode(precision) == "mixed"


def _band_zeros():
    return jnp.zeros(2, jnp.int32)


def _split_band(out, banded: bool):
    """Normalize a kernel result to ``(result, band_stats)``.

    The kernel entry points return ``(result, (2,) int32)`` under
    ``precision="mixed"`` OR an active sketch prefilter and the bare
    result otherwise; every band-stats consumer in this module goes
    through this one helper so the convention cannot be half-applied.
    """
    if banded:
        return out
    return out, _band_zeros()


def _resolve_sketch(sketch, d: int, metric) -> int:
    """The labels-layer sketch resolution: ``None`` defers to the
    ``PYPARDIS_SKETCH`` trace-time policy
    (:func:`pypardis_tpu.ops.sketch.sketch_dims` — the dispatch-knob
    discipline: baked into compiled programs, a flip needs
    ``jax.clear_caches()``); anything else is a pinned spec."""
    from .distances import _norm_metric
    from .sketch import resolve_sketch, sketch_dims

    m = _norm_metric(metric)
    if sketch is None:
        return sketch_dims(d, m)
    return resolve_sketch(sketch, d, m)


def pair_dispatch(metric, nt: int | None = None) -> bool:
    """Whether the XLA kernels run the compacted pair-list dispatch
    for this metric and grid size: the ``PYPARDIS_DISPATCH`` policy
    (auto-by-size / pair / dense), restricted to euclidean — the
    box-gap pair extraction is a squared-distance discipline, so
    cityblock stays on the dense grid."""
    from .distances import _norm_metric, pair_dispatch_enabled

    return (
        pair_dispatch_enabled(nt) and _norm_metric(metric) == "euclidean"
    )


def resolve_backend(
    backend: str, metric: str, n: int = 0, block: int = 1,
    d: int = 2, precision: str = "high",
) -> str:
    """Resolve "auto" to "pallas" on TPU (Euclidean only) else "xla".

    The Pallas kernels require Mosaic (TPU) and the matmul distance
    decomposition; everything else — CPU test meshes, cityblock — runs
    the pure-XLA tiled path with identical semantics.  Problems smaller
    than a few tiles also stay on XLA: a hand-scheduled kernel buys
    nothing there, and sub-millisecond XLA programs sidestep launch
    overhead entirely.  Configs whose effective tile Mosaic cannot lower
    (trailing block dim not a multiple of 128 — e.g. user block=64, or
    an n with no 128-multiple divisor) also resolve to "xla"
    deliberately, instead of paying a lowering-failure/fallback cycle.
    """
    from .distances import _norm_metric

    metric = _norm_metric(metric)
    if backend == "auto":
        if (
            metric == "euclidean"
            and jax.default_backend() == "tpu"
            and n >= 4 * block
        ):
            from .pallas_kernels import _norm_precision_mode, effective_tile

            if effective_tile(
                block, n, d, _norm_precision_mode(precision)
            ) is not None:
                return "pallas"
        return "xla"
    if backend not in ("xla", "pallas"):
        raise ValueError(f"backend must be auto|xla|pallas, got {backend!r}")
    if backend == "pallas" and metric != "euclidean":
        raise ValueError(
            f"backend='pallas' supports only the euclidean metric, got "
            f"{metric!r}; use backend='auto' or 'xla'"
        )
    return backend


def gm_backend(
    backend: str, metric: str, n_total: int, owned: int, block: int,
    d: int, precision: str,
) -> str:
    """Backend routing for the global-Morton cross-shard boundary scan.

    The global-Morton cluster step runs the owner-computes kernels over
    an ``owned + boundary`` slab whose split point is the shard's row
    range — on the Pallas path that split must land on a tile boundary
    (:func:`pypardis_tpu.ops.pallas_kernels.gm_tile_aligned`).  When it
    cannot, ``"auto"`` routes to the XLA kernels EXPLICITLY (they have
    no alignment constraint and identical semantics) and an explicit
    ``backend='pallas'`` fails loudly up front rather than surfacing a
    Mosaic lowering error from inside the exchange-fed program.
    """
    kind = resolve_backend(backend, metric, n_total, block, d, precision)
    if kind != "pallas":
        return backend
    from .pallas_kernels import _norm_precision_mode, gm_tile_aligned

    if gm_tile_aligned(
        block, n_total, owned, d, _norm_precision_mode(precision)
    ):
        return backend
    if backend == "pallas":
        raise ValueError(
            f"backend='pallas' cannot tile the global-Morton slab: the "
            f"effective tile does not divide the owned prefix "
            f"(owned={owned}, total={n_total}, block={block}); use "
            f"backend='auto' or 'xla'"
        )
    return "xla"


def is_kernel_lowering_error(exc: BaseException) -> bool:
    """True when ``exc`` plausibly comes from a Pallas kernel failing to
    lower or compile (Mosaic rejection, VMEM overflow, unsupported op).

    Used by the drivers to degrade ``backend='auto'`` to the XLA path
    with a warning instead of surfacing Mosaic internals to the user
    (round-2 regression: a lowering-illegal kernel made the *default*
    TPU path crash).  Walks the cause/context chain because JAX wraps
    compile errors at several layers.
    """
    seen = set()
    e: BaseException | None = exc
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        txt = f"{type(e).__name__}: {e}"
        if "Mosaic" in txt or "mosaic" in txt or "pallas" in txt.lower():
            return True
        e = e.__cause__ or e.__context__
    return False


def _pointer_jump(f: jnp.ndarray, active: jnp.ndarray) -> jnp.ndarray:
    """Chase f -> f[f] to a fixpoint (path shortcutting).

    ``f`` holds point indices for ``active`` points and INT32_MAX
    elsewhere; jumps only read entries belonging to active points, whose
    values are always valid indices.
    """

    def cond(state):
        f, changed = state
        return changed

    def body(state):
        f, _ = state
        tgt = jnp.clip(f, 0, f.shape[0] - 1)
        nxt = jnp.where(active, f[tgt], f)
        return nxt, jnp.any(nxt != f)

    f, _ = jax.lax.while_loop(cond, body, (f, jnp.bool_(True)))
    return f


def dbscan_fixed_size(
    points,
    eps,
    min_samples,
    mask,
    metric: str = "euclidean",
    block: int = 1024,
    max_rounds: int = 64,
    precision: str = "high",
    backend: str = "auto",
    layout: str = "nd",
    pair_budget: int | None = None,
    sketch: int | str | None = None,
):
    """Validating entry point for :func:`_dbscan_fixed_size_jit` (the
    jitted body, where ``eps`` may be a tracer and cannot be checked).
    Concrete hyperparameters reject here — ``eps=-0.3`` used to behave
    exactly like ``eps=0.3`` through the squared-distance kernels, and
    a typo'd ``precision``/``backend`` used to surface as an opaque
    error from deep inside the jit trace."""
    from ..utils.validate import (
        check_kernel_backend, check_precision, validate_params,
    )
    from .sketch import check_sketch_spec

    validate_params(eps, min_samples)
    check_precision(precision)
    check_kernel_backend(backend)
    sketch = check_sketch_spec(sketch)
    return _dbscan_fixed_size_jit(
        points, eps, min_samples, mask, metric=metric, block=block,
        max_rounds=max_rounds, precision=precision, backend=backend,
        layout=layout, pair_budget=pair_budget, sketch=sketch,
    )


# The wrapper keeps the jit surface callers rely on (tests drop cached
# executables through the public name).
dbscan_fixed_size.clear_cache = (  # type: ignore[attr-defined]
    lambda: _dbscan_fixed_size_jit.clear_cache()
)


@functools.partial(
    jax.jit,
    static_argnames=(
        "metric", "block", "max_rounds", "precision", "backend", "layout",
        "pair_budget", "sketch",
    ),
)
def _dbscan_fixed_size_jit(
    points: jnp.ndarray,
    eps: float,
    min_samples: int,
    mask: jnp.ndarray,
    metric: str = "euclidean",
    block: int = 1024,
    max_rounds: int = 64,
    precision: str = "high",
    backend: str = "auto",
    layout: str = "nd",
    pair_budget: int | None = None,
    sketch: int | str | None = None,
):
    """DBSCAN over a fixed-capacity padded point set.

    ``points``: (N, d) (``layout="nd"``) or transposed (d, N)
    (``layout="dn"`` — the memory-optimal device layout: XLA:TPU pads
    the minor axis of (N, small-d) buffers 8x), N a multiple of
    ``block``; ``mask``: (N,) bool validity.  Returns ``(labels, core,
    pair_stats)``:

    * ``pair_stats``: (5,) int32 ``[live_pairs_total, budget,
      kernel_passes, band_pairs, rescored_tiles]`` (width pinned by
      ``ops.precision.PAIR_STATS_WIDTH``).  The last two are the
      ``precision="mixed"`` COUNTS-PASS band telemetry (pairs whose
      fast-pass d^2 landed inside the rescore band, and tile-pair
      visits marked for the ``high`` rescore; classification is
      deterministic per pass, so one pass's measurement covers all —
      the propagation passes skip the bookkeeping) and are zero on
      every other precision.  On the Pallas path, the first two come from
      the tile-pair extraction: when ``total > budget`` the labels are
      INVALID — pairs were dropped — and the caller must rerun with
      ``pair_budget >= total`` (``pair_budget`` is static; the
      returned total is exact, so one retry always suffices).  The XLA
      path reports its true total with budget 0 ("cannot overflow") —
      or the caller's explicit ``pair_budget``, mirroring the overflow
      contract so the drivers' rerun ladder is exercisable off-TPU
      (labels stay valid either way).  ``kernel_passes`` counts the
      full tiled passes actually executed (1 counts pass + the
      propagation rounds + the border recompute when taken) — the
      ``passes`` term of the achieved-FLOP/s model in
      ``obs.report``.

    * ``labels``: (N,) int32 — the *root point index* of the point's
      cluster (min index over the component's core points), or -1 for
      noise/invalid.  Dense 0..C-1 ids are a host-side afterthought
      (:func:`densify_labels`); keeping roots on device makes labels
      globally meaningful across shards.
    * ``core``: (N,) bool — the eps/min_samples core test, matching
      sklearn's ``core_sample_indices_`` that the reference reads at
      dbscan.py:30.
    """
    if layout not in ("nd", "dn"):
        raise ValueError(f"layout must be 'nd' or 'dn', got {layout!r}")
    n = points.shape[0] if layout == "nd" else points.shape[1]
    d = points.shape[1] if layout == "nd" else points.shape[0]
    mixed = _is_mixed(precision)
    # Sketch resolution happens ONCE per trace and the same k threads
    # into the pair extraction and every pass — a half-sketched program
    # (sketch boxes feeding an unsketched kernel) would still be
    # correct but would silently lose the win.
    sk = _resolve_sketch(sketch, d, metric)
    banded = mixed or sk > 0
    if resolve_backend(backend, metric, n, block, d, precision) == "pallas":
        from .pallas_kernels import (
            _check_mosaic_tile,
            _norm_precision_mode,
            _pallas_block,
            kernel_pair_list,
            min_neighbor_label_pallas,
            neighbor_counts_pallas,
        )

        # Fail an explicitly-forced illegal tile BEFORE the pair-list
        # extraction runs (the most expensive pre-pass); 'auto' never
        # gets here (resolve_backend routes illegal tiles to XLA).
        # Off-TPU, forced-pallas runs go through the interpreter (test
        # harnesses monkeypatch interpret=True), which has no tiling
        # constraint — only gate on real Mosaic.
        _check_mosaic_tile(
            _pallas_block(block, n, d, _norm_precision_mode(precision)),
            n, interpret=jax.default_backend() != "tpu",
        )

        # Extract the live tile-pair list ONCE; every pass shares it.
        # It covers validity boxes — a superset of any per-pass source
        # subset (core masks), so sharing is sound.
        pairs, pair_stats = kernel_pair_list(
            points, eps, mask, block, precision, layout,
            budget=pair_budget, sketch=sk,
        )
        count_fn = functools.partial(
            neighbor_counts_pallas, block=block, precision=precision,
            layout=layout, pairs=pairs, sketch=sk,
        )
        minlab_fn = functools.partial(
            min_neighbor_label_pallas, block=block, precision=precision,
            layout=layout, pairs=pairs, sketch=sk,
        )
    elif pair_dispatch(metric, n // block):
        # Compacted dispatch (auto past PAIR_DISPATCH_MIN_TILES):
        # extract the live tile-pair list ONCE on the XLA kernels' own
        # grid and drive every pass over it — the same cell-list discipline the Pallas path has
        # always run, closing the dense-dispatch gap on the backend
        # the CPU mesh (and any Pallas fallback) actually exercises.
        # The stats carry the real [total, budget] overflow contract:
        # labels built from a truncated list are INVALID and the
        # drivers' ladder retries with the exact total.
        from .distances import xla_pair_list

        pairs, pair_stats = xla_pair_list(
            points, mask, eps, block, layout, budget=pair_budget,
            sketch=sk, precision=precision,
        )
        count_fn = functools.partial(
            neighbor_counts, metric=metric, block=block, precision=precision,
            layout=layout, pairs=pairs, sketch=sk,
        )
        minlab_fn = functools.partial(
            min_neighbor_label, metric=metric, block=block, precision=precision,
            layout=layout, pairs=pairs, sketch=sk,
        )
    else:
        count_fn = functools.partial(
            neighbor_counts, metric=metric, block=block, precision=precision,
            layout=layout, sketch=sk,
        )
        minlab_fn = functools.partial(
            min_neighbor_label, metric=metric, block=block, precision=precision,
            layout=layout, sketch=sk,
        )
        # Dense dispatch (PYPARDIS_DISPATCH=dense, or cityblock — its
        # boxes have no euclidean pair extraction).  Real [total,
        # budget] stats here too: budget == 0 when no static budget is
        # in play (the dense kernels never drop pairs) — drivers treat
        # 0 as "cannot overflow".  With an explicit pair_budget the
        # stats mirror the Pallas overflow contract, which is what
        # lets the drivers' rerun ladder exercise off-hardware.  The
        # count runs on the SAME effective tile the Pallas extraction
        # would use (when one exists): dense-mode hints share the
        # pallas grid (pair-mode hints key separately — see
        # utils.hints.dispatch_tag).
        from .distances import count_live_tile_pairs
        from .pallas_kernels import _norm_precision_mode, effective_tile

        count_block = effective_tile(
            block, n, d, _norm_precision_mode(precision)
        ) or block
        pair_stats = jnp.stack(
            [
                count_live_tile_pairs(
                    points, mask, eps, metric=metric, block=count_block,
                    layout=layout,
                ),
                jnp.int32(0 if pair_budget is None else pair_budget),
            ]
        )
    counts, band = _split_band(count_fn(points, eps, mask), banded)
    # A valid point always counts itself (distance 0 <= eps), but the
    # f32 |x|^2+|y|^2-2xy expansion can compute the self-pair a few ULP
    # above 0 and miss it once eps^2 sinks below that noise floor
    # (eps=1e-6 on unit-scale data) — clamping to 1 restores the exact
    # property with no false positives.
    core = (jnp.maximum(counts, 1) >= min_samples) & mask

    idx = jnp.arange(n, dtype=jnp.int32)
    f0 = jnp.where(core, idx, _INT_INF)

    def minlab_band(f):
        return _split_band(
            minlab_fn(points, f, eps, core, row_mask=mask), banded
        )

    def cond(state):
        f, g, changed, rounds, _band = state
        return changed & (rounds < max_rounds)

    def body(state):
        f, _, _, rounds, bacc = state
        # Hook: min label among core eps-neighbors (self included).
        # Rows cover the full valid mask (not just core) so the final
        # round's g doubles as the border-attach pass: at convergence g
        # is computed from the fixpoint labels, which is exactly "min
        # root among my core eps-neighbors" for every valid row.
        # Tradeoff: row bounds now include non-core valid points, which
        # can unskip a few extra column tiles per round — bounded in the
        # Morton-sorted layout (noise sits near its cluster, and column
        # tiles are core-masked, so noise-only row tiles still prune
        # everything) and repaid by dropping the whole post-loop pass.
        g, b = minlab_band(f)
        f_new = jnp.where(core, jnp.minimum(f, g), f)
        # Shortcut: chase pointers to the current root.
        f_new = _pointer_jump(f_new, core)
        return f_new, g, jnp.any(f_new != f), rounds + 1, bacc + b

    f, g, changed, rounds, band = jax.lax.while_loop(
        cond, body, (f0, f0, jnp.bool_(True), 0, band)
    )

    # Border points: nearest-core-label attach; noise: no core neighbor.
    # The carried g is that pass already — recompute only in the rare
    # exit-by-max_rounds case where g predates the final f.  (Under
    # vmap — the multi-partition-per-device layout — cond lowers to
    # select and both branches run, costing what the old unconditional
    # pass did; no worse, and the common one-partition path wins.)
    border, b_border = jax.lax.cond(
        changed,
        lambda: minlab_band(f),
        lambda: (g, _band_zeros()),
    )
    labels = jnp.where(
        core, f, jnp.where(mask & (border != _INT_INF), border, -1)
    ).astype(jnp.int32)
    # Tiled passes executed: the counts pass, one minlab per round, and
    # the border recompute when the loop exited at max_rounds.
    passes = 1 + rounds + changed.astype(jnp.int32)
    pair_stats = jnp.concatenate(
        [pair_stats[:2], passes[None], band + b_border]
    )
    return labels, core, pair_stats


# ---------------------------------------------------------------------------
# Owner-computes clustering: halo slots are adjacency evidence, never
# re-clustered.
#
# The legacy sharded step ran full DBSCAN over each partition's
# (owned + halo) slab — every halo point was neighbor-counted, core-
# tested and label-propagated a second time in every foreign partition
# (the reference's duplicate-points-into-neighborhoods design,
# PAPER.md steps 2-4; measured as a 3.16x duplicated-work tax at the
# r5 geometry).  The owner-computes formulation keeps the halo slots
# only as *evidence*:
#
# * counts run over OWNED rows only (halo columns still contribute, so
#   owned core status stays exact under the 2*eps halo guarantee);
# * halo core flags come from each point's OWNER (the home partition's
#   counts), not from a local recount;
# * the min-label propagation runs with (halo row, halo col) tile
#   pairs skipped: halo-core slots relay labels between owned clusters
#   they touch (a core halo point genuinely connects them), but
#   halo-halo edges are dropped — every such edge is some partition's
#   owned-halo edge (one endpoint is owned wherever it is home), so
#   the cross-partition merge recovers exactly those links from the
#   home runs' tables.  Local components may come back finer than the
#   legacy run's; the merged result is identical.
#
# Each halo slot's final label IS the compact (owned_root, halo_gid)
# edge table the merge consumes — same wire format as the legacy halo
# occurrence tables, so both merge modes (in-graph pmin loop and the
# host union-find spill) work unchanged.
# ---------------------------------------------------------------------------


def _oc_sorted_pairs(pairs, keep, nt):
    """Re-sort a filtered Pallas pair list back to row-major.

    Dropped entries take the dump row ``nt`` (col 0) and a stable sort
    on the row id moves them to the tail while preserving each kept
    row's consecutive run — the layout `_first_visit` requires.
    """
    rows, cols = pairs
    rows = jnp.where(keep, rows, nt)
    cols = jnp.where(keep, cols, 0)
    order = jnp.argsort(rows, stable=True)
    return rows[order], cols[order]


def oc_extract(
    points, eps, mask, *, owned, metric, block, precision, backend,
    layout: str = "nd", pair_budget: int | None = None,
    sketch: int | str | None = None,
):
    """Shared pre-pass for the owner-computes kernels.

    Resolves the backend once and extracts whatever the passes share:
    the Pallas tile-pair list, the XLA pair list (compacted dispatch,
    the default), or — dense dispatch — the diagnostic live-pair
    count.  Returns ``(kind, pairs, stats)`` — ``kind`` in ``("xla",
    "pallas")``, ``pairs`` None only on dense-XLA, ``stats`` (2,)
    int32 ``[live_pairs_total, budget]`` with the usual overflow
    contract (pair lists bind the budget to the FULL list).

    The dense-XLA total subtracts the halo-halo tile pairs the
    propagation will skip, so ``live_pairs`` reflects the work that
    path actually does.
    """
    from .distances import count_live_tile_pairs

    n = points.shape[0] if layout == "nd" else points.shape[1]
    d = points.shape[1] if layout == "nd" else points.shape[0]
    sk = _resolve_sketch(sketch, d, metric)
    kind = resolve_backend(backend, metric, n, block, d, precision)
    if kind == "pallas":
        from .pallas_kernels import (
            _check_mosaic_tile,
            _norm_precision_mode,
            _pallas_block,
            kernel_pair_list,
        )

        _check_mosaic_tile(
            _pallas_block(block, n, d, _norm_precision_mode(precision)),
            n, interpret=jax.default_backend() != "tpu",
        )
        pairs, stats = kernel_pair_list(
            points, eps, mask, block, precision, layout,
            budget=pair_budget, sketch=sk,
        )
        return "pallas", pairs, stats
    if pair_dispatch(metric, n // block):
        from .distances import xla_pair_list

        pairs, stats = xla_pair_list(
            points, mask, eps, block, layout, budget=pair_budget,
            sketch=sk, precision=precision,
        )
        return "xla", pairs, stats
    from .pallas_kernels import _norm_precision_mode, effective_tile

    count_block = effective_tile(
        block, n, d, _norm_precision_mode(precision)
    ) or block
    total = count_live_tile_pairs(
        points, mask, eps, metric=metric, block=count_block, layout=layout,
    )
    if owned < n:
        halo = (
            points[owned:] if layout == "nd" else points[:, owned:]
        )
        total = total - count_live_tile_pairs(
            halo, mask[owned:], eps, metric=metric,
            block=min(count_block, n - owned), layout=layout,
        )
    stats = jnp.stack(
        [total, jnp.int32(0 if pair_budget is None else pair_budget)]
    )
    return "xla", None, stats


def oc_raw_counts(
    points, eps, mask, *, owned, metric, block, precision,
    kind, pairs, layout: str = "nd", sketch: int | str | None = None,
):
    """Owned-row RAW neighbor counts (no min_samples threshold):
    counts over owned ROWS x all columns, returned as ``(counts,
    band_stats)`` uniformly (band zeros off ``precision="mixed"`` and
    off an active sketch).

    Split out of :func:`oc_counts` so the overlapped global-Morton
    route can SUM an owned-slab pass (dispatched before the boundary
    exchange) with a boundary-column delta (:func:`oc_counts_delta`)
    and threshold once — integer adds over disjoint column sets
    commute, so the sum is byte-identical to the fused counts pass.
    """
    mixed = _is_mixed(precision)
    n = points.shape[0] if layout == "nd" else points.shape[1]
    d = points.shape[1] if layout == "nd" else points.shape[0]
    sk = _resolve_sketch(sketch, d, metric)
    banded = mixed or sk > 0
    if kind == "pallas":
        from .pallas_kernels import (
            _norm_precision_mode, _pallas_block, neighbor_counts_pallas,
        )

        pb = _pallas_block(block, n, d, _norm_precision_mode(precision))
        nt, ont = n // pb, owned // pb
        counts, band = _split_band(
            neighbor_counts_pallas(
                points, eps, mask, block=block, precision=precision,
                layout=layout,
                pairs=_oc_sorted_pairs(pairs, pairs[0] < ont, nt),
                sketch=sk,
            ),
            banded,
        )
        counts = counts[:owned]
    else:
        counts, band = _split_band(
            neighbor_counts(
                points, eps, mask, metric=metric, block=block,
                precision=precision, layout=layout,
                row_tiles=owned // block, pairs=pairs, sketch=sk,
            ),
            banded,
        )
    return counts, band


def oc_counts_delta(
    points, eps, mask, *, owned, metric, block, precision,
    kind, pairs, layout: str = "nd", sketch: int | str | None = None,
):
    """Owned ROWS x boundary COLUMNS (cols >= owned) counts — the
    boundary-evidence delta the overlapped global-Morton counts pass
    adds after the exchange lands.  Requires a pair list (Pallas, or
    XLA compacted dispatch): the (owned row, boundary col) restriction
    IS a pair-list filter.  Returns ``(counts[:owned], band_stats)``.
    """
    mixed = _is_mixed(precision)
    if pairs is None:
        raise RuntimeError(
            "oc_counts_delta requires a pair list (Pallas backend or "
            "PYPARDIS_DISPATCH=pair); the caller gates the overlapped "
            "route off under dense dispatch"
        )
    n = points.shape[0] if layout == "nd" else points.shape[1]
    d = points.shape[1] if layout == "nd" else points.shape[0]
    sk = _resolve_sketch(sketch, d, metric)
    banded = mixed or sk > 0
    if kind == "pallas":
        from .pallas_kernels import (
            _norm_precision_mode, _pallas_block, neighbor_counts_pallas,
        )

        pb = _pallas_block(block, n, d, _norm_precision_mode(precision))
        nt, ont = n // pb, owned // pb
        rows, cols = pairs
        counts, band = _split_band(
            neighbor_counts_pallas(
                points, eps, mask, block=block, precision=precision,
                layout=layout,
                pairs=_oc_sorted_pairs(
                    pairs, (rows < ont) & (cols >= ont), nt
                ),
                sketch=sk,
            ),
            banded,
        )
        counts = counts[:owned]
    else:
        nt, ont = n // block, owned // block
        rows, cols = pairs
        counts, band = _split_band(
            neighbor_counts(
                points, eps, mask, metric=metric, block=block,
                precision=precision, layout=layout, row_tiles=ont,
                pairs=_oc_sorted_pairs(
                    pairs, (rows < ont) & (cols >= ont), nt
                ),
                sketch=sk,
            ),
            banded,
        )
    return counts, band


def oc_counts(
    points, eps, min_samples, mask, *, owned, metric, block, precision,
    kind, pairs, layout: str = "nd", sketch: int | str | None = None,
):
    """Owned-row core flags: counts over owned ROWS x all columns.

    ``owned`` (static) is the slab prefix length holding owned slots;
    halo columns contribute to the counts (exactness under the 2*eps
    halo) but no halo row is ever counted.  Returns (owned,) bool —
    widened to ``(core, band_stats)`` under ``precision="mixed"`` (the
    kernel convention, see :func:`neighbor_counts`; drivers use
    :func:`oc_counts_banded`, which also surfaces the sketch
    telemetry).
    """
    mixed = _is_mixed(precision)
    core, band = oc_counts_banded(
        points, eps, min_samples, mask, owned=owned, metric=metric,
        block=block, precision=precision, kind=kind, pairs=pairs,
        layout=layout, sketch=sketch,
    )
    if mixed:
        return core, band
    return core


def oc_propagate(
    points, eps, mask, core_all, *, owned, metric, block, precision,
    kind, pairs, max_rounds: int = 64, layout: str = "nd",
    sketch: int | str | None = None,
):
    """Min-label propagation with halo slots as relay-only nodes.

    ``core_all``: (N,) — owned slots' exact core flags followed by the
    halo slots' OWNER-computed flags.  Halo-halo tile pairs are
    skipped; halo-core slots still receive from and transmit to owned
    slots, so a core halo point adjacent to two owned clusters bridges
    them (the single-min edge a plain attachment table would emit is
    provably too weak — a bridging halo point must link EVERY adjacent
    owned cluster).  Returns ``(labels, passes)``: per-slot root local
    indices (-1 noise; halo slots carry their edge-table labels), and
    the number of minlab passes executed — widened to ``(labels,
    passes, band_stats)`` under ``precision="mixed"``.
    """
    mixed = _is_mixed(precision)
    n = points.shape[0] if layout == "nd" else points.shape[1]
    d = points.shape[1] if layout == "nd" else points.shape[0]
    sk = _resolve_sketch(sketch, d, metric)
    banded = mixed or sk > 0
    if kind == "pallas":
        from .pallas_kernels import (
            _norm_precision_mode, _pallas_block, min_neighbor_label_pallas,
        )

        pb = _pallas_block(block, n, d, _norm_precision_mode(precision))
        nt, ont = n // pb, owned // pb
        rows, cols = pairs
        prop_pairs = _oc_sorted_pairs(
            pairs, ~((rows >= ont) & (cols >= ont)), nt
        )
        minlab_fn = functools.partial(
            min_neighbor_label_pallas, block=block, precision=precision,
            layout=layout, pairs=prop_pairs, sketch=sk,
        )
    else:
        minlab_fn = functools.partial(
            min_neighbor_label, metric=metric, block=block,
            precision=precision, layout=layout,
            owned_tiles=owned // block, pairs=pairs, sketch=sk,
        )

    def minlab_band(f):
        return _split_band(
            minlab_fn(points, f, eps, core_all, row_mask=mask), banded
        )

    idx = jnp.arange(n, dtype=jnp.int32)
    f0 = jnp.where(core_all, idx, _INT_INF)

    def cond(state):
        f, g, changed, rounds, _band = state
        return changed & (rounds < max_rounds)

    def body(state):
        f, _, _, rounds, bacc = state
        g, b = minlab_band(f)
        f_new = jnp.where(core_all, jnp.minimum(f, g), f)
        f_new = _pointer_jump(f_new, core_all)
        return f_new, g, jnp.any(f_new != f), rounds + 1, bacc + b

    f, g, changed, rounds, band = jax.lax.while_loop(
        cond, body, (f0, f0, jnp.bool_(True), 0, _band_zeros())
    )
    border, b_border = jax.lax.cond(
        changed,
        lambda: minlab_band(f),
        lambda: (g, _band_zeros()),
    )
    labels = jnp.where(
        core_all, f, jnp.where(mask & (border != _INT_INF), border, -1)
    ).astype(jnp.int32)
    passes = rounds + changed.astype(jnp.int32)
    if mixed:
        return labels, passes, band + b_border
    return labels, passes


def oc_counts_banded(
    points, eps, min_samples, mask, *, owned, metric, block, precision,
    kind, pairs, layout: str = "nd", sketch: int | str | None = None,
):
    """:func:`oc_counts` with a UNIFORM ``(core, band_stats)`` return
    on every precision — the distributed drivers call this so their
    pair-stats rows always carry the (possibly zero) band columns
    (mixed-precision band telemetry, or sketch-band telemetry when the
    prefilter is on)."""
    counts, band = oc_raw_counts(
        points, eps, mask, owned=owned, metric=metric, block=block,
        precision=precision, kind=kind, pairs=pairs, layout=layout,
        sketch=sketch,
    )
    # Same self-count clamp as dbscan_fixed_size: a valid point is
    # always within eps of itself, whatever the f32 expansion says.
    core = (jnp.maximum(counts, 1) >= min_samples) & mask[:owned]
    return core, band


def oc_propagate_banded(*args, **kw):
    """:func:`oc_propagate` with a uniform ``(labels, passes,
    band_stats)`` return on every precision."""
    out = oc_propagate(*args, **kw)
    if _is_mixed(kw.get("precision", "high")):
        return out
    return out[0], out[1], _band_zeros()


# ---------------------------------------------------------------------------
# Host-stepped variant of the propagation loop (Pallas backend only).
#
# A single fused execution of the while_loop at tens of millions of
# points can run for minutes (each round is a minlab pass plus a
# pointer-jump fixpoint of whole-array gathers) — long enough to trip
# the remote-worker watchdog on tunneled deployments, which kills the
# worker mid-run.  The stepped variant runs ONE round per device call
# under host control (one scalar transfer per round), keeping every
# execution short.  The fused dbscan_fixed_size stays the entry for
# shard_map/vmap callers (host stepping is impossible inside a
# collective program) and for small problems where per-call latency
# would dominate.
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=("block", "precision", "layout", "pair_budget"),
)
def _prepare_extract(points, eps, mask, *, block, precision, layout,
                     pair_budget=None):
    from .pallas_kernels import kernel_pair_list

    # The host-stepped route pins sketch=0: it exists for 10M+-point
    # LOW-d workloads (watchdog latency, not compute, is its wall) and
    # its per-round programs are re-dispatched from host state, where a
    # trace-time env flip mid-loop could desync the extraction's gate
    # from the rounds' — the fused/distributed drivers carry the
    # sketch instead.
    return kernel_pair_list(
        points, eps, mask, block, precision, layout, budget=pair_budget,
        sketch=0,
    )


@functools.partial(
    jax.jit,
    static_argnames=("min_samples", "block", "precision", "layout"),
)
def _prepare_counts(points, eps, min_samples, mask, pairs, *, block,
                    precision, layout):
    from .pallas_kernels import neighbor_counts_pallas

    n = points.shape[0] if layout == "nd" else points.shape[1]
    counts, band = _split_band(
        neighbor_counts_pallas(
            points, eps, mask, block=block, precision=precision,
            layout=layout, pairs=pairs, sketch=0,
        ),
        _is_mixed(precision),
    )
    # Same self-count clamp as dbscan_fixed_size (a valid point is
    # always within eps of itself, whatever the f32 expansion says).
    core = (jnp.maximum(counts, 1) >= min_samples) & mask
    f0 = jnp.where(core, jnp.arange(n, dtype=jnp.int32), _INT_INF)
    return core, f0, band


_compiled_prepare_keys: set = set()


def dbscan_prepare_pallas(
    points, eps, min_samples, mask, *, block, precision, layout,
    pair_budget=None,
):
    """Pair extraction + counts pass + initial propagation state.

    TWO chained device programs, not one jit: the extraction's
    two-level scan machinery plus the counts kernel in a single module
    made the axon compile helper die outright (exit 1, no diagnostics)
    at 50M-point capacities — each half compiles fine alone.

    This function OWNS the first-call compile discipline for both
    programs (compiling while the device executes poisons the tunneled
    worker — same rule as the pipeline's staged layout): on the first
    call for a configuration it syncs the extraction before the counts
    program compiles, and syncs the counts output before returning so
    the CALLER's next program (the propagation round) also compiles
    against an idle device.  The key covers every static that retraces
    either program — shape, dtype, min_samples, block, precision,
    layout, pair_budget.  1-element fetches, not block_until_ready
    (which can return early on tunneled deployments).
    """
    import numpy as _np

    key = (
        points.shape, str(points.dtype), int(min_samples), block,
        precision, layout, pair_budget,
    )
    first = key not in _compiled_prepare_keys
    pairs, pair_stats = _prepare_extract(
        points, eps, mask, block=block, precision=precision, layout=layout,
        pair_budget=pair_budget,
    )
    if first:
        _np.asarray(pair_stats)
    core, f0, band = _prepare_counts(
        points, eps, min_samples, mask, pairs, block=block,
        precision=precision, layout=layout,
    )
    if first:
        _np.asarray(core[:1])
        _compiled_prepare_keys.add(key)
    return pairs, pair_stats, core, f0, band


@functools.partial(
    jax.jit,
    static_argnames=("block", "precision", "layout", "k_rounds"),
)
def dbscan_rounds_pallas(
    points, f, eps, core, mask, rows, cols, *, block, precision, layout,
    k_rounds,
):
    """Up to ``k_rounds`` propagation rounds in ONE device program.

    The host-stepped loop pays a device->host scalar sync per call to
    read the convergence flag — ~0.2s-2s each on the tunneled link, and
    at 50M points that latency (not compute) dominated the fit (round-4
    measurement: 61k pts/s with per-round syncs).  Batching k rounds
    under an in-program ``while_loop`` divides the sync count by k while
    each call stays seconds-long (bounded by k passes), far below the
    worker watchdog that motivates host stepping in the first place.

    Returns ``(f, g, changed, band_stats)``: ``changed`` False means
    the LAST executed round was a fixpoint — ``g`` is then the valid
    border-attach pass (min root among core eps-neighbors at the
    converged labels); ``band_stats`` accumulates this call's mixed-
    precision band telemetry (zeros on other precisions).
    """
    from .pallas_kernels import min_neighbor_label_pallas

    mixed = _is_mixed(precision)

    def body(state):
        f, _g, _changed, i, bacc = state
        g, b = _split_band(
            min_neighbor_label_pallas(
                points, f, eps, core, block=block, precision=precision,
                layout=layout, row_mask=mask, pairs=(rows, cols),
                sketch=0,
            ),
            mixed,
        )
        f_new = jnp.where(core, jnp.minimum(f, g), f)
        f_new = _pointer_jump(f_new, core)
        return f_new, g, jnp.any(f_new != f), i + 1, bacc + b

    f, g, changed, _, band = jax.lax.while_loop(
        lambda st: st[2] & (st[3] < k_rounds),
        body,
        (f, f, jnp.bool_(True), 0, _band_zeros()),
    )
    return f, g, changed, band


@functools.partial(
    jax.jit, static_argnames=("block", "precision", "layout")
)
def dbscan_border_pallas(
    points, f, eps, core, mask, rows, cols, *, block, precision, layout,
):
    """The final border-attach pass for a non-converged exit.
    Returns ``(border, band_stats)`` uniformly."""
    from .pallas_kernels import min_neighbor_label_pallas

    return _split_band(
        min_neighbor_label_pallas(
            points, f, eps, core, block=block, precision=precision,
            layout=layout, row_mask=mask, pairs=(rows, cols), sketch=0,
        ),
        _is_mixed(precision),
    )


def finish_labels(f, border, core, mask):
    """Labels from converged propagation state (host-stepped path)."""
    return jnp.where(
        core, f, jnp.where(mask & (border != _INT_INF), border, -1)
    ).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Graph-relabel engine — the per-config half of the amortized sweep.
#
# One pair-emission pass (ops.distances.neighbor_pair_graph) caches
# every (i, j, dval) triple at eps_max; each (eps, min_samples) config
# then re-thresholds dval for counts and min-propagates labels to a
# fixpoint over the cached pair list.  The loop mirrors
# dbscan_fixed_size round for round — same g each round (min over the
# same adjacency set; integer min/add commute), same pointer jumping,
# same border attach — so the labels are byte-identical to a full
# kernel fit at that config.
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("metric", "max_rounds"))
def graph_dbscan(
    gi: jnp.ndarray,
    gj: jnp.ndarray,
    dval: jnp.ndarray,
    mask: jnp.ndarray,
    eps,
    min_samples,
    metric: str = "euclidean",
    max_rounds: int = 64,
):
    """DBSCAN relabel over a cached neighbor-pair graph.

    ``gi``/``gj``: (E,) int32 directed edges (each true pair appears
    once per direction — the emission covers both orders exactly as
    the tiled column scans do); ``dval``: (E,) f32 threshold values
    (squared L2 or L1 per ``metric``; ``+inf`` padding is inert at any
    eps); ``mask``: (n,) validity of the id space the edges index
    (kernel slots for the fused route, all-true global gids for the
    sharded routes).  ``eps``/``min_samples`` are traced — one
    compiled program serves every config of a sweep.

    Returns ``(labels, core, passes)``: per-id component root (min
    core id, -1 noise — the same root convention as
    :func:`dbscan_fixed_size` in the same id space), the core mask,
    and the executed pass count (counts pass + propagation rounds +
    border recompute, the FLOP-model term).
    """
    n = mask.shape[0]
    eps_f = jnp.asarray(eps, jnp.float32)
    thr = eps_f * eps_f if metric == "euclidean" else eps_f
    adj = dval <= thr
    # Dump row n for row scatters; column reads clip to a valid id
    # (inert entries carry adj == False, so the value never matters).
    gi_c = jnp.clip(gi, 0, n)
    gj_c = jnp.clip(gj, 0, n - 1)
    counts = jnp.zeros(n + 1, jnp.int32).at[gi_c].add(
        adj.astype(jnp.int32)
    )[:n]
    # Same self-count clamp as dbscan_fixed_size: a valid point is
    # always within eps of itself, whatever the f32 expansion says.
    core = (
        jnp.maximum(counts, 1) >= jnp.asarray(min_samples, jnp.int32)
    ) & mask

    idx = jnp.arange(n, dtype=jnp.int32)
    f0 = jnp.where(core, idx, _INT_INF)

    def minlab(f):
        cand = jnp.where(adj & core[gj_c], f[gj_c], _INT_INF)
        return jnp.full(n + 1, _INT_INF, jnp.int32).at[gi_c].min(cand)[:n]

    def cond(state):
        f, g, changed, rounds = state
        return changed & (rounds < max_rounds)

    def body(state):
        f, _, _, rounds = state
        g = minlab(f)
        f_new = jnp.where(core, jnp.minimum(f, g), f)
        f_new = _pointer_jump(f_new, core)
        return f_new, g, jnp.any(f_new != f), rounds + 1

    f, g, changed, rounds = jax.lax.while_loop(
        cond, body, (f0, f0, jnp.bool_(True), 0)
    )
    # Border attach: the carried g IS the pass at convergence;
    # recompute only on a max_rounds exit (same rule as the kernels).
    border = jax.lax.cond(changed, lambda: minlab(f), lambda: g)
    labels = jnp.where(
        core, f, jnp.where(mask & (border != _INT_INF), border, -1)
    ).astype(jnp.int32)
    passes = 1 + rounds + changed.astype(jnp.int32)
    return labels, core, passes


def graph_dbscan_host_prepare(gi, gj, dval):
    """Sort-once state for repeated host relabels over one graph.

    Row-sorting the edge slab lets every config's per-row reductions
    (counts, border minima) run as ``np.*.reduceat`` over precomputed
    segment starts — C-speed streaming passes instead of the
    single-threaded XLA scatters that dominated the jitted relabel on
    CPU (measured ~0.75s/config at 3M edges; this path runs the same
    configs in ~0.1s).
    """
    gi = np.asarray(gi, np.int64)
    order = np.argsort(gi, kind="stable")
    gi_s = gi[order]
    gj_s = np.asarray(gj, np.int64)[order]
    dv_s = np.asarray(dval, np.float32)[order]
    if len(gi_s):
        starts = np.concatenate(
            [[0], np.flatnonzero(np.diff(gi_s)) + 1]
        ).astype(np.int64)
        uniq = gi_s[starts]
    else:
        starts = np.empty(0, np.int64)
        uniq = np.empty(0, np.int64)
    return gi_s, gj_s, dv_s, starts, uniq


def graph_dbscan_host(state, mask, eps, min_samples,
                      metric: str = "euclidean"):
    """Host twin of :func:`graph_dbscan` (CPU relabel fast path).

    The fixpoint :func:`graph_dbscan` converges to is unique — core
    status from exact integer counts, each core's label the min core
    id of its component, borders attached to the min adjacent core
    root — so computing it directly (scipy connected components over
    the core-core subgraph + segmented reductions) returns labels
    BYTE-IDENTICAL to the jitted propagation loop.  Thresholds compare
    in float32 exactly as the kernels do.  Returns ``(labels, core,
    passes)`` with ``passes == 1`` (one logical pass over the cached
    graph).
    """
    from scipy.sparse import csr_matrix
    from scipy.sparse.csgraph import connected_components

    gi_s, gj_s, dv_s, starts, uniq = state
    mask = np.asarray(mask, bool)
    n = len(mask)
    eps_f = np.float32(eps)
    thr = eps_f * eps_f if metric == "euclidean" else eps_f
    adj = dv_s <= thr
    counts = np.zeros(n, np.int64)
    if len(starts):
        counts[uniq] = np.add.reduceat(adj, starts)
    core = (np.maximum(counts, 1) >= int(min_samples)) & mask

    sel = adj & core[gi_s] & core[gj_s]
    r, c = gi_s[sel], gj_s[sel]
    graph = csr_matrix(
        (np.ones(len(r), np.int8), (r, c)), shape=(n, n)
    )
    ncomp, comp = connected_components(graph, directed=False)
    root_of_comp = np.full(max(ncomp, 1), n, np.int64)
    core_ids = np.flatnonzero(core)
    np.minimum.at(root_of_comp, comp[core_ids], core_ids)
    f = np.where(core, root_of_comp[comp], np.int64(_INT_INF))

    border = np.full(n, np.int64(_INT_INF))
    if len(starts):
        cand = np.where(
            adj & core[gj_s], f[gj_s], np.int64(_INT_INF)
        )
        border[uniq] = np.minimum.reduceat(cand, starts)
    labels = np.where(
        core, f, np.where(mask & (border != _INT_INF), border, -1)
    ).astype(np.int32)
    return labels, core, 1


def densify_labels(root_labels: np.ndarray) -> np.ndarray:
    """Host-side: map root-index labels to dense 0..C-1 ids, noise -> -1.

    Clusters are numbered by ascending root index, so ids are
    deterministic — the analogue of the reference's driver-side global-id
    assignment (aggregator.py:46-48) without the driver bottleneck.
    """
    root_labels = np.asarray(root_labels)
    out = np.full(root_labels.shape, -1, dtype=np.int32)
    valid = root_labels >= 0
    uniq, inv = np.unique(root_labels[valid], return_inverse=True)
    out[valid] = inv.astype(np.int32)
    return out
