"""Primitives behind incremental insert/delete on a fitted model.

Incremental DBSCAN (Ester et al., VLDB 1998) rests on one locality
fact: inserting or deleting a point only perturbs core-ness within
``eps`` of the change, and labels within ``eps`` of those flips — so
the write path never needs a global pass.  This module supplies the
three primitives :class:`pypardis_tpu.serve.live.LiveModel` composes:

* :func:`count_within_eps` — exact neighbor counts of a query set
  against a candidate set.  Runs in **float64 on the raw coordinates**:
  the fit kernels' float32 verdicts depend on the dataset mean (the
  recentring frame moves with every insert), so a maintained f32 count
  could flip across updates for a pair that never moved.  The f64
  verdict is frame-independent — one ground truth for the whole update
  sequence.  (A pair within one f32 ulp of eps can still disagree with
  a fresh fit's verdict; continuous data never produces one.)

* :func:`core_components` — eps-connectivity components of a set of
  KNOWN core points, by running the existing fused device kernel
  (:func:`pypardis_tpu.dbscan._pad_and_run`) with ``min_samples=1``.
  Core flags are maintained incrementally and exactly by the caller,
  so the local re-cluster needs *connectivity only* — with every point
  core by construction, the kernel's components ARE the eps-graph
  components, and no halo ring is needed to get slab-local counts
  right (the PR 2 owner-computes lesson, inverted: ship verdicts, not
  evidence).

* :func:`attach_to_cores` — deterministic border assignment: nearest
  core within eps, ties to the smallest label — the serving rule
  (:mod:`pypardis_tpu.ops.query`), in the same f64 frame as the
  counts.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

# Keep the (chunk x n_candidates) distance temp around 64MB of f64.
_CHUNK_ELEMS = 1 << 23

_INT_INF = np.int32(np.iinfo(np.int32).max)


def _chunk_rows(n_cand: int) -> int:
    return max(1, _CHUNK_ELEMS // max(n_cand, 1))


def sq_dists_f64(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(m, k) x (n, k) -> (m, n) float64 squared distances (one shot —
    callers chunk; the expansion-free direct form keeps f64 exact at
    any coordinate magnitude)."""
    diff = a[:, None, :] - b[None, :, :]
    return np.einsum("mnk,mnk->mn", diff, diff)


def count_within_eps(
    queries: np.ndarray, candidates: np.ndarray, eps: float
) -> np.ndarray:
    """(m,) int64 counts of candidate points within ``eps`` (inclusive,
    matching the fit kernels' ``d2 <= eps^2``) of each query row.

    A query that also appears among the candidates counts itself — the
    DBSCAN core rule's self-count (min_samples includes the point).
    """
    q = np.asarray(queries, np.float64)
    c = np.asarray(candidates, np.float64)
    m = len(q)
    out = np.zeros(m, np.int64)
    if m == 0 or len(c) == 0:
        return out
    e2 = float(eps) ** 2
    step = _chunk_rows(len(c))
    for s in range(0, m, step):
        out[s:s + step] = (sq_dists_f64(q[s:s + step], c) <= e2).sum(axis=1)
    return out


def within_eps_mask(
    queries: np.ndarray, candidates: np.ndarray, eps: float
) -> np.ndarray:
    """(m,) bool: query row has SOME candidate within eps (inclusive)."""
    return count_within_eps(queries, candidates, eps) > 0


def attach_to_cores(
    points: np.ndarray,
    cores: np.ndarray,
    core_labels: np.ndarray,
    eps: float,
) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic border attachment: ``(labels, d2)`` per point —
    the cluster of the nearest core within eps (ties: smallest label),
    -1 / +inf where no core reaches.  Same rule as the serving oracle
    (:func:`pypardis_tpu.ops.query.brute_force_query`), computed in the
    f64 frame the incremental counts use."""
    p = np.asarray(points, np.float64)
    c = np.asarray(cores, np.float64)
    lab = np.asarray(core_labels, np.int64)
    m = len(p)
    out_lab = np.full(m, -1, np.int32)
    out_d2 = np.full(m, np.inf, np.float64)
    if m == 0 or len(c) == 0:
        return out_lab, out_d2
    e2 = float(eps) ** 2
    step = _chunk_rows(len(c))
    for s in range(0, m, step):
        d2 = sq_dists_f64(p[s:s + step], c)
        dmin = d2.min(axis=1)
        tied = np.where(d2 == dmin[:, None], lab[None, :], np.int64(_INT_INF))
        labmin = tied.min(axis=1)
        sel = dmin <= e2
        out_lab[s:s + step] = np.where(sel, labmin, -1).astype(np.int32)
        out_d2[s:s + step] = np.where(sel, dmin, np.inf)
    return out_lab, out_d2


def bucket_size(n: int, floor: int = 64) -> int:
    """Power-of-two compile bucket for :func:`core_components` slabs."""
    b = max(int(floor), 1)
    while b < int(n):
        b *= 2
    return b


def core_components(
    cores: np.ndarray,
    eps: float,
    *,
    block: int = 256,
    precision: str = "high",
    backend: str = "auto",
    bucket: bool = True,
    min_bucket: int = 64,
) -> np.ndarray:
    """(n,) int32 eps-connectivity component ids (dense, from 0) of a
    set of KNOWN core points — the local re-cluster's compute step.

    Runs the existing fused single-chip kernel with ``min_samples=1``:
    every input is core by construction (the caller maintains core
    flags exactly), so the kernel's cluster labels are precisely the
    connected components of the eps-graph over these points.  The slab
    is the extracted blast radius — a few KD leaves — so this is the
    one device pass of an incremental update.

    ``bucket`` pads the slab to a power-of-two size with far-apart
    sentinel rows before the kernel runs.  Compiled programs are keyed
    by padded shape, so without buckets every distinct blast-radius
    size paid its own jit trace — the ~1.6s first-insert compile the
    live path used to eat per new size.  Buckets collapse those to a
    handful of shapes, and :meth:`LiveModel`'s build-time warmup
    compiles the bucket the first insert will actually hit.  The
    sentinels sit ``10*eps`` apart along one axis past the data's
    extent, so they form singleton components AFTER every real point
    in densify order — real components are untouched (sliced back to
    ``n``).
    """
    cores = np.asarray(cores, np.float64)
    n = len(cores)
    if n == 0:
        return np.empty(0, np.int32)
    if n == 1:
        return np.zeros(1, np.int32)
    from ..dbscan import _pad_and_run
    from . import densify_labels

    run = cores
    if bucket:
        target = bucket_size(n, min_bucket)
        pad = target - n
        if pad > 0:
            # Sentinels sit on a compact grid just past the data's
            # upper corner, spaced 3*eps apart (mutually > eps, and
            # every sentinel is > 2*steps beyond the real extent on
            # axis 0).  A grid — not a line — keeps the slab's spread
            # within ~10 steps per axis: the kernel recentres in f32,
            # whose distance error grows with coordinate magnitude, so
            # a pad-long line of sentinels would degrade the REAL
            # pairs' verdicts at large buckets.
            k = cores.shape[1]
            step = 3.0 * max(float(eps), 1e-6)
            g, side = 1, pad
            while side > 8 and g < k:
                g += 1
                side = int(np.ceil(pad ** (1.0 / g)))
            side = max(side, 2)
            hi = cores.max(axis=0)
            far = np.tile(cores.mean(axis=0), (pad, 1))
            idx = np.arange(pad)
            for a in range(g):
                far[:, a] = hi[a] + step * (2 + (idx % side))
                idx = idx // side
            run = np.concatenate([cores, far])
    roots, _core, _info = _pad_and_run(
        run, eps, 1, "euclidean", block, precision=precision,
        backend=backend,
    )
    return densify_labels(roots)[:n]


def label_lut(mapping: dict, max_id: int) -> np.ndarray:
    """Dense int32 LUT for a union-find label mapping
    (:func:`pypardis_tpu.parallel.merge.resolve_label_edges` output):
    identity outside the mapping, so it can be applied to any label
    array with one fancy-index — including the device-resident index
    labels row (:meth:`pypardis_tpu.serve.CorePointIndex
    .apply_label_map`)."""
    lut = np.arange(max(int(max_id) + 1, 1), dtype=np.int32)
    for k, v in mapping.items():
        if 0 <= int(k) < len(lut):
            lut[int(k)] = int(v)
    return lut
