"""(staging file for the pipelined kernel rewrite — merged into
pallas_kernels.py and deleted)"""
