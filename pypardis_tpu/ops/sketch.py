"""Random-projection sketch prefilter for the high-d distance pass.

At d = 256-1024 the exact distance pass is the wall again (the cost
model's own ``pairs * B^2 * d`` term), and axis-aligned full-d tile
boxes stop pruning: Morton order keys on the top-variance axes only,
so at high d almost every tile pair is "live" by box gap.  This module
supplies the same certified-classification pattern ``precision="mixed"``
applies to *arithmetic* (:mod:`pypardis_tpu.ops.precision`), applied to
*dimensionality*: a seeded k-dim sketch pass classifies every pair as
definitely-out / definitely-in / in-band against ``eps^2 +- band``, and
only in-band tiles rerun the unchanged full-d exact kernel — labels
byte-identical to the unsketched pass by the same rescoring argument.

The certified gate (what the kernels use)
-----------------------------------------

The Johnson-Lindenstrauss distortion bound (Achlioptas, JCSS 2003 —
see :func:`jl_band`) is PROBABILISTIC, so it cannot certify byte
parity.  The kernels instead use a deterministic split: draw an
Achlioptas-style sparse +-1 matrix seeded by ``(d, k,
PYPARDIS_SKETCH_SEED)``, orthonormalize it in float64 (QR), and keep

* ``s(x) = Q^T x``            — the k-dim sketch coordinates,
* ``r(x) = |x - Q s(x)|``     — the residual norm, stored as a
  (k+1)-th slab row, recovered as ``sqrt(|x|^2 - |s|^2)``.

With exactly orthonormal ``Q`` the residual is orthogonal to the
sketch subspace, so for any pair::

    t2 = |s(x) - s(y)|^2 + (r(x) - r(y))^2   <=  |x - y|^2
                                             <=  t2 + 4 r(x) r(y)

— ``t2`` (one (k+1)-dim squared distance over the slab) is a certified
LOWER bound and ``t2 + 4 rx ry`` a certified UPPER bound.  The float32
``Q`` is only near-orthonormal and the slab arithmetic rounds, so the
gates carry a scalar halfwidth (:func:`sketch_gate_band`) following
the ``band_halfwidth``/``exact_slack`` conventions of
:mod:`pypardis_tpu.ops.precision`:

* ``t2 - band > eps^2``              -> definitely-out,
* ``t2 + 4 rx ry <= eps^2 - band``   -> definitely-in,
* anything else                      -> in-band; the whole tile
  rescores through the UNCHANGED exact kernel arithmetic.

Because the gate brackets the exact kernel's own computed d^2 (the
band folds the exact pass's arithmetic slack in), every non-rescored
verdict equals the unsketched kernel's verdict and every rescored tile
runs its bytes — labels are byte-identical for ANY k, which also makes
a stale trace-time ``PYPARDIS_SKETCH`` read (the documented
``PYPARDIS_DISPATCH`` semantics, see :mod:`pypardis_tpu.utils.envreg`)
a telemetry-only hazard, never a correctness one.

The same slab serves as a tighter tile box: sketch-space bounding
boxes with the inflated gate threshold ``sqrt(eps^2 + band)`` give a
SOUND pair prune (``d2 <= eps^2`` implies ``t2 <= eps^2 + band``
implies the sketch box gap passes), replacing the useless full-d boxes
in the pair-list extraction and tightening the global-Morton boundary
ring (AND-composed with the full-d box test — each test is sound on
its own).  Note the two tests must never be summed: ``t2`` and the
full-d box gap both lower-bound the SAME distance, so their sum does
not.

Frames: the sketch transform is ``Q^T x`` with NO internal recentring
— every array a kernel call compares (owned + halo/boundary slabs)
sits in one staged coordinate frame, and a pointwise-deterministic
transform keeps cross-shard sketch coordinates comparable.  The
drivers' global recentring (which protects the ``|x|^2+|y|^2-2xy``
expansion) is what keeps frame magnitudes — and hence the band —
small; correctness never depends on it.
"""

from __future__ import annotations

import functools

import numpy as np

from ..utils import envreg
from .precision import _BAND_SAFETY, band_halfwidth, exact_slack

# k never exceeds this many sketch dimensions (past this the sketch
# pass itself costs like a mid-d exact pass and the d//4 ratio below
# has already flattened the win).
SKETCH_MAX_K = 256
# ... and never drops below this many (too few dims, everything lands
# in band and the prefilter only adds overhead).
SKETCH_MIN_K = 16


def sketch_seed() -> int:
    """The reproducible projection seed (``PYPARDIS_SKETCH_SEED``)."""
    return int(envreg.raw("PYPARDIS_SKETCH_SEED", "1299721"))


def sketch_delta() -> float:
    """JL failure probability for the PREDICTIVE band
    (``PYPARDIS_SKETCH_DELTA``)."""
    return float(envreg.raw("PYPARDIS_SKETCH_DELTA", "0.01"))


def sketch_min_d() -> int:
    """Dimensionality below which ``auto`` resolves to off
    (``PYPARDIS_SKETCH_MIN_D``)."""
    return int(envreg.raw("PYPARDIS_SKETCH_MIN_D", "128"))


def auto_k(d: int) -> int:
    """The ``auto`` sketch width for dimensionality ``d``: ``d // 4``
    clamped to [SKETCH_MIN_K, SKETCH_MAX_K].

    The ratio is set by the certified gate's geometry, not by JL
    accuracy: projecting onto a random k-subspace retains ~``k/d`` of
    a pair's squared distance, so the definitely-out gate only fires
    past ``~eps * sqrt(d/k)`` — while the regime where the prefilter
    matters at all (noise-dominated high-d frames whose axis-aligned
    tile boxes are blind) only extends to a few multiples of eps.
    ``k = d/4`` keeps ``sqrt(d/k) = 2`` so the gate fires inside that
    window; the measured counts-pass win at ``d//8`` was BELOW 1.0 on
    exactly the geometry the sketch targets (scripts/sketch_probe.py),
    which is what pinned this ratio."""
    return max(SKETCH_MIN_K, min(SKETCH_MAX_K, int(d) // 4))


def check_sketch_spec(spec):
    """Normalize a user-facing ``sketch=`` spec.

    Accepts ``None`` (defer to ``PYPARDIS_SKETCH``), ``"auto"``,
    ``"off"``/``0`` (force off), or a positive integer k.  Returns the
    canonical spec (``None`` | ``"auto"`` | int >= 0); raises
    ValueError on anything else — the construction-time validation
    every knob gets.
    """
    if spec is None:
        return None
    if isinstance(spec, str):
        s = spec.strip().lower()
        if s == "auto":
            return "auto"
        if s in ("off", ""):
            return 0
        try:
            spec = int(s)
        except ValueError:
            raise ValueError(
                f"sketch must be 'auto', 'off', or an integer k >= 0, "
                f"got {spec!r}"
            ) from None
    if isinstance(spec, (bool, float)) or not isinstance(
        spec, (int, np.integer)
    ):
        raise ValueError(
            f"sketch must be 'auto', 'off', or an integer k >= 0, "
            f"got {spec!r}"
        )
    if int(spec) < 0:
        raise ValueError(f"sketch k must be >= 0, got {spec}")
    return int(spec)


def resolve_sketch(spec, d: int, metric: str = "euclidean") -> int:
    """The effective sketch width for one kernel pass (0 = off).

    ``spec`` is a canonical spec (:func:`check_sketch_spec`); ``d`` the
    data dimensionality; ``metric`` the KERNEL metric.  The sketch is a
    squared-euclidean-distance discipline (like the box-gap pair
    extraction), so cityblock resolves to off; ``auto`` resolves to
    off below ``PYPARDIS_SKETCH_MIN_D`` (low-d boxes prune fine) and
    to :func:`auto_k` above it.  An explicit k is clamped so the
    sketch never reaches the full dimensionality (``k <= d // 2`` —
    past that the prefilter cannot pay for itself and the residual
    split degenerates at k = d).
    """
    if str(metric) != "euclidean":
        return 0
    spec = check_sketch_spec(spec)
    d = int(d)
    if spec == "auto" or spec is None:
        if d < sketch_min_d():
            return 0
        k = auto_k(d)
    else:
        k = int(spec)
    if k <= 0:
        return 0
    return max(1, min(k, d // 2))


def sketch_dims(d: int, metric: str = "euclidean") -> int:
    """Resolve ``PYPARDIS_SKETCH`` for one kernel pass (0 = off).

    Read at TRACE time like ``PYPARDIS_DISPATCH`` — flipping the
    variable after a program compiled needs ``jax.clear_caches()``;
    because the sketch is label-neutral for any k, a stale read can
    only stale the band telemetry, never the labels.
    """
    return resolve_sketch(envreg.raw("PYPARDIS_SKETCH", "auto"), d, metric)


@functools.lru_cache(maxsize=32)
def _sketch_matrix(d: int, k: int, seed: int):
    """(Q, eta) for one ``(d, k, seed)`` triple.

    ``Q`` is (d, k) float32 with near-orthonormal columns: an
    Achlioptas sparse {+1, 0, -1} draw (database-friendly random
    projections, JCSS 2003 — entries +-1 w.p. 1/6 each, 0 w.p. 2/3)
    orthonormalized by float64 QR, then rounded to f32.  The QR keeps
    the column SPAN of the sparse draw (a uniformly random k-subspace,
    which is what the JL statistics need) while making the
    sketch/residual split certifiable.  ``eta`` is the f32 matrix's
    orthonormality defect ``|Q^T Q - I|_F`` measured in float64 — the
    deterministic input of :func:`sketch_gate_band`.

    Host numpy on purpose: Q is a trace-time constant embedded in the
    compiled programs (seed/d/k-deterministic, so every shard of a
    mesh — and every host of a fleet — bakes the same matrix).
    """
    rng = np.random.default_rng(
        np.random.SeedSequence([int(seed), int(d), int(k)])
    )
    g = rng.choice(
        np.array([-1.0, 0.0, 1.0]), size=(d, k), p=[1 / 6, 2 / 3, 1 / 6]
    )
    # A degenerate draw (rank-deficient at tiny d) falls back to a
    # dense Gaussian column where needed; QR demands full column rank.
    while np.linalg.matrix_rank(g) < k:  # pragma: no cover - tiny-d only
        g = g + 1e-3 * rng.standard_normal((d, k))
    q64, _ = np.linalg.qr(g.astype(np.float64))
    q = np.ascontiguousarray(q64[:, :k], dtype=np.float32)
    gram = q.astype(np.float64).T @ q.astype(np.float64)
    eta = float(np.linalg.norm(gram - np.eye(k), "fro"))
    return q, eta


def sketch_matrix(d: int, k: int, seed=None):
    """The cached ``(Q, eta)`` pair; ``seed=None`` reads the env knob."""
    if seed is None:
        seed = sketch_seed()
    return _sketch_matrix(int(d), int(k), int(seed))


def jl_band(k: int, delta=None) -> float:
    """PREDICTIVE JL distortion halfwidth, relative to d^2.

    The Achlioptas bound: projecting onto a random k-subspace
    preserves ``|x - y|^2`` (after the ``d/k`` rescale) within relative
    distortion ``eps`` with failure probability ``delta`` once ``k >=
    4 ln(1/delta) / (eps^2/2 - eps^3/3)``; inverting the leading term
    gives ``eps ~ sqrt(8 ln(1/delta) / k)``.  This is what the
    planner's cost model and the probe's telemetry quote — the KERNEL
    gate never uses it (a probabilistic bound cannot certify byte
    parity; :func:`sketch_gate_band` is the certified one).
    """
    if delta is None:
        delta = sketch_delta()
    k = max(int(k), 1)
    delta = min(max(float(delta), 1e-12), 0.5)
    return float(np.sqrt(8.0 * np.log(1.0 / delta) / k))


def sketch_slab(pts_dn, q):
    """The (k+1, N) f32 sketch slab of a (d, N) coordinate slab.

    Rows 0..k-1 are ``Q^T x``; row k is the residual norm ``r(x) =
    sqrt(max(|x|^2 - |Q^T x|^2, 0))`` — so a plain (k+1)-dim squared
    distance over slab columns IS the certified lower bound ``t2``.
    Computed on device inside the jitted kernel entry (one (k, d) x
    (d, N) matmul plus two squared-norm passes); ``q`` is the
    trace-time constant from :func:`sketch_matrix`.  Pad columns
    (zeros) sketch to zeros, exactly like the coordinate slab.
    """
    import jax.lax as lax
    import jax.numpy as jnp

    pts = pts_dn.astype(jnp.float32)
    qj = jnp.asarray(q, jnp.float32)
    s = lax.dot_general(
        qj, pts, (((0,), (0,)), ((), ())),
        precision=lax.Precision.HIGHEST,
    )
    full = jnp.sum(pts * pts, axis=0)
    proj = jnp.sum(s * s, axis=0)
    r = jnp.sqrt(jnp.maximum(full - proj, 0.0))
    return jnp.concatenate([s, r[None, :]], axis=0)


def sketch_gate_band(nmax, d: int, k: int, eta: float,
                     precision: str = "high", fast_exact: bool = True):
    """Certified scalar halfwidth of the sketch classification gate.

    ``nmax`` is the masked GLOBAL maximum coordinate-column norm of
    the pass's operands (a traced f32 scalar — slab column norms are
    bounded by it, since ``|s|^2 + r^2 ~ |x|^2``); ``d``/``k`` the
    full/sketch dimensionalities; ``eta`` the host-measured
    orthonormality defect of Q.  The bound brackets ``|d2_kernel -
    t2|`` beyond the ``4 rx ry`` residual spread, covering (the
    ``exact_slack`` conventions of :mod:`ops.precision`):

    * the exact kernel's own arithmetic error vs true d^2 — one
      ``exact_slack`` plus a worst-case-sequential length-d f32
      accumulation term ``d * 2^-24 * (nx+ny)^2`` (material at
      d = 1024, invisible below);
    * the slab arithmetic: t2's own f32 slack (one more
      ``exact_slack`` + its length-(k+1) accumulation, folded into the
      d term) and the ``Q^T x`` matmul rounding crossed against the
      sketch difference, ``2 sqrt(k) d 2^-24 (nx+ny)^2``;
    * the f32 Q's orthonormality defect: cross terms bounded by
      ``4 eta (nx+ny)^2`` (``|Q^T e| <= eta |s|`` plus the Gram
      perturbation of ``|Q(sx-sy)|^2``), which also absorbs the
      residual-extraction rounding ``|s|^2 eta``-scale terms;
    * when the pass's fast dot is genuinely lossy
      (``precision='default'`` off CPU), the bf16 single-pass
      ``band_halfwidth`` — the gate then brackets the bf16 d^2 the
      kernel would actually compare.

    All terms ride the shared ``_BAND_SAFETY`` margin.  On recentred
    unit-scale data the band is ~1e-4 relative to frame scale — the
    in-band fraction is driven by the residual spread geometry, not by
    this halfwidth.
    """
    s = 2.0 * nmax
    s2 = s * s
    acc = (2.0 ** -24) * s2
    band = (
        2.0 * exact_slack(nmax, nmax)
        + 2.0 * float(d) * acc
        + 2.0 * float(np.sqrt(max(int(k), 1))) * float(d) * acc
        + 4.0 * float(eta) * s2
    )
    if str(precision) == "default" and not fast_exact:
        band = band + band_halfwidth(nmax, nmax)
    return _BAND_SAFETY * band


def sketch_box_norm(lo, hi):
    """Upper bound on slab COLUMN norms from per-tile sketch boxes.

    ``sqrt(max over non-empty tiles of sum_dim max(lo^2, hi^2))`` —
    what a receiver can certify about a REMOTE shard's slab from the
    boxes alone (the global-Morton boundary exchange ships boxes, not
    norms).  Empty tiles arrive as inverted (+BIG, -BIG) boxes and
    must not poison the bound, so ``lo > hi`` rows contribute zero.
    """
    import jax.numpy as jnp

    lo = jnp.asarray(lo, jnp.float32)
    hi = jnp.asarray(hi, jnp.float32)
    good = jnp.all(lo <= hi, axis=-1)
    corner = jnp.sum(jnp.maximum(lo * lo, hi * hi), axis=-1)
    return jnp.sqrt(jnp.max(jnp.where(good, corner, 0.0), initial=0.0))
