"""Out-of-sample query primitive: nearest core point within eps.

DBSCAN's own definition gives serving semantics for free (Ester et al.,
KDD 1996): a query point belongs to cluster ``c`` iff it lies within
``eps`` of a *core* point of ``c``, else it is noise.  The serving
subsystem (:mod:`pypardis_tpu.serve`) resolves ties deterministically:
the query takes the label of its NEAREST core point, and among equally
near core points the smallest label wins — so ``(min d^2, then min
label)`` is the complete assignment rule.

Exactness discipline: the device kernels and the numpy oracle
(:func:`brute_force_query`) compute squared distances with the SAME
sequence of IEEE float32 operations — per-axis ``(q_a - c_a)^2`` terms
accumulated in axis order (:func:`axis_sq_dists`).  One compiler hazard
stands between that and bit-equality: backends contract ``acc + d*d``
into an FMA (one rounding instead of two — measured last-ulp drift on
XLA:CPU, immune to every HLO-level barrier), so each square is sealed
behind an integer XOR with a RUNTIME zero (:func:`seal_f32`) that no
compiler can fold away.  With the seal, d^2 is bit-identical across
numpy / XLA / Pallas and ``predict`` matches the brute-force oracle
EXACTLY on every backend — by construction, not by tolerance.  (The
fit kernels' matmul decomposition is deliberately NOT used here: its
accumulation order is backend-scheduled.)

Layout mirrors the fit kernels: core-point slabs ride in the transposed
``(d, L*C)`` layout (point axis minor — dense in HBM for any d), one
padded slab of ``C`` slots per KD leaf, ``C`` a multiple of the column
``block``.  Pad slots carry ``PAD_COORD`` coordinates (astronomically
far — their d^2 overflows to +inf and can never win a min) and
``INT32_MAX`` labels, so no mask array enters the compute at all.
Query batches arrive as ``(nqt, d, qb)`` tiles, each tile scanning one
leaf's slab (``tile_leaf`` holds the leaf id per tile).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_INT_INF = np.int32(np.iinfo(np.int32).max)
# Pad-slot coordinate: (PAD_COORD - x)^2 overflows float32 to +inf for
# any real x, so pad slots lose every min and fail every eps test.
PAD_COORD = np.float32(2e19)
# Inverted-box sentinel for empty column blocks (same convention as
# ops.distances._BIG): gap to anything is astronomically positive.
BIG = np.float32(3e38)


def eps2_f32(eps) -> np.float32:
    """The float32 squared-eps threshold, computed identically on every
    path (host oracle and device kernels compare against this exact
    bit pattern)."""
    e = np.float32(eps)
    return np.float32(e * e)


def axis_sq_dists(q, c):
    """(m, d) x (n, d) -> (m, n) float32 squared distances, accumulated
    per axis in index order — the numpy reference arithmetic: each
    subtract/multiply/add is one correctly-rounded IEEE float32 op in a
    fixed order.  The device kernels replay the identical op sequence
    (:func:`_axis_sq_dists_t`), so d^2 is bit-identical between the
    oracle and every backend."""
    diff = q[:, 0, None] - c[None, :, 0]
    acc = diff * diff
    for a in range(1, q.shape[1]):
        diff = q[:, a, None] - c[None, :, a]
        # graftlint: disable=seal-f32 -- this IS the reference: numpy
        # ufuncs never FMA-contract, and this exact rounding sequence
        # defines the bit pattern the sealed device twin replays
        acc = acc + diff * diff
    return acc


def seal_f32(x, zero_i32):
    """Value-identity that compilers cannot see through: bitcast to
    int32, XOR with a RUNTIME zero, bitcast back.

    XLA:CPU's LLVM backend contracts ``acc + d*d`` into an FMA (one
    rounding instead of two — measured last-ulp drift vs numpy), and no
    HLO-level barrier survives to the instruction selector.  Routing
    the product through an integer op whose operand is a traced runtime
    value forces the multiply to materialize with its own rounding —
    restoring numpy's exact op sequence.  ``zero_i32`` MUST be traced
    (a jit argument or prefetched scalar); a literal 0 constant-folds
    and the contraction returns.
    """
    import jax

    return jax.lax.bitcast_convert_type(
        jax.lax.bitcast_convert_type(x, jnp.int32) ^ zero_i32,
        jnp.float32,
    )


def _axis_sq_dists_t(q_t, c_t, zero_i32):
    """Transposed-layout device twin of :func:`axis_sq_dists`: (d, m) x
    (d, n) -> (m, n), same ops in the same order (layout changes
    indexing, never arithmetic); every square rides through
    :func:`seal_f32` so no backend can fuse it into the accumulate."""
    diff = q_t[0][:, None] - c_t[0][None, :]
    acc = seal_f32(diff * diff, zero_i32)
    for a in range(1, q_t.shape[0]):
        diff = q_t[a][:, None] - c_t[a][None, :]
        acc = acc + seal_f32(diff * diff, zero_i32)
    return acc


def brute_force_query(queries, cores, labels, eps):
    """The numpy oracle: exact ``(label, d2)`` per query over ALL cores.

    ``queries``/``cores`` are cast to float32 first (the serving dtype
    — callers pass already-centered coordinates); d^2 accumulates via
    :func:`axis_sq_dists`.  Returns ``(labels, d2)``: label -1 and
    d2 = +inf where no core lies within eps.  This is the reference
    the device engine must match exactly (tests pin equality).
    """
    q = np.asarray(queries, np.float32)
    c = np.asarray(cores, np.float32)
    lab = np.asarray(labels, np.int32)
    m = len(q)
    out_lab = np.full(m, -1, np.int32)
    out_d2 = np.full(m, np.inf, np.float32)
    if m == 0 or len(c) == 0:
        return out_lab, out_d2
    e2 = eps2_f32(eps)
    # Chunk queries so the (chunk, n_core) temp stays ~256MB at most.
    chunk = max(1, (1 << 26) // max(len(c), 1))
    for s in range(0, m, chunk):
        d2 = axis_sq_dists(q[s:s + chunk], c)
        dmin = d2.min(axis=1)
        tied = np.where(d2 == dmin[:, None], lab[None, :], _INT_INF)
        labmin = tied.min(axis=1).astype(np.int32)
        sel = dmin <= e2
        out_lab[s:s + chunk] = np.where(sel, labmin, -1)
        out_d2[s:s + chunk] = np.where(sel, dmin, np.float32(np.inf))
    return out_lab, out_d2


def _fast_block_keep(q_t, c_t, eps2, center):
    """bf16-peak pre-filter for one (d, m) x (d, n) query/core block:
    True iff SOME pair's exact d^2 could lie within eps.

    Both sides recentre on ``center`` ((d, 1) — the core block's box
    midpoint) so bf16 operand magnitudes are block-local, then one
    DEFAULT-precision (bf16 on TPU) MXU dot gives fast squared
    distances; subtracting the shared per-ELEMENT error bound
    (:func:`pypardis_tpu.ops.precision.band_halfwidth` at recentred
    per-point norms, plus :func:`~pypardis_tpu.ops.precision.
    exact_slack` at the index-frame norms the sealed rescore computes
    in) yields a sound lower bound on the exact d^2.  A block whose
    every lower bound clears eps^2 cannot contain a within-eps
    candidate and is skipped — the same soundness argument as the
    box-gap pruning, so the final within-eps verdict (and therefore
    ``predict``'s bitwise-exact contract) is untouched; surviving
    blocks rescore through the UNCHANGED sealed exact path.

    Pad slots carry ``PAD_COORD``: their recentred norms and fast d^2
    are inf/NaN, the per-element band goes non-finite, and ``NaN <=
    x`` is False — so pad entries can never force a keep.  (A
    tile-max band would instead be blown to +inf by one pad slot and
    keep everything; per-element is what makes the filter effective
    on padded slabs.)
    """
    from .precision import band_halfwidth, exact_slack

    qc = q_t - center
    cc_ = c_t - center
    qq = jnp.sum(qc * qc, axis=0)[:, None]
    cc = jnp.sum(cc_ * cc_, axis=0)[None, :]
    d2f = qq + cc - 2.0 * jax.lax.dot_general(
        qc, cc_, (((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.DEFAULT,
        preferred_element_type=jnp.float32,
    )
    nq = jnp.sqrt(qq)
    nc = jnp.sqrt(cc)
    gq = jnp.sqrt(jnp.sum(q_t * q_t, axis=0))[:, None]
    gc = jnp.sqrt(jnp.sum(c_t * c_t, axis=0))[None, :]
    band = band_halfwidth(nq, nc) + exact_slack(gq, gc)
    return jnp.any(d2f - band <= eps2)


def _block_best(d2, lab_block, best_d2, best_lab):
    """Fold one (qb, block) distance tile into the per-row running
    ``(min d2, min label among ties)`` — the deterministic assignment
    rule, applied identically in the XLA scan, the Pallas kernel, and
    (via global min) the numpy oracle."""
    m = jnp.min(d2, axis=1)
    cand = jnp.min(
        jnp.where(d2 == m[:, None], lab_block[None, :], _INT_INF), axis=1
    )
    take = (m < best_d2) | ((m == best_d2) & (cand < best_lab))
    return jnp.where(take, m, best_d2), jnp.where(take, cand, best_lab)


@functools.partial(jax.jit, static_argnames=("block", "nb", "precision"))
def query_min_core(
    q, qmask, tile_leaf, coords, labels, blo, bhi, eps2, zero_i32,
    *, block, nb, precision="high"
):
    """XLA query kernel: per query row, ``(min d2, min label)`` over its
    leaf's core slab.

    ``precision="mixed"`` inserts the bf16-peak block pre-filter
    (:func:`_fast_block_keep`) between the box-gap prune and the exact
    sealed pass: blocks provably outside eps skip the expensive
    axis-ordered VPU accumulation entirely, surviving candidates
    rescore through the UNCHANGED ``seal_f32`` path — so the bitwise
    numpy-oracle contract holds in every mode.  Any other value keeps
    today's behavior (the exact pass has a single precision; the knob
    exists so the serving surface shares the fit's precision ladder).

    ``q``: (nqt, d, qb) float32 centered query tiles (pad rows at
    ``PAD_COORD``); ``qmask``: (nqt, qb) bool row validity (tightens
    the pruning boxes only — pad rows' outputs are garbage the caller
    drops); ``tile_leaf``: (nqt,) int32 leaf per tile; ``coords``:
    (d, L*C) core slabs; ``labels``: (L*C,) int32; ``blo``/``bhi``:
    (L*nb, d) per-column-block core bounds (inverted for empty
    blocks); ``eps2``: float32 scalar; ``zero_i32``: a TRACED int32
    zero (see :func:`seal_f32` — pass ``jnp.int32(0)`` as an argument,
    never bake a literal).  Column blocks whose box lies
    farther than eps from the tile's query box are skipped — sound for
    the final within-eps verdict because a within-eps core's block can
    never be pruned (box min-distance <= true distance <= eps).

    Returns one packed (2, nqt, qb) int32 array — ``[labels,
    bitcast(d2)]`` — so the engine fetches results in a single
    device->host transfer (:func:`unpack_query_result` decodes).
    """
    from .precision import norm_precision_mode

    mixed = norm_precision_mode(precision) == "mixed"
    nqt, d, qb = q.shape

    def tile(args):
        qi, mi, leaf = args
        valid = mi[None, :]
        qlo = jnp.min(jnp.where(valid, qi, BIG), axis=1)
        qhi = jnp.max(jnp.where(valid, qi, -BIG), axis=1)

        def col(carry, j):
            cb = leaf * nb + j
            gap = jnp.maximum(
                0.0, jnp.maximum(blo[cb] - qhi, qlo - bhi[cb])
            )
            skip = jnp.sum(gap * gap) > eps2

            def compute(c):
                cols = jax.lax.dynamic_slice(
                    coords, (0, cb * block), (d, block)
                )
                lb = jax.lax.dynamic_slice(labels, (cb * block,), (block,))

                def exact(c):
                    d2 = _axis_sq_dists_t(qi, cols, zero_i32)
                    return _block_best(d2, lb, c[0], c[1])

                if not mixed:
                    return exact(c)
                # Block box midpoint as the recentring frame (empty
                # blocks carry inverted boxes, but the box-gap test
                # above already skipped them).
                ctr = (0.5 * (blo[cb] + bhi[cb]))[:, None]
                keep = _fast_block_keep(qi, cols, eps2, ctr)
                return jax.lax.cond(keep, exact, lambda c: c, c)

            return jax.lax.cond(skip, lambda c: c, compute, carry), None

        init = (
            jnp.full((qb,), jnp.inf, jnp.float32),
            jnp.full((qb,), _INT_INF, jnp.int32),
        )
        (bd2, bl), _ = jax.lax.scan(col, init, jnp.arange(nb))
        return bl, bd2

    labs, d2 = jax.lax.map(tile, (q, qmask, tile_leaf))
    return jnp.stack([labs, jax.lax.bitcast_convert_type(d2, jnp.int32)])


def unpack_query_result(packed, eps2):
    """Host decode of the kernels' packed (2, nqt, qb) int32 result:
    ``(raw_labels, raw_d2)`` — raw, i.e. before the within-eps verdict
    (the engine folds multi-leaf replicas first, then applies
    ``d2 <= eps2``)."""
    packed = np.asarray(packed)
    return packed[0], packed[1].view(np.float32)


def resolve_query_backend(backend: str, qb: int, block: int) -> str:
    """Resolve "auto" to "pallas" on TPU when the tile shapes are
    Mosaic-legal (trailing dims multiples of 128), else "xla" — the
    same dispatch contract as :func:`pypardis_tpu.ops.labels.
    resolve_backend`, minus the metric cases (queries are Euclidean
    squared-distance by definition)."""
    if backend == "auto":
        if (
            jax.default_backend() == "tpu"
            and qb % 128 == 0
            and block % 128 == 0
        ):
            return "pallas"
        return "xla"
    if backend not in ("xla", "pallas"):
        raise ValueError(
            f"backend must be auto|xla|pallas, got {backend!r}"
        )
    return backend
