"""Numeric kernels: tiled eps-neighborhood ops and label propagation.

This subpackage replaces the reference's entire numeric hot loop — the
``sklearn.cluster.DBSCAN`` call inside each Spark partition
(``/root/reference/dbscan/dbscan.py:28-30``) — with TPU-native kernels:
pairwise interactions stream through MXU-friendly tiles without ever
materializing the N x N matrix, and DBSCAN's sequential region-query
expansion becomes parallel connected components over the core-point graph
(fixed-shape min-label propagation under ``lax.while_loop``).
"""

from .distances import (
    neighbor_counts,
    min_neighbor_label,
    pairwise_sq_dists,
)
from .labels import dbscan_fixed_size, densify_labels
from .query import brute_force_query, query_min_core

__all__ = [
    "neighbor_counts",
    "min_neighbor_label",
    "pairwise_sq_dists",
    "dbscan_fixed_size",
    "densify_labels",
    "brute_force_query",
    "query_min_core",
]
