"""Terminal-runnable demo — ``python -m pypardis_tpu.demo``.

Recreates the reference's absent-but-documented examples: README.md:40-42
says runnable examples lived in ``dbscan.py``/``partition.py`` and
produced the ``plots/`` images (per-partition scatters, ``partitioning``,
``clusters``) from the sklearn ``plot_dbscan`` demo setup — make_blobs,
750 points, 2-D, eps=0.3, min_samples=10.  No ``__main__`` survives in
the reference snapshot (SURVEY §3.5), so this module is the rebuild of
that demo: it clusters the same data on the TPU path, prints a summary
vs single-node sklearn, and (with matplotlib installed) regenerates the
``partitioning.png`` / ``clusters.png`` / ``clusters_partitions.png``
figures into ``--out``.
"""

from __future__ import annotations

import argparse
import sys


def make_demo_data(n: int = 750, seed: int = 0):
    """The reference's de-facto correctness baseline dataset."""
    from sklearn.datasets import make_blobs
    from sklearn.preprocessing import StandardScaler

    centers = [[1, 1], [-1, -1], [1, -1]]
    X, y = make_blobs(
        n_samples=n, centers=centers, cluster_std=0.4, random_state=seed
    )
    return StandardScaler().fit_transform(X), y


def run_demo(n: int = 750, eps: float = 0.3, min_samples: int = 10,
             max_partitions=None, out: str | None = None, seed: int = 0):
    from pypardis_tpu import DBSCAN, KDPartitioner

    X, _ = make_demo_data(n, seed)
    model = DBSCAN(
        eps=eps, min_samples=min_samples, max_partitions=max_partitions
    )
    labels = model.fit_predict(X)
    n_clusters = int(labels.max()) + 1 if labels.size else 0
    n_noise = int((labels == -1).sum())
    print(
        f"pypardis_tpu demo: {len(X)} pts, eps={eps}, "
        f"min_samples={min_samples} -> {n_clusters} clusters, "
        f"{n_noise} noise ({model.metrics_.get('total_s', 0):.3f}s)"
    )

    try:
        from sklearn.cluster import DBSCAN as SKDBSCAN
        from sklearn.metrics import adjusted_rand_score

        sk = SKDBSCAN(eps=eps, min_samples=min_samples).fit(X)
        print(
            "ARI vs single-node sklearn:",
            round(adjusted_rand_score(sk.labels_, labels), 4),
        )
    except ImportError:
        pass

    if out:
        # Prefer the split the clustering actually used (sharded runs
        # populate partitioner_); single-device runs have no split, so
        # build an illustrative one matching the reference's 4-box plots.
        part = model.partitioner_ or KDPartitioner(
            X, max_partitions=max_partitions or 4
        )
        _plots(X, labels, part, out)
    return labels


def _plots(X, labels, part, out):
    """Regenerate the reference's plots/ artifacts (matplotlib optional —
    reference README.md:53-56 lists it the same way)."""
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        print("matplotlib not installed; skipping plots", file=sys.stderr)
        return
    import os

    os.makedirs(out, exist_ok=True)

    def scatter(ax, c):
        ax.scatter(X[:, 0], X[:, 1], c=c, s=8, cmap="tab10")

    fig, ax = plt.subplots(figsize=(6, 6))
    scatter(ax, part.result)
    for box in part.bounding_boxes.values():
        lo, hi = box.lower, box.upper
        ax.add_patch(
            plt.Rectangle(lo, *(hi - lo), fill=False, ec="k", lw=0.8)
        )
    ax.set_title("KD partitioning")
    fig.savefig(os.path.join(out, "partitioning.png"), dpi=120)

    fig, ax = plt.subplots(figsize=(6, 6))
    scatter(ax, labels)
    ax.set_title("DBSCAN clusters (noise = -1)")
    fig.savefig(os.path.join(out, "clusters.png"), dpi=120)

    fig, ax = plt.subplots(figsize=(6, 6))
    scatter(ax, labels)
    for box in part.bounding_boxes.values():
        lo, hi = box.lower, box.upper
        ax.add_patch(
            plt.Rectangle(lo, *(hi - lo), fill=False, ec="k", lw=0.8)
        )
    ax.set_title("clusters + partitions")
    fig.savefig(os.path.join(out, "clusters_partitions.png"), dpi=120)

    # Per-partition scatters — the reference ships one partition_N.png
    # per KD leaf (plots/*/partition_*.png).
    for label_id in sorted(part.partitions):
        idx = part.partitions[label_id]
        fig, ax = plt.subplots(figsize=(4, 4))
        ax.scatter(X[:, 0], X[:, 1], c="0.85", s=6)
        if len(idx):
            ax.scatter(X[idx, 0], X[idx, 1], c=labels[idx], s=8,
                       cmap="tab10")
        ax.set_title(f"partition {label_id}")
        fig.savefig(os.path.join(out, f"partition_{label_id}.png"), dpi=100)
        plt.close(fig)

    # Animated build-up of the partitions — the reference embeds
    # dbscan_animated.gif (README.md:36).
    try:
        from matplotlib.animation import FuncAnimation, PillowWriter

        fig, ax = plt.subplots(figsize=(5, 5))
        order = sorted(part.partitions)

        def frame(i):
            ax.clear()
            ax.scatter(X[:, 0], X[:, 1], c="0.85", s=6)
            for label_id in order[: i + 1]:
                idx = part.partitions[label_id]
                if len(idx):
                    ax.scatter(X[idx, 0], X[idx, 1], c=labels[idx], s=8,
                               cmap="tab10")
                box = part.bounding_boxes[label_id]
                lo, hi = box.lower, box.upper
                ax.add_patch(
                    plt.Rectangle(lo, *(hi - lo), fill=False, ec="k",
                                  lw=0.8)
                )
            ax.set_title(f"partitions 0..{order[i]}")

        anim = FuncAnimation(fig, frame, frames=len(order))
        anim.save(
            os.path.join(out, "dbscan_animated.gif"),
            writer=PillowWriter(fps=2),
        )
    except Exception as e:  # noqa: BLE001 — the GIF is a nicety
        print(f"animation skipped: {e}", file=sys.stderr)
    plt.close("all")
    print(f"wrote plots to {out}/")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", type=int, default=750)
    ap.add_argument("--eps", type=float, default=0.3)
    ap.add_argument("--min-samples", type=int, default=10)
    ap.add_argument("--max-partitions", type=int, default=None)
    ap.add_argument("--out", default=None, help="directory for plots")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    run_demo(
        n=args.n,
        eps=args.eps,
        min_samples=args.min_samples,
        max_partitions=args.max_partitions,
        out=args.out,
        seed=args.seed,
    )


if __name__ == "__main__":
    main()
