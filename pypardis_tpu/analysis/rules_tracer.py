"""R1 — no module-level ``jnp`` constants (the PR 6 tracer-poisoning
class).

A module-scope binding whose value is built by a ``jax.numpy`` call
(``_ZERO = jnp.int32(0)``) is evaluated at *import time*.  If the
module's first import happens inside a jit trace (a lazy in-function
import — exactly how ``parallel/halo.py`` was first imported inside
``ring_exchange_step``'s trace), the "constant" is born a TRACER and
poisons every later use with ``UnexpectedTracerError``.  Numpy scalars
are the sanctioned replacement: trace-inert, and every kernel promotes
them identically.

Inert ``jnp`` accesses stay allowed: ``jnp.iinfo(...)``/``jnp.finfo``
return host-side dtype-info objects (``jnp.iinfo(jnp.int32).max`` is a
Python int), and bare attribute references (``jnp.float32`` as a dtype,
``jnp.inf``) create no array.  ``jax.jit(...)`` wrapping at module
scope is likewise fine — it traces lazily at first call, not at
import.
"""

from __future__ import annotations

import ast
from typing import List, Set

from .base import Finding, LintContext, Rule, attr_chain, register

# jnp-rooted calls that return host objects, not jax arrays.
_INERT_FUNCS = {"iinfo", "finfo", "dtype", "result_type", "issubdtype"}


def _jnp_aliases(tree: ast.Module) -> Set[str]:
    """Local names bound to the ``jax.numpy`` module."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.numpy":
                    aliases.add(a.asname or "jax")  # jax.numpy.x form
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "numpy":
                        aliases.add(a.asname or "numpy")
            elif node.module == "jax.numpy":
                # from jax.numpy import int32 — any call to the
                # imported name is an array constructor.
                for a in node.names:
                    if a.name not in _INERT_FUNCS:
                        aliases.add(a.asname or a.name)
    return aliases


def _is_jnp_call(node: ast.Call, aliases: Set[str]) -> bool:
    chain = attr_chain(node.func)
    if not chain:
        return False
    if chain[0] == "jnp" or chain[0] in aliases:
        pass
    elif len(chain) >= 2 and chain[0] == "jax" and chain[1] == "numpy":
        chain = chain[1:]
    else:
        return False
    return chain[-1] not in _INERT_FUNCS


def _module_scope_statements(tree: ast.Module):
    """Module-body statements, descending into module-level if/try
    blocks (conditional imports, platform guards) but never into
    function or class bodies."""
    stack = list(tree.body)
    while stack:
        node = stack.pop(0)
        yield node
        if isinstance(node, (ast.If, ast.Try)):
            for part in ("body", "orelse", "finalbody"):
                stack.extend(getattr(node, part, []) or [])
        elif isinstance(node, (ast.For, ast.While, ast.With)):
            stack.extend(node.body)
            stack.extend(getattr(node, "orelse", []) or [])


@register
class ModuleJnpConstantRule(Rule):
    name = "module-jnp-constant"
    issue_rule = "R1"
    doc = ("module-scope jnp/jax.numpy value bindings become tracers "
           "when first imported inside a trace; use numpy scalars")

    def visit(self, src, ctx: LintContext) -> List[Finding]:
        if src.tree is None or src.kind != "package":
            return []
        aliases = _jnp_aliases(src.tree)
        if not aliases and "jnp" not in src.text:
            return []
        aliases.add("jnp")  # the conventional alias, even if indirect
        out: List[Finding] = []
        for stmt in _module_scope_statements(src.tree):
            if not isinstance(
                stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)
            ):
                continue
            value = stmt.value
            if value is None:
                continue
            for node in ast.walk(value):
                if isinstance(node, ast.Call) and _is_jnp_call(
                    node, aliases
                ):
                    out.append(Finding(
                        self.name, src.rel, node.lineno,
                        node.col_offset,
                        "module-level jax.numpy value binding "
                        "(imported inside a trace it becomes a "
                        "tracer — PR 6); bind a numpy scalar/array "
                        "instead",
                    ))
        return out
