"""Human-facing rendering of a lint run (the CLI's output layer)."""

from __future__ import annotations

from typing import List

from .base import RULE_REGISTRY
from .driver import LintResult


def render(result: LintResult, verbose: bool = False) -> str:
    lines: List[str] = []
    for f in result.findings:
        lines.append(f"{f.location()}: [{f.rule}] {f.message}")
    if result.notes and (verbose or not result.findings):
        for f in result.notes:
            lines.append(
                f"{f.location()}: [{f.rule}] note: {f.message}"
            )
    extras = []
    if result.suppressed:
        extras.append(f"{result.suppressed} suppressed")
    if result.baselined:
        extras.append(f"{result.baselined} baselined")
    if result.notes:
        extras.append(f"{len(result.notes)} notes")
    tail = f" ({', '.join(extras)})" if extras else ""
    verdict = (
        "ok" if result.ok
        else f"{len(result.findings)} finding(s)"
    )
    lines.append(
        f"graftlint: {verdict} — {result.files} files in "
        f"{result.elapsed_s:.2f}s{tail}"
    )
    return "\n".join(lines)


def render_rules() -> str:
    lines = []
    for name in sorted(RULE_REGISTRY):
        cls = RULE_REGISTRY[name]
        lines.append(f"{name} ({cls.issue_rule}): {cls.doc}")
    return "\n".join(lines)
