"""R5 — ``seal_f32`` discipline in oracle-exact distance paths (the
PR 4 FMA-contraction class).

The serving contract is labels AND d2 bitwise-equal to the numpy
oracle.  XLA:CPU FMA-contracts ``acc + d*d`` (last-ulp drift immune to
``optimization_barrier`` / bitcast tricks — PR 4 tried them all);
the only construct that survives every optimizer is sealing each
squared term behind an integer XOR with a RUNTIME zero
(``ops.query.seal_f32``).  This rule pins that discipline where the
bitwise contract lives: a squared product (``d * d`` with identical
operands, or ``d ** 2``) appearing as an operand of an ADDITION — the
exact multiply-feeds-add shape an FMA fuses — must sit inside a
``seal_f32(...)`` argument.  Standalone squares (``jnp.sum(g * g)``,
``e * e``) have no contraction target and stay unflagged, which keeps
the conservative box-gap/band pruning code out of scope by
construction.

Scopes: all of ``ops/query.py``, and the ``query*`` kernels in
``ops/pallas_kernels.py``.  The bulk clustering kernels in
``ops/distances.py`` are deliberately NOT in scope — their contract is
symmetric-comparison consistency, not oracle bit-parity, and sealing
them would forfeit real MXU throughput.
"""

from __future__ import annotations

import ast
from typing import List

from .base import Finding, LintContext, Rule, attr_chain, register

_WHOLE_FILE_SCOPES = ("ops/query.py",)
_FUNC_SCOPES = {"ops/pallas_kernels.py": "query"}


def _squared_term(node: ast.AST) -> bool:
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, ast.Mult):
            left, right = node.left, node.right
            return (
                isinstance(left, ast.Name)
                and isinstance(right, ast.Name)
                and left.id == right.id
            )
        if isinstance(node.op, ast.Pow):
            return (
                isinstance(node.left, ast.Name)
                and isinstance(node.right, ast.Constant)
                and node.right.value == 2
            )
    return False


def _sealed(src, node: ast.AST) -> bool:
    for anc in src.ancestors(node):
        if isinstance(anc, ast.Call):
            chain = attr_chain(anc.func) or []
            if chain and chain[-1] == "seal_f32":
                return True
        if isinstance(anc, ast.stmt):
            break
    return False


def _feeds_addition(src, node: ast.AST) -> bool:
    """Whether the squared term is a direct operand of a ``+`` —
    the multiply-feeds-add shape FMA contraction fuses."""
    for anc in src.ancestors(node):
        if isinstance(anc, ast.BinOp) and isinstance(anc.op, ast.Add):
            return True
        if isinstance(anc, (ast.Call, ast.stmt)):
            break
    return False


@register
class SealF32Rule(Rule):
    name = "seal-f32"
    issue_rule = "R5"
    doc = ("squared-distance accumulation in oracle-exact paths must "
           "route each d*d through seal_f32 (PR 4: XLA FMA "
           "contraction breaks bitwise parity)")

    def _scoped_functions(self, src):
        """Function nodes whose bodies this rule covers (None =
        whole file)."""
        for rel_suffix, prefix in _FUNC_SCOPES.items():
            if src.rel.endswith(rel_suffix):
                return [
                    node for node in ast.walk(src.tree)
                    if isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ) and prefix in node.name
                ]
        for rel_suffix in _WHOLE_FILE_SCOPES:
            if src.rel.endswith(rel_suffix):
                return None
        return []

    def visit(self, src, ctx: LintContext) -> List[Finding]:
        if src.tree is None or src.kind != "package":
            return []
        scope = self._scoped_functions(src)
        if scope == []:
            return []
        roots = [src.tree] if scope is None else scope
        out: List[Finding] = []
        for root in roots:
            for node in ast.walk(root):
                if not _squared_term(node):
                    continue
                if not _feeds_addition(src, node):
                    continue
                if _sealed(src, node):
                    continue
                out.append(Finding(
                    self.name, src.rel, node.lineno, node.col_offset,
                    "unsealed squared term in an oracle-exact path — "
                    "XLA FMA-contracts `acc + d*d`, breaking bitwise "
                    "oracle parity (PR 4); wrap the square in "
                    "seal_f32(d * d, zero_i32)",
                ))
        return out
