"""Finding / Rule model shared by every graftlint check.

A rule sees one parsed :class:`~pypardis_tpu.analysis.source.SourceFile`
at a time (``visit``) and may emit more findings once the whole fileset
has been seen (``finalize`` — cross-file checks like the env-var
registry and fault-site registries).  Rules register themselves into
:data:`RULE_REGISTRY` via the :func:`register` decorator; the driver
instantiates one of each per run, so per-run state lives on the
instance.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Type


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``severity`` is ``"error"`` (fails the run) or ``"note"``
    (report-only — e.g. unused imports in ``scripts/``, where probe
    CLIs keep convenience imports on purpose).
    """

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    col: int
    message: str
    severity: str = "error"

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"


@dataclass
class LintContext:
    """Per-run shared state: the repo root, the statically parsed
    registries, and a scratch dict rules use to carry per-file
    collections into ``finalize``."""

    root: str
    env_registry: "object" = None  # analysis.envmodel.EnvRegistry
    fault_sites: Tuple[str, ...] = ()
    fault_sites_path: str = "pypardis_tpu/utils/faults.py"
    shared: Dict[str, object] = field(default_factory=dict)


class Rule:
    """Base class: subclass, set ``name``/``issue_rule``/``doc``,
    implement ``visit`` (and optionally ``finalize``)."""

    name: str = ""
    # The ISSUE-15 rule family this check implements (R1..R7) — one
    # family may ship as several named rules (R6 = fault-site +
    # magic-width).
    issue_rule: str = ""
    doc: str = ""

    def visit(self, src, ctx: LintContext) -> List[Finding]:
        return []

    def finalize(self, ctx: LintContext) -> List[Finding]:
        return []


RULE_REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    assert cls.name and cls.name not in RULE_REGISTRY, cls
    RULE_REGISTRY[cls.name] = cls
    return cls


def attr_chain(node: ast.AST) -> Optional[List[str]]:
    """``a.b.c`` -> ``["a", "b", "c"]``; None when the chain roots in
    anything but a plain name (calls, subscripts)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def call_name(node: ast.Call) -> str:
    """Dotted name of a call's callee ("" when not a plain chain)."""
    chain = attr_chain(node.func)
    return ".".join(chain) if chain else ""
