"""Source loading, fileset discovery, and inline suppressions.

Suppression syntax (mirrors the familiar ``noqa`` shape but demands a
reason — an unexplained suppression is itself a finding)::

    x = thing()  # graftlint: disable=device-put-aliasing -- replicated
                 # broadcast of caller-owned arrays, never pool-borrowed

* On a code line: suppresses the named rules for findings ON that line.
* On a comment-only line: suppresses them for the next CODE line (long
  call expressions rarely have trailing room); the reason may continue
  over following comment lines, which are skipped.
* ``disable=all`` is intentionally not supported — every suppression
  names its rule, so deleting a rule surfaces its stale suppressions.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .base import Finding

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=(?P<rules>[a-z0-9_,\- ]+?)"
    r"(?:\s*--\s*(?P<reason>.*\S))?\s*$"
)


@dataclass
class SourceFile:
    """One parsed file plus everything rules need from it."""

    path: str        # absolute
    rel: str         # repo-relative, posix
    kind: str        # "package" | "scripts" | "root"
    text: str
    lines: List[str]
    tree: Optional[ast.Module]
    parse_error: Optional[Finding]
    # line -> rule names suppressed on that line
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)
    suppression_findings: List[Finding] = field(default_factory=list)
    _parents: Optional[Dict[int, ast.AST]] = None

    def parent_map(self) -> Dict[int, ast.AST]:
        """id(node) -> parent node, built lazily once per file."""
        if self._parents is None:
            parents: Dict[int, ast.AST] = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    parents[id(child)] = node
            self._parents = parents
        return self._parents

    def ancestors(self, node: ast.AST):
        parents = self.parent_map()
        cur = parents.get(id(node))
        while cur is not None:
            yield cur
            cur = parents.get(id(cur))

    def statement_text(self, node: ast.AST) -> str:
        """Source of the statement enclosing ``node`` (the node itself
        when it is a statement)."""
        stmt = node
        for anc in [node] + list(self.ancestors(node)):
            if isinstance(anc, ast.stmt):
                stmt = anc
                break
        end = getattr(stmt, "end_lineno", stmt.lineno)
        return "\n".join(self.lines[stmt.lineno - 1:end])


def _scan_suppressions(src: SourceFile, known_rules: Set[str]) -> None:
    """Populate ``src.suppressions`` from ``# graftlint:`` comments.

    tokenize (not line regex) so a ``# graftlint:`` inside a string
    literal never parses as a directive.
    """
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(src.text).readline
        ))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return
    code_lines: Set[int] = set()
    comments: List[Tuple[int, str]] = []
    for tok in tokens:
        if tok.type == tokenize.COMMENT:
            comments.append((tok.start[0], tok.string))
        elif tok.type not in (
            tokenize.NL, tokenize.NEWLINE, tokenize.INDENT,
            tokenize.DEDENT, tokenize.ENDMARKER,
        ):
            code_lines.update(range(tok.start[0], tok.end[0] + 1))
    for lineno, comment in comments:
        m = _SUPPRESS_RE.search(comment)
        if m is None:
            # The tool name followed by a colon marks a directive;
            # prose mentions of the bare tool name stay legal.
            if "graftlint" + ":" in comment:
                src.suppression_findings.append(Finding(
                    "bad-suppression", src.rel, lineno, 0,
                    "unparseable graftlint directive (expected "
                    "'# graftlint: disable=<rule>[,<rule>] -- "
                    "<reason>')",
                ))
            continue
        rules = {r.strip() for r in m.group("rules").split(",")
                 if r.strip()}
        reason = m.group("reason")
        bad = sorted(r for r in rules if r not in known_rules)
        if bad:
            src.suppression_findings.append(Finding(
                "bad-suppression", src.rel, lineno, 0,
                f"suppression names unknown rule(s): {', '.join(bad)}",
            ))
            rules -= set(bad)
        if not reason:
            src.suppression_findings.append(Finding(
                "bad-suppression", src.rel, lineno, 0,
                "suppression without a reason — append "
                "'-- <why this site is safe>'",
            ))
            continue  # a reasonless suppression suppresses nothing
        if lineno in code_lines:
            target = lineno
        else:
            after = [ln for ln in code_lines if ln > lineno]
            if not after:
                continue
            target = min(after)
        src.suppressions.setdefault(target, set()).update(rules)


def load_source(path: str, root: str,
                known_rules: Set[str]) -> SourceFile:
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    if rel.startswith("pypardis_tpu/"):
        kind = "package"
    elif rel.startswith("scripts/"):
        kind = "scripts"
    else:
        kind = "root"
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    tree = None
    err = None
    try:
        tree = ast.parse(text, filename=rel)
    except SyntaxError as e:
        err = Finding(
            "parse-error", rel, e.lineno or 1, e.offset or 0,
            f"syntax error: {e.msg}",
        )
    src = SourceFile(
        path=path, rel=rel, kind=kind, text=text,
        lines=text.splitlines(), tree=tree, parse_error=err,
    )
    if tree is not None:
        _scan_suppressions(src, known_rules)
    return src


def discover_files(root: str) -> List[str]:
    """The enforced fileset: the package, the probe/CI scripts, and
    the repo-root entry points (``bench.py`` / ``benchdata.py``)."""
    out: List[str] = []
    for sub in ("pypardis_tpu", "scripts"):
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__"
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    for fn in ("bench.py", "benchdata.py"):
        p = os.path.join(root, fn)
        if os.path.exists(p):
            out.append(p)
    return out
