"""Static models of the two in-repo registries graftlint enforces
against: the env-var registry (``utils/envreg.py``) and the
fault-injection site registry (``utils/faults.py``).

Parsed with ``ast`` from source — never imported — so the linter stays
jax-free and sub-second, and a syntactically broken registry is itself
a loud lint failure rather than an import-time crash.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class EnvEntry:
    name: str
    type: str
    default: str
    doc: str


@dataclass
class EnvRegistry:
    path: str  # repo-relative
    entries: Tuple[EnvEntry, ...]

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(e.name for e in self.entries)

    def render_markdown(self) -> str:
        """Byte-identical to ``envreg.render_markdown()`` — asserted
        by tests/test_analysis.py so the static and runtime renderers
        cannot drift."""
        lines = [
            "| Variable | Type | Default | Meaning |",
            "| --- | --- | --- | --- |",
        ]
        for e in self.entries:
            doc = " ".join(e.doc.split())
            lines.append(
                f"| `{e.name}` | {e.type} | `{e.default}` | {doc} |"
            )
        return "\n".join(lines) + "\n"


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def parse_env_registry(root: str) -> EnvRegistry:
    """Extract the ``_DECLARATIONS`` tuple of ``EnvVar(...)`` literal
    calls.  Non-literal fields raise — the registry is declared data,
    not code."""
    rel = "pypardis_tpu/utils/envreg.py"
    path = os.path.join(root, rel)
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=rel)
    entries: List[EnvEntry] = []
    for node in tree.body:
        if not (isinstance(node, (ast.Assign, ast.AnnAssign))):
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign)
            else [node.target]
        )
        if not any(isinstance(t, ast.Name) and t.id == "_DECLARATIONS"
                   for t in targets):
            continue
        value = node.value
        if not isinstance(value, ast.Tuple):
            raise ValueError(f"{rel}: _DECLARATIONS must be a tuple")
        for elt in value.elts:
            if not (isinstance(elt, ast.Call)
                    and isinstance(elt.func, ast.Name)
                    and elt.func.id == "EnvVar"):
                raise ValueError(
                    f"{rel}:{elt.lineno}: _DECLARATIONS entries must "
                    f"be literal EnvVar(...) calls"
                )
            fields = [_const_str(a) for a in elt.args]
            for kw in elt.keywords:
                fields.append(_const_str(kw.value))
            if len(fields) != 4 or any(f is None for f in fields):
                raise ValueError(
                    f"{rel}:{elt.lineno}: EnvVar fields must be four "
                    f"string literals (name, type, default, doc)"
                )
            entries.append(EnvEntry(*fields))
    if not entries:
        raise ValueError(f"{rel}: no _DECLARATIONS tuple found")
    return EnvRegistry(path=rel, entries=tuple(entries))


def parse_fault_sites(root: str) -> Tuple[Tuple[str, ...],
                                          Dict[str, int]]:
    """``(sites, site -> declaration line)`` from the ``KNOWN_SITES``
    tuple in ``utils/faults.py``.  Duplicates are preserved so the
    fault-site rule can flag them."""
    rel = "pypardis_tpu/utils/faults.py"
    path = os.path.join(root, rel)
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=rel)
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "KNOWN_SITES"
                   for t in node.targets):
            continue
        if not isinstance(node.value, (ast.Tuple, ast.List)):
            break
        sites: List[str] = []
        lines: Dict[str, int] = {}
        for elt in node.value.elts:
            s = _const_str(elt)
            if s is None:
                raise ValueError(
                    f"{rel}:{elt.lineno}: KNOWN_SITES entries must be "
                    f"string literals"
                )
            sites.append(s)
            lines.setdefault(s, elt.lineno)
        return tuple(sites), lines
    raise ValueError(f"{rel}: no KNOWN_SITES tuple found")
