"""R3/R4 — environment-variable discipline.

R3 ``trace-env-read`` (the PR 11 dispatch-tag class): a direct
``os.environ`` / ``os.getenv`` read inside a function reachable from a
``jax.jit`` / ``pjit`` / ``shard_map`` / ``pallas_call`` entry point is
evaluated at TRACE time — the value is silently baked into the
compiled program and flipping the variable later does nothing until
caches clear.  Such reads must go through
``pypardis_tpu.utils.envreg.raw``, whose docstring owns that contract.
Reachability is a best-effort static call graph: module-local calls by
name (lexically scoped, so jitted closures inside builder functions
resolve), plus cross-module edges through package-internal imports
(``from .distances import foo`` / ``from .. import staging``).  The
graph over-approximates (a name match is an edge); the whole-repo
zero-findings gate in tests keeps the over-approximation honest.

R4 ``env-registry``: every ``PYPARDIS_*`` token anywhere in the
fileset — string literals, docstrings, comments — must be declared in
``utils/envreg.py``.  Unregistered names fail with a did-you-mean
suggestion (the near-miss-typo gate), and the README "Environment
variables" table must match the registry render exactly
(``scripts/graftlint.py --envdocs`` regenerates it).
"""

from __future__ import annotations

import ast
import difflib
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .base import Finding, LintContext, Rule, attr_chain, register

_JIT_WRAPPERS = {"jit", "pjit", "shard_map", "pallas_call"}

ENVDOCS_BEGIN = "<!-- graftlint:envdocs:begin -->"
ENVDOCS_END = "<!-- graftlint:envdocs:end -->"

# The final char class excludes a trailing underscore, so a prefix
# reference written with a star (the PYPARDIS_COMPACT_* watermarks,
# say) tokenizes as the prefix with the star following it.
_TOKEN_RE = re.compile(r"PYPARDIS_[A-Z0-9_]*[A-Z0-9]")


def _rel_to_module(rel: str) -> Optional[Tuple[str, ...]]:
    """Package-relative module path: ``pypardis_tpu/ops/distances.py``
    -> ``("ops", "distances")``; None outside the package."""
    if not rel.startswith("pypardis_tpu/") or not rel.endswith(".py"):
        return None
    parts = rel[len("pypardis_tpu/"):-len(".py")].split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return tuple(parts)


def _module_to_rel(parts: Tuple[str, ...]) -> str:
    return "pypardis_tpu/" + "/".join(parts) + ".py"


class _ModuleGraph:
    """Per-module symbol/call/read collection for R3."""

    def __init__(self, rel: str, tree: ast.Module):
        self.rel = rel
        self.mod = _rel_to_module(rel)
        # funckey -> ast node; funckey = (rel, qualname)
        self.functions: Dict[Tuple[str, str], ast.AST] = {}
        self.jit_roots: Set[Tuple[str, str]] = set()
        self.edges: Dict[Tuple[str, str],
                         Set[Tuple[str, str]]] = {}
        self.env_reads: Dict[Tuple[str, str],
                             List[ast.AST]] = {}
        # local alias -> target module rel (import of a module)
        self.mod_aliases: Dict[str, str] = {}
        # local name -> (target module rel, name) (from-import)
        self.from_names: Dict[str, Tuple[str, str]] = {}
        self._collect_imports(tree)
        self._walk_scope(tree, qual="", scopes=[{}])

    # -- imports -------------------------------------------------------
    def _resolve_from(self, node: ast.ImportFrom) -> Optional[
            Tuple[str, ...]]:
        if self.mod is None:
            return None
        if node.level == 0:
            if not (node.module or "").startswith("pypardis_tpu"):
                return None
            return tuple((node.module or "").split(".")[1:])
        # relative: level 1 = this module's package
        base = self.mod[:-1] if self.mod else ()
        up = node.level - 1
        if up > len(base):
            return None
        base = base[:len(base) - up] if up else base
        extra = tuple((node.module or "").split(".")) \
            if node.module else ()
        return base + extra

    def _collect_imports(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith("pypardis_tpu."):
                        parts = tuple(a.name.split(".")[1:])
                        alias = a.asname or a.name.split(".")[-1]
                        self.mod_aliases[alias] = _module_to_rel(parts)
            elif isinstance(node, ast.ImportFrom):
                target = self._resolve_from(node)
                if target is None:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    # `from ..parallel import staging` binds a module;
                    # `from .distances import foo` binds a function.
                    sub = target + (a.name,)
                    self.mod_aliases.setdefault(
                        local, _module_to_rel(sub)
                    )
                    if target:
                        self.from_names[local] = (
                            _module_to_rel(target), a.name
                        )

    # -- scoped walk ---------------------------------------------------
    def _walk_scope(self, node: ast.AST, qual: str,
                    scopes: List[Dict[str, Tuple[str, str]]]) -> None:
        body = getattr(node, "body", [])
        local: Dict[str, Tuple[str, str]] = {}
        for stmt in body:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                q = f"{qual}.{stmt.name}" if qual else stmt.name
                local[stmt.name] = (self.rel, q)
        scopes = scopes + [local]
        owner = (self.rel, qual) if qual else None
        for stmt in body:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                q = f"{qual}.{stmt.name}" if qual else stmt.name
                key = (self.rel, q)
                self.functions[key] = stmt
                if self._jitted_decorators(stmt):
                    self.jit_roots.add(key)
                self._walk_scope(stmt, q, scopes)
            elif isinstance(stmt, ast.ClassDef):
                q = f"{qual}.{stmt.name}" if qual else stmt.name
                self._walk_scope(stmt, q, scopes)
            else:
                # module/class-scope statement: jit-wrap calls here
                # (`step = jax.jit(_step)`) mark their arguments.
                self._scan_statement(stmt, owner, scopes)

    def _jitted_decorators(self, fn: ast.AST) -> bool:
        for dec in fn.decorator_list:
            for sub in ast.walk(dec):
                chain = attr_chain(sub) or []
                if chain and chain[-1] in _JIT_WRAPPERS:
                    return True
                if isinstance(sub, ast.Call):
                    chain = attr_chain(sub.func) or []
                    if chain and chain[-1] in _JIT_WRAPPERS:
                        return True
        return False

    def _resolve_name(self, name: str,
                      scopes: List[Dict[str, Tuple[str, str]]]
                      ) -> Optional[Tuple[str, str]]:
        for scope in reversed(scopes):
            if name in scope:
                return scope[name]
        if name in self.from_names:
            rel, target = self.from_names[name]
            return (rel, target)
        return None

    def _resolve_call(self, call: ast.Call,
                      scopes: List[Dict[str, Tuple[str, str]]]
                      ) -> Optional[Tuple[str, str]]:
        chain = attr_chain(call.func)
        if not chain:
            return None
        if len(chain) == 1:
            return self._resolve_name(chain[0], scopes)
        if len(chain) == 2 and chain[0] in self.mod_aliases:
            return (self.mod_aliases[chain[0]], chain[1])
        return None

    def _mark_jit_args(self, call: ast.Call,
                       scopes: List[Dict[str, Tuple[str, str]]]
                       ) -> None:
        chain = attr_chain(call.func) or []
        if not chain or chain[-1] not in _JIT_WRAPPERS:
            return
        for arg in list(call.args) + [
            kw.value for kw in call.keywords
        ]:
            if isinstance(arg, ast.Name):
                key = self._resolve_name(arg.id, scopes)
                if key is not None:
                    self.jit_roots.add(key)

    @staticmethod
    def _env_read(node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func) or []
            if chain[-2:] == ["environ", "get"]:
                return True
            if chain and chain[-1] == "getenv":
                return True
            # __import__("os").environ.get(...)
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "get"
                    and isinstance(f.value, ast.Attribute)
                    and f.value.attr == "environ"):
                return True
        if isinstance(node, ast.Subscript):
            chain = attr_chain(node.value) or []
            if chain[-1:] == ["environ"]:
                # reads AND writes subscript; only flag loads
                return isinstance(node.ctx, ast.Load)
        return False

    def _scan_statement(self, stmt: ast.stmt,
                        owner: Optional[Tuple[str, str]],
                        scopes: List[Dict[str, Tuple[str, str]]]
                        ) -> None:
        """Calls, jit-wrap markings, and env reads in one non-def
        statement (def statements recurse via ``_walk_scope``, so a
        statement walk here never meets a nested FunctionDef)."""
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self._mark_jit_args(node, scopes)
                if owner is not None:
                    callee = self._resolve_call(node, scopes)
                    if callee is not None:
                        self.edges.setdefault(owner, set()).add(callee)
            if owner is not None and self._env_read(node):
                self.env_reads.setdefault(owner, []).append(node)


@register
class TraceEnvReadRule(Rule):
    name = "trace-env-read"
    issue_rule = "R3"
    doc = ("os.environ reads reachable from jit/shard_map/pjit bake "
           "the value into the compiled program (PR 11); route "
           "through utils.envreg.raw")

    def visit(self, src, ctx: LintContext) -> List[Finding]:
        if src.tree is None or src.kind != "package":
            return []
        if src.rel.endswith("utils/envreg.py"):
            return []  # the accessor module owns the contract
        graphs = ctx.shared.setdefault("r3_graphs", {})
        graphs[src.rel] = _ModuleGraph(src.rel, src.tree)
        return []

    def finalize(self, ctx: LintContext) -> List[Finding]:
        graphs: Dict[str, _ModuleGraph] = ctx.shared.get(
            "r3_graphs", {}
        )
        roots: Set[Tuple[str, str]] = set()
        edges: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        for g in graphs.values():
            roots |= g.jit_roots
            for k, v in g.edges.items():
                edges.setdefault(k, set()).update(v)
        # Nested functions of a reachable function are reachable
        # (closures trace with their parent): add parent->child edges.
        for g in graphs.values():
            for rel, qual in g.functions:
                if "." in qual:
                    parent = qual.rsplit(".", 1)[0]
                    if (rel, parent) in g.functions:
                        edges.setdefault((rel, parent), set()).add(
                            (rel, qual)
                        )
        reachable: Set[Tuple[str, str]] = set()
        frontier = list(roots)
        while frontier:
            key = frontier.pop()
            if key in reachable:
                continue
            reachable.add(key)
            frontier.extend(edges.get(key, ()))
        out: List[Finding] = []
        for g in graphs.values():
            for key, nodes in g.env_reads.items():
                if key not in reachable:
                    continue
                for node in nodes:
                    out.append(Finding(
                        self.name, key[0], node.lineno,
                        node.col_offset,
                        f"os.environ read in {key[1]!r}, reachable "
                        f"from a jit/shard_map entry point — the "
                        f"value is baked in at trace time (PR 11); "
                        f"read it via utils.envreg.raw, which "
                        f"documents that contract",
                    ))
        return out


@register
class EnvRegistryRule(Rule):
    name = "env-registry"
    issue_rule = "R4"
    doc = ("every PYPARDIS_* name must be declared in utils/envreg.py; "
           "the README table is generated from the registry")

    def visit(self, src, ctx: LintContext) -> List[Finding]:
        names = set(ctx.env_registry.names)
        out: List[Finding] = []
        seen_here: Set[str] = set()
        for m in _TOKEN_RE.finditer(src.text):
            token = m.group(0)
            tail = src.text[m.end():m.end() + 2]
            if tail[:1] == "*" or tail == "_*":
                if any(n.startswith(token) for n in names):
                    continue
            elif token in names:
                continue
            if token in seen_here:
                continue
            seen_here.add(token)
            line = src.text.count("\n", 0, m.start()) + 1
            hint = difflib.get_close_matches(token, names, n=1)
            suffix = f" — did you mean {hint[0]}?" if hint else ""
            out.append(Finding(
                self.name, src.rel, line, 0,
                f"{token} is not declared in utils/envreg.py"
                f"{suffix} (declare it with a type/default/doc, or "
                f"fix the typo)",
            ))
        return out

    def finalize(self, ctx: LintContext) -> List[Finding]:
        readme = os.path.join(ctx.root, "README.md")
        if not os.path.exists(readme):
            return []
        with open(readme, "r", encoding="utf-8") as f:
            text = f.read()
        begin = text.find(ENVDOCS_BEGIN)
        end = text.find(ENVDOCS_END)
        if begin < 0 or end < 0 or end < begin:
            return [Finding(
                self.name, "README.md", 1, 0,
                f"README.md lacks the generated env-var table "
                f"markers {ENVDOCS_BEGIN!r} / {ENVDOCS_END!r}",
            )]
        committed = text[begin + len(ENVDOCS_BEGIN):end].strip("\n")
        expected = ctx.env_registry.render_markdown().strip("\n")
        if committed != expected:
            line = text.count("\n", 0, begin) + 1
            return [Finding(
                self.name, "README.md", line, 0,
                "README env-var table is stale vs utils/envreg.py — "
                "regenerate with `python scripts/graftlint.py "
                "--envdocs` and paste between the markers",
            )]
        return []
