"""graftlint — AST-level enforcement of the project's correctness
invariants.

Four of ten consecutive PRs each shipped a fix for a *latent, silent*
violation of an unwritten project rule: PR 6's ``UnexpectedTracerError``
from module-level ``jnp`` scalar constants, PR 13's corrupted labels
from ``jax.device_put`` zero-copy aliasing of pooled build buffers,
PR 11's trace-time ``os.environ`` read baked into a jitted program, and
PR 4's ``seal_f32`` discipline against XLA FMA contraction.  This
package turns those rules (plus env-var registration, fault-site and
magic-width hygiene, and an unused-import sweep) into named,
machine-checked lint gates — the correctness-tooling third leg of the
repo's self-verification stool next to ``check_bench_json`` (telemetry
schema) and ``bench_diff`` (perf regressions).

Everything here is stdlib-``ast`` only: no jax, no numpy, no imports
from the rest of the package at runtime (the env-var registry and the
fault-site registry are parsed *statically* from their source files),
so ``scripts/graftlint.py`` runs in well under a second.

Surface: :func:`run_lint` (the driver), :data:`ALL_RULES`, and the
rule classes themselves for targeted use in tests.
"""

from .base import Finding, LintContext, Rule, RULE_REGISTRY
from .driver import LintResult, default_fileset, run_lint

__all__ = [
    "Finding", "LintContext", "Rule", "RULE_REGISTRY",
    "LintResult", "default_fileset", "run_lint",
]
