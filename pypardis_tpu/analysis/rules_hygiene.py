"""R6/R7 — fault-site, magic-width, and import hygiene.

R6a ``fault-site``: every fault-injection site string (the first
argument of ``faults.maybe_fail``, the ``site=`` of
``staging.transfer``, and the site names inside ``faults.plan`` spec
literals) must be declared in the ``KNOWN_SITES`` tuple in
``utils/faults.py`` — and every declared site must be used somewhere,
so the registry (and the docstring table generated next to it) cannot
rot the way the module's site table silently missed ``gm.execute`` /
``gm.chained_range`` for two PRs.  Dynamic (non-literal) site
arguments are allowed only inside the staging/faults plumbing that
forwards them.

R6b ``magic-width``: the pair-stats row is ``(PAIR_STATS_WIDTH,)`` =
``(5,)`` wide — and was ``(3,)`` before PR 7 widened it, which is
exactly why a literal ``5`` (or legacy ``3``) in stats shapes and
unpack subscripts is a trap: the next widening silently truncates.
In the kernel/driver modules that carry pair stats, stats-shaped
constructor calls and negative unpack subscripts on stats-named
values must spell ``ops.precision.PAIR_STATS_WIDTH``.

R7 ``unused-import`` (bonus): an import whose bound name never
appears again in the file.  Enforced for the package and the repo-root
entry points; report-only (a note) for ``scripts/`` where probe CLIs
keep convenience imports.  Side-effect imports suppress with
``# graftlint: disable=unused-import -- <side effect>``.
"""

from __future__ import annotations

import ast
import difflib
import re
from typing import Dict, List, Optional, Set, Tuple

from .base import Finding, LintContext, Rule, attr_chain, register

# -- R6a fault-site ----------------------------------------------------

_SPEC_SITE_RE = re.compile(r"(^|,)\s*(?P<site>[a-z0-9_.]+?)\s*[:=]")

_FORWARDING_FILES = (
    "pypardis_tpu/parallel/staging.py",
    "pypardis_tpu/utils/faults.py",
)


def _spec_sites(spec: str) -> List[str]:
    return [m.group("site") for m in _SPEC_SITE_RE.finditer(spec)]


@register
class FaultSiteRule(Rule):
    name = "fault-site"
    issue_rule = "R6"
    doc = ("every fault-injection site string must be declared in "
           "faults.KNOWN_SITES, and every declared site used")

    def visit(self, src, ctx: LintContext) -> List[Finding]:
        if src.tree is None:
            return []
        out: List[Finding] = []
        used: Dict[str, Tuple[str, int]] = ctx.shared.setdefault(
            "fault_sites_used", {}
        )

        def record(site: str, lineno: int) -> None:
            used.setdefault(site, (src.rel, lineno))

        for node in ast.walk(src.tree):
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func) or []
                tail = chain[-1] if chain else ""
                if tail == "maybe_fail" and node.args:
                    arg = node.args[0]
                    if (isinstance(arg, ast.Constant)
                            and isinstance(arg.value, str)):
                        record(arg.value, node.lineno)
                    elif src.rel not in _FORWARDING_FILES:
                        out.append(Finding(
                            self.name, src.rel, node.lineno,
                            node.col_offset,
                            "non-literal fault site — only the "
                            "staging/faults forwarding layer may "
                            "pass a computed site name",
                        ))
                elif tail == "transfer":
                    for kw in node.keywords:
                        if kw.arg != "site":
                            continue
                        if (isinstance(kw.value, ast.Constant)
                                and isinstance(kw.value.value, str)):
                            record(kw.value.value, node.lineno)
                        elif src.rel not in _FORWARDING_FILES:
                            out.append(Finding(
                                self.name, src.rel, node.lineno,
                                node.col_offset,
                                "non-literal fault site in "
                                "staging.transfer(site=...)",
                            ))
                elif tail == "plan" and node.args:
                    arg = node.args[0]
                    if (isinstance(arg, ast.Constant)
                            and isinstance(arg.value, str)):
                        for site in _spec_sites(arg.value):
                            record(site, node.lineno)
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                # literal defaults of a `site` parameter (the
                # staging.transfer signature default is a real use)
                args = node.args
                pos = args.posonlyargs + args.args
                for a, d in zip(pos[len(pos) - len(args.defaults):],
                                args.defaults):
                    if (a.arg == "site"
                            and isinstance(d, ast.Constant)
                            and isinstance(d.value, str)):
                        record(d.value, node.lineno)
                for a, d in zip(args.kwonlyargs, args.kw_defaults):
                    if (a.arg == "site" and d is not None
                            and isinstance(d, ast.Constant)
                            and isinstance(d.value, str)):
                        record(d.value, node.lineno)
        return out

    def finalize(self, ctx: LintContext) -> List[Finding]:
        used: Dict[str, Tuple[str, int]] = ctx.shared.get(
            "fault_sites_used", {}
        )
        known = ctx.fault_sites
        known_set = set(known)
        out: List[Finding] = []
        seen: Set[str] = set()
        for site in known:
            if site in seen:
                out.append(Finding(
                    self.name, ctx.fault_sites_path,
                    ctx.shared.get("fault_site_lines", {}).get(site, 1),
                    0,
                    f"duplicate KNOWN_SITES entry {site!r}",
                ))
            seen.add(site)
        for site, (rel, lineno) in sorted(used.items()):
            if site in known_set:
                continue
            hint = difflib.get_close_matches(site, known_set, n=1)
            suffix = f" — did you mean {hint[0]!r}?" if hint else ""
            out.append(Finding(
                self.name, rel, lineno, 0,
                f"fault site {site!r} is not declared in "
                f"faults.KNOWN_SITES{suffix}",
            ))
        if ctx.shared.get("partial_run"):
            return out  # can't judge "unused" from a partial fileset
        for site in known:
            if site not in used:
                out.append(Finding(
                    self.name, ctx.fault_sites_path,
                    ctx.shared.get("fault_site_lines", {}).get(site, 1),
                    0,
                    f"KNOWN_SITES entry {site!r} has no remaining "
                    f"injection site — remove it (or restore the "
                    f"site)",
                ))
        return out


# -- R6b magic-width ---------------------------------------------------

_STATS_MODULES = (
    "pypardis_tpu/ops/pipeline.py",
    "pypardis_tpu/ops/labels.py",
    "pypardis_tpu/ops/distances.py",
    "pypardis_tpu/ops/pallas_kernels.py",
    "pypardis_tpu/parallel/sharded.py",
    "pypardis_tpu/parallel/global_morton.py",
    "pypardis_tpu/utils/budget.py",
)

_STATS_NAME_RE = re.compile(r"(pair_?stats|pstats|packed)", re.I)
_CTOR_NAMES = {"zeros", "ones", "full", "empty", "reshape",
               "broadcast_to"}
_WIDTHS = (5, 3)  # current width and the pre-PR 7 legacy width


def _neg_const(node: ast.AST) -> Optional[int]:
    if (isinstance(node, ast.UnaryOp)
            and isinstance(node.op, ast.USub)
            and isinstance(node.operand, ast.Constant)
            and isinstance(node.operand.value, int)):
        return -node.operand.value
    return None


@register
class MagicWidthRule(Rule):
    name = "magic-width"
    issue_rule = "R6"
    doc = ("pair-stats shapes and unpack subscripts must spell "
           "ops.precision.PAIR_STATS_WIDTH, not literal 5/3 — the "
           "PR 7 widening trap")

    def visit(self, src, ctx: LintContext) -> List[Finding]:
        if src.tree is None:
            return []
        if not any(src.rel.endswith(m.split("/", 1)[1]) or src.rel == m
                   for m in _STATS_MODULES):
            return []
        out: List[Finding] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Subscript):
                base = node.value
                if not (isinstance(base, ast.Name)
                        and _STATS_NAME_RE.search(base.id)):
                    continue
                flagged = []
                idx = node.slice
                v = _neg_const(idx)
                # -1 stays legal (generic last-element); -2..-5 are
                # stats-column arithmetic in disguise.
                if v is not None and v in (-2, -3, -4, -5):
                    flagged.append(idx)
                if isinstance(idx, ast.Slice):
                    for bound in (idx.lower, idx.upper):
                        if bound is None:
                            continue
                        bv = _neg_const(bound)
                        if bv is not None and bv in (-3, -5):
                            flagged.append(bound)
                for f in flagged:
                    out.append(Finding(
                        self.name, src.rel, node.lineno,
                        node.col_offset,
                        f"literal stats-width subscript on "
                        f"{base.id!r} — index relative to "
                        f"ops.precision.PAIR_STATS_WIDTH instead "
                        f"(the row was (3,) before PR 7 widened it; "
                        f"the next widening will silently truncate "
                        f"this unpack)",
                    ))
            elif isinstance(node, ast.Call):
                chain = attr_chain(node.func) or []
                if not chain or chain[-1] not in _CTOR_NAMES:
                    continue
                stmt_text = src.statement_text(node)
                if not re.search(r"stat", stmt_text, re.I):
                    continue
                shape_args = [a for a in node.args
                              if isinstance(a, ast.Tuple)]
                for tup in shape_args:
                    if not tup.elts:
                        continue
                    last = tup.elts[-1]
                    if (isinstance(last, ast.Constant)
                            and last.value in _WIDTHS):
                        out.append(Finding(
                            self.name, src.rel, node.lineno,
                            node.col_offset,
                            "literal pair-stats width in a shape — "
                            "use ops.precision.PAIR_STATS_WIDTH",
                        ))
        return out


# -- R7 unused-import --------------------------------------------------


@register
class UnusedImportRule(Rule):
    name = "unused-import"
    issue_rule = "R7"
    doc = ("import whose bound name never appears again in the file; "
           "enforced for the package, report-only for scripts/")

    def visit(self, src, ctx: LintContext) -> List[Finding]:
        if src.tree is None or src.rel.endswith("__init__.py"):
            return []
        severity = "note" if src.kind == "scripts" else "error"
        # (name, import stmt node)
        bindings: List[Tuple[str, ast.stmt]] = []
        import_spans: List[Tuple[int, int]] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    bindings.append((name, node))
                import_spans.append(
                    (node.lineno, getattr(node, "end_lineno",
                                          node.lineno))
                )
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    bindings.append((a.asname or a.name, node))
                import_spans.append(
                    (node.lineno, getattr(node, "end_lineno",
                                          node.lineno))
                )
        if not bindings:
            return []
        import_text = "\n".join(
            "\n".join(src.lines[s - 1:e]) for s, e in import_spans
        )
        out: List[Finding] = []
        seen: Set[Tuple[str, int]] = set()
        for name, node in bindings:
            key = (name, node.lineno)
            if key in seen:
                continue
            seen.add(key)
            pat = re.compile(rf"\b{re.escape(name)}\b")
            total = len(pat.findall(src.text))
            in_imports = len(pat.findall(import_text))
            if total > in_imports:
                continue
            out.append(Finding(
                self.name, src.rel, node.lineno, node.col_offset,
                f"{name!r} is imported but never used",
                severity=severity,
            ))
        return out
