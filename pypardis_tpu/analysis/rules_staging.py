"""R2 — ``jax.device_put`` aliasing discipline (the PR 13 corruption
class).

On CPU (and any backend where :func:`staging.may_alias_host` is true)
``jax.device_put`` of an aligned numpy buffer is ZERO-COPY: the
returned "device" array aliases the host memory.  PR 13 found
fit(eps1)→fit(eps2) returning corrupted labels because pooled build
buffers were device_put into the slab cache and then handed back to
the pool — the next borrow overwrote live cached slabs.  The fix is
:func:`staging.give_back_after_put`, which *drops* (never pools) build
buffers on aliasing backends.

The enforceable AST contract: a direct ``jax.device_put`` call in the
package must sit inside one of the sanctioned shapes —

* in ``parallel/staging.py`` itself (the layer that owns the hazard);
* inside a callable passed to ``staging.transfer(...)`` (the fault-
  injected, retried transfer scope every slab shipment uses);
* in a function that also calls ``staging.give_back_after_put`` (the
  audited put-then-drop pairing);
* under an inline ``# graftlint: disable=device-put-aliasing -- <why
  this buffer is never pool-borrowed>`` suppression.

Everything else is a finding: the author must either route through the
staging layer or state the buffer's provenance in a suppression
reason.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .base import Finding, LintContext, Rule, attr_chain, register


def _is_device_put(node: ast.Call) -> bool:
    chain = attr_chain(node.func)
    if not chain:
        return False
    return chain[-1] == "device_put" and (
        len(chain) == 1 or chain[-2] in ("jax", "_jax")
    )


def _enclosing_function(src, node: ast.AST) -> Optional[ast.AST]:
    for anc in src.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def _inside_transfer_arg(src, node: ast.AST) -> bool:
    """Whether ``node`` sits inside a lambda/def that is an argument
    of a ``staging.transfer(...)`` call."""
    prev = node
    for anc in src.ancestors(node):
        if isinstance(
            anc, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            prev = anc
            continue
        if isinstance(anc, ast.Call) and prev is not node:
            chain = attr_chain(anc.func)
            if chain and chain[-1] == "transfer":
                return True
    return False


def _function_gives_back(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] == "give_back_after_put":
                return True
    return False


@register
class DevicePutAliasingRule(Rule):
    name = "device-put-aliasing"
    issue_rule = "R2"
    doc = ("direct jax.device_put outside the staging layer risks "
           "zero-copy aliasing of pooled buffers (PR 13); wrap in "
           "staging.transfer / pair with give_back_after_put")

    def visit(self, src, ctx: LintContext) -> List[Finding]:
        if src.tree is None or src.kind != "package":
            return []
        if src.rel.endswith("parallel/staging.py"):
            return []
        if "device_put" not in src.text:
            return []
        out: List[Finding] = []
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and _is_device_put(node)):
                continue
            if _inside_transfer_arg(src, node):
                continue
            fn = _enclosing_function(src, node)
            if fn is not None and _function_gives_back(fn):
                continue
            out.append(Finding(
                self.name, src.rel, node.lineno, node.col_offset,
                "direct jax.device_put outside the staging "
                "discipline: on aliasing backends a pooled build "
                "buffer put this way corrupts cached slabs (PR 13); "
                "wrap the put in staging.transfer(...), pair it with "
                "staging.give_back_after_put, or suppress with the "
                "buffer's provenance as the reason",
            ))
        return out
